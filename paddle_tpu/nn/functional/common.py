"""Common functionals: linear, dropout, norm application, padding,
interpolate, one_hot, embedding (parity: python/paddle/nn/functional/common.py
+ input.py + norm.py; reference kernels operators/dropout_op.*,
operators/layer_norm_op.*, batch_norm_op.*, lookup_table_v2_op.*,
interpolate_v2_op.*, pad3d_op.*)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

import functools
import math

from ...framework.core import Tensor, _apply, to_tensor
from ...framework.random import split_key

__all__ = [
    "linear", "dropout", "dropout2d", "dropout3d", "alpha_dropout",
    "bilinear", "diag_embed", "gather_tree",
    "embedding", "one_hot", "pad", "zeropad2d", "interpolate", "upsample",
    "batch_norm", "layer_norm", "instance_norm", "group_norm", "local_response_norm",
    "normalize", "cosine_similarity", "pixel_shuffle", "pixel_unshuffle",
    "channel_shuffle", "unfold", "fold", "label_smooth", "class_center_sample",
    "pairwise_distance", "cos_sim", "data_norm",
]


def _two_stage_sum0(t):
    """Hierarchical leading-axis sum: [rows, ...] -> [...].

    XLA's TPU reduction emitter regresses on tall column sums — measured
    on a v5e, the [32768, H] -> [H] bias/LN-param gradient reductions of
    a batch-256 BERT-base step cost 19x their batch-128 time (28 ms of
    pure emitter regression, PERF.md "batch-256 knee").  Splitting into
    sqrt(rows)-ish blocks keeps both stages on the fast path.  Short
    columns keep the plain single-stage sum (it is already optimal
    there)."""
    rows = t.shape[0]
    if rows < 8192:
        return t.sum(axis=0)
    g = int(math.isqrt(rows))
    while g > 1 and rows % g:
        g -= 1
    if g <= 1:
        return t.sum(axis=0)
    return t.reshape(g, rows // g, *t.shape[1:]).sum(axis=1).sum(axis=0)


@jax.custom_vjp
def _bias_add(mat, b):
    return mat + b


def _bias_add_fwd(mat, b):
    return mat + b, None


def _bias_add_bwd(_, dy):
    db = _two_stage_sum0(
        dy.astype(jnp.float32).reshape(-1, dy.shape[-1])).astype(dy.dtype)
    return dy, db


# the custom boundary wraps ONLY the elementwise +bias tail — the
# matmul stays plain HLO (fusable, MXU-scheduled); the backward routes
# the bias gradient through the two-stage reduction
_bias_add.defvjp(_bias_add_fwd, _bias_add_bwd)


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b. Reference: operators/matmul_v2_op.* + elementwise_add
    fused by XLA into one MXU call. Under amp.auto_cast runs in bf16.
    The bias gradient reduces hierarchically (see _two_stage_sum0)."""
    from ...amp import maybe_cast_inputs

    def f(v, w, *mb):
        v, w = maybe_cast_inputs("linear", v, w)
        out = jnp.matmul(v, w)
        if mb:
            out = _bias_add(out, mb[0].astype(out.dtype))
        return out
    if bias is not None:
        return _apply(f, x, weight, bias, op_name="linear")
    return _apply(f, x, weight, op_name="linear")


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    if not training or p == 0:
        return x.clone() if isinstance(x, Tensor) else x
    k = split_key()

    def f(v):
        if axis is None:
            shape = v.shape
        else:
            axes = axis if isinstance(axis, (list, tuple)) else [axis]
            shape = tuple(v.shape[i] if i in [a % v.ndim for a in axes] else 1
                          for i in range(v.ndim))
        keep = jax.random.bernoulli(k, 1.0 - p, shape)
        if mode == "upscale_in_train":
            return jnp.where(keep, v / (1.0 - p), jnp.zeros((), v.dtype))
        return jnp.where(keep, v, jnp.zeros((), v.dtype))
    return _apply(f, x, op_name="dropout")


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    ax = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p, axis=ax, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    ax = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p, axis=ax, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0:
        return x
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    a = ((1 - p) * (1 + p * alpha_p ** 2)) ** -0.5
    b = -a * alpha_p * p
    k = split_key()

    def f(v):
        keep = jax.random.bernoulli(k, 1.0 - p, v.shape)
        return a * jnp.where(keep, v, jnp.full((), alpha_p, v.dtype)) + b
    return _apply(f, x, op_name="alpha_dropout")


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """Reference: operators/lookup_table_v2_op.* — here a gather the TPU
    executes natively.  With ``sparse=True`` the eager backward emits a
    row-sparse ``SelectedRows`` gradient (ids + touched rows) instead of
    a dense [vocab, dim] scatter-add — the reference's is_sparse path
    (framework/selected_rows.h:41).  Inside jit the dense path is used
    (XLA fuses the scatter; sparse only pays off on the eager tape)."""
    import jax as _jax

    from ...framework.core import is_grad_enabled
    idx = x._value.astype(jnp.int32) if isinstance(x, Tensor) else jnp.asarray(x, jnp.int32)

    if (sparse and isinstance(weight, Tensor) and not weight.stop_gradient
            and is_grad_enabled()
            and not isinstance(weight._value, _jax.core.Tracer)
            and not isinstance(idx, _jax.core.Tracer)):
        return _sparse_embedding(idx, weight, padding_idx)

    def f(w):
        out = jnp.take(w, idx, axis=0)
        if padding_idx is not None:
            mask = (idx == padding_idx)[..., None]
            out = jnp.where(mask, jnp.zeros((), out.dtype), out)
        return out
    return _apply(f, weight, op_name="embedding")


def _sparse_embedding(idx, weight, padding_idx):
    """Gather forward + custom GradNode producing SelectedRows for the
    weight (no dense vocab-sized gradient is ever materialized)."""
    from ...framework.core import GradNode
    from ...framework.selected_rows import SelectedRows

    wv = weight._value
    out = jnp.take(wv, idx, axis=0)
    if padding_idx is not None:
        out = jnp.where((idx == padding_idx)[..., None],
                        jnp.zeros((), out.dtype), out)
    flat_ids = idx.reshape(-1)

    def vjp_fn(cot):
        vals = cot.reshape(-1, cot.shape[-1])
        if padding_idx is not None:
            vals = jnp.where((flat_ids == padding_idx)[:, None],
                             jnp.zeros((), vals.dtype), vals)
        return (SelectedRows(flat_ids, vals, wv.shape),)

    node = GradNode(vjp_fn, [weight], [(out.shape, out.dtype)],
                    name="embedding_sparse")
    t = Tensor(out, stop_gradient=False)
    t._node = node
    return t


def one_hot(x, num_classes, name=None):
    idx = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(jax.nn.one_hot(idx.astype(jnp.int32), num_classes,
                                 dtype=jnp.float32))


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    if isinstance(pad, Tensor):
        pad = [int(p) for p in pad.numpy()]
    pad = [int(p) for p in pad]
    nd = x._value.ndim

    if len(pad) == nd * 2:
        cfg = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        n_sp = len(pad) // 2
        # paddle pads innermost spatial dims; map per data_format
        cfg = [(0, 0)] * nd
        if data_format.startswith("NC"):
            sp = list(range(2, 2 + n_sp))
        else:
            sp = list(range(1, 1 + n_sp))
        # paddle order is (left, right, top, bottom, front, back) over last
        # spatial dim first
        for i, axi in enumerate(reversed(sp)):
            cfg[axi] = (pad[2 * i], pad[2 * i + 1])

    jmode = {"constant": "constant", "reflect": "reflect",
             "replicate": "edge", "circular": "wrap"}[mode]

    def f(v):
        if jmode == "constant":
            return jnp.pad(v, cfg, mode="constant", constant_values=value)
        return jnp.pad(v, cfg, mode=jmode)
    return _apply(f, x, op_name="pad")


def zeropad2d(x, padding, data_format="NCHW", name=None):
    return pad(x, padding, mode="constant", value=0.0,
               data_format=data_format)


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    """Reference: operators/interpolate_v2_op.* — jax.image.resize based."""
    channel_last = data_format in ("NHWC", "NWC", "NDHWC")
    nd = x._value.ndim
    n_sp = nd - 2
    sp_axes = list(range(1, nd - 1)) if channel_last else list(range(2, nd))
    in_sizes = [x._value.shape[a] for a in sp_axes]
    if size is not None:
        if isinstance(size, Tensor):
            size = [int(s) for s in size.numpy()]
        out_sizes = [int(s.item()) if isinstance(s, Tensor) else int(s) for s in size]
    else:
        if isinstance(scale_factor, (int, float)):
            scale_factor = [scale_factor] * n_sp
        out_sizes = [int(in_sizes[i] * scale_factor[i]) for i in range(n_sp)]

    method = {"nearest": "nearest", "bilinear": "bilinear",
              "trilinear": "trilinear", "bicubic": "bicubic",
              "linear": "linear", "area": "linear"}[mode]

    def f(v):
        shape = list(v.shape)
        for i, a in enumerate(sp_axes):
            shape[a] = out_sizes[i]
        if method == "nearest" or not align_corners:
            return jax.image.resize(v, shape, method=method)
        # align_corners path: explicit coordinate map
        out = v
        for i, a in enumerate(sp_axes):
            in_sz, out_sz = v.shape[a], out_sizes[i]
            if out_sz == 1 or in_sz == 1:
                idx = jnp.zeros(out_sz)
            else:
                idx = jnp.linspace(0, in_sz - 1, out_sz)
            lo = jnp.floor(idx).astype(jnp.int32)
            hi = jnp.clip(lo + 1, 0, in_sz - 1)
            w = (idx - lo).astype(v.dtype)
            shape_b = [1] * out.ndim
            shape_b[a] = out_sz
            w = w.reshape(shape_b)
            out = (jnp.take(out, lo, axis=a) * (1 - w) +
                   jnp.take(out, hi, axis=a) * w)
        return out
    return _apply(f, x, op_name="interpolate")


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW",
             name=None):
    return interpolate(x, size, scale_factor, mode, align_corners,
                       align_mode, data_format)


# ---------------- normalisation application ----------------

def _moments(vf, axes):
    """f32 two-pass moments: mean first, then E[(x-m)^2]. The one-pass
    E[x^2]-m^2 form cancels catastrophically in f32 for un-centered
    inputs (measured: normalized-output error 0.18 at mean=1e3); XLA
    fuses this form to the same throughput anyway (PERF.md)."""
    n = 1
    for a in axes:
        n *= vf.shape[a]
    m = jnp.sum(vf, axis=axes) / n
    mk = _keep(m, vf.ndim, axes)
    var = jnp.sum((vf - mk) * (vf - mk), axis=axes) / n
    return m, var, n


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _norm_train(v, w, b, red_axes, eps):
    """Normalize over ``red_axes`` with batch statistics; closed-form
    backward (operators/batch_norm_op.* / layer_norm_op.* grad kernels
    compute the same two sums + one elementwise pass)."""
    out, m, var = _norm_train_fwd(v, w, b, red_axes, eps)[0]
    return out, m, var


def _keep(t, ref_ndim, red_axes):
    """Reshape a tensor whose dims are the KEPT axes into a broadcastable
    shape (1s at the reduced axes)."""
    shape = [1] * ref_ndim
    it = iter(t.shape)
    for i in range(ref_ndim):
        if i not in red_axes:
            shape[i] = next(it)
    return t.reshape(shape)


def _norm_train_fwd(v, w, b, red_axes, eps):
    vf = v.astype(jnp.float32)
    m, var, n = _moments(vf, red_axes)
    rstd = jax.lax.rsqrt(var + eps)
    mk = _keep(m, v.ndim, red_axes)
    rk = _keep(rstd, v.ndim, red_axes)
    xhat = (vf - mk) * rk
    out = xhat
    if w is not None:
        out = out * _keep(w.astype(jnp.float32), v.ndim, red_axes) \
            + _keep(b.astype(jnp.float32), v.ndim, red_axes)
    return ((out.astype(v.dtype), m, var),
            (v, w, m, rstd, n))


def _norm_train_bwd(red_axes, eps, res, cts):
    g, gm, gvar = cts
    v, w, m, rstd, n = res
    vf = v.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    mk = _keep(m, v.ndim, red_axes)
    rk = _keep(rstd, v.ndim, red_axes)
    xhat = (vf - mk) * rk
    if w is not None:
        gy = gf * _keep(w.astype(jnp.float32), v.ndim, red_axes)
        dw = jnp.sum(gf * xhat, axis=red_axes).astype(w.dtype)
        db = jnp.sum(gf, axis=red_axes).astype(w.dtype)
    else:
        gy, dw, db = gf, None, None
    sum_gy = jnp.sum(gy, axis=red_axes)
    sum_gy_xhat = jnp.sum(gy * xhat, axis=red_axes)
    dx = (rk / n) * (n * gy - _keep(sum_gy, v.ndim, red_axes)
                     - xhat * _keep(sum_gy_xhat, v.ndim, red_axes))
    # exact cotangent paths through the returned batch stats (constant-
    # folded away when, as in training steps, they only feed the
    # non-differentiated running-stat buffers)
    dx = dx + _keep(gm, v.ndim, red_axes) / n
    dx = dx + _keep(gvar, v.ndim, red_axes) * 2.0 * (vf - mk) / n
    return dx.astype(v.dtype), dw, db


_norm_train.defvjp(lambda v, w, b, red_axes, eps:
                   _norm_train_fwd(v, w, b, red_axes, eps),
                   _norm_train_bwd)


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5,
               data_format="NCHW", use_global_stats=None, name=None):
    """Reference: operators/batch_norm_op.*. Running stats update is done
    host-side on the Tensor (eager), matching the reference's in-place
    mean/var variables."""
    channel_last = data_format in ("NHWC", "NLC", "NDHWC", "NC")
    nd = x._value.ndim
    ch_axis = nd - 1 if channel_last and nd > 2 else 1
    red_axes = tuple(i for i in range(nd) if i != ch_axis)
    use_batch = training and not use_global_stats

    if use_batch:
        def f(v, *params):
            w, b = (params[0], params[1]) if params else (None, None)
            out, m, var = _norm_train(v, w, b, red_axes, epsilon)
            return out, m, var

        args = [x] + ([weight, bias] if weight is not None else [])
        out, mean_t, var_t = _apply(f, *args, op_name="batch_norm")
        # update running stats in place (eager side effect); biased
        # variance, matching the reference kernel
        # (operators/batch_norm_op.cc:367 divides by N*sample_size)
        if running_mean is not None:
            running_mean._value = (momentum * running_mean._value +
                                   (1 - momentum) * mean_t._value)
            running_var._value = (momentum * running_var._value +
                                  (1 - momentum) * var_t._value)
        return out

    def f(v, m, va, *params):
        shape = [1] * nd
        shape[ch_axis] = v.shape[ch_axis]
        out = (v - m.reshape(shape)) * jax.lax.rsqrt(
            va.reshape(shape) + epsilon)
        if params:
            out = out * params[0].reshape(shape)
            out = out + params[1].reshape(shape)
        return out

    args = [x, running_mean, running_var]
    if weight is not None:
        args += [weight, bias]
    return _apply(f, *args, op_name="batch_norm")


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5,
               name=None):
    """Reference: operators/layer_norm_op.* — inline f32 moments inside
    one fused XLA expression. Deliberately NOT the custom-vjp core BN
    uses: a custom_vjp boundary blocks XLA's cross-op fusion and costs
    ~3% of a BERT-base train step on a v5e (A/B in PERF.md), while
    autodiff of this form compiles to the same closed-form passes."""
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    n_norm = len(list(normalized_shape))

    def f(v, *params):
        axes = tuple(range(v.ndim - n_norm, v.ndim))
        vf = v.astype(jnp.float32)       # f32 stats even under bf16 AMP
        m = jnp.mean(vf, axis=axes, keepdims=True)
        va = jnp.mean((vf - m) * (vf - m), axis=axes, keepdims=True)
        out = (vf - m) * jax.lax.rsqrt(va + epsilon)
        if params:
            out = _scale_shift(out, params[0].astype(jnp.float32),
                               params[1].astype(jnp.float32))
        return out.astype(v.dtype)
    if weight is not None:
        return _apply(f, x, weight, bias, op_name="layer_norm")
    return _apply(f, x, op_name="layer_norm")


@jax.custom_vjp
def _scale_shift(xhat, g, b):
    return xhat * g + b


def _scale_shift_fwd(xhat, g, b):
    return xhat * g + b, (xhat, g)


def _scale_shift_bwd(res, dy):
    xhat, g = res
    pshape = g.shape
    lead = dy.shape[:dy.ndim - g.ndim]
    rows = 1
    for d in lead:
        rows *= d
    dg = _two_stage_sum0((dy * xhat).reshape(rows, *pshape))
    db = _two_stage_sum0(dy.reshape(rows, *pshape))
    return dy * g, dg, db


# ONLY the elementwise scale-shift tail sits behind the custom boundary
# (the normalization itself stays inline for cross-op fusion — a
# whole-LN custom vjp costs ~3% of a BERT step, PERF.md); the backward
# routes the [rows, H] -> [H] parameter-gradient column sums through
# the two-stage reduction (the batch-256 knee fix)
_scale_shift.defvjp(_scale_shift_fwd, _scale_shift_bwd)


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9,
                  epsilon=1e-5, data_format="NCHW", name=None):
    nd = x._value.ndim
    red_axes = tuple(range(2, nd))

    def f(v, *params):
        m = jnp.mean(v, axis=red_axes, keepdims=True)
        va = jnp.var(v, axis=red_axes, keepdims=True)
        out = (v - m) * jax.lax.rsqrt(va + epsilon)
        if params:
            shape = [1, v.shape[1]] + [1] * (nd - 2)
            out = out * params[0].reshape(shape) + params[1].reshape(shape)
        return out
    if weight is not None:
        return _apply(f, x, weight, bias, op_name="instance_norm")
    return _apply(f, x, op_name="instance_norm")


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    nd = x._value.ndim
    ch_axis = nd - 1 if channel_last else 1

    def f(v, *params):
        c = v.shape[ch_axis]
        g = num_groups
        vm = jnp.moveaxis(v, ch_axis, 1)
        shp = vm.shape
        grouped = vm.reshape(shp[0], g, c // g, *shp[2:])
        axes = tuple(range(2, grouped.ndim))
        m = jnp.mean(grouped, axis=axes, keepdims=True)
        va = jnp.var(grouped, axis=axes, keepdims=True)
        out = (grouped - m) * jax.lax.rsqrt(va + epsilon)
        out = out.reshape(shp)
        if params:
            pshape = [1, c] + [1] * (out.ndim - 2)
            out = out * params[0].reshape(pshape) + params[1].reshape(pshape)
        return jnp.moveaxis(out, 1, ch_axis)
    if weight is not None:
        return _apply(f, x, weight, bias, op_name="group_norm")
    return _apply(f, x, op_name="group_norm")


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    def f(v):
        sq = v * v
        ch_axis = 1 if data_format.startswith("NC") else v.ndim - 1
        half = size // 2
        c = v.shape[ch_axis]
        sq_m = jnp.moveaxis(sq, ch_axis, -1)
        padded = jnp.pad(sq_m, [(0, 0)] * (sq_m.ndim - 1) + [(half, size - 1 - half)])
        win = sum(jax.lax.slice_in_dim(padded, i, i + c, axis=-1)
                  for i in range(size))
        div = (k + alpha * win / size) ** beta
        return v / jnp.moveaxis(div, -1, ch_axis)
    return _apply(f, x, op_name="local_response_norm")


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def f(v):
        n = jnp.sum(jnp.abs(v) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return v / jnp.maximum(n, epsilon)
    return _apply(f, x, op_name="normalize")


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    def f(a, b):
        dot = jnp.sum(a * b, axis=axis)
        na = jnp.sqrt(jnp.sum(a * a, axis=axis))
        nb = jnp.sqrt(jnp.sum(b * b, axis=axis))
        return dot / jnp.maximum(na * nb, eps)
    return _apply(f, x1, x2, op_name="cosine_similarity")


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    def f(a, b):
        d = a - b + epsilon
        return jnp.sum(jnp.abs(d) ** p, axis=-1, keepdims=keepdim) ** (1.0 / p)
    return _apply(f, x, y, op_name="pairwise_distance")


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def f(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            v = v.reshape(n, c // (r * r), r, r, h, w)
            v = v.transpose(0, 1, 4, 2, 5, 3)
            return v.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = v.shape
        v = v.reshape(n, h, w, r, r, c // (r * r))
        v = v.transpose(0, 1, 3, 2, 4, 5)
        return v.reshape(n, h * r, w * r, c // (r * r))
    return _apply(f, x, op_name="pixel_shuffle")


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor

    def f(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            v = v.reshape(n, c, h // r, r, w // r, r)
            v = v.transpose(0, 1, 3, 5, 2, 4)
            return v.reshape(n, c * r * r, h // r, w // r)
        n, h, w, c = v.shape
        v = v.reshape(n, h // r, r, w // r, r, c)
        v = v.transpose(0, 1, 3, 2, 4, 5)
        return v.reshape(n, h // r, w // r, c * r * r)
    return _apply(f, x, op_name="pixel_unshuffle")


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    def f(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            v = v.reshape(n, groups, c // groups, h, w)
            return v.swapaxes(1, 2).reshape(n, c, h, w)
        n, h, w, c = v.shape
        v = v.reshape(n, h, w, groups, c // groups)
        return v.swapaxes(3, 4).reshape(n, h, w, c)
    return _apply(f, x, op_name="channel_shuffle")


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (reference: operators/math/im2col.*)."""
    ks = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) else [kernel_sizes] * 2
    st = strides if isinstance(strides, (list, tuple)) else [strides] * 2
    pd = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 2
    dl = dilations if isinstance(dilations, (list, tuple)) else [dilations] * 2
    if len(pd) == 2:
        pd = [pd[0], pd[0], pd[1], pd[1]]

    def f(v):
        n, c, h, w = v.shape
        v = jnp.pad(v, [(0, 0), (0, 0), (pd[0], pd[1]), (pd[2], pd[3])])
        out_h = (v.shape[2] - (dl[0] * (ks[0] - 1) + 1)) // st[0] + 1
        out_w = (v.shape[3] - (dl[1] * (ks[1] - 1) + 1)) // st[1] + 1
        cols = []
        for i in range(ks[0]):
            for j in range(ks[1]):
                patch = v[:, :,
                          i * dl[0]: i * dl[0] + out_h * st[0]: st[0],
                          j * dl[1]: j * dl[1] + out_w * st[1]: st[1]]
                cols.append(patch)
        out = jnp.stack(cols, axis=2)  # n, c, k*k, oh, ow
        return out.reshape(n, c * ks[0] * ks[1], out_h * out_w)
    return _apply(f, x, op_name="unfold")


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    os_ = output_sizes if isinstance(output_sizes, (list, tuple)) else [output_sizes] * 2
    ks = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) else [kernel_sizes] * 2
    st = strides if isinstance(strides, (list, tuple)) else [strides] * 2
    pd = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 2
    dl = dilations if isinstance(dilations, (list, tuple)) else [dilations] * 2

    def f(v):
        n, ckk, L = v.shape
        c = ckk // (ks[0] * ks[1])
        H = os_[0] + 2 * pd[0]
        W = os_[1] + 2 * pd[1]
        out_h = (H - (dl[0] * (ks[0] - 1) + 1)) // st[0] + 1
        out_w = (W - (dl[1] * (ks[1] - 1) + 1)) // st[1] + 1
        v = v.reshape(n, c, ks[0], ks[1], out_h, out_w)
        out = jnp.zeros((n, c, H, W), v.dtype)
        for i in range(ks[0]):
            for j in range(ks[1]):
                out = out.at[:, :,
                             i * dl[0]: i * dl[0] + out_h * st[0]: st[0],
                             j * dl[1]: j * dl[1] + out_w * st[1]: st[1]].add(
                    v[:, :, i, j])
        return out[:, :, pd[0]: H - pd[0], pd[1]: W - pd[1]]
    return _apply(f, x, op_name="fold")


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def f(v, *pd):
        k = v.shape[-1]
        if pd:
            return (1 - epsilon) * v + epsilon * pd[0]
        return (1 - epsilon) * v + epsilon / k
    if prior_dist is not None:
        return _apply(f, label, prior_dist, op_name="label_smooth")
    return _apply(f, label, op_name="label_smooth")


def class_center_sample(label, num_classes, num_samples, group=None):
    lab = np.asarray(label._value)
    pos = np.unique(lab)
    if len(pos) >= num_samples:
        sampled = pos
    else:
        rest = np.setdiff1d(np.arange(num_classes), pos)
        extra = np.random.choice(rest, num_samples - len(pos), replace=False)
        sampled = np.sort(np.concatenate([pos, extra]))
    remap = {c: i for i, c in enumerate(sampled)}
    remapped = np.array([remap[v] for v in lab], np.int32)
    return (Tensor(jnp.asarray(remapped)), Tensor(jnp.asarray(sampled.astype(np.int32))))


def bilinear(x1, x2, weight, bias=None, name=None):
    """out[b, k] = x1[b, :] W[k] x2[b, :] (+ bias) (parity:
    nn/functional/common.py bilinear, BilinearTensorProduct kernel) —
    one einsum, MXU-friendly."""
    def f(a, b, w, *rest):
        out = jnp.einsum("bi,kij,bj->bk", a, w, b)
        if rest:
            out = out + rest[0]
        return out
    args = (x1, x2, weight) + ((bias,) if bias is not None else ())
    return _apply(f, *args, op_name="bilinear")


def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None):
    """Batched vectors -> batched diagonal matrices (parity:
    nn/functional/extension.py diag_embed)."""
    from ...tensor.creation import to_tensor as _tt
    x = input if hasattr(input, "_value") else _tt(input)

    def f(v):
        last = v.shape[-1]
        n = last + abs(offset)
        out_shape = v.shape[:-1] + (n, n)
        d = jnp.zeros(out_shape, v.dtype)
        idx = jnp.arange(last)
        rows = idx + max(-offset, 0)
        cols = idx + max(offset, 0)
        d = d.at[..., rows, cols].set(v)
        # move the two diagonal dims into position
        nd = d.ndim
        d1, d2 = dim1 % nd, dim2 % nd
        perm = [i for i in range(nd) if i not in (nd - 2, nd - 1)]
        order = sorted([(d1, nd - 2), (d2, nd - 1)])
        for dest, src in order:
            perm.insert(dest, src)
        return jnp.transpose(d, perm)
    return _apply(f, x, op_name="diag_embed")


def gather_tree(ids, parents):
    """Back-trace beam-search parent pointers into full sequences
    (parity: operators/gather_tree_op.cc, used by nn.dynamic_decode).
    ``ids``/``parents``: (T, B, beam)."""
    def f(i, p):
        T = i.shape[0]

        def step(carry, xs):
            beams = carry            # (B, beam) beam indices at t+1
            ids_t, par_t = xs        # each (B, beam)
            out = jnp.take_along_axis(ids_t, beams, axis=-1)
            prev = jnp.take_along_axis(par_t, beams, axis=-1)
            return prev, out
        init = jnp.broadcast_to(jnp.arange(i.shape[-1]), i.shape[1:])
        _, rev = jax.lax.scan(step, init, (i[::-1], p[::-1]))
        return rev[::-1]
    return _apply(f, ids, parents, op_name="gather_tree")


def cos_sim(X, Y, name=None):
    """Row-wise cosine similarity (reference fluid/layers/nn.py:921,
    operators/cos_sim_op.*): Y broadcasts when it has one row. Returns
    [N, 1]."""
    def f(x, y):
        if y.shape[0] == 1 and x.shape[0] != 1:
            y = jnp.broadcast_to(y, x.shape)
        num = jnp.sum(x * y, axis=-1)
        den = jnp.sqrt(jnp.sum(x * x, axis=-1)) \
            * jnp.sqrt(jnp.sum(y * y, axis=-1))
        return (num / jnp.maximum(den, 1e-12))[:, None]
    return _apply(f, X, Y, op_name="cos_sim")


def data_norm(x, batch_size, batch_sum, batch_square_sum, scale_w=None,
              bias=None, epsilon=1e-4, name=None):
    """The PS-CTR running normalizer (reference operators/
    data_norm_op.cc:302): means = batch_sum / batch_size, scales =
    sqrt(batch_size / batch_square_sum); out = (x - means) * scales
    (optionally folded with scale_w/bias)."""
    args = [x, batch_size, batch_sum, batch_square_sum]
    has_affine = scale_w is not None
    if has_affine:
        args += [scale_w, bias]

    def f(xv, bsz, bsum, bsq, *affine):
        means = bsum / bsz
        scales = jnp.sqrt(bsz / jnp.maximum(bsq, epsilon))
        out = (xv - means[None, :]) * scales[None, :]
        if affine:
            out = out * affine[0][None, :] + affine[1][None, :]
        return out
    return _apply(f, *args, op_name="data_norm")
