"""Pooling functionals (parity: python/paddle/nn/functional/pooling.py;
reference kernels operators/pool_op.*, adaptive variants). Implemented with
``lax.reduce_window`` — XLA's native windowed reduction on TPU."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.core import Tensor, _apply

__all__ = ["avg_pool1d", "avg_pool2d", "avg_pool3d", "max_pool1d",
           "max_pool2d", "max_pool3d", "adaptive_avg_pool1d",
           "adaptive_avg_pool2d", "adaptive_avg_pool3d",
           "adaptive_max_pool1d", "adaptive_max_pool2d",
           "adaptive_max_pool3d"]


def _pair(v, n):
    if isinstance(v, (list, tuple)):
        vv = list(v)
        if len(vv) == 1:
            vv = vv * n
        return tuple(int(i) for i in vv)
    return (int(v),) * n


def _resolve_padding(padding, n, kernel, stride, sizes, ceil_mode):
    if isinstance(padding, str):
        if padding.upper() == "VALID":
            return [(0, 0)] * n
        pads = []
        for i in range(n):
            out = -(-sizes[i] // stride[i])
            total = max(0, (out - 1) * stride[i] + kernel[i] - sizes[i])
            pads.append((total // 2, total - total // 2))
        return pads
    p = _pair(padding, n) if not (isinstance(padding, (list, tuple)) and
                                  isinstance(padding[0], (list, tuple))) else None
    if p is not None:
        pads = [(pp, pp) for pp in p]
    else:
        pads = [tuple(pp) for pp in padding]
    if ceil_mode:
        pads = [
            (lo, hi + stride[i] - 1) for i, (lo, hi) in enumerate(pads)]
    return pads


def _pool(x, kernel_size, stride, padding, n, reducer, init, avg,
          exclusive=True, ceil_mode=False, data_format="NCHW"):
    kernel = _pair(kernel_size, n)
    stride = _pair(stride if stride is not None else kernel_size, n)
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    nd = x._value.ndim
    if channel_last:
        window = (1,) + kernel + (1,)
        strides = (1,) + stride + (1,)
        sp_axes = list(range(1, nd - 1))
    else:
        window = (1, 1) + kernel
        strides = (1, 1) + stride
        sp_axes = list(range(2, nd))
    sizes = [x._value.shape[a] for a in sp_axes]
    pads = _resolve_padding(padding, n, kernel, stride, sizes, ceil_mode)
    full_pads = ([(0, 0)] + pads + [(0, 0)]) if channel_last else \
        ([(0, 0), (0, 0)] + pads)

    def f(v):
        # NOTE: init values must be Python literals — jax recognises the
        # (literal, add/max) monoid to derive the reverse-mode rule for
        # reduce_window; traced-array inits break that pattern match.
        if avg:
            summed = jax.lax.reduce_window(
                v, 0.0, jax.lax.add, window, strides, full_pads)
            if exclusive and any(p != (0, 0) for p in pads):
                ones = jnp.ones_like(v)
                counts = jax.lax.reduce_window(
                    ones, 0.0, jax.lax.add, window, strides, full_pads)
                return summed / counts
            return (summed / np.prod(kernel)).astype(v.dtype)
        return jax.lax.reduce_window(v, -jnp.inf, jax.lax.max, window,
                                     strides, full_pads)
    return _apply(f, x, op_name="pool")


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    out = _pool(x, kernel_size, stride, padding, 1, jax.lax.max, -jnp.inf,
                False, ceil_mode=ceil_mode,
                data_format="NLC" if data_format == "NLC" else "NCL")
    return out


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, 2, jax.lax.max, -jnp.inf,
                 False, ceil_mode=ceil_mode, data_format=data_format)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, jax.lax.max, -jnp.inf,
                 False, ceil_mode=ceil_mode, data_format=data_format)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    return _pool(x, kernel_size, stride, padding, 1, jax.lax.add, 0.0, True,
                 exclusive=exclusive, ceil_mode=ceil_mode,
                 data_format="NLC" if data_format == "NLC" else "NCL")


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 2, jax.lax.add, 0.0, True,
                 exclusive=exclusive, ceil_mode=ceil_mode,
                 data_format=data_format)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 3, jax.lax.add, 0.0, True,
                 exclusive=exclusive, ceil_mode=ceil_mode,
                 data_format=data_format)


def _adaptive_pool(x, output_size, n, avg, data_format):
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    out_sizes = _pair(output_size, n)
    nd = x._value.ndim
    sp_axes = list(range(1, nd - 1)) if channel_last else list(range(2, nd))

    def f(v):
        out = v
        for i, ax in enumerate(sp_axes):
            in_sz = v.shape[ax]
            o = out_sizes[i]
            if o is None:
                continue
            if in_sz % o == 0:
                k = in_sz // o
                # reshape trick: split the axis into (o, k) and reduce k
                new_shape = out.shape[:ax] + (o, k) + out.shape[ax + 1:]
                r = out.reshape(new_shape)
                out = r.mean(axis=ax + 1) if avg else r.max(axis=ax + 1)
            else:
                # general case: per-output-bin gather + reduce
                starts = (np.arange(o) * in_sz) // o
                ends = ((np.arange(o) + 1) * in_sz + o - 1) // o
                pieces = []
                for s, e in zip(starts, ends):
                    seg = jax.lax.slice_in_dim(out, int(s), int(e), axis=ax)
                    seg = seg.mean(axis=ax, keepdims=True) if avg else \
                        seg.max(axis=ax, keepdims=True)
                    pieces.append(seg)
                out = jnp.concatenate(pieces, axis=ax)
        return out
    return _apply(f, x, op_name="adaptive_pool")


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(x, output_size, 1, True, "NCL")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(x, output_size, 2, True, data_format)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(x, output_size, 3, True, data_format)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 1, False, "NCL")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 2, False, "NCHW")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 3, False, "NCDHW")
