"""paddle_tpu.nn.functional — functional mirror of the layer library
(parity surface: python/paddle/nn/functional/)."""
from .activation import *  # noqa: F401,F403
from .attention import *  # noqa: F401,F403
from .common import *  # noqa: F401,F403
from .conv import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .pooling import *  # noqa: F401,F403
from .crf import *  # noqa: F401,F403
from .sequence import *  # noqa: F401,F403
from .vision import *  # noqa: F401,F403

from . import (activation, attention, common, conv, crf,  # noqa: F401
               loss, pooling, sequence, vision)

__all__ = (activation.__all__ + attention.__all__ + common.__all__ +
           conv.__all__ + crf.__all__ + loss.__all__ + pooling.__all__ +
           sequence.__all__ + vision.__all__)
