"""Sequence-labeling op family: linear-chain CRF, Viterbi decode, edit
distance, CTC greedy decode, chunk evaluation.

Parity targets:
- linear_chain_crf  — fluid/layers/nn.py:726, operators/linear_chain_crf_op.{cc,h}
- crf_decoding      — fluid/layers/nn.py:853, operators/crf_decoding_op.h
  (with Label given, 1 marks a CORRECT position, crf_decoding_op.h:109)
- edit_distance     — fluid/layers/loss.py:360, operators/edit_distance_op.cc
- ctc_greedy_decoder — fluid/layers/nn.py:5267, operators/ctc_align_op.cc
- chunk_eval        — fluid/layers/nn.py:1069, operators/chunk_eval_op.cc

TPU-native shape contract: LoD sequences become padded [N, S] + lengths
(the framework-wide convention, nn/functional/sequence.py). The CRF
recursions are ``lax.scan`` over time — static shapes, jit/grad-safe; the
transition parameter keeps the reference's [num_tags + 2, num_tags]
layout (row 0 start weights, row 1 stop weights, rows 2: the square
transition matrix) so checkpoints translate 1:1.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.core import Tensor, _apply

__all__ = ["linear_chain_crf", "crf_decoding", "viterbi_decode",
           "edit_distance", "ctc_greedy_decoder", "chunk_eval"]


def _split_transition(transition):
    """[T+2, T] -> (start[T], stop[T], trans[T, T]) per the reference
    layout (linear_chain_crf_op.h: w[0]=a, w[1]=b, w[2:]=square)."""
    return transition[0], transition[1], transition[2:]


def _mask_from_length(length, n, s):
    if length is None:
        return jnp.ones((n, s), jnp.float32)
    t = jnp.arange(s)[None, :]
    return (t < jnp.reshape(length, (-1, 1))).astype(jnp.float32)


def linear_chain_crf(input, label, transition, length=None):
    """Negative log-likelihood of ``label`` paths under a linear-chain
    CRF — the quantity the reference's crf_cost minimizes.

    Args: input [N, S, T] emissions; label [N, S] int; transition
    [T+2, T] (learnable); length [N] optional valid lengths.
    Returns [N, 1] float32 NLL (differentiable w.r.t. input/transition).
    """
    args = [input, label, transition] + ([length] if length is not None
                                         else [])

    def f(em, lab, w, *rest):
        ln = rest[0] if rest else None
        em = em.astype(jnp.float32)
        n, s, t = em.shape
        start, stop, trans = _split_transition(w.astype(jnp.float32))
        mask = _mask_from_length(ln, n, s)
        lab = lab.astype(jnp.int32)
        lens = (jnp.full((n,), s, jnp.int32) if ln is None
                else jnp.reshape(ln, (-1,)).astype(jnp.int32))

        # ---- numerator: score of the labeled path -------------------
        em_path = jnp.take_along_axis(em, lab[:, :, None],
                                      axis=2)[..., 0]          # [N,S]
        num = jnp.sum(em_path * mask, axis=1)
        num = num + start[lab[:, 0]]
        last = jnp.take_along_axis(lab, (lens - 1)[:, None],
                                   axis=1)[:, 0]
        num = num + stop[last]
        pair = trans[lab[:, :-1], lab[:, 1:]]                  # [N,S-1]
        num = num + jnp.sum(pair * mask[:, 1:], axis=1)

        # ---- denominator: log Z via the alpha recursion -------------
        alpha0 = start[None, :] + em[:, 0]                     # [N,T]

        def step(alpha, inp):
            e_t, m_t = inp
            nxt = jax.nn.logsumexp(alpha[:, :, None] + trans[None],
                                   axis=1) + e_t
            alpha = jnp.where(m_t[:, None] > 0, nxt, alpha)
            return alpha, None

        xs = (jnp.swapaxes(em[:, 1:], 0, 1),
              jnp.swapaxes(mask[:, 1:], 0, 1))
        alpha, _ = jax.lax.scan(step, alpha0, xs)
        logz = jax.nn.logsumexp(alpha + stop[None, :], axis=1)
        return (logz - num)[:, None]

    return _apply(f, *args, op_name="linear_chain_crf")


def crf_decoding(input, transition, label=None, length=None):
    """Viterbi decode. Without ``label``: the best path [N, S] int64
    (positions past ``length`` are 0). With ``label``: a [N, S] 0/1
    tensor where **1 marks a correct position** (crf_decoding_op.h:109).
    """
    args = [input, transition] + ([label] if label is not None else []) \
        + ([length] if length is not None else [])
    # close over plain bools, not the optional Tensors — a Tensor in a
    # closure cell makes the eager vjp-cache key unhashable (core.py
    # _key_scalar) and every call would re-trace the Viterbi scan
    has_label, has_len = label is not None, length is not None

    def f(em, w, *rest):
        rest = list(rest)
        lab = rest.pop(0) if has_label else None
        ln = rest.pop(0) if has_len else None
        em = em.astype(jnp.float32)
        n, s, t = em.shape
        start, stop, trans = _split_transition(w.astype(jnp.float32))
        mask = _mask_from_length(ln, n, s)
        lens = (jnp.full((n,), s, jnp.int32) if ln is None
                else jnp.reshape(ln, (-1,)).astype(jnp.int32))

        alpha0 = start[None, :] + em[:, 0]

        def fwd(alpha, inp):
            e_t, m_t = inp
            scores = alpha[:, :, None] + trans[None]          # [N,T,T]
            bp = jnp.argmax(scores, axis=1).astype(jnp.int32)  # [N,T]
            nxt = jnp.max(scores, axis=1) + e_t
            alpha_new = jnp.where(m_t[:, None] > 0, nxt, alpha)
            # frozen rows carry identity backpointers so traceback
            # walks through padding unchanged
            bp = jnp.where(m_t[:, None] > 0, bp,
                           jnp.arange(t, dtype=jnp.int32)[None, :])
            return alpha_new, bp

        xs = (jnp.swapaxes(em[:, 1:], 0, 1),
              jnp.swapaxes(mask[:, 1:], 0, 1))
        alpha, bps = jax.lax.scan(fwd, alpha0, xs)            # [S-1,N,T]
        best_last = jnp.argmax(alpha + stop[None, :],
                               axis=1).astype(jnp.int32)      # [N]

        def back(tag, bp):
            # emit the PREDECESSOR: at reverse step k the emitted value
            # is path[k] = bp_k[path[k+1]] (emitting the carry instead
            # would drop path[0] and duplicate the last tag)
            prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
            return prev, prev

        _, path_rev = jax.lax.scan(back, best_last, bps, reverse=True)
        path = jnp.concatenate(
            [path_rev, best_last[None]], axis=0)               # [S,N]
        path = jnp.swapaxes(path, 0, 1).astype(jnp.int64)      # [N,S]
        path = jnp.where(mask > 0, path, 0)
        if lab is None:
            return path
        return ((path == lab.astype(jnp.int64)) & (mask > 0)) \
            .astype(jnp.int64)

    return _apply(f, *args, op_name="crf_decoding")


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True):
    """Convenience wrapper over :func:`crf_decoding` returning
    ``(scores, path)``. NOTE: the 2021-era reference exposes only the
    crf_decoding op (no ``paddle.text.viterbi_decode``); this helper
    exists for decode-score consumers. ``include_bos_eos_tag=True``
    expects our reference-layout ``[T+2, T]`` transitions; ``False``
    takes the square ``[T, T]`` matrix (start/stop weights zero)."""
    tp = transition_params
    if not include_bos_eos_tag:
        t = tp.shape[-1] if hasattr(tp, "shape") else tp._value.shape[-1]
        zeros = Tensor(jnp.zeros((2, t), jnp.float32))
        from ...tensor.manipulation import concat
        tp = concat([zeros, tp], axis=0)
    path = crf_decoding(potentials, tp, length=lengths)

    def score_of(em, w, p, *rest):
        ln = rest[0] if rest else None
        em = em.astype(jnp.float32)
        n, s, t = em.shape
        start, stop, trans = _split_transition(w.astype(jnp.float32))
        mask = _mask_from_length(ln, n, s)
        lens = (jnp.full((n,), s, jnp.int32) if ln is None
                else jnp.reshape(ln, (-1,)).astype(jnp.int32))
        p32 = p.astype(jnp.int32)
        em_path = jnp.take_along_axis(em, p32[:, :, None],
                                      axis=2)[..., 0]
        sc = jnp.sum(em_path * mask, axis=1) + start[p32[:, 0]]
        last = jnp.take_along_axis(p32, (lens - 1)[:, None],
                                   axis=1)[:, 0]
        sc = sc + stop[last]
        sc = sc + jnp.sum(trans[p32[:, :-1], p32[:, 1:]] * mask[:, 1:],
                          axis=1)
        return sc

    args = [potentials, tp, path] + ([lengths] if lengths is not None
                                     else [])
    scores = _apply(score_of, *args, op_name="viterbi_score")
    return scores, path


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None):
    """Levenshtein distance between token sequences (reference
    operators/edit_distance_op.cc). Inputs are padded [N, S] int with
    optional lengths. Returns (distance [N, 1] float32, sequence_num).
    """
    args = [input, label] \
        + ([input_length] if input_length is not None else []) \
        + ([label_length] if label_length is not None else [])
    has_hl, has_rl = input_length is not None, label_length is not None

    def f(hyp, ref, *rest):
        rest = list(rest)
        hl = rest.pop(0) if has_hl else None
        rl = rest.pop(0) if has_rl else None
        hyp = hyp.astype(jnp.int32)
        ref = ref.astype(jnp.int32)
        n, sh = hyp.shape
        sr = ref.shape[1]
        hlen = (jnp.full((n,), sh, jnp.int32) if hl is None
                else jnp.reshape(hl, (-1,)).astype(jnp.int32))
        rlen = (jnp.full((n,), sr, jnp.int32) if rl is None
                else jnp.reshape(rl, (-1,)).astype(jnp.int32))
        if ignored_tokens:
            # drop ignored tokens by compacting valid entries left
            for tok in ignored_tokens:
                keep_h = (hyp != tok) & (jnp.arange(sh)[None, :]
                                         < hlen[:, None])
                order = jnp.argsort(~keep_h, axis=1, stable=True)
                hyp = jnp.take_along_axis(hyp, order, axis=1)
                hlen = keep_h.sum(axis=1).astype(jnp.int32)
                keep_r = (ref != tok) & (jnp.arange(sr)[None, :]
                                         < rlen[:, None])
                order = jnp.argsort(~keep_r, axis=1, stable=True)
                ref = jnp.take_along_axis(ref, order, axis=1)
                rlen = keep_r.sum(axis=1).astype(jnp.int32)

        # DP over ref positions; row = distances over hyp prefix [0..sh]
        big = jnp.float32(1e9)
        row0 = jnp.minimum(jnp.arange(sh + 1, dtype=jnp.float32),
                           hlen[:, None].astype(jnp.float32))
        # row0[j] = min(j, hlen): j>hlen is clamped (those cells are
        # never read for the final answer)
        row0 = jnp.broadcast_to(row0, (n, sh + 1))

        def step(row, inp):
            # classic row relax: new[k+1] = min(row[k+1]+1 (delete),
            # new[k]+1 (insert), row[k]+sub[k] (substitute)); columns
            # past hlen and rows past rlen freeze so the final read at
            # (rlen, hlen) is exact
            j, r_j = inp      # 1-based ref index, ref tokens [N]
            valid_r = (j <= rlen)
            sub = (hyp != r_j[:, None]).astype(jnp.float32)    # [N,sh]

            def relax(new_prev, k):
                cand = jnp.minimum(
                    jnp.minimum(row[:, k + 1] + 1.0, new_prev + 1.0),
                    row[:, k] + sub[:, k])
                cand = jnp.where(k < hlen, cand, new_prev)
                return cand, cand
            new0 = jnp.minimum(jnp.float32(j),
                               rlen.astype(jnp.float32))
            new0 = jnp.broadcast_to(new0, (n,))
            _, cols = jax.lax.scan(relax, new0, jnp.arange(sh))
            new = jnp.concatenate(
                [new0[None], cols], axis=0)                    # [sh+1,N]
            new = jnp.swapaxes(new, 0, 1)
            new = jnp.where(valid_r[:, None], new, row)
            return new, None

        xs = (jnp.arange(1, sr + 1), jnp.swapaxes(ref, 0, 1))
        row, _ = jax.lax.scan(step, row0, xs)
        d = jnp.take_along_axis(row, hlen[:, None], axis=1)[:, 0]
        if normalized:
            d = d / jnp.maximum(rlen.astype(jnp.float32), 1.0)
        return d[:, None]

    dist = _apply(f, *args, op_name="edit_distance")
    n = input._value.shape[0] if isinstance(input, Tensor) \
        else np.asarray(input).shape[0]
    return dist, Tensor(jnp.asarray([n], jnp.int64))


def ctc_greedy_decoder(input, blank, input_length=None, padding_value=0):
    """Greedy CTC decode: argmax per frame, merge repeats, drop blanks
    (reference operators/ctc_align_op.cc). Static-shape output: decoded
    [N, S] int64 padded with ``padding_value`` (default 0, matching
    fluid.layers.ctc_greedy_decoder) + lengths [N, 1]."""
    args = [input] + ([input_length] if input_length is not None else [])

    def f(logits, *rest):
        ln = rest[0] if rest else None
        n, s = logits.shape[0], logits.shape[1]
        ids = jnp.argmax(logits, axis=-1).astype(jnp.int32)    # [N,S]
        frame_ok = _mask_from_length(ln, n, s) > 0
        prev = jnp.concatenate(
            [jnp.full((n, 1), -1, jnp.int32), ids[:, :-1]], axis=1)
        keep = frame_ok & (ids != blank) & (ids != prev)
        # compact kept tokens left (stable argsort of drop flags)
        order = jnp.argsort(~keep, axis=1, stable=True)
        toks = jnp.take_along_axis(ids, order, axis=1).astype(jnp.int64)
        cnt = keep.sum(axis=1).astype(jnp.int64)
        pos_ok = jnp.arange(s)[None, :] < cnt[:, None]
        toks = jnp.where(pos_ok, toks, padding_value)
        return toks, cnt[:, None]

    out = _apply(f, *args, op_name="ctc_greedy_decoder")
    return out[0], out[1]


def _extract_chunks(tags, length, scheme, num_chunk_types,
                    excluded=frozenset()):
    """Host-side chunk extraction for one sequence (list of label ids)."""
    n_tag = {"IOB": 2, "IOE": 2, "IOBES": 4, "plain": 1}[scheme]
    chunks = []
    start = None
    cur_type = None

    def flush(end):
        nonlocal start, cur_type
        if start is not None and cur_type is not None \
                and cur_type not in excluded:
            chunks.append((start, end, cur_type))
        start, cur_type = None, None

    for i in range(length):
        lab = int(tags[i])
        if lab < 0 or lab >= n_tag * num_chunk_types:
            flush(i - 1)        # out-of-range (e.g. O tag id) ends chunk
            continue
        tag = lab % n_tag
        ctype = lab // n_tag
        if scheme == "plain":
            # every in-range token is its own single-token chunk
            # (chunk_eval_op.cc plain scheme)
            flush(i - 1)
            start, cur_type = i, ctype
            flush(i)
        elif scheme == "IOB":   # tag 0 = B, 1 = I
            if tag == 0 or cur_type != ctype:
                flush(i - 1)
                start, cur_type = i, ctype
        elif scheme == "IOE":   # tag 0 = I, 1 = E
            if cur_type != ctype:
                flush(i - 1)
                start, cur_type = i, ctype
            if tag == 1:        # E closes the chunk at i
                flush(i)
        elif scheme == "IOBES":  # 0=B 1=I 2=E 3=S
            if tag == 3:
                flush(i - 1)
                start, cur_type = i, ctype
                flush(i)
            elif tag == 2:
                # E closes the running same-type chunk, or is a
                # single-token chunk when nothing matching is open
                if cur_type == ctype and start is not None:
                    flush(i)
                else:
                    flush(i - 1)
                    start, cur_type = i, ctype
                    flush(i)
            elif tag == 0 or cur_type != ctype:
                flush(i - 1)
                start, cur_type = i, ctype
    flush(length - 1)
    return set(chunks)


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None, seq_length=None):
    """Chunk-level precision/recall/F1 (reference chunk_eval_op.cc) —
    host-side metric (the reference op is CPU-only too). Label→(tag,
    type) mapping follows the reference: tag = label % num_tag_types,
    type = label // num_tag_types.

    Returns (precision, recall, f1, num_infer_chunks, num_label_chunks,
    num_correct_chunks) as Tensors."""
    pred = np.asarray(input.numpy() if isinstance(input, Tensor)
                      else input)
    lab = np.asarray(label.numpy() if isinstance(label, Tensor)
                     else label)
    if pred.ndim == 1:
        pred, lab = pred[None], lab[None]
    n, s = pred.shape
    lens = (np.full((n,), s, np.int64) if seq_length is None else
            np.asarray(seq_length.numpy() if isinstance(
                seq_length, Tensor) else seq_length).reshape(-1))
    excluded = frozenset(excluded_chunk_types or ())
    n_inf = n_lab = n_cor = 0
    for i in range(n):
        pi = _extract_chunks(pred[i], int(lens[i]), chunk_scheme,
                             num_chunk_types, excluded)
        li = _extract_chunks(lab[i], int(lens[i]), chunk_scheme,
                             num_chunk_types, excluded)
        n_inf += len(pi)
        n_lab += len(li)
        n_cor += len(pi & li)
    p = n_cor / n_inf if n_inf else 0.0
    r = n_cor / n_lab if n_lab else 0.0
    f1 = 2 * p * r / (p + r) if p + r else 0.0
    mk = lambda v, dt: Tensor(jnp.asarray([v], dt))  # noqa: E731
    return (mk(p, jnp.float32), mk(r, jnp.float32), mk(f1, jnp.float32),
            mk(n_inf, jnp.int64), mk(n_lab, jnp.int64),
            mk(n_cor, jnp.int64))
