"""Compat alias module (reference python/paddle/nn/functional/extension.py
exposes diag_embed and friends as a submodule import target)."""
from .common import diag_embed, gather_tree  # noqa: F401
from .sequence import sequence_mask  # noqa: F401

__all__ = ["diag_embed", "gather_tree", "sequence_mask"]
