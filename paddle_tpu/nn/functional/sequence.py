"""Sequence ops — TPU-native replacement for the reference's LoD machinery.

The reference represents variable-length batches as LoDTensor (ragged
offsets, framework/lod_tensor.h:114) with ~30 LoD kernels under
operators/sequence_ops/ (sequence_pool_op.cc, sequence_pad_op.cc,
sequence_mask_op.cc, sequence_softmax_op.cc, sequence_expand_op.cc …).
Ragged offsets force dynamic shapes, which XLA cannot tile onto the MXU —
so the TPU-native representation is **dense padded [batch, max_len, ...] +
a lengths vector**, and every op is a masked dense computation with static
shapes. Autograd flows through the standard eager tape (and the same code
traces under jit).
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ...framework.core import Tensor, _apply, to_tensor

__all__ = ["sequence_mask", "sequence_pad", "sequence_unpad",
           "sequence_pool", "sequence_softmax", "sequence_expand",
           "sequence_first_step", "sequence_last_step",
           "sequence_reverse", "sequence_concat", "sequence_slice"]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _len_val(lengths):
    return lengths._value if isinstance(lengths, Tensor) else jnp.asarray(lengths)


def sequence_mask(lengths, maxlen: Optional[int] = None, dtype="int64",
                  name=None) -> Tensor:
    """[batch] lengths -> [batch, maxlen] 0/1 mask (parity:
    operators/sequence_ops/sequence_mask_op.cc, fluid.layers.sequence_mask).
    """
    lv = _len_val(lengths)
    if maxlen is None:
        maxlen = int(np.asarray(lv).max()) if lv.size else 0
    from ...framework import dtype as dtypes
    import jax
    if dtype in ("int64", np.int64) and not jax.config.jax_enable_x64:
        dtype = "int32"  # avoid a per-call truncation UserWarning
    jd = dtypes.to_jax(dtype)

    def fn(l):
        return (jnp.arange(maxlen)[None, :] < l[..., None]).astype(jd)

    return _apply(fn, _t(lengths), op_name="sequence_mask")


def sequence_pad(x, lengths, pad_value=0.0, maxlen: Optional[int] = None,
                 name=None):
    """Packed [total, ...] rows + lengths -> (padded [batch, maxlen, ...],
    lengths) (parity: operators/sequence_ops/sequence_pad_op.cc; the
    LoDTensor input becomes the packed-rows + lengths pair)."""
    x = _t(x)
    lv = np.asarray(_len_val(lengths)).astype(np.int64)
    if maxlen is None:
        maxlen = int(lv.max()) if lv.size else 0
    batch = lv.shape[0]
    # gather indices computed on host: shapes are static given lengths
    idx = np.zeros((batch, maxlen), np.int32)
    valid = np.zeros((batch, maxlen), bool)
    off = 0
    for b, n in enumerate(lv.tolist()):
        n = int(n)
        keep = min(n, maxlen)  # truncate the copy, NOT the packed offset
        idx[b, :keep] = np.arange(off, off + keep)
        valid[b, :keep] = True
        off += n

    def fn(xv):
        g = xv[idx.reshape(-1)].reshape((batch, maxlen) + xv.shape[1:])
        m = jnp.asarray(valid).reshape((batch, maxlen) + (1,) * (xv.ndim - 1))
        return jnp.where(m, g, jnp.asarray(pad_value, xv.dtype))

    out = _apply(fn, x, op_name="sequence_pad")
    return out, to_tensor(lv)


def sequence_unpad(x, lengths, name=None) -> Tensor:
    """Padded [batch, maxlen, ...] -> packed [total, ...] (parity:
    operators/sequence_ops/sequence_unpad_op.cc). Output row count depends
    on lengths, so this runs with concrete lengths (eager / host)."""
    x = _t(x)
    lv = np.asarray(_len_val(lengths)).astype(np.int64)
    rows = []
    for b, n in enumerate(lv.tolist()):
        rows.append(np.arange(b * x.shape[1], b * x.shape[1] + int(n)))
    flat_idx = np.concatenate(rows) if rows else np.zeros((0,), np.int64)

    def fn(xv):
        f = xv.reshape((-1,) + xv.shape[2:])
        return f[flat_idx]

    return _apply(fn, x, op_name="sequence_unpad")


def sequence_pool(x, lengths, pool_type: str = "sum", name=None) -> Tensor:
    """Masked pooling over the time axis of padded [batch, maxlen, ...]
    (parity: operators/sequence_ops/sequence_pool_op.cc — SUM/MEAN/MAX/
    SQRT/FIRST/LAST variants)."""
    x = _t(x)
    pool = pool_type.lower()
    maxlen = x.shape[1]
    ln = _t(lengths)

    def fn(xv, lv):
        m = (jnp.arange(maxlen)[None, :] < lv[:, None])
        mf = m.reshape(m.shape + (1,) * (xv.ndim - 2)).astype(xv.dtype)
        if pool == "sum":
            return (xv * mf).sum(axis=1)
        if pool == "average" or pool == "mean":
            d = jnp.maximum(lv, 1).astype(xv.dtype)
            return (xv * mf).sum(axis=1) / d.reshape(
                (-1,) + (1,) * (xv.ndim - 2))
        if pool == "sqrt":
            d = jnp.sqrt(jnp.maximum(lv, 1).astype(xv.dtype))
            return (xv * mf).sum(axis=1) / d.reshape(
                (-1,) + (1,) * (xv.ndim - 2))
        if pool == "max":
            neg = jnp.asarray(jnp.finfo(xv.dtype).min
                              if jnp.issubdtype(xv.dtype, jnp.floating)
                              else jnp.iinfo(xv.dtype).min, xv.dtype)
            return jnp.where(mf.astype(bool), xv, neg).max(axis=1)
        if pool == "first":
            return xv[:, 0]
        if pool == "last":
            i = jnp.maximum(lv - 1, 0)
            return jnp.take_along_axis(
                xv, i.reshape((-1, 1) + (1,) * (xv.ndim - 2)), axis=1
            ).squeeze(1)
        raise ValueError(f"unknown pool_type {pool_type}")

    return _apply(fn, x, ln, op_name=f"sequence_pool_{pool}")


def sequence_softmax(x, lengths, name=None) -> Tensor:
    """Masked softmax along axis 1 of padded [batch, maxlen, ...] (parity:
    operators/sequence_ops/sequence_softmax_op.cc); padding positions get
    probability 0."""
    import jax

    x = _t(x)
    maxlen = x.shape[1]

    def fn(xv, lv):
        m = (jnp.arange(maxlen)[None, :] < lv[:, None])
        m = m.reshape(m.shape + (1,) * (xv.ndim - 2))
        neg = jnp.asarray(jnp.finfo(xv.dtype).min, xv.dtype)
        z = jnp.where(m, xv, neg)
        z = z - jax.lax.stop_gradient(z.max(axis=1, keepdims=True))
        e = jnp.where(m, jnp.exp(z), 0.0)
        return e / jnp.maximum(e.sum(axis=1, keepdims=True), 1e-30)

    return _apply(fn, x, _t(lengths), op_name="sequence_softmax")


def sequence_expand(x, ref_lengths, name=None) -> Tensor:
    """Repeat row b of ``x`` ref_lengths[b] times (parity:
    operators/sequence_ops/sequence_expand_op.cc in its common
    one-level-LoD use). Concrete lengths required (dynamic output rows)."""
    x = _t(x)
    lv = np.asarray(_len_val(ref_lengths)).astype(np.int64)
    idx = np.repeat(np.arange(lv.shape[0]), lv)

    def fn(xv):
        return xv[idx]

    return _apply(fn, x, op_name="sequence_expand")


def sequence_reverse(x, lengths=None, name=None) -> Tensor:
    """Reverse each sequence within its valid length; padding stays in
    place (parity: operators/sequence_ops/sequence_reverse_op.h)."""
    x = _t(x)
    maxlen = x.shape[1]
    if lengths is None:
        lengths = np.full((x.shape[0],), maxlen, np.int64)

    def fn(xv, lv):
        idx = jnp.arange(maxlen)[None, :]
        rev = lv[:, None] - 1 - idx            # reversed index inside seq
        src = jnp.where(idx < lv[:, None], rev, idx).astype(jnp.int32)
        return jnp.take_along_axis(
            xv, src.reshape(src.shape + (1,) * (xv.ndim - 2)), axis=1)

    return _apply(fn, x, _t(lengths), op_name="sequence_reverse")


def sequence_concat(xs, lengths_list, name=None):
    """Concatenate per-row sequences from several padded inputs ->
    (padded, lengths) (parity: sequence_ops/sequence_concat_op.h: rows
    are joined sequence-wise, not batch-wise)."""
    xs = [_t(x) for x in xs]
    lens = [np.asarray(_len_val(l)).astype(np.int64) for l in lengths_list]
    for xi, (x, ln) in enumerate(zip(xs, lens)):
        if np.any(ln < 0) or np.any(ln > x.shape[1]):
            raise ValueError(
                f"lengths for input {xi} must be in [0, {x.shape[1]}] "
                f"(its padded width), got {ln.tolist()} — an over-long "
                f"length would silently read the NEXT input's rows")
    total = np.sum(lens, axis=0)               # [batch]
    out_len = int(total.max()) if total.size else 0
    batch = xs[0].shape[0]
    # gather map computed host-side (lengths are concrete)
    idx_src = np.zeros((batch, out_len), np.int64)   # position in concat-x
    valid = np.zeros((batch, out_len), bool)
    widths = [x.shape[1] for x in xs]
    offsets = np.concatenate([[0], np.cumsum(widths)])[:-1]
    for b in range(batch):
        o = 0
        for xi, ln in enumerate(lens):
            n = int(ln[b])
            idx_src[b, o:o + n] = offsets[xi] + np.arange(n)
            valid[b, o:o + n] = True
            o += n

    def fn(*vals):
        cat = jnp.concatenate(vals, axis=1)    # [B, sum(widths), ...]
        g = jnp.take_along_axis(
            cat, jnp.asarray(idx_src).reshape(
                (batch, out_len) + (1,) * (cat.ndim - 2)), axis=1)
        m = jnp.asarray(valid).reshape(
            (batch, out_len) + (1,) * (cat.ndim - 2))
        return jnp.where(m, g, jnp.zeros((), cat.dtype))

    out = _apply(fn, *xs, op_name="sequence_concat")
    return out, to_tensor(total)


def sequence_slice(x, lengths, offset, length, name=None):
    """Per-row subsequence [offset, offset+length) -> (padded, lengths)
    (parity: sequence_ops/sequence_slice_op.h)."""
    x = _t(x)
    off = np.asarray(_len_val(offset)).astype(np.int64)
    ln = np.asarray(_len_val(length)).astype(np.int64)
    lv = np.asarray(_len_val(lengths)).astype(np.int64)
    if np.any(off < 0) or np.any(ln < 0):
        raise ValueError(
            f"offset and length must be non-negative, got "
            f"offsets={off.tolist()}, lengths={ln.tolist()} "
            f"(reference sequence_slice_op enforces offset >= 0)")
    if np.any(off + ln > lv):
        raise ValueError(
            f"slice [offset+length] exceeds sequence lengths: "
            f"offsets={off.tolist()}, lengths={ln.tolist()}, "
            f"seq_lengths={lv.tolist()}")
    out_len = int(ln.max()) if ln.size else 0
    batch = x.shape[0]

    def fn(xv):
        idx = (jnp.asarray(off)[:, None]
               + jnp.arange(out_len)[None, :]).astype(jnp.int32)
        idx = jnp.minimum(idx, xv.shape[1] - 1)
        g = jnp.take_along_axis(
            xv, idx.reshape((batch, out_len) + (1,) * (xv.ndim - 2)),
            axis=1)
        m = (jnp.arange(out_len)[None, :] < jnp.asarray(ln)[:, None])
        m = m.reshape((batch, out_len) + (1,) * (xv.ndim - 2))
        return jnp.where(m, g, jnp.zeros((), xv.dtype))

    return _apply(fn, x, op_name="sequence_slice"), to_tensor(ln)


def sequence_first_step(x, lengths=None, name=None) -> Tensor:
    n = lengths if lengths is not None else np.full((_t(x).shape[0],),
                                                    _t(x).shape[1])
    return sequence_pool(x, n, "first")


def sequence_last_step(x, lengths, name=None) -> Tensor:
    return sequence_pool(x, lengths, "last")


def sequence_conv(input, filter_weight, bias=None, context_length=3,
                  context_start=None, context_stride=1, length=None,
                  name=None):
    """Context-window convolution over padded sequences (reference
    fluid/layers/sequence_lod.py:44, operators/sequence_conv_op.*):
    each step concatenates ``context_length`` neighbor rows starting at
    offset ``context_start`` (default -(L-1)//2) and multiplies by
    ``filter_weight`` [context_length * H, F]. Input [N, S, H] (+
    optional lengths masking padded steps)."""
    from ..functional.crf import _mask_from_length
    from ...framework.core import _apply
    cs = -((context_length - 1) // 2) if context_start is None \
        else int(context_start)
    has_len = length is not None
    args = [input, filter_weight] + ([bias] if bias is not None else []) \
        + ([length] if has_len else [])
    has_bias = bias is not None

    def f(x, w, *rest):
        rest = list(rest)
        b = rest.pop(0) if has_bias else None
        ln = rest.pop(0) if has_len else None
        n, s, h = x.shape
        mask = _mask_from_length(ln, n, s)
        xm = x * mask[:, :, None].astype(x.dtype)
        cols = []
        for j in range(context_length):
            off = cs + j * context_stride
            cols.append(jnp.roll(xm, -off, axis=1) * (
                ((jnp.arange(s) + off >= 0)
                 & (jnp.arange(s) + off < s))[None, :, None]
            ).astype(x.dtype))
        ctx = jnp.concatenate(cols, axis=-1)      # [N,S,L*H]
        out = jnp.einsum("nsh,hf->nsf", ctx, w.astype(ctx.dtype))
        if b is not None:
            out = out + b
        return out * mask[:, :, None].astype(out.dtype)

    return _apply(f, *args, op_name="sequence_conv")


def row_conv(input, weight, act=None, length=None, name=None):
    """Lookahead row convolution (reference fluid/layers/nn.py:5666,
    operators/row_conv_op.*): out[t] = sum_i w[i] * x[t + i], kernel
    [future_context_size + 1, H]. Input [N, S, H]."""
    from ..functional.crf import _mask_from_length
    from ...framework.core import _apply
    has_len = length is not None
    args = [input, weight] + ([length] if has_len else [])

    def f(x, w, *rest):
        ln = rest[0] if rest else None
        n, s, h = x.shape
        k = w.shape[0]
        mask = _mask_from_length(ln, n, s)
        xm = x * mask[:, :, None].astype(x.dtype)
        out = jnp.zeros_like(xm)
        for i in range(k):
            shifted = jnp.roll(xm, -i, axis=1) * (
                (jnp.arange(s) + i < s)[None, :, None]).astype(x.dtype)
            out = out + shifted * w[i][None, None, :].astype(x.dtype)
        out = out * mask[:, :, None].astype(out.dtype)
        return out

    out = _apply(f, *args, op_name="row_conv")
    if act is not None:
        from .. import functional as F
        out = getattr(F, act)(out)
    return out


__all__ += ["sequence_conv", "row_conv"]
