"""Loss functionals (parity: python/paddle/nn/functional/loss.py; reference
kernels operators/cross_entropy_op.*, softmax_with_cross_entropy_op.*,
bce_loss_op.*, huber_loss_op.*, kldiv_loss_op.*, margin ops...)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.core import Tensor, _apply

__all__ = [
    "cross_entropy", "softmax_with_cross_entropy", "binary_cross_entropy",
    "binary_cross_entropy_with_logits", "nll_loss", "l1_loss", "mse_loss",
    "smooth_l1_loss", "kl_div", "margin_ranking_loss", "hinge_embedding_loss",
    "cosine_embedding_loss", "triplet_margin_loss", "ctc_loss", "square_error_cost",
    "log_loss", "npair_loss", "sigmoid_focal_loss", "dice_loss",
    "hsigmoid_loss",
]


def _reduce(v, reduction):
    if reduction == "mean":
        return jnp.mean(v)
    if reduction == "sum":
        return jnp.sum(v)
    return v


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, name=None):
    """Reference: operators/softmax_with_cross_entropy_op.* — fused
    log_softmax + NLL in one XLA expression (numerically stable)."""
    lab = label._value if isinstance(label, Tensor) else jnp.asarray(label)

    def f(v, *maybe_w):
        logp = jax.nn.log_softmax(v, axis=axis) if use_softmax else jnp.log(
            jnp.clip(v, 1e-12, None))
        if soft_label:
            per = -jnp.sum(lab.astype(logp.dtype) * logp, axis=axis)
        else:
            li = lab.astype(jnp.int32)
            if li.ndim == logp.ndim:
                li = jnp.squeeze(li, axis=axis)
            per = -jnp.take_along_axis(
                logp, jnp.expand_dims(li, axis), axis=axis).squeeze(axis)
            mask = (li != ignore_index)
            per = jnp.where(mask, per, jnp.zeros((), per.dtype))
            if maybe_w:
                w = maybe_w[0][li]
                per = per * w
                if reduction == "mean":
                    denom = jnp.sum(jnp.where(mask, w, jnp.zeros((), w.dtype)))
                    return jnp.sum(per) / jnp.maximum(denom, 1e-12)
            if reduction == "mean":
                n_valid = jnp.maximum(jnp.sum(mask.astype(per.dtype)), 1.0)
                return jnp.sum(per) / n_valid
        return _reduce(per, reduction)
    if weight is not None:
        return _apply(f, input, weight, op_name="cross_entropy")
    return _apply(f, input, op_name="cross_entropy")


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none",
                         axis=axis)
    loss = _apply(lambda v: jnp.expand_dims(v, axis), loss, op_name="unsq")
    if return_softmax:
        from .activation import softmax
        return loss, softmax(logits, axis=axis)
    return loss


def binary_cross_entropy(input, label, weight=None, reduction="mean",
                         name=None):
    def f(v, lab, *maybe_w):
        v = jnp.clip(v, 1e-12, 1 - 1e-12)
        per = -(lab * jnp.log(v) + (1 - lab) * jnp.log(1 - v))
        if maybe_w:
            per = per * maybe_w[0]
        return _reduce(per, reduction)
    args = [input, label] + ([weight] if weight is not None else [])
    return _apply(f, *args, op_name="bce")


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    def f(v, lab, *rest):
        i = 0
        w = None
        pw = None
        if weight is not None:
            w = rest[i]; i += 1
        if pos_weight is not None:
            pw = rest[i]; i += 1
        max_val = jnp.clip(-v, 0, None)
        if pw is not None:
            log_w = (pw - 1) * lab + 1
            per = (1 - lab) * v + log_w * (jnp.log(
                jnp.exp(-max_val) + jnp.exp(-v - max_val)) + max_val)
        else:
            per = (1 - lab) * v + max_val + jnp.log(
                jnp.exp(-max_val) + jnp.exp(-v - max_val))
        if w is not None:
            per = per * w
        return _reduce(per, reduction)
    args = [logit, label]
    if weight is not None:
        args.append(weight)
    if pos_weight is not None:
        args.append(pos_weight)
    return _apply(f, *args, op_name="bce_logits")


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    lab = label._value if isinstance(label, Tensor) else jnp.asarray(label)

    def f(v, *maybe_w):
        li = lab.astype(jnp.int32)
        per = -jnp.take_along_axis(v, jnp.expand_dims(li, 1), axis=1).squeeze(1)
        mask = li != ignore_index
        per = jnp.where(mask, per, jnp.zeros((), per.dtype))
        if maybe_w:
            wv = maybe_w[0][li]
            per = per * wv
            if reduction == "mean":
                return jnp.sum(per) / jnp.maximum(
                    jnp.sum(jnp.where(mask, wv, jnp.zeros((), wv.dtype))), 1e-12)
        if reduction == "mean":
            return jnp.sum(per) / jnp.maximum(jnp.sum(mask.astype(per.dtype)), 1.0)
        return _reduce(per, reduction)
    if weight is not None:
        return _apply(f, input, weight, op_name="nll_loss")
    return _apply(f, input, op_name="nll_loss")


def l1_loss(input, label, reduction="mean", name=None):
    return _apply(lambda a, b: _reduce(jnp.abs(a - b), reduction),
                  input, label, op_name="l1_loss")


def mse_loss(input, label, reduction="mean", name=None):
    return _apply(lambda a, b: _reduce((a - b) ** 2, reduction),
                  input, label, op_name="mse_loss")


def square_error_cost(input, label):
    return _apply(lambda a, b: (a - b) ** 2, input, label,
                  op_name="square_error_cost")


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def f(a, b):
        d = jnp.abs(a - b)
        per = jnp.where(d < delta, 0.5 * d * d, delta * (d - 0.5 * delta))
        return _reduce(per, reduction)
    return _apply(f, input, label, op_name="smooth_l1")


def kl_div(input, label, reduction="mean", name=None):
    def f(logp, tgt):
        per = tgt * (jnp.log(jnp.clip(tgt, 1e-12, None)) - logp)
        if reduction == "batchmean":
            return jnp.sum(per) / logp.shape[0]
        return _reduce(per, reduction)
    return _apply(f, input, label, op_name="kl_div")


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    def f(a, b, lab):
        per = jnp.clip(-lab * (a - b) + margin, 0, None)
        return _reduce(per, reduction)
    return _apply(f, input, other, label, op_name="margin_ranking")


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean",
                         name=None):
    def f(a, lab):
        per = jnp.where(lab == 1, a, jnp.clip(margin - a, 0, None))
        return _reduce(per, reduction)
    return _apply(f, input, label, op_name="hinge_embedding")


def cosine_embedding_loss(input1, input2, label, margin=0, reduction="mean",
                          name=None):
    def f(a, b, lab):
        cos = jnp.sum(a * b, -1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        per = jnp.where(lab == 1, 1 - cos, jnp.clip(cos - margin, 0, None))
        return _reduce(per, reduction)
    return _apply(f, input1, input2, label, op_name="cosine_embedding")


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean",
                        name=None):
    def f(a, pos, neg):
        def dist(x, y):
            return jnp.sum(jnp.abs(x - y + epsilon) ** p, axis=-1) ** (1 / p)
        d_pos = dist(a, pos)
        d_neg = dist(a, neg)
        if swap:
            d_neg = jnp.minimum(d_neg, dist(pos, neg))
        per = jnp.clip(d_pos - d_neg + margin, 0, None)
        return _reduce(per, reduction)
    return _apply(f, input, positive, negative, op_name="triplet_margin")


def log_loss(input, label, epsilon=1e-4, name=None):
    def f(v, lab):
        return -lab * jnp.log(v + epsilon) - (1 - lab) * jnp.log(
            1 - v + epsilon)
    return _apply(f, input, label, op_name="log_loss")


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    def f(a, p, lab):
        batch = a.shape[0]
        sim = jnp.matmul(a, p.T)
        tgt = (lab[:, None] == lab[None, :]).astype(sim.dtype)
        tgt = tgt / jnp.sum(tgt, axis=1, keepdims=True)
        logp = jax.nn.log_softmax(sim, axis=1)
        ce = -jnp.mean(jnp.sum(tgt * logp, axis=1))
        reg = l2_reg * (jnp.mean(jnp.sum(a * a, 1)) +
                        jnp.mean(jnp.sum(p * p, 1))) * 0.25
        return ce + reg
    return _apply(f, anchor, positive, labels, op_name="npair_loss")


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def f(v, lab, *maybe_norm):
        p = jax.nn.sigmoid(v)
        ce = jnp.clip(v, 0, None) - v * lab + jnp.log1p(jnp.exp(-jnp.abs(v)))
        p_t = p * lab + (1 - p) * (1 - lab)
        a_t = alpha * lab + (1 - alpha) * (1 - lab)
        per = a_t * ((1 - p_t) ** gamma) * ce
        if maybe_norm:
            per = per / maybe_norm[0]
        return _reduce(per, reduction)
    if normalizer is not None:
        return _apply(f, logit, label, normalizer, op_name="focal")
    return _apply(f, logit, label, op_name="focal")


def dice_loss(input, label, epsilon=1e-5, name=None):
    def f(v, lab):
        lab_oh = jax.nn.one_hot(lab.squeeze(-1).astype(jnp.int32),
                                v.shape[-1], dtype=v.dtype)
        inter = jnp.sum(v * lab_oh, axis=tuple(range(1, v.ndim)))
        union = jnp.sum(v, axis=tuple(range(1, v.ndim))) + jnp.sum(
            lab_oh, axis=tuple(range(1, lab_oh.ndim)))
        return jnp.mean(1 - 2 * inter / (union + epsilon))
    return _apply(f, input, label, op_name="dice_loss")


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC forward (reference: operators/warpctc_op.* wrapping warp-ctc).
    TPU-native: dynamic-programming alpha recursion with lax.scan."""
    ll = labels._value.astype(jnp.int32) if isinstance(labels, Tensor) else jnp.asarray(labels, jnp.int32)
    il = input_lengths._value.astype(jnp.int32) if isinstance(input_lengths, Tensor) else jnp.asarray(input_lengths, jnp.int32)
    tl = label_lengths._value.astype(jnp.int32) if isinstance(label_lengths, Tensor) else jnp.asarray(label_lengths, jnp.int32)

    def f(lp):
        # lp: (T, B, C) log-probs
        if lp.ndim != 3:
            raise ValueError("ctc_loss expects (T, B, C) log_probs")
        T, B, C = lp.shape
        S = ll.shape[1]
        # extended label seq with blanks: length 2S+1
        ext = jnp.full((B, 2 * S + 1), blank, jnp.int32)
        ext = ext.at[:, 1::2].set(ll)
        ext_len = 2 * tl + 1
        neg_inf = jnp.asarray(-1e30, lp.dtype)

        alpha0 = jnp.full((B, 2 * S + 1), neg_inf, lp.dtype)
        alpha0 = alpha0.at[:, 0].set(lp[0, jnp.arange(B), blank])
        first_lab = jnp.where(tl > 0, ll[:, 0], blank)
        alpha0 = alpha0.at[:, 1].set(
            jnp.where(tl > 0, lp[0, jnp.arange(B), first_lab], neg_inf))

        can_skip = jnp.logical_and(
            jnp.arange(2 * S + 1)[None, :] >= 2,
            ext != jnp.roll(ext, 2, axis=1))

        def step(alpha, lp_t):
            a_prev = alpha
            a_shift1 = jnp.concatenate(
                [jnp.full((B, 1), neg_inf, lp.dtype), alpha[:, :-1]], axis=1)
            a_shift2 = jnp.concatenate(
                [jnp.full((B, 2), neg_inf, lp.dtype), alpha[:, :-2]], axis=1)
            a_shift2 = jnp.where(can_skip, a_shift2, neg_inf)
            merged = jnp.logaddexp(jnp.logaddexp(a_prev, a_shift1), a_shift2)
            emit = jnp.take_along_axis(lp_t, ext, axis=1)
            return merged + emit, None

        def scan_step(alpha, lp_t):
            new, _ = step(alpha, lp_t)
            return new, new

        _, alphas = jax.lax.scan(scan_step, alpha0, lp[1:])
        alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # (T,B,2S+1)
        # pick alpha at t = il-1, s in {ext_len-1, ext_len-2}
        t_idx = jnp.clip(il - 1, 0, T - 1)
        a_T = alphas[t_idx, jnp.arange(B)]  # (B, 2S+1)
        lastA = jnp.take_along_axis(a_T, (ext_len - 1)[:, None], axis=1)[:, 0]
        lastB = jnp.take_along_axis(a_T, jnp.clip(ext_len - 2, 0)[:, None],
                                    axis=1)[:, 0]
        nll = -jnp.logaddexp(lastA, lastB)
        if reduction == "mean":
            return jnp.mean(nll / jnp.maximum(tl.astype(nll.dtype), 1.0))
        return _reduce(nll, reduction)
    return _apply(f, log_probs, op_name="ctc_loss")


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid loss (parity: operators/hierarchical_sigmoid_op.*
    and nn/functional/loss.py hsigmoid_loss). Returns (batch, 1) costs.

    Default (complete binary tree over ``num_classes`` in heap layout):
    each label's root->leaf path derives from its index with a fixed
    ``ceil(log2(C))`` unroll, so the whole loss is dense gathers + dot
    products — static shapes, jit-able. A custom tree passes
    ``path_table``/``path_code`` (batch, path_len), -1 padded.
    ``is_sparse`` is accepted for config parity (gathers already touch
    only the rows on the paths).
    """
    import math as _math
    nc = int(num_classes)

    if (path_table is None) != (path_code is None):
        raise ValueError(
            "hsigmoid_loss custom-tree mode needs BOTH path_table and "
            "path_code (got only one)")
    if path_table is not None:
        def f(x, tbl, code, w, *rest):
            b = rest[0] if rest else None
            valid = tbl >= 0
            idx = jnp.maximum(tbl, 0)
            logits = jnp.einsum("bf,blf->bl", x, w[idx])
            if b is not None:
                logits = logits + b[idx]
            t = code.astype(x.dtype)
            ll = (jnp.maximum(logits, 0) - logits * t
                  + jnp.log1p(jnp.exp(-jnp.abs(logits))))
            return jnp.sum(jnp.where(valid, ll, 0.0), -1, keepdims=True)
        args = (input, path_table, path_code, weight) + (
            (bias,) if bias is not None else ())
        return _apply(f, *args, op_name="hsigmoid_loss")

    depth = max(1, _math.ceil(_math.log2(max(nc, 2))))

    def f(x, lb, w, *rest):
        b = rest[0] if rest else None
        h = lb.astype(jnp.int32).reshape(-1) + (nc - 1)  # heap leaf
        total = jnp.zeros((x.shape[0], 1), x.dtype)
        for _ in range(depth + 1):
            valid = h > 0
            parent = jnp.maximum((h - 1) // 2, 0)
            is_right = (h % 2 == 0)
            logits = jnp.einsum("bf,bf->b", x, w[parent])
            if b is not None:
                logits = logits + b[parent]
            t = is_right.astype(x.dtype)
            ll = (jnp.maximum(logits, 0) - logits * t
                  + jnp.log1p(jnp.exp(-jnp.abs(logits))))
            total = total + jnp.where(valid, ll, 0.0)[:, None]
            h = parent
        return total

    args = (input, label, weight) + ((bias,) if bias is not None else ())
    return _apply(f, *args, op_name="hsigmoid_loss")
