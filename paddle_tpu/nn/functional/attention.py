"""Attention functionals.

The 2021-era reference has no fused attention op (only
operators/fused/multihead_matmul_op.* for inference); long-context
attention is greenfield here per SURVEY.md §5.7. The public entry is
``scaled_dot_product_attention``; on TPU it dispatches to a Pallas
flash-attention kernel when shapes allow (ops/flash_attention.py),
falling back to the XLA softmax composition.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.core import Tensor, _apply
from ...framework.random import split_key

__all__ = ["scaled_dot_product_attention"]


def _sdpa_ref(q, k, v, mask, dropout_p, causal, scale, dropout_key=None):
    # q,k,v: (B, S, H, D) paddle layout
    qh = jnp.swapaxes(q, 1, 2)  # B,H,S,D
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    s = scale if scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * s
    if causal:
        S_q, S_k = logits.shape[-2], logits.shape[-1]
        causal_mask = jnp.tril(jnp.ones((S_q, S_k), bool), S_k - S_q)
        logits = jnp.where(causal_mask, logits, jnp.asarray(-1e30, logits.dtype))
    if mask is not None:
        logits = logits + mask
    w = jax.nn.softmax(logits, axis=-1)
    if dropout_p > 0.0 and dropout_key is not None:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout_p, w.shape)
        w = jnp.where(keep, w / (1.0 - dropout_p), jnp.zeros((), w.dtype))
    out = jnp.einsum("bhqk,bhkd->bhqd", w, vh)
    return jnp.swapaxes(out, 1, 2)  # B,S,H,D


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, scale=None, name=None):
    """Inputs in paddle layout (batch, seq, heads, head_dim).

    On TPU, uses the Pallas flash-attention kernel (ops/flash_attention.py)
    for long sequences; XLA composition otherwise (XLA already fuses the
    softmax chain well for short seqs).
    """
    drop = dropout_p if training else 0.0
    use_flash = False
    try:
        from ...ops.flash_attention import flash_eligible
        qv = query._value
        if qv.ndim == 4:
            mv = attn_mask._value if attn_mask is not None else None
            use_flash = flash_eligible(
                qv.shape[1], qv.shape[3],
                has_mask=mv is not None, dropout=drop,
                mask_shape=None if mv is None else tuple(mv.shape),
                mask_dtype=None if mv is None else mv.dtype,
                kv_seq_len=key._value.shape[1])
    except Exception:
        use_flash = False

    if use_flash:
        from ...ops.flash_attention import flash_attention as _fa

        if attn_mask is None:
            if drop > 0.0:
                # flash_eligible only admits dropout>0 mask-free, where
                # the kernel applies it via the on-chip PRNG — seed
                # minted per call from the framework RNG chain so it
                # advances like the XLA path's key.  The seed rides as
                # an OPERAND (keyed by aval in the eager vjp cache, so
                # repeat steps stay cached) rather than a closure cell
                # (unhashable -> full Pallas re-trace every call).
                from ...ops.flash_attention import dropout_seed
                seed = dropout_seed(split_key())

                def f(q, k, v, s):
                    return _fa(q, k, v, causal=is_causal, scale=scale,
                               dropout_p=drop, seed=s)
                return _apply(f, query, key, value, seed,
                              op_name="flash_attention")

            def f(q, k, v):
                return _fa(q, k, v, causal=is_causal, scale=scale)
            return _apply(f, query, key, value,
                          op_name="flash_attention")

        def f(q, k, v, m):
            return _fa(q, k, v, bias=m.astype(q.dtype), causal=is_causal,
                       scale=scale)
        return _apply(f, query, key, value, attn_mask,
                      op_name="flash_attention")

    dk = split_key() if drop > 0.0 else None
    if attn_mask is not None:
        def f(q, k, v, m):
            return _sdpa_ref(q, k, v, m, drop, is_causal, scale, dk)
        return _apply(f, query, key, value, attn_mask, op_name="sdpa")

    def f(q, k, v):
        return _sdpa_ref(q, k, v, None, drop, is_causal, scale, dk)
    return _apply(f, query, key, value, op_name="sdpa")
