"""Weight initializers (parity: python/paddle/nn/initializer/ backed by the
reference's C++ fill ops operators/fill_constant_op.*, gaussian_random_op.*,
uniform_random_op.*, and python/paddle/fluid/initializer.py). Each is a
callable (shape, jnp_dtype) -> jax array drawing from the global RNG."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.random import split_key

__all__ = ["Initializer", "Constant", "Normal", "TruncatedNormal", "Uniform",
           "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
           "Assign", "Orthogonal", "Dirac", "calculate_gain", "Bilinear", "set_global_initializer"]


def calculate_gain(nonlinearity, param=None):
    gains = {
        "sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
        "conv3d": 1.0, "conv_transpose1d": 1.0, "conv_transpose2d": 1.0,
        "conv_transpose3d": 1.0, "tanh": 5.0 / 3.0,
        "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param if param is not None else 0.01) ** 2)),
        "selu": 3.0 / 4.0,
    }
    return gains[nonlinearity]


def _fans(shape):
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv weights (paddle: out, in, *k)
    rf = int(np.prod(shape[2:]))
    return shape[1] * rf, shape[0] * rf


class Initializer:
    def __call__(self, shape, dtype=jnp.float32):
        shape = tuple(int(d) for d in shape)
        from ...framework.core import is_abstract_init
        if is_abstract_init():
            # meta-device creation (framework.core.abstract_init): aval
            # only, no storage and no RNG draw — abstract models are for
            # AOT geometry work, never for training from this "init"
            return jax.ShapeDtypeStruct(shape, jnp.dtype(dtype))
        return self._generate(shape, dtype)

    def _generate(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def _generate(self, shape, dtype=jnp.float32):
        return jnp.full(shape, self.value, dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0, name=None):
        self.mean, self.std = mean, std

    def _generate(self, shape, dtype=jnp.float32):
        return (jax.random.normal(split_key(), shape, dtype) * self.std
                + self.mean)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, name=None):
        self.mean, self.std = mean, std

    def _generate(self, shape, dtype=jnp.float32):
        return (jax.random.truncated_normal(split_key(), -2.0, 2.0, shape,
                                            dtype) * self.std + self.mean)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0, name=None):
        self.low, self.high = low, high

    def _generate(self, shape, dtype=jnp.float32):
        return jax.random.uniform(split_key(), shape, dtype, self.low,
                                  self.high)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def _generate(self, shape, dtype=jnp.float32):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return jax.random.normal(split_key(), shape, dtype) * std


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def _generate(self, shape, dtype=jnp.float32):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(split_key(), shape, dtype, -limit, limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0,
                 nonlinearity="relu", name=None):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def _generate(self, shape, dtype=jnp.float32):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        return jax.random.normal(split_key(), shape, dtype) * std


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0,
                 nonlinearity="relu", name=None):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def _generate(self, shape, dtype=jnp.float32):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(split_key(), shape, dtype, -limit, limit)


class Assign(Initializer):
    def __init__(self, value, name=None):
        self.value = value

    def _generate(self, shape, dtype=jnp.float32):
        from ...framework.core import Tensor
        v = self.value
        if isinstance(v, Tensor):
            v = v._value
        arr = jnp.asarray(np.asarray(v), dtype)
        assert tuple(arr.shape) == tuple(shape), \
            f"Assign shape {arr.shape} != {shape}"
        return arr


class Orthogonal(Initializer):
    def __init__(self, gain=1.0, name=None):
        self.gain = gain

    def _generate(self, shape, dtype=jnp.float32):
        rows = shape[0]
        cols = int(np.prod(shape[1:]))
        flat = jax.random.normal(split_key(), (max(rows, cols),
                                               min(rows, cols)), dtype)
        q, r = jnp.linalg.qr(flat)
        q = q * jnp.sign(jnp.diagonal(r))
        if rows < cols:
            q = q.T
        return (self.gain * q[:rows, :cols]).reshape(shape)


class Dirac(Initializer):
    def __init__(self, groups=1, name=None):
        self.groups = groups

    def _generate(self, shape, dtype=jnp.float32):
        out = np.zeros(shape, np.float32)
        out_ch, in_ch = shape[0], shape[1]
        mins = min(out_ch // self.groups, in_ch)
        centers = [s // 2 for s in shape[2:]]
        for g in range(self.groups):
            for i in range(mins):
                idx = (g * (out_ch // self.groups) + i, i, *centers)
                out[idx] = 1.0
        return jnp.asarray(out, dtype)


class Bilinear(Initializer):
    """Bilinear-upsampling kernel init for transposed convs (parity:
    nn/initializer/Bilinear, fluid BilinearInitializer) — initializes a
    (C_out, C_in, k, k) weight so conv_transpose performs bilinear
    interpolation."""

    def _generate(self, shape, dtype=jnp.float32):
        if len(shape) != 4:
            raise ValueError(
                f"Bilinear init needs a 4-D conv weight, got {shape}")
        import numpy as np
        k = shape[-1]
        if shape[-2] != k:
            raise ValueError("Bilinear init needs square kernels")
        f = (k + 1) // 2
        center = f - 1 if k % 2 == 1 else f - 0.5
        og = np.ogrid[:k, :k]
        filt = ((1 - abs(og[0] - center) / f)
                * (1 - abs(og[1] - center) / f)).astype(np.float32)
        # reference BilinearInitializer writes the filter into EVERY
        # (out, in) pair — the canonical groups=C depthwise upsample
        # weight (C, 1, k, k) must get the filter in every channel
        w = np.broadcast_to(filt, shape).copy()
        return jnp.asarray(w, dtype)


_global_initializer = None


def set_global_initializer(weight_init, bias_init=None):
    """Parity: nn/initializer/set_global_initializer — the default init
    layers fall back to when no weight_attr is given. Pass None to
    reset."""
    global _global_initializer
    _global_initializer = (weight_init, bias_init)


def _global_default(is_bias):
    g = _global_initializer
    if g is None:
        return None
    return g[1] if is_bias else g[0]
