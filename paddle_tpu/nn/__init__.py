"""paddle_tpu.nn — layers + functional (parity surface: python/paddle/nn/)."""
from . import functional  # noqa: F401
from . import utils  # noqa: F401
from . import initializer  # noqa: F401
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: F401
from .layer.activation import *  # noqa: F401,F403
from .layer.common import *  # noqa: F401,F403
from .layer.container import *  # noqa: F401,F403
from .layer.conv import *  # noqa: F401,F403
from .layer.layers import Layer, Parameter, create_parameter  # noqa: F401
from .layer.loss import *  # noqa: F401,F403
from .layer.moe import *  # noqa: F401,F403
from .layer.norm import *  # noqa: F401,F403
from .layer.pooling import *  # noqa: F401,F403
from .layer.rnn import *  # noqa: F401,F403
from .layer.transformer import *  # noqa: F401,F403
from .decode import BeamSearchDecoder, Decoder, dynamic_decode  # noqa: F401

from .layer import (activation, common, container, conv, loss, norm,  # noqa: F401
                    pooling, rnn, transformer)
from .layer.layers import Layer as _Layer

# paddle.nn.functional style alias
from . import functional as F  # noqa: F401

from .layer.activation import __all__ as _a
from .layer.common import __all__ as _c
from .layer.container import __all__ as _ct
from .layer.conv import __all__ as _cv
from .layer.loss import __all__ as _l
from .layer.norm import __all__ as _n
from .layer.pooling import __all__ as _p
from .layer.rnn import __all__ as _r
from .layer.transformer import __all__ as _t

__all__ = (["Layer", "Parameter", "create_parameter",
            "BeamSearchDecoder", "Decoder", "dynamic_decode", "functional",
            "initializer", "ClipGradByGlobalNorm", "ClipGradByNorm",
            "ClipGradByValue"] + _a + _c + _ct + _cv + _l + _n + _p + _r + _t)

# compat: reference exposes nn.extension as a submodule
from .functional import extension  # noqa: F401,E402
