"""paddle_tpu.observability — unified tracing, metrics and timelines.

The ISSUE 5 subsystem, three pillars over one design rule (everything
off by default, opt-in by env, ~zero cost when off):

1. **Cross-process tracing** (:mod:`.trace`): ``Span`` trees with
   trace/span-id propagation stamped through the PS RPC frame header,
   per-process JSONL sinks, clock-offset samples from RPC round trips;
   ``tools/trace_merge.py`` fuses the sinks into one Chrome/Perfetto
   trace where a trainer's ``ps.client.push`` span contains the
   server's ``ps.server.push`` apply span.
2. **Metrics** (:mod:`.metrics` over the
   :mod:`~paddle_tpu.framework.monitor` StatRegistry): counters,
   gauges and fixed-bucket histograms from the hot seams (PS retries/
   failovers, serving queue/latency, DataLoader prefetch, TrainGuard
   verdicts), exported as a Prometheus ``/metrics`` endpoint and/or a
   periodic JSONL flusher.
3. **Step timeline** (:mod:`.timeline`): per-step phase attribution
   (data wait / h2d / dispatch / health fetch / host) with
   ``trace_every=N`` sampling.

ISSUE 12 grows the subsystem into a FLEET observatory:

4. **Per-request tracing + tenants** (:mod:`.request_trace`): one
   span lane per serving request (submit -> queue -> admit -> prefill
   -> sampled decode -> finish) and ``tenant=`` usage accounting via
   the registry's new labeled series.
5. **Fleet aggregator** (:mod:`.aggregator`): scrapes N
   ``/metrics.json`` endpoints or flusher JSONL files, merges
   counters/le-buckets EXACTLY, computes per-process rates, flags
   stragglers (k x MAD below fleet median) and stale scrapees;
   ``/fleet`` + ``tools/fleet_top.py``.
6. **SLO engine** (:mod:`.slo`): declarative objectives (latency
   percentile, error rate, gauge bound) with multi-window burn
   rates; a breach is a flight-recorder event + postmortem bundle.

Env quick reference::

    PADDLE_TRACE=1  PADDLE_TRACE_DIR=... PADDLE_TRACE_ROLE=...
    PADDLE_TRACE_EVERY=16
    PADDLE_METRICS=1  PADDLE_METRICS_PORT=9464  PADDLE_METRICS_FILE=...
    PADDLE_METRICS_HOST=127.0.0.1   (loopback default; opt into wider)

Importable without jax (PS server subprocesses stay lightweight).
"""
from __future__ import annotations

from ..framework.monitor import (  # noqa: F401
    Histogram, enable_metrics, gauge_add, gauge_get, gauge_set,
    get_histogram, hist_observe, metrics_enabled, metrics_reset,
    metrics_snapshot, stat_add, stat_get)
from . import (aggregator, flight_recorder, metrics,  # noqa: F401
               request_trace, slo, timeline, trace)
from .aggregator import FleetAggregator  # noqa: F401
from .flight_recorder import (  # noqa: F401
    FlightRecorder, Watchdog, compile_log, flight_dump, flight_enabled,
    flight_record)
from .metrics import (  # noqa: F401
    MetricsFlusher, MetricsServer, prometheus_text, start_metrics_server)
from .request_trace import RequestTrace  # noqa: F401
from .slo import SLO, SloEngine  # noqa: F401
from .timeline import StepTimeline  # noqa: F401
from .trace import (  # noqa: F401
    Span, disable as disable_tracing, enable as enable_tracing, enabled
    as tracing_enabled, propagation_ctx, record_clock, server_span, span)

__all__ = [
    "trace", "metrics", "timeline", "flight_recorder", "aggregator",
    "slo", "request_trace",
    "Span", "span", "server_span", "propagation_ctx", "record_clock",
    "enable_tracing", "disable_tracing", "tracing_enabled",
    "StepTimeline", "Histogram", "RequestTrace",
    "FleetAggregator", "SLO", "SloEngine",
    "FlightRecorder", "Watchdog", "flight_record", "flight_dump",
    "flight_enabled", "compile_log",
    "MetricsServer", "MetricsFlusher", "prometheus_text",
    "start_metrics_server",
    "enable_metrics", "metrics_enabled", "metrics_snapshot",
    "metrics_reset", "gauge_set", "gauge_add", "gauge_get",
    "hist_observe", "get_histogram", "stat_add", "stat_get",
]

# honour PADDLE_METRICS / PADDLE_METRICS_PORT / PADDLE_METRICS_FILE
metrics.enable_from_env()
# honour PADDLE_FLIGHT (full mode installs the dump triggers)
flight_recorder.enable_from_env()
