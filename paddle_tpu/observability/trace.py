"""Cross-process tracing: spans, trace-id propagation, JSONL sinks.

The reference's profiler (platform/profiler.h RecordEvent + the
DeviceTracer timeline) is single-process: it can say what THIS process
did, never why a training step stalled on a parameter-server three
sockets away.  This module is the distributed half of the ISSUE 5
observability subsystem:

- a :class:`Span` is a named `[start, end)` interval carrying a
  ``trace_id`` (one per causal chain, minted at the root span) and a
  ``span_id``/``parent_id`` pair; spans nest through a thread-local
  stack, so ``with span("a"): with span("b"): ...`` parents b under a
  with zero bookkeeping at the call site;
- :func:`propagation_ctx` / the ``ctx=`` argument let a context cross a
  process boundary: the PS client stamps ``(trace_id, span_id)`` into
  the RPC frame header and the server opens its handler span with that
  parent — the merged trace then shows the client's ``ps.client.push``
  span *containing* the server's ``ps.server.push`` apply span;
- every process appends records to its OWN JSONL sink file
  (``<dir>/trace-<role>-<pid>.jsonl``) — no cross-process locking, no
  collector daemon; ``tools/trace_merge.py`` fuses the sinks into one
  Chrome/Perfetto trace afterwards;
- clock correction: span timestamps are wall-clock microseconds
  (``time.time_ns``), and :func:`record_clock` persists peer clock
  offsets measured over RPC round trips (the PS register handshake) so
  the merger can shift every sink onto one timeline.

Everything is OFF by default.  ``PADDLE_TRACE=1`` (or :func:`enable`)
turns it on; when off, :func:`span` returns a shared no-op object and
the only cost at an instrumentation site is one attribute check.

Env knobs::

    PADDLE_TRACE=1            enable tracing
    PADDLE_TRACE_DIR=path     sink directory   (default ./paddle_trace)
    PADDLE_TRACE_ROLE=name    role tag in the sink file name + records
                              (default "proc"; e.g. trainer / ps / serve)
    PADDLE_TRACE_EVERY=N      step-timeline sampling period (timeline.py)

This module must stay importable without jax (the PS server
subprocesses are jax-free).
"""
from __future__ import annotations

import itertools
import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

__all__ = ["Span", "span", "server_span", "enable", "disable", "enabled",
           "current_ctx", "propagation_ctx", "record_clock", "sink_id",
           "sink_path", "trace_every", "flush", "new_id", "emit_span"]

_lock = threading.Lock()
_tls = threading.local()

_enabled = os.environ.get("PADDLE_TRACE", "0") == "1"
_dir = os.environ.get("PADDLE_TRACE_DIR", "paddle_trace")
_role = os.environ.get("PADDLE_TRACE_ROLE", "proc")
try:
    _every = max(1, int(os.environ.get("PADDLE_TRACE_EVERY", "1")))
except ValueError:
    _every = 1

_fh = None           # sink file handle
_fh_pid = None       # pid the handle was opened under (fork safety)

# span/trace id scheme: unique across processes without touching the
# global `random` stream (tracing must never perturb seeded training
# RNG) — pid + 4 urandom bytes prefix, per-process counter suffix
_id_prefix = f"{os.getpid():x}{int.from_bytes(os.urandom(4), 'big'):08x}"
_id_counter = itertools.count(1)


def _new_id() -> str:
    return f"{_id_prefix}-{next(_id_counter):x}"


def enabled() -> bool:
    return _enabled


def trace_every() -> int:
    return _every


def sink_id() -> str:
    return f"{_role}-{os.getpid()}"


def sink_path() -> str:
    return os.path.join(_dir, f"trace-{sink_id()}.jsonl")


def enable(dir: Optional[str] = None, role: Optional[str] = None,
           every: Optional[int] = None):
    """Turn tracing on (programmatic alternative to ``PADDLE_TRACE=1``).
    A changed dir/role closes the current sink; the next record opens
    the new one."""
    global _enabled, _dir, _role, _every
    with _lock:
        if dir is not None and dir != _dir:
            _close_locked()
            _dir = dir
        if role is not None and role != _role:
            _close_locked()
            _role = role
        if every is not None:
            _every = max(1, int(every))
        _enabled = True


def disable():
    """Turn tracing off and close the sink (tests must call this so one
    test's sink never leaks into the next)."""
    global _enabled
    with _lock:
        _enabled = False
        _close_locked()


def _close_locked():
    global _fh, _fh_pid
    if _fh is not None:
        try:
            _fh.close()
        except OSError:
            pass
        _fh = None
        _fh_pid = None


def flush():
    with _lock:
        if _fh is not None:
            _fh.flush()


def _write(rec: dict):
    """Append one record to this process's sink (opened lazily; reopened
    after fork — a forked DataLoader worker must not interleave writes
    into its parent's stream)."""
    global _fh, _fh_pid
    pid = os.getpid()
    line = json.dumps(rec, separators=(",", ":"))
    with _lock:
        if not _enabled:
            # a span finishing after disable() must not resurrect the
            # sink (tests would leak files into the next test's dir)
            return
        if _fh is None or _fh_pid != pid:
            if _fh is not None:     # inherited handle from a fork
                _fh = None
            os.makedirs(_dir, exist_ok=True)
            # line-buffered: a SIGKILLed process (chaos crash plans,
            # failover tests) keeps every completed span on disk
            _fh = open(os.path.join(
                _dir, f"trace-{_role}-{pid}.jsonl"), "a", buffering=1)
            _fh_pid = pid
            _fh.write(json.dumps(
                {"t": "meta", "sink": f"{_role}-{pid}", "role": _role,
                 "pid": pid, "start_us": time.time_ns() // 1000},
                separators=(",", ":")) + "\n")
        _fh.write(line + "\n")


def _stack() -> List[Tuple[str, str]]:
    s = getattr(_tls, "stack", None)
    if s is None:
        s = _tls.stack = []
    return s


def current_ctx() -> Optional[Tuple[str, str]]:
    """(trace_id, span_id) of the innermost live span on this thread."""
    s = getattr(_tls, "stack", None)
    return s[-1] if s else None


def propagation_ctx() -> Optional[List[str]]:
    """The context to stamp into an outgoing RPC frame header (a plain
    json/pickle-able 2-list), or None when there is nothing to
    propagate."""
    if not _enabled:
        return None
    ctx = current_ctx()
    return [ctx[0], ctx[1]] if ctx else None


class _NullSpan:
    """Shared no-op stand-in when tracing is off: the instrumentation
    site costs one call + one attribute check, no allocation."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **args):
        return self


_NULL = _NullSpan()


class Span:
    """One named interval in the trace.  Context manager::

        with span("ps.client.push", cat="rpc", shard=0):
            ...

    ``ctx=(trace_id, span_id)`` parents this span under a REMOTE span
    (server side of an RPC); otherwise the parent is the innermost live
    span on this thread, and a span with no parent mints a fresh
    trace_id (it is the root of a new causal chain).
    """

    __slots__ = ("name", "cat", "args", "trace", "span_id", "parent",
                 "_ts_us", "_t0")

    def __init__(self, name: str, cat: str = "host",
                 ctx: Optional[Tuple[str, str]] = None, **args):
        self.name = name
        self.cat = cat
        self.args = args or None
        if ctx is not None:
            self.trace, self.parent = str(ctx[0]), str(ctx[1])
        else:
            cur = current_ctx()
            if cur is not None:
                self.trace, self.parent = cur
            else:
                self.trace, self.parent = _new_id(), None
        self.span_id = _new_id()
        self._ts_us = 0
        self._t0 = 0

    def set(self, **args):
        if self.args is None:
            self.args = {}
        self.args.update(args)
        return self

    def __enter__(self):
        self._ts_us = time.time_ns() // 1000
        self._t0 = time.perf_counter_ns()
        _stack().append((self.trace, self.span_id))
        return self

    def __exit__(self, exc_type, exc, tb):
        dur_us = (time.perf_counter_ns() - self._t0) // 1000
        s = _stack()
        if s and s[-1] == (self.trace, self.span_id):
            s.pop()
        if exc_type is not None:
            self.set(error=exc_type.__name__)
        rec = {"t": "span", "name": self.name, "cat": self.cat,
               "ts_us": self._ts_us, "dur_us": dur_us,
               "pid": os.getpid(), "tid": threading.get_ident(),
               "trace": self.trace, "span": self.span_id}
        if self.parent is not None:
            rec["parent"] = self.parent
        if self.args:
            rec["args"] = self.args
        _write(rec)
        return False


def span(name: str, cat: str = "host", **args):
    """Factory used at every instrumentation site: a real :class:`Span`
    while tracing is on, the shared no-op otherwise."""
    if not _enabled:
        return _NULL
    return Span(name, cat=cat, **args)


def server_span(name: str, ctx, cat: str = "rpc", **args):
    """Server-side child span of a remote parent context (the 2-list a
    client stamped into the frame header; None opens a local root)."""
    if not _enabled:
        return _NULL
    if ctx is not None:
        return Span(name, cat=cat, ctx=(ctx[0], ctx[1]), **args)
    return Span(name, cat=cat, **args)


def new_id() -> str:
    """Mint a fresh trace/span id (public for :mod:`.request_trace`,
    which manages its own id chains instead of the thread-local
    stack)."""
    return _new_id()


def emit_span(name: str, ts_us: int, dur_us: int, trace_id: str,
              span_id: str, parent: Optional[str] = None,
              cat: str = "req", tid: Optional[int] = None,
              args: Optional[Dict] = None):
    """Write one span record with EXPLICIT ids, timestamps and lane.

    The :class:`Span` context manager parents through the thread-local
    stack — correct for code that nests on one thread, wrong for a
    scheduler thread interleaving many requests per iteration (ISSUE
    12): a request's queue phase opens on the submitting thread and
    closes on the scheduler thread, and two requests' phases overlap
    arbitrarily.  This function bypasses the stack entirely; the
    caller supplies the chain.  ``tid`` overrides the thread ident in
    the record — :mod:`.request_trace` assigns one virtual lane id per
    request so ``tools/trace_merge.py`` renders one lane per request.
    """
    if not _enabled:
        return
    rec = {"t": "span", "name": name, "cat": cat,
           "ts_us": int(ts_us), "dur_us": int(dur_us),
           "pid": os.getpid(),
           "tid": int(tid) if tid is not None
           else threading.get_ident(),
           "trace": trace_id, "span": span_id}
    if parent is not None:
        rec["parent"] = parent
    if args:
        rec["args"] = args
    _write(rec)


def record_clock(peer_sink: str, offset_us: float, rtt_us: float):
    """Persist one clock-offset sample: ``offset_us`` is (peer clock −
    this process's clock) estimated at the midpoint of a round trip of
    ``rtt_us``.  trace_merge uses these edges to shift every sink onto
    the root process's timeline."""
    if not _enabled:
        return
    _write({"t": "clock", "peer": str(peer_sink),
            "offset_us": float(offset_us), "rtt_us": float(rtt_us),
            "pid": os.getpid(), "ts_us": time.time_ns() // 1000})
