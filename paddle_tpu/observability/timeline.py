"""Step timeline: attribute each training step to phases.

"Why was step 4812 slow" decomposes into a handful of host-side phases
— waiting on the DataLoader, staging the batch to device, dispatching
the compiled step, fetching guard health, and the leftover host work
(param rebinds, callbacks).  :class:`StepTimeline` measures those
phases at the two loops that own them (``DistributedTrainStep.__call__``
and the hapi fit loop) and emits them two ways:

- **spans** (``step`` root + ``step.<phase>`` children) into the trace
  sink — but only on SAMPLED steps (``trace_every=N``, env
  ``PADDLE_TRACE_EVERY``): a clean-path step on the llama proxy is
  ~8 ms, so tracing every step would spend a measurable fraction of it
  serializing JSON; sampling 1/N keeps the overhead ≤1% while still
  catching every systematic stall;
- **histograms** (``step_<phase>_ms`` in the StatRegistry) on EVERY
  step while metrics are enabled — p50/p99 per phase without storing
  samples, the always-on production signal.

Both off -> a phase costs one attribute check and no clock read.
"""
from __future__ import annotations

from time import perf_counter_ns
from typing import Optional

from ..framework import monitor as _monitor
from . import trace as _trace

__all__ = ["StepTimeline"]


class _NullPhase:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullPhase()


class _Phase:
    __slots__ = ("_name", "_hist", "_span", "_t0")

    def __init__(self, name: str, hist: bool, span):
        self._name = name
        self._hist = hist
        self._span = span
        self._t0 = 0

    def __enter__(self):
        if self._span is not None:
            self._span.__enter__()
        self._t0 = perf_counter_ns()
        return self

    def __exit__(self, *exc):
        dur_ms = (perf_counter_ns() - self._t0) / 1e6
        if self._span is not None:
            self._span.__exit__(*exc)
        if self._hist:
            _monitor.hist_observe(f"step_{self._name}_ms", dur_ms)
        return False


class _StepScope:
    __slots__ = ("_span",)

    def __init__(self, span):
        self._span = span

    def __enter__(self):
        if self._span is not None:
            self._span.__enter__()
        return self

    def __exit__(self, *exc):
        if self._span is not None:
            self._span.__exit__(*exc)
        return False


class StepTimeline:
    """Per-loop phase attributor.

    ::

        tl = StepTimeline("train")
        with tl.step(i):
            with tl.phase("data_wait"): batch = next(it)
            with tl.phase("dispatch"):  loss = step(*batch)
    """

    def __init__(self, name: str = "step", every: Optional[int] = None):
        self.name = name
        self._every = every        # None -> follow PADDLE_TRACE_EVERY
        self._sampled = False      # current step emits spans?

    def _period(self) -> int:
        return self._every if self._every else _trace.trace_every()

    def step(self, step_i: int):
        """Scope for one whole step.  Decides the sampling verdict every
        phase of this step inherits."""
        self._sampled = (_trace.enabled()
                         and step_i % self._period() == 0)
        if not self._sampled:
            return _NULL
        return _StepScope(_trace.Span(self.name, cat="step",
                                      step=int(step_i)))

    def phase(self, name: str):
        """Scope for one phase of the current step."""
        hist = _monitor.metrics_enabled()
        if not (hist or self._sampled):
            return _NULL
        sp = (_trace.Span(f"{self.name}.{name}", cat="step")
              if self._sampled else None)
        return _Phase(name, hist, sp)
