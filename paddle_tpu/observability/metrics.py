"""Metrics export: Prometheus text exposition + periodic JSONL flusher.

The collection side lives in :mod:`paddle_tpu.framework.monitor` (the
StatRegistry singleton, extended with gauges and fixed-bucket
histograms); this module is the EXPORT side:

- :func:`prometheus_text` renders the registry in Prometheus text
  exposition format 0.0.4 (counters, gauges, and le-bucketed
  histograms with ``_sum``/``_count``), names sanitized and prefixed
  ``paddle_``;
- :class:`MetricsServer` serves it at ``GET /metrics`` from a
  background ``ThreadingHTTPServer`` — point a Prometheus scrape job at
  ``http://host:port/metrics``;
- :class:`MetricsFlusher` appends timestamped registry snapshots to a
  JSONL file on a fixed cadence — the zero-infrastructure alternative
  when no scraper exists (same spirit as the VisualDL callback).

Opt-in (everything off by default)::

    PADDLE_METRICS=1           enable high-frequency observation sites
    PADDLE_METRICS_PORT=9464   also serve /metrics on this port
    PADDLE_METRICS_HOST=addr   bind address (default 127.0.0.1 —
                               loopback; set 0.0.0.0 explicitly for a
                               real deployment scrape)
    PADDLE_METRICS_FILE=path   also flush snapshots to this JSONL file
    PADDLE_METRICS_FLUSH_S=10  flusher cadence (seconds)

ISSUE 12 growth: labeled series (the ``"labeled"`` snapshot key from
:mod:`~paddle_tpu.framework.monitor`) render inside their family —
``paddle_serve_tenant_tokens_out{tenant="a"} 5`` — while a label-free
snapshot's exposition stays byte-identical (golden contract).  A
``GET /metrics.json`` endpoint serves the RAW snapshot (+ role/pid/
ts_us) so :mod:`.aggregator` can merge counters and le-buckets
EXACTLY instead of re-parsing rendered text.

Must stay importable without jax (PS server subprocesses).
"""
from __future__ import annotations

import json
import os
import re
import threading
import time
from typing import Dict, Optional

from ..framework import monitor as _monitor

__all__ = ["prometheus_text", "snapshot_json", "build_info",
           "MetricsServer", "MetricsFlusher", "start_metrics_server",
           "enable_from_env", "default_host"]

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")

# process start, for /healthz uptime
_START_MONO = time.monotonic()

_build_info_cache: Optional[Dict[str, str]] = None


def build_info() -> Dict[str, str]:
    """Version identity for the ``paddle_build_info`` gauge: the
    paddle_tpu version plus the jax/jaxlib DIST versions — read from
    package metadata, never by importing jax (this module serves
    /metrics from jax-free PS subprocesses)."""
    global _build_info_cache
    if _build_info_cache is None:
        try:
            from .. import __version__ as ver
        except Exception:
            ver = "unknown"
        import importlib.metadata as _md
        info = {"version": str(ver)}
        for dist in ("jax", "jaxlib"):
            try:
                info[dist] = _md.version(dist)
            except Exception:
                info[dist] = "unavailable"
        _build_info_cache = info
    return dict(_build_info_cache)


def _prom_name(name: str) -> str:
    n = _NAME_RE.sub("_", str(name))
    if not n.startswith("paddle_"):
        n = "paddle_" + n
    if n[len("paddle_"):][:1].isdigit():
        n = "paddle_m" + n[len("paddle_"):]
    return n


def _fmt(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _hist_lines(lines, pn, h, lk: str = ""):
    """Append one histogram series' exposition lines; ``lk`` is the
    canonical inner label string ("" for the unlabeled series)."""
    pre = f"{lk}," if lk else ""
    cum = 0
    for le, cum in h["buckets"]:
        lines.append(f'{pn}_bucket{{{pre}le="{_fmt(le)}"}} {cum}')
    lines.append(f'{pn}_bucket{{{pre}le="+Inf"}} {h["count"]}')
    suffix = f"{{{lk}}}" if lk else ""
    lines.append(f"{pn}_sum{suffix} {repr(float(h['sum']))}")
    lines.append(f"{pn}_count{suffix} {h['count']}")


def prometheus_text(snapshot: Optional[Dict] = None) -> str:
    """Render a registry snapshot (default: the live registry) as
    Prometheus text exposition format.  A constant
    ``paddle_build_info`` gauge (version + jax/jaxlib dist versions as
    labels, value 1 — the standard ``*_build_info`` idiom) leads the
    exposition so every scrape identifies WHAT produced the numbers.
    Labeled series render under their family's one ``# TYPE`` line; a
    snapshot with no labeled series renders byte-identically to the
    pre-label format (the golden contract)."""
    snap = snapshot if snapshot is not None \
        else _monitor.metrics_snapshot()
    lab = snap.get("labeled", {})
    bi = build_info()
    lines = ["# TYPE paddle_build_info gauge",
             "paddle_build_info{"
             + ",".join(f'{k}="{bi[k]}"' for k in sorted(bi)) + "} 1"]
    plain_c = snap.get("counters", {})
    lab_c = lab.get("counters", {})
    for name in sorted(set(plain_c) | set(lab_c)):
        pn = _prom_name(name)
        lines.append(f"# TYPE {pn} counter")
        if name in plain_c:
            lines.append(f"{pn} {_fmt(plain_c[name])}")
        for lk in sorted(lab_c.get(name, {})):
            lines.append(f"{pn}{{{lk}}} {_fmt(lab_c[name][lk])}")
    plain_g = snap.get("gauges", {})
    lab_g = lab.get("gauges", {})
    for name in sorted(set(plain_g) | set(lab_g)):
        pn = _prom_name(name)
        lines.append(f"# TYPE {pn} gauge")
        if name in plain_g:
            lines.append(f"{pn} {_fmt(plain_g[name])}")
        for lk in sorted(lab_g.get(name, {})):
            lines.append(f"{pn}{{{lk}}} {_fmt(lab_g[name][lk])}")
    plain_h = snap.get("histograms", {})
    lab_h = lab.get("histograms", {})
    for name in sorted(set(plain_h) | set(lab_h)):
        pn = _prom_name(name)
        lines.append(f"# TYPE {pn} histogram")
        if name in plain_h:
            _hist_lines(lines, pn, plain_h[name])
        for lk in sorted(lab_h.get(name, {})):
            _hist_lines(lines, pn, lab_h[name][lk], lk)
    return "\n".join(lines) + "\n"


def snapshot_json(snapshot: Optional[Dict] = None) -> Dict:
    """The ``/metrics.json`` payload: the raw snapshot plus scrape
    identity — what the fleet aggregator consumes (exact merge needs
    the numbers, not the rendered text)."""
    snap = snapshot if snapshot is not None \
        else _monitor.metrics_snapshot()
    return {"ts_us": time.time_ns() // 1000,
            "role": os.environ.get("PADDLE_TRACE_ROLE", "proc"),
            "pid": os.getpid(), **snap}


def default_host() -> str:
    """Metrics bind address: loopback unless ``PADDLE_METRICS_HOST``
    says otherwise.  (ISSUE 12 satellite: the previous ``0.0.0.0``
    default exposed every process's registry to the whole network the
    moment a port was set — real deployments opt in explicitly.)"""
    return os.environ.get("PADDLE_METRICS_HOST", "127.0.0.1")


class MetricsServer:
    """``GET /metrics`` (+ ``/metrics.json`` + ``/healthz``) endpoint
    over the live registry.

    ::

        srv = MetricsServer(port=0).start()   # 0 = ephemeral
        requests.get(f"http://127.0.0.1:{srv.port}/metrics")
        srv.stop()

    ``host`` defaults to loopback (``PADDLE_METRICS_HOST`` or an
    explicit ctor value overrides — pass ``"0.0.0.0"`` to expose a
    real deployment to its scraper).  ``snapshot_fn`` substitutes the
    snapshot both text and JSON endpoints render (the fleet aggregator
    serves its MERGED rollup this way); ``routes`` maps extra paths to
    ``() -> (body_bytes, content_type)`` callables (the aggregator's
    ``/fleet``)."""

    def __init__(self, port: int = 0, host: Optional[str] = None,
                 snapshot_fn=None, routes: Optional[Dict] = None):
        self._want_port = int(port)
        self._host = host if host is not None else default_host()
        self._snapshot_fn = snapshot_fn
        self._routes = dict(routes or {})
        self._httpd = None
        self._thread: Optional[threading.Thread] = None
        self.port: Optional[int] = None

    def start(self) -> "MetricsServer":
        if self._httpd is not None:
            return self
        from http.server import (BaseHTTPRequestHandler,
                                 ThreadingHTTPServer)
        snapshot_fn = self._snapshot_fn
        routes = self._routes

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):            # noqa: N802 (stdlib API name)
                path = self.path.split("?")[0]
                if path == "/healthz":
                    # liveness probe: 200 + identity (the thing a k8s
                    # readiness check or a human's curl asks first)
                    body = json.dumps({
                        "status": "ok",
                        "uptime_s": round(
                            time.monotonic() - _START_MONO, 3),
                        "role": os.environ.get("PADDLE_TRACE_ROLE",
                                               "proc"),
                        "pid": os.getpid(),
                        **build_info(),
                    }).encode()
                    ctype = "application/json"
                elif path == "/metrics.json":
                    snap = snapshot_fn() if snapshot_fn else None
                    body = json.dumps(
                        snapshot_json(snap),
                        separators=(",", ":")).encode()
                    ctype = "application/json"
                elif path in ("/metrics", "/"):
                    snap = snapshot_fn() if snapshot_fn else None
                    body = prometheus_text(snap).encode()
                    ctype = ("text/plain; version=0.0.4; "
                             "charset=utf-8")
                elif path in routes:
                    body, ctype = routes[path]()
                    if isinstance(body, str):
                        body = body.encode()
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):   # scrapes must not spam stdout
                pass

        self._httpd = ThreadingHTTPServer((self._host, self._want_port),
                                          _Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="paddle-metrics",
            daemon=True)
        self._thread.start()
        return self

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


class MetricsFlusher:
    """Append a timestamped registry snapshot to ``path`` every
    ``interval_s`` seconds (and once at :meth:`stop`)."""

    def __init__(self, path: str, interval_s: float = 10.0):
        self.path = path
        self.interval_s = float(interval_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def flush_once(self):
        rec = {"ts_us": time.time_ns() // 1000, "pid": os.getpid()}
        rec.update(_monitor.metrics_snapshot())
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(self.path, "a") as f:
            f.write(json.dumps(rec, separators=(",", ":")) + "\n")

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            self.flush_once()

    def start(self) -> "MetricsFlusher":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="paddle-metrics-flush",
                daemon=True)
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self.flush_once()


_env_server: Optional[MetricsServer] = None
_env_flusher: Optional[MetricsFlusher] = None


def start_metrics_server(port: int = 0,
                         host: Optional[str] = None) -> MetricsServer:
    return MetricsServer(port=port, host=host).start()


def enable_from_env():
    """Honour the PADDLE_METRICS* env knobs (called at package import;
    idempotent).  PADDLE_METRICS=1 alone only flips the collection
    switch — the exporters need an explicit port/file."""
    global _env_server, _env_flusher
    if os.environ.get("PADDLE_METRICS", "0") == "1":
        _monitor.enable_metrics(True)
    port = os.environ.get("PADDLE_METRICS_PORT")
    if port and _env_server is None:
        try:
            _env_server = start_metrics_server(int(port))
        except OSError:          # port taken: metrics must never kill
            _env_server = None   # the training job
    path = os.environ.get("PADDLE_METRICS_FILE")
    if path and _env_flusher is None:
        _env_flusher = MetricsFlusher(
            path, float(os.environ.get("PADDLE_METRICS_FLUSH_S",
                                       "10") or 10)).start()
