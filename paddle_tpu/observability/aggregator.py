"""Fleet metrics aggregator — the cross-process half of /metrics
(ISSUE 12 tentpole).

PRs 5/7 gave every process its own registry, ``/metrics`` endpoint and
flight recorder; the system now runs as a FLEET (trainer, PS primary,
read replicas, geo follower, predictor + generation servers) and
nothing sees it whole.  :class:`FleetAggregator` is that view:

- **Scrape.**  Each target is either an HTTP endpoint (``host:port``
  or a full URL — the new ``GET /metrics.json`` returns the RAW
  registry snapshot, so no text re-parsing) or a
  :class:`~paddle_tpu.observability.metrics.MetricsFlusher` JSONL file
  (the last complete record is the sample — the zero-infrastructure
  path).  Scrapes run on an ``interval_s`` cadence
  (:meth:`start`/:meth:`stop`) or on demand (:meth:`scrape_once` —
  what the deterministic tests drive).

- **Exact merge.**  Fleet rollup = counters summed exactly (ints),
  labeled series summed per (family, label set), le-bucket histograms
  merged bucket-by-bucket when bounds agree (cumulative counts, sum
  and count all add — the merged percentile is the percentile of the
  POOLED samples to within bucket resolution; mismatched bounds are
  left un-merged and listed in ``unmerged_histograms``), gauges
  reduced by MAX (a lag/queue-depth fleet rollup asks "how bad is the
  worst process").  The rollup is itself snapshot-shaped: it renders
  through :func:`~paddle_tpu.observability.metrics.prometheus_text`
  and feeds :class:`~paddle_tpu.observability.slo.SloEngine`
  unchanged.

- **Rates + stragglers.**  Per process, every counter's delta/dt
  between its last two samples (file targets use the records' own
  ``ts_us``; endpoints use the scrape's).  For ``straggler_key`` (a
  counter name), a process whose rate sits below the fleet median by
  more than ``straggler_k`` x MAD is flagged — the robust-statistics
  version of "one replica is mysteriously slow" (SURVEY §2.6's fleet
  monitoring).  With fewer than 3 rate-bearing processes MAD is
  degenerate and nothing is flagged (two processes cannot outvote
  each other).

- **Staleness.**  A target whose scrape fails — or whose newest
  sample is older than ``stale_after_s`` — is flagged stale and
  EXCLUDED from the rollup (a dead process's last counters must not
  freeze into the fleet sums forever); its identity stays listed so
  the dashboard shows the hole.

- **Expose.**  :meth:`serve` publishes the aggregator's own
  ``/metrics`` (+ ``/metrics.json``) rendering the MERGED rollup, and
  ``/fleet`` with the full JSON fleet view (per-process rates,
  stragglers, staleness) — ``tools/fleet_top.py`` renders it as a
  live table.  Straggler/stale transitions are flight-recorder events
  so a postmortem shows when the fleet view first degraded, and
  ``state_file=`` appends each fleet snapshot as JSONL
  (``fleet-*.jsonl``, covered by the tier-1 leak check).

Must stay importable without jax (the aggregator is a sidecar
process in real deployments).
"""
from __future__ import annotations

import json
import os
import threading
import time
import urllib.request
from typing import Dict, List, Optional

from ..framework import monitor as _monitor
from . import flight_recorder as _flight
from . import metrics as _metrics

__all__ = ["FleetAggregator", "merge_snapshots", "merge_histograms"]


def merge_histograms(a: Dict, b: Dict) -> Optional[Dict]:
    """Exact merge of two histogram snapshots sharing bucket bounds:
    cumulative counts, sum and count all add.  Returns None when the
    bounds differ (caller records the family as un-merged)."""
    ab = [le for le, _ in a["buckets"]]
    bb = [le for le, _ in b["buckets"]]
    if ab != bb:
        return None
    return {"buckets": [[le, ca + cb] for (le, ca), (_, cb)
                        in zip(a["buckets"], b["buckets"])],
            "sum": a["sum"] + b["sum"],
            "count": a["count"] + b["count"]}


def merge_snapshots(snaps: List[Dict]) -> Dict:
    """Fleet rollup over metrics snapshots: counters sum exactly,
    gauges take the fleet MAX, histograms merge exactly per
    :func:`merge_histograms`; labeled families merge per label set the
    same way.  Returns a snapshot-shaped dict plus
    ``unmerged_histograms`` (families whose bounds disagreed — first
    seen wins, the rest dropped from the rollup)."""
    counters: Dict[str, int] = {}
    gauges: Dict[str, float] = {}
    hists: Dict[str, Dict] = {}
    lab = {"counters": {}, "gauges": {}, "histograms": {}}
    unmerged: List[str] = []
    for snap in snaps:
        for k, v in snap.get("counters", {}).items():
            counters[k] = counters.get(k, 0) + int(v)
        for k, v in snap.get("gauges", {}).items():
            gauges[k] = max(gauges.get(k, float("-inf")), float(v))
        for k, h in snap.get("histograms", {}).items():
            if k in unmerged:
                continue
            if k not in hists:
                hists[k] = {"buckets": [list(b) for b in h["buckets"]],
                            "sum": h["sum"], "count": h["count"]}
            else:
                m = merge_histograms(hists[k], h)
                if m is None:
                    unmerged.append(k)
                else:
                    hists[k] = m
        sl = snap.get("labeled", {})
        for k, fam in sl.get("counters", {}).items():
            out = lab["counters"].setdefault(k, {})
            for lk, v in fam.items():
                out[lk] = out.get(lk, 0) + int(v)
        for k, fam in sl.get("gauges", {}).items():
            out = lab["gauges"].setdefault(k, {})
            for lk, v in fam.items():
                out[lk] = max(out.get(lk, float("-inf")), float(v))
        for k, fam in sl.get("histograms", {}).items():
            out = lab["histograms"].setdefault(k, {})
            for lk, h in fam.items():
                key = f"{k}{{{lk}}}"
                if key in unmerged:
                    continue
                if lk not in out:
                    out[lk] = {"buckets": [list(b)
                                           for b in h["buckets"]],
                               "sum": h["sum"], "count": h["count"]}
                else:
                    m = merge_histograms(out[lk], h)
                    if m is None:
                        unmerged.append(key)
                    else:
                        out[lk] = m
    rollup = {"counters": counters, "gauges": gauges,
              "histograms": hists}
    if any(lab.values()):
        rollup["labeled"] = lab
    rollup["unmerged_histograms"] = sorted(unmerged)
    return rollup


def _median(xs: List[float]) -> float:
    s = sorted(xs)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


class _Target:
    """One scrapee: endpoint URL or flusher JSONL path + its sample
    history (the last two samples give the rate window)."""

    __slots__ = ("spec", "url", "path", "tid", "role", "pid",
                 "last_snap", "last_ts_s", "prev_counters",
                 "prev_ts_s", "last_ok_mono", "errors", "ok")

    def __init__(self, spec: str):
        self.spec = str(spec)
        if "://" in self.spec or (":" in self.spec
                                  and os.path.sep not in self.spec
                                  and not self.spec.endswith(".jsonl")):
            base = (self.spec if "://" in self.spec
                    else f"http://{self.spec}")
            self.url = base.rstrip("/") + "/metrics.json"
            self.path = None
        else:
            self.url = None
            self.path = self.spec
        self.tid = self.spec       # refined to role-pid on first scrape
        self.role = "proc"
        self.pid = 0
        self.last_snap: Optional[Dict] = None
        self.last_ts_s: Optional[float] = None
        self.prev_counters: Optional[Dict[str, int]] = None
        self.prev_ts_s: Optional[float] = None
        self.last_ok_mono: Optional[float] = None
        self.errors = 0
        self.ok = False

    def fetch(self, timeout_s: float):
        """-> (sample, previous_sample_or_None).  Endpoints have no
        baked-in history; flusher files carry their own — the last TWO
        complete records prime the rate window even from a static file
        (``fleet_top --once`` over a finished run still shows rates).
        A torn tail line (process died mid-write) falls back one
        line."""
        if self.url is not None:
            with urllib.request.urlopen(self.url,
                                        timeout=timeout_s) as r:
                return json.loads(r.read().decode()), None
        last = prev = None
        with open(self.path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                last, prev = rec, last
        if last is None:
            raise ValueError(f"no complete record in {self.path}")
        return last, prev


class FleetAggregator:
    """Scrape-merge-flag loop over N processes (module docstring)."""

    def __init__(self, targets: List[str], interval_s: float = 5.0,
                 stale_after_s: Optional[float] = None,
                 straggler_key: Optional[str] = None,
                 straggler_k: float = 3.0,
                 scrape_timeout_s: float = 5.0,
                 state_file: Optional[str] = None):
        self._targets = [_Target(t) for t in targets]
        self.interval_s = float(interval_s)
        self.stale_after_s = (float(stale_after_s)
                              if stale_after_s is not None
                              else 3.0 * self.interval_s)
        self.straggler_key = straggler_key
        self.straggler_k = float(straggler_k)
        self._scrape_timeout = float(scrape_timeout_s)
        self._state_file = state_file
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._srv: Optional[_metrics.MetricsServer] = None
        self._fleet: Dict = {"targets": {}, "rollup": {},
                             "stragglers": [], "stale": [],
                             "n_scrapes": 0}
        self._was_straggler: set = set()
        self._was_stale: set = set()
        self.n_scrapes = 0

    # -- scraping -----------------------------------------------------
    def scrape_once(self) -> Dict:
        """One synchronous scrape round over every target; recomputes
        the fleet view and returns it (also kept for :meth:`fleet`)."""
        now_mono = time.monotonic()
        for t in self._targets:
            try:
                rec, filed_prev = t.fetch(self._scrape_timeout)
            except Exception:
                t.ok = False
                t.errors += 1
                continue
            ts_s = float(rec.get("ts_us", time.time_ns() // 1000)) / 1e6
            if t.last_ts_s is not None and ts_s > t.last_ts_s:
                # a NEW sample advances the rate window; a re-read of
                # the same flusher record must not zero the rates
                t.prev_counters = dict(
                    t.last_snap.get("counters", {}))
                t.prev_ts_s = t.last_ts_s
            elif t.prev_counters is None and filed_prev is not None:
                # static file: its own second-to-last record opens the
                # rate window
                pts = filed_prev.get("ts_us")
                if pts is not None and float(pts) / 1e6 < ts_s:
                    t.prev_counters = dict(
                        filed_prev.get("counters", {}))
                    t.prev_ts_s = float(pts) / 1e6
            t.last_snap = {k: rec.get(k, {}) for k in
                           ("counters", "gauges", "histograms")}
            if "labeled" in rec:
                t.last_snap["labeled"] = rec["labeled"]
            t.last_ts_s = ts_s
            t.role = rec.get("role", t.role)
            t.pid = int(rec.get("pid", t.pid) or 0)
            t.tid = (f"{t.role}-{t.pid}" if t.pid else t.spec)
            t.last_ok_mono = now_mono
            t.ok = True
        self.n_scrapes += 1
        fleet = self._recompute(now_mono)
        if self._state_file:
            try:
                d = os.path.dirname(self._state_file)
                if d:
                    os.makedirs(d, exist_ok=True)
                with open(self._state_file, "a") as f:
                    f.write(json.dumps(fleet, separators=(",", ":"),
                                       default=str) + "\n")
            except OSError:
                pass          # state persistence must never kill scrapes
        return fleet

    def _recompute(self, now_mono: float) -> Dict:
        per: Dict[str, Dict] = {}
        fresh_snaps: List[Dict] = []
        rates_for_key: Dict[str, float] = {}
        stale: List[str] = []
        for t in self._targets:
            age = (None if t.last_ok_mono is None
                   else now_mono - t.last_ok_mono)
            is_stale = (t.last_snap is None
                        or (not t.ok and (age is None
                                          or age > self.stale_after_s))
                        or (t.last_ts_s is not None
                            and time.time() - t.last_ts_s
                            > self.stale_after_s))
            rates: Dict[str, float] = {}
            if (t.last_snap is not None and t.prev_counters is not None
                    and t.last_ts_s is not None
                    and t.prev_ts_s is not None
                    and t.last_ts_s > t.prev_ts_s):
                dt = t.last_ts_s - t.prev_ts_s
                cur = t.last_snap.get("counters", {})
                for k in set(cur) | set(t.prev_counters):
                    rates[k] = (int(cur.get(k, 0))
                                - int(t.prev_counters.get(k, 0))) / dt
            per[t.tid] = {"target": t.spec, "role": t.role,
                          "pid": t.pid, "ok": t.ok,
                          "stale": bool(is_stale), "errors": t.errors,
                          "age_s": (round(age, 3)
                                    if age is not None else None),
                          "rates": {k: round(v, 3)
                                    for k, v in rates.items()}}
            if is_stale:
                stale.append(t.tid)
            else:
                fresh_snaps.append(t.last_snap)
                if self.straggler_key is not None \
                        and self.straggler_key in rates:
                    rates_for_key[t.tid] = rates[self.straggler_key]
        stragglers = self._find_stragglers(rates_for_key)
        rollup = merge_snapshots(fresh_snaps)
        fleet = {"ts_us": time.time_ns() // 1000,
                 "n_scrapes": self.n_scrapes,
                 "straggler_key": self.straggler_key,
                 "targets": per, "rollup": rollup,
                 "stragglers": stragglers, "stale": stale}
        # transition events: the postmortem wants WHEN the fleet view
        # first degraded, not a heartbeat spam
        for tid in stragglers:
            if tid not in self._was_straggler:
                _flight.record("fleet.straggler", proc=tid,
                               key=self.straggler_key,
                               rate=rates_for_key.get(tid))
        for tid in stale:
            if tid not in self._was_stale:
                _flight.record("fleet.stale", proc=tid)
        self._was_straggler = set(stragglers)
        self._was_stale = set(stale)
        _monitor.gauge_set("fleet_targets", len(self._targets))
        _monitor.gauge_set("fleet_stale", len(stale))
        _monitor.gauge_set("fleet_stragglers", len(stragglers))
        with self._lock:
            self._fleet = fleet
        return fleet

    def _find_stragglers(self, rates: Dict[str, float]) -> List[str]:
        """Robust low-rate outliers: rate below the fleet median by
        more than k x MAD (median absolute deviation).  Needs >= 3
        rate-bearing processes — with 2 the MAD equals every
        deviation, so nothing can sit k>1 MADs out."""
        if len(rates) < 3:
            return []
        vals = list(rates.values())
        med = _median(vals)
        mad = _median([abs(v - med) for v in vals])
        if mad <= 0.0:
            return []
        return sorted(t for t, v in rates.items()
                      if med - v > self.straggler_k * mad)

    # -- views --------------------------------------------------------
    def fleet(self) -> Dict:
        """The last computed fleet view (``/fleet`` payload)."""
        with self._lock:
            return dict(self._fleet)

    def rollup(self) -> Dict:
        """The last merged snapshot — feed it to
        :func:`~paddle_tpu.observability.metrics.prometheus_text` or
        an :class:`~paddle_tpu.observability.slo.SloEngine`."""
        with self._lock:
            return dict(self._fleet.get("rollup", {}))

    # -- lifecycle ----------------------------------------------------
    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.scrape_once()
            except Exception:
                # an aggregator crash must never take the fleet's
                # dashboard down with it
                _monitor.stat_add("fleet_scrape_errors")

    def start(self) -> "FleetAggregator":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="paddle-fleet-aggregator",
                daemon=True)
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        if self._srv is not None:
            self._srv.stop()
            self._srv = None

    def serve(self, port: int = 0,
              host: Optional[str] = None) -> _metrics.MetricsServer:
        """Publish the fleet view: ``/metrics``(+``.json``) render the
        MERGED rollup, ``/fleet`` the full JSON fleet state."""
        if self._srv is None:
            def _fleet_route():
                return (json.dumps(self.fleet(), default=str),
                        "application/json")
            self._srv = _metrics.MetricsServer(
                port=port, host=host,
                snapshot_fn=self.rollup,
                routes={"/fleet": _fleet_route}).start()
        return self._srv
