"""Flight recorder — always-on postmortem telemetry (ISSUE 7).

PR 5's tracing/metrics stack is opt-in and forward-looking: you must
enable it BEFORE the interesting step happens.  The reference ships a
dedicated profiler layer (platform/profiler.h, SURVEY L0) because the
question that actually pages people — "why did the run die/stall/slow
down at step N" — must be answerable *after the fact*.  This module is
that layer:

1. **Ring buffer** (:class:`FlightRecorder`): a bounded-memory,
   no-I/O-on-the-hot-path event ring (~O(1k) events, fixed byte
   budget, oldest-first eviction).  Recording sites all over the repo
   (train step, TrainGuard health verdicts, PS RPC begin/end, serving
   queue events, chaos fault firings, compile events) append ~one
   small dict each; with the recorder on this costs one json encode +
   deque append (~6 us measured, PERF.md round 11), with
   ``PADDLE_FLIGHT=0`` it is one attribute check (~0.2 us).  Nothing
   ever touches disk until a dump is triggered.

2. **Compile observatory** (:func:`note_compile` / :func:`compile_log`):
   every lowering/compile in ``DistributedTrainStep`` and the AOT
   ``Predictor`` logs its retrace cause (first build vs. a new shape
   bucket vs. an AVOIDABLE retrace — same shapes, different dtypes),
   compile wall time, and the XLA memory-analysis observables
   (argument/output/temp/peak bytes — the same numbers ``audit()`` /
   ``compile_abstract`` expose, now logged per run so the
   auto-sharding planner of ROADMAP item 4 has real trajectories).
   Memory analysis needs an extra AOT compile on the training-step
   call path, so it resolves only in full mode (dumps enabled) or on
   demand via ``compile_log(resolve=True)``; the serving path holds
   its executables and logs it for free.

3. **Dump triggers**: a postmortem bundle is written on
   - typed failures — ``NumericalDivergence``, ``PSUnavailable``,
     ``ServerOverloaded`` call :func:`maybe_dump` at their raise sites;
   - unhandled exceptions — ``sys.excepthook`` +
     ``threading.excepthook`` chains (the previous hook still runs);
   - ``SIGUSR2`` — dump on demand, process continues;
   - fatal-but-dumpable signals — SIGTERM/SIGABRT write the bundle,
     then restore the default handler and re-raise; ``faulthandler``
     covers SIGSEGV-grade deaths with raw stacks in a sidecar file;
   - the **stall watchdog** — a daemon thread that fires when no
     step/RPC progress has been observed for ``PADDLE_FLIGHT_STALL_S``
     seconds (a SIGKILLed peer wedging this process in a recv is the
     canonical trigger; the bundle's in-flight op table names the
     stalled RPC).

4. **Bundle** (``$PADDLE_TRACE_DIR/flight-<role>-<pid>-<n>.jsonl``):
   meta + reason, the ring (JSONL), in-flight ops, all-thread stacks,
   the last metrics snapshot, the compile log, and the exception (when
   one triggered).  ``tools/postmortem.py`` merges bundles from
   trainer + PS primary + replica onto one clock-corrected Perfetto
   timeline (clock edges ride the ring — the PS register reply carries
   the server clock whether or not tracing is on) and renders the
   "last 50 events per process, first divergence first" report.

Enablement::

    PADDLE_FLIGHT unset   ring records in memory; dumps/handlers OFF
    PADDLE_FLIGHT=1       full mode: + dump triggers, signal handlers,
                          faulthandler, excepthooks, watchdog (when
                          PADDLE_FLIGHT_STALL_S > 0)
    PADDLE_FLIGHT=0       everything off (kill switch)
    PADDLE_TRACE_DIR      bundle directory (default ./paddle_trace)
    PADDLE_TRACE_ROLE     role tag in bundle names (shared with trace)
    PADDLE_FLIGHT_STALL_S stall watchdog deadline, seconds (0 = off)

Must stay importable without jax (PS server subprocesses are jax-free
at the module level).
"""
from __future__ import annotations

import collections
import itertools
import json
import os
import sys
import threading
import time
import traceback
from typing import Callable, Dict, List, Optional

__all__ = ["FlightRecorder", "Watchdog", "enabled", "dumps_enabled",
           "enable", "disable", "record", "begin", "end", "progress",
           "progress_age", "note_compile", "compile_log", "dump",
           "maybe_dump", "events", "in_flight", "clear",
           "install_handlers", "bundle_paths", "enable_from_env",
           "recorder", "MEM_SCHEMA_VERSION", "MEM_SCHEMA_KEYS"]

_lock = threading.Lock()

# ring on unless explicitly killed; dumps only in full mode
_env = os.environ.get("PADDLE_FLIGHT", "")
_ring_on = _env != "0"
_dumps_on = _env == "1"

_DEFAULT_CAPACITY = 1024
_DEFAULT_MAX_BYTES = 256 * 1024

# progress kinds: recording one of these proves the process is alive
# (the stall watchdog measures the age of the newest one).
# serve.decode/serve.admit: the generation scheduler's per-step and
# per-admission heartbeats (ISSUE 8) — decode mostly ticks via
# progress(), but its sampled ring events count too
# elastic.join/reshard/resume (ISSUE 9): a membership transition can
# legitimately stall the step stream for seconds (restore + reshard
# from the pinned checkpoint) — these events tell the watchdog the
# transition itself is making progress.  elastic.leave is deliberately
# NOT progress: a worker loss with no reshard following it is exactly
# the stall worth dumping.
# ps.replica.attach / ps.promote / ps.geo.push / elastic.promote
# (ISSUE 10): a failover or a geo catch-up legitimately pauses the
# data stream while the serving tier reorganises — these events ARE
# the recovery making progress; ps.replica_error and the client's
# read_stale_exhausted stay bad kinds (tools/postmortem.py).
# serve.spec_verify (ISSUE 11): a speculative-decode verify step IS
# decode progress — the gateway ticks serve.decode every iteration and
# additionally samples verify events into the ring
# online.ingest / ps.ttl_sweep (ISSUE 14): the streaming trainer's
# event loop and the TTL sweeper ARE the online loop making progress —
# either going silent is exactly the stall a bundle should autopsy
# (online.freshness_breach stays a bad kind in tools/postmortem.py).
# elastic.reshard.exchange/load/compile (ISSUE 17): the reshard
# decomposition — range-wise slot exchange, ranged checkpoint reads,
# per-mesh recompile.  Each sub-phase can individually dominate a
# transition (a big model's compile, a cold disk's load), so each is
# its own heartbeat with byte counts for the postmortem to apportion.
# gw.route (ISSUE 18): every gateway placement — initial routes,
# failover re-routes and drain re-homes all pass through it, so a
# router that stops placing IS the stall a bundle should autopsy
# (gw.failover / gw.drain stay bad kinds in tools/postmortem.py).
_PROGRESS_KINDS = frozenset({"step", "rpc", "serve.batch", "ps.apply",
                             "gw.route",
                             "serve.decode", "serve.admit",
                             "serve.spec_verify",
                             "elastic.join", "elastic.reshard",
                             "elastic.reshard.exchange",
                             "elastic.reshard.load",
                             "elastic.reshard.compile",
                             "elastic.resume", "elastic.promote",
                             "ps.replica.attach", "ps.promote",
                             "ps.geo.push", "online.ingest",
                             "ps.ttl_sweep"})

# typed-failure dumps are rate limited per reason (a retry storm must
# not turn every PSUnavailable into a bundle) and capped per process
_DUMP_MIN_INTERVAL_S = 5.0
_DUMP_MAX_BUNDLES = 32

_COMPILE_LOG_CAP = 256

# XLA CompiledMemoryStats attributes worth logging (bytes)
_MEM_ATTRS = ("argument_size_in_bytes", "output_size_in_bytes",
              "temp_size_in_bytes", "alias_size_in_bytes",
              "generated_code_size_in_bytes")

# ---- compile-log memory schema (ISSUE 15 satellite) ------------------
# Machine-readable contract for the byte counts a compile-log record
# carries.  Consumers (the planner's calibration hook,
# distributed/planner/calibrate.py) key on MEM_SCHEMA_KEYS and check
# ``mem_schema == MEM_SCHEMA_VERSION`` — a field rename or semantics
# change MUST bump the version so downstream readers fail loudly
# instead of silently zeroing their calibration (shape-drift test:
# tests/test_flight_recorder.py).  Every record that carries ANY byte
# count carries ALL of MEM_SCHEMA_KEYS (absent analysis attrs emit 0).
MEM_SCHEMA_VERSION = 1
MEM_SCHEMA_KEYS = ("argument_bytes", "output_bytes", "temp_bytes",
                   "alias_bytes", "peak_bytes")


def _dir() -> str:
    return os.environ.get("PADDLE_TRACE_DIR", "paddle_trace")


def _role() -> str:
    return os.environ.get("PADDLE_TRACE_ROLE", "proc")


def enabled() -> bool:
    """Is the ring recording?  (The default; ``PADDLE_FLIGHT=0`` kills
    it.)"""
    return _ring_on


def dumps_enabled() -> bool:
    """Are dump triggers live?  (Full mode: ``PADDLE_FLIGHT=1`` or
    :func:`enable`.)"""
    return _dumps_on


class FlightRecorder:
    """Bounded ring of recent events: capped by count AND by the total
    serialized byte size, evicting oldest-first.  An event's cost is
    the length of its JSONL line — exactly what a dump would write, so
    the byte bound is the bound on bundle size too."""

    def __init__(self, capacity: int = _DEFAULT_CAPACITY,
                 max_bytes: int = _DEFAULT_MAX_BYTES):
        self.capacity = int(capacity)
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque()
        self._bytes = 0
        self.dropped = 0      # events evicted since process start

    def record(self, kind: str, **fields):
        rec = {"t": "event", "kind": str(kind),
               "ts_us": time.time_ns() // 1000}
        rec.update(fields)
        try:
            line = json.dumps(rec, separators=(",", ":"))
        except (TypeError, ValueError):
            rec = {k: (v if isinstance(v, (int, float, str, bool,
                                           type(None))) else str(v))
                   for k, v in rec.items()}
            line = json.dumps(rec, separators=(",", ":"))
        n = len(line) + 1
        with self._lock:
            self._ring.append((rec, n))
            self._bytes += n
            while self._ring and (len(self._ring) > self.capacity
                                  or self._bytes > self.max_bytes):
                _, m = self._ring.popleft()
                self._bytes -= m
                self.dropped += 1
        return rec

    def events(self) -> List[dict]:
        with self._lock:
            return [r for r, _ in self._ring]

    def nbytes(self) -> int:
        with self._lock:
            return self._bytes

    def clear(self):
        with self._lock:
            self._ring.clear()
            self._bytes = 0


_rec = FlightRecorder()

# in-flight op table: begin() without a matching end() means the op is
# still running — a dump lists them so a stall names its wedged RPC
_open_lock = threading.Lock()
_open: Dict[int, dict] = {}
_op_ids = itertools.count(1)

# stall-watchdog progress clock (monotonic; one float write per event)
_progress_mono = time.monotonic()

# latest clock-offset sample per peer, kept OUTSIDE the ring: a clock
# edge is what lets tools/postmortem.py fuse this process's bundle
# onto the run timeline, so it must survive ring eviction no matter
# how many events a long run churned through
_clock_lock = threading.Lock()
_sticky_clocks: Dict[str, dict] = {}

# dump bookkeeping
_dump_lock = threading.Lock()
_dump_seq = itertools.count(1)
_last_dump_by_reason: Dict[str, float] = {}
_bundle_paths: List[str] = []

# compile observatory log
_compile_lock = threading.Lock()
_compile_log: List[dict] = []

_watchdog: Optional["Watchdog"] = None
_handlers_installed = False


def recorder() -> FlightRecorder:
    return _rec


def record(kind: str, **fields):
    """Append one event to the ring (no-op when the recorder is off).
    Kinds in the progress set additionally feed the stall watchdog;
    ``clock`` events are additionally pinned per peer so a dump can
    always be clock-corrected."""
    if not _ring_on:
        return None
    if kind in _PROGRESS_KINDS:
        global _progress_mono
        _progress_mono = time.monotonic()
    rec = _rec.record(kind, **fields)
    if kind == "clock":
        with _clock_lock:
            _sticky_clocks[str(fields.get("peer"))] = rec
    return rec


def begin(kind: str, **fields) -> Optional[int]:
    """Mark the START of a long-running op (an RPC, a serve batch).
    Registers the op in the in-flight table only — NO ring write, so
    the completed-op hot path pays one event, not two.  Returns a
    token for :func:`end`; until then every dump lists the op as in
    flight — the watchdog's bundle names a stalled RPC through exactly
    this."""
    if not _ring_on:
        return None
    tok = next(_op_ids)
    rec = {"kind": str(kind), "ts_us": time.time_ns() // 1000}
    rec.update(fields)
    with _open_lock:
        _open[tok] = rec
    return tok


def end(tok: Optional[int], **fields):
    """Close a :func:`begin` op: writes ONE ring event spanning the op
    (the begin timestamp + duration + merged begin/end fields)."""
    if tok is None or not _ring_on:
        return
    with _open_lock:
        b = _open.pop(tok, None)
    if b is None:
        return
    dur_us = time.time_ns() // 1000 - b["ts_us"]
    global _progress_mono
    _progress_mono = time.monotonic()
    merged = {k: v for k, v in b.items() if k != "kind"}
    merged.update(fields)
    merged["dur_us"] = dur_us      # ts_us from begin rides in merged
    _rec.record(b["kind"], **merged)


def progress(what: str = ""):
    """Mark forward progress without recording an event (hot loops that
    already record elsewhere)."""
    global _progress_mono
    _progress_mono = time.monotonic()


def progress_age() -> float:
    """Seconds since the last observed progress event."""
    return time.monotonic() - _progress_mono


def events() -> List[dict]:
    return _rec.events()


def in_flight() -> List[dict]:
    with _open_lock:
        return [dict(v) for v in _open.values()]


def clear():
    """Tests: empty the ring + in-flight table + compile log + dump
    bookkeeping (rate limits and the per-process bundle cap must not
    leak across tests in a long suite run)."""
    _rec.clear()
    with _open_lock:
        _open.clear()
    with _compile_lock:
        _compile_log.clear()
    with _clock_lock:
        _sticky_clocks.clear()
    with _dump_lock:
        _bundle_paths.clear()
        _last_dump_by_reason.clear()


# ----------------------------------------------------------------------
# compile observatory
# ----------------------------------------------------------------------

def _mem_stats(compiled) -> Optional[dict]:
    """Extract the XLA memory-analysis byte counts from a jax
    ``Compiled`` (or a raw CompiledMemoryStats).  ``peak_bytes`` is the
    standard estimate: arguments + outputs + temps − aliased (donated
    buffers count once)."""
    try:
        ma = (compiled.memory_analysis()
              if hasattr(compiled, "memory_analysis") else compiled)
    except Exception:
        return None
    if ma is None:
        return None
    out = {}
    for a in _MEM_ATTRS:
        v = getattr(ma, a, None)
        if v is not None:
            out[a.replace("_size_in_bytes", "_bytes")] = int(v)
    if not out:
        return None
    out["peak_bytes"] = (out.get("argument_bytes", 0)
                         + out.get("output_bytes", 0)
                         + out.get("temp_bytes", 0)
                         - out.get("alias_bytes", 0))
    # stable schema (MEM_SCHEMA_KEYS): every byte-carrying record has
    # the full key set + version stamp, so calibration readers can
    # detect drift instead of silently reading zeros
    for k in MEM_SCHEMA_KEYS:
        out.setdefault(k, 0)
    out["mem_schema"] = MEM_SCHEMA_VERSION
    return out


def note_compile(program: str, cause: str, wall_ms: float,
                 key=None, compiled=None,
                 mem_cb: Optional[Callable] = None, **extra):
    """Log one lowering/compile event.

    ``cause``: ``first_build`` | ``new_shape_bucket`` |
    ``avoidable_retrace`` (same shapes re-traced for a dtype change) |
    ``load`` / ``prewarm`` (AOT serving) | ``abstract``.

    ``compiled``: a live jax Compiled — memory analysis is read off it
    directly (free).  ``mem_cb``: a thunk producing one (the training
    step's call path, where reaching the executable costs an AOT
    compile) — resolved immediately in full mode, else only on an
    explicit :func:`compile_log` ``resolve=True`` (dumps never
    compile).
    """
    ent = {"program": str(program), "cause": str(cause),
           "wall_ms": round(float(wall_ms), 3),
           "ts_us": time.time_ns() // 1000}
    if key is not None:
        ent["key"] = str(key)
    ent.update(extra)
    mem = _mem_stats(compiled) if compiled is not None else None
    if mem is None and mem_cb is not None:
        if _dumps_on:
            mem = _resolve_mem(mem_cb)
        else:
            ent["_mem_cb"] = mem_cb     # lazy; stripped from dumps
    if mem:
        ent.update(mem)
    with _compile_lock:
        _compile_log.append(ent)
        while len(_compile_log) > _COMPILE_LOG_CAP:
            _compile_log.pop(0)
    if _ring_on:
        _rec.record("compile", **{k: v for k, v in ent.items()
                                  if not k.startswith("_")
                                  and k != "ts_us"})
    try:
        from ..framework import monitor as _monitor
        if _monitor.metrics_enabled():
            _monitor.hist_observe("compile_ms", float(wall_ms))
    except Exception:
        pass
    return ent


def _resolve_mem(cb) -> Optional[dict]:
    try:
        return _mem_stats(cb())
    except Exception:
        return None


def compile_log(resolve: bool = False) -> List[dict]:
    """The per-process compile trajectory (capped FIFO).  With
    ``resolve=True`` pending memory-analysis thunks are evaluated (one
    cached AOT compile each) and folded in."""
    with _compile_lock:
        entries = list(_compile_log)
    out = []
    for e in entries:
        cb = e.get("_mem_cb")
        if cb is not None and resolve:
            mem = _resolve_mem(cb)
            e.pop("_mem_cb", None)
            if mem:
                e.update(mem)
        out.append({k: v for k, v in e.items() if not k.startswith("_")})
    return out


# ----------------------------------------------------------------------
# dumps
# ----------------------------------------------------------------------

def _thread_stacks() -> dict:
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for tid, frame in sys._current_frames().items():
        out[str(tid)] = {
            "name": names.get(tid, "?"),
            "frames": [ln.rstrip() for ln in
                       traceback.format_stack(frame)][-40:],
        }
    return out


def dump(reason: str, exc_info=None, path: Optional[str] = None,
         force: bool = True) -> Optional[str]:
    """Write one postmortem bundle now.  Returns the path (None when
    skipped: recorder killed, rate-limited non-forced dump, or bundle
    cap reached).  Safe to call from signal handlers and excepthooks —
    never raises."""
    if not _ring_on:
        return None
    try:
        now = time.monotonic()
        with _dump_lock:
            if len(_bundle_paths) >= _DUMP_MAX_BUNDLES:
                return None
            last = _last_dump_by_reason.get(reason)
            if not force and last is not None \
                    and now - last < _DUMP_MIN_INTERVAL_S:
                return None
            _last_dump_by_reason[reason] = now
            seq = next(_dump_seq)
        if path is None:
            d = _dir()
            os.makedirs(d, exist_ok=True)
            path = os.path.join(
                d, f"flight-{_role()}-{os.getpid()}-{seq}.jsonl")
        recs: List[dict] = [{
            "t": "meta", "sink": f"{_role()}-{os.getpid()}",
            "role": _role(), "pid": os.getpid(), "reason": str(reason),
            "seq": seq, "ts_us": time.time_ns() // 1000,
            "dropped": _rec.dropped,
            "progress_age_s": round(progress_age(), 3),
        }]
        if exc_info is not None and exc_info[0] is not None:
            recs.append({
                "t": "exc", "type": exc_info[0].__name__,
                "value": str(exc_info[1]),
                "tb": [ln.rstrip() for ln in
                       traceback.format_exception(*exc_info)][-40:]})
        ring = _rec.events()
        recs.extend(ring)
        # pinned clock samples whose ring copy was evicted ride along
        have = {(r.get("peer"), r.get("ts_us")) for r in ring
                if r.get("kind") == "clock"}
        with _clock_lock:
            recs.extend(r for r in _sticky_clocks.values()
                        if (r.get("peer"), r.get("ts_us")) not in have)
        fl = in_flight()
        if fl:
            now_us = time.time_ns() // 1000
            for op in fl:
                op["open_us"] = now_us - op["ts_us"]
            recs.append({"t": "inflight", "ops": fl})
        recs.append({"t": "stacks", "threads": _thread_stacks()})
        try:
            from ..framework import monitor as _monitor
            recs.append({"t": "metrics",
                         **_monitor.metrics_snapshot()})
        except Exception:
            pass
        # resolve=False: a dump must never COMPILE (a lazy mem thunk
        # costs an AOT compile each — a long default-mode run can hold
        # hundreds, turning a crash dump into minutes of XLA work).
        # Full mode resolved memory analysis eagerly at note_compile
        # time, so its entries already carry the bytes.
        cl = compile_log(resolve=False)
        if cl:
            recs.append({"t": "compiles", "entries": cl})
        with open(path, "w") as f:
            for r in recs:
                try:
                    f.write(json.dumps(r, separators=(",", ":")) + "\n")
                except (TypeError, ValueError):
                    pass
        with _dump_lock:
            _bundle_paths.append(path)
        return path
    except Exception:
        return None


def maybe_dump(reason: str) -> Optional[str]:
    """Typed-failure dump site (PSUnavailable / NumericalDivergence /
    ServerOverloaded raise paths): dumps only in full mode, rate
    limited per reason."""
    if not _dumps_on:
        return None
    return dump(reason, exc_info=sys.exc_info(), force=False)


def bundle_paths() -> List[str]:
    with _dump_lock:
        return list(_bundle_paths)


# ----------------------------------------------------------------------
# triggers: excepthooks, signals, watchdog
# ----------------------------------------------------------------------

class Watchdog(threading.Thread):
    """Fires one dump when no progress event lands for ``deadline_s``;
    re-arms once progress resumes."""

    def __init__(self, deadline_s: float, poll_s: Optional[float] = None):
        super().__init__(name="paddle-flight-watchdog", daemon=True)
        self.deadline_s = float(deadline_s)
        self.poll_s = (poll_s if poll_s is not None
                       else max(0.05, min(1.0, self.deadline_s / 4)))
        self._stop = threading.Event()
        self._fired = False
        self.stalls = 0

    def run(self):
        # the watchdog's own start counts as progress: a process that
        # never steps at all (still initializing) is not "stalled"
        # until a full deadline has passed since here
        progress("watchdog_start")
        while not self._stop.wait(self.poll_s):
            age = progress_age()
            if age > self.deadline_s:
                if not self._fired:
                    self._fired = True
                    record("stall", age_s=round(age, 3),
                           deadline_s=self.deadline_s)
                    dump("stall")
                    # publish LAST: a caller polling `stalls` must
                    # find the bundle already on disk (a dump takes
                    # ~ms once many threads' stacks need formatting)
                    self.stalls += 1
            else:
                self._fired = False

    def stop(self):
        self._stop.set()


_prev_excepthook = None
_prev_threading_hook = None


def _excepthook(exc_type, exc, tb):
    dump("unhandled", exc_info=(exc_type, exc, tb))
    if _prev_excepthook is not None:
        _prev_excepthook(exc_type, exc, tb)


def _threading_hook(args):
    dump("thread_unhandled",
         exc_info=(args.exc_type, args.exc_value, args.exc_traceback))
    if _prev_threading_hook is not None:
        _prev_threading_hook(args)


def install_handlers(stall_s: Optional[float] = None):
    """Install the dump triggers: excepthooks, SIGUSR2 on-demand dump,
    SIGTERM/SIGABRT dump-then-die, faulthandler, and (when
    ``stall_s``/``PADDLE_FLIGHT_STALL_S`` > 0) the stall watchdog.
    Idempotent; signal handlers are skipped off the main thread."""
    global _handlers_installed, _prev_excepthook, _prev_threading_hook
    global _watchdog
    if not _handlers_installed:
        _handlers_installed = True
        _prev_excepthook = sys.excepthook
        sys.excepthook = _excepthook
        _prev_threading_hook = threading.excepthook
        threading.excepthook = _threading_hook
        try:
            import faulthandler
            import signal as _signal
            d = _dir()
            os.makedirs(d, exist_ok=True)
            # sidecar for signals Python code cannot survive (SEGV/FPE)
            fh = open(os.path.join(
                d, f"faulthandler-{_role()}-{os.getpid()}.txt"), "w")
            faulthandler.enable(file=fh)
            globals()["_faulthandler_file"] = fh  # keep fd alive

            def _fatal(signum, frame):
                dump(f"signal_{_signal.Signals(signum).name}")
                _signal.signal(signum, _signal.SIG_DFL)
                os.kill(os.getpid(), signum)

            def _usr2(signum, frame):
                dump("SIGUSR2")

            for sig, h in ((getattr(_signal, "SIGUSR2", None), _usr2),
                           (getattr(_signal, "SIGTERM", None), _fatal),
                           (getattr(_signal, "SIGABRT", None), _fatal)):
                if sig is None:
                    continue
                try:
                    _signal.signal(sig, h)
                except (ValueError, OSError):
                    pass        # not the main thread / platform limit
        except Exception:
            pass
    if stall_s is None:
        try:
            stall_s = float(os.environ.get("PADDLE_FLIGHT_STALL_S",
                                           "0") or 0)
        except ValueError:
            stall_s = 0.0
    if stall_s and stall_s > 0 and _watchdog is None:
        _watchdog = Watchdog(stall_s)
        _watchdog.start()


def enable(stall_s: Optional[float] = None, dumps: bool = True):
    """Programmatic full enable (tests; the env path is
    ``PADDLE_FLIGHT=1``)."""
    global _ring_on, _dumps_on
    _ring_on = True
    if dumps:
        _dumps_on = True
        install_handlers(stall_s=stall_s)


def disable(ring: bool = False):
    """Turn dump triggers (and optionally the ring) off.  Installed
    signal/except hooks stay installed but :func:`dump` becomes a
    no-op when the ring is off."""
    global _ring_on, _dumps_on, _watchdog
    _dumps_on = False
    if ring:
        _ring_on = False
    if _watchdog is not None:
        _watchdog.stop()
        _watchdog = None


def enable_from_env():
    """Honour ``PADDLE_FLIGHT`` (called at package import;
    idempotent)."""
    if _dumps_on:
        install_handlers()


# package-__init__ re-export names (record/dump/enabled are too generic
# to put on the paddle_tpu.observability surface unprefixed)
flight_record = record
flight_dump = dump
flight_enabled = enabled
