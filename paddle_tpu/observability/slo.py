"""Declarative SLOs with multi-window burn rates (ISSUE 12 tentpole).

A dashboard full of metrics is not an objective; production serving
runs on a handful of explicit promises — "p99 TTFT under X ms", "shed
rate under Y%", "replica lag under Z mutations" — and pages when the
ERROR BUDGET burns too fast, not when a single sample spikes.  This
module is that layer, built directly on the snapshot shape
:mod:`~paddle_tpu.framework.monitor` already exports (and therefore on
the fleet aggregator's merged rollup too: pass
``FleetAggregator.rollup`` as the engine's source and the objectives
become FLEET objectives).

Objectives (:class:`SLO`):

- ``kind="latency"`` — a histogram family + a bound: the good/bad
  split is "samples <= bound" using the le-bucket at or above the
  bound (exact for bounds on a bucket edge, documented-conservative
  otherwise).  ``budget`` is the allowed bad fraction — 0.01 means
  "p99 under bound".
- ``kind="error_rate"`` — bad-counter / total-counter (e.g. sheds /
  submitted), ``budget`` the allowed ratio.
- ``kind="gauge_bound"`` — a gauge must stay <= bound (e.g.
  ``ps_replica_lag_seq``); breaches immediately on the current value
  (no burn windows — a lag bound is a state, not a budget).

Burn-rate evaluation (the SRE multi-window pattern): for each
``(window_s, threshold)`` pair the engine takes the counter deltas
over the trailing window from its own sample history and computes
``burn = (bad/total) / budget`` — burn 1.0 means "exactly spending the
budget", 14.4 means "the whole 30-day budget in 2 days".  A breach
requires EVERY window to exceed its threshold (the short window makes
alerts fast, the long window keeps them from flapping) plus
``min_events`` total events in the longest window (no paging on 3
requests).  History shorter than a window degrades to since-first-
sample deltas — a cold engine can still breach, it just cannot
under-report by pretending the past was clean.

On an ok -> breach transition the engine records an ``slo.breach``
flight event and calls ``maybe_dump("SLOBreach:<name>")`` so full-mode
processes capture a postmortem bundle WITH the breach context (the
ring holds the recent request/serve/PS events; ``tools/postmortem.py``
sorts the breach first via ``_BAD_KINDS``).  Recovery records
``slo.recover``; repeated breach ticks do not re-fire (latched).
Current burn rates are published as labeled gauges
(``slo_burn_rate{slo="...",window="..."}``) and breach states as
``slo_breached{slo="..."}`` so the fleet's own /metrics shows the
objectives.

Must stay importable without jax.
"""
from __future__ import annotations

import bisect
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Sequence, Tuple

from ..framework import monitor as _monitor
from . import flight_recorder as _flight

__all__ = ["SLO", "SloEngine", "DEFAULT_WINDOWS"]

# (window_s, burn threshold): the classic fast+slow pair, scaled to
# service-minutes rather than SRE-handbook days — tune per deployment
DEFAULT_WINDOWS = ((60.0, 14.4), (300.0, 6.0))


class SLO:
    """One declarative objective (module docstring for the kinds)."""

    KINDS = ("latency", "error_rate", "gauge_bound")

    def __init__(self, name: str, kind: str, metric: str,
                 bound: Optional[float] = None,
                 total: Optional[str] = None,
                 budget: float = 0.01,
                 windows: Sequence[Tuple[float, float]]
                 = DEFAULT_WINDOWS,
                 min_events: int = 1,
                 labels: Optional[Dict] = None):
        if kind not in self.KINDS:
            raise ValueError(f"unknown SLO kind {kind!r} "
                             f"(want one of {self.KINDS})")
        if kind in ("latency", "gauge_bound") and bound is None:
            raise ValueError(f"SLO {name!r}: kind {kind!r} needs a "
                             "bound")
        if kind == "error_rate" and total is None:
            raise ValueError(f"SLO {name!r}: error_rate needs the "
                             "total counter name")
        if not 0.0 < float(budget) <= 1.0:
            raise ValueError(f"SLO {name!r}: budget must be in (0, 1]")
        self.name = str(name)
        self.kind = kind
        self.metric = str(metric)     # histogram / bad counter / gauge
        self.bound = None if bound is None else float(bound)
        self.total = total            # total counter (error_rate)
        self.budget = float(budget)
        self.windows = tuple((float(w), float(t)) for w, t in windows)
        if not self.windows and kind != "gauge_bound":
            raise ValueError(f"SLO {name!r}: needs >= 1 burn window")
        self.min_events = int(min_events)
        self.labels = dict(labels) if labels else None

    # -- snapshot -> cumulative (bad, total) ---------------------------
    def _series(self, snap: Dict, family: str) -> Dict:
        fam = snap.get(family, {})
        if self.labels:
            fam = snap.get("labeled", {}).get(family, {})
            ent = fam.get(self.metric, {})
            return {self.metric: ent.get(
                _monitor.label_key(self.labels))}
        return fam

    def counts(self, snap: Dict) -> Optional[Tuple[int, int]]:
        """Cumulative (bad, total) events in ``snap`` — the burn
        windows difference these.  None when the series is absent
        (nothing observed yet)."""
        if self.kind == "latency":
            h = self._series(snap, "histograms").get(self.metric)
            if not h:
                return None
            bounds = [le for le, _ in h["buckets"]]
            i = bisect.bisect_left(bounds, self.bound)
            good = h["buckets"][i][1] if i < len(bounds) else h["count"]
            return int(h["count"]) - int(good), int(h["count"])
        if self.kind == "error_rate":
            bad = self._series(snap, "counters").get(self.metric)
            tot = snap.get("counters", {}).get(self.total)
            if bad is None and tot is None:
                return None
            return int(bad or 0), int(tot or 0)
        return None                    # gauge_bound has no counts

    def gauge_value(self, snap: Dict) -> Optional[float]:
        v = self._series(snap, "gauges").get(self.metric)
        return None if v is None else float(v)


class SloEngine:
    """Evaluate a set of :class:`SLO`\\ s against a stream of metric
    snapshots (local registry by default; pass the fleet aggregator's
    ``rollup`` for fleet objectives)."""

    def __init__(self, slos: Sequence[SLO], source=None,
                 history_s: Optional[float] = None):
        names = [s.name for s in slos]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO names in {names}")
        self.slos = list(slos)
        self._source = source or _monitor.metrics_snapshot
        max_w = max((w for s in self.slos for w, _ in s.windows),
                    default=300.0)
        self.history_s = float(history_s or (2.0 * max_w))
        # per-slo history of (ts_s, (bad, total)) cumulative samples
        self._hist: Dict[str, Deque[Tuple[float, Tuple[int, int]]]] = {
            s.name: deque() for s in self.slos}
        self._breached: Dict[str, bool] = {s.name: False
                                           for s in self.slos}
        self.breaches = 0

    # -- one tick ------------------------------------------------------
    def evaluate(self, snapshot: Optional[Dict] = None,
                 now: Optional[float] = None) -> List[Dict]:
        """One evaluation tick.  Returns one status dict per SLO:
        ``{"slo", "kind", "ok", "burn": {window: rate}, "value"}``.
        Breach transitions emit flight events + ``maybe_dump`` (module
        docstring)."""
        snap = snapshot if snapshot is not None else self._source()
        now = time.time() if now is None else float(now)
        out = []
        for slo in self.slos:
            if slo.kind == "gauge_bound":
                st = self._eval_gauge(slo, snap)
            else:
                st = self._eval_burn(slo, snap, now)
            self._transition(slo, st)
            out.append(st)
        return out

    def _eval_gauge(self, slo: SLO, snap: Dict) -> Dict:
        v = slo.gauge_value(snap)
        ok = v is None or v <= slo.bound
        return {"slo": slo.name, "kind": slo.kind, "ok": ok,
                "value": v, "bound": slo.bound, "burn": {}}

    def _eval_burn(self, slo: SLO, snap: Dict, now: float) -> Dict:
        cur = slo.counts(snap)
        hist = self._hist[slo.name]
        burn: Dict[str, float] = {}
        ok = True
        if cur is not None:
            hist.append((now, cur))
            while hist and now - hist[0][0] > self.history_s \
                    and len(hist) > 1:
                hist.popleft()
            breach_all = True
            events_long = 0
            for w, threshold in slo.windows:
                # oldest sample still inside the window; degrade to
                # the first sample when history is shorter
                base = hist[0]
                for ts, c in hist:
                    if now - ts <= w:
                        break
                    base = (ts, c)
                dbad = cur[0] - base[1][0]
                dtot = cur[1] - base[1][1]
                events_long = max(events_long, dtot)
                rate = (dbad / dtot) if dtot > 0 else 0.0
                b = rate / slo.budget
                burn[str(int(w))] = round(b, 4)
                if b < threshold:
                    breach_all = False
            ok = not (breach_all and events_long >= slo.min_events)
        return {"slo": slo.name, "kind": slo.kind, "ok": ok,
                "burn": burn,
                "value": (cur[0] / cur[1]) if cur and cur[1] else None}

    def _transition(self, slo: SLO, st: Dict):
        for w, b in st["burn"].items():
            _monitor.gauge_set("slo_burn_rate", b,
                               labels={"slo": slo.name, "window": w})
        _monitor.gauge_set("slo_breached", 0.0 if st["ok"] else 1.0,
                           labels={"slo": slo.name})
        was = self._breached[slo.name]
        if not st["ok"] and not was:
            self.breaches += 1
            _monitor.stat_add("slo_breaches",
                              labels={"slo": slo.name})
            _flight.record("slo.breach", slo=slo.name,
                           slo_kind=slo.kind, metric=slo.metric,
                           value=st.get("value"), burn=st["burn"],
                           bound=slo.bound)
            # full-mode processes capture the breach context as a
            # postmortem bundle (rate limited per reason inside)
            _flight.maybe_dump(f"SLOBreach:{slo.name}")
        elif st["ok"] and was:
            _flight.record("slo.recover", slo=slo.name)
        self._breached[slo.name] = not st["ok"]

    # -- background loop ----------------------------------------------
    def run_every(self, interval_s: float):
        """Spawn a daemon evaluating every ``interval_s`` seconds;
        returns a ``stop()``-able handle."""
        import threading
        stop = threading.Event()
        engine = self

        class _Handle:
            def stop(self):
                stop.set()
                t.join(timeout=10.0)

        def _loop():
            while not stop.wait(interval_s):
                try:
                    engine.evaluate()
                except Exception:
                    _monitor.stat_add("slo_eval_errors")

        t = threading.Thread(target=_loop, name="paddle-slo-engine",
                             daemon=True)
        t.start()
        return _Handle()
