"""Per-request lifecycle tracing for the serving tier (ISSUE 12).

The PR 5 tracing stack draws what each THREAD did; a continuous-
batching server multiplexes every request through one scheduler
thread, so a thread view shows one undifferentiated decode stream and
answers none of the questions a serving incident asks: where did THIS
request's time go — queued, admitted cold or on a prefix hit, evicted
and re-admitted, how long to first token?

A :class:`RequestTrace` is one request's span chain:

    req (root, submit -> finish)
      req.queue     submit -> admission (re-opened after an eviction)
      req.admit     instant; kind = prefix-hit / cold / readmit, plus
                    an admit-rollback instant when the capacity check
                    sheds the admission
      req.prefill   the prefill dispatch window (batched: every rider
                    of one dispatch gets its own span over it)
      req.first_token  instant carrying ``ttft_ms`` — the SAME value
                    the server observes into ``serve_ttft_ms``, so the
                    span view and the histogram agree by construction
      req.decode    sampled decode iterations (1 in
                    ``PADDLE_TRACE_EVERY``)
      req.evict / req.finish  terminal / requeue instants

Every span is written via :func:`trace.emit_span` with explicit ids —
no thread-local stack, because phases open and close on different
threads and interleave across requests — and the whole chain shares
one virtual lane id (``tid``), so ``tools/trace_merge.py`` and
``tools/postmortem.py`` render ONE LANE PER REQUEST (lane name from
the root span's ``lane`` arg).

Cost discipline: construction is gated at the call site on
``trace.enabled()`` (servers hold ``rt = None`` when tracing is off —
the off path stays one attribute check); phase bookkeeping is two
dict writes; decode spans are sampled.  Timestamps anchor wall-clock
microseconds at construction and advance by ``perf_counter`` deltas,
so phases nest exactly inside the root span regardless of wall-clock
steps.

Must stay importable without jax.
"""
from __future__ import annotations

import os
import time
from typing import Dict, Optional

from . import trace as _trace

__all__ = ["RequestTrace", "LANE_BASE"]

# virtual-lane tid base: far above real thread idents' low bits after
# the renderers' % (1 << 31) fold is NOT guaranteed, but collisions
# only cosmetically share a lane — ids in records stay per-request
LANE_BASE = 0x40000000


class RequestTrace:
    """Span chain + virtual lane for ONE serving request."""

    __slots__ = ("server", "rid", "tenant", "trace_id", "root_id",
                 "lane", "t0_us", "_mono0", "_open")

    def __init__(self, server: str, rid: int,
                 tenant: Optional[str] = None):
        self.server = str(server)
        self.rid = int(rid)
        self.tenant = tenant
        self.trace_id = _trace.new_id()
        self.root_id = _trace.new_id()
        # one lane per request; fold into 31 bits for Chrome tids
        self.lane = (LANE_BASE + ((os.getpid() << 12) ^ self.rid)) \
            % (1 << 31)
        self.t0_us = time.time_ns() // 1000
        self._mono0 = time.perf_counter()
        self._open: Dict[str, float] = {}

    # -- clock ----------------------------------------------------------
    def _now_us(self) -> int:
        return self.t0_us + int(
            (time.perf_counter() - self._mono0) * 1e6)

    def _args(self, extra: Dict) -> Dict:
        a = {"rid": self.rid}
        if self.tenant is not None:
            a["tenant"] = self.tenant
        a.update(extra)
        return a

    # -- phases ---------------------------------------------------------
    def begin(self, phase: str):
        """Open a named phase (re-openable: ``queue`` re-opens after an
        eviction).  Cheap — one dict write, no record."""
        self._open[phase] = time.perf_counter()

    def end(self, phase: str, **args):
        """Close a phase -> one ``req.<phase>`` span in this request's
        lane (ignored when the phase was never opened — a server
        restart path must not crash on bookkeeping)."""
        t0 = self._open.pop(phase, None)
        if t0 is None:
            return
        now = time.perf_counter()
        ts_us = self.t0_us + int((t0 - self._mono0) * 1e6)
        _trace.emit_span(
            f"req.{phase}", ts_us, int((now - t0) * 1e6),
            self.trace_id, _trace.new_id(), parent=self.root_id,
            tid=self.lane, args=self._args(args))

    def span_at(self, name: str, dur_ms: float, **args):
        """One span of known duration ENDING now (the decode loop
        measures the step first, then attributes it)."""
        dur_us = max(int(dur_ms * 1e3), 0)
        _trace.emit_span(
            f"req.{name}", self._now_us() - dur_us, dur_us,
            self.trace_id, _trace.new_id(), parent=self.root_id,
            tid=self.lane, args=self._args(args))

    def instant(self, name: str, **args):
        """Zero-duration marker in the request lane."""
        _trace.emit_span(
            f"req.{name}", self._now_us(), 0, self.trace_id,
            _trace.new_id(), parent=self.root_id, tid=self.lane,
            args=self._args(args))

    # -- terminal -------------------------------------------------------
    def finish(self, reason: str, **args):
        """Close the chain: any still-open phases end here, then the
        ROOT span covering submit -> now is written, carrying the lane
        name (``<server>-req-<rid>``) the renderers turn into the
        lane's thread_name."""
        for phase in list(self._open):
            self.end(phase)
        _trace.emit_span(
            "req", self.t0_us,
            int((time.perf_counter() - self._mono0) * 1e6),
            self.trace_id, self.root_id, tid=self.lane,
            args=self._args({"lane": f"{self.server}-req-{self.rid}",
                             "reason": reason, **args}))
