"""paddle_tpu.optimizer (parity: python/paddle/optimizer/)."""
from . import lr  # noqa: F401
from .optimizer import Optimizer  # noqa: F401
from .wrappers import LookaheadOptimizer, ModelAverage  # noqa: F401
from .optimizers import (SGD, Adadelta, Adagrad, Adam, Adamax, AdamW, Lamb,  # noqa: F401
                         Lars, Momentum, RMSProp)

__all__ = ["Optimizer", "SGD", "Momentum", "Adam", "AdamW", "Adamax",
           "Adagrad", "Adadelta", "RMSProp", "Lamb", "Lars", "lr"]
