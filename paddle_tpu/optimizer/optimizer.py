"""Optimizer base.

Parity: reference python/paddle/optimizer/optimizer.py. The reference emits
per-parameter *graph ops* (operators/optimizers/sgd_op.cc, adam_op.cc...);
here each optimizer defines a pure ``_update(param, grad, *state) ->
(new_param, *new_state)`` rule that runs as one jitted XLA call per
parameter (fused muls/adds on the VPU), and the same rule is reusable
inside a fully-jitted train step (jit/train_step.py) where XLA fuses the
whole update sweep.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

from ..framework.core import Tensor, no_grad
from ..nn.clip import ClipGradBase
from .lr import LRScheduler

__all__ = ["Optimizer"]


class _AbstractParamView:
    """Stand-in handed to ``_create_state`` when shape-tracing slots for
    a meta-init parameter (see Optimizer.opt_state): ``_value`` is the
    eval_shape tracer; every other attribute forwards to the real
    parameter.  Caveat: ``id(view) != id(param)``, so id-keyed per-param
    flags (AdamW no-decay, Lamb exclusions) fall back to their defaults —
    harmless here because only slot SHAPES survive eval_shape."""

    __slots__ = ("_p", "_value")

    def __init__(self, p, value):
        object.__setattr__(self, "_p", p)
        object.__setattr__(self, "_value", value)

    def __getattr__(self, name):
        return getattr(object.__getattribute__(self, "_p"), name)


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        if parameters is None:
            raise ValueError(
                "parameters is required in the TPU-native build (no global "
                "program to harvest them from)")
        self._parameter_list = list(parameters)
        self._learning_rate = learning_rate
        self._grad_clip = grad_clip
        if isinstance(weight_decay, (int, float)) and weight_decay is not None:
            from ..regularizer import L2Decay
            weight_decay = L2Decay(weight_decay)
        self._weight_decay = weight_decay
        # state: param id -> dict of jnp arrays
        self._accumulators: Dict[int, Dict[str, jnp.ndarray]] = {}
        self._global_step = 0
        self._skipped_steps = 0   # guard/scaler-dropped steps (audit only)
        self._jit_update = jax.jit(self._update)
        # NOT jitted: rows/vals shapes track the batch's unique-id count,
        # which changes almost every step — jit would retrace per count.
        # The row-sliced update is a handful of small eager ops.
        self._sparse_update = self._sparse_apply

    # ------------------------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def set_lr(self, value: float):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError(
                "set_lr is not allowed when the lr is an LRScheduler; call "
                "scheduler.step() instead (parity with the reference)")
        self._learning_rate = value

    # ------------------------------------------------------------------
    def _create_state(self, p: Tensor) -> Dict[str, jnp.ndarray]:
        """Per-parameter slot init (override)."""
        return {}

    def _update(self, p, g, lr, state: Dict[str, jnp.ndarray]):
        """Pure update rule (override): returns (new_p, new_state)."""
        raise NotImplementedError

    def _sparse_apply(self, pv, rows, vals, lr, state):
        """Lazy row-wise update for a SelectedRows gradient: run the
        optimizer's own dense ``_update`` rule on the touched rows only
        (reference: operators/optimizers/adam_op.h lazy_mode — untouched
        rows keep their momenta/params; scalar state such as beta powers
        advances globally, matching the reference's per-step beta_pow
        ops)."""
        sub = {k: (v[rows] if getattr(v, "shape", None) == pv.shape else v)
               for k, v in state.items()}
        new_rows, new_sub = self._update(pv[rows], vals, lr, sub)
        new_p = pv.at[rows].set(new_rows.astype(pv.dtype))
        new_state = {
            k: (state[k].at[rows].set(v) if getattr(
                state[k], "shape", None) == pv.shape else v)
            for k, v in new_sub.items()}
        return new_p, new_state

    # ------------------------------------------------------------------
    @no_grad()
    def step(self):
        params_grads = [(p, p.grad) for p in self._parameter_list
                        if p.grad is not None and not p.stop_gradient]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        lr = jnp.asarray(self.get_lr(), jnp.float32)
        from ..framework.selected_rows import SelectedRows
        for p, g in params_grads:
            sid = id(p)
            if sid not in self._accumulators:
                self._accumulators[sid] = self._create_state(p)
            # ParamAttr contract: per-param lr multiplier; a param-level
            # regularizer overrides the optimizer-level weight decay
            lr_mult = getattr(p, "optimize_attr",
                              {}).get("learning_rate", 1.0)
            plr = lr if lr_mult == 1.0 else lr * lr_mult
            decay = getattr(p, "regularizer", None) or self._weight_decay
            if isinstance(g, SelectedRows):
                sr = g.merge()
                vals = sr.values
                if decay is not None:
                    # lazy semantics: decay only the touched rows
                    vals = decay.apply_gradient(p._value[sr.rows], vals)
                new_p, new_state = self._sparse_update(
                    p._value, sr.rows, vals, plr, self._accumulators[sid])
            else:
                gv = g._value if isinstance(g, Tensor) else g
                if decay is not None:
                    gv = decay.apply_gradient(p._value, gv)
                new_p, new_state = self._jit_update(
                    p._value, gv, plr, self._accumulators[sid])
            p._value = new_p
            self._accumulators[sid] = new_state
        self._global_step += 1

    minimize_step = step

    def grad_leaves(self):
        """Raw grad arrays for every parameter holding one (SelectedRows
        contribute their value blocks).  This is the canonical input to
        train_guard's fused health check and GradScaler.unscale_'s
        found_inf reduction — one list, zero host syncs."""
        from ..framework.selected_rows import SelectedRows
        out = []
        for p in self._parameter_list:
            g = p.grad
            if g is None:
                continue
            out.append(g.values if isinstance(g, SelectedRows)
                       else (g._value if isinstance(g, Tensor) else g))
        return out

    def skip_step(self):
        """Drop this step's gradients without applying them (train_guard
        skip verdict / GradScaler found_inf).  ``_global_step`` does NOT
        advance — a skipped step must leave the optimizer bit-identical
        to never having seen the batch, or rewind-exactness breaks."""
        self.clear_grad()
        self._skipped_steps += 1

    def clear_grad(self, set_to_zero=False):
        for p in self._parameter_list:
            p.grad = None

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        """paddle v1-style: backward + step in one call."""
        loss.backward()
        self.step()
        return [], [(p, p.grad) for p in self._parameter_list]

    # ------------------------------------------------------------------
    def state_dict(self):
        out = {"global_step": self._global_step}
        if isinstance(self._learning_rate, LRScheduler):
            out["LR_Scheduler"] = self._learning_rate.state_dict()
        for i, p in enumerate(self._parameter_list):
            st = self._accumulators.get(id(p))
            if st:
                key = p.name or f"param_{i}"
                for k, v in st.items():
                    out[f"{key}.{k}"] = Tensor(v)
        return out

    def set_state_dict(self, state_dict):
        import numpy as np
        self._global_step = int(state_dict.get("global_step", 0))
        if (isinstance(self._learning_rate, LRScheduler)
                and "LR_Scheduler" in state_dict):
            self._learning_rate.set_state_dict(state_dict["LR_Scheduler"])
        for i, p in enumerate(self._parameter_list):
            key = p.name or f"param_{i}"
            st = self._create_state(p)
            found = False
            for k in list(st):
                sk = f"{key}.{k}"
                if sk in state_dict:
                    v = state_dict[sk]
                    st[k] = v._value if isinstance(v, Tensor) else jnp.asarray(np.asarray(v))
                    found = True
            # storage-transformed slots beyond _create_state's layout
            # (e.g. int8 moments carry "<slot>@scale" leaves when saved
            # from a DistributedTrainStep with moment_dtype="int8"):
            # restore any dot-free suffix under this param's prefix
            prefix = f"{key}."
            for sk, v in state_dict.items():
                k = sk[len(prefix):] if sk.startswith(prefix) else None
                if k and k not in st and "." not in k:
                    st[k] = v._value if isinstance(v, Tensor) \
                        else jnp.asarray(np.asarray(v))
                    found = True
            # decode int8-quantized slots back to plain f32 at restore:
            # eager step() math and DistributedTrainSteps configured
            # with a DIFFERENT moment_dtype must never see raw codes —
            # a step with moment_dtype="int8" simply re-encodes on its
            # next call (dist_step._storage_cast)
            for k in [k for k in st if k.endswith("@scale")]:
                base = k[: -len("@scale")]
                if base in st and st[base].dtype == jnp.int8:
                    from ..distributed.fleet.dist_step import _q8_decode
                    st[base] = _q8_decode(st[base], st.pop(k))
                else:
                    st.pop(k)
            if found:
                self._accumulators[id(p)] = st

    # functional view for jitted train steps -----------------------------
    def opt_state(self):
        """Pytree of all accumulator state, aligned with parameter list."""
        states = []
        for p in self._parameter_list:
            if id(p) not in self._accumulators:
                if isinstance(p._value, jax.ShapeDtypeStruct):
                    # meta-init param (framework.core.abstract_init):
                    # derive slot AVALS by shape-tracing _create_state —
                    # a 7B model's moments must never materialize here
                    self._accumulators[id(p)] = jax.eval_shape(
                        lambda v, _p=p: self._create_state(
                            _AbstractParamView(_p, v)), p._value)
                else:
                    self._accumulators[id(p)] = self._create_state(p)
            states.append(self._accumulators[id(p)])
        return states

    def functional_update(self, params: Sequence[jnp.ndarray],
                          grads: Sequence[jnp.ndarray], states, lr=None,
                          sequential: bool = False,
                          state_decode=None, state_encode=None):
        """Pure batched update for use inside jit/pjit (no Tensor objects).
        Applies grad_clip and weight_decay exactly like the eager step().

        ``state_decode(i, s)`` / ``state_encode(i, ns)`` convert slot
        storage to/from the update's f32 working form (dist_step's
        low-precision moment_dtype).  ``sequential=True`` threads an
        optimization_barrier token through the per-param updates so XLA
        schedules them one after another and REUSES the decode/encode
        scratch buffers — otherwise every slot's f32 copy materializes
        concurrently, adding O(total params) f32 temps to peak HBM.  The
        epilogue is bandwidth-bound elementwise work, so ordering it
        costs nothing.
        """
        lr = jnp.asarray(self.get_lr() if lr is None else lr, jnp.float32)
        if self._grad_clip is not None:
            grads = self._grad_clip.apply_values(list(grads))
        new_ps, new_ss = [], []
        token = None
        for i, (p, g, s) in enumerate(zip(params, grads, states)):
            if sequential and token is not None:
                (g, s), _ = jax.lax.optimization_barrier(((g, s), token))
            if state_decode is not None:
                s = state_decode(i, s)
            if self._weight_decay is not None:
                g = self._weight_decay.apply_gradient(p, g)
            np_, ns = self._update(p, g, lr, s)
            if state_encode is not None:
                ns = state_encode(i, ns)
            new_ps.append(np_)
            new_ss.append(ns)
            token = np_
        return new_ps, new_ss

    def load_opt_state(self, states):
        for p, s in zip(self._parameter_list, states):
            self._accumulators[id(p)] = s
