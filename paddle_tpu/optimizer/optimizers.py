"""The optimizer zoo.

Parity: python/paddle/optimizer/{sgd,momentum,adam,adamw,adamax,adagrad,
adadelta,rmsprop,lamb}.py + incubate Lars. Reference executes these as
per-parameter C++/CUDA graph ops (operators/optimizers/*.cc); here each is
a pure jitted update rule (see optimizer.py) fused by XLA.
"""
from __future__ import annotations

from typing import Dict

import jax.numpy as jnp

from ..framework.core import Tensor
from .optimizer import Optimizer

__all__ = ["SGD", "Momentum", "Adam", "AdamW", "Adamax", "Adagrad",
           "Adadelta", "RMSProp", "Lamb", "Lars"]


class SGD(Optimizer):
    def _update(self, p, g, lr, state):
        return p - lr.astype(p.dtype) * g.astype(p.dtype), state


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 name=None):
        self._momentum = momentum
        self._nesterov = use_nesterov
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)

    def _create_state(self, p):
        return {"velocity": jnp.zeros_like(p._value)}

    def _update(self, p, g, lr, state):
        g = g.astype(p.dtype)
        v = self._momentum * state["velocity"] + g
        if self._nesterov:
            step = g + self._momentum * v
        else:
            step = v
        return p - lr.astype(p.dtype) * step, {"velocity": v}


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 name=None):
        self._beta1 = beta1
        self._beta2 = beta2
        self._eps = epsilon
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)

    def _create_state(self, p):
        return {"m": jnp.zeros_like(p._value),
                "v": jnp.zeros_like(p._value),
                "beta1_pow": jnp.ones((), jnp.float32),
                "beta2_pow": jnp.ones((), jnp.float32)}

    def _adam_core(self, p, g, lr, state):
        g32 = g.astype(jnp.float32)
        m = self._beta1 * state["m"] + (1 - self._beta1) * g32
        v = self._beta2 * state["v"] + (1 - self._beta2) * (g32 * g32)
        b1p = state["beta1_pow"] * self._beta1
        b2p = state["beta2_pow"] * self._beta2
        m_hat = m / (1 - b1p)
        v_hat = v / (1 - b2p)
        step = lr * m_hat / (jnp.sqrt(v_hat) + self._eps)
        new_state = {"m": m, "v": v, "beta1_pow": b1p, "beta2_pow": b2p}
        return step, new_state

    def _update(self, p, g, lr, state):
        step, new_state = self._adam_core(p, g, lr, state)
        return (p.astype(jnp.float32) - step).astype(p.dtype), new_state


class AdamW(Adam):
    """Decoupled weight decay (the reference implements AdamW as adam op +
    pre-scaled param decay, python/paddle/optimizer/adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None):
        self._coeff = weight_decay if isinstance(weight_decay, (int, float)) \
            else 0.01
        self._apply_decay_param_fun = apply_decay_param_fun
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip)
        self._decay_flags = {
            id(p): (apply_decay_param_fun is None or
                    apply_decay_param_fun(p.name))
            for p in self._parameter_list}

    def _update(self, p, g, lr, state):
        step, new_state = self._adam_core(p, g, lr, state)
        p32 = p.astype(jnp.float32)
        decay = state["decay"]
        p32 = p32 * (1.0 - lr * self._coeff * decay)
        new_state["decay"] = decay  # carry the flag through every step
        return (p32 - step).astype(p.dtype), new_state

    def _create_state(self, p):
        st = super()._create_state(p)
        st["decay"] = jnp.asarray(
            1.0 if self._decay_flags.get(id(p), True) else 0.0, jnp.float32)
        return st


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        self._beta1 = beta1
        self._beta2 = beta2
        self._eps = epsilon
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)

    def _create_state(self, p):
        return {"m": jnp.zeros_like(p._value),
                "inf_norm": jnp.zeros_like(p._value),
                "beta1_pow": jnp.ones((), jnp.float32)}

    def _update(self, p, g, lr, state):
        g32 = g.astype(jnp.float32)
        m = self._beta1 * state["m"] + (1 - self._beta1) * g32
        u = jnp.maximum(self._beta2 * state["inf_norm"], jnp.abs(g32))
        b1p = state["beta1_pow"] * self._beta1
        step = lr * m / ((1 - b1p) * (u + self._eps))
        return ((p.astype(jnp.float32) - step).astype(p.dtype),
                {"m": m, "inf_norm": u, "beta1_pow": b1p})


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None,
                 initial_accumulator_value=0.0, name=None):
        self._eps = epsilon
        self._init_acc = initial_accumulator_value
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)

    def _create_state(self, p):
        return {"moment": jnp.full_like(p._value, self._init_acc)}

    def _update(self, p, g, lr, state):
        g32 = g.astype(jnp.float32)
        acc = state["moment"] + g32 * g32
        step = lr * g32 / (jnp.sqrt(acc) + self._eps)
        return ((p.astype(jnp.float32) - step).astype(p.dtype),
                {"moment": acc})


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        self._eps = epsilon
        self._rho = rho
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)

    def _create_state(self, p):
        return {"avg_sq_grad": jnp.zeros_like(p._value),
                "avg_sq_update": jnp.zeros_like(p._value)}

    def _update(self, p, g, lr, state):
        g32 = g.astype(jnp.float32)
        asg = self._rho * state["avg_sq_grad"] + (1 - self._rho) * g32 * g32
        upd = (jnp.sqrt(state["avg_sq_update"] + self._eps) /
               jnp.sqrt(asg + self._eps)) * g32
        asu = self._rho * state["avg_sq_update"] + (1 - self._rho) * upd * upd
        return ((p.astype(jnp.float32) - lr * upd).astype(p.dtype),
                {"avg_sq_grad": asg, "avg_sq_update": asu})


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        self._rho = rho
        self._eps = epsilon
        self._momentum = momentum
        self._centered = centered
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)

    def _create_state(self, p):
        st = {"mean_square": jnp.zeros_like(p._value),
              "momentum": jnp.zeros_like(p._value)}
        if self._centered:
            st["mean_grad"] = jnp.zeros_like(p._value)
        return st

    def _update(self, p, g, lr, state):
        g32 = g.astype(jnp.float32)
        ms = self._rho * state["mean_square"] + (1 - self._rho) * g32 * g32
        if self._centered:
            mg = self._rho * state["mean_grad"] + (1 - self._rho) * g32
            denom = jnp.sqrt(ms - mg * mg + self._eps)
        else:
            denom = jnp.sqrt(ms + self._eps)
        mom = self._momentum * state["momentum"] + lr * g32 / denom
        new_state = {"mean_square": ms, "momentum": mom}
        if self._centered:
            new_state["mean_grad"] = mg
        return (p.astype(jnp.float32) - mom).astype(p.dtype), new_state


class Lamb(Optimizer):
    """Layer-wise adaptive moments for large batch (reference:
    operators/optimizers/lamb_op.*)."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 name=None):
        self._beta1 = beta1
        self._beta2 = beta2
        self._eps = epsilon
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn
        super().__init__(learning_rate, parameters, None, grad_clip)
        self._wd_flags = {
            id(p): 0.0 if (exclude_from_weight_decay_fn is not None and
                           exclude_from_weight_decay_fn(p)) else 1.0
            for p in self._parameter_list}

    def _create_state(self, p):
        return {"m": jnp.zeros_like(p._value),
                "v": jnp.zeros_like(p._value),
                "beta1_pow": jnp.ones((), jnp.float32),
                "beta2_pow": jnp.ones((), jnp.float32),
                "wd": jnp.asarray(self._wd_flags.get(id(p), 1.0) *
                                  self._lamb_wd, jnp.float32)}

    def _update(self, p, g, lr, state):
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        m = self._beta1 * state["m"] + (1 - self._beta1) * g32
        v = self._beta2 * state["v"] + (1 - self._beta2) * g32 * g32
        b1p = state["beta1_pow"] * self._beta1
        b2p = state["beta2_pow"] * self._beta2
        m_hat = m / (1 - b1p)
        v_hat = v / (1 - b2p)
        r = m_hat / (jnp.sqrt(v_hat) + self._eps) + state["wd"] * p32
        w_norm = jnp.sqrt(jnp.sum(p32 * p32))
        r_norm = jnp.sqrt(jnp.sum(r * r))
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return ((p32 - lr * trust * r).astype(p.dtype),
                {"m": m, "v": v, "beta1_pow": b1p, "beta2_pow": b2p,
                 "wd": state["wd"]})


class Lars(Optimizer):
    """Layer-wise adaptive rate scaling (reference:
    operators/optimizers/lars_momentum_op.*)."""

    def __init__(self, learning_rate, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, parameters=None, grad_clip=None,
                 exclude_from_weight_decay=None, epsilon=0, name=None):
        self._momentum = momentum
        self._coeff = lars_coeff
        self._wd = lars_weight_decay
        self._eps = epsilon
        super().__init__(learning_rate, parameters, None, grad_clip)

    def _create_state(self, p):
        return {"velocity": jnp.zeros_like(p._value)}

    def _update(self, p, g, lr, state):
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        w_norm = jnp.sqrt(jnp.sum(p32 * p32))
        g_norm = jnp.sqrt(jnp.sum(g32 * g32))
        local_lr = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            self._coeff * w_norm / (g_norm + self._wd * w_norm + self._eps),
            1.0)
        v = (self._momentum * state["velocity"] +
             lr * local_lr * (g32 + self._wd * p32))
        return (p32 - v).astype(p.dtype), {"velocity": v}
