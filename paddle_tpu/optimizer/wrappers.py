"""Optimizer wrappers: Lookahead and ModelAverage.

Parity targets (SURVEY §2.5 "optimizers (py)"): the reference ships both
as v1 optimizer wrappers — LookaheadOptimizer (fluid/optimizer.py, slow/
fast weights with k-step interpolation) and ModelAverage
(fluid/optimizer.py, accumulating parameter averages applied during eval
via an apply()/restore() scope). Here both wrap any paddle_tpu Optimizer
and operate on the eager parameter tensors directly — the update math
stays in jax (single fused device computation per application).
"""
from __future__ import annotations

import contextlib
from typing import Dict, List, Optional

import jax.numpy as jnp

from ..framework.core import Tensor, no_grad

__all__ = ["LookaheadOptimizer", "ModelAverage"]


class LookaheadOptimizer:
    """Lookahead (k steps forward, 1 step back; Zhang et al. 2019).

    ``inner_optimizer`` advances the fast weights every step; every ``k``
    steps the slow weights move ``alpha`` of the way toward the fast ones
    and the fast weights are reset to the slow weights (parity:
    fluid/optimizer.py LookaheadOptimizer).
    """

    def __init__(self, inner_optimizer, alpha: float = 0.5, k: int = 5):
        if not 0.0 <= alpha <= 1.0:
            raise ValueError("alpha must be in [0, 1]")
        if k < 1:
            raise ValueError("k must be a positive integer")
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._step_count = 0
        self._parameter_list = list(inner_optimizer._parameter_list)
        # slow weights start at the INITIAL parameters (before any inner
        # step), as in the paper / reference
        self._slow: List[jnp.ndarray] = [p._value
                                         for p in self._parameter_list]

    def step(self):
        self.inner_optimizer.step()
        self._step_count += 1
        if self._step_count % self.k == 0:
            with no_grad():
                for i, p in enumerate(self._parameter_list):
                    slow = self._slow[i] + self.alpha * (p._value
                                                         - self._slow[i])
                    self._slow[i] = slow
                    p._value = slow

    def clear_grad(self):
        self.inner_optimizer.clear_grad()

    clear_gradients = clear_grad

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def state_dict(self):
        return {"inner": self.inner_optimizer.state_dict(),
                "step_count": self._step_count,
                "slow": [np_asarray(s) for s in self._slow]}

    def set_state_dict(self, sd):
        self.inner_optimizer.set_state_dict(sd["inner"])
        self._step_count = int(sd.get("step_count", 0))
        if "slow" in sd:
            self._slow = [jnp.asarray(s) for s in sd["slow"]]

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()
        self.clear_grad()


def np_asarray(x):
    import numpy as np
    return np.asarray(x)


class ModelAverage:
    """Running average of parameters for evaluation (parity:
    fluid/optimizer.py ModelAverage — accumulate each step, swap the
    averaged weights in under ``apply()`` and swap back with
    ``restore()``).

    The window grows with training up to ``max_average_window`` (the
    reference's average_window_rate/min/max mechanics collapse to a
    moving window over the last N accumulated steps).
    """

    def __init__(self, average_window_rate: float = 0.15,
                 parameters=None, min_average_window: int = 10000,
                 max_average_window: int = 10000 * 10):
        if parameters is None:
            raise ValueError("parameters is required")
        self._params = list(parameters)
        self._rate = average_window_rate
        self._min_w = int(min_average_window)
        self._max_w = int(max_average_window)
        self._sum = [jnp.zeros_like(p._value) for p in self._params]
        self._denom = 0.0  # accumulated with the SAME decays as _sum
        self._count = 0
        self._saved: Optional[List[jnp.ndarray]] = None

    def _window(self) -> int:
        return max(self._min_w,
                   min(self._max_w, int(self._count * self._rate) or 1))

    def step(self):
        """Accumulate the current parameter values (call after
        optimizer.step())."""
        with no_grad():
            decay = 1.0 - 1.0 / self._window()  # moving window as EMA
            for i, p in enumerate(self._params):
                self._sum[i] = self._sum[i] * decay + p._value
            # the denominator must see the exact same decay sequence as
            # the sum — a closed-form geometric series would assume one
            # constant decay and bias the average while the window grows
            self._denom = self._denom * decay + 1.0
            self._count += 1

    def _average(self, i):
        return self._sum[i] / (self._denom or 1.0)

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore: bool = True):
        """Swap averaged weights in (context manager, like the
        reference's scope-based apply)."""
        if self._count == 0:
            yield
            return
        self._saved = [p._value for p in self._params]
        with no_grad():
            for i, p in enumerate(self._params):
                p._value = self._average(i)
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self, executor=None):
        if self._saved is not None:
            for p, v in zip(self._params, self._saved):
                p._value = v
            self._saved = None
