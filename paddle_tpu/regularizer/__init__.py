"""Weight-decay regularizers (parity: python/paddle/regularizer.py —
L1Decay/L2Decay appended to gradients before the update op)."""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["L1Decay", "L2Decay"]


class WeightDecayRegularizer:
    def apply_gradient(self, p, g):
        raise NotImplementedError


class L1Decay(WeightDecayRegularizer):
    def __init__(self, coeff=0.0):
        self.coeff = coeff

    def apply_gradient(self, p, g):
        return g + self.coeff * jnp.sign(p).astype(g.dtype)


class L2Decay(WeightDecayRegularizer):
    def __init__(self, coeff=0.0):
        self.coeff = coeff

    def apply_gradient(self, p, g):
        return g + self.coeff * p.astype(g.dtype)
