"""paddle_tpu.autograd (parity: python/paddle/autograd/ — backward, grad,
PyLayer custom-op autograd; reference C++ engine imperative/basic_engine.cc)."""
from __future__ import annotations

from typing import Any, List

import jax.numpy as jnp

from ..framework.core import (GradNode, Tensor, enable_grad, grad,  # noqa: F401
                              is_grad_enabled, no_grad, run_backward,
                              set_grad_enabled)

__all__ = ["backward", "grad", "no_grad", "enable_grad", "is_grad_enabled",
           "set_grad_enabled", "PyLayer", "PyLayerContext"]


def backward(tensors, grad_tensors=None, retain_graph=False):
    """paddle.autograd.backward over multiple roots."""
    tensors = tensors if isinstance(tensors, (list, tuple)) else [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    for t, g in zip(tensors, grad_tensors):
        run_backward(t, g, retain_graph=retain_graph)


class PyLayerContext:
    """ctx object passed to PyLayer.forward/backward (parity:
    python/paddle/autograd/py_layer.py)."""

    def __init__(self):
        self._saved: List[Tensor] = []
        self.not_inplace_tensors = ()

    def save_for_backward(self, *tensors):
        self._saved = list(tensors)

    def saved_tensor(self):
        return list(self._saved)

    saved_tensors = saved_tensor


class PyLayerMeta(type):
    def __init__(cls, name, bases, attrs):
        super().__init__(name, bases, attrs)


class PyLayer(metaclass=PyLayerMeta):
    """User-defined forward/backward pair recorded on the eager tape.

    class Tanh(PyLayer):
        @staticmethod
        def forward(ctx, x):
            y = paddle.tanh(x)
            ctx.save_for_backward(y)
            return y
        @staticmethod
        def backward(ctx, dy):
            (y,) = ctx.saved_tensor()
            return dy * (1 - y * y)
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        with no_grad():
            out = cls.forward(ctx, *args, **kwargs)
        multi = isinstance(out, (tuple, list))
        outs = list(out) if multi else [out]

        tensor_inputs = [a for a in args if isinstance(a, Tensor)]
        needs = is_grad_enabled() and any(
            not t.stop_gradient for t in tensor_inputs)
        if not needs:
            return out

        def vjp_fn(cotangents):
            cots = cotangents if isinstance(cotangents, tuple) else (cotangents,)
            gts = [Tensor(c) for c in cots]
            with no_grad():
                gin = cls.backward(ctx, *gts)
            gin = gin if isinstance(gin, (tuple, list)) else [gin]
            vals = []
            for g in gin:
                vals.append(g._value if isinstance(g, Tensor) else g)
            return tuple(vals)

        node = GradNode(vjp_fn, tensor_inputs,
                        [(o._value.shape, o._value.dtype) for o in outs],
                        name=cls.__name__)
        for i, o in enumerate(outs):
            o._node = node
            o._out_idx = i
            o.stop_gradient = False
        return out if multi else outs[0]


class PyLayerBackward:  # compat alias used by some scripts
    pass
