"""paddle_tpu.distribution — probability distributions.

Parity target: reference python/paddle/distribution.py (v2.0 ships
Distribution base + Normal, Uniform, Categorical with sample/entropy/
log_prob/probs/kl_divergence). TPU-native: sampling draws from the global
PRNG-key stream (framework/random.py) instead of stateful cuRAND, and all
math is jax — so the same code traces under jit and differentiates.
"""
from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .framework.core import Tensor, _apply, to_tensor
from .framework.random import split_key

__all__ = ["Distribution", "Normal", "Uniform", "Categorical",
           "kl_divergence"]


def _v(x):
    if isinstance(x, Tensor):
        return x._value
    return jnp.asarray(x, jnp.float32) if not isinstance(
        x, (jnp.ndarray, jax.Array)) else x


def _t(x):
    """Wrap as Tensor PRESERVING autograd identity — a distribution built
    on a trainable parameter must backprop into it (the reference's
    Normal(loc=variable) does)."""
    return x if isinstance(x, Tensor) else to_tensor(_v(x))


def _sample_key(seed: int):
    """paddle sample(seed=...) semantics: seed=0 means draw from the
    global stream; a nonzero seed gives a reproducible standalone draw."""
    if seed:
        from .framework.random import make_key
        return make_key(seed)
    return split_key(1)


class Distribution:
    """Base class (parity: paddle.distribution.Distribution)."""

    def sample(self, shape: Sequence[int] = ()):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def probs(self, value):
        return _apply(jnp.exp, self.log_prob(value), op_name="exp")

    def kl_divergence(self, other: "Distribution"):
        raise NotImplementedError


class Normal(Distribution):
    """N(loc, scale) (parity: paddle.distribution.Normal — sample,
    entropy, log_prob, kl_divergence; reference distribution.py)."""

    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)

    def sample(self, shape: Sequence[int] = (), seed: int = 0):
        key = _sample_key(seed)
        shp = tuple(shape) + tuple(np.broadcast_shapes(
            self.loc.shape, self.scale.shape))

        def fn(loc, scale):
            eps = jax.random.normal(key, shp, dtype=jnp.float32)
            return loc + scale * eps

        return _apply(fn, self.loc, self.scale, op_name="normal_sample")

    def entropy(self):
        def fn(scale):
            return 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(scale)
        return _apply(fn, self.scale, op_name="normal_entropy")

    def log_prob(self, value):
        value = _t(value)  # preserve autograd through the evaluated point
        # (reparameterized samples need d(log_prob)/d(value))

        def fn(v, loc, scale):
            var = scale * scale
            return (-((v - loc) ** 2) / (2 * var)
                    - jnp.log(scale) - 0.5 * math.log(2 * math.pi))

        return _apply(fn, value, self.loc, self.scale,
                      op_name="normal_log_prob")

    def kl_divergence(self, other: "Normal"):
        def fn(l1, s1, l2, s2):
            var_ratio = (s1 / s2) ** 2
            t1 = ((l1 - l2) / s2) ** 2
            return 0.5 * (var_ratio + t1 - 1.0 - jnp.log(var_ratio))

        return _apply(fn, self.loc, self.scale, other.loc, other.scale,
                      op_name="normal_kl")


class Uniform(Distribution):
    """U[low, high) (parity: paddle.distribution.Uniform)."""

    def __init__(self, low, high, name=None):
        self.low = _t(low)
        self.high = _t(high)

    def sample(self, shape: Sequence[int] = (), seed: int = 0):
        key = _sample_key(seed)
        shp = tuple(shape) + tuple(np.broadcast_shapes(
            self.low.shape, self.high.shape))

        def fn(lo, hi):
            u = jax.random.uniform(key, shp, dtype=jnp.float32)
            return lo + (hi - lo) * u

        return _apply(fn, self.low, self.high, op_name="uniform_sample")

    def entropy(self):
        def fn(lo, hi):
            return jnp.log(hi - lo)
        return _apply(fn, self.low, self.high, op_name="uniform_entropy")

    def log_prob(self, value):
        value = _t(value)

        def fn(v, lo, hi):
            inside = (v >= lo) & (v < hi)
            lp = -jnp.log(hi - lo)
            return jnp.where(inside, lp, -jnp.inf)

        return _apply(fn, value, self.low, self.high,
                      op_name="uniform_log_prob")


class Categorical(Distribution):
    """Categorical over unnormalized logits (parity:
    paddle.distribution.Categorical — sample, entropy, kl_divergence,
    probs)."""

    def __init__(self, logits, name=None):
        self.logits = _t(logits)

    def _log_pmf(self):
        def fn(lg):
            return lg - jax.scipy.special.logsumexp(lg, axis=-1,
                                                    keepdims=True)
        return _apply(fn, self.logits, op_name="categorical_log_pmf")

    def sample(self, shape: Sequence[int] = ()):
        key = split_key(1)

        def fn(lg):
            return jax.random.categorical(key, lg, axis=-1,
                                          shape=tuple(shape) + lg.shape[:-1])

        out = _apply(fn, self.logits, op_name="categorical_sample")
        out.stop_gradient = True
        return out

    def entropy(self):
        def fn(lg):
            logp = lg - jax.scipy.special.logsumexp(lg, axis=-1,
                                                    keepdims=True)
            return -(jnp.exp(logp) * logp).sum(-1)
        return _apply(fn, self.logits, op_name="categorical_entropy")

    def log_prob(self, value):
        value = to_tensor(value)
        logp = self._log_pmf()

        def fn(lp, idx):
            idx = idx.astype(jnp.int32)
            if lp.ndim == 1:
                # 1-D logits: value is a list of category indices
                return lp[idx]
            return jnp.take_along_axis(lp, idx[..., None], axis=-1)[..., 0]

        return _apply(fn, logp, value, op_name="categorical_log_prob")

    def probs(self, value=None):
        def fn(lg):
            return jax.nn.softmax(lg, axis=-1)
        p = _apply(fn, self.logits, op_name="categorical_probs")
        if value is None:
            return p

        def pick(pv, idx):
            idx = idx.astype(jnp.int32)
            if pv.ndim == 1:
                return pv[idx]
            return jnp.take_along_axis(pv, idx[..., None], axis=-1)[..., 0]
        return _apply(pick, p, to_tensor(value), op_name="categorical_pick")

    def kl_divergence(self, other: "Categorical"):
        def fn(a, b):
            la = a - jax.scipy.special.logsumexp(a, -1, keepdims=True)
            lb = b - jax.scipy.special.logsumexp(b, -1, keepdims=True)
            return (jnp.exp(la) * (la - lb)).sum(-1)
        return _apply(fn, self.logits, other.logits,
                      op_name="categorical_kl")


def kl_divergence(p: Distribution, q: Distribution):
    """paddle.distribution.kl_divergence dispatch."""
    return p.kl_divergence(q)


class MultivariateNormalDiag(Distribution):
    """Diagonal-covariance multivariate normal (reference
    fluid/layers/distributions.py:531 — loc [..., D] mean and scale
    [..., D] diagonal standard deviations)."""

    def __init__(self, loc, scale):
        self.loc = _t(loc)
        self.scale = _t(scale)

    def sample(self, shape=(), seed=0):
        key = _sample_key(seed)
        shape = tuple(shape)

        def fn(m, s):
            eps = jax.random.normal(key, shape + m.shape, m.dtype)
            return m + s * eps
        return _apply(fn, self.loc, self.scale, op_name="mvn_sample")

    def log_prob(self, value):
        def fn(m, s, v):
            z = (v - m) / s
            return (-0.5 * (z * z).sum(-1)
                    - jnp.log(s).sum(-1)
                    - 0.5 * m.shape[-1] * jnp.log(2 * jnp.pi))
        return _apply(fn, self.loc, self.scale, _t(value),
                      op_name="mvn_log_prob")

    def entropy(self):
        def fn(m, s):
            d = m.shape[-1]
            return 0.5 * d * (1.0 + jnp.log(2 * jnp.pi)) \
                + jnp.log(s).sum(-1)
        return _apply(fn, self.loc, self.scale, op_name="mvn_entropy")

    def kl_divergence(self, other: "MultivariateNormalDiag"):
        def fn(m0, s0, m1, s1):
            v0, v1 = s0 * s0, s1 * s1
            return 0.5 * ((v0 / v1).sum(-1)
                          + (((m1 - m0) ** 2) / v1).sum(-1)
                          - m0.shape[-1]
                          + jnp.log(v1).sum(-1) - jnp.log(v0).sum(-1))
        return _apply(fn, self.loc, self.scale, other.loc, other.scale,
                      op_name="mvn_kl")


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="int64"):
    """Sample one index per row from row-probability matrix ``x``
    (reference fluid/layers/nn.py:10673, operators/sampling_id_op.cc:
    u ~ U(min, max) compared directly against the row cumsum, result
    clamped to the last index)."""
    key = _sample_key(seed)

    def fn(p):
        c = jnp.cumsum(p, axis=-1)
        u = jax.random.uniform(key, p.shape[:-1] + (1,), p.dtype,
                               minval=min, maxval=max)
        idx = (u > c).sum(-1)
        return jnp.clip(idx, 0, p.shape[-1] - 1).astype(dtype)
    return _apply(fn, _t(x), op_name="sampling_id")


__all__ += ["MultivariateNormalDiag", "sampling_id"]
