"""GraftLint pillar 1 — the jaxpr program auditor (ISSUE 6 tentpole).

The reference frames its graph layer around analyzability: ~104 IR
passes over the Program graph (``framework/ir/pass.h``).  The TPU-native
analog keeps a thin jaxpr-level pass layer: any jittable step (or a
loaded :class:`~paddle_tpu.inference.Predictor`) is traced to its
ClosedJaxpr and walked by a fixed set of audit rules that prove the
properties a human reviewer otherwise has to eyeball per PR:

``jaxpr.undonated-buffer``  (error)
    a large input leaf whose (shape, dtype) matches an output but is not
    donated — params/opt-state round-tripped without ``donate_argnums``
    hold both copies live and double peak HBM on a real chip.
``jaxpr.dtype-widen-state`` (error)
    a low-precision (bf16/f16) input leaf comes back as a WIDER float of
    the same shape — silent state upcast creep (the 2x-HBM failure mode
    of a moment_dtype knob quietly ignored).
``jaxpr.dtype-f64``         (error)
    an equation first *produces* float64 from non-f64 inputs (or an f64
    leaf enters the program) — f64 creep runs at 1/8th MXU rate and
    doubles every downstream buffer.
``jaxpr.host-callback``     (error)
    a host callback primitive (pure_callback / io_callback / ...)
    inside the compiled step — every host sync must route through the
    train_guard ``_host_fetch`` funnel *outside* the program.
``jaxpr.large-const``       (warning)
    a large closed-over constant baked into the program — it is
    re-uploaded with every executable and invisible to checkpointing.

Beyond findings, the report carries a **collective inventory** (count +
bytes of psum / all_gather / ppermute / ... at the jaxpr level, plus the
post-SPMD HLO instruction counts when a compiled text is available), a
**kernel inventory** (ISSUE 13: pallas/Mosaic custom calls classified
as device kernels — never host callbacks — by name and count, with the
compiled ``tpu_custom_call`` targets mirrored from HLO) and
a per-input **donation table** — the observable surface
``DistributedTrainStep.audit()`` / ``Predictor.audit()`` expose and the
auto-sharding planner (ROADMAP item 4) will reuse for memory and
collective predictions.
"""
from __future__ import annotations

import re
from collections import Counter
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .findings import SEV_ERROR, SEV_WARNING, Finding

__all__ = ["AuditReport", "audit_fn", "audit_traced", "audit_jaxpr",
           "collective_inventory", "hlo_collective_inventory",
           "kernel_inventory", "hlo_kernel_inventory",
           "COLLECTIVE_PRIMS", "CALLBACK_PRIMS", "KERNEL_PRIMS"]

# jaxpr-level collective primitives (psum lowers as psum2 on jax 0.4.x)
COLLECTIVE_PRIMS = {
    "psum": "psum", "psum2": "psum", "pmax": "pmax", "pmin": "pmin",
    "all_gather": "all_gather", "all_to_all": "all_to_all",
    "ppermute": "ppermute", "pgather": "pgather",
    "reduce_scatter": "reduce_scatter", "psum_scatter": "reduce_scatter",
}

# host-callback primitives: anything here inside a step program is a
# per-step host round trip through the PJRT tunnel
CALLBACK_PRIMS = {"pure_callback", "io_callback", "callback",
                  "outside_call", "host_callback_call"}
DEBUG_PRIMS = {"debug_callback", "debug_print"}

# device-kernel primitives (ISSUE 13): pallas custom calls are KERNELS
# — device code behind a custom-call boundary, NOT host callbacks.
# They land in the report's kernel inventory (name + count) so a step
# program's custom-call surface is auditable; they must never trip the
# jaxpr.host-callback rule.
KERNEL_PRIMS = {"pallas_call", "tpu_custom_call", "mosaic"}
# post-SPMD HLO: what a compiled pallas call looks like on TPU
_HLO_KERNEL_TARGETS = ("tpu_custom_call", "mosaic", "__gpu$xla.gpu")

# post-SPMD HLO collective instructions (what XLA actually emits once
# shardings partition the program — jaxpr psums may be absent entirely
# for jit-with-shardings programs)
_HLO_COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter",
                    "collective-permute", "all-to-all",
                    "collective-broadcast")
_HLO_SHAPE_RE = re.compile(r"([a-z]+[0-9]+)\[([0-9,]*)\]")
_HLO_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16,
}

_FLOAT_WIDTH = {"bfloat16": 2, "float16": 2, "float32": 4, "float64": 8}


def _aval_nbytes(aval) -> int:
    try:
        return int(np.prod(aval.shape)) * int(
            np.dtype(aval.dtype).itemsize)
    except Exception:       # extended dtypes (PRNG keys): size unknowable
        return 0


def _dtype_str(aval) -> str:
    try:
        return str(np.dtype(aval.dtype))
    except Exception:
        return str(getattr(aval, "dtype", "?"))


def _sig(aval) -> Tuple[Tuple[int, ...], str]:
    return (tuple(getattr(aval, "shape", ())), _dtype_str(aval))


def _iter_jaxprs(obj):
    """Yield every Jaxpr reachable from ``obj`` (an eqn params value):
    ClosedJaxpr / Jaxpr / containers thereof — covers pjit, scan, cond
    branches, shard_map, custom_jvp/vjp and future wrapper primitives
    without naming them."""
    if obj is None:
        return
    if hasattr(obj, "jaxpr") and hasattr(obj, "consts"):   # ClosedJaxpr
        yield obj.jaxpr
        return
    if hasattr(obj, "eqns") and hasattr(obj, "invars"):    # Jaxpr
        yield obj
        return
    if isinstance(obj, (list, tuple)):
        for o in obj:
            yield from _iter_jaxprs(o)


def iter_eqns(jaxpr):
    """Every equation in ``jaxpr``, recursing into sub-jaxprs."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in _iter_jaxprs(v):
                yield from iter_eqns(sub)


@dataclass
class AuditReport:
    """The audit result for one traced program."""

    program: str
    findings: List[Finding] = field(default_factory=list)
    collectives: Dict[str, Dict[str, int]] = field(default_factory=dict)
    hlo_collectives: Optional[Dict[str, Dict[str, int]]] = None
    donation: List[Dict] = field(default_factory=list)
    widening_casts: int = 0
    # ISSUE 13: pallas/Mosaic custom calls classified as device
    # KERNELS — {kernel_name: count}; hlo_kernels mirrors the compiled
    # custom-call targets when HLO text was audited
    kernels: Dict[str, int] = field(default_factory=dict)
    hlo_kernels: Optional[Dict[str, int]] = None

    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == SEV_ERROR]

    def ok(self) -> bool:
        return not self.errors()

    def collective_count(self, kind: Optional[str] = None) -> int:
        """Collective ops in the program.  When compiled HLO text was
        audited, the post-SPMD instruction counts are the ground truth
        (jit-with-shardings programs carry no jaxpr collectives at
        all); otherwise the jaxpr primitive counts are used.  ``kind``
        filters to one family (``"psum"`` maps to HLO ``all-reduce``,
        etc.)."""
        alias = {"psum": "all-reduce", "all_gather": "all-gather",
                 "reduce_scatter": "reduce-scatter",
                 "ppermute": "collective-permute",
                 "all_to_all": "all-to-all"}
        if self.hlo_collectives is not None:
            return sum(v["count"]
                       for k, v in self.hlo_collectives.items()
                       if kind is None or alias.get(kind, kind) == k)
        return sum(v["count"] for k, v in self.collectives.items()
                   if kind is None or k == kind)

    def donated_fraction(self) -> float:
        tot = sum(d["bytes"] for d in self.donation)
        don = sum(d["bytes"] for d in self.donation if d["donated"])
        return (don / tot) if tot else 1.0

    def summary(self) -> str:
        lines = [f"audit[{self.program}]: "
                 f"{len(self.errors())} error(s), "
                 f"{len(self.findings) - len(self.errors())} other "
                 f"finding(s), donated {self.donated_fraction():.0%} "
                 f"of {sum(d['bytes'] for d in self.donation)} input "
                 f"bytes, {self.widening_casts} widening cast(s)"]
        inv = dict(self.collectives)
        if self.hlo_collectives:
            inv.update({f"hlo:{k}": v
                        for k, v in self.hlo_collectives.items()})
        if inv:
            lines.append("  collectives: " + ", ".join(
                f"{k} x{v['count']} ({v['bytes']}B)"
                for k, v in sorted(inv.items())))
        kinv = dict(self.kernels)
        if self.hlo_kernels:
            kinv.update({f"hlo:{k}": v
                         for k, v in self.hlo_kernels.items()})
        if kinv:
            lines.append("  kernels: " + ", ".join(
                f"{k} x{v}" for k, v in sorted(kinv.items())))
        for f in self.findings:
            lines.append("  " + f.format())
        return "\n".join(lines)

    def asdict(self) -> Dict:
        return {"program": self.program,
                "findings": [f.asdict() for f in self.findings],
                "collectives": self.collectives,
                "hlo_collectives": self.hlo_collectives,
                "donation": self.donation,
                "widening_casts": self.widening_casts,
                "kernels": self.kernels,
                "hlo_kernels": self.hlo_kernels}


def collective_inventory(closed_jaxpr) -> Dict[str, Dict[str, int]]:
    """Count + output bytes of every collective primitive in the jaxpr
    (recursively — shard_map bodies are where they live)."""
    inv: Dict[str, Dict[str, int]] = {}
    for eqn in iter_eqns(closed_jaxpr.jaxpr):
        fam = COLLECTIVE_PRIMS.get(eqn.primitive.name)
        if fam is None:
            continue
        d = inv.setdefault(fam, {"count": 0, "bytes": 0})
        d["count"] += 1
        d["bytes"] += sum(_aval_nbytes(v.aval) for v in eqn.outvars)
    return inv


def _kernel_name(eqn) -> str:
    """Best-effort kernel name for a pallas/Mosaic custom call: the
    pallas_call's NameAndSrcInfo carries the kernel function name."""
    nsi = eqn.params.get("name_and_src_info")
    nm = getattr(nsi, "name", None)
    if nm:
        return str(nm)
    nm = eqn.params.get("name")
    return str(nm) if nm else eqn.primitive.name


def kernel_inventory(closed_jaxpr) -> Dict[str, int]:
    """Count device-kernel custom calls (pallas_call etc.) per kernel
    name — the ISSUE 13 classification: kernels, not host callbacks."""
    inv: Dict[str, int] = {}
    for eqn in iter_eqns(closed_jaxpr.jaxpr):
        if eqn.primitive.name in KERNEL_PRIMS:
            nm = _kernel_name(eqn)
            inv[nm] = inv.get(nm, 0) + 1
    return inv


def hlo_kernel_inventory(hlo_text: str) -> Dict[str, int]:
    """Count compiled custom-call instructions whose target is a known
    device-kernel entry point (``tpu_custom_call`` is what a pallas
    kernel lowers to on TPU)."""
    inv: Dict[str, int] = {}
    for line in hlo_text.splitlines():
        if "custom-call" not in line:
            continue
        m = re.search(r'custom_call_target="([^"]+)"', line)
        if not m:
            continue
        tgt = m.group(1)
        if any(t in tgt for t in _HLO_KERNEL_TARGETS):
            inv[tgt] = inv.get(tgt, 0) + 1
    return inv


def hlo_collective_inventory(hlo_text: str) -> Dict[str, Dict[str, int]]:
    """Count + bytes of collective instructions in compiled HLO text —
    the post-SPMD ground truth for jit-with-shardings programs, where
    the jaxpr carries no explicit collectives at all."""
    inv: Dict[str, Dict[str, int]] = {}
    for line in hlo_text.splitlines():
        for op in _HLO_COLLECTIVES:
            marker = f" {op}("
            idx = line.find(marker)
            if idx < 0 or "=" not in line[:idx]:
                continue
            # result type sits between '=' and the op name:
            #   %x = f32[128,256]{1,0} all-reduce(...)
            typ = line[line.index("=") + 1:idx]
            nbytes = 0
            for dt, dims in _HLO_SHAPE_RE.findall(typ):
                n = 1
                for d in dims.split(","):
                    if d:
                        n *= int(d)
                nbytes += n * _HLO_DTYPE_BYTES.get(dt, 4)
            d = inv.setdefault(op, {"count": 0, "bytes": 0})
            d["count"] += 1
            d["bytes"] += nbytes
            break
    return inv


def audit_jaxpr(closed_jaxpr, *, program: str = "program",
                in_names: Optional[Sequence[str]] = None,
                donated: Optional[Sequence[bool]] = None,
                check_donation: bool = True,
                min_donate_bytes: int = 1 << 20,
                min_state_bytes: int = 256,
                min_const_bytes: int = 64 * 1024) -> AuditReport:
    """Run every audit rule over one ClosedJaxpr.

    ``in_names``/``donated`` align with the jaxpr's flat ``in_avals``;
    missing entries default to ``arg[i]`` / not-donated.
    """
    in_avals = list(closed_jaxpr.in_avals)
    out_avals = list(closed_jaxpr.out_avals)
    names = list(in_names or [])
    names += [f"arg[{i}]" for i in range(len(names), len(in_avals))]
    don = list(donated or [])
    don += [False] * (len(in_avals) - len(don))
    rep = AuditReport(program=program)

    # donation table (reported even when the rule is off)
    for i, aval in enumerate(in_avals):
        rep.donation.append({"input": names[i], "donated": bool(don[i]),
                             "bytes": _aval_nbytes(aval),
                             "shape": list(getattr(aval, "shape", ())),
                             "dtype": _dtype_str(aval)})

    # rule: undonated-buffer -------------------------------------------
    if check_donation:
        outs_by_sig = Counter(_sig(a) for a in out_avals)
        donated_by_sig: Counter = Counter()
        for i, aval in enumerate(in_avals):
            if don[i]:
                donated_by_sig[_sig(aval)] += 1
        for i, aval in enumerate(in_avals):
            if don[i] or _aval_nbytes(aval) < min_donate_bytes:
                continue
            s = _sig(aval)
            if outs_by_sig[s] > donated_by_sig[s]:
                donated_by_sig[s] += 1   # one output slot consumed
                rep.findings.append(Finding(
                    SEV_ERROR, "jaxpr.undonated-buffer",
                    f"{program}::{names[i]}",
                    f"input {names[i]} ({_dtype_str(aval)}"
                    f"{list(aval.shape)}, {_aval_nbytes(aval)} bytes) "
                    "aliases an output of the same shape/dtype but is "
                    "not donated — both copies stay live and peak HBM "
                    "doubles; add it to donate_argnums",
                    data={"bytes": _aval_nbytes(aval)}))

    # rule: dtype-widen-state ------------------------------------------
    out_float_by_shape: Dict[Tuple[int, ...], set] = {}
    for a in out_avals:
        w = _FLOAT_WIDTH.get(_dtype_str(a))
        if w:
            out_float_by_shape.setdefault(
                tuple(a.shape), set()).add(_dtype_str(a))
    for i, aval in enumerate(in_avals):
        dt = _dtype_str(aval)
        w = _FLOAT_WIDTH.get(dt)
        if not w or w >= 4 or _aval_nbytes(aval) < min_state_bytes:
            continue
        wider = sorted(d for d in out_float_by_shape.get(
            tuple(aval.shape), ()) if _FLOAT_WIDTH[d] > w)
        same = [d for d in out_float_by_shape.get(tuple(aval.shape), ())
                if _FLOAT_WIDTH[d] <= w]
        if wider and not same:
            rep.findings.append(Finding(
                SEV_ERROR, "jaxpr.dtype-widen-state",
                f"{program}::{names[i]}",
                f"{dt} input {names[i]} {list(aval.shape)} only comes "
                f"back as {'/'.join(wider)} — state silently widened "
                "(low-precision storage lost on the round trip)"))

    # rules over equations ---------------------------------------------
    for eqn in iter_eqns(closed_jaxpr.jaxpr):
        prim = eqn.primitive.name
        if prim in KERNEL_PRIMS:
            # a pallas custom call is a DEVICE kernel: inventoried,
            # never flagged as a host callback (its inner jaxpr is
            # still recursed for the other rules)
            nm = _kernel_name(eqn)
            rep.kernels[nm] = rep.kernels.get(nm, 0) + 1
            continue
        if prim in CALLBACK_PRIMS or prim in DEBUG_PRIMS:
            sev = SEV_ERROR if prim in CALLBACK_PRIMS else SEV_WARNING
            cb = eqn.params.get("callback")
            cb_s = "" if cb is None else f" ({str(cb)[:60]})"
            rep.findings.append(Finding(
                sev, "jaxpr.host-callback",
                f"{program}::{prim}",
                f"host callback primitive {prim!r}" + cb_s
                + " inside the compiled program — a host round trip "
                "per step; route host work through the train_guard "
                "_host_fetch funnel outside the step"))
            continue
        if prim == "convert_element_type":
            new = eqn.params.get("new_dtype")
            old = getattr(getattr(eqn.invars[0], "aval", None),
                          "dtype", None)
            try:
                # NB: ml_dtypes bfloat16 is NOT numpy kind 'f' — width
                # comes from the explicit float table, not dtype.kind
                wn = _FLOAT_WIDTH.get(str(np.dtype(new))) if new is not \
                    None else None
                wo = _FLOAT_WIDTH.get(str(np.dtype(old))) if old is not \
                    None else None
                if wn and wo and wn > wo:
                    rep.widening_casts += 1
            except TypeError:
                pass
        # f64 creep: flag the eqn that first PRODUCES f64 from narrower
        # inputs (downstream f64-consuming eqns are fallout, not cause)
        for ov in eqn.outvars:
            if _dtype_str(ov.aval) == "float64" and not any(
                    _dtype_str(getattr(iv, "aval", None)) == "float64"
                    for iv in eqn.invars if hasattr(iv, "aval")):
                rep.findings.append(Finding(
                    SEV_ERROR, "jaxpr.dtype-f64",
                    f"{program}::{prim}",
                    f"{prim} produces float64 "
                    f"{list(ov.aval.shape)} from non-f64 inputs — f64 "
                    "creep (1/8th MXU rate, 2x buffer bytes); cast "
                    "explicitly or fix the accidental promotion"))
                break
    for i, aval in enumerate(in_avals):
        if _dtype_str(aval) == "float64":
            rep.findings.append(Finding(
                SEV_ERROR, "jaxpr.dtype-f64",
                f"{program}::{names[i]}",
                f"input {names[i]} enters the program as float64"))

    # rule: large-const ------------------------------------------------
    for i, c in enumerate(closed_jaxpr.consts):
        nbytes = getattr(c, "nbytes", 0) or 0
        if nbytes >= min_const_bytes:
            rep.findings.append(Finding(
                SEV_WARNING, "jaxpr.large-const",
                f"{program}::const[{i}]",
                f"closed-over constant {_dtype_str(c)}"
                f"{list(np.shape(c))} ({nbytes} bytes) baked into the "
                "program — it bloats every serialized executable and "
                "bypasses checkpointing; pass it as an argument",
                data={"bytes": int(nbytes)}))

    rep.collectives = collective_inventory(closed_jaxpr)
    return rep


def _names_from_args_info(args_info, arg_names=None) -> List[str]:
    import jax
    flat = jax.tree_util.tree_flatten_with_path(args_info)[0]
    names = []
    for path, _ in flat:
        ks = jax.tree_util.keystr(path)
        # paths look like "[0][2][0]['m']": [0] = the args tuple,
        # next index = the positional arg — swap it for its name
        m = re.match(r"^\[0\]\[(\d+)\](.*)$", ks)
        if m and arg_names:
            i = int(m.group(1))
            nm = arg_names[i] if i < len(arg_names) else f"arg{i}"
            names.append(nm + m.group(2))
        else:
            names.append(ks)
    return names


def audit_traced(traced, *, program: str = "program",
                 arg_names: Optional[Sequence[str]] = None,
                 hlo_text: Optional[str] = None,
                 check_donation: bool = True, **thresholds) -> AuditReport:
    """Audit a ``jax.jit(...).trace(...)`` result: the jaxpr plus jax's
    own per-leaf donation flags (``args_info``)."""
    import jax
    flat_info = jax.tree_util.tree_leaves(traced.args_info)
    donated = [bool(getattr(a, "donated", False)) for a in flat_info]
    names = _names_from_args_info(traced.args_info, arg_names)
    rep = audit_jaxpr(traced.jaxpr, program=program, in_names=names,
                      donated=donated, check_donation=check_donation,
                      **thresholds)
    if hlo_text is not None:
        rep.hlo_collectives = hlo_collective_inventory(hlo_text)
        rep.hlo_kernels = hlo_kernel_inventory(hlo_text)
    return rep


def audit_fn(fn, args: Sequence, *, donate_argnums=(), program=None,
             arg_names: Optional[Sequence[str]] = None,
             include_hlo: bool = False, check_donation: bool = True,
             **thresholds) -> AuditReport:
    """Audit any jittable function against example args (arrays or
    ``jax.ShapeDtypeStruct`` avals — nothing is executed)."""
    import jax
    jitted = jax.jit(fn, donate_argnums=tuple(donate_argnums))
    traced = jitted.trace(*args)
    hlo = None
    if include_hlo:
        try:
            hlo = traced.lower().compile().as_text()
        except Exception:   # backend can't compile (e.g. TPU-only ops)
            hlo = None
    return audit_traced(
        traced, program=program or getattr(fn, "__name__", "program"),
        arg_names=arg_names, hlo_text=hlo,
        check_donation=check_donation, **thresholds)
