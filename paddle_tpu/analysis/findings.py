"""Shared report format for the GraftLint analyzers (ISSUE 6).

Both pillars — the jaxpr program auditor (:mod:`.jaxpr_audit`) and the
AST framework linter (:mod:`.ast_lint`) — emit :class:`Finding` records
so one CLI / one baseline file / one CI gate covers the whole static
analysis tier (the TPU-native analog of the reference's
``framework/ir/pass.h`` pass diagnostics).

A finding's :attr:`Finding.key` is its *stable identity* for baselining:
``rule|loc`` with ``loc`` deliberately line-number-free (file::scope or
program::input-path), so an unrelated edit that shifts lines never
invalidates the baseline, while moving/renaming the offending code does.

Baseline file (``tools/lint_baseline.json``)::

    {"version": 1,
     "entries": [{"key": "<rule>|<loc>", "reason": "<why accepted>"}]}

Every entry MUST carry a non-empty reason — the baseline records
*justified* findings, not a mute button.
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

__all__ = ["Finding", "SEV_ERROR", "SEV_WARNING", "SEV_INFO",
           "load_baseline", "apply_baseline", "baseline_entry",
           "format_findings"]

SEV_ERROR = "error"
SEV_WARNING = "warning"
SEV_INFO = "info"

_SEV_ORDER = {SEV_ERROR: 0, SEV_WARNING: 1, SEV_INFO: 2}


@dataclass
class Finding:
    """One analyzer diagnostic.

    ``loc`` is the stable location (``file::scope`` for lint findings,
    ``program::input-path`` for jaxpr findings); ``line`` is best-effort
    display detail and never part of the baseline identity.
    """

    severity: str
    rule: str
    loc: str
    detail: str
    line: Optional[int] = None
    data: Dict = field(default_factory=dict)

    @property
    def key(self) -> str:
        return f"{self.rule}|{self.loc}"

    def format(self) -> str:
        where = self.loc if self.line is None else f"{self.loc}:{self.line}"
        return f"[{self.severity}] {self.rule} @ {where}: {self.detail}"

    def asdict(self) -> Dict:
        d = {"severity": self.severity, "rule": self.rule,
             "loc": self.loc, "detail": self.detail, "key": self.key}
        if self.line is not None:
            d["line"] = self.line
        if self.data:
            d["data"] = self.data
        return d


def format_findings(findings: List[Finding]) -> str:
    ordered = sorted(findings,
                     key=lambda f: (_SEV_ORDER.get(f.severity, 9), f.key))
    return "\n".join(f.format() for f in ordered)


def baseline_entry(finding: Finding, reason: str) -> Dict:
    if not reason or not str(reason).strip():
        raise ValueError("a baseline entry needs a non-empty reason "
                         f"(finding {finding.key})")
    return {"key": finding.key, "reason": str(reason)}


def load_baseline(path: str) -> Dict[str, str]:
    """Read a baseline file -> {finding key: reason}.  A missing file is
    an empty baseline; a malformed file (or an entry without a reason)
    raises — a silently ignored baseline would un-gate CI."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except FileNotFoundError:
        return {}
    if not isinstance(doc, dict) or "entries" not in doc:
        raise ValueError(f"baseline {path}: expected "
                         '{"version": 1, "entries": [...]}')
    out: Dict[str, str] = {}
    for e in doc["entries"]:
        key, reason = e.get("key"), e.get("reason")
        if not key or not reason or not str(reason).strip():
            raise ValueError(
                f"baseline {path}: entry {e!r} needs both a key and a "
                "non-empty reason — the baseline pins JUSTIFIED findings")
        out[str(key)] = str(reason)
    return out


def apply_baseline(findings: List[Finding], baseline: Dict[str, str],
                   ) -> Tuple[List[Finding], List[Finding], List[str]]:
    """Split findings into (new, accepted) against a baseline and report
    stale baseline keys that no longer match anything (informational —
    prune them when amending)."""
    new: List[Finding] = []
    accepted: List[Finding] = []
    seen = set()
    for f in findings:
        if f.key in baseline:
            accepted.append(f)
            seen.add(f.key)
        else:
            new.append(f)
    stale = [k for k in baseline if k not in seen]
    return new, accepted, stale
