"""GraftLint pillar 2 — AST linter for concurrency and tracing hazards.

Where :mod:`.jaxpr_audit` proves properties of the *compiled programs*,
this module audits the *framework source*: the threaded modules (PS
service, serving, heter, observability) for lock-ordering hazards of
exactly the PR 3 deadlock class, and the jit-adjacent modules for
tracing hazards (host syncs, impure time/random/env reads under trace).

Lock analysis
-------------
Lock objects are discovered at their creation sites
(``threading.Lock/RLock/Condition/Semaphore`` calls, including locks
held in dict literals like ``rep = {"lock": threading.Lock()}`` and in
list comprehensions).  Each function is then walked statement-by-
statement with an abstract "held set": ``with lock:`` blocks and
``.acquire()``/``.release()`` calls move locks in and out, and acquiring
B while holding A records the edge ``A -> B``.  One interprocedural step
propagates through same-module calls (``self._forward()`` under the
apply lock contributes the locks ``_forward`` takes), which is exactly
how the PR 3 ``_apply_lock`` vs replica-sink-lock deadlock arose.  A
cycle in the resulting graph is ``lock.order-cycle``; an observed edge
whose reverse is *declared* is the more specific
``lock.order-violation``.

Declarations and suppressions ride structured comments::

    # lint: lock-order: PSServer._apply_lock -> rep[lock]
    some_call()   # lint: ok(trace.host-sync) reason...

Tracing hazards
---------------
Functions are "traced" when they are passed to ``jax.jit`` /
``shard_map`` / ``jax.checkpoint`` / ``lax.cond``-style combinators
(directly, via decorator, or transitively by being called from a traced
function in the same module).  Inside traced code the rules flag:

``trace.host-sync``     ``.item()/.tolist()``, ``np.asarray/np.array``,
                        ``float()/int()/bool()`` on non-literals — each
                        is a device->host sync per step (or a silent
                        constant-folding of a traced value).
``trace.impure-time``   ``time.time()/monotonic()/perf_counter()`` —
                        baked in at trace time, frozen forever.
``trace.impure-random`` stateful ``random``/``np.random`` — same.
``trace.env-read``      ``os.environ``/``os.getenv`` — config frozen
                        into the compiled program.

Hot-path rules (outside traced code):

``hot.env-read-loop``   an env read lexically inside a loop — per-step
                        syscalls for what should be read once.
``hot.host-sync-loop``  ``.item()`` inside a loop — a per-iteration
                        device sync in eager host code.
"""
from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .findings import SEV_ERROR, SEV_WARNING, Finding

__all__ = ["lint_source", "lint_file", "lint_paths", "LintConfig",
           "DEFAULT_LINT_PATHS"]

# the repo module set the CLI and the clean-repo test lint by default:
# the threaded modules (lock rules) + the hot-path/jit-adjacent modules
# (tracing-hazard rules), per ISSUE 6
DEFAULT_LINT_PATHS = (
    "paddle_tpu/distributed/fleet/ps_service.py",
    "paddle_tpu/distributed/fleet/elastic.py",
    "paddle_tpu/distributed/fleet/geo.py",
    "paddle_tpu/distributed/fleet/heter.py",
    "paddle_tpu/inference/serving.py",
    "paddle_tpu/inference/generation_server.py",
    "paddle_tpu/inference/prefix_cache.py",
    "paddle_tpu/inference/__init__.py",
    "paddle_tpu/observability/trace.py",
    "paddle_tpu/observability/timeline.py",
    "paddle_tpu/observability/request_trace.py",
    "paddle_tpu/observability/aggregator.py",
    "paddle_tpu/observability/slo.py",
    "paddle_tpu/framework/monitor.py",
    "paddle_tpu/distributed/fleet/dist_step.py",
    "paddle_tpu/io/dataloader.py",
    "paddle_tpu/train_guard.py",
    # ISSUE 14: the online learning loop (threaded trainer/sweeper/
    # freshness watch)
    "paddle_tpu/online/__init__.py",
    "paddle_tpu/online/streaming.py",
    "paddle_tpu/online/lifecycle.py",
    "paddle_tpu/online/freshness.py",
    # ISSUE 16: the tiered PS table (pin/resolve shared-lock protocol
    # around raw arena addresses) and the pull-dequant kernel entry
    "paddle_tpu/distributed/fleet/ps.py",
    "paddle_tpu/ops/pallas/pull_dequant.py",
    # ISSUE 17: the device-native elastic engine (jit reduce + fused
    # apply compiled per mesh generation — tracing-hazard territory)
    "paddle_tpu/distributed/fleet/elastic_engine.py",
    # ISSUE 15: the auto-sharding planner (SpecLayout + search +
    # calibration — the verify path builds/compiles steps, so the
    # tracing-hazard rules apply)
    "paddle_tpu/distributed/planner/__init__.py",
    "paddle_tpu/distributed/planner/spec_layout.py",
    "paddle_tpu/distributed/planner/memory_model.py",
    "paddle_tpu/distributed/planner/search.py",
    "paddle_tpu/distributed/planner/calibrate.py",
    # ISSUE 13: the Pallas kernel tier (registry locking + kernels)
    "paddle_tpu/ops/pallas/__init__.py",
    "paddle_tpu/ops/pallas/registry.py",
    "paddle_tpu/ops/pallas/flash_attention.py",
    "paddle_tpu/ops/pallas/opt_apply.py",
    "paddle_tpu/ops/pallas/int8_matmul.py",
    "paddle_tpu/ops/pallas/kv_attention.py",
    "paddle_tpu/ops/pallas/segment_sum.py",
    # ISSUE 18: the inference gateway (router ring lock -> per-replica
    # client lock hierarchy, declared in-file; migration runs on the
    # scheduler thread so the server lock graph is unchanged)
    "paddle_tpu/inference/gateway.py",
    "paddle_tpu/inference/migration.py",
)

_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                   "BoundedSemaphore"}
# Condition wraps an RLock; re-acquiring these is legal
_REENTRANT_FACTORIES = {"RLock", "Condition"}

_TRACER_ENTRY_FUNCS = {"jit", "checkpoint", "remat", "vmap", "pmap",
                       "grad", "value_and_grad", "shard_map", "scan",
                       "cond", "while_loop", "switch", "custom_jvp",
                       "custom_vjp"}

_DIRECTIVE_RE = re.compile(r"#\s*lint:\s*(.+)$")
_OK_RE = re.compile(r"ok\(([^)]*)\)")
_ORDER_RE = re.compile(r"lock-order:\s*(.+)$")


@dataclass
class LintConfig:
    check_locks: bool = True
    check_tracing: bool = True
    check_hot: bool = True


# ----------------------------------------------------------------------
# directives
# ----------------------------------------------------------------------

def _parse_directives(src: str):
    """-> (suppressions {lineno: set(rules)}, declared lock-order edges
    [(a, b, lineno)])."""
    suppress: Dict[int, Set[str]] = {}
    declared: List[Tuple[str, str, int]] = []
    for i, line in enumerate(src.splitlines(), start=1):
        m = _DIRECTIVE_RE.search(line)
        if not m:
            continue
        body = m.group(1).strip()
        ok = _OK_RE.match(body)
        if ok:
            rules = {r.strip() for r in ok.group(1).split(",") if r.strip()}
            suppress.setdefault(i, set()).update(rules or {"*"})
            continue
        order = _ORDER_RE.match(body)
        if order:
            chain = [p.strip() for p in order.group(1).split("->")]
            for a, b in zip(chain, chain[1:]):
                if a and b:
                    declared.append((a, b, i))
    return suppress, declared


# ----------------------------------------------------------------------
# expression canonicalization
# ----------------------------------------------------------------------

def _canon(expr, aliases: Dict[str, str]) -> Optional[str]:
    """Canonical dotted path of an expression, resolving simple local
    aliases (``mon = self.monitor``) — None when it isn't a plain
    name/attribute/subscript chain."""
    if isinstance(expr, ast.Name):
        return aliases.get(expr.id, expr.id)
    if isinstance(expr, ast.Attribute):
        base = _canon(expr.value, aliases)
        return None if base is None else f"{base}.{expr.attr}"
    if isinstance(expr, ast.Subscript):
        base = _canon(expr.value, aliases)
        if base is None:
            return None
        sl = expr.slice
        if isinstance(sl, ast.Constant) and isinstance(sl.value,
                                                       (str, int)):
            return f"{base}[{sl.value}]"
        return f"{base}[]"
    return None


def _scope_name(canonical: str, class_name: Optional[str]) -> str:
    """``self.x`` -> ``Class.x`` so the same attribute referenced from
    different methods of one class lands on one graph node."""
    if class_name and (canonical == "self"
                       or canonical.startswith("self.")):
        return class_name + canonical[4:]
    return canonical


def _lock_tail(name: str) -> str:
    tail = name.rsplit(".", 1)[-1]
    return tail.split("[", 1)[0]


# ----------------------------------------------------------------------
# per-function walk
# ----------------------------------------------------------------------

@dataclass
class _FnInfo:
    qualname: str
    node: ast.AST
    class_name: Optional[str]
    acquires: Set[str] = field(default_factory=set)     # direct
    edges: List[Tuple[str, str, int]] = field(default_factory=list)
    calls: List[Tuple[str, Tuple[str, ...], int]] = \
        field(default_factory=list)   # (callee name, held-at-call, line)


class _Module:
    def __init__(self, path: str, src: str, config: LintConfig):
        self.path = path
        # stable display path for finding locs/baseline keys: the
        # repo-relative tail when recognizable, else the basename —
        # never cwd-relative (baseline keys must not depend on where
        # the linter was invoked from)
        norm = path.replace(os.sep, "/")
        idx = norm.rfind("paddle_tpu/")
        if idx < 0:
            idx = norm.rfind("tests/")
        if idx < 0:
            idx = norm.rfind("tools/")
        self.relpath = norm[idx:] if idx >= 0 else os.path.basename(norm)
        self.src = src
        self.tree = ast.parse(src)
        self._parents = None
        self.config = config
        self.suppress, self.declared = _parse_directives(src)
        self.findings: List[Finding] = []
        self.locks: Dict[str, str] = {}       # canonical -> factory
        self.fns: Dict[str, _FnInfo] = {}     # qualname -> info
        self.by_name: Dict[str, List[str]] = {}  # bare name -> qualnames
        self.traced: Set[str] = set()         # qualnames traced by jax

    # -- finding emission ------------------------------------------------
    def emit(self, severity, rule, scope, detail, line):
        for sup_rules in (self.suppress.get(line, ()),):
            if sup_rules and (rule in sup_rules or "*" in sup_rules):
                return
        self.findings.append(Finding(
            severity, rule, f"{self.relpath}::{scope}", detail,
            line=line))

    # -- pass 0: function + lock discovery -------------------------------
    def index(self):
        for node, cls, qual in _walk_functions(self.tree):
            info = _FnInfo(qual, node, cls)
            self.fns[qual] = info
            self.by_name.setdefault(node.name, []).append(qual)
        for node in ast.walk(self.tree):
            if _is_lock_factory(node):
                name = self._lock_name_for(node)
                if name:
                    self.locks[name] = node.func.attr \
                        if isinstance(node.func, ast.Attribute) \
                        else node.func.id

    def _lock_name_for(self, call: ast.Call) -> Optional[str]:
        """Canonical name for a lock created at this call site, derived
        from the assignment that stores it."""
        if self._parents is None:
            self._parents = _parent_map(self.tree)
        parents = self._parents
        node, key, in_container = call, None, False
        while node in parents:
            parent = parents[node]
            if isinstance(parent, ast.Dict) and node in parent.values:
                k = parent.keys[parent.values.index(node)]
                if isinstance(k, ast.Constant):
                    key = str(k.value)
                in_container = True
            elif isinstance(parent, (ast.List, ast.Tuple, ast.ListComp,
                                     ast.comprehension)):
                in_container = True
            elif isinstance(parent, (ast.Assign, ast.AnnAssign)):
                targets = parent.targets if isinstance(parent, ast.Assign) \
                    else [parent.target]
                for t in targets:
                    base = _canon(t, {})
                    if base is None:
                        continue
                    cls = _enclosing_class(parents, parent)
                    base = _scope_name(base, cls)
                    if key is not None:
                        return f"{base}[{key}]"
                    if in_container:
                        return f"{base}[]"
                    return base
                return None
            node = parent
        return None

    def _is_lock(self, canonical: Optional[str]) -> bool:
        if canonical is None:
            return False
        if canonical in self.locks:
            return True
        tail = _lock_tail(canonical)
        return any(_lock_tail(k) == tail for k in self.locks)

    # -- pass 1: lock walk ----------------------------------------------
    def analyze_locks(self):
        for info in self.fns.values():
            aliases: Dict[str, str] = {}
            self._walk_stmts(list(_body_of(info.node)), [], info, aliases)

        # interprocedural fixpoint: a function "acquires" everything its
        # same-module callees acquire
        total: Dict[str, Set[str]] = {q: set(i.acquires)
                                      for q, i in self.fns.items()}
        changed = True
        while changed:
            changed = False
            for q, info in self.fns.items():
                for callee, _, _ in info.calls:
                    for cq in self.by_name.get(callee, ()):
                        extra = total[cq] - total[q]
                        if extra:
                            total[q] |= extra
                            changed = True
        edges: List[Tuple[str, str, int, str]] = []
        for q, info in self.fns.items():
            for a, b, line in info.edges:
                edges.append((a, b, line, q))
            for callee, held, line in info.calls:
                for cq in self.by_name.get(callee, ()):
                    for a in held:
                        for b in total[cq]:
                            if a != b:
                                edges.append((a, b, line,
                                              f"{q} -> {callee}()"))
        self._report_lock_graph(edges)

    def _walk_stmts(self, stmts, held: List[str], info: _FnInfo,
                    aliases: Dict[str, str]):
        for st in stmts:
            self._walk_stmt(st, held, info, aliases)

    def _walk_stmt(self, st, held, info, aliases):
        cls = info.class_name
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return      # nested defs are analyzed as their own functions
        if isinstance(st, (ast.Assign, ast.AnnAssign)) :
            value = st.value
            targets = st.targets if isinstance(st, ast.Assign) \
                else ([st.target] if st.value else [])
            if value is not None and len(targets) == 1 \
                    and isinstance(targets[0], ast.Name):
                rhs = _canon(value, aliases)
                if rhs is not None:
                    aliases[targets[0].id] = rhs
            self._scan_calls(st, held, info, aliases)
            return
        if isinstance(st, ast.With):
            pushed = []
            for item in st.items:
                ce = item.context_expr
                lk = self._lock_of(ce, aliases, cls)
                if lk is not None:
                    self._acquire(lk, held, info, ce.lineno, cls)
                    pushed.append(lk)
                else:
                    self._scan_calls(item.context_expr, held, info,
                                     aliases)
            self._walk_stmts(st.body, held, info, aliases)
            for lk in reversed(pushed):
                if lk in held:
                    held.remove(lk)
            return
        if isinstance(st, ast.Try):
            entry = list(held)
            self._walk_stmts(st.body, held, info, aliases)
            after_try = list(held)
            for h in st.handlers:
                held[:] = list(entry)
                self._walk_stmts(h.body, held, info, aliases)
            held[:] = after_try
            self._walk_stmts(st.orelse, held, info, aliases)
            fin_state = list(held)
            self._walk_stmts(st.finalbody, held, info, aliases)
            held[:] = fin_state
            return
        if isinstance(st, (ast.If, ast.For, ast.While, ast.AsyncFor)):
            if hasattr(st, "test"):
                self._scan_calls(st.test, held, info, aliases)
            if hasattr(st, "iter"):
                self._scan_calls(st.iter, held, info, aliases)
            branch = list(held)
            self._walk_stmts(st.body, branch, info, aliases)
            branch2 = list(held)
            self._walk_stmts(st.orelse, branch2, info, aliases)
            return
        # leaf statement: look for acquire/release + calls
        for node in ast.walk(st):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute):
                tgt = self._lock_of(node.func.value, aliases, cls)
                if tgt is not None and node.func.attr == "acquire":
                    self._acquire(tgt, held, info, node.lineno, cls)
                    continue
                if tgt is not None and node.func.attr == "release":
                    if tgt in held:
                        held.remove(tgt)
                    continue
        self._scan_calls(st, held, info, aliases)

    def _lock_of(self, expr, aliases, cls) -> Optional[str]:
        c = _canon(expr, aliases)
        if c is None:
            return None
        c = _scope_name(c, cls)
        return c if self._is_lock(c) else None

    def _acquire(self, lk: str, held: List[str], info: _FnInfo,
                 line: int, cls):
        for h in held:
            if h == lk:
                if self.locks.get(lk) in _REENTRANT_FACTORIES:
                    continue
                # tail-matched aliases of a reentrant factory also pass
                tails = {_lock_tail(k): f for k, f in self.locks.items()}
                if tails.get(_lock_tail(lk)) in _REENTRANT_FACTORIES:
                    continue
                self.emit(SEV_ERROR, "lock.reentrant-acquire",
                          info.qualname,
                          f"{lk} re-acquired while already held — "
                          "threading.Lock self-deadlocks; use RLock or "
                          "restructure", line)
                continue
            info.edges.append((h, lk, line))
        info.acquires.add(lk)
        held.append(lk)

    def _scan_calls(self, node, held, info, aliases):
        """Record same-module calls made while holding locks (for the
        interprocedural edge pass)."""
        for call in ast.walk(node):
            if not isinstance(call, ast.Call):
                continue
            name = None
            if isinstance(call.func, ast.Attribute):
                name = call.func.attr
            elif isinstance(call.func, ast.Name):
                name = call.func.id
            if name and name in self.by_name:
                info.calls.append((name, tuple(held), call.lineno))

    def _report_lock_graph(self, edges):
        declared = {(a, b) for a, b, _ in self.declared}
        observed: Dict[Tuple[str, str], Tuple[int, str]] = {}
        for a, b, line, where in edges:
            observed.setdefault((a, b), (line, where))

        # declared-order violations first (more specific than a cycle)
        violated = set()
        for (a, b), (line, where) in sorted(observed.items()):
            if (b, a) in declared:
                violated.add((a, b))
                self.emit(SEV_ERROR, "lock.order-violation",
                          where,
                          f"acquires {b} while holding {a}, but the "
                          f"declared order is {b} -> {a} "
                          "(# lint: lock-order directive)", line)

        graph: Dict[str, Set[str]] = {}
        for (a, b) in set(observed) | declared:
            if (a, b) in violated or (b, a) in violated:
                continue    # already reported as a violation
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        for cyc in _find_cycles(graph):
            locs = [observed.get((x, y), (None, None))
                    for x, y in zip(cyc, cyc[1:] + cyc[:1])]
            line = next((l for l, _ in locs if l), None)
            wheres = sorted({w for _, w in locs if w})
            self.emit(SEV_ERROR, "lock.order-cycle",
                      ",".join(sorted(set(cyc))),
                      "lock acquisition cycle "
                      + " -> ".join(cyc + [cyc[0]])
                      + (f" (observed in {', '.join(wheres)})"
                         if wheres else "")
                      + " — two threads taking these locks in opposing "
                      "orders deadlock", line)

    # -- pass 2: tracing hazards ----------------------------------------
    def analyze_tracing(self):
        self._mark_traced()
        for qual in sorted(self.traced):
            info = self.fns.get(qual)
            if info is not None:
                self._scan_traced(info)
        if self.config.check_hot:
            self._scan_hot()

    def _mark_traced(self):
        # seed: functions handed to jax.jit/shard_map/lax.cond/... or
        # decorated with them
        seeds: Set[str] = set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call) and _is_tracer_entry(node.func):
                for arg in node.args:
                    if isinstance(arg, ast.Name):
                        seeds.add(arg.id)
                    elif isinstance(arg, ast.Lambda):
                        pass    # lambdas scanned via enclosing function
        for info in self.fns.values():
            node = info.node
            for dec in getattr(node, "decorator_list", ()):
                f = dec.func if isinstance(dec, ast.Call) else dec
                if _is_tracer_entry(f) or (
                        isinstance(dec, ast.Call)
                        and any(_is_tracer_entry(a) for a in dec.args)):
                    seeds.add(node.name)
        traced = {q for q, i in self.fns.items()
                  if i.node.name in seeds}
        # propagate through same-module calls: a helper called from a
        # traced function runs under the tracer too
        changed = True
        while changed:
            changed = False
            for q in list(traced):
                info = self.fns[q]
                for call in ast.walk(info.node):
                    if not isinstance(call, ast.Call):
                        continue
                    nm = None
                    if isinstance(call.func, ast.Name):
                        nm = call.func.id
                    elif isinstance(call.func, ast.Attribute) \
                            and isinstance(call.func.value, ast.Name) \
                            and call.func.value.id == "self":
                        nm = call.func.attr
                    if nm is None:
                        continue
                    for cq in self.by_name.get(nm, ()):
                        if cq not in traced:
                            traced.add(cq)
                            changed = True
        self.traced = traced

    def _scan_traced(self, info: _FnInfo):
        qual = info.qualname
        body_nodes = []

        def collect(node):
            for child in ast.iter_child_nodes(node):
                # the payload of a host callback IS host code — np/
                # float on it is the point, not a hazard
                if isinstance(child, ast.Call) and isinstance(
                        child.func, (ast.Name, ast.Attribute)):
                    nm = child.func.attr if isinstance(
                        child.func, ast.Attribute) else child.func.id
                    if nm in ("pure_callback", "io_callback",
                              "debug_callback"):
                        continue
                body_nodes.append(child)
                collect(child)

        for st in _body_of(info.node):
            body_nodes.append(st)
            collect(st)
        for node in body_nodes:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue    # nested defs get their own traced pass
            if isinstance(node, ast.Call):
                self._check_traced_call(node, qual)
            elif isinstance(node, ast.Subscript):
                base = _canon(node.value, {})
                if base in ("os.environ",):
                    self.emit(SEV_ERROR, "trace.env-read", qual,
                              "os.environ read inside traced code — the "
                              "value is frozen into the compiled program",
                              node.lineno)

    def _check_traced_call(self, call: ast.Call, qual: str):
        f = call.func
        if isinstance(f, ast.Attribute):
            base = _canon(f.value, {})
            if f.attr in ("item", "tolist") and not call.args:
                self.emit(SEV_ERROR, "trace.host-sync", qual,
                          f".{f.attr}() on a value inside traced code — "
                          "a concretization error under jit, a per-step "
                          "device->host sync outside it; keep values on "
                          "device or fetch through _host_fetch",
                          call.lineno)
                return
            if base in ("np", "numpy") and f.attr in ("asarray", "array"):
                self.emit(SEV_ERROR, "trace.host-sync", qual,
                          f"np.{f.attr}() inside traced code pulls the "
                          "traced value to host — use jnp equivalents",
                          call.lineno)
                return
            if base == "time" and f.attr in (
                    "time", "time_ns", "monotonic", "monotonic_ns",
                    "perf_counter", "perf_counter_ns"):
                self.emit(SEV_ERROR, "trace.impure-time", qual,
                          f"time.{f.attr}() inside traced code is "
                          "evaluated ONCE at trace time and constant-"
                          "folded forever", call.lineno)
                return
            if base in ("random", "np.random", "numpy.random"):
                self.emit(SEV_ERROR, "trace.impure-random", qual,
                          f"stateful {base}.{f.attr}() inside traced "
                          "code — evaluated once at trace time; use "
                          "jax.random with an explicit key",
                          call.lineno)
                return
            if base == "os" and f.attr == "getenv":
                self.emit(SEV_ERROR, "trace.env-read", qual,
                          "os.getenv inside traced code — the value is "
                          "frozen into the compiled program",
                          call.lineno)
                return
            if base == "os.environ" and f.attr == "get":
                self.emit(SEV_ERROR, "trace.env-read", qual,
                          "os.environ.get inside traced code — the "
                          "value is frozen into the compiled program",
                          call.lineno)
                return
        elif isinstance(f, ast.Name) and f.id in ("float", "bool") \
                and len(call.args) == 1:
            # int() is deliberately NOT flagged: it is overwhelmingly
            # static shape/config math; float()/bool() on a traced
            # value are the classic concretization hazards (the old
            # GradScaler paid one bool(isfinite) round trip PER PARAM)
            a = call.args[0]
            if not isinstance(a, (ast.Constant, ast.JoinedStr)) \
                    and not _is_shape_like(a):
                self.emit(SEV_ERROR, "trace.host-sync", qual,
                          f"{f.id}() on a non-literal inside traced "
                          "code concretizes the traced value (host "
                          "sync / ConcretizationTypeError)",
                          call.lineno)

    def _scan_hot(self):
        traced_nodes = {id(self.fns[q].node) for q in self.traced
                        if q in self.fns}
        for info in self.fns.values():
            if id(info.node) in traced_nodes:
                continue
            for loop in ast.walk(info.node):
                if not isinstance(loop, (ast.For, ast.While,
                                         ast.AsyncFor)):
                    continue
                for node in ast.walk(loop):
                    if isinstance(node, ast.Call):
                        f = node.func
                        if isinstance(f, ast.Attribute):
                            base = _canon(f.value, {})
                            if (base == "os" and f.attr == "getenv") or \
                                    (base == "os.environ"
                                     and f.attr == "get"):
                                self.emit(
                                    SEV_WARNING, "hot.env-read-loop",
                                    info.qualname,
                                    "env var read inside a loop — read "
                                    "once outside the hot path",
                                    node.lineno)
                            elif f.attr == "item" and not node.args:
                                self.emit(
                                    SEV_WARNING, "hot.host-sync-loop",
                                    info.qualname,
                                    ".item() inside a loop — one "
                                    "device sync per iteration",
                                    node.lineno)
                    elif isinstance(node, ast.Subscript):
                        if _canon(node.value, {}) == "os.environ":
                            self.emit(
                                SEV_WARNING, "hot.env-read-loop",
                                info.qualname,
                                "os.environ[...] inside a loop — read "
                                "once outside the hot path",
                                node.lineno)

    # -- driver ----------------------------------------------------------
    def run(self) -> List[Finding]:
        self.index()
        if self.config.check_locks:
            self.analyze_locks()
        if self.config.check_tracing:
            self.analyze_tracing()
        # a nested def inside a traced fn is scanned inline AND as its
        # own traced function — report each site once
        seen = set()
        out = []
        for f in self.findings:
            k = (f.rule, f.line, f.detail)
            if k not in seen:
                seen.add(k)
                out.append(f)
        self.findings = out
        return self.findings


# ----------------------------------------------------------------------
# helpers
# ----------------------------------------------------------------------

def _body_of(node):
    return getattr(node, "body", [])


def _walk_functions(tree):
    """Yield (node, enclosing class name, qualname) for every function,
    including nested ones."""
    def rec(node, cls, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                yield from rec(child, child.name,
                               prefix + (child.name,))
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                qual = ".".join(prefix + (child.name,))
                yield child, cls, qual
                yield from rec(child, cls, prefix + (child.name,))
            else:
                yield from rec(child, cls, prefix)
    yield from rec(tree, None, ())


def _parent_map(tree):
    parents = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def _enclosing_class(parents, node) -> Optional[str]:
    while node in parents:
        node = parents[node]
        if isinstance(node, ast.ClassDef):
            return node.name
    return None


def _is_lock_factory(node) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr in _LOCK_FACTORIES:
        base = _canon(f.value, {})
        return base in ("threading", "_threading", "mp",
                        "multiprocessing")
    if isinstance(f, ast.Name) and f.id in _LOCK_FACTORIES:
        return True
    return False


def _is_tracer_entry(f) -> bool:
    if isinstance(f, ast.Attribute):
        return f.attr in _TRACER_ENTRY_FUNCS
    if isinstance(f, ast.Name):
        return f.id in _TRACER_ENTRY_FUNCS
    return False


def _is_shape_like(node) -> bool:
    """int()/float() on shapes, len(), or dict lookups of config are
    legitimate under trace (static values)."""
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
            and node.func.id == "len":
        return True
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in (
                "shape", "ndim", "size", "dtype"):
            return True
    return False


def _find_cycles(graph: Dict[str, Set[str]]) -> List[List[str]]:
    """Cycles in the lock graph via Tarjan SCCs (one report per SCC) +
    self-loops."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    out: List[List[str]] = []
    counter = [0]

    def strongconnect(v):
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        for w in graph.get(v, ()):
            if w not in index:
                strongconnect(w)
                low[v] = min(low[v], low[w])
            elif w in on_stack:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            comp = []
            while True:
                w = stack.pop()
                on_stack.discard(w)
                comp.append(w)
                if w == v:
                    break
            if len(comp) > 1:
                out.append(sorted(comp))

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)
    for v, nbrs in graph.items():
        if v in nbrs:
            out.append([v])
    return out


# ----------------------------------------------------------------------
# public API
# ----------------------------------------------------------------------

def lint_source(src: str, path: str = "<string>",
                config: Optional[LintConfig] = None) -> List[Finding]:
    return _Module(path, src, config or LintConfig()).run()


def lint_file(path: str,
              config: Optional[LintConfig] = None) -> List[Finding]:
    with open(path) as f:
        src = f.read()
    return lint_source(src, path=path, config=config)


def lint_paths(paths=None, root: Optional[str] = None,
               config: Optional[LintConfig] = None) -> List[Finding]:
    """Lint a set of files (default: the ISSUE 6 repo module set,
    resolved against ``root`` or the repo checkout this package lives
    in)."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
    findings: List[Finding] = []
    for p in (paths or DEFAULT_LINT_PATHS):
        full = p if os.path.isabs(p) else os.path.join(root, p)
        findings.extend(lint_file(full, config=config))
    return findings
