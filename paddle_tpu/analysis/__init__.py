"""paddle_tpu.analysis — GraftLint: the static-analysis tier (ISSUE 6).

Two pillars over one :class:`~paddle_tpu.analysis.findings.Finding`
report format:

- :mod:`~paddle_tpu.analysis.jaxpr_audit` — the jaxpr program auditor
  (donation, dtype creep, host callbacks, collective inventory, baked
  constants); surfaced as ``DistributedTrainStep.audit()`` and
  ``Predictor.audit()``.
- :mod:`~paddle_tpu.analysis.ast_lint` — the AST framework linter
  (lock-ordering cycles of the PR 3 deadlock class, tracing hazards,
  hot-path env reads); surfaced as ``tools/graft_lint.py`` and the
  ``tools/run_tier1.sh --lint`` CI pass against
  ``tools/lint_baseline.json``.

This module imports jax-free (:mod:`.ast_lint` and :mod:`.findings`
never touch jax; :mod:`.jaxpr_audit` imports it lazily inside the entry
points) so the lint CLI stays cheap.
"""
from .findings import (Finding, SEV_ERROR, SEV_INFO,  # noqa: F401
                       SEV_WARNING, apply_baseline, baseline_entry,
                       format_findings, load_baseline)
from .ast_lint import (DEFAULT_LINT_PATHS, LintConfig,  # noqa: F401
                       lint_file, lint_paths, lint_source)
from .jaxpr_audit import (AuditReport, audit_fn,  # noqa: F401
                          audit_jaxpr, audit_traced,
                          collective_inventory,
                          hlo_collective_inventory)

__all__ = [
    "Finding", "SEV_ERROR", "SEV_WARNING", "SEV_INFO",
    "apply_baseline", "baseline_entry", "format_findings",
    "load_baseline",
    "LintConfig", "DEFAULT_LINT_PATHS", "lint_source", "lint_file",
    "lint_paths",
    "AuditReport", "audit_fn", "audit_traced", "audit_jaxpr",
    "collective_inventory", "hlo_collective_inventory",
]
