"""TrainGuard — in-step numerics health checks, loss-spike skip/rewind,
batch blame, and numeric chaos integration.

PR 3 made the PS *transport* survive crashes and retries; this module is
the numerical counterpart for the training loop itself.  Production LLM
runs treat bf16 loss spikes / NaN gradients as a first-class robustness
problem: PaLM (Chowdhery et al., 2022) restarted from a checkpoint ~100
steps back and skipped the offending data batches; the OPT-175B logbook
records dozens of such manual restarts.  TrainGuard automates that
detect -> skip -> rewind -> blame pipeline on top of pieces the repo
already has (GradScaler inf-skip, CheckpointManager + exact
failure-resume, the PR 3 chaos harness):

1. **Fused health check** (:func:`health_check`): ONE jit-compiled
   reduction over the whole grad tree producing ``[global_norm,
   nonfinite_count, loss]`` as a single 3-element device array.  The
   caller pays exactly one device->host transfer per step for all guard
   state (the old GradScaler.unscale_ paid one ``bool(isfinite.all())``
   round trip *per parameter*).  Every host sync funnels through
   :func:`_host_fetch` so tests can spy the count (the same discipline
   as test_serving's ``num_compiles``).

2. **Policy engine** (:class:`TrainGuard`): skip the step on nonfinite
   grads/loss; detect loss spikes against a rolling median/MAD window;
   after ``max_consecutive_bad`` bad steps rewind to the last-healthy
   (pinned) CheckpointManager step and continue with the NEXT data
   batches — the offending data window is skipped, like PaLM, so the
   post-rewind trajectory intentionally diverges from the fault-free
   one.  When the rewind budget is exhausted a typed
   :class:`NumericalDivergence` is raised.

3. **Batch blame** (:meth:`TrainGuard.blame`): on a skipped step, bisect
   the batch by microbatch halves to identify the poisoned rows;
   counts land in framework.monitor StatRegistry counters
   (``guard_skips`` / ``guard_rewinds`` / ``guard_blamed_rows``).

4. **Numeric chaos**: fleet/chaos.py gains ``nan``/``inf`` fault kinds
   (``PADDLE_CHAOS="nan:grad:step=50"``); :func:`chaos_corrupt` is the
   injection hook the guard (grads) and hapi/tools (batch, activation)
   call, so every guard path is exercised deterministically in tier-1
   (tools/chaos_numerics.py is the driver).
"""
from __future__ import annotations

import collections
from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .framework import monitor as _monitor
from .framework.errors import EnforceNotMet
from .framework.monitor import gauge_set, stat_add, stat_get
from .observability import flight_recorder as _flight
from .observability import trace as _obs_trace

__all__ = ["TrainGuard", "HealthState", "NumericalDivergence",
           "health_check", "fused_health", "chaos_corrupt",
           "host_sync_count", "GUARD_STAT_NAMES"]

# StatRegistry counter names the guard reports through (framework.monitor)
GUARD_STAT_NAMES = ("guard_skips", "guard_rewinds", "guard_blamed_rows")


class NumericalDivergence(EnforceNotMet):
    """Raised when the guard's rewind budget is exhausted and the run is
    still numerically diverging — the automatic-recovery analog of the
    reference's FatalError: nothing left to do but page a human."""


# ----------------------------------------------------------------------
# fused in-step health check
# ----------------------------------------------------------------------

def _health_reduce(loss, grads):
    """Pure: grad leaves + loss -> f32[3] = [global_norm, nonfinite_count,
    loss].  Nonfinite entries are masked out of the norm so the norm stays
    informative even on a poisoned step (an all-NaN norm says nothing
    about the healthy remainder)."""
    sq = jnp.zeros((), jnp.float32)
    bad = jnp.zeros((), jnp.float32)
    for g in grads:
        finite = jnp.isfinite(g)
        bad += jnp.sum(~finite).astype(jnp.float32)
        g32 = jnp.where(finite, g, 0).astype(jnp.float32)
        sq += jnp.sum(g32 * g32)
    if loss is None:
        lv = jnp.float32(0)
    else:
        lv = jnp.asarray(loss, jnp.float32).reshape(())
        bad += (~jnp.isfinite(lv)).astype(jnp.float32)
    return jnp.stack([jnp.sqrt(sq), bad, lv])


def _health_reduce_fast(loss, grads):
    """Single-reduction variant for compiled hot paths: ONE pass over
    each grad (sum of squares only).  NaN/Inf propagate into the sum, so
    badness falls out of the result's own finiteness — no isfinite/mask
    passes over the tree.  Trade-off vs the precise reduce: slot [1] is
    a 0/1 indicator (not an element count) and the norm reads nonfinite
    on a bad step; both are exactly what the skip policy needs.  An f32
    square-sum can also overflow to inf on ~1e19 finite grads — a
    magnitude that IS divergence, so flagging it is correct."""
    sq = jnp.zeros((), jnp.float32)
    for g in grads:
        g32 = g.astype(jnp.float32)
        sq += jnp.sum(g32 * g32)
    if loss is None:
        lv = jnp.float32(0)
        bad = (~jnp.isfinite(sq)).astype(jnp.float32)
    else:
        lv = jnp.asarray(loss, jnp.float32).reshape(())
        bad = (~(jnp.isfinite(sq) & jnp.isfinite(lv))).astype(jnp.float32)
    return jnp.stack([jnp.sqrt(sq), bad, lv])


_fused = jax.jit(_health_reduce, static_argnames=())

# every guard device->host transfer funnels through _host_fetch so the
# count is spy-able; architecture rule: NOTHING else in this module may
# call np.asarray/float/bool on a device value
_host_syncs = 0


def host_sync_count() -> int:
    return _host_syncs


def _host_fetch(dev_arr) -> np.ndarray:
    global _host_syncs
    _host_syncs += 1
    if _obs_trace.enabled() or _monitor.metrics_enabled():
        # the funnel doubles as the step timeline's "health fetch"
        # phase: this transfer is the guard's only device sync, so its
        # duration IS the time the host stalls on guard state
        import time as _time
        with _obs_trace.span("step.health_fetch", cat="step"):
            t0 = _time.perf_counter()
            out = np.asarray(dev_arr)
        if _monitor.metrics_enabled():
            _monitor.hist_observe("step_health_fetch_ms",
                                  (_time.perf_counter() - t0) * 1e3)
        return out
    return np.asarray(dev_arr)


def _grad_leaves(source) -> List:
    """Raw grad arrays from an Optimizer, a list of Tensors/arrays, or a
    parameter list.  SelectedRows contribute their (unmerged) value
    blocks — duplicates inflate the norm slightly but finiteness, the
    guard's signal, is exact."""
    from .framework.core import Tensor
    from .framework.selected_rows import SelectedRows
    if hasattr(source, "grad_leaves"):       # an Optimizer
        return list(source.grad_leaves())
    if hasattr(source, "_parameter_list"):
        source = [p.grad for p in source._parameter_list
                  if p.grad is not None]
    leaves = []
    for g in source:
        if g is None:
            continue
        if isinstance(g, SelectedRows):
            leaves.append(g.values)
        elif isinstance(g, Tensor):
            leaves.append(g._value)
        else:
            leaves.append(jnp.asarray(g))
    return leaves


class HealthState:
    """One step's health: wraps the 3-element device array; ``.fetch()``
    is the single host transfer (cached)."""

    __slots__ = ("device", "_host")

    def __init__(self, device_arr):
        self.device = device_arr
        self._host = None

    def fetch(self) -> np.ndarray:
        if self._host is None:
            self._host = _host_fetch(self.device)
        return self._host

    @property
    def global_norm(self) -> float:
        return float(self.fetch()[0])

    @property
    def nonfinite_count(self) -> int:
        # inf-marked loss contributes; count is clamped sane for display
        v = self.fetch()[1]
        return int(v) if np.isfinite(v) else 1

    @property
    def loss(self) -> float:
        return float(self.fetch()[2])

    @property
    def ok(self) -> bool:
        h = self.fetch()
        return bool(h[1] == 0 and np.isfinite(h[2]))


def fused_health(grads: Sequence, loss=None, precise: bool = True):
    """In-jit building block: returns the f32[3] health array WITHOUT any
    host transfer — compose it into a jitted train step and hand the
    result to :meth:`TrainGuard.check` (DistributedTrainStep
    guard_health and bench.py BENCH_GUARD do this).  ``precise=False``
    selects the single-pass reduction (indicator instead of element
    count, unmasked norm) — the right choice inside a hot step."""
    reduce = _health_reduce if precise else _health_reduce_fast
    return reduce(loss, list(grads))


def health_check(grads, loss=None) -> HealthState:
    """Run the fused health reduction over ``grads`` (an Optimizer, or a
    list of Tensors / SelectedRows / arrays).  No host sync happens until
    the returned state's ``.fetch()``/properties are read — and then
    exactly one."""
    leaves = _grad_leaves(grads)
    lv = getattr(loss, "_value", loss)
    if not leaves:
        dev = _fused(jnp.float32(0) if lv is None else lv, [jnp.zeros((1,))])
    else:
        dev = _fused(lv, leaves)
    return HealthState(dev)


# ----------------------------------------------------------------------
# numeric chaos injection hook
# ----------------------------------------------------------------------

def chaos_corrupt(op: str, arrays):
    """If a chaos plan with a matching numeric fault (kinds ``nan`` /
    ``inf``, op ``grad`` / ``batch`` / ``activation`` / ``loss``) is
    active and scheduled to fire NOW, corrupt ``arrays`` (list of
    numpy/jax arrays or a single array) and return (arrays, fired).

    Corruption is deterministic: the first ``max(1, int(arg))`` rows (or
    flat elements, for 0/1-d arrays) of the FIRST float array are set to
    the fault value — so batch blame can assert exactly which rows were
    poisoned."""
    from .distributed.fleet import chaos as _chaos
    plan = _chaos.active()
    if plan is None:
        return arrays, False
    single = not isinstance(arrays, (list, tuple))
    arrs = [arrays] if single else list(arrays)
    fault = plan.match_numeric(op)
    if fault is None:
        return arrays, False
    val = np.nan if fault.kind == "nan" else np.inf
    n = max(1, int(fault.arg))
    out = []
    done = False
    for a in arrs:
        is_float = "float" in str(getattr(a, "dtype", ""))
        if done or not is_float:
            out.append(a)
            continue
        if isinstance(a, np.ndarray):
            b = a.copy()
            if b.ndim >= 2:
                b[:n] = val
            else:
                b.reshape(-1)[:min(n, b.size)] = val
            out.append(b)
        else:
            b = jnp.asarray(a)
            if b.ndim >= 2:
                b = b.at[:n].set(val)
            else:
                flat = b.reshape(-1).at[:min(n, b.size)].set(val)
                b = flat.reshape(b.shape)
            out.append(b)
        done = True
    plan.stats[f"{fault.kind}:{op}"] += 1
    return (out[0] if single else out), True


def _corrupt_optimizer_grads(optimizer) -> bool:
    """Apply a scheduled ``nan:grad``/``inf:grad`` fault to the REAL
    p.grad tensors (not a copy), so the guard is exercised against the
    state the optimizer would actually consume."""
    from .framework.core import Tensor
    # dense grads only: SelectedRows stay clean (their corruption story
    # is the PS-side chaos of PR 3)
    params = [p for p in optimizer._parameter_list
              if isinstance(p.grad, Tensor)]
    if not params:
        return False
    vals = [p.grad._value for p in params]
    new, fired = chaos_corrupt("grad", vals)
    if fired:
        for p, v in zip(params, new):
            p.grad = Tensor(v)
    return fired


# ----------------------------------------------------------------------
# policy engine
# ----------------------------------------------------------------------

class TrainGuard:
    """Automatic detection -> skip -> rewind -> blame for a training loop.

    ::

        guard = TrainGuard(optimizer=opt, manager=ckpt_mgr,
                           state_fn=lambda: {...}, restore_fn=restore)
        for step, batch in enumerate(loader):
            loss = loss_fn(batch); loss.backward()
            verdict = guard.step(loss, step=step,
                                 blame_fn=lambda rows: ...)
            # verdict: "ok" (stepped), "skip" (grads dropped),
            #          "rewind" (state restored to last healthy ckpt)

    * ``state_fn()`` -> nested state dict (model/opt/sched/rng) saved via
      ``manager`` every ``checkpoint_every`` healthy steps; the newest
      healthy step is PINNED in the manager so ``max_to_keep`` rotation
      can never delete the rewind target.
    * ``restore_fn(state)`` must restore EXACTLY what a fresh-process
      resume would (test_failure_resume proves that contract) — the
      in-process rewind then equals kill+resume, minus the data batches
      of the bad window, which are intentionally skipped (PaLM-style).
    * Detection: nonfinite grads/loss always skip; a finite loss further
      than ``spike_factor`` * MAD from the rolling median (after
      ``min_history`` healthy steps) is a spike.  ``max_consecutive_bad``
      bad steps escalate skip -> rewind; ``rewind_budget`` rewinds
      escalate to :class:`NumericalDivergence`.
    """

    def __init__(self, optimizer=None, manager=None, state_fn=None,
                 restore_fn=None, scaler=None, window: int = 32,
                 min_history: int = 8, spike_factor: float = 10.0,
                 mad_floor: float = 1e-3, max_consecutive_bad: int = 3,
                 rewind_budget: int = 2, checkpoint_every: int = 1,
                 blame_fn: Optional[Callable] = None):
        self.optimizer = optimizer
        self.manager = manager
        self.state_fn = state_fn
        self.restore_fn = restore_fn
        self.scaler = scaler
        # default blame hook: hapi's fit loop passes its own row-slicing
        # blame_fn per batch UNLESS this explicit override is set (the
        # PR 4 caller-provided contract, kept)
        self.blame_fn = blame_fn
        self.window = int(window)
        self.min_history = int(min_history)
        self.spike_factor = float(spike_factor)
        self.mad_floor = float(mad_floor)
        self.max_consecutive_bad = int(max_consecutive_bad)
        self.rewind_budget = int(rewind_budget)
        self.checkpoint_every = int(checkpoint_every)

        self._history: collections.deque = collections.deque(
            maxlen=self.window)
        self._bad_streak = 0
        self._healthy_since_ckpt = 0
        self.last_healthy_step: Optional[int] = None
        self.skips = 0
        self.rewinds = 0
        # gauges mirror THIS guard's live counts (hapi/ProgBar read
        # them); a fresh guard zeroes the previous run's values
        for k in GUARD_STAT_NAMES:
            gauge_set(k, 0)
        self.blamed_rows: List = []          # (step, [row indices])
        self.events: List[Dict] = []         # audit log of skip/rewind
        self.last_health: Optional[HealthState] = None

    # -- detection -----------------------------------------------------
    def _spike(self, loss_val: float) -> bool:
        if len(self._history) < self.min_history:
            return False
        med = float(np.median(self._history))
        mad = float(np.median(np.abs(np.asarray(self._history) - med)))
        dev = max(mad, self.mad_floor)
        # only upward excursions are divergence; a sudden *drop* is luck
        return loss_val - med > self.spike_factor * dev

    def check(self, health, step: Optional[int] = None) -> str:
        """Classify one step's health (no optimizer/manager actions —
        :meth:`step` drives those).  ``health``: a HealthState, or the
        raw f32[3] array a jitted step computed via :func:`fused_health`.
        Returns "ok" | "skip" | "rewind"."""
        if not isinstance(health, HealthState):
            health = HealthState(health)
        self.last_health = health
        h = health.fetch()               # the step's ONE host transfer
        nonfinite = h[1] != 0 or not np.isfinite(h[2])
        reason = None
        if nonfinite:
            reason = "nonfinite"
        elif self._spike(float(h[2])):
            reason = "loss_spike"
        if reason is None:
            self._history.append(float(h[2]))
            self._bad_streak = 0
            self._flight_health(step, h, "ok", None)
            return "ok"
        self._bad_streak += 1
        self.events.append({"step": step, "reason": reason,
                            "loss": float(h[2]),
                            "nonfinite": int(h[1]) if np.isfinite(h[1])
                            else -1, "streak": self._bad_streak})
        if (self._bad_streak >= self.max_consecutive_bad
                and self._can_rewind()):
            self._flight_health(step, h, "rewind", reason)
            return "rewind"
        self._flight_health(step, h, "skip", reason)
        return "skip"

    @staticmethod
    def _flight_health(step, h, verdict, reason):
        """Flight-recorder copy of the step's health vector + verdict —
        the per-step history a postmortem bundle replays (a diverging
        run's last N health vectors including the fatal one)."""
        if not _flight.enabled():
            return
        ev = {"step": step, "norm": float(h[0]),
              "nonfinite": float(h[1]), "loss": float(h[2]),
              "verdict": verdict}
        if reason is not None:
            ev["reason"] = reason
        _flight.record("health", **ev)

    def _can_rewind(self) -> bool:
        return (self.manager is not None and self.restore_fn is not None
                and self.last_healthy_step is not None)

    # -- actions -------------------------------------------------------
    def mark_healthy(self, step: int):
        """Record a healthy step; checkpoint + pin every
        ``checkpoint_every`` healthy steps (pinning keeps the rewind
        target alive through max_to_keep rotation)."""
        if self.manager is None or self.state_fn is None:
            self.last_healthy_step = step
            return
        self._healthy_since_ckpt += 1
        if (self.last_healthy_step is None
                or self._healthy_since_ckpt >= self.checkpoint_every):
            self.manager.save(step, self.state_fn())
            prev = self.last_healthy_step
            self.manager.pin(step)
            if prev is not None:
                self.manager.unpin(prev)
            self.last_healthy_step = step
            self._healthy_since_ckpt = 0

    def rewind(self, at_step: Optional[int] = None) -> int:
        """Restore the last-healthy checkpoint (raises
        NumericalDivergence once the budget is spent).  Returns the
        checkpoint step rewound to.  The data batches between that step
        and ``at_step`` are NOT replayed — the caller just continues
        with its next batch (the PaLM skip-data semantics)."""
        if not self._can_rewind():
            _flight.record("divergence", step=at_step,
                           detail="no rewind target")
            _flight.maybe_dump("NumericalDivergence")
            raise NumericalDivergence(
                "TrainGuard cannot rewind: no CheckpointManager/"
                "restore_fn/healthy checkpoint available")
        if self.rewinds >= self.rewind_budget:
            # the fatal path: the bundle written here carries the whole
            # skip/rewind history plus the last health vectors
            _flight.record("divergence", step=at_step,
                           rewinds=self.rewinds,
                           budget=self.rewind_budget)
            _flight.maybe_dump("NumericalDivergence")
            raise NumericalDivergence(
                f"rewind budget exhausted ({self.rewinds}/"
                f"{self.rewind_budget}) and the run is still diverging "
                f"(last events: {self.events[-3:]})")
        target = self.last_healthy_step
        state = self.manager.restore(target)
        self.restore_fn(state)
        self.rewinds += 1
        stat_add("guard_rewinds")
        gauge_set("guard_rewinds", self.rewinds)
        self.events.append({"step": at_step, "reason": "rewind",
                            "to_step": target})
        _flight.record("rewind", step=at_step, to_step=target,
                       rewinds=self.rewinds)
        # the diverged region poisoned the rolling window; restart it
        self._history.clear()
        self._bad_streak = 0
        if self.optimizer is not None:
            self.optimizer.clear_grad()
        return target

    def blame(self, blame_fn: Callable, n_rows: int,
              step: Optional[int] = None) -> List[int]:
        """Bisect the batch by microbatch halves to find poisoned rows.
        ``blame_fn(row_indices: np.ndarray) -> bool`` returns True when
        that sub-batch is HEALTHY (recompute forward/loss/grads on the
        slice and check finiteness).  O(k log n) evaluations for k bad
        rows.  Found rows are quarantined on ``self.blamed_rows`` and
        counted in the ``guard_blamed_rows`` stat."""
        bad: List[int] = []

        def _bisect(idx: np.ndarray):
            if blame_fn(idx):
                return
            if idx.size == 1:
                bad.append(int(idx[0]))
                return
            mid = idx.size // 2
            _bisect(idx[:mid])
            _bisect(idx[mid:])

        _bisect(np.arange(n_rows))
        if bad:
            self.blamed_rows.append((step, sorted(bad)))
            stat_add("guard_blamed_rows", len(bad))
            _flight.record("blame", step=step, rows=sorted(bad))
        gauge_set("guard_blamed_rows",
                  sum(len(r) for _, r in self.blamed_rows))
        return sorted(bad)

    def step(self, loss=None, step: Optional[int] = None,
             optimizer=None, health=None, blame_fn=None,
             n_rows: Optional[int] = None) -> str:
        """Drive one full guarded step: (chaos grad injection) -> fused
        health check -> policy -> act.

        "ok":     optimizer.step() + clear_grad + mark_healthy
        "skip":   grads dropped (clear_grad), GradScaler told (its
                  dynamic-scale backoff still sees the inf), blame run
                  when ``blame_fn``/``n_rows`` given
        "rewind": state restored to the last healthy checkpoint
        """
        opt = optimizer or self.optimizer
        if blame_fn is None:
            blame_fn = self.blame_fn         # explicit ctor override
        if opt is not None:
            _corrupt_optimizer_grads(opt)    # deterministic chaos hook
        if health is None:
            source = opt if opt is not None else []
            health = health_check(source, loss=loss)
        verdict = self.check(health, step=step)
        if verdict == "ok":
            if opt is not None:
                opt.step()
                opt.clear_grad()
            if self.scaler is not None:
                self.scaler._found_inf = False
                self.scaler.update()
                self.scaler._unscaled.discard(id(opt))
            if step is not None:
                self.mark_healthy(step)
            return verdict
        # bad step: never let the poisoned grads reach the weights
        if opt is not None:
            if hasattr(opt, "skip_step"):
                opt.skip_step()
            else:
                opt.clear_grad()
        if self.scaler is not None:
            # dynamic loss scaling backs off exactly as if its own
            # found_inf check had fired
            self.scaler._found_inf = True
            self.scaler.update()
            self.scaler._unscaled.discard(id(opt))
        if verdict == "rewind":
            self.rewind(at_step=step)
            return verdict
        self.skips += 1
        stat_add("guard_skips")
        gauge_set("guard_skips", self.skips)
        if blame_fn is not None and n_rows:
            self.blame(blame_fn, n_rows, step=step)
        return verdict

    # -- reporting -----------------------------------------------------
    def stats(self) -> Dict:
        return {
            "skips": self.skips,
            "rewinds": self.rewinds,
            "blamed_rows": sum(len(r) for _, r in self.blamed_rows),
            "quarantine": list(self.blamed_rows),
            "last_healthy_step": self.last_healthy_step,
            "host_syncs": host_sync_count(),
            "registry": {k: stat_get(k) for k in GUARD_STAT_NAMES},
            "events": list(self.events),
        }
