"""paddle_tpu.inference.prefix_cache — content-addressed block index +
refcounted allocator for the paged KV cache (ISSUE 11 tentpole).

The paged design (ISSUE 8) was built for this: physical block ids never
enter the attention math — a sequence's cache is a gather over its
block table — so two conversations whose token streams share a prefix
can alias the SAME physical blocks and stay bit-identical by
construction.  This module is the pure host-side bookkeeping that makes
the aliasing safe:

- **content-hash chain index** — a full block (``block_size`` tokens)
  is addressed by ``(chain_hash_of_prefix, its_own_tokens)``.  KV at
  position ``q`` depends on the WHOLE token prefix ``0..q`` (attention
  mixes it into every layer's hidden states), so the chain hash —
  ``h_b = blake2b(h_{b-1} || tokens_b)`` — is the correctness key:
  equal chain hash + equal block tokens  ⇒  bit-equal pool contents.
- **per-block refcounts, with the index itself holding a reference** —
  a block's refcount counts its sequence users PLUS one for its index
  entry.  A block whose ONLY reference is the index sits in an LRU of
  reusable cached blocks: allocation prefers never-cached free blocks,
  then recycles the LRU tail (dropping its index entry).  This is what
  lets cached prefixes outlive the conversations that built them
  without ever leaking: ``free + lru + in_use == capacity`` always.
- **copy-on-write discipline** — the scheduler may WRITE into an
  aliased block only after :meth:`writable` says so; a refcount > 1
  (another sequence, or the index entry, still needs the old contents)
  means the write must fork: allocate, device-copy, remap the block
  table, unref the original.  Because the index counts as a reference,
  a partial-tail alias (a block whose leading tokens match but whose
  tail the new sequence overwrites) forks automatically — the indexed
  original stays valid for future full matches.

Thread-safety: NONE here by design — every method must be called under
the owning scheduler's lock (GenerationServer holds ``self._lock``).
Keeping the cache lock-free avoids a second lock order to verify and
keeps GraftLint's lock graph for the serving tier unchanged.
"""
from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["PrefixCache", "chain_hashes"]


def _block_hash(prev_hex: str, tokens) -> str:
    h = hashlib.blake2b(digest_size=16)
    h.update(prev_hex.encode("ascii"))
    h.update(b"|")
    h.update(",".join(str(int(t)) for t in tokens).encode("ascii"))
    return h.hexdigest()


def chain_hashes(tokens: Sequence[int], block_size: int) -> List[str]:
    """Chain hash per FULL block of ``tokens``: entry ``i`` commits to
    every token in blocks ``0..i`` (the whole prefix, which is what KV
    contents depend on)."""
    out, prev = [], ""
    n_full = len(tokens) // block_size
    for i in range(n_full):
        blk = tokens[i * block_size:(i + 1) * block_size]
        prev = _block_hash(prev, blk)
        out.append(prev)
    return out


class PrefixCache:
    """Block allocator + content index over ``capacity`` physical
    blocks (ids ``first_block .. first_block+capacity-1``; the paged
    pools' trash block 0 is outside the managed range).

    With ``index_enabled=False`` this degrades to the plain free-list
    allocator ISSUE 8 shipped (no entries are ever created, ``lru``
    stays empty), so one accounting path serves both server modes.
    """

    def __init__(self, capacity: int, block_size: int,
                 index_enabled: bool = True, first_block: int = 1):
        self.bs = int(block_size)
        self.capacity = int(capacity)
        self.index_enabled = bool(index_enabled)
        # LIFO free list for locality, same order as the ISSUE 8 list
        self.free: List[int] = list(
            range(first_block + self.capacity - 1, first_block - 1, -1))
        self.refcnt: Dict[int, int] = {}
        # key -> block;  key = (prefix_chain_hash, tokens_tuple)
        self.index: Dict[Tuple[str, tuple], int] = {}
        self.entry_of: Dict[int, Tuple[str, tuple]] = {}
        # prefix_chain_hash -> {tokens_tuple -> block}: the partial-
        # tail lookup only scans entries sharing the prefix hash
        self.by_prefix: Dict[str, Dict[tuple, int]] = {}
        # blocks whose only reference is their index entry (recyclable)
        self.lru: "OrderedDict[int, None]" = OrderedDict()
        self.stats = {"hits": 0, "hit_tokens": 0, "queries": 0,
                      "query_tokens": 0, "inserted": 0, "recycled": 0,
                      "cow_forks": 0}

    # -- allocation ---------------------------------------------------
    def available(self) -> int:
        """Blocks allocatable right now (free + recyclable cached)."""
        return len(self.free) + len(self.lru)

    def in_use(self) -> int:
        return self.capacity - self.available()

    def alloc(self) -> Optional[int]:
        """One block with refcount 1, or None when truly exhausted.
        Prefers never-cached blocks; recycles the LRU-oldest cached
        block (dropping its index entry) under pressure."""
        if self.free:
            b = self.free.pop()
        elif self.lru:
            b, _ = self.lru.popitem(last=False)
            self._drop_entry(b)
            self.stats["recycled"] += 1
        else:
            return None
        self.refcnt[b] = 1
        return b

    def _drop_entry(self, block: int):
        key = self.entry_of.pop(block)
        del self.index[key]
        bp = self.by_prefix[key[0]]
        del bp[key[1]]
        if not bp:
            del self.by_prefix[key[0]]

    def ref(self, block: int):
        """A sequence starts using an indexed block (a prefix hit)."""
        self.refcnt[block] = self.refcnt.get(block, 0) + 1
        self.lru.pop(block, None)      # actively used: not recyclable

    def unref(self, block: int):
        n = self.refcnt.get(block, 0) - 1
        if n < 0:
            raise AssertionError(f"block {block} unref'd below zero")
        self.refcnt[block] = n
        if n == 0:
            del self.refcnt[block]
            self.free.append(block)
        elif n == 1 and block in self.entry_of:
            # only the index still needs it: recyclable, keep contents
            self.lru[block] = None
            self.lru.move_to_end(block)

    def writable(self, block: int) -> bool:
        """May the (single) sequence holding one reference write into
        ``block`` in place?  False means copy-on-write: someone else —
        another sequence or the index entry — still needs the old
        bytes."""
        return self.refcnt.get(block, 0) <= 1

    # -- content index ------------------------------------------------
    def match(self, tokens: Sequence[int]):
        """Longest cached prefix of ``tokens``: full blocks via the
        chain index, then one partial-tail block whose leading tokens
        extend the match.  Returns ``(blocks, cached_tokens)`` WITHOUT
        taking references or touching hit statistics — the scheduler
        may probe the same queue head many times before admission;
        call :meth:`note_query` once per ADMITTED sequence."""
        if not self.index_enabled:
            return [], 0
        blocks: List[int] = []
        prev = ""
        n_full = len(tokens) // self.bs
        matched = 0
        for i in range(n_full):
            blk = tuple(int(t) for t in
                        tokens[i * self.bs:(i + 1) * self.bs])
            prev_next = _block_hash(prev, blk)
            b = self.index.get((prev, blk))
            if b is None:
                break
            blocks.append(b)
            matched += self.bs
            prev = prev_next
        # partial tail: an indexed block under the same prefix hash
        # whose leading tokens extend the match by >= 1 token
        tail = tuple(int(t) for t in tokens[matched:])
        if tail:
            best, best_n = None, 0
            for toks, b in self.by_prefix.get(prev, {}).items():
                n = 0
                for a, c in zip(tail, toks):
                    if a != c:
                        break
                    n += 1
                if n > best_n:
                    best, best_n = b, n
            if best is not None:
                blocks.append(best)
                matched += best_n
        return blocks, matched

    def note_query(self, prompt_tokens: int, hit_tokens: int):
        """Record one admitted sequence's prefix-cache outcome."""
        self.stats["queries"] += 1
        self.stats["query_tokens"] += int(prompt_tokens)
        if hit_tokens:
            self.stats["hits"] += 1
            self.stats["hit_tokens"] += int(hit_tokens)

    def insert(self, tokens: Sequence[int], blocks: Sequence[int]):
        """Index every FULL block of ``tokens`` that isn't indexed yet
        (``blocks[i]`` holds block ``i``'s KV).  Each new entry adds
        the index's reference.  Returns how many entries were added."""
        if not self.index_enabled:
            return 0
        added = 0
        prev = ""
        for i, h in enumerate(chain_hashes(tokens, self.bs)):
            blk = tuple(int(t) for t in
                        tokens[i * self.bs:(i + 1) * self.bs])
            key = (prev, blk)
            prev = h
            if i >= len(blocks):
                break
            b = blocks[i]
            if key in self.index or b in self.entry_of:
                continue       # first content wins; one entry per block
            self.index[key] = b
            self.entry_of[b] = key
            self.by_prefix.setdefault(key[0], {})[key[1]] = b
            self.refcnt[b] = self.refcnt.get(b, 0) + 1
            added += 1
        self.stats["inserted"] += added
        return added

    def flush(self):
        """Drop every index entry (blocks in active use keep their
        sequence references; cached-only blocks return to free)."""
        for b in list(self.entry_of):
            self._drop_entry(b)
            self.lru.pop(b, None)
            self.unref(b)

    # -- introspection -------------------------------------------------
    def snapshot(self) -> Dict:
        return {"free": len(self.free), "cached": len(self.lru),
                "in_use": self.in_use(), "entries": len(self.index),
                "capacity": self.capacity, **self.stats}
