"""paddle_tpu.inference.gateway — self-healing inference federation
(ISSUE 18): a prefix-affinity router over N ``GenerationServer``
replicas whose failover is CORRECT by construction, not best-effort.

One ``GenerationServer`` owns one KV pool, so one SIGKILL loses every
in-flight conversation.  The reference framework's training data plane
survives primary loss with zero lost writes (PR 3/10); this module is
the serving tier's analog, built on three existing contracts:

- **prefix-affinity routing** — requests consistent-hash onto the
  replica ring by the blake2b chain hash of their FIRST prompt block
  (``prefix_cache.chain_hashes``: the same digest the per-replica
  prefix cache indexes KV by), over PR 10's vnode ring
  (``ps_service._build_ring``).  Same-session turns share their first
  block, so multi-turn traffic lands where its KV blocks already live
  and adding/removing a replica remaps ~1/N of sessions, not all.
- **health-checked failover with re-prefill recovery** — the PR 10
  one-shot-RPC pattern per replica (no internal retries; a failure
  closes the socket, arms bounded backoff, and the ring fall-through
  IS the retry).  When a replica dies mid-stream (EOF / timeout /
  SIGKILL), the router re-submits the ORIGINAL prompt ring-order with
  ``replay_tokens=`` everything already delivered: PR 8's replay
  contract (token j's RNG key is ``fold_in(request_key, j-1)``, a pure
  function of stream position) makes the re-run token-identical, and
  ``check_replay`` asserts it live.  The client-visible
  :class:`GenerationStream` never errors — it stalls for the failover
  window and resumes exactly where it left off, zero tokens lost, zero
  duplicated (the router's cursor is the number of tokens it has
  emitted; a replica is only ever asked for what comes after).
- **KV migration for graceful drain** — :meth:`GatewayRouter.drain`
  stops a replica's admission, serializes each live sequence's block
  table + pool rows (:mod:`.migration`), rebuilds them on ring-order
  targets (cheap fallback: ship only tokens and re-prefill), and drops
  the replica from the ring — elastic scale-down for the serving
  fleet.
- **deadline-aware admission, once at the router** — per-tenant
  in-flight token budgets and priorities (PR 12's labeled counters do
  the accounting), the REMAINING deadline propagated on every re-route
  (a failed-over request can never exceed its original budget), typed
  :class:`ServerDraining` / :class:`ServerOverloaded` /
  :class:`RequestTimeout` at the router boundary, and deadline-ordered
  shedding under pressure (the request with the most slack is the one
  shed).

Chaos is the acceptance gate: ``fleet/chaos.py`` gained a gateway kill
site (``kill:gen_step`` SIGKILLs a replica mid-decode via the
scheduler's ``maybe_kill_replica`` hook) and the RPC protocol here
rides the PS framing layer (``_send_msg`` / ``_recv_msg``), so
cut/slow/drop faults on the replica link come free with op-level
matching (``gen_poll`` etc.).  ``tools/chaos_gateway.py`` exits 0 iff
every stream completes token-equal under a seeded fault plan.

Observability: always-on counters ``gw_failovers`` /
``gw_migrated_seqs`` / ``gw_sheds{reason}``, a ``gw_failover_ms``
histogram on /metrics, and flight events ``gw.route`` (progress kind)
/ ``gw.failover`` / ``gw.migrate`` / ``gw.drain`` (postmortem bad
kinds) so a post-incident bundle shows WHERE conversations moved.
"""
from __future__ import annotations

import hashlib
import socket
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..distributed.fleet import chaos as _chaos
from ..distributed.fleet.ps_service import (_build_ring, _recv_msg,
                                            _send_msg)
from ..framework import monitor as _monitor
from ..observability import flight_recorder as _flight
from .generation_server import GenerationStream
from .migration import MigrationUnsupported, export_sequence, \
    import_sequence
from .prefix_cache import chain_hashes
from .serving import (RequestTimeout, ServeError, ServerClosed,
                      ServerDraining, ServerOverloaded)

__all__ = ["GatewayRouter", "LocalReplica", "RemoteReplica",
           "GenerationRpcServer", "ReplicaLost"]


class ReplicaLost(ServeError):
    """A replica stopped answering (EOF, timeout, refused, SIGKILL).
    Internal to the gateway: the router converts it into a failover,
    never into a client-visible error."""


# -- replica-side request book ------------------------------------------

class _ReplicaCore:
    """Maps gateway request ids to live streams on ONE
    ``GenerationServer`` — shared by the in-process and the RPC-served
    replica front ends.  ``base`` is the token prefix the stream was
    re-submitted with (``replay_tokens``): the full view of a request
    on this replica is always ``base + stream.tokens``, so the
    router's cursor arithmetic is identical whether the request lived
    here from the start or failed over in."""

    def __init__(self, server):
        self.server = server
        self._lock = threading.Lock()
        self._reqs: Dict[int, dict] = {}

    def submit(self, grid: int, prompt, kw: dict, base=()):
        stream = self.server.submit(
            np.asarray(prompt, np.int32),
            replay_tokens=list(base) or None, **kw)
        with self._lock:
            self._reqs[grid] = {"stream": stream, "base": list(base)}

    def poll(self, reqs) -> List[dict]:
        out = []
        for grid, cursor in reqs:
            with self._lock:
                ent = self._reqs.get(grid)
            if ent is None:
                out.append({"grid": grid, "toks": [], "done": True,
                            "reason": None, "err": "unknown"})
                continue
            st = ent["stream"]
            # read completion BEFORE tokens: finish_reason is set
            # after the final append, so done=True here guarantees the
            # token list below is complete (the reverse order could
            # report done with the last token missing — a lost token)
            exc = st._exc
            done = st.finish_reason is not None or exc is not None
            err = None
            if exc is not None:
                err = ("timeout" if isinstance(exc, RequestTimeout)
                       else "lost")
            toks = ent["base"] + list(st.tokens)
            out.append({"grid": grid, "toks": toks[int(cursor):],
                        "done": done, "reason": st.finish_reason,
                        "err": err})
            if done:
                with self._lock:
                    self._reqs.pop(grid, None)
        return out

    def cancel(self, grid: int) -> bool:
        with self._lock:
            ent = self._reqs.pop(grid, None)
        if ent is None:
            return False
        return self.server.cancel(ent["stream"].request_id,
                                  reason="gw_cancel")

    def drain(self):
        self.server.drain_begin()

    def export(self, grid: int) -> Optional[dict]:
        with self._lock:
            ent = self._reqs.get(grid)
        if ent is None:
            return None
        blob = export_sequence(self.server, ent["stream"].request_id)
        if blob is not None:
            # None means the sequence finished in the gap since the
            # caller's last poll — keep the record so that poll can
            # still deliver the tail tokens + the finish reason
            with self._lock:
                self._reqs.pop(grid, None)
        return blob

    def import_(self, grid: int, blob: dict, base=()):
        stream = import_sequence(self.server, blob)
        with self._lock:
            self._reqs[grid] = {"stream": stream, "base": list(base)}

    def ping(self) -> dict:
        return {"ok": True, "draining": self.server.draining}


class LocalReplica:
    """In-process replica: the duck-typed replica interface over a
    ``GenerationServer`` in this process (unit tests, single-host
    multi-replica).  ``kill()`` makes it LOOK SIGKILLed from the
    router's side: every subsequent call raises :class:`ReplicaLost`
    immediately, and the server is torn down in the background without
    the router ever seeing its final state.  With ``owns_server=False``
    the server outlives ``kill()`` — the loss is a pure partition (the
    router sees a dead replica, the process is fine), which also lets
    tests share one warm server across many simulated losses."""

    def __init__(self, name: str, server, owns_server: bool = True):
        self.name = name
        self.server = server
        self._owns_server = owns_server
        self._core = _ReplicaCore(server)
        self._dead = False

    def _check(self):
        if self._dead:
            raise ReplicaLost(f"replica {self.name} was killed")

    def submit(self, grid, prompt, kw, base=()):
        self._check()
        try:
            self._core.submit(grid, prompt, kw, base)
        except ServerClosed as e:
            raise ReplicaLost(f"replica {self.name}: {e}") from e

    def poll(self, reqs):
        self._check()
        return self._core.poll(reqs)

    def cancel(self, grid):
        self._check()
        return self._core.cancel(grid)

    def drain(self):
        self._check()
        self._core.drain()

    def export(self, grid):
        self._check()
        return self._core.export(grid)

    def import_(self, grid, blob, base=()):
        self._check()
        self._core.import_(grid, blob, base)

    def ping(self):
        self._check()
        try:
            return self._core.ping()
        except ServerClosed as e:
            raise ReplicaLost(f"replica {self.name}: {e}") from e

    def kill(self):
        self._dead = True
        if self._owns_server:
            threading.Thread(target=self.server.stop,
                             daemon=True).start()


# -- RPC front end (rides the PS framing layer) -------------------------

class GenerationRpcServer:
    """Socket front end for one ``GenerationServer`` replica.  The
    protocol rides :mod:`~paddle_tpu.distributed.fleet.ps_service`'s
    ``_send_msg`` / ``_recv_msg`` framing, which means every gateway op
    (``gen_submit`` / ``gen_poll`` / ``gen_export`` / ...) is already a
    chaos injection site: seeded cut/slow/drop plans match it by op
    name with zero new plumbing, and ``crash:gen_poll`` works exactly
    like the PS server's crash site (``plan.on_serve``)."""

    def __init__(self, server, host: str = "127.0.0.1", port: int = 0):
        self._core = _ReplicaCore(server)
        self._server = server
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, int(port)))
        self._sock.listen(64)
        self.host, self.port = self._sock.getsockname()[:2]
        self._running = True
        self._accept = threading.Thread(target=self._accept_loop,
                                        name="gen-rpc-accept",
                                        daemon=True)
        self._accept.start()

    def _accept_loop(self):
        while self._running:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn):
        plan = _chaos.active()
        try:
            while self._running:
                try:
                    msg = _recv_msg(conn)
                except (OSError, ConnectionError):
                    break
                if msg is None:
                    break
                op = msg.get("op", "?")
                if plan is not None:
                    plan.on_serve(msg)       # may crash the process
                    plan.set_context(op)     # replies match <op>_reply
                try:
                    rep = self._handle(op, msg)
                except (ServerDraining, ServerOverloaded,
                        RequestTimeout, MigrationUnsupported) as e:
                    # typed, retry-elsewhere errors travel by name so
                    # the client re-raises the SAME type at its side
                    rep = {"ok": False, "kind": type(e).__name__,
                           "error": str(e)}
                except Exception as e:   # noqa: BLE001 — to the wire
                    rep = {"ok": False, "kind": "ServeError",
                           "error": f"{type(e).__name__}: {e}"}
                try:
                    _send_msg(conn, rep)
                except (OSError, ConnectionError):
                    break
                finally:
                    if plan is not None:
                        plan.set_context(None)
        finally:
            if plan is not None:
                plan.set_context(None)
            try:
                conn.close()
            except OSError:
                pass

    def _handle(self, op: str, msg: dict) -> dict:
        if op == "gen_submit":
            self._core.submit(msg["grid"],
                              np.asarray(msg["prompt"], np.int32),
                              msg["kw"], msg.get("base") or [])
            return {"ok": True}
        if op == "gen_poll":
            return {"ok": True, "results": self._core.poll(msg["reqs"])}
        if op == "gen_cancel":
            return {"ok": True,
                    "cancelled": self._core.cancel(msg["grid"])}
        if op == "gen_drain":
            self._core.drain()
            return {"ok": True}
        if op == "gen_export":
            return {"ok": True, "blob": self._core.export(msg["grid"])}
        if op == "gen_import":
            self._core.import_(msg["grid"], msg["blob"],
                               msg.get("base") or [])
            return {"ok": True}
        if op == "gen_ping":
            return self._core.ping()
        if op == "gen_stop":
            # reply first, THEN die: the driver's shutdown must not
            # read an EOF it would mistake for a crash
            threading.Thread(target=self._stop_all,
                             daemon=True).start()
            return {"ok": True}
        return {"ok": False, "kind": "ServeError",
                "error": f"unknown gateway op {op!r}"}

    def _stop_all(self):
        time.sleep(0.05)
        self.stop()
        self._server.stop()

    def stop(self):
        self._running = False
        try:
            self._sock.close()
        except OSError:
            pass


class RemoteReplica:
    """Socket client for a :class:`GenerationRpcServer` replica — the
    PR 10 one-shot-RPC pattern: one persistent connection, NO internal
    retries.  A failure closes the socket, bumps a bounded exponential
    backoff, and raises :class:`ReplicaLost`; the router's ring
    fall-through is the retry, which is what lets a request pinned to
    a dead replica rotate without ever surfacing a failed call."""

    # the router never calls a replica while holding its own lock, but
    # the hierarchy is still declared so GraftLint can prove it:
    # lint: lock-order: GatewayRouter._lock -> RemoteReplica._lock

    def __init__(self, name: str, host: str, port: int,
                 connect_timeout: float = 2.0,
                 rpc_timeout: float = 60.0):
        self.name = name
        self._ep = (host, int(port))
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._fails = 0
        self._down_until = 0.0
        self._connect_timeout = float(connect_timeout)
        self._rpc_timeout = float(rpc_timeout)

    def _call(self, op: str, payload: dict) -> dict:
        plan = _chaos.active()
        with self._lock:
            if time.monotonic() < self._down_until:
                raise ReplicaLost(
                    f"replica {self.name} in backoff "
                    f"({self._fails} consecutive failures)")
            sock = self._sock
            try:
                if sock is None:
                    if plan is not None:
                        plan.check_connect(self._ep)
                    sock = socket.create_connection(
                        self._ep, timeout=self._connect_timeout)
                    self._sock = sock
                sock.settimeout(self._rpc_timeout)
                msg = dict(payload)
                msg["op"] = op
                _send_msg(sock, msg)
                rep = _recv_msg(sock)
                if rep is None:
                    raise ConnectionError(
                        "replica closed the connection")
            except (OSError, ConnectionError, socket.timeout) as e:
                self._sock = None
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
                self._fails += 1
                self._down_until = time.monotonic() + min(
                    0.25 * (2 ** min(self._fails - 1, 5)), 5.0)
                raise ReplicaLost(
                    f"replica {self.name}: {e}") from e
            self._fails = 0
        if isinstance(rep, dict) and rep.get("ok") is False:
            kind = rep.get("kind", "ServeError")
            err = rep.get("error", "")
            cls = {"ServerDraining": ServerDraining,
                   "ServerOverloaded": ServerOverloaded,
                   "RequestTimeout": RequestTimeout,
                   "MigrationUnsupported": MigrationUnsupported,
                   }.get(kind, ServeError)
            raise cls(f"replica {self.name}: {err}")
        return rep

    def submit(self, grid, prompt, kw, base=()):
        self._call("gen_submit", {
            "grid": int(grid),
            "prompt": np.asarray(prompt, np.int32),
            "kw": kw, "base": list(base)})

    def poll(self, reqs):
        return self._call("gen_poll",
                          {"reqs": [[int(g), int(c)]
                                    for g, c in reqs]})["results"]

    def cancel(self, grid):
        return self._call("gen_cancel",
                          {"grid": int(grid)}).get("cancelled", False)

    def drain(self):
        self._call("gen_drain", {})

    def export(self, grid):
        return self._call("gen_export", {"grid": int(grid)}).get("blob")

    def import_(self, grid, blob, base=()):
        self._call("gen_import", {"grid": int(grid), "blob": blob,
                                  "base": list(base)})

    def ping(self):
        return self._call("gen_ping", {})

    def stop_remote(self):
        self._call("gen_stop", {})


# -- the router ---------------------------------------------------------

class _GwReq:
    """Router-side request record (one per client stream)."""

    __slots__ = ("grid", "prompt", "kw", "stream", "emitted", "replica",
                 "pos", "deadline", "t_submit", "failovers", "done",
                 "tenant", "cost", "migrating", "placed")

    def __init__(self, grid, prompt, kw, pos, deadline, tenant, cost):
        self.grid = grid
        self.prompt = prompt              # np.int32 [L]
        self.kw = kw                      # submit kwargs sans timeout_s
        self.stream = GenerationStream(grid)
        self.emitted: List[int] = []      # delivered to the client
        self.replica: Optional[str] = None
        self.pos = pos                    # ring position (routing key)
        self.deadline = deadline          # monotonic; NEVER re-anchored
        self.t_submit = time.monotonic()
        self.failovers = 0
        self.done = False
        self.tenant = tenant
        self.cost = cost                  # prompt + max_new (budget)
        self.migrating = False            # drain owns it, pump skips
        self.placed = False               # ever placed on a replica;
        # until then submit() owns placement and the pump's orphan
        # sweep must NOT race it (a double place = a leaked sequence)


class GatewayRouter:
    """Prefix-affinity gateway over N ``GenerationServer`` replicas
    (duck-typed: :class:`LocalReplica` and :class:`RemoteReplica` mix
    freely).  See the module docstring for the recovery contracts.

    Usage::

        router = GatewayRouter([LocalReplica("a", sa),
                                RemoteReplica("b", host, port)],
                               block_size=16, seed=0)
        router.start()
        stream = router.submit(prompt_ids, max_new_tokens=64, seed=7)
        toks = stream.result()       # survives replica SIGKILL
        router.drain("a")            # graceful scale-down
        router.stop()

    ``tenant_budgets`` maps tenant -> max in-flight tokens
    (prompt + max_new summed over that tenant's live requests); past
    it, submits shed typed with ``gw_sheds{reason="tenant_budget"}``.
    ``max_pending`` bounds total in-flight requests; at the cap the
    request with the MOST remaining deadline is the one shed
    (deadline-ordered shedding — the tightest deadlines keep their
    slots)."""

    # never hold the router lock across a replica RPC; declared so the
    # linter can prove the hierarchy stays acyclic:
    # lint: lock-order: GatewayRouter._lock -> RemoteReplica._lock

    def __init__(self, replicas: Sequence, *, block_size: int = 16,
                 seed: int = 0, request_timeout_s: float = 300.0,
                 tenant_budgets: Optional[Dict[str, int]] = None,
                 max_pending: int = 256,
                 poll_interval_s: float = 0.002):
        reps = list(replicas)
        self._replicas = {r.name: r for r in reps}
        if len(self._replicas) != len(reps):
            raise ValueError("replica names must be unique")
        if not self._replicas:
            raise ValueError("need at least one replica")
        self._bs = int(block_size)
        self._seed = int(seed)
        self._timeout_s = float(request_timeout_s)
        self._budgets = dict(tenant_budgets or {})
        self._max_pending = int(max_pending)
        self._poll_s = float(poll_interval_s)
        self._lock = threading.Lock()
        self._names: List[str] = sorted(self._replicas)
        self._ring = _build_ring(self._names)
        self._reqs: Dict[int, _GwReq] = {}
        self._grid = 0
        self._tenant_used: Dict[str, int] = {}
        self._down: Dict[str, float] = {}
        self._down_fails: Dict[str, int] = {}
        self._draining: set = set()
        self._stats = {"submitted": 0, "finished": 0, "failovers": 0,
                       "migrated": 0, "deadline_sheds": 0,
                       "sheds": {}, "routed": {}}
        self._running = False
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------
    def start(self) -> "GatewayRouter":
        if self._running:
            return self
        self._running = True
        self._thread = threading.Thread(target=self._pump,
                                        name="gateway-router",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        if not self._running:
            return
        self._running = False
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None
        with self._lock:
            victims = [r for r in self._reqs.values() if not r.done]
            self._reqs.clear()
        for r in victims:
            r.done = True
            r.stream._fail(ServerClosed("gateway stopped"))

    def __enter__(self) -> "GatewayRouter":
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- routing ------------------------------------------------------
    def _route_pos(self, prompt: np.ndarray) -> int:
        """Ring position for a prompt: the chain hash of its FIRST
        full block (stable as the conversation grows — turn N+1 keeps
        turn N's affinity), blake2b over the raw tokens when the
        prompt is shorter than one block."""
        hs = chain_hashes(prompt.tolist(), self._bs)
        if hs:
            h = int(hs[0][:16], 16)
        else:
            h = int.from_bytes(
                hashlib.blake2b(prompt.tobytes(),
                                digest_size=8).digest(), "big")
        pts, _ = self._ring
        if len(pts) == 0:
            return 0
        return int(np.searchsorted(pts, np.uint64(h), side="left")
                   % len(pts))

    def _candidates(self, pos: int, exclude=()) -> List[str]:
        """Replica names clockwise from ``pos``, deduplicated, with
        draining / backed-off / excluded members skipped — the
        fall-through order that IS the retry policy."""
        pts, owners = self._ring
        now = time.monotonic()
        order: List[str] = []
        for k in range(len(pts)):
            name = self._names[int(owners[(pos + k) % len(pts)])]
            if name not in order:
                order.append(name)
        return [nm for nm in order
                if nm not in exclude and nm not in self._draining
                and self._down.get(nm, 0.0) <= now]

    def route_owner(self, prompt) -> Optional[str]:
        """The replica a fresh submit of ``prompt`` would try first
        (affinity introspection for tests/tools)."""
        p = np.asarray(prompt, np.int32).reshape(-1)
        with self._lock:
            cands = self._candidates(self._route_pos(p))
        return cands[0] if cands else None

    def _mark_down(self, name: str):
        with self._lock:
            self._down_fails[name] = self._down_fails.get(name, 0) + 1
            self._down[name] = time.monotonic() + min(
                0.25 * (2 ** min(self._down_fails[name] - 1, 5)), 5.0)

    # -- admission ----------------------------------------------------
    def submit(self, prompt, max_new_tokens: int = 32,
               do_sample: bool = False, temperature: float = 1.0,
               top_k: int = 0, top_p: float = 1.0,
               eos_token_id: Optional[int] = None,
               seed: Optional[int] = None, priority: int = 0,
               timeout_s: Optional[float] = None,
               tenant: Optional[str] = None) -> GenerationStream:
        """Route one generation request; returns a stream that
        survives replica loss.  The RNG seed is pinned HERE (the
        user's, or derived from the gateway seed + request id): every
        replica incarnation of this request samples the same stream,
        which is what makes failover token-identical.  Raises
        :class:`ServerDraining` (every replica draining),
        :class:`ServerOverloaded` (tenant budget / pressure shed /
        no replica accepting)."""
        if not self._running:
            raise ServerClosed("gateway not started")
        p = np.asarray(prompt.numpy() if hasattr(prompt, "numpy")
                       else prompt).astype(np.int32).reshape(-1)
        if p.size < 1:
            raise ValueError("empty prompt")
        to = self._timeout_s if timeout_s is None else float(timeout_s)
        cost = int(p.size) + int(max_new_tokens)
        shed_victim: Optional[_GwReq] = None
        with self._lock:
            deadline = time.monotonic() + to
            if tenant is not None and tenant in self._budgets:
                used = self._tenant_used.get(tenant, 0)
                if used + cost > self._budgets[tenant]:
                    shed = "tenant_budget"
                    self._note_shed_locked(shed)
                    raise_after = (ServerOverloaded(
                        f"tenant {tenant!r} budget "
                        f"{self._budgets[tenant]} tokens: {used} in "
                        f"flight + {cost} requested — shed"), shed)
                    # fallthrough to raise outside the lock
                    pending = None
                else:
                    raise_after = None
                    pending = [r for r in self._reqs.values()
                               if not r.done]
            else:
                raise_after = None
                pending = [r for r in self._reqs.values()
                           if not r.done]
            if raise_after is None and len(pending) >= self._max_pending:
                # deadline-ordered shedding: the request with the most
                # slack loses its slot (tightest deadlines ride out
                # the pressure)
                slackest = max(pending, key=lambda r: r.deadline)
                if slackest.deadline > deadline:
                    shed_victim = slackest
                    slackest.done = True
                    self._note_shed_locked("pressure")
                else:
                    self._note_shed_locked("pressure")
                    raise_after = (ServerOverloaded(
                        f"gateway at max_pending={self._max_pending} "
                        "and every in-flight request has a tighter "
                        "deadline — shed"), "pressure")
            if raise_after is None:
                self._grid += 1
                grid = self._grid
                rseed = (int(seed) if seed is not None
                         else self._seed * 1000003 + grid)
                kw = dict(max_new_tokens=int(max_new_tokens),
                          do_sample=bool(do_sample),
                          temperature=float(temperature),
                          top_k=int(top_k), top_p=float(top_p),
                          eos_token_id=eos_token_id, seed=rseed,
                          priority=int(priority), tenant=tenant)
                req = _GwReq(grid, p, kw, self._route_pos(p), deadline,
                             tenant, cost)
                self._reqs[grid] = req
                self._stats["submitted"] += 1
                if tenant is not None:
                    self._tenant_used[tenant] = \
                        self._tenant_used.get(tenant, 0) + cost
        if shed_victim is not None:
            self._finalize_shed(shed_victim, "pressure")
        if raise_after is not None:
            exc, reason = raise_after
            _monitor.stat_add("gw_sheds", labels={"reason": reason})
            raise exc from None
        name = self._try_place(req, exclude=set())
        if name is None:
            with self._lock:
                req.done = True
                self._reqs.pop(grid, None)
                if tenant is not None:
                    self._tenant_used[tenant] -= cost
                all_draining = bool(self._replicas) and all(
                    nm in self._draining for nm in self._replicas)
                self._note_shed_locked("no_replica")
            _monitor.stat_add("gw_sheds", labels={"reason": "no_replica"})
            if all_draining:
                raise ServerDraining(
                    "every replica is draining — the fleet is "
                    "scaling down, retry against its successor")
            raise ServerOverloaded(
                "no replica accepted the request (all down, draining "
                "or overloaded) — back off and retry")
        return req.stream

    def _note_shed_locked(self, reason: str):
        self._stats["sheds"][reason] = \
            self._stats["sheds"].get(reason, 0) + 1

    def _finalize_shed(self, req: _GwReq, reason: str):
        """Fail a shed victim's stream outside the router lock."""
        _monitor.stat_add("gw_sheds", labels={"reason": reason})
        if req.replica is not None:
            rep = self._replicas.get(req.replica)
            if rep is not None:
                try:
                    rep.cancel(req.grid)
                except (ReplicaLost, ServeError):
                    pass
        with self._lock:
            self._reqs.pop(req.grid, None)
            if req.tenant is not None:
                self._tenant_used[req.tenant] = \
                    self._tenant_used.get(req.tenant, 0) - req.cost
        req.stream._fail(ServerOverloaded(
            f"request {req.grid} shed under pressure "
            f"({reason}: a tighter-deadline request took its slot)"))

    # -- placement ----------------------------------------------------
    def _try_place(self, req: _GwReq, exclude) -> Optional[str]:
        """Ring-order placement (the fall-through IS the retry).  The
        REMAINING deadline travels with every attempt, so a re-routed
        request keeps its original budget."""
        if self._finish_if_complete(req):
            return req.replica
        with self._lock:
            cands = self._candidates(req.pos, exclude)
        for name in cands:
            remaining = req.deadline - time.monotonic()
            if remaining <= 0:
                return None         # pump's deadline check sheds it
            kw = dict(req.kw)
            kw["timeout_s"] = remaining
            rep = self._replicas[name]
            try:
                rep.submit(req.grid, req.prompt, kw,
                           base=req.emitted)
            except (ServerDraining, ServerOverloaded):
                continue
            except ReplicaLost:
                self._mark_down(name)
                continue
            with self._lock:
                req.replica = name
                req.placed = True
                self._stats["routed"][name] = \
                    self._stats["routed"].get(name, 0) + 1
            _flight.record("gw.route", grid=req.grid, replica=name,
                           failovers=req.failovers,
                           emitted=len(req.emitted))
            _flight.progress("gw.route")
            return name
        return None

    def _finish_if_complete(self, req: _GwReq) -> bool:
        """A request whose delivered tokens already satisfy its stop
        condition (the dead replica finished it but the 'done' poll
        was lost) must NOT be re-placed: ``replay_tokens`` of a
        complete stream is a contract violation on the replica."""
        eos = req.kw.get("eos_token_id")
        if len(req.emitted) >= req.kw["max_new_tokens"]:
            self._finish(req, "length")
            return True
        if eos is not None and req.emitted \
                and req.emitted[-1] == eos:
            self._finish(req, "eos")
            return True
        return False

    # -- the pump: poll / deliver / failover --------------------------
    def _pump(self):
        try:
            while self._running:
                self._pump_once()
                time.sleep(self._poll_s)
        except BaseException as e:   # noqa: BLE001 — fail streams loudly
            with self._lock:
                victims = [r for r in self._reqs.values()
                           if not r.done]
                self._reqs.clear()
                self._running = False
            for r in victims:
                r.done = True
                r.stream._fail(ServeError(
                    f"gateway pump died: {e!r}"))
            raise

    def _pump_once(self):
        with self._lock:
            by_rep: Dict[str, List[_GwReq]] = {}
            orphans: List[_GwReq] = []
            for r in self._reqs.values():
                if r.done or r.migrating:
                    continue
                if r.replica is None:
                    if r.placed:    # never-placed = submit() owns it
                        orphans.append(r)
                else:
                    by_rep.setdefault(r.replica, []).append(r)
        now = time.monotonic()
        for name, reqs in by_rep.items():
            rep = self._replicas.get(name)
            expired = [r for r in reqs if now > r.deadline]
            live = [r for r in reqs if now <= r.deadline]
            for r in expired:
                try:
                    rep.cancel(r.grid)
                except (ReplicaLost, ServeError):
                    pass
                self._fail_deadline(r)
            if not live:
                continue
            try:
                results = rep.poll([(r.grid, len(r.emitted))
                                    for r in live])
            except ReplicaLost:
                self._mark_down(name)
                for r in live:
                    self._failover(r, name)
                continue
            by_grid = {res["grid"]: res for res in results}
            for r in live:
                res = by_grid.get(r.grid)
                if res is None:
                    continue
                if res["err"] in ("lost", "unknown"):
                    # the replica process survives but this stream
                    # died (scheduler error / server stopped / record
                    # gone after a restart): recover it elsewhere
                    self._failover(r, name)
                    continue
                for t in res["toks"]:
                    r.emitted.append(int(t))
                    r.stream._emit(int(t))
                if res["err"] == "timeout":
                    self._fail_deadline(r)
                elif res["done"]:
                    self._finish(r, res["reason"] or "length")
        for r in orphans:
            if now > r.deadline:
                self._fail_deadline(r)
            elif not self._finish_if_complete(r):
                # a failover that found no home yet (double failure /
                # all replicas briefly down): keep trying ring-order
                # each round until the deadline says stop
                self._try_place(r, exclude=set())

    def _failover(self, req: _GwReq, dead_name: Optional[str]):
        req.failovers += 1
        req.replica = None
        with self._lock:
            self._stats["failovers"] += 1
        _monitor.stat_add("gw_failovers")
        _flight.record("gw.failover", grid=req.grid,
                       replica=dead_name, n=req.failovers,
                       emitted=len(req.emitted),
                       remaining_s=round(
                           req.deadline - time.monotonic(), 3))
        if time.monotonic() > req.deadline:
            self._fail_deadline(req)
            return
        t0 = time.perf_counter()
        name = self._try_place(
            req, exclude={dead_name} if dead_name else set())
        if name is not None and _monitor.metrics_enabled():
            _monitor.hist_observe("gw_failover_ms",
                                  (time.perf_counter() - t0) * 1e3)
        # no home right now: the pump's orphan sweep keeps retrying

    def _fail_deadline(self, req: _GwReq):
        if req.done:
            return
        req.done = True
        _monitor.stat_add("gw_sheds", labels={"reason": "deadline"})
        with self._lock:
            self._stats["deadline_sheds"] += 1
            self._note_shed_locked("deadline")
            self._reqs.pop(req.grid, None)
            if req.tenant is not None:
                self._tenant_used[req.tenant] = \
                    self._tenant_used.get(req.tenant, 0) - req.cost
        req.stream._fail(RequestTimeout(
            f"request {req.grid} spent its whole deadline "
            f"({req.failovers} failovers, {len(req.emitted)} tokens "
            "delivered) — the deadline is anchored at submit and "
            "survives re-routing"))

    def _finish(self, req: _GwReq, reason: str):
        if req.done:
            return
        req.done = True
        with self._lock:
            self._stats["finished"] += 1
            self._reqs.pop(req.grid, None)
            if req.tenant is not None:
                self._tenant_used[req.tenant] = \
                    self._tenant_used.get(req.tenant, 0) - req.cost
        req.stream._end(reason)

    # -- graceful drain (KV migration) --------------------------------
    def drain(self, name: str) -> int:
        """Gracefully remove replica ``name``: stop its admission,
        migrate every live conversation to ring-order survivors (KV
        blob when the target has capacity, token replay otherwise),
        and drop it from the ring.  Returns how many sequences moved.
        The drained replica's server keeps running (caller stops it)
        — it is simply no longer addressable."""
        if name not in self._replicas:
            raise KeyError(f"unknown replica {name!r}")
        rep = self._replicas[name]
        with self._lock:
            self._draining.add(name)
            survivors = [n for n in sorted(self._replicas)
                         if n not in self._draining]
            self._names = survivors or sorted(self._replicas)
            self._ring = _build_ring(self._names)
            mine = [r for r in self._reqs.values()
                    if r.replica == name and not r.done]
            for r in mine:
                r.migrating = True    # pump hands them off to us
        _flight.record("gw.drain", replica=name, live=len(mine))
        try:
            rep.drain()
        except ReplicaLost:
            # died as we drained it: plain failover recovers the reqs
            self._mark_down(name)
            for r in mine:
                r.migrating = False
                self._failover(r, name)
            return 0
        moved = 0
        for r in mine:
            moved += int(self._migrate_one(r, rep, name))
            r.migrating = False
        return moved

    def _migrate_one(self, req: _GwReq, rep, src: str) -> bool:
        # catch up first: every token the source emitted must reach
        # the client (and the cursor) before the sequence moves
        try:
            res = rep.poll([(req.grid, len(req.emitted))])[0]
            if res["err"] in ("lost", "unknown"):
                self._failover(req, src)
                return False
            for t in res["toks"]:
                req.emitted.append(int(t))
                req.stream._emit(int(t))
            if res["err"] == "timeout":
                self._fail_deadline(req)
                return False
            if res["done"]:
                self._finish(req, res["reason"] or "length")
                return False
        except ReplicaLost:
            self._failover(req, src)
            return False
        if time.monotonic() > req.deadline:
            try:
                rep.cancel(req.grid)
            except (ReplicaLost, ServeError):
                pass
            self._fail_deadline(req)
            return False
        blob = None
        try:
            blob = rep.export(req.grid)
        except (ReplicaLost, ServeError):
            blob = None
        if blob is None:
            # the sequence finished (or vanished) between the catch-up
            # poll and the export — one more poll collects the tail
            try:
                res = rep.poll([(req.grid, len(req.emitted))])[0]
            except ReplicaLost:
                self._failover(req, src)
                return False
            if res["err"] in ("lost", "unknown", "timeout"):
                self._failover(req, src)
                return False
            for t in res["toks"]:
                req.emitted.append(int(t))
                req.stream._emit(int(t))
            if res["done"]:
                self._finish(req, res["reason"] or "length")
            else:
                self._failover(req, src)
            return False
        # the export is the authoritative cut: every token the source
        # generated past the catch-up poll is in the blob but NOT in
        # the cursor — deliver those now, or the import target would
        # treat them as already-streamed and they'd be lost
        for t in blob["generated"][len(req.emitted):]:
            req.emitted.append(int(t))
            req.stream._emit(int(t))
        if self._finish_if_complete(req):
            return False
        path = None
        if blob is not None and blob.get("kv") is not None:
            # the gateway owns deadline truth: whatever the source
            # measured, the target gets the ROUTER's remaining budget
            blob["deadline_remaining"] = max(
                req.deadline - time.monotonic(), 0.0)
            with self._lock:
                cands = self._candidates(req.pos, exclude={src})
            for nm in cands:
                try:
                    self._replicas[nm].import_(req.grid, blob,
                                               base=req.emitted)
                    with self._lock:
                        req.replica = nm
                    path = "kv"
                    break
                except (MigrationUnsupported, ServerOverloaded,
                        ServerDraining):
                    continue
                except ReplicaLost:
                    self._mark_down(nm)
                    continue
        if path is None:
            # cheap fallback: tokens only, re-prefill + replay on the
            # target (export already detached it from the source)
            req.replica = None
            if self._finish_if_complete(req):
                return False
            path = "replay" if self._try_place(
                req, exclude={src}) is not None else None
        if path is None:
            return False    # orphan: the pump keeps retrying it
        with self._lock:
            self._stats["migrated"] += 1
        _monitor.stat_add("gw_migrated_seqs")
        _flight.record("gw.migrate", grid=req.grid, src=src,
                       dst=req.replica, path=path,
                       tokens=len(req.emitted))
        return True

    # -- introspection ------------------------------------------------
    def stats(self) -> Dict:
        with self._lock:
            s = {k: (dict(v) if isinstance(v, dict) else v)
                 for k, v in self._stats.items()}
            s["pending"] = sum(1 for r in self._reqs.values()
                               if not r.done)
            s["replicas"] = sorted(self._replicas)
            s["ring"] = list(self._names)
            s["draining"] = sorted(self._draining)
            now = time.monotonic()
            s["down"] = sorted(n for n, t in self._down.items()
                               if t > now)
            s["tenant_inflight_tokens"] = dict(self._tenant_used)
        return s
