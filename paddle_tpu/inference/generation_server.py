"""paddle_tpu.inference.generation_server — the inference gateway:
continuous-batching LLM serving with copy-on-write prefix sharing,
batched prefill, and speculative decoding (ISSUE 8 engine, grown by
ISSUE 11; ROADMAP item 4).

``PredictorServer`` micro-batches FIXED-shape requests; generative
decoding is the other regime: every sequence advances one token per
model call, sequences finish at different times, and a dedicated
``[B, Smax]`` KV buffer per conversation would cap concurrency at
HBM / (Smax * layers * heads).  This module is the Orca-style
iteration-level scheduler + vLLM-style paged KV cache built on the
same AOT discipline as the rest of ``inference/``:

- **block-paged KV cache** — K/V live in per-layer pools
  ``[num_blocks, block_size, KH, D]`` shared by every sequence; a
  sequence owns a list of physical blocks and its cache reads are a
  gather over its block table (``LlamaAttention.forward_paged``).
  Physical block 0 is the TRASH block: never allocated, the target of
  masked writes (prompt padding, idle decode slots), never read (the
  slot <= position mask).  Thousands of conversations share one HBM
  budget and freeing is O(blocks), not O(bytes).
- **copy-on-write prefix sharing** (``prefix_cache=True``) — a
  content-hash chain index over the pools (``prefix_cache.py``) maps
  full blocks of token prefix to physical blocks; a new prompt's
  cached prefix blocks are ALIASED (refcounted) instead of re-
  prefilled, so a shared system prompt is ONE set of physical blocks
  across every conversation and prefill only processes the uncached
  suffix.  A write into a shared block (refcount > 1, which includes
  the index's own reference) forks it first: allocate, device-copy,
  remap the block table — the trash-block and slot<=position
  invariants are untouched because tables only ever remap.  Prefill
  on a prefix-sharing server runs the CHUNKED program (the cache-
  gather attention path) for cold prompts too, so cold and warm runs
  of the same stream are bit-identical (the flash prefill path is a
  different floating-point formulation — measured ~1e-4 apart on this
  container — so it stays reserved for prefix_cache=False servers).
- **batched prefill** — prefill compiles one program per (power-of-2
  prompt bucket, power-of-2 batch <= ``max_prefill_batch``) shape, so
  a burst of short prompts costs ONE dispatch instead of B; padding
  rows write only to the trash block.  Verified bit-equal to B=1
  prefill row-for-row (same program family, row-independent math).
- **speculative decoding** (``draft_model=``) — a draft model rides
  the same block tables with its own (smaller) pools; each iteration
  it proposes up to ``spec_k`` tokens autoregressively, and the
  target model scores all of them in ONE verify forward (an S=k+1
  block through the cache-gather attention — bit-identical per
  position to S=1 decode, measured).  Deterministic positional-
  stream acceptance keeps the output BIT-IDENTICAL to plain decode:
  the verify program samples the target's own token at every
  position with the same ``fold_in(request_key, position)`` stream
  plain decode uses, a proposal is accepted iff it EQUALS that
  token, and the first mismatch simply emits the target's token (the
  classical stochastic accept/resample of Leviathan et al. trades
  that bit-identity for a higher accept rate; this repo's replay and
  eviction contracts are built on bit-identity, so determinism
  wins).  Rejected tokens' pool writes are invisible by
  construction: slot index == position, the next write at that
  position lands first, and slot <= position masks the rest.
- **iteration-level scheduling** — admission/eviction decisions happen
  every decode step, not per request: finished sequences free their
  blocks immediately and waiting requests are admitted mid-flight.
  DECODE is ONE fixed-shape program over all ``num_slots`` batch slots
  regardless of how many are live — steady state never retraces
  (``num_compiles()`` is the proof, same contract as ``Predictor``).
- **typed shed semantics** shared with ``PredictorServer``
  (:class:`ServerOverloaded` at the waiting-queue cap,
  :class:`RequestTimeout` for requests whose deadline passes while
  waiting) extended with **block-pool-exhaustion eviction**: when a
  running sequence needs a block and the pool is dry, the
  lowest-priority sequence is evicted (blocks freed, back to the
  waiting queue) and later re-admitted.
- **bit-identical re-admission** — re-admission re-runs the ORIGINAL
  prompt through the same prefill family (same bucket, same inputs =>
  identical K/V and logits; with prefix sharing the cached prefix is
  aliased back and only the suffix recomputes), then replays the
  already-emitted tokens through the normal decode/verify program with
  the sampled token overridden by the stored one.  The RNG key for
  token j is ``fold_in(request_key, j-1)``, a pure function of the
  stream position, so the RNG stream position survives eviction by
  construction.
- **streaming responses** — :meth:`GenerationServer.submit` returns a
  :class:`GenerationStream` immediately; tokens arrive on it as each
  decode step completes (iterate it, or ``result()`` to block for the
  full continuation).

Observability rides the existing seams: serve histograms
(``decode_step_ms`` / ``prefill_ms`` / ``serve_ttft_ms``), counters
and gauges in the StatRegistry (always-on ``serve_prefix_hits`` /
``serve_cow_forks`` / ``serve_spec_proposed`` / ``serve_spec_accepted``
counters; ``serve_prefix_hit_rate`` / ``serve_spec_accept_rate``
gauges on the /metrics endpoint), and flight-recorder events
(``serve.admit`` / ``serve.evict`` / ``serve.stream_end`` /
``serve.prefix_hit`` / ``serve.cow_fork`` + sampled ``serve.decode``
and ``serve.spec_verify``) so ``tools/postmortem.py`` can autopsy a
pool-exhaustion shed.

ISSUE 12 (fleet observatory) adds the REQUEST dimension:
``submit(tenant=...)`` tags a request for usage accounting (always-on
labeled counters ``serve_tenant_tokens_in/out`` /
``serve_tenant_sheds`` / ``serve_tenant_prefix_hit_tokens`` plus the
untagged ``serve_tokens_in/out`` totals they sum to), and with tracing
on every request gets its own span lane
(:mod:`~paddle_tpu.observability.request_trace`): submit -> queue ->
admit[cold/prefix-hit/readmit] -> prefill -> sampled decode steps ->
first_token/evict/finish, one Perfetto lane per request, with the
span-carried ``ttft_ms`` equal BY CONSTRUCTION to the value
``serve_ttft_ms`` observed.  ``serve_admit_rollbacks`` and
``serve_spec_index_withheld_tokens`` (PR 11 review fixes) are
always-on counters too, the rollback also a flight event.
"""
from __future__ import annotations

import queue as _queue
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..framework import monitor as _monitor
from ..observability import flight_recorder as _flight
from ..observability import trace as _trace
from ..observability.request_trace import RequestTrace
from .prefix_cache import PrefixCache
from .serving import (RequestTimeout, ServeError, ServerClosed,
                      ServerDraining, ServerOverloaded)

__all__ = ["GenerationServer", "GenerationStream", "ServeError",
           "ServerOverloaded", "ServerClosed", "ServerDraining",
           "RequestTimeout"]

_chaos_mod = None


def _gw_chaos():
    """Lazy handle on :mod:`~paddle_tpu.distributed.fleet.chaos` (the
    package root has loaded it long before any server runs, so this is
    a cached-global lookup per decode step, not an import)."""
    global _chaos_mod
    if _chaos_mod is None:
        try:
            from ..distributed.fleet import chaos as _c
        except Exception:        # pragma: no cover - import-order guard
            return None
        _chaos_mod = _c
    return _chaos_mod

# one serve.decode ring event per this many decode steps: the ring is
# postmortem context, not a per-token log (progress() still ticks the
# stall watchdog every step).  serve.spec_verify samples on the same
# cadence, offset so the FIRST verify step is always recorded.
_FLIGHT_DECODE_EVERY = 32

_END = object()


class GenerationStream:
    """Streaming handle for one generation request.

    Iterating yields token ids as the scheduler produces them; the
    iterator ends when the sequence finishes (``eos`` or
    ``max_new_tokens``).  Errors (timeout while waiting, server
    stopped) raise from the iterator / :meth:`result`.  ``tokens``
    holds everything yielded so far.
    """

    def __init__(self, request_id: int):
        self.request_id = request_id
        self._q: _queue.Queue = _queue.Queue()
        self.tokens: List[int] = []
        self._exc: Optional[BaseException] = None
        self._ended = False
        self.finish_reason: Optional[str] = None

    # -- producer side (scheduler thread) ----------------------------
    def _emit(self, tok: int):
        self.tokens.append(int(tok))
        self._q.put(int(tok))

    def _end(self, reason: str):
        self.finish_reason = reason
        self._q.put(_END)

    def _fail(self, exc: BaseException):
        self._exc = exc
        self._q.put(_END)

    # -- consumer side -----------------------------------------------
    def __iter__(self):
        return self

    def __next__(self, timeout: float = 600.0):
        if self._ended:
            raise StopIteration
        item = self._q.get(timeout=timeout)
        if item is _END:
            self._ended = True
            if self._exc is not None:
                raise self._exc
            raise StopIteration
        return item

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until the stream ends; returns the full token list."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while not self._ended:
            rem = (None if deadline is None
                   else max(deadline - time.monotonic(), 0.0))
            try:
                self.__next__(timeout=600.0 if rem is None else rem)
            except StopIteration:
                break
            except _queue.Empty:
                raise RequestTimeout(
                    f"stream {self.request_id} did not finish within "
                    f"{timeout}s") from None
        return list(self.tokens)


class _GenSeq:
    """Scheduler-internal sequence state (one per request)."""

    __slots__ = (
        "rid", "prompt", "L", "max_new", "eos", "do_sample", "temp",
        "top_k", "top_p", "key_data", "priority", "arrival", "deadline",
        "stream", "generated", "decoded", "blocks", "slot", "evictions",
        "t_submit", "t_first_tok", "cached", "draft_decoded", "tenant",
        "rt")

    def __init__(self, rid, prompt, max_new, eos, do_sample, temp,
                 top_k, top_p, key_data, priority, arrival, deadline,
                 tenant=None):
        self.rid = rid
        self.prompt = prompt                  # np.int32 [L]
        self.L = int(prompt.shape[0])
        self.max_new = int(max_new)
        self.eos = eos
        self.do_sample = bool(do_sample)
        self.temp = float(temp)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.key_data = key_data              # np.uint32 [W]
        self.priority = int(priority)
        self.arrival = arrival
        self.deadline = deadline
        self.stream = GenerationStream(rid)
        self.generated: List[int] = []        # emitted tokens t1..tn
        self.decoded = 0          # decode steps done since (re)prefill
        self.blocks: List[int] = []
        self.slot: Optional[int] = None
        self.evictions = 0
        self.t_submit = time.monotonic()
        self.t_first_tok: Optional[float] = None
        self.cached = 0           # prefix tokens aliased at admission
        self.draft_decoded = 0    # generated tokens the draft consumed
        self.tenant = tenant      # usage-accounting tag (ISSUE 12)
        # per-request span lane; None keeps the traced-off path at one
        # attribute check per site
        self.rt: Optional[RequestTrace] = None


def _pow2_buckets(lo: int, hi: int) -> List[int]:
    out, b = [], lo
    while b < hi:
        out.append(b)
        b *= 2
    out.append(hi)
    return sorted(set(out))


class GenerationServer:
    """Continuous-batching generative gateway over a KV-cache-capable
    causal LM (``supports_kv_cache()`` / ``forward_paged``).

    Usage::

        server = GenerationServer(model, num_slots=8, block_size=16,
                                  num_blocks=256, max_model_len=512,
                                  prefix_cache=True,
                                  draft_model=small_lm, spec_k=4)
        server.start()                    # prewarms every program
        stream = server.submit(prompt_ids, max_new_tokens=64)
        for tok in stream:                # tokens stream per step
            ...
        server.stop()

    Knobs:

    - ``num_slots``: decode batch width — the ONE fixed-shape decode
      (or spec-verify) program runs over this many slots every step,
      live or idle.
    - ``block_size`` / ``num_blocks``: KV pool geometry.  Block 0 is
      the trash block, so ``num_blocks - 1`` blocks are allocatable;
      default ``num_blocks`` sizes the pool for ``num_slots``
      full-length sequences (no oversubscription — oversubscribe
      deliberately to exercise eviction).
    - ``max_model_len``: prompt + generation cap per sequence; fixes
      the block-table width ``ceil(max_model_len / block_size)``.
    - ``prompt_buckets``: prefill compiles one program per (bucket,
      batch) pair (default buckets: powers of two up to
      ``max_model_len``).
    - ``max_prefill_batch``: widest batched-prefill program (powers of
      two up to this; 1 restores the ISSUE 8 one-prompt-per-dispatch
      behavior).
    - ``prefix_cache``: enable copy-on-write prefix sharing.  Changes
      pool accounting semantics: finished conversations' full blocks
      stay cached (recyclable under pressure) instead of returning to
      the free list, and ALL prefill runs the chunked cache-gather
      program so cold and warm runs are bit-identical.
    - ``draft_model`` / ``spec_k``: speculative decoding — the draft
      model (same vocab, typically far smaller) proposes up to
      ``spec_k`` tokens per iteration, verified in one target forward.
      Greedy and seeded-sampling outputs are bit-identical to plain
      decode by construction (deterministic positional-stream accept).
    - ``max_waiting``: waiting-queue depth cap; past it ``submit``
      sheds with :class:`ServerOverloaded`.
    - ``request_timeout_s``: deadline enforced while a request WAITS
      (initial queue or evicted); admitted sequences run to
      completion.
    - ``check_replay``: assert that every replayed (post-eviction)
      step reproduces the stored token — the bit-identity contract
      checked live, at one host compare per replayed token.
    """

    def __init__(self, model, num_slots: int = 8, block_size: int = 16,
                 num_blocks: Optional[int] = None,
                 max_model_len: Optional[int] = None,
                 prompt_buckets: Optional[Sequence[int]] = None,
                 max_waiting: int = 256,
                 request_timeout_s: float = 300.0,
                 seed: int = 0, check_replay: bool = False,
                 max_prefill_batch: int = 4,
                 prefix_cache: bool = False,
                 draft_model=None, spec_k: int = 4):
        if not bool(getattr(model, "supports_kv_cache",
                            lambda: False)()):
            # surface the model's own typed error (names the
            # scan_layers=False workaround for stacked llamas)
            init = getattr(model, "init_paged_cache", None)
            if init is not None:
                init(1, 1)   # raises KVCacheUnsupportedError
            raise ServeError(
                "GenerationServer requires a KV-cache-capable model "
                "(supports_kv_cache() is False)")
        if num_slots < 1:
            raise ValueError("num_slots must be >= 1")
        if block_size < 1:
            raise ValueError("block_size must be >= 1")
        self._model = model
        self._draft = draft_model
        self._spec = draft_model is not None
        self._k = int(spec_k)
        if self._spec:
            if self._k < 1:
                raise ValueError("spec_k must be >= 1")
            if not bool(getattr(draft_model, "supports_kv_cache",
                                lambda: False)()):
                raise ServeError(
                    "draft_model must be KV-cache-capable "
                    "(supports_kv_cache() is False)")
            if (getattr(draft_model.config, "vocab_size", None)
                    != getattr(model.config, "vocab_size", None)):
                raise ValueError(
                    "draft_model vocab_size must match the target's")
        self._num_slots = int(num_slots)
        self._bs = int(block_size)
        if max_model_len is None:
            max_model_len = int(getattr(model.config,
                                        "max_position_embeddings", 2048))
        self._max_len = int(max_model_len)
        self._M = -(-self._max_len // self._bs)   # block-table width
        if num_blocks is None:
            num_blocks = self._num_slots * self._M + 1
        self._num_blocks = int(num_blocks)
        if self._num_blocks < self._M + 1:
            raise ValueError(
                f"num_blocks={self._num_blocks} cannot hold even one "
                f"max-length sequence ({self._M} blocks) plus the "
                "trash block; raise num_blocks or lower max_model_len")
        bks = sorted(set(int(b) for b in (
            prompt_buckets or _pow2_buckets(min(8, self._max_len),
                                            self._max_len))))
        if bks[-1] < self._max_len:
            bks.append(self._max_len)
        self._buckets = bks
        if max_prefill_batch < 1:
            raise ValueError("max_prefill_batch must be >= 1")
        self._pbatches = _pow2_buckets(
            1, min(int(max_prefill_batch), self._num_slots))
        self._max_waiting = int(max_waiting)
        self._timeout_s = float(request_timeout_s)
        self._seed = int(seed)
        self._check_replay = bool(check_replay)
        self._prefix_on = bool(prefix_cache)

        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._waiting: List[_GenSeq] = []
        self._active: Dict[int, _GenSeq] = {}
        self._free_slots = list(range(self._num_slots))
        # block 1..num_blocks-1 are allocatable (0 is trash); the
        # PrefixCache is the one accounting path for both modes —
        # with the index disabled it IS the ISSUE 8 free list
        self._cache = PrefixCache(self._num_blocks - 1, self._bs,
                                  index_enabled=self._prefix_on,
                                  first_block=1)
        self._running = False
        self._draining = False
        self._thread: Optional[threading.Thread] = None
        # scheduler command queue (ISSUE 18): cancel/export/import
        # mutate sequence + slot state that _decode_once snapshots
        # without the lock, so they run ON the scheduler thread between
        # steps rather than growing the lock graph
        self._cmds: _queue.Queue = _queue.Queue()
        self._rid = 0
        self._arrival = 0
        self._compiles = 0
        self._compile_records: List[dict] = []
        self._stats = {
            "submitted": 0, "admitted": 0, "readmitted": 0,
            "evicted": 0, "finished": 0, "shed_overload": 0,
            "shed_timeout": 0, "tokens_generated": 0,
            "decode_steps": 0, "replay_steps": 0,
            "decode_ms": 0.0, "prefill_ms": 0.0,
            "prefill_batches": 0, "prefill_tokens": 0,
            "prefill_tokens_skipped": 0,
            "spec_verify_steps": 0, "draft_steps": 0,
            "spec_proposed": 0, "spec_accepted": 0,
            "admit_rollbacks": 0, "spec_index_withheld_tokens": 0,
            "shed_draining": 0, "migrated_in": 0, "migrated_out": 0,
            "cancelled": 0,
            "prefill_bucket_hits": {b: 0 for b in self._buckets},
        }

        # device state: params + pools + compiled step fns (lazy so the
        # constructor stays cheap; start() builds everything)
        self._pvals = None
        self._pools = None
        self._dvals = None
        self._dpools = None
        self._decode_fn = None
        self._prefill_fn = None
        self._draft_prefill_fn = None
        self._draft_decode_fn = None
        self._verify_fn = None
        self._fork_fn = None

    # -- program construction ----------------------------------------
    def _build_programs(self):
        import jax
        import jax.numpy as jnp

        from ..framework.core import Tensor, no_grad

        server = self
        prefix_on = self._prefix_on

        def make_call(model):
            def call_model(pvals, ids, pos, pools, tables, wm,
                           gather_at=None, verify_mode=False):
                st = model.state_dict()
                old = {k: t._value for k, t in st.items()}
                try:
                    for k, t in st.items():
                        if k in pvals:
                            t._value = pvals[k]
                    with no_grad():
                        logits, pools = model.forward_paged(
                            Tensor(ids), Tensor(pos), pools, tables, wm,
                            gather_at=gather_at, verify_mode=verify_mode)
                finally:
                    for k, t in st.items():
                        t._value = old[k]
                lv = logits._value if isinstance(logits, Tensor) \
                    else logits

                def raw(v):
                    return v._value if isinstance(v, Tensor) else v
                pools = [{kk: raw(vv) for kk, vv in d.items()}
                         for d in pools]
                return lv, pools
            return call_model

        call_model = make_call(self._model)
        self._pvals = {k: t._value
                       for k, t in self._model.state_dict().items()}
        self._pools = self._model.init_paged_cache(self._num_blocks,
                                                   self._bs)
        if self._spec:
            call_draft = make_call(self._draft)
            self._dvals = {k: t._value
                           for k, t in self._draft.state_dict().items()}
            self._dpools = self._draft.init_paged_cache(
                self._num_blocks, self._bs)
        else:
            self._dpools = []

        def sample(lg, kd, rng_steps, temp, top_k, top_p, do_sample):
            """Per-row next-token selection: exact argmax for greedy
            rows, temperature/top-k/top-p categorical for sampling
            rows — one program covers any mix.  The key for token j of
            a request is fold_in(request_key, j-1): a pure function of
            the stream position, so replay after eviction — and
            spec-decode verification, which samples the same stream at
            many positions in one call — reproduce the draw exactly."""
            V = lg.shape[-1]
            greedy = jnp.argmax(lg, axis=-1).astype(jnp.int32)
            x = lg / jnp.maximum(temp, 1e-6)[:, None]
            srt = jnp.sort(x, axis=-1)[:, ::-1]
            kk = jnp.clip(top_k, 1, V).astype(jnp.int32)
            kth = jnp.take_along_axis(srt, (kk - 1)[:, None], axis=-1)
            use_k = ((top_k > 0) & (top_k < V))[:, None]
            x = jnp.where(use_k & (x < kth), -jnp.inf, x)
            srt2 = jnp.sort(x, axis=-1)[:, ::-1]
            probs = jax.nn.softmax(srt2, axis=-1)
            cum = jnp.cumsum(probs, axis=-1)
            keep = jnp.maximum((cum < top_p[:, None]).sum(-1) + 1, 1)
            kth2 = jnp.take_along_axis(srt2, (keep - 1)[:, None],
                                       axis=-1)
            use_p = (top_p < 1.0)[:, None]
            x = jnp.where(use_p & (x < kth2), -jnp.inf, x)
            impl = {2: "threefry2x32", 4: "rbg"}.get(
                int(kd.shape[-1]), "threefry2x32")
            base = jax.random.wrap_key_data(kd, impl=impl)
            keys = jax.vmap(jax.random.fold_in)(base, rng_steps)
            sampled = jax.vmap(jax.random.categorical)(keys, x)
            return jnp.where(do_sample, sampled.astype(jnp.int32),
                             greedy)

        def decode_fn(pvals, pools, tokens, positions, tables, wm, kd,
                      rng_steps, temp, top_k, top_p, do_sample):
            # python side effect runs at TRACE time only: the counter
            # proves steady-state decode never retraces
            server._compiles += 1
            server._note_compile("decode", 1, tokens.shape[0])
            logits, pools = call_model(pvals, tokens, positions, pools,
                                       tables, wm)
            lg = logits[:, -1, :].astype(jnp.float32)
            nxt = sample(lg, kd, rng_steps, temp, top_k, top_p,
                         do_sample)
            return nxt, pools

        def make_prefill(call, name):
            def prefill_fn(pvals, pools, prompt, start, length, table,
                           kd, temp, top_k, top_p, do_sample):
                server._compiles += 1
                server._note_compile(name, prompt.shape[1],
                                     prompt.shape[0])
                B, Lb = prompt.shape
                pos = start[:, None] + jnp.broadcast_to(
                    jnp.arange(Lb, dtype=jnp.int32)[None, :], (B, Lb))
                wm = (jnp.arange(Lb, dtype=jnp.int32)[None, :]
                      < length[:, None])
                gather_at = jnp.clip(length - 1, 0, Lb - 1)
                # prefix-sharing servers run ALL prefill through the
                # cache-gather path (verify_mode) so a cold full
                # prefill and a warm suffix prefill are the same
                # floating-point program per position — the bit-
                # identity the shared-prefix contract rests on
                logits, pools = call(pvals, prompt, pos, pools, table,
                                     wm, gather_at=gather_at,
                                     verify_mode=prefix_on)
                lg = logits[:, -1, :].astype(jnp.float32)
                first = sample(lg, kd, jnp.zeros_like(length), temp,
                               top_k, top_p, do_sample)
                return first, pools
            return prefill_fn

        def verify_fn(pvals, pools, tokens, positions, tables, wm, kd,
                      rng_steps, temp, top_k, top_p, do_sample):
            """Score S=spec_k+1 fed tokens in one forward and sample
            the target's OWN token at every position with its
            positional key — the deterministic accept reference."""
            server._compiles += 1
            server._note_compile("verify", tokens.shape[1],
                                 tokens.shape[0])
            B, S = tokens.shape
            logits, pools = call_model(pvals, tokens, positions, pools,
                                       tables, wm, verify_mode=True)
            lg = logits.astype(jnp.float32).reshape(B * S, -1)
            rep = lambda a: jnp.repeat(a, S, axis=0)
            sampled = sample(lg, rep(kd), rng_steps.reshape(B * S),
                             rep(temp), rep(top_k), rep(top_p),
                             rep(do_sample))
            return sampled.reshape(B, S), pools

        def fork_fn(pools, dpools, src, dst):
            """Copy-on-write fork: duplicate one physical block across
            every pool tensor (target + draft, K/V + int8 scales).
            Physical ids never enter the attention math, so remapping
            the table to the copy is invisible to the stream."""
            server._compiles += 1
            server._note_compile("fork", 1, 1)

            def cp(d):
                return {k: v.at[dst].set(v[src]) for k, v in d.items()}
            return [cp(d) for d in pools], [cp(d) for d in dpools]

        # donate the pools: each step consumes the previous pool
        # buffers in place (the CPU backend can't donate — skip the
        # unusable-donation warning there)
        donate = () if jax.default_backend() == "cpu" else (1,)
        self._decode_fn = jax.jit(decode_fn, donate_argnums=donate)
        self._prefill_fn = jax.jit(make_prefill(call_model, "prefill"),
                                   donate_argnums=donate)
        if self._prefix_on:
            dfork = () if jax.default_backend() == "cpu" else (0, 1)
            self._fork_fn = jax.jit(fork_fn, donate_argnums=dfork)
        if self._spec:
            self._draft_prefill_fn = jax.jit(
                make_prefill(call_draft, "draft_prefill"),
                donate_argnums=donate)

            def draft_decode_fn(dvals, dpools, tokens, positions,
                                tables, wm, kd, rng_steps, temp, top_k,
                                top_p, do_sample):
                server._compiles += 1
                server._note_compile("draft_decode", 1, tokens.shape[0])
                logits, dpools = call_draft(dvals, tokens, positions,
                                            dpools, tables, wm)
                lg = logits[:, -1, :].astype(jnp.float32)
                nxt = sample(lg, kd, rng_steps, temp, top_k, top_p,
                             do_sample)
                return nxt, dpools
            self._draft_decode_fn = jax.jit(draft_decode_fn,
                                            donate_argnums=donate)
            self._verify_fn = jax.jit(verify_fn, donate_argnums=donate)

    def _note_compile(self, program: str, width: int, batch: int = 1):
        """Runs inside a trace: log the compile to the server's shared
        bucket-compile table and the flight recorder's observatory."""
        cause = "prewarm" if not self._running else "new_shape_bucket"
        self._compile_records.append(
            {"program": program, "bucket": int(width),
             "batch": int(batch), "cause": cause})
        _flight.note_compile(f"GenerationServer[{program}]", cause, 0.0,
                             key=(program, int(width), int(batch)),
                             n_buckets=self._compiles)

    # -- lifecycle ---------------------------------------------------
    def start(self, prewarm: bool = True) -> "GenerationServer":
        if self._running:
            return self
        if self._decode_fn is None:
            self._build_programs()
        if prewarm:
            self._prewarm()
        self._draining = False
        self._running = True
        self._thread = threading.Thread(target=self._loop,
                                        name="generation-server",
                                        daemon=True)
        self._thread.start()
        return self

    def _prewarm(self):
        """Compile every program before traffic: each (prompt bucket,
        prefill batch) pair's prefill (target + draft), the decode /
        draft-decode / verify programs, and the COW fork.  Dummy calls
        write only to the trash block (write masks all False), so the
        pools' live contents are untouched by construction."""
        W = int(np.asarray(self._seq_key_data(0)).shape[-1])
        for b in self._buckets:
            for pb in self._pbatches:
                args = (np.zeros((pb, b), np.int32),
                        np.zeros((pb,), np.int32),
                        np.zeros((pb,), np.int32),
                        np.zeros((pb, self._M), np.int32),
                        np.zeros((pb, W), np.uint32),
                        np.ones((pb,), np.float32),
                        np.zeros((pb,), np.int32),
                        np.ones((pb,), np.float32),
                        np.zeros((pb,), bool))
                _, self._pools = self._prefill_fn(
                    self._pvals, self._pools, *args)
                if self._spec:
                    _, self._dpools = self._draft_prefill_fn(
                        self._dvals, self._dpools, *args)
        B = self._num_slots
        dec_args = (np.zeros((B, 1), np.int32),
                    np.zeros((B, 1), np.int32),
                    np.zeros((B, self._M), np.int32),
                    np.zeros((B, 1), bool),
                    np.zeros((B, W), np.uint32),
                    np.zeros((B,), np.int32),
                    np.ones((B,), np.float32),
                    np.zeros((B,), np.int32),
                    np.ones((B,), np.float32),
                    np.zeros((B,), bool))
        nxt, self._pools = self._decode_fn(self._pvals, self._pools,
                                           *dec_args)
        if self._spec:
            dn, self._dpools = self._draft_decode_fn(
                self._dvals, self._dpools, *dec_args)
            S = self._k + 1
            sv, self._pools = self._verify_fn(
                self._pvals, self._pools,
                np.zeros((B, S), np.int32), np.zeros((B, S), np.int32),
                np.zeros((B, self._M), np.int32),
                np.zeros((B, S), bool), np.zeros((B, W), np.uint32),
                np.zeros((B, S), np.int32), np.ones((B,), np.float32),
                np.zeros((B,), np.int32), np.ones((B,), np.float32),
                np.zeros((B,), bool))
            np.asarray(sv)
        if self._fork_fn is not None:
            self._pools, self._dpools = self._fork_fn(
                self._pools, self._dpools, np.int32(0), np.int32(0))
        np.asarray(nxt)   # block until the warmup steps really ran

    def stop(self, drain: bool = False, timeout: float = 30.0):
        if not self._running:
            return
        if drain:
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                with self._lock:
                    if not self._active and not self._waiting:
                        break
                time.sleep(0.005)
        with self._cond:
            self._running = False
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
        # commands enqueued in the stop window would otherwise strand
        # their callers: the scheduler thread is gone, so run them here
        self._drain_cmds()
        with self._lock:
            leftovers = list(self._waiting) + list(self._active.values())
            self._waiting.clear()
        for seq in leftovers:
            self._release(seq)
            if seq.rt is not None:
                seq.rt.finish("server_stopped")
            seq.stream._fail(ServerClosed("server stopped"))

    def drain_begin(self):
        """Stop admitting NEW requests (``submit`` raises
        :class:`ServerDraining`) while the scheduler keeps running what
        it already owns — the first half of a graceful drain; KV
        migration / ``stop(drain=True)`` is the second."""
        with self._cond:
            self._draining = True

    @property
    def draining(self) -> bool:
        return self._draining

    # -- scheduler command queue (ISSUE 18) ---------------------------
    def _run_on_scheduler(self, fn, timeout: float = 30.0):
        """Run ``fn()`` on the scheduler thread between steps (sequence
        and slot state is only coherent there — _decode_once indexes
        its snapshot by ``seq.slot`` without holding the lock).  Runs
        inline when the scheduler is not running (stopped server) or
        when already ON the scheduler thread."""
        if not self._running or self._thread is None \
                or threading.current_thread() is self._thread:
            return fn()
        box: Dict = {}
        done = threading.Event()
        with self._cond:
            self._cmds.put((fn, box, done))
            self._cond.notify_all()
        if not done.wait(timeout):
            raise ServeError("scheduler command timed out "
                             f"after {timeout}s")
        if "exc" in box:
            raise box["exc"]
        return box.get("val")

    def _drain_cmds(self):
        while True:
            try:
                fn, box, done = self._cmds.get_nowait()
            except _queue.Empty:
                return
            try:
                box["val"] = fn()
            except BaseException as e:   # noqa: BLE001 — to the caller
                box["exc"] = e
            finally:
                done.set()

    def cancel(self, request_id: int, reason: str = "cancelled") -> bool:
        """Remove a request (waiting or active) WITHOUT failing its
        stream: blocks + slot free immediately and the stream ends with
        ``finish_reason == reason``.  Returns False when the request is
        unknown (already finished).  Runs on the scheduler thread."""
        def _do():
            with self._lock:
                seq = next((s for s in self._waiting
                            if s.rid == request_id), None)
                if seq is not None:
                    self._waiting.remove(seq)
                else:
                    seq = next((s for s in self._active.values()
                                if s.rid == request_id), None)
            if seq is None:
                return False
            self._release(seq)
            with self._lock:
                self._stats["cancelled"] += 1
            if seq.rt is not None:
                seq.rt.finish(reason, tokens=len(seq.generated))
            seq.stream._end(reason)
            return True
        return self._run_on_scheduler(_do)

    def __enter__(self) -> "GenerationServer":
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- client surface ----------------------------------------------
    def _seq_key_data(self, seed: int):
        from ..framework.random import key_to_data, make_key
        return np.asarray(key_to_data(make_key(seed))).astype(np.uint32)

    def submit(self, prompt, max_new_tokens: int = 32,
               do_sample: bool = False, temperature: float = 1.0,
               top_k: int = 0, top_p: float = 1.0,
               eos_token_id: Optional[int] = None,
               seed: Optional[int] = None, priority: int = 0,
               timeout_s: Optional[float] = None,
               tenant: Optional[str] = None,
               replay_tokens: Optional[Sequence[int]] = None,
               ) -> GenerationStream:
        """Enqueue one generation request; returns a
        :class:`GenerationStream` that yields tokens as decode steps
        complete.  ``priority``: lower = more important (evicted last).
        ``seed`` fixes the request's sampling RNG stream (default:
        derived from the server seed + request id).  ``tenant`` tags
        the request for usage accounting: always-on labeled counters
        (``serve_tenant_tokens_in/out``, ``serve_tenant_sheds``,
        ``serve_tenant_prefix_hit_tokens`` + a ``serve_tenant_queue_ms``
        gauge) accumulate per tenant, and — tagged or not — the request
        also counts into the untagged ``serve_tokens_in/out`` totals,
        so all-tagged traffic's tenant series sum EXACTLY to the
        totals.  Raises :class:`ServerOverloaded` at the waiting-queue
        cap, :class:`ServerDraining` on a draining server and
        :class:`ServerClosed` on a stopped one — all IMMEDIATELY, from
        under the scheduler lock, so a submit racing ``stop()`` can
        never enqueue a stream that will never start.

        ``replay_tokens`` (ISSUE 18 failover recovery): tokens this
        request's stream ALREADY emitted elsewhere — admission re-runs
        the prompt through prefill, then replays them through the
        normal decode path without re-emitting (``check_replay``
        asserts each one), and new tokens continue the stream from
        there.  The caller must pass the ORIGINAL request's explicit
        ``seed`` for the replayed stream to be the same RNG stream."""
        p = np.asarray(prompt.numpy() if hasattr(prompt, "numpy")
                       else prompt).astype(np.int32).reshape(-1)
        if p.size < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if p.size + max_new_tokens > self._max_len:
            raise ValueError(
                f"prompt ({p.size}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds max_model_len={self._max_len}")
        replay = [int(t) for t in replay_tokens] if replay_tokens \
            else []
        if len(replay) >= max_new_tokens:
            raise ValueError(
                f"replay_tokens ({len(replay)}) must be shorter than "
                f"max_new_tokens ({max_new_tokens}) — that stream is "
                "already complete")
        if do_sample and float(temperature) == 0.0:
            do_sample = False      # temperature 0.0 IS greedy (exact)
        to = self._timeout_s if timeout_s is None else float(timeout_s)
        with self._cond:
            # liveness checks INSIDE the lock: ``stop()`` flips
            # _running and sweeps leftovers under this same lock, so a
            # racing submit either lands before the sweep (its stream
            # fails typed) or observes the stop here — the pre-ISSUE-18
            # lock-free check let it enqueue AFTER the sweep, leaving a
            # stream nothing would ever end (caller hung to deadline)
            if not self._running:
                raise ServerClosed(
                    "server not running — submit refused (the stream "
                    "could never start)")
            if self._draining:
                self._stats["shed_draining"] += 1
                shed = ("draining", len(self._waiting))
            elif len(self._waiting) >= self._max_waiting:
                self._stats["shed_overload"] += 1
                shed = ("overload", len(self._waiting))
            else:
                self._rid += 1
                self._arrival += 1
                key_data = self._seq_key_data(
                    self._seed * 1000003 + self._rid
                    if seed is None else int(seed))
                seq = _GenSeq(self._rid, p, max_new_tokens,
                              eos_token_id, do_sample, temperature,
                              top_k, top_p, key_data, priority,
                              self._arrival, time.monotonic() + to,
                              tenant=tenant)
                if replay:
                    seq.generated = list(replay)
                if _trace.enabled():
                    seq.rt = RequestTrace("gen", seq.rid, tenant)
                    seq.rt.instant("submit", prompt_len=seq.L,
                                   max_new=seq.max_new)
                    seq.rt.begin("queue")
                self._waiting.append(seq)
                self._stats["submitted"] += 1
                self._cond.notify_all()
                shed = None
        if shed is not None:
            reason, depth = shed
            _monitor.stat_add("serve_shed_" + reason)
            if tenant is not None:
                _monitor.stat_add("serve_tenant_sheds",
                                  labels={"tenant": tenant,
                                          "reason": reason})
            _flight.record("serve.shed", reason=reason,
                           depth=depth, server="generation")
            if reason == "draining":
                raise ServerDraining(
                    "server is draining — submit this request to "
                    "another replica") from None
            _flight.maybe_dump("ServerOverloaded")
            raise ServerOverloaded(
                f"waiting-queue cap {self._max_waiting} reached; "
                "request shed — back off and retry") from None
        if _monitor.metrics_enabled():
            _monitor.gauge_set("serve_gen_waiting", len(self._waiting))
        return seq.stream

    def generate_sync(self, prompt, timeout: Optional[float] = None,
                      **kw) -> List[int]:
        """Blocking submit + collect (the per-client bench call)."""
        return self.submit(prompt, **kw).result(timeout=timeout)

    def num_compiles(self) -> int:
        """Distinct program traces (prefill grid + decode + spec/fork
        programs).  Steady state after warmup: delta == 0."""
        return self._compiles

    def flush_prefix_cache(self):
        """Drop every prefix-index entry (active sequences keep their
        references; cached-only blocks return to the free list)."""
        with self._lock:
            self._cache.flush()

    def stats(self) -> Dict:
        with self._lock:
            s = {k: (dict(v) if isinstance(v, dict) else v)
                 for k, v in self._stats.items()}
            s["waiting"] = len(self._waiting)
            s["active"] = len(self._active)
            s["draining"] = self._draining
            cache = self._cache.snapshot()
            records = list(self._compile_records)
        # "free" keeps its ISSUE 8 meaning — allocatable right now —
        # which with prefix sharing includes cached blocks (they
        # recycle on demand); "cached_blocks" is the subset holding
        # reusable prefix content
        s["free_blocks"] = cache["free"] + cache["cached"]
        s["allocated_blocks"] = cache["in_use"]
        s["cached_blocks"] = cache["cached"]
        s["prefix_entries"] = cache["entries"]
        s["prefix_hits"] = cache["hits"]
        s["prefix_hit_tokens"] = cache["hit_tokens"]
        s["prefix_queries"] = cache["queries"]
        s["prefix_hit_rate"] = (cache["hit_tokens"]
                                / max(cache["query_tokens"], 1))
        s["prefix_recycled"] = cache["recycled"]
        s["cow_forks"] = cache["cow_forks"]
        s["total_blocks"] = self._num_blocks - 1   # trash excluded
        s["block_size"] = self._bs
        s["num_slots"] = self._num_slots
        s["num_compiles"] = self._compiles
        s["spec_enabled"] = self._spec
        s["spec_k"] = self._k if self._spec else 0
        s["spec_accept_rate"] = (s["spec_accepted"]
                                 / max(s["spec_proposed"], 1))
        s["prefix_cache_enabled"] = self._prefix_on
        s["server"] = "generation"   # provenance, see PredictorServer
        # shared bucket-compile accounting shape with
        # PredictorServer.stats() (ISSUE 8 satellite; ISSUE 11 adds
        # the batch axis): per program "name:bucketxbatch" ->
        # {count, cause}
        bc: Dict = {}
        for r in records:
            key = f"{r['program']}:{r['bucket']}x{r.get('batch', 1)}"
            ent = bc.setdefault(key, {"count": 0, "cause": r["cause"]})
            ent["count"] += 1
        s["bucket_compiles"] = bc
        s["prewarm_compiles"] = sum(1 for r in records
                                    if r["cause"] == "prewarm")
        s["traffic_compiles"] = sum(1 for r in records
                                    if r["cause"] != "prewarm")
        return s

    # -- scheduler ---------------------------------------------------
    def _loop(self):
        try:
            while True:
                self._drain_cmds()
                with self._cond:
                    if not self._running:
                        return
                    if not self._active and not self._waiting \
                            and self._cmds.empty():
                        self._cond.wait(timeout=0.05)
                        continue
                self._expire_waiting()
                self._admit()
                if self._active:
                    if self._spec:
                        self._spec_once()
                    else:
                        self._decode_once()
        except BaseException as e:   # noqa: BLE001 — fail streams loudly
            with self._lock:
                victims = (list(self._waiting)
                           + list(self._active.values()))
                self._waiting.clear()
                self._active.clear()
                self._running = False
            for seq in victims:
                if seq.rt is not None:
                    seq.rt.finish("scheduler_error")
                seq.stream._fail(ServeError(
                    f"generation scheduler died: {e!r}"))
            raise

    def _expire_waiting(self):
        now = time.monotonic()
        with self._lock:
            expired = [s for s in self._waiting if now > s.deadline]
            if not expired:
                return
            self._waiting = [s for s in self._waiting
                             if now <= s.deadline]
            for s in expired:
                self._stats["shed_timeout"] += 1
        for s in expired:
            _monitor.stat_add("serve_shed_timeout")
            if s.tenant is not None:
                _monitor.stat_add("serve_tenant_sheds",
                                  labels={"tenant": s.tenant,
                                          "reason": "timeout"})
            _flight.record("serve.shed", reason="timeout", rid=s.rid,
                           waited_ms=round((now - s.t_submit) * 1e3, 1),
                           evictions=s.evictions, server="generation")
            _flight.record("serve.stream_end", rid=s.rid,
                           reason="timeout", tokens=len(s.generated))
            if s.rt is not None:
                s.rt.finish("shed_timeout", tokens=len(s.generated))
            s.stream._fail(RequestTimeout(
                f"request {s.rid} spent its whole deadline "
                + ("evicted and waiting for re-admission"
                   if s.evictions else "queued")
                + " — pool/slots overloaded"))

    # -- admission + prefill -----------------------------------------
    def _admit(self):
        """Admit as many waiting sequences as slots + blocks allow, in
        strict (priority, arrival) order, then prefill them in batches
        grouped by prompt/suffix bucket (ONE dispatch per group chunk
        — the batched-prefill win)."""
        taken: List[_GenSeq] = []
        forks: List[tuple] = []
        rollback: Optional[_GenSeq] = None
        with self._lock:
            while self._waiting and self._free_slots:
                self._waiting.sort(key=lambda s: (s.priority, s.arrival))
                seq = self._waiting[0]
                hit_blocks, matched = self._cache.match(seq.prompt)
                cached = min(matched, seq.L - 1)
                a = len(hit_blocks)
                nb = -(-seq.L // self._bs)
                fresh = nb - a
                # +1 headroom when the first decode write lands on a
                # block boundary; +1 more when the tail alias must COW-
                # fork before the suffix prefill writes into it
                w = cached // self._bs
                fork = bool(a and w < a)
                need = fresh + (1 if seq.L % self._bs == 0 else 0) \
                    + (1 if fork else 0)
                # available() counts LRU-cached blocks, but ref()ing
                # the hits below pins exactly the LRU ones out of the
                # recyclable pool — count only what alloc() can still
                # hand out afterwards (a warm cache under
                # oversubscription routinely has hits as the BULK of
                # the recyclable pool)
                pinned = sum(1 for b in hit_blocks
                             if b in self._cache.lru)
                if self._cache.available() - pinned < max(need, 0):
                    # pinning the hits + the fork destination can make
                    # the warm path need MORE allocatable blocks than
                    # a cold admission (which recycles the hit blocks
                    # as fresh ones) — fall back rather than starve
                    cold = nb + (1 if seq.L % self._bs == 0 else 0)
                    if self._cache.available() < cold:
                        break   # strict priority: no queue jumping
                    hit_blocks, cached = [], 0
                    fresh, fork = nb, False
                    need = cold
                self._waiting.pop(0)
                for b in hit_blocks:
                    self._cache.ref(b)
                seq.blocks = list(hit_blocks)
                # fresh blocks, plus the COW destination reserved
                # UNDER the admission check's lock — a same-round
                # sibling's fresh allocations must not eat the block
                # the check just promised this fork
                grabbed: List[int] = []
                dst = None
                for _ in range(fresh):
                    blk = self._cache.alloc()
                    if blk is None:
                        break
                    grabbed.append(blk)
                if fork and len(grabbed) == fresh:
                    dst = self._cache.alloc()
                if len(grabbed) < fresh or (fork and dst is None):
                    # the capacity check miscounted: roll back (free
                    # the grabs, unpin the hits, requeue) so one shed
                    # admission never kills the scheduler thread
                    for b in grabbed:
                        self._cache.unref(b)
                    for b in hit_blocks:
                        self._cache.unref(b)
                    seq.blocks = []
                    self._waiting.insert(0, seq)
                    self._stats["admit_rollbacks"] += 1
                    rollback = seq
                    break
                seq.blocks.extend(grabbed)
                seq.cached = cached
                self._cache.note_query(seq.L, cached)
                if fork:
                    self._cache.stats["cow_forks"] += 1
                    forks.append((seq, w, seq.blocks[w], dst))
                seq.slot = self._free_slots.pop()
                self._active[seq.slot] = seq
                taken.append(seq)
        if rollback is not None:
            # shed-class anomaly (ISSUE 12 satellite): the capacity
            # check miscounted and one admission was rolled back —
            # always-on counter + flight event (postmortem _BAD_KINDS)
            _monitor.stat_add("serve_admit_rollbacks")
            _flight.record("serve.admit_rollback", rid=rollback.rid,
                           prompt_len=rollback.L,
                           available=self._cache.available())
            if rollback.rt is not None:
                rollback.rt.instant("admit_rollback")
        for seq in taken:
            # usage accounting at admission: queue age per wait,
            # prompt tokens once per REQUEST (re-admissions re-alias,
            # they don't re-ingest)
            queue_ms = (time.monotonic() - seq.t_submit) * 1e3
            if seq.evictions == 0:
                _monitor.stat_add("serve_tokens_in", seq.L)
            if seq.tenant is not None:
                lab = {"tenant": seq.tenant}
                if seq.evictions == 0:
                    # first admission: queue age == submit -> now; a
                    # re-admission's wait shows on its req.queue span
                    _monitor.stat_add("serve_tenant_tokens_in", seq.L,
                                      labels=lab)
                    _monitor.gauge_add("serve_tenant_queue_ms",
                                       queue_ms, labels=lab)
                if seq.cached:
                    _monitor.stat_add("serve_tenant_prefix_hit_tokens",
                                      seq.cached, labels=lab)
            if seq.rt is not None:
                seq.rt.end("queue", evictions=seq.evictions)
                kind = ("readmit" if seq.evictions
                        else "prefix-hit" if seq.cached else "cold")
                seq.rt.instant("admit", kind=kind, cached=seq.cached,
                               blocks=len(seq.blocks), slot=seq.slot)
        if not taken:
            return
        # COW-fork each aliased tail block the suffix prefill will
        # write into (refcount > 1 counts the index entry, so an
        # indexed original is never clobbered): device-copy into the
        # reserved block, remap the table, drop the alias reference
        for seq, w, src, dst in forks:
            self._pools, self._dpools = self._fork_fn(
                self._pools, self._dpools, np.int32(src),
                np.int32(dst))
            with self._lock:
                seq.blocks[w] = dst
                self._cache.unref(src)
            _monitor.stat_add("serve_cow_forks")
            _flight.record("serve.cow_fork", rid=seq.rid, src=src,
                           dst=dst, logical=w)
        # group by suffix bucket and dispatch in chunks
        groups: Dict[int, List[_GenSeq]] = {}
        for seq in taken:
            groups.setdefault(self._bucket_for(seq.L - seq.cached),
                              []).append(seq)
        for bucket, seqs in sorted(groups.items()):
            for i in range(0, len(seqs), self._pbatches[-1]):
                self._prefill_batch(seqs[i:i + self._pbatches[-1]],
                                    bucket)

    def _bucket_for(self, L: int) -> int:
        for b in self._buckets:
            if L <= b:
                return b
        return self._buckets[-1]

    def _pbatch_for(self, n: int) -> int:
        for b in self._pbatches:
            if n <= b:
                return b
        return self._pbatches[-1]

    def _prefill_batch(self, seqs: List[_GenSeq], bucket: int):
        """One prefill dispatch for up to max_prefill_batch sequences
        sharing a bucket; padding rows (length 0) write only trash."""
        B = self._pbatch_for(len(seqs))
        W = int(seqs[0].key_data.shape[-1])
        prompt = np.zeros((B, bucket), np.int32)
        start = np.zeros((B,), np.int32)
        length = np.zeros((B,), np.int32)
        tables = np.zeros((B, self._M), np.int32)
        kd = np.zeros((B, W), np.uint32)
        temp = np.ones((B,), np.float32)
        top_k = np.zeros((B,), np.int32)
        top_p = np.ones((B,), np.float32)
        do_sample = np.zeros((B,), bool)
        for i, seq in enumerate(seqs):
            sfx = seq.prompt[seq.cached:]
            prompt[i, :sfx.shape[0]] = sfx
            start[i] = seq.cached
            length[i] = sfx.shape[0]
            tables[i, :len(seq.blocks)] = seq.blocks
            kd[i] = seq.key_data
            temp[i] = seq.temp
            top_k[i] = seq.top_k
            top_p[i] = seq.top_p
            do_sample[i] = seq.do_sample
            if seq.rt is not None:
                seq.rt.begin("prefill")
        t0 = time.perf_counter()
        first, self._pools = self._prefill_fn(
            self._pvals, self._pools, prompt, start, length, tables,
            kd, temp, top_k, top_p, do_sample)
        if self._spec:
            _, self._dpools = self._draft_prefill_fn(
                self._dvals, self._dpools, prompt, start, length,
                tables, kd, temp, top_k, top_p, do_sample)
        first = np.asarray(first)
        dt_ms = (time.perf_counter() - t0) * 1e3
        with self._lock:
            self._stats["prefill_ms"] += dt_ms
            self._stats["prefill_batches"] += 1
            self._stats["prefill_bucket_hits"][bucket] = \
                self._stats["prefill_bucket_hits"].get(bucket, 0) \
                + len(seqs)
            self._stats["prefill_tokens"] += int(
                sum(s.L - s.cached for s in seqs))
            self._stats["prefill_tokens_skipped"] += int(
                sum(s.cached for s in seqs))
        if _monitor.metrics_enabled():
            _monitor.hist_observe("prefill_ms", dt_ms)
        for seq in seqs:
            if seq.rt is not None:
                seq.rt.end("prefill", bucket=bucket, batch=len(seqs),
                           suffix=seq.L - seq.cached)
        for i, seq in enumerate(seqs):
            self._post_prefill(seq, int(first[i]), bucket)

    def _post_prefill(self, seq: _GenSeq, first: int, bucket: int):
        # a replay-submitted request (ISSUE 18 failover: generated
        # pre-seeded, zero evictions) takes the same no-re-emit path as
        # a re-admission; "readmitted" keeps counting evictions only
        readmit = seq.evictions > 0 or bool(seq.generated)
        with self._lock:
            self._stats["admitted"] += 1
            self._stats["readmitted"] += int(seq.evictions > 0)
            # index the prompt's full blocks for future sharing; the
            # aliased ones are already indexed (insert is idempotent)
            self._cache.insert(seq.prompt.tolist(), seq.blocks)
        _monitor.stat_add("serve_gen_admitted")
        _flight.record("serve.admit", rid=seq.rid, prompt_len=seq.L,
                       bucket=bucket, blocks=len(seq.blocks),
                       slot=seq.slot, readmit=readmit,
                       priority=seq.priority, cached=seq.cached)
        if seq.cached:
            _monitor.stat_add("serve_prefix_hits")
            _monitor.stat_add("serve_prefix_hit_tokens", seq.cached)
            _flight.record("serve.prefix_hit", rid=seq.rid,
                           cached_tokens=seq.cached,
                           prompt_len=seq.L)
        if _monitor.metrics_enabled():
            _monitor.gauge_set("serve_gen_active", len(self._active))
            _monitor.gauge_set("serve_gen_free_blocks",
                               self._cache.available())
            st = self._cache.stats
            _monitor.gauge_set("serve_prefix_hit_rate",
                               st["hit_tokens"]
                               / max(st["query_tokens"], 1))
        seq.decoded = 0
        seq.draft_decoded = 0
        if readmit:
            # replay: prefill re-derives t1 from the identical program
            # + inputs; the stored token is authoritative either way
            if self._check_replay and first != seq.generated[0]:
                raise AssertionError(
                    f"re-prefill of request {seq.rid} resampled token 1 "
                    f"as {first}, stream already emitted "
                    f"{seq.generated[0]} — paged prefill is not "
                    "bit-stable")
        else:
            self._emit(seq, first)

    # -- emission / release ------------------------------------------
    def _emit(self, seq: _GenSeq, tok: int):
        seq.generated.append(tok)
        if seq.t_first_tok is None:
            seq.t_first_tok = time.monotonic()
            # ONE ttft value feeds both the histogram and the span
            # lane: the span view and serve_ttft_ms must agree exactly
            # (the ISSUE 12 consistency contract)
            ttft_ms = (seq.t_first_tok - seq.t_submit) * 1e3
            if _monitor.metrics_enabled():
                _monitor.hist_observe("serve_ttft_ms", ttft_ms)
            if seq.rt is not None:
                seq.rt.instant("first_token", ttft_ms=ttft_ms)
        seq.stream._emit(tok)
        with self._lock:
            self._stats["tokens_generated"] += 1
        if (seq.eos is not None and tok == seq.eos) \
                or len(seq.generated) >= seq.max_new:
            reason = ("eos" if seq.eos is not None and tok == seq.eos
                      else "length")
            self._finish(seq, reason)

    def _finish(self, seq: _GenSeq, reason: str):
        withheld = 0
        with self._lock:
            # index completed full blocks (prompt + generated): the
            # next turn of this conversation aliases them — multi-turn
            # chat is the prefix cache's defining traffic
            toks = seq.prompt.tolist() + seq.generated
            if self._spec and self._prefix_on:
                # the draft pools hold valid KV only through position
                # L + draft_decoded - 1 (capped/rejected proposals
                # leave the draft behind the emitted stream); indexing
                # past that would hand a future alias stale draft-KV —
                # output stays bit-correct via the deterministic
                # accept, but the accept rate silently sinks for
                # exactly the warm multi-turn traffic the cache
                # targets.  Withhold the tail and count it.
                valid = seq.L + seq.draft_decoded
                withheld = max(len(toks) - valid, 0)
                self._stats["spec_index_withheld_tokens"] += withheld
                toks = toks[:valid]
            self._cache.insert(toks, seq.blocks)
        self._release(seq)
        with self._lock:
            self._stats["finished"] += 1
        if withheld:
            # stats()-only until ISSUE 12: the accept-rate sink is a
            # fleet-visible signal, so it counts on /metrics too
            _monitor.stat_add("serve_spec_index_withheld_tokens",
                              withheld)
        _monitor.stat_add("serve_gen_finished")
        _monitor.stat_add("serve_tokens_out", len(seq.generated))
        if seq.tenant is not None:
            _monitor.stat_add("serve_tenant_tokens_out",
                              len(seq.generated),
                              labels={"tenant": seq.tenant})
        _flight.record("serve.stream_end", rid=seq.rid, reason=reason,
                       tokens=len(seq.generated),
                       evictions=seq.evictions)
        if seq.rt is not None:
            seq.rt.finish(reason, tokens=len(seq.generated),
                          evictions=seq.evictions)
        seq.stream._end(reason)

    def _release(self, seq: _GenSeq):
        """Drop the sequence's block references + slot immediately
        (shared blocks survive through their other references; indexed
        blocks stay cached until recycled)."""
        with self._lock:
            if seq.blocks:
                for b in seq.blocks:
                    self._cache.unref(b)
                seq.blocks = []
            if seq.slot is not None:
                self._active.pop(seq.slot, None)
                self._free_slots.append(seq.slot)
                seq.slot = None

    def _evict(self, seq: _GenSeq):
        """Block-pool exhaustion: free the victim's blocks and send it
        back to the waiting queue (its generated tokens are kept; re-
        admission re-prefills + replays them bit-identically)."""
        freed = len(seq.blocks)
        self._release(seq)
        seq.decoded = 0
        seq.draft_decoded = 0
        seq.cached = 0
        seq.evictions += 1
        with self._lock:
            self._stats["evicted"] += 1
            self._waiting.append(seq)
        _monitor.stat_add("serve_gen_evicted")
        _flight.record("serve.evict", rid=seq.rid,
                       reason="pool_exhausted", freed_blocks=freed,
                       tokens_so_far=len(seq.generated),
                       priority=seq.priority, evictions=seq.evictions)
        if seq.rt is not None:
            seq.rt.instant("evict", tokens=len(seq.generated))
            seq.rt.begin("queue")   # waiting for re-admission
        _flight.maybe_dump("BlockPoolExhausted")

    def _grow_or_evict(self):
        """Before a decode/verify step every live sequence must own the
        blocks its next K/V writes land in (one position for plain
        decode, up to spec_k+1 for a spec iteration); a dry pool evicts
        the lowest-priority sequence (highest priority number, then
        youngest)."""
        ahead = self._k if self._spec else 0
        for seq in sorted(self._active.values(), key=lambda s: s.slot):
            if seq.slot is None:
                continue      # evicted below us this round
            p = min(seq.L + seq.decoded + ahead, self._max_len - 1)
            need = p // self._bs + 1
            while len(seq.blocks) < need and seq.slot is not None:
                with self._lock:
                    blk = self._cache.alloc()
                    if blk is not None:
                        seq.blocks.append(blk)
                        continue
                victim = max(self._active.values(),
                             key=lambda s: (s.priority, s.arrival))
                self._evict(victim)
                # the growing sequence itself can be the lowest
                # priority: it re-queues and this slot sits out

    # -- plain decode -------------------------------------------------
    def _decode_once(self):
        self._grow_or_evict()
        with self._lock:
            live = sorted(self._active.values(), key=lambda s: s.slot)
        if not live:
            return
        B, M = self._num_slots, self._M
        W = live[0].key_data.shape[-1]
        tokens = np.zeros((B, 1), np.int32)
        positions = np.zeros((B, 1), np.int32)
        tables = np.zeros((B, M), np.int32)
        wm = np.zeros((B, 1), bool)
        kd = np.zeros((B, W), np.uint32)
        rng_steps = np.zeros((B,), np.int32)
        temp = np.ones((B,), np.float32)
        top_k = np.zeros((B,), np.int32)
        top_p = np.ones((B,), np.float32)
        do_sample = np.zeros((B,), bool)
        for seq in live:
            s = seq.slot
            tokens[s, 0] = seq.generated[seq.decoded]
            positions[s, 0] = seq.L + seq.decoded
            tables[s, :len(seq.blocks)] = seq.blocks
            wm[s, 0] = True
            kd[s] = seq.key_data
            rng_steps[s] = seq.decoded + 1
            temp[s] = seq.temp
            top_k[s] = seq.top_k
            top_p[s] = seq.top_p
            do_sample[s] = seq.do_sample
        t0 = time.perf_counter()
        nxt, self._pools = self._decode_fn(
            self._pvals, self._pools, tokens, positions, tables, wm,
            kd, rng_steps, temp, top_k, top_p, do_sample)
        nxt = np.asarray(nxt)
        dt_ms = (time.perf_counter() - t0) * 1e3
        replays = 0
        every = _trace.trace_every()
        for seq in live:
            s = seq.slot
            seq.decoded += 1
            if seq.rt is not None and seq.decoded % every == 0:
                # sampled per-request decode span (PADDLE_TRACE_EVERY)
                seq.rt.span_at("decode", dt_ms, step=seq.decoded)
            j = seq.decoded + 1          # 1-based index produced
            if j <= len(seq.generated):
                replays += 1             # catching up after eviction
                if self._check_replay \
                        and int(nxt[s]) != seq.generated[j - 1]:
                    raise AssertionError(
                        f"replayed decode step for request {seq.rid} "
                        f"produced {int(nxt[s])}, stream already "
                        f"emitted {seq.generated[j - 1]} — paged "
                        "decode is not bit-stable")
            else:
                self._emit(seq, int(nxt[s]))
        self._after_step(len(live), replays, dt_ms)

    def _after_step(self, n_live: int, replays: int, dt_ms: float):
        with self._lock:
            self._stats["decode_steps"] += 1
            self._stats["replay_steps"] += replays
            self._stats["decode_ms"] += dt_ms
            n_steps = self._stats["decode_steps"]
            free_now = self._cache.available()
        _flight.progress("serve.decode")
        if n_steps % _FLIGHT_DECODE_EVERY == 0:
            _flight.record("serve.decode", steps=n_steps, live=n_live,
                           free_blocks=free_now, ms=round(dt_ms, 3))
        if _monitor.metrics_enabled():
            _monitor.hist_observe("decode_step_ms", dt_ms)
            _monitor.gauge_set("serve_gen_active", len(self._active))
            _monitor.gauge_set("serve_gen_free_blocks", free_now)
        # gateway chaos site (ISSUE 18): a seeded ``kill:gen_step``
        # plan SIGKILLs this replica process at an exact decode step —
        # the acceptance fault for router failover.  No plan installed
        # => one cached-module call per step.
        ch = _gw_chaos()
        if ch is not None:
            ch.maybe_kill_replica()

    # -- speculative decode -------------------------------------------
    def _spec_once(self):
        """One spec iteration: k batched draft steps propose, one
        target verify forward scores k+1 positions, the accepted
        prefix advances.  Bit-identical to plain decode: every
        candidate is the target's own positional-stream token, and a
        proposal is accepted only when it EQUALS that token."""
        self._grow_or_evict()
        with self._lock:
            live = sorted(self._active.values(), key=lambda s: s.slot)
        if not live:
            return
        B, M, k = self._num_slots, self._M, self._k
        W = live[0].key_data.shape[-1]
        t0 = time.perf_counter()

        # ---- draft phase: k batched draft-decode steps.  Per slot the
        # feed is the next unconsumed token: stored tokens first
        # (catch-up after eviction or a rejected round), then its own
        # proposal chain.  Chain outputs past the end of the stored
        # stream are this round's proposals.
        chains: Dict[int, List[int]] = {s.slot: [] for s in live}
        draft_feeds: Dict[int, List[int]] = {s.slot: [] for s in live}
        for _ in range(k):
            tokens = np.zeros((B, 1), np.int32)
            positions = np.zeros((B, 1), np.int32)
            tables = np.zeros((B, M), np.int32)
            wm = np.zeros((B, 1), bool)
            kd = np.zeros((B, W), np.uint32)
            rng_steps = np.zeros((B,), np.int32)
            temp = np.ones((B,), np.float32)
            top_k = np.zeros((B,), np.int32)
            top_p = np.ones((B,), np.float32)
            do_sample = np.zeros((B,), bool)
            fed_any = False
            fed_this: Dict[int, int] = {}
            for seq in live:
                s = seq.slot
                f = seq.draft_decoded + len(draft_feeds[s])
                pos = seq.L + f
                gen, chain = seq.generated, chains[s]
                # accepting m proposals emits m+1 tokens, so proposals
                # beyond max_new - len(gen) - 1 can never be consumed —
                # don't draft them (they'd be fed to verify, counted
                # rejected, and waste a draft dispatch)
                cap = max(seq.max_new - len(gen) - 1, 0)
                if f < len(gen):
                    if f >= len(gen) - 1 and len(chain) >= cap:
                        continue      # proposal budget spent
                    tok = gen[f]
                elif f - len(gen) < len(chain):
                    if len(chain) >= cap:
                        continue      # proposal budget spent
                    tok = chain[f - len(gen)]
                else:
                    continue          # chain exhausted (position cap)
                if pos >= self._max_len:
                    continue          # context full: draft idles
                tokens[s, 0] = tok
                positions[s, 0] = pos
                tables[s, :len(seq.blocks)] = seq.blocks
                wm[s, 0] = True
                kd[s] = seq.key_data
                rng_steps[s] = f + 1
                temp[s] = seq.temp
                top_k[s] = seq.top_k
                top_p[s] = seq.top_p
                do_sample[s] = seq.do_sample
                fed_any = True
                fed_this[s] = (f, int(tok))
            if not fed_any:
                break
            nxt, self._dpools = self._draft_decode_fn(
                self._dvals, self._dpools, tokens, positions, tables,
                wm, kd, rng_steps, temp, top_k, top_p, do_sample)
            nxt = np.asarray(nxt)
            with self._lock:
                self._stats["draft_steps"] += 1
            for seq in live:
                s = seq.slot
                if s not in fed_this:
                    continue
                f, ftok = fed_this[s]
                draft_feeds[s].append(ftok)
                # outputs from the last stored token onward extend the
                # proposal chain
                if f >= len(seq.generated) - 1:
                    chains[s].append(int(nxt[s]))

        # ---- verify phase: one S=k+1 target forward over [last
        # stored suffix ++ proposals] per slot
        S = k + 1
        tokens = np.zeros((B, S), np.int32)
        positions = np.zeros((B, S), np.int32)
        tables = np.zeros((B, M), np.int32)
        wm = np.zeros((B, S), bool)
        kd = np.zeros((B, W), np.uint32)
        rng_steps = np.zeros((B, S), np.int32)
        temp = np.ones((B,), np.float32)
        top_k = np.zeros((B,), np.int32)
        top_p = np.ones((B,), np.float32)
        do_sample = np.zeros((B,), bool)
        feeds: Dict[int, List[int]] = {}
        n_props: Dict[int, int] = {}
        for seq in live:
            s = seq.slot
            f0 = seq.decoded
            known = seq.generated[f0:]       # >= 1 (last emitted)
            fed = (known + chains[s])[:S]
            cap = self._max_len - (seq.L + f0)   # positions available
            # candidates beyond the replay region + remaining token
            # budget can never be consumed — don't feed them
            useful = (len(known) - 1) + max(
                seq.max_new - len(seq.generated), 0)
            fed = fed[:max(min(len(fed), cap, useful), 0)]
            if not fed:
                continue      # context full: nothing to verify
            feeds[s] = fed
            n_props[s] = max(len(fed) - len(known), 0)
            for o, tok in enumerate(fed):
                tokens[s, o] = tok
                positions[s, o] = seq.L + f0 + o
                wm[s, o] = True
                rng_steps[s, o] = f0 + o + 1
            tables[s, :len(seq.blocks)] = seq.blocks
            kd[s] = seq.key_data
            temp[s] = seq.temp
            top_k[s] = seq.top_k
            top_p[s] = seq.top_p
            do_sample[s] = seq.do_sample
        if not feeds:
            return
        cand, self._pools = self._verify_fn(
            self._pvals, self._pools, tokens, positions, tables, wm,
            kd, rng_steps, temp, top_k, top_p, do_sample)
        cand = np.asarray(cand)
        dt_ms = (time.perf_counter() - t0) * 1e3

        # ---- host accept: candidate o realizes generated index
        # f0+o+1.  Stored region => replay check; beyond => emit the
        # target's token, continue only while the NEXT fed proposal
        # equals it (the deterministic accept).
        replays = 0
        accepted_total = 0
        proposed_total = 0
        for seq in live:
            s = seq.slot
            if s not in feeds or seq.slot is None:
                continue
            fed = feeds[s]
            f0 = seq.decoded
            proposed_total += n_props[s]
            valid_fed = 0
            for o in range(len(fed)):
                if seq.slot is None:
                    break           # finished mid-verify
                tok = int(cand[s, o])
                idx = f0 + o + 1    # 0-based generated index realized
                if idx < len(seq.generated):
                    replays += 1
                    valid_fed += 1
                    if self._check_replay \
                            and tok != seq.generated[idx]:
                        raise AssertionError(
                            f"replayed verify step for request "
                            f"{seq.rid} produced {tok}, stream "
                            f"already emitted {seq.generated[idx]} — "
                            "paged verify is not bit-stable")
                    continue
                valid_fed += 1      # fed token o was gen[f0+o]
                self._emit(seq, tok)
                if o + 1 < len(fed) and fed[o + 1] == tok:
                    accepted_total += 1
                    continue        # proposal matched: keep going
                break               # mismatch or out of proposals
            if seq.slot is not None:
                seq.decoded = min(f0 + valid_fed,
                                  len(seq.generated) - 1)
                if seq.rt is not None \
                        and seq.decoded % _trace.trace_every() == 0:
                    seq.rt.span_at("decode", dt_ms, step=seq.decoded,
                                   spec=True)
                # draft validity: a fed token counts while it matches
                # the FINAL stream at its index (stored feeds match by
                # construction; proposal feeds match iff accepted) —
                # the draft's KV at those positions is then correct
                df0 = seq.draft_decoded
                nvalid = 0
                for t, ftok in enumerate(draft_feeds[s]):
                    i2 = df0 + t
                    if i2 < len(seq.generated) \
                            and seq.generated[i2] == ftok:
                        nvalid += 1
                    else:
                        break
                seq.draft_decoded = min(df0 + nvalid,
                                        len(seq.generated) - 1)
        with self._lock:
            self._stats["spec_verify_steps"] += 1
            self._stats["spec_proposed"] += proposed_total
            self._stats["spec_accepted"] += accepted_total
            n_verify = self._stats["spec_verify_steps"]
            p_tot = self._stats["spec_proposed"]
            a_tot = self._stats["spec_accepted"]
        _monitor.stat_add("serve_spec_proposed", proposed_total)
        _monitor.stat_add("serve_spec_accepted", accepted_total)
        if _monitor.metrics_enabled():
            _monitor.gauge_set("serve_spec_accept_rate",
                               a_tot / max(p_tot, 1))
        if n_verify % _FLIGHT_DECODE_EVERY == 1:
            _flight.record("serve.spec_verify", steps=n_verify,
                           proposed=proposed_total,
                           accepted=accepted_total,
                           accept_rate=round(a_tot / max(p_tot, 1), 3))
        self._after_step(len(live), replays, dt_ms)
