"""paddle_tpu.inference.generation_server — continuous-batching LLM
serving: block-paged KV cache + iteration-level decode scheduler
(ISSUE 8 tentpole; ROADMAP item 1).

``PredictorServer`` micro-batches FIXED-shape requests; generative
decoding is the other regime: every sequence advances one token per
model call, sequences finish at different times, and a dedicated
``[B, Smax]`` KV buffer per conversation would cap concurrency at
HBM / (Smax * layers * heads).  This module is the Orca-style
iteration-level scheduler + vLLM-style paged KV cache built on the
same AOT discipline as the rest of ``inference/``:

- **block-paged KV cache** — K/V live in per-layer pools
  ``[num_blocks, block_size, KH, D]`` shared by every sequence; a
  sequence owns a list of physical blocks and its cache reads are a
  gather over its block table (``LlamaAttention.forward_paged``).
  Physical block 0 is the TRASH block: never allocated, the target of
  masked writes (prompt padding, idle decode slots), never read (the
  slot <= position mask).  Thousands of conversations share one HBM
  budget and freeing is O(blocks), not O(bytes).
- **iteration-level scheduling** — admission/eviction decisions happen
  every decode step, not per request: finished sequences free their
  blocks immediately and waiting requests are admitted mid-flight.
  PREFILL compiles one program per power-of-2 prompt bucket (B=1);
  DECODE is ONE fixed-shape program over all ``num_slots`` batch slots
  regardless of how many are live — steady state never retraces
  (``num_compiles()`` is the proof, same contract as ``Predictor``).
- **typed shed semantics** shared with ``PredictorServer``
  (:class:`ServerOverloaded` at the waiting-queue cap,
  :class:`RequestTimeout` for requests whose deadline passes while
  waiting) extended with **block-pool-exhaustion eviction**: when a
  running sequence needs a block and the pool is dry, the
  lowest-priority sequence is evicted (blocks freed, back to the
  waiting queue) and later re-admitted.
- **bit-identical re-admission** — re-admission re-runs the ORIGINAL
  prompt through the same prefill program (same bucket, same inputs =>
  identical K/V and logits), then replays the already-emitted tokens
  through the normal decode program with the sampled token overridden
  by the stored one.  Because every decode slot's math depends only on
  its own inputs (no cross-slot reduction), each replayed step is the
  exact computation the uninterrupted run performed, so the resumed
  stream is bit-identical — including sampling: the RNG key for token
  j is ``fold_in(request_key, j-1)``, a pure function of the stream
  position, so the RNG stream position survives eviction by
  construction.  (A plain re-prefill over prompt+suffix would NOT be
  bit-identical: prefill and decode use different attention kernels.)
- **streaming responses** — :meth:`GenerationServer.submit` returns a
  :class:`GenerationStream` immediately; tokens arrive on it as each
  decode step completes (iterate it, or ``result()`` to block for the
  full continuation).

Observability rides the existing seams: serve histograms
(``decode_step_ms`` / ``prefill_ms`` / ``serve_ttft_ms``), counters
and gauges in the StatRegistry, and flight-recorder events
(``serve.admit`` / ``serve.evict`` / ``serve.stream_end`` +
sampled ``serve.decode``) so ``tools/postmortem.py`` can autopsy a
pool-exhaustion shed.
"""
from __future__ import annotations

import queue as _queue
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..framework import monitor as _monitor
from ..observability import flight_recorder as _flight
from .serving import (RequestTimeout, ServeError, ServerClosed,
                      ServerOverloaded)

__all__ = ["GenerationServer", "GenerationStream", "ServeError",
           "ServerOverloaded", "ServerClosed", "RequestTimeout"]

# one serve.decode ring event per this many decode steps: the ring is
# postmortem context, not a per-token log (progress() still ticks the
# stall watchdog every step)
_FLIGHT_DECODE_EVERY = 32

_END = object()


class GenerationStream:
    """Streaming handle for one generation request.

    Iterating yields token ids as the scheduler produces them; the
    iterator ends when the sequence finishes (``eos`` or
    ``max_new_tokens``).  Errors (timeout while waiting, server
    stopped) raise from the iterator / :meth:`result`.  ``tokens``
    holds everything yielded so far.
    """

    def __init__(self, request_id: int):
        self.request_id = request_id
        self._q: _queue.Queue = _queue.Queue()
        self.tokens: List[int] = []
        self._exc: Optional[BaseException] = None
        self._ended = False
        self.finish_reason: Optional[str] = None

    # -- producer side (scheduler thread) ----------------------------
    def _emit(self, tok: int):
        self.tokens.append(int(tok))
        self._q.put(int(tok))

    def _end(self, reason: str):
        self.finish_reason = reason
        self._q.put(_END)

    def _fail(self, exc: BaseException):
        self._exc = exc
        self._q.put(_END)

    # -- consumer side -----------------------------------------------
    def __iter__(self):
        return self

    def __next__(self, timeout: float = 600.0):
        if self._ended:
            raise StopIteration
        item = self._q.get(timeout=timeout)
        if item is _END:
            self._ended = True
            if self._exc is not None:
                raise self._exc
            raise StopIteration
        return item

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until the stream ends; returns the full token list."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while not self._ended:
            rem = (None if deadline is None
                   else max(deadline - time.monotonic(), 0.0))
            try:
                self.__next__(timeout=600.0 if rem is None else rem)
            except StopIteration:
                break
            except _queue.Empty:
                raise RequestTimeout(
                    f"stream {self.request_id} did not finish within "
                    f"{timeout}s") from None
        return list(self.tokens)


class _GenSeq:
    """Scheduler-internal sequence state (one per request)."""

    __slots__ = (
        "rid", "prompt", "L", "max_new", "eos", "do_sample", "temp",
        "top_k", "top_p", "key_data", "priority", "arrival", "deadline",
        "stream", "generated", "decoded", "blocks", "slot", "evictions",
        "t_submit", "t_first_tok")

    def __init__(self, rid, prompt, max_new, eos, do_sample, temp,
                 top_k, top_p, key_data, priority, arrival, deadline):
        self.rid = rid
        self.prompt = prompt                  # np.int32 [L]
        self.L = int(prompt.shape[0])
        self.max_new = int(max_new)
        self.eos = eos
        self.do_sample = bool(do_sample)
        self.temp = float(temp)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.key_data = key_data              # np.uint32 [W]
        self.priority = int(priority)
        self.arrival = arrival
        self.deadline = deadline
        self.stream = GenerationStream(rid)
        self.generated: List[int] = []        # emitted tokens t1..tn
        self.decoded = 0          # decode steps done since (re)prefill
        self.blocks: List[int] = []
        self.slot: Optional[int] = None
        self.evictions = 0
        self.t_submit = time.monotonic()
        self.t_first_tok: Optional[float] = None


def _pow2_buckets(lo: int, hi: int) -> List[int]:
    out, b = [], lo
    while b < hi:
        out.append(b)
        b *= 2
    out.append(hi)
    return sorted(set(out))


class GenerationServer:
    """Continuous-batching generative server over a KV-cache-capable
    causal LM (``supports_kv_cache()`` / ``forward_paged``).

    Usage::

        server = GenerationServer(model, num_slots=8, block_size=16,
                                  num_blocks=256, max_model_len=512)
        server.start()                    # prewarms every program
        stream = server.submit(prompt_ids, max_new_tokens=64)
        for tok in stream:                # tokens stream per step
            ...
        server.stop()

    Knobs:

    - ``num_slots``: decode batch width — the ONE fixed-shape decode
      program runs over this many slots every step, live or idle.
    - ``block_size`` / ``num_blocks``: KV pool geometry.  Block 0 is
      the trash block, so ``num_blocks - 1`` blocks are allocatable;
      default ``num_blocks`` sizes the pool for ``num_slots``
      full-length sequences (no oversubscription — oversubscribe
      deliberately to exercise eviction).
    - ``max_model_len``: prompt + generation cap per sequence; fixes
      the block-table width ``ceil(max_model_len / block_size)``.
    - ``prompt_buckets``: prefill compiles one program per bucket
      (default: powers of two up to ``max_model_len``).
    - ``max_waiting``: waiting-queue depth cap; past it ``submit``
      sheds with :class:`ServerOverloaded`.
    - ``request_timeout_s``: deadline enforced while a request WAITS
      (initial queue or evicted); admitted sequences run to
      completion.
    - ``check_replay``: assert that every replayed (post-eviction)
      step reproduces the stored token — the bit-identity contract
      checked live, at one host compare per replayed token.
    """

    def __init__(self, model, num_slots: int = 8, block_size: int = 16,
                 num_blocks: Optional[int] = None,
                 max_model_len: Optional[int] = None,
                 prompt_buckets: Optional[Sequence[int]] = None,
                 max_waiting: int = 256,
                 request_timeout_s: float = 300.0,
                 seed: int = 0, check_replay: bool = False):
        if not bool(getattr(model, "supports_kv_cache",
                            lambda: False)()):
            # surface the model's own typed error (names the
            # scan_layers=False workaround for stacked llamas)
            init = getattr(model, "init_paged_cache", None)
            if init is not None:
                init(1, 1)   # raises KVCacheUnsupportedError
            raise ServeError(
                "GenerationServer requires a KV-cache-capable model "
                "(supports_kv_cache() is False)")
        if num_slots < 1:
            raise ValueError("num_slots must be >= 1")
        if block_size < 1:
            raise ValueError("block_size must be >= 1")
        self._model = model
        self._num_slots = int(num_slots)
        self._bs = int(block_size)
        if max_model_len is None:
            max_model_len = int(getattr(model.config,
                                        "max_position_embeddings", 2048))
        self._max_len = int(max_model_len)
        self._M = -(-self._max_len // self._bs)   # block-table width
        if num_blocks is None:
            num_blocks = self._num_slots * self._M + 1
        self._num_blocks = int(num_blocks)
        if self._num_blocks < self._M + 1:
            raise ValueError(
                f"num_blocks={self._num_blocks} cannot hold even one "
                f"max-length sequence ({self._M} blocks) plus the "
                "trash block; raise num_blocks or lower max_model_len")
        bks = sorted(set(int(b) for b in (
            prompt_buckets or _pow2_buckets(min(8, self._max_len),
                                            self._max_len))))
        if bks[-1] < self._max_len:
            bks.append(self._max_len)
        self._buckets = bks
        self._max_waiting = int(max_waiting)
        self._timeout_s = float(request_timeout_s)
        self._seed = int(seed)
        self._check_replay = bool(check_replay)

        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._waiting: List[_GenSeq] = []
        self._active: Dict[int, _GenSeq] = {}
        self._free_slots = list(range(self._num_slots))
        # block 0 is trash; LIFO free list for locality
        self._free_blocks = list(range(self._num_blocks - 1, 0, -1))
        self._running = False
        self._thread: Optional[threading.Thread] = None
        self._rid = 0
        self._arrival = 0
        self._compiles = 0
        self._compile_records: List[dict] = []
        self._stats = {
            "submitted": 0, "admitted": 0, "readmitted": 0,
            "evicted": 0, "finished": 0, "shed_overload": 0,
            "shed_timeout": 0, "tokens_generated": 0,
            "decode_steps": 0, "replay_steps": 0,
            "decode_ms": 0.0, "prefill_ms": 0.0,
            "prefill_bucket_hits": {b: 0 for b in self._buckets},
        }

        # device state: params + pools + compiled step fns (lazy so the
        # constructor stays cheap; start() builds everything)
        self._pvals = None
        self._pools = None
        self._decode_fn = None
        self._prefill_fn = None

    # -- program construction ----------------------------------------
    def _build_programs(self):
        import jax
        import jax.numpy as jnp

        from ..framework.core import Tensor, no_grad

        model = self._model
        self._pvals = {k: t._value for k, t in model.state_dict().items()}
        self._pools = model.init_paged_cache(self._num_blocks, self._bs)
        server = self

        def call_model(pvals, ids, pos, pools, tables, wm,
                       gather_at=None):
            st = model.state_dict()
            old = {k: t._value for k, t in st.items()}
            try:
                for k, t in st.items():
                    if k in pvals:
                        t._value = pvals[k]
                with no_grad():
                    logits, pools = model.forward_paged(
                        Tensor(ids), Tensor(pos), pools, tables, wm,
                        gather_at=gather_at)
            finally:
                for k, t in st.items():
                    t._value = old[k]
            lv = logits._value if isinstance(logits, Tensor) else logits

            def raw(v):
                return v._value if isinstance(v, Tensor) else v
            pools = [{kk: raw(vv) for kk, vv in d.items()}
                     for d in pools]
            return lv, pools

        def sample(lg, kd, rng_steps, temp, top_k, top_p, do_sample):
            """Per-slot next-token selection: exact argmax for greedy
            slots, temperature/top-k/top-p categorical for sampling
            slots — one program covers any mix.  The key for token j of
            a request is fold_in(request_key, j-1): a pure function of
            the stream position, so replay after eviction reproduces
            the draw exactly."""
            V = lg.shape[-1]
            greedy = jnp.argmax(lg, axis=-1).astype(jnp.int32)
            x = lg / jnp.maximum(temp, 1e-6)[:, None]
            srt = jnp.sort(x, axis=-1)[:, ::-1]
            kk = jnp.clip(top_k, 1, V).astype(jnp.int32)
            kth = jnp.take_along_axis(srt, (kk - 1)[:, None], axis=-1)
            use_k = ((top_k > 0) & (top_k < V))[:, None]
            x = jnp.where(use_k & (x < kth), -jnp.inf, x)
            srt2 = jnp.sort(x, axis=-1)[:, ::-1]
            probs = jax.nn.softmax(srt2, axis=-1)
            cum = jnp.cumsum(probs, axis=-1)
            keep = jnp.maximum((cum < top_p[:, None]).sum(-1) + 1, 1)
            kth2 = jnp.take_along_axis(srt2, (keep - 1)[:, None],
                                       axis=-1)
            use_p = (top_p < 1.0)[:, None]
            x = jnp.where(use_p & (x < kth2), -jnp.inf, x)
            impl = {2: "threefry2x32", 4: "rbg"}.get(
                int(kd.shape[-1]), "threefry2x32")
            base = jax.random.wrap_key_data(kd, impl=impl)
            keys = jax.vmap(jax.random.fold_in)(base, rng_steps)
            sampled = jax.vmap(jax.random.categorical)(keys, x)
            return jnp.where(do_sample, sampled.astype(jnp.int32),
                             greedy)

        def decode_fn(pvals, pools, tokens, positions, tables, wm, kd,
                      rng_steps, temp, top_k, top_p, do_sample):
            # python side effect runs at TRACE time only: the counter
            # proves steady-state decode never retraces
            server._compiles += 1
            server._note_compile("decode", tokens.shape[0])
            logits, pools = call_model(pvals, tokens, positions, pools,
                                       tables, wm)
            lg = logits[:, -1, :].astype(jnp.float32)
            nxt = sample(lg, kd, rng_steps, temp, top_k, top_p,
                         do_sample)
            return nxt, pools

        def prefill_fn(pvals, pools, prompt, length, table, kd, temp,
                       top_k, top_p, do_sample):
            server._compiles += 1
            server._note_compile("prefill", prompt.shape[1])
            B, Lb = prompt.shape
            pos = jnp.broadcast_to(
                jnp.arange(Lb, dtype=jnp.int32)[None, :], (B, Lb))
            wm = pos < length[:, None]
            gather_at = jnp.clip(length - 1, 0, Lb - 1)
            logits, pools = call_model(pvals, prompt, pos, pools, table,
                                       wm, gather_at=gather_at)
            lg = logits[:, -1, :].astype(jnp.float32)
            first = sample(lg, kd, jnp.zeros_like(length), temp, top_k,
                           top_p, do_sample)
            return first, pools

        # donate the pools: each step consumes the previous pool
        # buffers in place (the CPU backend can't donate — skip the
        # unusable-donation warning there)
        donate = () if jax.default_backend() == "cpu" else (1,)
        self._decode_fn = jax.jit(decode_fn, donate_argnums=donate)
        self._prefill_fn = jax.jit(prefill_fn, donate_argnums=donate)

    def _note_compile(self, program: str, width: int):
        """Runs inside a trace: log the compile to the server's shared
        bucket-compile table and the flight recorder's observatory."""
        cause = "prewarm" if not self._running else "new_shape_bucket"
        self._compile_records.append(
            {"program": program, "bucket": int(width), "cause": cause})
        _flight.note_compile(f"GenerationServer[{program}]", cause, 0.0,
                             key=(program, int(width)),
                             n_buckets=self._compiles)

    # -- lifecycle ---------------------------------------------------
    def start(self, prewarm: bool = True) -> "GenerationServer":
        if self._running:
            return self
        if self._decode_fn is None:
            self._build_programs()
        if prewarm:
            self._prewarm()
        self._running = True
        self._thread = threading.Thread(target=self._loop,
                                        name="generation-server",
                                        daemon=True)
        self._thread.start()
        return self

    def _prewarm(self):
        """Compile every program before traffic: each prompt bucket's
        prefill + the single decode program.  Dummy calls write only to
        the trash block (write masks all False), so the pools' live
        contents are untouched by construction."""
        W = int(np.asarray(self._seq_key_data(0)).shape[-1])
        for b in self._buckets:
            first, self._pools = self._prefill_fn(
                self._pvals, self._pools,
                np.zeros((1, b), np.int32), np.zeros((1,), np.int32),
                np.zeros((1, self._M), np.int32),
                np.zeros((1, W), np.uint32),
                np.ones((1,), np.float32), np.zeros((1,), np.int32),
                np.ones((1,), np.float32), np.zeros((1,), bool))
        B = self._num_slots
        nxt, self._pools = self._decode_fn(
            self._pvals, self._pools,
            np.zeros((B, 1), np.int32), np.zeros((B, 1), np.int32),
            np.zeros((B, self._M), np.int32), np.zeros((B, 1), bool),
            np.zeros((B, W), np.uint32), np.zeros((B,), np.int32),
            np.ones((B,), np.float32), np.zeros((B,), np.int32),
            np.ones((B,), np.float32), np.zeros((B,), bool))
        np.asarray(nxt)   # block until the warmup step really ran

    def stop(self, drain: bool = False, timeout: float = 30.0):
        if not self._running:
            return
        if drain:
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                with self._lock:
                    if not self._active and not self._waiting:
                        break
                time.sleep(0.005)
        with self._cond:
            self._running = False
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
        with self._lock:
            leftovers = list(self._waiting) + list(self._active.values())
            self._waiting.clear()
        for seq in leftovers:
            self._release(seq)
            seq.stream._fail(ServerClosed("server stopped"))

    def __enter__(self) -> "GenerationServer":
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- client surface ----------------------------------------------
    def _seq_key_data(self, seed: int):
        from ..framework.random import key_to_data, make_key
        return np.asarray(key_to_data(make_key(seed))).astype(np.uint32)

    def submit(self, prompt, max_new_tokens: int = 32,
               do_sample: bool = False, temperature: float = 1.0,
               top_k: int = 0, top_p: float = 1.0,
               eos_token_id: Optional[int] = None,
               seed: Optional[int] = None, priority: int = 0,
               timeout_s: Optional[float] = None) -> GenerationStream:
        """Enqueue one generation request; returns a
        :class:`GenerationStream` that yields tokens as decode steps
        complete.  ``priority``: lower = more important (evicted last).
        ``seed`` fixes the request's sampling RNG stream (default:
        derived from the server seed + request id).  Raises
        :class:`ServerOverloaded` at the waiting-queue cap."""
        if not self._running:
            raise ServerClosed("server not started")
        p = np.asarray(prompt.numpy() if hasattr(prompt, "numpy")
                       else prompt).astype(np.int32).reshape(-1)
        if p.size < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if p.size + max_new_tokens > self._max_len:
            raise ValueError(
                f"prompt ({p.size}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds max_model_len={self._max_len}")
        if do_sample and float(temperature) == 0.0:
            do_sample = False      # temperature 0.0 IS greedy (exact)
        to = self._timeout_s if timeout_s is None else float(timeout_s)
        with self._cond:
            if len(self._waiting) >= self._max_waiting:
                self._stats["shed_overload"] += 1
                shed_depth = len(self._waiting)
            else:
                self._rid += 1
                self._arrival += 1
                key_data = self._seq_key_data(
                    self._seed * 1000003 + self._rid
                    if seed is None else int(seed))
                seq = _GenSeq(self._rid, p, max_new_tokens,
                              eos_token_id, do_sample, temperature,
                              top_k, top_p, key_data, priority,
                              self._arrival, time.monotonic() + to)
                self._waiting.append(seq)
                self._stats["submitted"] += 1
                self._cond.notify_all()
                shed_depth = None
        if shed_depth is not None:
            _monitor.stat_add("serve_shed_overload")
            _flight.record("serve.shed", reason="overload",
                           depth=shed_depth, server="generation")
            _flight.maybe_dump("ServerOverloaded")
            raise ServerOverloaded(
                f"waiting-queue cap {self._max_waiting} reached; "
                "request shed — back off and retry") from None
        if _monitor.metrics_enabled():
            _monitor.gauge_set("serve_gen_waiting", len(self._waiting))
        return seq.stream

    def generate_sync(self, prompt, timeout: Optional[float] = None,
                      **kw) -> List[int]:
        """Blocking submit + collect (the per-client bench call)."""
        return self.submit(prompt, **kw).result(timeout=timeout)

    def num_compiles(self) -> int:
        """Distinct program traces (prefill buckets + the decode
        program).  Steady state after warmup: delta == 0."""
        return self._compiles

    def stats(self) -> Dict:
        with self._lock:
            s = {k: (dict(v) if isinstance(v, dict) else v)
                 for k, v in self._stats.items()}
            s["waiting"] = len(self._waiting)
            s["active"] = len(self._active)
            s["free_blocks"] = len(self._free_blocks)
            s["allocated_blocks"] = (self._num_blocks - 1
                                     - len(self._free_blocks))
            records = list(self._compile_records)
        s["total_blocks"] = self._num_blocks - 1   # trash excluded
        s["block_size"] = self._bs
        s["num_slots"] = self._num_slots
        s["num_compiles"] = self._compiles
        # shared bucket-compile accounting shape with
        # PredictorServer.stats() (ISSUE 8 satellite): per program
        # bucket -> {count, cause}
        bc: Dict = {}
        for r in records:
            key = f"{r['program']}:{r['bucket']}"
            ent = bc.setdefault(key, {"count": 0, "cause": r["cause"]})
            ent["count"] += 1
        s["bucket_compiles"] = bc
        s["prewarm_compiles"] = sum(1 for r in records
                                    if r["cause"] == "prewarm")
        s["traffic_compiles"] = sum(1 for r in records
                                    if r["cause"] != "prewarm")
        return s

    # -- scheduler ---------------------------------------------------
    def _loop(self):
        try:
            while True:
                with self._cond:
                    if not self._running:
                        return
                    if not self._active and not self._waiting:
                        self._cond.wait(timeout=0.05)
                        continue
                self._expire_waiting()
                self._admit()
                if self._active:
                    self._decode_once()
        except BaseException as e:   # noqa: BLE001 — fail streams loudly
            with self._lock:
                victims = (list(self._waiting)
                           + list(self._active.values()))
                self._waiting.clear()
                self._active.clear()
                self._running = False
            for seq in victims:
                seq.stream._fail(ServeError(
                    f"generation scheduler died: {e!r}"))
            raise

    def _expire_waiting(self):
        now = time.monotonic()
        with self._lock:
            expired = [s for s in self._waiting if now > s.deadline]
            if not expired:
                return
            self._waiting = [s for s in self._waiting
                             if now <= s.deadline]
            for s in expired:
                self._stats["shed_timeout"] += 1
        for s in expired:
            _monitor.stat_add("serve_shed_timeout")
            _flight.record("serve.shed", reason="timeout", rid=s.rid,
                           waited_ms=round((now - s.t_submit) * 1e3, 1),
                           evictions=s.evictions, server="generation")
            _flight.record("serve.stream_end", rid=s.rid,
                           reason="timeout", tokens=len(s.generated))
            s.stream._fail(RequestTimeout(
                f"request {s.rid} spent its whole deadline "
                + ("evicted and waiting for re-admission"
                   if s.evictions else "queued")
                + " — pool/slots overloaded"))

    def _admit(self):
        while True:
            with self._lock:
                if not self._waiting or not self._free_slots:
                    return
                self._waiting.sort(key=lambda s: (s.priority, s.arrival))
                seq = self._waiting[0]
                # ceil(L/bs) blocks for the prompt, +1 headroom when L
                # lands exactly on a block boundary (the first decode
                # write would otherwise evict immediately)
                need = seq.L // self._bs + 1
                if len(self._free_blocks) < need:
                    return   # strict priority order: no queue jumping
                self._waiting.pop(0)
                nb = -(-seq.L // self._bs)
                seq.blocks = [self._free_blocks.pop()
                              for _ in range(nb)]
                seq.slot = self._free_slots.pop()
                self._active[seq.slot] = seq
            self._prefill(seq)

    def _bucket_for(self, L: int) -> int:
        for b in self._buckets:
            if L <= b:
                return b
        return self._buckets[-1]

    def _prefill(self, seq: _GenSeq):
        bucket = self._bucket_for(seq.L)
        prompt = np.zeros((1, bucket), np.int32)
        prompt[0, :seq.L] = seq.prompt
        table = np.zeros((1, self._M), np.int32)
        table[0, :len(seq.blocks)] = seq.blocks
        t0 = time.perf_counter()
        first, self._pools = self._prefill_fn(
            self._pvals, self._pools, prompt,
            np.asarray([seq.L], np.int32), table,
            seq.key_data[None, :], np.asarray([seq.temp], np.float32),
            np.asarray([seq.top_k], np.int32),
            np.asarray([seq.top_p], np.float32),
            np.asarray([seq.do_sample], bool))
        first = int(np.asarray(first)[0])
        dt_ms = (time.perf_counter() - t0) * 1e3
        readmit = seq.evictions > 0
        with self._lock:
            self._stats["admitted"] += 1
            self._stats["readmitted"] += int(readmit)
            self._stats["prefill_ms"] += dt_ms
            self._stats["prefill_bucket_hits"][bucket] = \
                self._stats["prefill_bucket_hits"].get(bucket, 0) + 1
        _monitor.stat_add("serve_gen_admitted")
        _flight.record("serve.admit", rid=seq.rid, prompt_len=seq.L,
                       bucket=bucket, blocks=len(seq.blocks),
                       slot=seq.slot, readmit=readmit,
                       priority=seq.priority)
        if _monitor.metrics_enabled():
            _monitor.hist_observe("prefill_ms", dt_ms)
            _monitor.gauge_set("serve_gen_active", len(self._active))
            _monitor.gauge_set("serve_gen_free_blocks",
                               len(self._free_blocks))
        seq.decoded = 0
        if readmit:
            # replay: prefill re-derives t1 from the identical program
            # + inputs; the stored token is authoritative either way
            if self._check_replay and first != seq.generated[0]:
                raise AssertionError(
                    f"re-prefill of request {seq.rid} resampled token 1 "
                    f"as {first}, stream already emitted "
                    f"{seq.generated[0]} — paged prefill is not "
                    "bit-stable")
        else:
            self._emit(seq, first)

    def _emit(self, seq: _GenSeq, tok: int):
        seq.generated.append(tok)
        if seq.t_first_tok is None:
            seq.t_first_tok = time.monotonic()
            if _monitor.metrics_enabled():
                _monitor.hist_observe(
                    "serve_ttft_ms",
                    (seq.t_first_tok - seq.t_submit) * 1e3)
        seq.stream._emit(tok)
        with self._lock:
            self._stats["tokens_generated"] += 1
        if (seq.eos is not None and tok == seq.eos) \
                or len(seq.generated) >= seq.max_new:
            reason = ("eos" if seq.eos is not None and tok == seq.eos
                      else "length")
            self._finish(seq, reason)

    def _finish(self, seq: _GenSeq, reason: str):
        self._release(seq)
        with self._lock:
            self._stats["finished"] += 1
        _monitor.stat_add("serve_gen_finished")
        _flight.record("serve.stream_end", rid=seq.rid, reason=reason,
                       tokens=len(seq.generated),
                       evictions=seq.evictions)
        seq.stream._end(reason)

    def _release(self, seq: _GenSeq):
        """Return a sequence's blocks + slot to the pools immediately."""
        with self._lock:
            if seq.blocks:
                self._free_blocks.extend(seq.blocks)
                seq.blocks = []
            if seq.slot is not None:
                self._active.pop(seq.slot, None)
                self._free_slots.append(seq.slot)
                seq.slot = None

    def _evict(self, seq: _GenSeq):
        """Block-pool exhaustion: free the victim's blocks and send it
        back to the waiting queue (its generated tokens are kept; re-
        admission re-prefills + replays them bit-identically)."""
        freed = len(seq.blocks)
        self._release(seq)
        seq.decoded = 0
        seq.evictions += 1
        with self._lock:
            self._stats["evicted"] += 1
            self._waiting.append(seq)
        _monitor.stat_add("serve_gen_evicted")
        _flight.record("serve.evict", rid=seq.rid,
                       reason="pool_exhausted", freed_blocks=freed,
                       tokens_so_far=len(seq.generated),
                       priority=seq.priority, evictions=seq.evictions)
        _flight.maybe_dump("BlockPoolExhausted")

    def _grow_or_evict(self):
        """Before a decode step every live sequence must own the block
        its next K/V write lands in; a dry pool evicts the lowest-
        priority sequence (highest priority number, then youngest)."""
        for seq in sorted(self._active.values(), key=lambda s: s.slot):
            if seq.slot is None:
                continue      # evicted below us this round
            p = seq.L + seq.decoded          # position written next
            need = p // self._bs + 1
            while len(seq.blocks) < need and seq.slot is not None:
                with self._lock:
                    blk = (self._free_blocks.pop()
                           if self._free_blocks else None)
                    if blk is not None:
                        seq.blocks.append(blk)
                        continue
                victim = max(self._active.values(),
                             key=lambda s: (s.priority, s.arrival))
                self._evict(victim)
                # the growing sequence itself can be the lowest
                # priority: it re-queues and this slot sits out

    def _decode_once(self):
        self._grow_or_evict()
        with self._lock:
            live = sorted(self._active.values(), key=lambda s: s.slot)
        if not live:
            return
        B, M = self._num_slots, self._M
        W = live[0].key_data.shape[-1]
        tokens = np.zeros((B, 1), np.int32)
        positions = np.zeros((B, 1), np.int32)
        tables = np.zeros((B, M), np.int32)
        wm = np.zeros((B, 1), bool)
        kd = np.zeros((B, W), np.uint32)
        rng_steps = np.zeros((B,), np.int32)
        temp = np.ones((B,), np.float32)
        top_k = np.zeros((B,), np.int32)
        top_p = np.ones((B,), np.float32)
        do_sample = np.zeros((B,), bool)
        for seq in live:
            s = seq.slot
            tokens[s, 0] = seq.generated[seq.decoded]
            positions[s, 0] = seq.L + seq.decoded
            tables[s, :len(seq.blocks)] = seq.blocks
            wm[s, 0] = True
            kd[s] = seq.key_data
            rng_steps[s] = seq.decoded + 1
            temp[s] = seq.temp
            top_k[s] = seq.top_k
            top_p[s] = seq.top_p
            do_sample[s] = seq.do_sample
        t0 = time.perf_counter()
        nxt, self._pools = self._decode_fn(
            self._pvals, self._pools, tokens, positions, tables, wm,
            kd, rng_steps, temp, top_k, top_p, do_sample)
        nxt = np.asarray(nxt)
        dt_ms = (time.perf_counter() - t0) * 1e3
        replays = 0
        for seq in live:
            s = seq.slot
            seq.decoded += 1
            j = seq.decoded + 1          # 1-based index produced
            if j <= len(seq.generated):
                replays += 1             # catching up after eviction
                if self._check_replay \
                        and int(nxt[s]) != seq.generated[j - 1]:
                    raise AssertionError(
                        f"replayed decode step for request {seq.rid} "
                        f"produced {int(nxt[s])}, stream already "
                        f"emitted {seq.generated[j - 1]} — paged "
                        "decode is not bit-stable")
            else:
                self._emit(seq, int(nxt[s]))
        with self._lock:
            self._stats["decode_steps"] += 1
            self._stats["replay_steps"] += replays
            self._stats["decode_ms"] += dt_ms
            n_steps = self._stats["decode_steps"]
        _flight.progress("serve.decode")
        if n_steps % _FLIGHT_DECODE_EVERY == 0:
            _flight.record("serve.decode", steps=n_steps,
                           live=len(live),
                           free_blocks=len(self._free_blocks),
                           ms=round(dt_ms, 3))
        if _monitor.metrics_enabled():
            _monitor.hist_observe("decode_step_ms", dt_ms)
            _monitor.gauge_set("serve_gen_active", len(self._active))
            _monitor.gauge_set("serve_gen_free_blocks",
                               len(self._free_blocks))
