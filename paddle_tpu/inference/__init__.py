"""paddle_tpu.inference — deployment API over exported StableHLO.

Parity target: the reference inference engine
(reference: paddle/fluid/inference/api/analysis_predictor.h:82
AnalysisPredictor, paddle_analysis_config.h AnalysisConfig,
python/paddle/inference/).  The reference loads a serialized ProgramDesc,
runs ~100 IR analysis passes (fusion, memory optim, TensorRT subgraph
capture) and executes op-by-op with zero-copy feed/fetch
(analysis_predictor.cc:168 init, :215 PrepareProgram, :231
OptimizeInferenceProgram, ZeroCopyRun).

TPU-native collapse: the serialized artifact is StableHLO (written by
``paddle_tpu.jit.save``), so the entire analysis/optimization pipeline is
XLA compilation — fusion, layout, memory planning happen at load time via
``jax.jit`` of the deserialized function.  What remains for this layer is
the deployment surface: Config (device/precision knobs), Predictor with
named zero-copy input/output handles, and batch-size-polymorphic
execution (the export uses symbolic batch dims, so one artifact serves
any batch size — the reference needs TensorRT dynamic-shape profiles for
that).
"""
from __future__ import annotations

import os
import pickle
import time as _time
from typing import Dict, List, Optional

import numpy as np

from ..observability import flight_recorder as _flight

__all__ = ["Config", "Predictor", "Tensor", "create_predictor",
           "PrecisionType", "PlaceType", "get_version",
           "PredictorServer", "GenerationServer", "GenerationStream",
           "PrefixCache", "ServeError", "ServerOverloaded",
           "UpstreamUnavailable", "ServerClosed", "RequestTimeout",
           "ServerDraining", "GatewayRouter", "LocalReplica",
           "RemoteReplica", "GenerationRpcServer", "ReplicaLost",
           "MigrationUnsupported",
           "enable_compile_cache"]


def get_version() -> str:
    from .. import __version__
    return __version__


class PrecisionType:
    """Parity: paddle_analysis_config.h Precision enum."""
    Float32 = 0
    Half = 1      # on TPU: bfloat16 (MXU-native), not IEEE fp16
    Bfloat16 = 1
    Int8 = 2


class PlaceType:
    kUNK = -1
    kCPU = 0
    kGPU = 1   # accepted for API compat; maps to the accelerator (TPU)
    kTPU = 2
    kXPU = 3


class Config:
    """Inference config (parity: AnalysisConfig,
    reference paddle/fluid/inference/api/paddle_analysis_config.h).

    Accepts ``Config(model_dir)`` or ``Config(prog_file, params_file)``
    like the reference; here both name the ``jit.save`` path prefix
    (``<prefix>.pdmodel`` + ``<prefix>.pdiparams``).
    """

    def __init__(self, model_arg: Optional[str] = None,
                 params_file: Optional[str] = None):
        self._model_dir = None
        self._prog_file = None
        self._params_file = None
        if model_arg is not None and params_file is not None:
            self._prog_file = model_arg
            self._params_file = params_file
        elif model_arg is not None:
            self._model_dir = model_arg
        self._use_accelerator = True      # TPU by default when present
        self._device_id = 0
        self._precision = PrecisionType.Float32
        self._ir_optim = True             # recorded; XLA always optimizes
        self._memory_optim = True
        self._cpu_math_threads = 1
        self._enable_profile = False
        self._donate_inputs = False
        # persistent XLA compile cache (reference API name:
        # AnalysisConfig::SetOptimCacheDir — there it caches optimized
        # IR programs, here serialized XLA executables): "auto" resolves
        # to $PADDLE_INFER_CACHE_DIR or ~/.cache/paddle_tpu/xla_cache;
        # None/"" disables.  A second process cold-loads its compiled
        # program from this cache instead of re-running XLA.
        self._optim_cache_dir = "auto"
        self._load_batch = 1              # batch the load-time AOT uses

    # -- model paths -------------------------------------------------
    def set_model(self, model_arg, params_file=None):
        self._model_dir = self._prog_file = self._params_file = None
        if params_file is not None:
            self._prog_file = model_arg
            self._params_file = params_file
        else:
            self._model_dir = model_arg

    def model_dir(self):
        return self._model_dir

    def prog_file(self):
        return self._prog_file

    def params_file(self):
        return self._params_file

    def _path_prefix(self):
        p = self._model_dir if self._model_dir is not None else self._prog_file
        if p is None:
            raise ValueError("Config has no model path; pass Config(path) "
                             "or use set_model()")
        # accept ".pdmodel" file path, a bare prefix, or a directory
        if p.endswith(".pdmodel"):
            return p[:-len(".pdmodel")]
        if os.path.isdir(p):
            cands = sorted(f for f in os.listdir(p)
                           if f.endswith(".pdmodel"))
            if not cands:
                raise FileNotFoundError(f"no .pdmodel under {p}")
            if len(cands) > 1:
                raise ValueError(
                    f"ambiguous model dir {p}: {cands}; pass the .pdmodel "
                    "path explicitly")
            return os.path.join(p, cands[0][:-len(".pdmodel")])
        return p

    def _params_path(self):
        """Params file: the explicit Config(prog, params) path wins,
        else <prefix>.pdiparams."""
        if self._params_file is not None:
            return self._params_file
        return self._path_prefix() + ".pdiparams"

    # -- device ------------------------------------------------------
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        # API-compat name; selects the accelerator (TPU). Memory pool
        # size is meaningless under XLA's allocator — recorded only.
        _warn_inert("enable_use_gpu",
                    "maps to the TPU accelerator; memory_pool_init_size"
                    "_mb is ignored (XLA owns device memory)")
        self._use_accelerator = True
        self._device_id = device_id

    def enable_use_tpu(self, device_id=0):
        self._use_accelerator = True
        self._device_id = device_id

    def disable_gpu(self):
        self._use_accelerator = False

    def use_gpu(self):
        return self._use_accelerator

    def gpu_device_id(self):
        return self._device_id

    def set_cpu_math_library_num_threads(self, n):
        _warn_inert("set_cpu_math_library_num_threads",
                    "recorded only; XLA owns host threading")
        self._cpu_math_threads = int(n)

    # -- precision / optimization ------------------------------------
    def enable_bf16(self):
        """TPU-native half precision: cast weights + compute to bf16."""
        self._precision = PrecisionType.Bfloat16

    enable_mkldnn_bfloat16 = enable_bf16   # reference API name

    def switch_ir_optim(self, flag=True):
        if not flag:
            _warn_inert("switch_ir_optim",
                        "False has no effect; XLA always optimizes the "
                        "compiled program")
        self._ir_optim = bool(flag)

    def ir_optim(self):
        return self._ir_optim

    def enable_memory_optim(self, flag=True):
        _warn_inert("enable_memory_optim",
                    "recorded only; XLA's buffer assignment already "
                    "reuses memory")
        self._memory_optim = bool(flag)

    def enable_profile(self):
        self._enable_profile = True

    def set_optim_cache_dir(self, path: Optional[str]):
        """Directory for the persistent compile cache (reference:
        AnalysisConfig::SetOptimCacheDir).  ``"auto"`` (the default)
        resolves to ``$PADDLE_INFER_CACHE_DIR`` or
        ``~/.cache/paddle_tpu/xla_cache``; ``None`` or ``""`` disables
        cross-process caching for predictors built from this config."""
        self._optim_cache_dir = path

    def set_load_batch(self, batch: int):
        """Batch size the load-time AOT compile specializes symbolic
        dims to (default 1).  Additional shapes compile on first use or
        via :meth:`Predictor.prewarm`."""
        self._load_batch = int(batch)

    def switch_use_feed_fetch_ops(self, flag):
        _warn_inert("switch_use_feed_fetch_ops",
                    "no feed/fetch ops exist under XLA — zero-copy "
                    "always")

    def switch_specify_input_names(self, flag=True):
        pass

    def enable_tensorrt_engine(self, *a, **kw):
        # The TensorRT subgraph role (fused low-precision serving) is
        # XLA compilation itself; bf16 covers the Half precision mode.
        _warn_inert("enable_tensorrt_engine",
                    "TensorRT does not exist on TPU; Half/Int8 "
                    "precision modes map to bf16 XLA compilation, other "
                    "arguments are ignored")
        prec = kw.get("precision_mode", PrecisionType.Float32)
        if prec in (PrecisionType.Half, PrecisionType.Int8):
            self._precision = PrecisionType.Bfloat16

    def tensorrt_engine_enabled(self):
        return False

    def summary(self) -> str:
        return ("Config(model=%s, accelerator=%s, precision=%s, "
                "ir_optim=%s)" % (self._path_prefix(), self._use_accelerator,
                                  self._precision, self._ir_optim))



def _warn_inert(knob: str, detail: str):
    """One warning per inert reference knob (the fleet strategy surface
    does the same via warn_noop_toggles — silent divergence from user
    intent is worse than noise)."""
    import warnings
    if knob not in _warned_knobs:
        _warned_knobs.add(knob)
        warnings.warn(f"inference.Config.{knob}: {detail}", stacklevel=3)


_warned_knobs: set = set()


class Tensor:
    """Zero-copy input/output handle (parity: ZeroCopyTensor,
    reference paddle/fluid/inference/api/details/zero_copy_tensor.cc).
    """

    def __init__(self, name: str, shape, dtype):
        self._name = name
        self._shape = list(shape)
        self._dtype = np.dtype(dtype)
        self._data: Optional[np.ndarray] = None

    @property
    def name(self):
        return self._name

    def reshape(self, shape):
        self._shape = list(shape)

    def shape(self):
        if self._data is not None:
            return list(self._data.shape)
        return self._shape

    def copy_from_cpu(self, arr: np.ndarray):
        arr = np.ascontiguousarray(arr)
        if self._dtype is not None and arr.dtype != self._dtype:
            arr = arr.astype(self._dtype)
        self._data = arr
        self._shape = list(arr.shape)

    def copy_to_cpu(self) -> np.ndarray:
        if self._data is None:
            raise RuntimeError(f"output '{self._name}' not computed yet; "
                               "call predictor.run() first")
        return np.asarray(self._data)

    # numpy-style convenience
    def numpy(self):
        return self.copy_to_cpu()


def _resolve_cache_dir(config: Config) -> Optional[str]:
    d = getattr(config, "_optim_cache_dir", None)
    if d == "auto":
        d = os.environ.get("PADDLE_INFER_CACHE_DIR") or os.path.join(
            os.path.expanduser("~"), ".cache", "paddle_tpu", "xla_cache")
    return d or None


_cache_dir_enabled: Optional[str] = None


def enable_compile_cache(path: str):
    """Point JAX's persistent compilation cache at ``path`` (idempotent;
    first caller wins for the process).  Every XLA executable the
    Predictor AOT-compiles is then serialized to disk, so a SECOND
    process loading the same artifact skips XLA entirely — this is what
    makes cold-load-to-first-inference a disk read instead of a compile
    (reference analog: AnalysisConfig::SetOptimCacheDir persisting the
    optimized program)."""
    global _cache_dir_enabled
    if _cache_dir_enabled is not None:
        return _cache_dir_enabled
    import jax
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    # serving programs are small and compile fast — cache them anyway
    # (the defaults skip sub-second compiles, which is every smoke model)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    try:
        jax.config.update("jax_persistent_cache_enable_xla_caches", "all")
    except Exception:      # older jax: knob absent, cache still works
        pass
    # any compile BEFORE the dir was set froze the lazily-initialized
    # cache in its disabled state for the whole process (jax memoizes
    # the init); reset so the predictor's compiles actually persist
    try:
        from jax._src import compilation_cache as _cc
        _cc.reset_cache()
    except Exception:      # pragma: no cover - internal API moved
        pass
    _cache_dir_enabled = path
    return path


class Predictor:
    """Compile-once AOT predictor over a deserialized StableHLO artifact
    (parity: AnalysisPredictor, reference
    inference/api/analysis_predictor.cc:168).

    The constructor deserializes the export and AOT-compiles it against
    the meta's input specs (``jax.jit(...).lower(...).compile()``) —
    load time IS compile time, exactly like the reference's
    OptimizeInferenceProgram.  ``run()`` then only looks up the
    executable for its input shapes and dispatches: no retracing, no
    per-call Python flatten of the outputs, no handle-skeleton rebuild.
    One executable exists per input-shape signature (``num_compiles()``
    counts them; a steady-state server holds one per batch bucket), and
    with the persistent compile cache enabled (default) a second
    process cold-loads executables from disk instead of re-running XLA.
    """

    def __init__(self, config: Config):
        import jax
        import jax.numpy as jnp
        from jax import export as jexport

        self._config = config
        cache_dir = _resolve_cache_dir(config)
        if cache_dir:
            enable_compile_cache(cache_dir)
        prefix = config._path_prefix()
        with open(prefix + ".pdmodel", "rb") as f:
            self._exported = jexport.deserialize(bytearray(f.read()))
        with open(config._params_path(), "rb") as f:
            blob = pickle.load(f)
        meta = {}
        if os.path.exists(prefix + ".pdmeta"):
            with open(prefix + ".pdmeta", "rb") as f:
                meta = pickle.load(f)
        self._meta = meta

        if config._use_accelerator:
            try:
                dev = jax.devices()[config._device_id]
            except Exception:
                dev = jax.devices("cpu")[0]
        else:
            dev = jax.devices("cpu")[0]
        self._device = dev

        # The exported program's parameter dtypes are baked into the
        # StableHLO, so bf16 serving stores weights in bf16 (halving HBM
        # footprint + load bandwidth) and upcasts inside one jitted
        # program around the exported call; the MXU executes f32 matmuls
        # as bf16 passes natively, so compute is already bf16-rate.
        bf16 = config._precision == PrecisionType.Bfloat16
        self._expected = {k: np.asarray(v).dtype
                          for k, v in {**blob["params"],
                                       **blob["buffers"]}.items()}

        def _put(v):
            a = jnp.asarray(v)
            if bf16 and a.dtype in (jnp.float32, jnp.float64):
                a = a.astype(jnp.bfloat16)
            return jax.device_put(a, dev)

        self._params = {k: _put(v) for k, v in blob["params"].items()}
        self._buffers = {k: _put(v) for k, v in blob["buffers"].items()}
        # exported artifacts bake the key SHAPE in at save time:
        # stay on portable threefry regardless of FLAGS_rng_impl
        self._rng = jax.random.PRNGKey(0)

        exported_call = self._exported.call
        if bf16:
            expected = self._expected

            def _model_call(params, buffers, rng, vals):
                # the upcast fuses into the compiled program; the f32
                # copies are compiler-managed, not per-run eager
                # materializations of the whole weight set
                up = lambda d: {k: v.astype(expected[k]) for k, v in
                                d.items()}
                return exported_call(up(params), up(buffers), rng,
                                     list(vals))
        else:
            def _model_call(params, buffers, rng, vals):
                return exported_call(params, buffers, rng, list(vals))

        def _flat_call(params, buffers, rng, vals):
            out, _bufs = _model_call(params, buffers, rng, vals)
            return tuple(_flatten(out))

        self._flat_call = _flat_call
        self._jit_call = jax.jit(_flat_call)
        self._executables: Dict[tuple, object] = {}
        self._compile_count = 0
        # per-executable compile provenance: shape key -> {cause,
        # batch, wall_ms} (PredictorServer.stats() surfaces these as
        # per-bucket prewarm/compile counts)
        self._compile_info: Dict[tuple, dict] = {}

        n = meta.get("n_inputs", len(meta.get("input_names", [])) or 1)
        names = meta.get("input_names") or [f"x{i}" for i in range(n)]
        shapes = meta.get("input_shapes") or [[-1]] * n
        dtypes = meta.get("input_dtypes") or ["float32"] * n
        self._input_names: List[str] = list(names)
        self._input_shapes = [list(s) for s in shapes]
        self._input_dtypes = [np.dtype(d) for d in dtypes]
        self._inputs: Dict[str, Tensor] = {
            nm: Tensor(nm, shp, dt)
            for nm, shp, dt in zip(names, shapes, dtypes)}
        self._output_names: List[str] = []
        self._outputs: Dict[str, Tensor] = {}

        # AOT compile at load against the meta input specs (symbolic
        # dims specialized: dim 0 -> load_batch, others -> 1).  Old
        # artifacts without recorded shapes keep the lazy compile-on-
        # first-run behavior.
        if meta.get("input_shapes"):
            try:
                self._compile_for_specs(self._specs_for_batch(
                    getattr(config, "_load_batch", 1)), cause="load")
            except Exception as e:     # pragma: no cover - degraded path
                import warnings
                warnings.warn(
                    "Predictor load-time AOT compile failed "
                    f"({type(e).__name__}: {e}); falling back to "
                    "compile-on-first-run", stacklevel=2)

    # -- AOT machinery -----------------------------------------------
    def _specs_for_batch(self, batch: int):
        """Concrete ShapeDtypeStructs from the meta input specs: the
        leading symbolic (-1) dim becomes ``batch``, interior symbolic
        dims become 1."""
        import jax
        specs = []
        for shp, dt in zip(self._input_shapes, self._input_dtypes):
            dims = []
            for j, d in enumerate(shp):
                if isinstance(d, int) and d >= 0:
                    dims.append(int(d))
                else:
                    dims.append(int(batch) if j == 0 else 1)
            specs.append(jax.ShapeDtypeStruct(tuple(dims), dt))
        return specs

    @staticmethod
    def _shape_key(vals) -> tuple:
        return tuple((tuple(int(d) for d in v.shape), str(v.dtype))
                     for v in vals)

    def _compile_for_specs(self, specs, cause: str = "new_shape_bucket"):
        """AOT lower + compile ONE executable for this input-shape
        signature; cache it and fix the output handle skeleton.  Each
        compile is logged to the flight recorder's compile observatory
        (cause = load / prewarm / new_shape_bucket, wall time, XLA
        memory analysis — the Predictor HOLDS its executables, so the
        memory observables are read off them for free)."""
        import jax
        key = self._shape_key(specs)
        exe = self._executables.get(key)
        if exe is not None:
            return exe
        t0 = _time.perf_counter()
        lowered = self._jit_call.lower(self._params, self._buffers,
                                       self._rng, tuple(specs))
        exe = lowered.compile()
        self._compile_count += 1
        self._executables[key] = exe
        try:
            batch = int(key[0][0][0])
        except (IndexError, TypeError, ValueError):
            batch = None
        self._compile_info[key] = {
            "cause": str(cause), "batch": batch,
            "wall_ms": round((_time.perf_counter() - t0) * 1e3, 3)}
        _flight.note_compile(
            f"Predictor[{os.path.basename(self._config._path_prefix())}]",
            cause, (_time.perf_counter() - t0) * 1e3,
            key=tuple(s for s, _ in key), compiled=exe,
            n_buckets=self._compile_count)
        if not self._output_names:
            out_avals = jax.eval_shape(self._flat_call, self._params,
                                       self._buffers, self._rng,
                                       tuple(specs))
            self._output_names = [f"out{i}"
                                  for i in range(len(out_avals))]
        return exe

    def num_compiles(self) -> int:
        """How many distinct XLA executables this predictor built — the
        steady-state zero-retrace contract: one per (model, input-shape
        bucket), never one per call."""
        return self._compile_count

    def compiled_shapes(self) -> List[tuple]:
        return list(self._executables.keys())

    def compile_records(self) -> List[dict]:
        """One record per built executable: {cause, batch, wall_ms} —
        cause is load / prewarm / new_shape_bucket.  The serving tier
        aggregates these into per-bucket compile counts."""
        return [dict(v) for v in self._compile_info.values()]

    def prewarm(self, batch_sizes) -> "Predictor":
        """Compile (or cache-load) the executable for each batch size
        ahead of traffic — a serving bucket never pays its compile
        inside a request."""
        for b in batch_sizes:
            self._compile_for_specs(self._specs_for_batch(int(b)),
                                    cause="prewarm")
        return self

    # -- handles -----------------------------------------------------
    def get_input_names(self) -> List[str]:
        return list(self._input_names)

    def get_input_handle(self, name: str) -> Tensor:
        return self._inputs[name]

    def get_output_names(self) -> List[str]:
        return list(self._output_names)

    def get_output_handle(self, name: str) -> Tensor:
        return self._outputs[name]

    # -- execution ---------------------------------------------------
    def run(self, inputs: Optional[List[np.ndarray]] = None):
        """Execute. Either pre-fill input handles (reference style) or
        pass arrays positionally; returns the list of output arrays.

        Steady state this is a dict lookup + one XLA dispatch: the
        executable, output names and handle skeleton were all fixed at
        compile time (load, prewarm, or this shape's first call)."""
        if inputs is not None:
            for nm, arr in zip(self._input_names, inputs):
                self._inputs[nm].copy_from_cpu(np.asarray(arr))
        vals = []
        for nm in self._input_names:
            h = self._inputs[nm]
            if h._data is None:
                raise RuntimeError(f"input '{nm}' has no data; call "
                                   "copy_from_cpu first")
            vals.append(h._data)

        exe = self._executables.get(self._shape_key(vals))
        if exe is None:
            exe = self._compile_for_specs(vals)
        flat = exe(self._params, self._buffers, self._rng, tuple(vals))

        if not self._outputs or len(self._outputs) != len(flat):
            self._outputs = {nm: Tensor(nm, (), np.float32)
                             for nm in self._output_names[:len(flat)]}
        results = []
        for nm, v in zip(self._output_names, flat):
            a = np.asarray(v)
            t = self._outputs[nm]
            t._data = a
            t._shape = list(a.shape)
            t._dtype = a.dtype
            results.append(a)
        return results

    # -- static analysis ---------------------------------------------
    def audit(self, batch: Optional[int] = None,
              include_hlo: bool = False, **thresholds):
        """Run the jaxpr program auditor (GraftLint pillar 1,
        :mod:`paddle_tpu.analysis`) over the serving program for one
        batch bucket (default: the load batch).

        Donation checking is off — a predictor's weights are reused
        across calls by design, never donated — so the rules that apply
        are dtype creep (an artifact exported f32 but silently upcast,
        or f64 creep in a custom head), host callbacks inside the
        serving program (a per-request host round trip), baked-in large
        constants, and the collective inventory.  Returns an
        :class:`~paddle_tpu.analysis.AuditReport`.
        """
        from ..analysis.jaxpr_audit import audit_traced
        b = int(batch) if batch is not None else \
            getattr(self._config, "_load_batch", 1)
        specs = self._specs_for_batch(b)
        traced = self._jit_call.trace(self._params, self._buffers,
                                      self._rng, tuple(specs))
        hlo = None
        if include_hlo:
            try:
                hlo = traced.lower().compile().as_text()
            except Exception:
                hlo = None
        prog = f"Predictor[{os.path.basename(self._config._path_prefix())}]"
        return audit_traced(
            traced, program=prog, check_donation=False, hlo_text=hlo,
            arg_names=["params", "buffers", "rng", "inputs"],
            **thresholds)

    def clone(self) -> "Predictor":
        return Predictor(self._config)

    def clear_intermediate_tensor(self):
        pass    # XLA owns intermediates; nothing persists between runs

    def try_shrink_memory(self):
        import gc
        gc.collect()


def _flatten(obj):
    if isinstance(obj, (list, tuple)):
        out = []
        for o in obj:
            out.extend(_flatten(o))
        return out
    if isinstance(obj, dict):
        out = []
        for k in sorted(obj):
            out.extend(_flatten(obj[k]))
        return out
    return [obj]


def create_predictor(config: Config) -> Predictor:
    """Parity: paddle.inference.create_predictor /
    CreatePaddlePredictor (analysis_predictor.cc:168)."""
    return Predictor(config)


from .gateway import (GatewayRouter, GenerationRpcServer,  # noqa: E402
                      LocalReplica, RemoteReplica, ReplicaLost)
from .generation_server import (GenerationServer,  # noqa: E402
                                GenerationStream)
from .migration import MigrationUnsupported  # noqa: E402
from .prefix_cache import PrefixCache  # noqa: E402
from .serving import (PredictorServer, RequestTimeout,  # noqa: E402
                      ServeError, ServerClosed, ServerDraining,
                      ServerOverloaded, UpstreamUnavailable)
