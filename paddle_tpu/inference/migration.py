"""paddle_tpu.inference.migration — live-sequence KV migration for
graceful replica drain (ISSUE 18).

``drain(replica)`` on the gateway must move a replica's live
conversations elsewhere without the client seeing anything but a short
stall.  Two mechanisms, in preference order:

- **KV migration** (:func:`export_sequence` / :func:`import_sequence`):
  serialize the sequence's scheduler state (prompt, emitted tokens,
  RNG key data, priorities, the REMAINING deadline) plus the physical
  pool rows its block table points at, then rebuild it on the target —
  fresh block ids, same bytes.  Physical block ids never enter the
  attention math (tables are gather indices) and every pool tensor
  round-trips through numpy at its own dtype, so a migrated sequence's
  continuation is BIT-IDENTICAL to never having moved: the target's
  next decode step reads exactly the K/V the source would have read.
- **token replay** (the cheap fallback the gateway uses when the
  target lacks capacity, the geometries differ, or the blob carries no
  KV because the sequence was waiting/evicted): ship only the prompt +
  emitted tokens and re-submit with ``replay_tokens=`` — re-prefill
  recomputes the KV and the ISSUE 8 replay contract makes the
  continuation token-identical (``check_replay`` asserts it live).

Speculative-decoding servers take the replay path by construction: the
draft model's pools trail the emitted stream (``draft_decoded``), and
shipping target KV without coherent draft KV would silently sink the
accept rate — :class:`MigrationUnsupported` routes those to replay.

Everything here runs ON the scheduler thread of the server it touches
(via ``_run_on_scheduler``): sequence/slot/pool state is only coherent
between decode steps, and keeping mutation there keeps the lock graph
exactly as the lint baseline declares it.
"""
from __future__ import annotations

import time
from typing import Dict, Optional

import numpy as np

from .serving import ServeError

__all__ = ["MigrationUnsupported", "export_sequence", "import_sequence"]


class MigrationUnsupported(ServeError):
    """The KV path cannot carry this sequence (no capacity on the
    target, mismatched pool geometry, a spec-decode server, or a blob
    with no KV) — the caller falls back to token replay."""


def export_sequence(server, request_id: int) -> Optional[dict]:
    """Serialize one live request off ``server`` and REMOVE it there
    (its stream ends with ``finish_reason="migrated"``).  Returns the
    blob, or None when the request is unknown (already finished).

    An ACTIVE sequence ships its pool rows (KV valid through position
    ``L + decoded - 1``); a WAITING one (queued or evicted) has no
    blocks to ship and returns a tokens-only blob (``kv is None``) for
    the replay fallback.  ``deadline_remaining`` is measured here and
    re-anchored at import — the wall time a migration takes counts
    against the request's budget, it does not reset it.
    """
    def _do():
        with server._lock:
            seq = next((s for s in server._active.values()
                        if s.rid == request_id), None)
            waiting = None
            if seq is None:
                waiting = next((s for s in server._waiting
                                if s.rid == request_id), None)
                if waiting is not None:
                    server._waiting.remove(waiting)
            active = seq is not None
            if not active:
                seq = waiting
        if seq is None:
            return None
        blob: Dict = {
            "prompt": np.asarray(seq.prompt, np.int32),
            "generated": list(seq.generated),
            "decoded": int(seq.decoded),
            "max_new": seq.max_new,
            "eos": seq.eos,
            "do_sample": seq.do_sample,
            "temp": seq.temp,
            "top_k": seq.top_k,
            "top_p": seq.top_p,
            "key_data": np.asarray(seq.key_data),
            "priority": seq.priority,
            "tenant": seq.tenant,
            "evictions": seq.evictions,
            "deadline_remaining": max(
                seq.deadline - time.monotonic(), 0.0),
            "block_size": server._bs,
            "kv": None,
        }
        if active and not server._spec and seq.blocks:
            # gather the pool rows BEFORE releasing: an unreffed block
            # is recyclable the moment another admission wants it
            idx = np.asarray(seq.blocks, np.int64)
            blob["kv"] = [{k: np.asarray(v)[idx]
                           for k, v in layer.items()}
                          for layer in server._pools]
            blob["n_blocks"] = len(seq.blocks)
        server._release(seq)
        with server._lock:
            server._stats["migrated_out"] += 1
        if seq.rt is not None:
            seq.rt.finish("migrated", tokens=len(seq.generated))
        seq.stream._end("migrated")
        return blob
    return server._run_on_scheduler(_do)


def import_sequence(server, blob: dict):
    """Rebuild an exported sequence on ``server``: allocate fresh
    blocks, write the shipped pool rows at them, and enter the
    sequence directly into the active set (no prefill — its KV is
    already valid through ``L + decoded - 1``; a mid-replay sequence
    keeps replaying on the target).  Returns the new
    :class:`~paddle_tpu.inference.generation_server.GenerationStream`.

    Raises :class:`MigrationUnsupported` when the KV path cannot apply
    (the caller re-submits with ``replay_tokens=`` instead); the
    server is left exactly as found.
    """
    from .generation_server import _GenSeq

    kv = blob.get("kv")
    if kv is None:
        raise MigrationUnsupported("blob carries no KV (sequence was "
                                   "waiting) — replay it instead")
    if server._spec:
        raise MigrationUnsupported(
            "target runs speculative decoding (draft KV cannot be "
            "reconstructed) — replay instead")
    if int(blob["block_size"]) != server._bs \
            or len(kv) != len(server._pools) \
            or any(v.shape[1:] != np.asarray(
                server._pools[i][k]).shape[1:]
                for i, layer in enumerate(kv)
                for k, v in layer.items()):
        raise MigrationUnsupported("pool geometry mismatch — replay "
                                   "instead")

    def _do():
        import jax.numpy as jnp

        n = int(blob["n_blocks"])
        with server._lock:
            if not server._free_slots:
                raise MigrationUnsupported("no free slot on target")
            got = []
            for _ in range(n):
                b = server._cache.alloc()
                if b is None:
                    break
                got.append(b)
            if len(got) < n:
                for b in got:
                    server._cache.unref(b)
                raise MigrationUnsupported(
                    f"target pool has room for {len(got)}/{n} blocks")
            slot = server._free_slots.pop()
            server._rid += 1
            server._arrival += 1
            rid, arrival = server._rid, server._arrival
        # device writes outside the lock, on the scheduler thread:
        # nothing else touches the pools between steps
        idx = np.asarray(got, np.int32)
        server._pools = [
            {k: v.at[idx].set(jnp.asarray(rows[k]))
             for k, v in layer.items()}
            for layer, rows in zip(server._pools, kv)]
        now = time.monotonic()
        prompt = np.asarray(blob["prompt"], np.int32)
        seq = _GenSeq(rid, prompt, blob["max_new"], blob["eos"],
                      blob["do_sample"], blob["temp"], blob["top_k"],
                      blob["top_p"],
                      np.asarray(blob["key_data"], np.uint32),
                      blob["priority"], arrival,
                      now + float(blob["deadline_remaining"]),
                      tenant=blob.get("tenant"))
        seq.generated = list(blob["generated"])
        seq.decoded = int(blob["decoded"])
        seq.evictions = int(blob.get("evictions", 0))
        seq.blocks = got
        seq.slot = slot
        seq.t_first_tok = now    # first token long since delivered
        with server._lock:
            server._active[slot] = seq
            server._stats["migrated_in"] += 1
            # index the KV-valid full blocks (prompt + replayed
            # tokens) so survivors' traffic can alias them
            server._cache.insert(
                prompt.tolist() + seq.generated[:seq.decoded], got)
        return seq.stream
    return server._run_on_scheduler(_do)
