"""paddle_tpu.inference.serving — shape-bucketed dynamic micro-batching.

The L10 serving engine over the compile-once :class:`~paddle_tpu.
inference.Predictor` (ISSUE 2 tentpole; VERDICT next-round item 8).
Reference analog: the multi-stream AnalysisPredictor pool behind
PaddleServing — there concurrency comes from cloning predictors per
thread; on TPU the chip wants ONE big program per step, so concurrency
comes from *coalescing* instead:

- concurrent batch-1/-N requests queue up and are merged under a
  max-wait deadline into one device batch;
- the batch pads to a power-of-2 BUCKET (the same bucket-and-prime
  trick that fixed DeviceCachedTable's per-shape recompiles, PERF.md
  r4), so the predictor holds exactly one pre-warmed XLA executable per
  bucket and steady state never retraces;
- results split back to the callers' futures; padding rows are sliced
  off before anyone sees them.

Overload degrades instead of collapsing (VERDICT: "serve heavy traffic
... as fast as the hardware allows" is meaningless if the 1.01x-load
behavior is an unbounded queue): the submit queue has a hard depth cap
— past it requests shed immediately with :class:`ServerOverloaded` —
and every request carries a deadline; requests that exceed it before
execution fail with :class:`RequestTimeout` rather than occupying a
batch slot.

Phase accounting (``stats()``): wall time attributes to queue / pad /
run / unpad so ``tools/profile_serve.py`` can say WHERE a slow server
spends its step — the same discipline as ``tools/profile_ps.py``.

PS-backed embedding serving (ISSUE 10): ``ps_client`` + ``ps_tables``
wire a pull-only parameter-server client into the micro-batcher — an
input position holding int ids is swapped for freshly pulled embedding
rows (shape ``ids.shape + (dim,)``) right before the device runs, so a
wide_deep-style model serves embeddings the TRAINING cluster updated
seconds ago without any checkpoint round trip.  The pull happens once
per coalesced batch (the whole point of batching: one fan-out RPC set
amortized over every rider), its wall time lands in ``stats()["ps_ms"]``
and the shed/timeout discipline extends to the new failure mode: a
PS read that fails past the read tier's own fan-out/failover fails
that batch's requests with typed :class:`UpstreamUnavailable` — the
server keeps serving, the client backs off exactly like an overload
shed.
"""
from __future__ import annotations

import queue as _queue
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..framework import monitor as _monitor
from ..observability import flight_recorder as _flight
from ..observability import trace as _trace
from ..observability.request_trace import RequestTrace

__all__ = ["PredictorServer", "ServeError", "ServerOverloaded",
           "ServerClosed", "ServerDraining", "RequestTimeout",
           "UpstreamUnavailable"]


class ServeError(RuntimeError):
    """Base class for serving-path errors."""


class ServerOverloaded(ServeError):
    """Typed load-shed: the submit queue is at its depth cap.  Clients
    should back off and retry; the server keeps serving what it already
    admitted."""


class ServerClosed(ServeError):
    """The server was stopped before (or while) handling the request."""


class ServerDraining(ServeError):
    """The server is draining toward removal (ISSUE 18): it refuses NEW
    admissions while live sequences run to completion or migrate.
    Clients (and the gateway router) treat it like a shed targeted at
    one replica: retry a DIFFERENT replica immediately — unlike
    :class:`ServerOverloaded` there is no point backing off and
    retrying here."""


class RequestTimeout(ServeError, TimeoutError):
    """The request's deadline passed before its batch executed."""


class UpstreamUnavailable(ServeError):
    """A PS embedding read failed past the read tier's own fan-out and
    failover (every replica stale/down AND the primary unreachable).
    The batch's requests fail typed; the server keeps serving — clients
    treat it like an overload shed and back off."""


class _Future:
    """Minimal thread-safe one-shot future (no executor dependency)."""

    __slots__ = ("_ev", "_value", "_exc")

    def __init__(self):
        self._ev = threading.Event()
        self._value = None
        self._exc: Optional[BaseException] = None

    def set_result(self, value):
        self._value = value
        self._ev.set()

    def set_exception(self, exc: BaseException):
        self._exc = exc
        self._ev.set()

    def done(self) -> bool:
        return self._ev.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._ev.wait(timeout):
            raise RequestTimeout("request did not complete within "
                                 f"{timeout}s")
        if self._exc is not None:
            raise self._exc
        return self._value


class _Request:
    __slots__ = ("arrays", "n", "future", "t_submit", "deadline",
                 "tenant", "rt")

    def __init__(self, arrays: List[np.ndarray], n: int,
                 deadline: float, tenant: Optional[str] = None):
        self.arrays = arrays
        self.n = n
        self.future = _Future()
        self.t_submit = time.monotonic()
        self.deadline = deadline
        self.tenant = tenant
        self.rt: Optional[RequestTrace] = None


def _default_buckets(max_batch: int) -> List[int]:
    out, b = [], 1
    while b < max_batch:
        out.append(b)
        b *= 2
    out.append(max_batch)
    return out


class PredictorServer:
    """Dynamic micro-batching server over a compile-once Predictor.

    Usage::

        server = PredictorServer(predictor, max_batch=32,
                                 max_wait_ms=2.0)
        server.start()                       # prewarms every bucket
        out = server.infer([x])              # blocking, thread-safe
        fut = server.submit([x])             # async; fut.result()
        server.stop()

    Knobs:

    - ``max_batch``: largest device batch (top bucket).
    - ``max_wait_ms``: how long the batcher holds the FIRST request of
      a batch open for co-travelers.  0 disables coalescing-by-wait
      (still batches whatever is already queued).
    - ``buckets``: ascending batch buckets; default powers of two up to
      ``max_batch``.  One compiled program exists per bucket.
    - ``max_queue``: submit-queue depth cap; beyond it ``submit``
      raises :class:`ServerOverloaded` (load-shedding, never unbounded
      memory).
    - ``request_timeout_s``: per-request deadline; enforced both while
      queued (stale requests are dropped with :class:`RequestTimeout`
      before wasting a batch slot) and in :meth:`infer`'s wait.
    - ``ps_client`` / ``ps_tables``: PS-backed embedding inputs —
      ``ps_tables`` maps an input POSITION (index into the request's
      array list) to a PS table name; that input must carry int ids and
      is replaced by pulled rows before the predictor runs (module
      docstring).  Use a pull-only read-mode
      :class:`~paddle_tpu.distributed.fleet.ps_service.PSClient` with
      ``read_replicas`` + ``max_lag`` for replica fan-out with bounded
      staleness.
    """

    def __init__(self, predictor, max_batch: int = 32,
                 max_wait_ms: float = 2.0,
                 buckets: Optional[Sequence[int]] = None,
                 max_queue: int = 256,
                 request_timeout_s: float = 30.0,
                 prewarm: bool = True,
                 ps_client=None,
                 ps_tables: Optional[Dict[int, str]] = None):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if ps_tables and ps_client is None:
            raise ValueError("ps_tables needs a ps_client")
        self._ps = ps_client
        self._ps_tables = dict(ps_tables or {})
        if self._ps_tables:
            # the typed errors the read tier surfaces (import kept off
            # the serving module's import path until PS serving is used)
            from ..distributed.fleet.ps_service import (PSError,
                                                        PSUnavailable)
            self._ps_errors = (PSError, PSUnavailable, OSError,
                               ConnectionError)
        self._pred = predictor
        self._max_batch = int(max_batch)
        self._max_wait_s = float(max_wait_ms) / 1e3
        bks = sorted(set(int(b) for b in (buckets or
                                          _default_buckets(max_batch))))
        if bks[-1] < max_batch:
            bks.append(int(max_batch))
        self._buckets = bks
        self._q: _queue.Queue = _queue.Queue(maxsize=int(max_queue))
        self._timeout_s = float(request_timeout_s)
        self._prewarm = bool(prewarm)
        self._thread: Optional[threading.Thread] = None
        self._running = False
        self._carry: Optional[_Request] = None
        self._lock = threading.Lock()
        self._rid = 0                 # request-lane ids (ISSUE 12)
        self._stats = {
            "requests": 0, "examples": 0, "batches": 0,
            "padded_examples": 0, "shed_overload": 0, "shed_timeout": 0,
            "shed_ps": 0,
            "bucket_hits": {b: 0 for b in self._buckets},
            "queue_ms": 0.0, "pad_ms": 0.0, "ps_ms": 0.0, "run_ms": 0.0,
            "unpad_ms": 0.0,
        }

    # -- lifecycle ---------------------------------------------------
    def start(self) -> "PredictorServer":
        if self._running:
            return self
        if self._prewarm and hasattr(self._pred, "prewarm"):
            # every bucket's executable exists BEFORE traffic: a
            # first-seen shape never pays its compile inside a request
            self._pred.prewarm(self._buckets)
        self._running = True
        self._thread = threading.Thread(target=self._loop,
                                        name="predictor-server",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self, drain: bool = True, timeout: float = 30.0):
        if not self._running:
            return
        if drain:
            deadline = time.monotonic() + timeout
            while (not self._q.empty() or self._carry is not None) \
                    and time.monotonic() < deadline:
                time.sleep(0.005)
        self._running = False
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
        # anything still queued fails loudly, not silently
        while True:
            try:
                req = self._q.get_nowait()
            except _queue.Empty:
                break
            if req.rt is not None:
                req.rt.finish("server_stopped")
            req.future.set_exception(ServerClosed("server stopped"))
        if self._carry is not None:
            if self._carry.rt is not None:
                self._carry.rt.finish("server_stopped")
            self._carry.future.set_exception(
                ServerClosed("server stopped"))
            self._carry = None

    def __enter__(self) -> "PredictorServer":
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- client surface ----------------------------------------------
    def submit(self, inputs: Sequence[np.ndarray],
               timeout_s: Optional[float] = None,
               tenant: Optional[str] = None) -> _Future:
        """Enqueue one request (list of arrays, shared leading batch
        dim).  Returns a future; raises :class:`ServerOverloaded` when
        the queue is at its cap and :class:`ServerClosed` when stopped.
        ``tenant`` tags the request for usage accounting (always-on
        ``serve_tenant_examples`` / ``serve_tenant_sheds`` labeled
        counters + a ``serve_tenant_queue_ms`` gauge) and for the
        per-request trace lane when tracing is on (ISSUE 12).
        """
        if not self._running:
            raise ServerClosed("server not started")
        arrays = [np.asarray(a) for a in inputs]
        if not arrays:
            raise ValueError("empty request")
        n = int(arrays[0].shape[0]) if arrays[0].ndim else 1
        for a in arrays:
            if a.ndim == 0 or int(a.shape[0]) != n:
                raise ValueError(
                    "all request inputs must share the leading batch "
                    f"dim, got {[tuple(a.shape) for a in arrays]}")
        if n > self._max_batch:
            raise ValueError(
                f"request batch {n} exceeds max_batch="
                f"{self._max_batch}; split it client-side")
        to = self._timeout_s if timeout_s is None else float(timeout_s)
        req = _Request(arrays, n, time.monotonic() + to, tenant=tenant)
        if _trace.enabled():
            with self._lock:
                self._rid += 1
                rid = self._rid
            req.rt = RequestTrace("pred", rid, tenant)
            req.rt.instant("submit", rows=n)
            req.rt.begin("queue")
        try:
            self._q.put_nowait(req)
        except _queue.Full:
            with self._lock:
                self._stats["shed_overload"] += 1
            _monitor.stat_add("serve_shed_overload")
            if tenant is not None:
                _monitor.stat_add("serve_tenant_sheds",
                                  labels={"tenant": tenant,
                                          "reason": "overload"})
            if req.rt is not None:
                req.rt.finish("shed_overload")
            _flight.record("serve.shed", reason="overload",
                           depth=self._q.qsize(), rows=n)
            # typed-failure trigger (rate limited: a load spike sheds
            # thousands of requests but warrants ONE bundle)
            _flight.maybe_dump("ServerOverloaded")
            raise ServerOverloaded(
                f"queue depth cap {self._q.maxsize} reached; request "
                "shed — back off and retry") from None
        if _monitor.metrics_enabled():
            _monitor.gauge_set("serve_queue_depth", self._q.qsize())
        return req.future

    def infer(self, inputs: Sequence[np.ndarray],
              timeout_s: Optional[float] = None,
              tenant: Optional[str] = None) -> List[np.ndarray]:
        """Blocking submit + wait.  Thread-safe; this is the per-client
        call the bench's concurrent workers use."""
        to = self._timeout_s if timeout_s is None else float(timeout_s)
        return self.submit(inputs, timeout_s=to,
                           tenant=tenant).result(timeout=to)

    def stats(self) -> Dict:
        with self._lock:
            s = dict(self._stats)
            s["bucket_hits"] = dict(self._stats["bucket_hits"])
        s["num_compiles"] = (self._pred.num_compiles()
                             if hasattr(self._pred, "num_compiles")
                             else None)
        s["queue_depth"] = self._q.qsize()
        # provenance tag (ISSUE 11 gateway wiring): a process running
        # both servers — PS-backed PredictorServer for fixed-shape
        # models AND a GenerationServer for LLM streams — merges their
        # stats into one report; the tag says which engine produced
        # which numbers
        s["server"] = "predictor"
        # per-bucket compile provenance (ISSUE 8 satellite, shared
        # shape with GenerationServer.stats()["bucket_compiles"],
        # whose keys gained a batch axis — "prefill:16x4" — in
        # ISSUE 11): which buckets were prewarmed vs compiled under
        # traffic — "traffic_compiles > 0" is the prewarm-gap smoking
        # gun that hit counts alone cannot show
        if hasattr(self._pred, "compile_records"):
            records = self._pred.compile_records()
            bc: Dict = {}
            for r in records:
                b = r.get("batch")
                key = f"run:{b}" if b is not None else "run:?"
                ent = bc.setdefault(key, {"count": 0,
                                          "cause": r.get("cause")})
                ent["count"] += 1
            s["bucket_compiles"] = bc
            s["prewarm_compiles"] = sum(
                1 for r in records if r.get("cause") in ("prewarm",
                                                         "load"))
            s["traffic_compiles"] = sum(
                1 for r in records if r.get("cause") not in ("prewarm",
                                                             "load"))
        return s

    # -- batcher loop ------------------------------------------------
    def _bucket_for(self, rows: int) -> int:
        for b in self._buckets:
            if rows <= b:
                return b
        return self._buckets[-1]

    def _gather(self) -> Optional[List[_Request]]:
        """Collect one batch: the first request (carry-over or queue)
        opens a ``max_wait`` window; co-travelers join until the window
        closes or the next request would overflow ``max_batch`` (it
        carries to the next batch)."""
        first = self._carry
        self._carry = None
        if first is None:
            try:
                first = self._q.get(timeout=0.05)
            except _queue.Empty:
                return None
        batch, rows = [first], first.n
        deadline = time.monotonic() + self._max_wait_s
        while rows < self._max_batch:
            rem = deadline - time.monotonic()
            if rem <= 0:
                break
            try:
                nxt = self._q.get(timeout=rem)
            except _queue.Empty:
                break
            if rows + nxt.n > self._max_batch:
                self._carry = nxt
                break
            batch.append(nxt)
            rows += nxt.n
        return batch

    def _loop(self):
        while self._running:
            batch = self._gather()
            if not batch:
                continue
            try:
                self._execute(batch)
            except BaseException as e:    # noqa: BLE001 - fail futures
                for r in batch:
                    if not r.future.done():
                        if r.rt is not None:
                            r.rt.finish("batch_error")
                        r.future.set_exception(
                            ServeError(f"batch execution failed: {e!r}"))

    def _execute(self, batch: List[_Request]):
        t0 = time.monotonic()
        live = []
        for r in batch:
            if t0 > r.deadline:
                with self._lock:
                    self._stats["shed_timeout"] += 1
                _monitor.stat_add("serve_shed_timeout")
                if r.tenant is not None:
                    _monitor.stat_add("serve_tenant_sheds",
                                      labels={"tenant": r.tenant,
                                              "reason": "timeout"})
                _flight.record("serve.shed", reason="timeout",
                               queued_ms=round(
                                   (t0 - r.t_submit) * 1e3, 3))
                if r.rt is not None:
                    r.rt.finish("shed_timeout")
                r.future.set_exception(RequestTimeout(
                    "request spent its whole deadline queued — server "
                    "overloaded"))
            else:
                live.append(r)
        if not live:
            return
        for r in live:
            # usage accounting at batch entry: the queue phase ends
            # here whether the batch later succeeds or sheds
            if r.tenant is not None:
                lab = {"tenant": r.tenant}
                _monitor.stat_add("serve_tenant_examples", r.n,
                                  labels=lab)
                _monitor.gauge_add("serve_tenant_queue_ms",
                                   (t0 - r.t_submit) * 1e3, labels=lab)
            if r.rt is not None:
                r.rt.end("queue")
                r.rt.begin("run")
        queue_s = sum(t0 - r.t_submit for r in live)
        rows = sum(r.n for r in live)
        bucket = self._bucket_for(rows)
        pad = bucket - rows
        batch_sp = (_trace.Span("serve.batch", cat="serve",
                                bucket=bucket, rows=rows,
                                requests=len(live))
                    if _trace.enabled() else None)
        if batch_sp is not None:
            batch_sp.__enter__()
        try:
            tok = (_flight.begin("serve.batch", bucket=bucket,
                                 rows=rows, requests=len(live))
                   if _flight.enabled() else None)
            n_in = len(live[0].arrays)
            padded = []
            for i in range(n_in):
                parts = [r.arrays[i] for r in live]
                if pad:
                    # pad with copies of the first row: REAL data, so a
                    # model with input-dependent control ranges (log/
                    # gather/embedding lookups) never sees out-of-domain
                    # zeros in the dead rows
                    fill = np.broadcast_to(
                        parts[0][:1], (pad,) + parts[0].shape[1:])
                    parts = parts + [fill]
                padded.append(np.concatenate(parts, axis=0)
                              if len(parts) > 1 else parts[0])
            t1 = time.monotonic()

            ps_s = 0.0
            if self._ps_tables:
                # swap id inputs for freshly pulled embedding rows —
                # one read fan-out per coalesced batch, amortized over
                # every rider (and the pad rows, which are copies of a
                # real row, so their ids are in-domain by construction)
                try:
                    for idx in sorted(self._ps_tables):
                        table = self._ps_tables[idx]
                        ids = np.ascontiguousarray(padded[idx],
                                                   np.int64)
                        pulled = self._ps.pull(table, ids.reshape(-1))
                        padded[idx] = np.ascontiguousarray(
                            pulled, np.float32).reshape(
                                ids.shape + (pulled.shape[-1],))
                except self._ps_errors as e:
                    with self._lock:
                        self._stats["shed_ps"] += len(live)
                    _monitor.stat_add("serve_shed_ps", len(live))
                    _flight.record("serve.shed", reason="ps_read",
                                   err=type(e).__name__,
                                   requests=len(live))
                    _flight.maybe_dump("UpstreamUnavailable")
                    err = UpstreamUnavailable(
                        f"PS embedding read failed past replica "
                        f"fan-out and primary failover: {e}")
                    err.__cause__ = e
                    for r in live:
                        if r.tenant is not None:
                            _monitor.stat_add(
                                "serve_tenant_sheds",
                                labels={"tenant": r.tenant,
                                        "reason": "ps_read"})
                        if r.rt is not None:
                            r.rt.finish("shed_ps")
                        r.future.set_exception(err)
                    return
                ps_s = time.monotonic() - t1

            outs = self._pred.run(padded)
            t2 = time.monotonic()

            off = 0
            slices = []
            for r in live:
                slices.append([o[off:off + r.n] for o in outs])
                off += r.n
            t3 = time.monotonic()
            # commit stats BEFORE resolving futures: a client that has
            # observed its result must never read stats that don't yet
            # count its batch (read-after-completion consistency)
            with self._lock:
                s = self._stats
                s["requests"] += len(live)
                s["examples"] += rows
                s["batches"] += 1
                s["padded_examples"] += pad
                s["bucket_hits"][bucket] = \
                    s["bucket_hits"].get(bucket, 0) + 1
                s["queue_ms"] += queue_s * 1e3
                s["pad_ms"] += (t1 - t0) * 1e3
                s["ps_ms"] += ps_s * 1e3
                s["run_ms"] += (t2 - t1 - ps_s) * 1e3
                s["unpad_ms"] += (t3 - t2) * 1e3
            for r, sl in zip(live, slices):
                if r.rt is not None:
                    r.rt.finish("ok", rows=r.n, bucket=bucket)
                r.future.set_result(sl)
        finally:
            # a failed run must still close the span, or the batcher
            # thread's span stack would mis-parent every later batch
            if batch_sp is not None:
                batch_sp.__exit__(None, None, None)
            if tok is not None:
                # an open serve.batch in a bundle = the batcher thread
                # died/stalled mid-run; a closed one is queue history
                et = sys.exc_info()[0]
                _flight.end(tok, **({} if et is None
                                    else {"err": et.__name__}))

        if _monitor.metrics_enabled():
            # per-request end-to-end latency + queue-age histograms;
            # the p50/p99 a serving dashboard actually alerts on
            for r in live:
                _monitor.hist_observe("serve_latency_ms",
                                      (t3 - r.t_submit) * 1e3)
            _monitor.hist_observe("serve_queue_ms",
                                  queue_s / len(live) * 1e3)
            _monitor.stat_add("serve_bucket_hits")
            _monitor.gauge_set("serve_queue_depth", self._q.qsize())
