"""paddle_tpu.rec — recommendation model zoo (wide&deep, DeepFM).

Parity target: BASELINE north-star config 4 ("PaddleRec-style wide_deep /
DeepFM, Fleet parameter-server sparse embeddings"). The reference ships
these as PaddleRec configs over its PS stack (SURVEY §2.6 "Parameter
server"); here they are first-class Layers:

- single-chip/dense mode: `nn.Embedding` tables, everything on the TPU —
  batch the multi-field int ids as one [B, F] tensor with per-field id
  offsets (TPU-friendly: one gather);
- PS mode: the same dense trunk compiled with `jax` while embeddings live
  in host :class:`~paddle_tpu.distributed.fleet.ps.SparseTable` shards,
  driven by :class:`~paddle_tpu.distributed.fleet.heter.HeterTrainer`
  (see tests/test_ps_e2e.py for the wired slice).
"""
from .models import DeepFM, WideDeep  # noqa: F401

__all__ = ["WideDeep", "DeepFM"]
