"""Wide&Deep and DeepFM (BASELINE config 4 model families).

Design notes (TPU-first): all F sparse fields share ONE embedding table
addressed with per-field id offsets, so a batch is a single [B, F] int
tensor and the lookup is one gather the XLA partitioner can shard; the FM
interaction uses the O(F*D) identity 0.5*((Σv)² − Σv²) instead of the
O(F²) pairwise form.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..framework.core import _apply
from ..tensor import concat as _concat
from ..tensor import sum as _sum
from ..tensor.manipulation import flatten as _flatten
from ..tensor.math import sigmoid as _sigmoid
from ..nn import Embedding, Layer, Linear, ReLU, Sequential

__all__ = ["WideDeep", "DeepFM"]


def _offsets(field_dims: Sequence[int]) -> np.ndarray:
    return np.concatenate([[0], np.cumsum(field_dims)[:-1]]).astype(np.int64)


def _check_dense(dense_dim: int, dense_feats):
    if dense_dim and dense_feats is None:
        raise ValueError(
            f"model was built with dense_dim={dense_dim}; pass dense_feats")
    if not dense_dim and dense_feats is not None:
        raise ValueError(
            "dense_feats given but the model was built with dense_dim=0 "
            "(they would be silently ignored)")


def _mlp(in_dim: int, hidden: Sequence[int], out_dim: int = 1):
    layers = []
    d = in_dim
    for h in hidden:
        layers += [Linear(d, h), ReLU()]
        d = h
    layers.append(Linear(d, out_dim))
    return Sequential(*layers)


class _FieldEmbedding(Layer):
    """Shared table over all fields with id offsets (one gather)."""

    def __init__(self, field_dims: Sequence[int], embed_dim: int):
        super().__init__()
        self.table = Embedding(int(sum(field_dims)), embed_dim)
        self._dims = np.asarray(field_dims, np.int64)
        self._off = _offsets(field_dims)

    def forward(self, ids, validate: bool = True):
        off = self._off
        import jax
        v = ids._value if hasattr(ids, "_value") else ids
        if validate and not isinstance(v, jax.core.Tracer):
            # eager: out-of-range ids would silently read a NEIGHBORING
            # field's rows after the offset shift — fail loudly instead
            a = np.asarray(v)
            bad = (a < 0) | (a >= self._dims[None, :])
            if bad.any():
                f = int(np.argwhere(bad)[0][1])
                raise ValueError(
                    f"sparse id {a[bad][0]} out of range for field {f} "
                    f"(dim {int(self._dims[f])})")

        def shift(vv):
            return vv + jnp.asarray(off)[None, :]

        return self.table(_apply(shift, ids, op_name="field_offset"))


class WideDeep(Layer):
    """Wide & Deep (Cheng et al. 2016; PaddleRec wide_deep config).

    ``forward(sparse_ids [B, F], dense_feats [B, Dd] or None)`` ->
    logits [B, 1]. The wide half is a linear model over the sparse ids
    (one 1-dim embedding) + dense features; the deep half is an MLP over
    concatenated field embeddings + dense features.
    """

    def __init__(self, field_dims: Sequence[int], dense_dim: int = 0,
                 embed_dim: int = 16,
                 hidden_units: Sequence[int] = (64, 32)):
        super().__init__()
        self.num_fields = len(field_dims)
        self.dense_dim = dense_dim
        self.wide_emb = _FieldEmbedding(field_dims, 1)
        self.wide_dense = Linear(dense_dim, 1) if dense_dim else None
        self.deep_emb = _FieldEmbedding(field_dims, embed_dim)
        self.deep_mlp = _mlp(self.num_fields * embed_dim + dense_dim,
                             hidden_units)

    def forward(self, sparse_ids, dense_feats=None):
        _check_dense(self.dense_dim, dense_feats)
        wide = _sum(self.wide_emb(sparse_ids), axis=1)       # [B, 1]
        if self.wide_dense is not None:
            wide = wide + self.wide_dense(dense_feats)
        emb = self.deep_emb(sparse_ids, validate=False)       # [B, F, D]
        flat = _flatten(emb, start_axis=1)
        if self.dense_dim:
            flat = _concat([flat, dense_feats], axis=1)
        deep = self.deep_mlp(flat)                            # [B, 1]
        return wide + deep

    def predict_proba(self, sparse_ids, dense_feats=None):
        return _sigmoid(self.forward(sparse_ids, dense_feats))


class DeepFM(Layer):
    """DeepFM (Guo et al. 2017; PaddleRec deepfm config).

    logit = first_order(ids) + FM second-order over shared field
    embeddings + MLP(deep). ``forward(sparse_ids [B, F])`` -> [B, 1].
    """

    def __init__(self, field_dims: Sequence[int], embed_dim: int = 16,
                 hidden_units: Sequence[int] = (64, 32),
                 dense_dim: int = 0):
        super().__init__()
        self.num_fields = len(field_dims)
        self.dense_dim = dense_dim
        self.first_order = _FieldEmbedding(field_dims, 1)
        self.embedding = _FieldEmbedding(field_dims, embed_dim)
        self.deep_mlp = _mlp(self.num_fields * embed_dim + dense_dim,
                             hidden_units)

    def fm(self, emb):
        """0.5 * ((Σ_f v)² − Σ_f v²) summed over embed dim -> [B, 1]."""
        def fn(v):
            s = v.sum(axis=1)
            return 0.5 * (s * s - (v * v).sum(axis=1)).sum(
                axis=-1, keepdims=True)
        return _apply(fn, emb, op_name="fm_interaction")

    def forward(self, sparse_ids, dense_feats=None):
        _check_dense(self.dense_dim, dense_feats)
        first = _sum(self.first_order(sparse_ids), axis=1)   # [B, 1]
        emb = self.embedding(sparse_ids, validate=False)      # [B, F, D]
        second = self.fm(emb)
        flat = _flatten(emb, start_axis=1)
        if self.dense_dim:
            flat = _concat([flat, dense_feats], axis=1)
        deep = self.deep_mlp(flat)
        return first + second + deep

    def predict_proba(self, sparse_ids, dense_feats=None):
        return _sigmoid(self.forward(sparse_ids, dense_feats))
