"""DataLoader.

Parity: reference python/paddle/fluid/reader.py:149 DataLoader +
fluid/dataloader/dataloader_iter.py (:265 single-process, :469
multi-process with worker loop :379 and shared-memory transport).

TPU-native pipeline:
  workers (numpy batches) -> prefetch thread -> jax.device_put -> HBM
The device transfer is overlapped with compute by keeping a small queue of
in-flight device batches (the analog of the reference's double-buffered
``operators/reader/buffered_reader.cc``).
"""
from __future__ import annotations

import itertools
import queue
import threading
import traceback
from typing import Any, Callable, List, Optional

import numpy as np

from ..framework import monitor as _monitor
from ..framework.core import Tensor
from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler

__all__ = ["DataLoader", "default_collate_fn", "get_worker_info"]

_worker_info = threading.local()


def get_worker_info():
    return getattr(_worker_info, "info", None)


class WorkerInfo:
    def __init__(self, id, num_workers, dataset):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


def default_collate_fn(batch: List[Any]):
    """Stack samples into batched numpy arrays (device transfer happens in
    the loader, once per batch)."""
    sample = batch[0]
    if isinstance(sample, Tensor):
        return np.stack([np.asarray(s._value) for s in batch])
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, np.int32)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, np.float32)
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return type(sample)(default_collate_fn(list(t)) for t in transposed)
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    return batch


def _to_device(batch, places=None):
    import jax

    def conv(x):
        if isinstance(x, np.ndarray):
            if x.dtype == np.float64:
                x = x.astype(np.float32)
            if x.dtype == np.int64:
                x = x.astype(np.int32)
            return Tensor(jax.device_put(x))
        if isinstance(x, (list, tuple)):
            return type(x)(conv(v) for v in x)
        if isinstance(x, dict):
            return {k: conv(v) for k, v in x.items()}
        return x
    return conv(batch)


class _RemoteError:
    """An exception raised in a worker process, shipped with its trace."""

    def __init__(self, exc: BaseException):
        self.type_name = type(exc).__name__
        self.message = str(exc)
        self.trace = traceback.format_exc()

    def reraise(self):
        raise RuntimeError(
            f"DataLoader worker raised {self.type_name}: {self.message}\n"
            f"worker traceback:\n{self.trace}")


def _process_worker_loop(dataset, collate_fn, worker_init_fn, wid,
                         num_workers, index_queue, result_queue):
    """Runs in a forked child: pull index lists, push collated batches.

    Parity: reference fluid/dataloader/worker.py _worker_loop (the
    reference ships results through shared memory via core._convert_to_
    tensor_list; here the mp.Queue pickles numpy batches, and the fork
    start method means the dataset itself is never pickled).
    """
    _worker_info.info = WorkerInfo(wid, num_workers, dataset)
    if worker_init_fn is not None:
        try:
            worker_init_fn(wid)
        except Exception as e:          # surface init failures per-batch
            err = _RemoteError(e)
            while True:
                job = index_queue.get()
                if job is None:
                    return
                result_queue.put((job[0], err))
    while True:
        job = index_queue.get()
        if job is None:             # shutdown sentinel
            return
        batch_idx, idxs = job
        try:
            out = collate_fn([dataset[i] for i in idxs])
        except Exception as e:
            out = _RemoteError(e)
        result_queue.put((batch_idx, out))


class DataLoader:
    def __init__(self, dataset: Dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False, use_process=False,
                 mp_start_method="fork"):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = max(prefetch_factor, 2)
        self.worker_init_fn = worker_init_fn
        self.places = places
        self.timeout = timeout
        # use_process=True forks OS workers (the reference's default
        # multi-process mode): needed when per-sample work is Python-
        # bound (PIL-style transforms, per-element loops) and the GIL
        # would serialize a thread pool. Threads remain the default for
        # numpy-bound collate, which releases the GIL.
        # Workers must stay off jax: fork from a process with a live
        # backend is only safe because children touch numpy alone (the
        # device transfer happens in the parent). Pass
        # mp_start_method="spawn" for fully isolated workers — the
        # dataset and collate_fn must then be picklable.
        self.use_process = use_process
        self.mp_start_method = mp_start_method
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_size = batch_size
            self.batch_sampler = None
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            if batch_size is None:
                raise ValueError("batch_size or batch_sampler required")
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no len()")
        return len(self.batch_sampler)

    # ------------------------------------------------------------------
    def _iter_batches_sync(self):
        if self._iterable_mode:
            it = iter(self.dataset)
            while True:
                chunk = list(itertools.islice(it, self.batch_size))
                if not chunk:
                    return
                if len(chunk) < self.batch_size and self.drop_last:
                    return
                yield self.collate_fn(chunk)
        else:
            for idxs in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in idxs])

    def _iter_batches_workers(self):
        """Thread-pool workers.

        The reference forks OS processes and ships batches through shared
        memory because CPython + its C++ core hold the GIL during decode;
        here batch assembly is numpy-bound (releases the GIL), so threads
        deliver the same overlap without process startup / serialization.
        """
        from concurrent.futures import ThreadPoolExecutor

        wid_counter = itertools.count()

        def init_worker():
            wid = next(wid_counter)
            _worker_info.info = WorkerInfo(wid, self.num_workers,
                                           self.dataset)
            if self.worker_init_fn is not None:
                self.worker_init_fn(wid)

        pool = ThreadPoolExecutor(max_workers=self.num_workers,
                                  initializer=init_worker)
        try:
            def make(idxs):
                return self.collate_fn([self.dataset[i] for i in idxs])

            pending = []
            it = iter(self.batch_sampler)
            depth = self.num_workers * self.prefetch_factor
            for idxs in itertools.islice(it, depth):
                pending.append(pool.submit(make, idxs))
            while pending:
                fut = pending.pop(0)
                nxt = next(it, None)
                if nxt is not None:
                    pending.append(pool.submit(make, nxt))
                yield fut.result()
        finally:
            pool.shutdown(wait=False, cancel_futures=True)

    def _iter_batches_process(self):
        """Forked worker processes with per-worker index queues, a shared
        result queue, and an in-order reorder buffer (the reference's
        _DataLoaderIterMultiProcess structure, dataloader_iter.py:469).

        A worker that dies without replying (OOM-killed, segfault in a
        C transform) is detected by polling liveness while waiting, so
        the loader raises instead of hanging forever.
        """
        import multiprocessing as mp
        ctx = mp.get_context(self.mp_start_method)

        workers, index_queues = [], []
        result_queue = ctx.Queue()
        for wid in range(self.num_workers):
            iq = ctx.Queue()
            p = ctx.Process(
                target=_process_worker_loop,
                args=(self.dataset, self.collate_fn, self.worker_init_fn,
                      wid, self.num_workers, iq, result_queue),
                daemon=True)
            p.start()
            workers.append(p)
            index_queues.append(iq)

        try:
            it = enumerate(iter(self.batch_sampler))
            send_idx = 0            # next batch number to dispatch
            recv_idx = 0            # next batch number to yield
            reorder: dict = {}

            def dispatch():
                nonlocal send_idx
                job = next(it, None)
                if job is None:
                    return False
                index_queues[send_idx % self.num_workers].put(job)
                send_idx += 1
                return True

            for _ in range(self.num_workers * self.prefetch_factor):
                if not dispatch():
                    break
            while recv_idx < send_idx:
                while recv_idx not in reorder:
                    try:
                        idx, data = result_queue.get(
                            timeout=self.timeout or 5.0)
                    except queue.Empty:
                        dead = [w.pid for w in workers if not w.is_alive()]
                        if dead:
                            raise RuntimeError(
                                f"DataLoader worker(s) {dead} exited "
                                f"unexpectedly") from None
                        if self.timeout:
                            raise RuntimeError(
                                f"DataLoader timed out after "
                                f"{self.timeout}s waiting for a batch")
                        continue
                    reorder[idx] = data
                data = reorder.pop(recv_idx)
                recv_idx += 1
                dispatch()
                if isinstance(data, _RemoteError):
                    data.reraise()
                yield data
        finally:
            for iq in index_queues:
                try:
                    iq.put(None)
                except (OSError, ValueError):
                    pass
            for w in workers:
                w.join(timeout=1.0)
                if w.is_alive():
                    w.terminate()
            for q_ in index_queues + [result_queue]:
                q_.cancel_join_thread()
                q_.close()

    def __iter__(self):
        if self.num_workers > 0 and not self._iterable_mode:
            gen = (self._iter_batches_process() if self.use_process
                   else self._iter_batches_workers())
        else:
            gen = self._iter_batches_sync()

        # prefetch-to-device pipeline (double buffering). The feeder checks
        # ``abandoned`` around every blocking put so an early `break` in the
        # consumer releases the thread (and closes the worker pool) instead
        # of leaking it.
        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch_factor)
        stop = object()
        abandoned = threading.Event()

        def feeder():
            try:
                for b in gen:
                    item = _to_device(b, self.places)
                    while not abandoned.is_set():
                        try:
                            q.put(item, timeout=0.1)
                            break
                        except queue.Full:
                            continue
                    if abandoned.is_set():
                        gen.close()
                        return
            except Exception as e:
                if not abandoned.is_set():
                    q.put(e)
            while not abandoned.is_set():
                try:
                    q.put(stop, timeout=0.1)
                    return
                except queue.Full:
                    continue

        t = threading.Thread(target=feeder, daemon=True)
        t.start()
        try:
            import time as _time
            while True:
                if _monitor.metrics_enabled():
                    # data-wait: how long the consumer blocks on the
                    # prefetch queue — nonzero p50 here means the input
                    # pipeline, not the device, bounds the step
                    _monitor.gauge_set("dataloader_queue_depth",
                                       q.qsize())
                    t0 = _time.perf_counter()
                    item = q.get()
                    _monitor.hist_observe(
                        "dataloader_wait_ms",
                        (_time.perf_counter() - t0) * 1e3)
                else:
                    item = q.get()
                if item is stop:
                    break
                if isinstance(item, Exception):
                    raise item
                yield item
        finally:
            abandoned.set()
            # drain so a blocked put wakes immediately
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass

    # reference-compat constructors
    @staticmethod
    def from_generator(feed_list=None, capacity=None, use_double_buffer=True,
                       iterable=True, return_list=False,
                       use_multiprocess=False, drop_last=True):
        raise NotImplementedError(
            "from_generator is the legacy fluid reader API; wrap your "
            "generator in an IterableDataset instead")

    @staticmethod
    def from_dataset(dataset, places=None, drop_last=True):
        return DataLoader(dataset, places=places, drop_last=drop_last)
