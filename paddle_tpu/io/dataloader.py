"""DataLoader.

Parity: reference python/paddle/fluid/reader.py:149 DataLoader +
fluid/dataloader/dataloader_iter.py (:265 single-process, :469
multi-process with worker loop :379 and shared-memory transport).

TPU-native pipeline:
  workers (numpy batches) -> prefetch thread -> jax.device_put -> HBM
The device transfer is overlapped with compute by keeping a small queue of
in-flight device batches (the analog of the reference's double-buffered
``operators/reader/buffered_reader.cc``).
"""
from __future__ import annotations

import itertools
import queue
import threading
import traceback
from typing import Any, Callable, List, Optional

import numpy as np

from ..framework import monitor as _monitor
from ..framework.core import Tensor
from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler

__all__ = ["DataLoader", "default_collate_fn", "get_worker_info"]

_worker_info = threading.local()


def get_worker_info():
    return getattr(_worker_info, "info", None)


class WorkerInfo:
    def __init__(self, id, num_workers, dataset):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


def default_collate_fn(batch: List[Any]):
    """Stack samples into batched numpy arrays (device transfer happens in
    the loader, once per batch)."""
    sample = batch[0]
    if isinstance(sample, Tensor):
        return np.stack([np.asarray(s._value) for s in batch])
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, np.int32)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, np.float32)
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return type(sample)(default_collate_fn(list(t)) for t in transposed)
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    return batch


def _to_device(batch, places=None):
    import jax

    def conv(x):
        if isinstance(x, np.ndarray):
            if x.dtype == np.float64:
                x = x.astype(np.float32)
            if x.dtype == np.int64:
                x = x.astype(np.int32)
            return Tensor(jax.device_put(x))
        if isinstance(x, (list, tuple)):
            return type(x)(conv(v) for v in x)
        if isinstance(x, dict):
            return {k: conv(v) for k, v in x.items()}
        return x
    return conv(batch)


class _RemoteError:
    """An exception raised in a worker process, shipped with its trace."""

    def __init__(self, exc: BaseException):
        self.type_name = type(exc).__name__
        self.message = str(exc)
        self.trace = traceback.format_exc()

    def reraise(self):
        raise RuntimeError(
            f"DataLoader worker raised {self.type_name}: {self.message}\n"
            f"worker traceback:\n{self.trace}")


def _process_worker_loop(dataset, collate_fn, worker_init_fn, wid,
                         num_workers, index_queue, result_queue):
    """Runs in a forked child: pull index lists, push collated batches.

    Parity: reference fluid/dataloader/worker.py _worker_loop (the
    reference ships results through shared memory via core._convert_to_
    tensor_list; here the mp.Queue pickles numpy batches, and the fork
    start method means the dataset itself is never pickled).
    """
    _worker_info.info = WorkerInfo(wid, num_workers, dataset)
    if worker_init_fn is not None:
        try:
            worker_init_fn(wid)
        except Exception as e:          # surface init failures per-batch
            err = _RemoteError(e)
            while True:
                job = index_queue.get()
                if job is None:
                    return
                result_queue.put((job[0], err))
    while True:
        job = index_queue.get()
        if job is None:             # shutdown sentinel
            return
        batch_idx, idxs = job
        try:
            out = collate_fn([dataset[i] for i in idxs])
        except Exception as e:
            out = _RemoteError(e)
        result_queue.put((batch_idx, out))


class DataLoader:
    def __init__(self, dataset: Dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False, use_process=False,
                 mp_start_method="fork", seed=None):
        self.dataset = dataset
        # batch-cursor resume (ISSUE 9): ``seed`` makes every epoch's
        # shuffle permutation a pure function of (seed, epoch) so
        # ``state_dict()/load_state_dict()`` can resume mid-epoch
        # bit-exactly.  None keeps the legacy global-RNG behaviour
        # (and state_dict() on a shuffling loader then raises).
        self.seed = seed
        self._pos_epoch = 0   # epoch the live/next iteration runs
        self._pos_batch = 0   # batches already yielded within it
        self._resume = None   # (epoch, batch) pending from load_state_dict
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = max(prefetch_factor, 2)
        self.worker_init_fn = worker_init_fn
        self.places = places
        self.timeout = timeout
        # use_process=True forks OS workers (the reference's default
        # multi-process mode): needed when per-sample work is Python-
        # bound (PIL-style transforms, per-element loops) and the GIL
        # would serialize a thread pool. Threads remain the default for
        # numpy-bound collate, which releases the GIL.
        # Workers must stay off jax: fork from a process with a live
        # backend is only safe because children touch numpy alone (the
        # device transfer happens in the parent). Pass
        # mp_start_method="spawn" for fully isolated workers — the
        # dataset and collate_fn must then be picklable.
        self.use_process = use_process
        self.mp_start_method = mp_start_method
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_size = batch_size
            self.batch_sampler = None
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            if batch_size is None:
                raise ValueError("batch_size or batch_sampler required")
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no len()")
        return len(self.batch_sampler)

    # -- exact batch-cursor resume (ISSUE 9) ---------------------------
    def state_dict(self) -> dict:
        """The loader's exact batch cursor: ``{"epoch", "batch",
        "seed"}`` where ``batch`` counts batches already YIELDED to the
        consumer this epoch (prefetched-but-undelivered batches do not
        count).  Feeding it to :meth:`load_state_dict` on a freshly
        constructed identical loader resumes the stream element-wise —
        no sample skipped, none double-seen.  A shuffling loader must
        be constructed with ``seed=`` (or a seeded sampler): a
        global-RNG permutation cannot be reproduced on resume."""
        if self.seed is None and not self._iterable_mode:
            from .sampler import RandomSampler
            s = getattr(self.batch_sampler, "sampler", None)
            if isinstance(s, RandomSampler) and s.generator is None:
                raise ValueError(
                    "state_dict() on a shuffling DataLoader requires "
                    "seed=... (an unseeded global-RNG epoch permutation "
                    "cannot be reproduced when resuming)")
        return {"epoch": int(self._pos_epoch),
                "batch": int(self._pos_batch), "seed": self.seed}

    def load_state_dict(self, state: dict):
        """Arm the cursor: the NEXT ``iter()`` resumes at
        ``state["epoch"]`` with ``state["batch"]`` batches skipped.
        Map-style loaders fast-forward at the sampler-index level (the
        dataset is never touched for skipped batches); iterable-style
        loaders consume and discard the skipped batches' raw items."""
        if state.get("seed") is not None and self.seed is not None \
                and state["seed"] != self.seed:
            raise ValueError(
                f"checkpoint cursor was taken under seed="
                f"{state['seed']!r} but this loader has seed="
                f"{self.seed!r}; the shuffle streams would diverge")
        self._resume = (int(state["epoch"]), int(state["batch"]))
        self._pos_epoch, self._pos_batch = self._resume

    def _setup_epoch(self, epoch: int):
        """Per-epoch RNG derivation (only when ``seed`` is set, so
        legacy loaders keep their exact global-RNG behaviour):
        epoch-aware samplers get ``set_epoch``; an internally created
        RandomSampler draws from ``default_rng([seed, epoch])`` — the
        permutation is a pure function of (seed, epoch)."""
        if self.seed is None or self._iterable_mode:
            return
        bs = self.batch_sampler
        if hasattr(bs, "set_epoch"):
            bs.set_epoch(epoch)
        from .sampler import RandomSampler
        s = getattr(bs, "sampler", None)
        if isinstance(s, RandomSampler):
            s.generator = np.random.default_rng(
                [int(self.seed) & 0xFFFFFFFF, int(epoch)])

    # ------------------------------------------------------------------
    def _iter_batches_sync(self, sampler_iter=None, skip=0):
        if self._iterable_mode:
            it = iter(self.dataset)
            if skip:
                # cursor resume: an arbitrary iterable cannot be
                # fast-forwarded — consume the skipped batches' raw
                # items (only full batches can precede the cursor, so
                # skip * batch_size is exact)
                for _ in itertools.islice(it, skip * self.batch_size):
                    pass
            while True:
                chunk = list(itertools.islice(it, self.batch_size))
                if not chunk:
                    return
                if len(chunk) < self.batch_size and self.drop_last:
                    return
                yield self.collate_fn(chunk)
        else:
            for idxs in sampler_iter:
                yield self.collate_fn([self.dataset[i] for i in idxs])

    def _iter_batches_workers(self, sampler_iter):
        """Thread-pool workers.

        The reference forks OS processes and ships batches through shared
        memory because CPython + its C++ core hold the GIL during decode;
        here batch assembly is numpy-bound (releases the GIL), so threads
        deliver the same overlap without process startup / serialization.
        """
        from concurrent.futures import ThreadPoolExecutor

        wid_counter = itertools.count()

        def init_worker():
            wid = next(wid_counter)
            _worker_info.info = WorkerInfo(wid, self.num_workers,
                                           self.dataset)
            if self.worker_init_fn is not None:
                self.worker_init_fn(wid)

        pool = ThreadPoolExecutor(max_workers=self.num_workers,
                                  initializer=init_worker)
        try:
            def make(idxs):
                return self.collate_fn([self.dataset[i] for i in idxs])

            pending = []
            it = sampler_iter
            depth = self.num_workers * self.prefetch_factor
            for idxs in itertools.islice(it, depth):
                pending.append(pool.submit(make, idxs))
            while pending:
                fut = pending.pop(0)
                nxt = next(it, None)
                if nxt is not None:
                    pending.append(pool.submit(make, nxt))
                yield fut.result()
        finally:
            pool.shutdown(wait=False, cancel_futures=True)

    def _iter_batches_process(self, sampler_iter):
        """Forked worker processes with per-worker index queues, a shared
        result queue, and an in-order reorder buffer (the reference's
        _DataLoaderIterMultiProcess structure, dataloader_iter.py:469).

        A worker that dies without replying (OOM-killed, segfault in a
        C transform) is detected by polling liveness while waiting, so
        the loader raises instead of hanging forever.
        """
        import multiprocessing as mp
        ctx = mp.get_context(self.mp_start_method)

        workers, index_queues = [], []
        result_queue = ctx.Queue()
        for wid in range(self.num_workers):
            iq = ctx.Queue()
            p = ctx.Process(
                target=_process_worker_loop,
                args=(self.dataset, self.collate_fn, self.worker_init_fn,
                      wid, self.num_workers, iq, result_queue),
                daemon=True)
            p.start()
            workers.append(p)
            index_queues.append(iq)

        try:
            it = enumerate(sampler_iter)
            send_idx = 0            # next batch number to dispatch
            recv_idx = 0            # next batch number to yield
            reorder: dict = {}

            def dispatch():
                nonlocal send_idx
                job = next(it, None)
                if job is None:
                    return False
                index_queues[send_idx % self.num_workers].put(job)
                send_idx += 1
                return True

            for _ in range(self.num_workers * self.prefetch_factor):
                if not dispatch():
                    break
            while recv_idx < send_idx:
                while recv_idx not in reorder:
                    try:
                        idx, data = result_queue.get(
                            timeout=self.timeout or 5.0)
                    except queue.Empty:
                        dead = [w.pid for w in workers if not w.is_alive()]
                        if dead:
                            raise RuntimeError(
                                f"DataLoader worker(s) {dead} exited "
                                f"unexpectedly") from None
                        if self.timeout:
                            raise RuntimeError(
                                f"DataLoader timed out after "
                                f"{self.timeout}s waiting for a batch")
                        continue
                    reorder[idx] = data
                data = reorder.pop(recv_idx)
                recv_idx += 1
                dispatch()
                if isinstance(data, _RemoteError):
                    data.reraise()
                yield data
        finally:
            for iq in index_queues:
                try:
                    iq.put(None)
                except (OSError, ValueError):
                    pass
            for w in workers:
                w.join(timeout=1.0)
                if w.is_alive():
                    w.terminate()
            for q_ in index_queues + [result_queue]:
                q_.cancel_join_thread()
                q_.close()

    def __iter__(self):
        # batch cursor: a pending load_state_dict resumes at its
        # (epoch, batch); otherwise continue from the live position
        # (a fresh pass of epoch N, or epoch N+1 after exhaustion)
        resume = self._resume
        self._resume = None
        epoch = resume[0] if resume else self._pos_epoch
        skip = resume[1] if resume else 0
        self._pos_epoch, self._pos_batch = epoch, skip
        self._setup_epoch(epoch)
        if not self._iterable_mode:
            sampler_iter = iter(self.batch_sampler)
            if skip:
                # fast-forward at the index level: skipped batches cost
                # sampler draws only, never a dataset __getitem__
                for _ in itertools.islice(sampler_iter, skip):
                    pass
            if self.num_workers > 0:
                gen = (self._iter_batches_process(sampler_iter)
                       if self.use_process
                       else self._iter_batches_workers(sampler_iter))
            else:
                gen = self._iter_batches_sync(sampler_iter=sampler_iter)
        else:
            gen = self._iter_batches_sync(skip=skip)

        # prefetch-to-device pipeline (double buffering). The feeder checks
        # ``abandoned`` around every blocking put so an early `break` in the
        # consumer releases the thread (and closes the worker pool) instead
        # of leaking it.
        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch_factor)
        stop = object()
        abandoned = threading.Event()

        def feeder():
            try:
                for b in gen:
                    item = _to_device(b, self.places)
                    while not abandoned.is_set():
                        try:
                            q.put(item, timeout=0.1)
                            break
                        except queue.Full:
                            continue
                    if abandoned.is_set():
                        gen.close()
                        return
            except Exception as e:
                if not abandoned.is_set():
                    q.put(e)
            while not abandoned.is_set():
                try:
                    q.put(stop, timeout=0.1)
                    return
                except queue.Full:
                    continue

        t = threading.Thread(target=feeder, daemon=True)
        t.start()
        try:
            import time as _time
            while True:
                if _monitor.metrics_enabled():
                    # data-wait: how long the consumer blocks on the
                    # prefetch queue — nonzero p50 here means the input
                    # pipeline, not the device, bounds the step
                    _monitor.gauge_set("dataloader_queue_depth",
                                       q.qsize())
                    t0 = _time.perf_counter()
                    item = q.get()
                    _monitor.hist_observe(
                        "dataloader_wait_ms",
                        (_time.perf_counter() - t0) * 1e3)
                else:
                    item = q.get()
                if item is stop:
                    # clean exhaustion: the cursor rolls to the next
                    # epoch (an abandoned iterator keeps its position)
                    self._pos_epoch += 1
                    self._pos_batch = 0
                    break
                if isinstance(item, Exception):
                    raise item
                self._pos_batch += 1
                yield item
        finally:
            abandoned.set()
            # drain so a blocked put wakes immediately
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass

    # reference-compat constructors
    @staticmethod
    def from_generator(feed_list=None, capacity=None, use_double_buffer=True,
                       iterable=True, return_list=False,
                       use_multiprocess=False, drop_last=True):
        raise NotImplementedError(
            "from_generator is the legacy fluid reader API; wrap your "
            "generator in an IterableDataset instead")

    @staticmethod
    def from_dataset(dataset, places=None, drop_last=True):
        return DataLoader(dataset, places=places, drop_last=drop_last)
