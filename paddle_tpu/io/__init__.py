"""paddle_tpu.io — Dataset / DataLoader / samplers.

Parity: reference python/paddle/fluid/dataloader/ (Dataset, BatchSampler,
dataloader_iter.py:265 single-process & :469 multi-process iterators with
shared-memory tensor transport via memory/allocation/mmap_allocator.cc).

TPU-native design: workers produce **host numpy** batches (multiprocessing
with pickle/shm — no custom mmap allocator needed since the expensive hop
is host->HBM, which happens once per batch via device_put, overlapped by a
prefetch depth like the reference's buffered_reader
(operators/reader/buffered_reader.cc)).
"""
from .dataset import (ChainDataset, ComposeDataset, ConcatDataset, Dataset,  # noqa: F401
                      IterableDataset, Subset, TensorDataset, random_split)
from .sampler import (BatchSampler, DistributedBatchSampler, RandomSampler,  # noqa: F401
                      Sampler, SequenceSampler, SubsetRandomSampler,
                      WeightedRandomSampler)
from .dataloader import DataLoader, default_collate_fn, get_worker_info  # noqa: F401

__all__ = ["Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
           "ChainDataset", "ConcatDataset", "Subset", "random_split",
           "Sampler", "SequenceSampler", "RandomSampler", "BatchSampler",
           "DistributedBatchSampler", "SubsetRandomSampler",
           "WeightedRandomSampler", "DataLoader", "default_collate_fn",
           "get_worker_info"]
