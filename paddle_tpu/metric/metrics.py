"""Compat submodule (the reference implements paddle.metric's classes in
python/paddle/metric/metrics.py and re-exports them at package level)."""
from . import Accuracy, Auc, Metric, Precision, Recall, accuracy  # noqa: F401

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]
