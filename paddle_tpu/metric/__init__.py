"""paddle_tpu.metric (parity: python/paddle/metric/metrics.py — Accuracy,
Auc, Precision, Recall; reference C++ graph-op versions
operators/metrics/accuracy_op.*, auc_op.*)."""
from __future__ import annotations

import numpy as np

from ..framework.core import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Functional top-k accuracy (reference operators/metrics/accuracy_op)."""
    import jax.numpy as jnp
    logits = input._value
    lab = label._value if isinstance(label, Tensor) else np.asarray(label)
    topk = jnp.argsort(-logits, axis=-1)[..., :k]
    lab = lab.reshape(-1, 1)
    hit = (topk == lab).any(axis=-1)
    return Tensor(jnp.mean(hit.astype(jnp.float32)))


class Metric:
    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        pred_np = np.asarray(pred._value if isinstance(pred, Tensor) else pred)
        lab = np.asarray(label._value if isinstance(label, Tensor) else label)
        topk_idx = np.argsort(-pred_np, axis=-1)[..., :self.maxk]
        if lab.ndim == pred_np.ndim:  # one-hot
            lab = lab.argmax(-1)
        lab = lab.reshape(-1, 1)
        return (topk_idx == lab).astype(np.float32)

    def update(self, correct, *args):
        c = np.asarray(correct._value if isinstance(correct, Tensor) else correct)
        accs = []
        for k in self.topk:
            num = c[..., :k].sum()
            self.total[self.topk.index(k)] += num
            self.count[self.topk.index(k)] += c.shape[0]
            accs.append(num / c.shape[0])
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        return self._name


class Precision(Metric):
    def __init__(self, name="precision"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = np.asarray(preds._value if isinstance(preds, Tensor) else preds)
        l = np.asarray(labels._value if isinstance(labels, Tensor) else labels)
        pred_pos = (p.reshape(-1) > 0.5)
        lab = l.reshape(-1).astype(bool)
        self.tp += int((pred_pos & lab).sum())
        self.fp += int((pred_pos & ~lab).sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = np.asarray(preds._value if isinstance(preds, Tensor) else preds)
        l = np.asarray(labels._value if isinstance(labels, Tensor) else labels)
        pred_pos = (p.reshape(-1) > 0.5)
        lab = l.reshape(-1).astype(bool)
        self.tp += int((pred_pos & lab).sum())
        self.fn += int((~pred_pos & lab).sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """Histogram-bucketed AUC (the reference's auc_op uses the same
    statistics-bucket approach, operators/metrics/auc_op.h)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        super().__init__()
        self.num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = np.asarray(preds._value if isinstance(preds, Tensor) else preds)
        l = np.asarray(labels._value if isinstance(labels, Tensor) else labels)
        if p.ndim == 2 and p.shape[1] == 2:
            p = p[:, 1]
        p = p.reshape(-1)
        l = l.reshape(-1)
        idx = np.clip((p * self.num_thresholds).astype(int), 0,
                      self.num_thresholds)
        mask = l.astype(bool)
        self._stat_pos += np.bincount(idx[mask],
                                      minlength=self.num_thresholds + 1)
        self._stat_neg += np.bincount(idx[~mask],
                                      minlength=self.num_thresholds + 1)

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def accumulate(self):
        tot_pos = 0.0
        tot_neg = 0.0
        auc = 0.0
        for i in range(self.num_thresholds, -1, -1):
            new_pos = tot_pos + self._stat_pos[i]
            new_neg = tot_neg + self._stat_neg[i]
            auc += (new_pos + tot_pos) / 2.0 * (new_neg - tot_neg)
            tot_pos, tot_neg = new_pos, new_neg
        denom = tot_pos * tot_neg
        return float(auc / denom) if denom else 0.0

    def name(self):
        return self._name

from . import metrics  # noqa: F401,E402  (submodule compat)
