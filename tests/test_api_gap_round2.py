"""Round-2 API-parity additions (found by auditing the reference's
import surface): beam search decode, hsigmoid, bilinear/diag_embed/
gather_tree, tensor array ops, inplace variants, ParamAttr and other
top-level exports."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


# ------------------------------------------------------------- beam search
class _BigramCell(nn.RNNCellBase):
    """Deterministic 'LM': logits depend only on the previous token via a
    fixed bigram table — lets a brute-force search define ground truth."""

    def __init__(self, table):
        super().__init__()
        self.table = paddle.to_tensor(table.astype(np.float32))

    def forward(self, inputs, states=None):
        import jax.numpy as jnp
        from paddle_tpu.framework.core import _apply
        # inputs: (B,) previous token ids; states: (B, 1) dummy
        out = _apply(lambda t, i: t[i.astype(jnp.int32)], self.table,
                     inputs, op_name="bigram")
        return out, states


def _brute_force_best(table, start, end, steps):
    """Exhaustive max-logprob path of length <= steps."""
    import itertools

    def logp(tok_seq):
        import scipy.special as sp
        lp, prev, total = None, start, 0.0
        for t in tok_seq:
            row = table[prev]
            total += row[t] - sp.logsumexp(row)
            if t == end:
                break
            prev = t
        return total
    best, best_lp = None, -1e18
    V = table.shape[1]
    for seq in itertools.product(range(V), repeat=steps):
        # truncate at first eos for fairness
        if end in seq:
            seq = seq[:seq.index(end) + 1]
        lp = logp(seq)
        if lp > best_lp:
            best_lp, best = lp, seq
    return list(best)


def test_beam_search_matches_brute_force():
    rng = np.random.default_rng(0)
    V, start, end = 5, 0, 4
    table = rng.normal(size=(V, V)).astype(np.float32) * 2.0
    cell = _BigramCell(table)
    dec = nn.BeamSearchDecoder(cell, start_token=start, end_token=end,
                               beam_size=V)  # full-width = exact search
    init = paddle.to_tensor(np.zeros((1, 1), np.float32))
    ids, lens = nn.dynamic_decode(dec, inits=init, max_step_num=3)
    got = list(np.asarray(ids.numpy())[0, 0, :int(lens.numpy()[0, 0])])
    want = _brute_force_best(table, start, end, 3)
    assert got == want, (got, want)


def test_dynamic_decode_batch_and_eos_lengths():
    V, start, end = 4, 0, 3
    table = np.full((V, V), -5.0, np.float32)
    table[:, end] = 5.0      # every path wants to emit eos immediately
    cell = _BigramCell(table)
    dec = nn.BeamSearchDecoder(cell, start, end, beam_size=2)
    init = paddle.to_tensor(np.zeros((3, 1), np.float32))
    ids, lens = nn.dynamic_decode(dec, inits=init, max_step_num=5)
    assert ids.shape[0] == 3 and ids.shape[1] == 2
    # best beam emits eos immediately; the runner-up beam (forced to a
    # different first token by the fan-out) ends one step later
    lens = np.asarray(lens.numpy())
    assert (lens[:, 0] == 1).all(), lens
    assert (lens[:, 1] == 2).all(), lens


def test_gather_tree_backtrace():
    # T=3, B=1, beam=2: parent pointers reorder the history
    ids = paddle.to_tensor(np.array(
        [[[2, 3]], [[4, 5]], [[6, 7]]], np.int64))
    parents = paddle.to_tensor(np.array(
        [[[0, 0]], [[1, 0]], [[1, 0]]], np.int64))
    out = np.asarray(F.gather_tree(ids, parents).numpy())
    # beam 0 at t=2 came from beam 1 at t=1, which came from beam 0
    np.testing.assert_array_equal(out[:, 0, 0], [2, 5, 6])
    np.testing.assert_array_equal(out[:, 0, 1], [3, 4, 7])


# ---------------------------------------------------------------- hsigmoid
def test_hsigmoid_trains_small_classifier():
    rng = np.random.default_rng(0)
    nfeat, ncls = 8, 6
    layer = nn.HSigmoidLoss(nfeat, ncls)
    opt = paddle.optimizer.Adam(learning_rate=0.1,
                                parameters=layer.parameters())
    x = rng.normal(size=(64, nfeat)).astype(np.float32)
    y = (np.abs(x[:, :1]).astype(np.int64) * 0 +
         rng.integers(0, ncls, (64, 1)))
    # learnable signal: class determined by argmax of first ncls feats
    y = x[:, :ncls].argmax(1, keepdims=True).astype(np.int64)
    first = last = None
    for _ in range(60):
        loss = layer(paddle.to_tensor(x),
                     paddle.to_tensor(y)).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        if first is None:
            first = float(loss.numpy())
        last = float(loss.numpy())
    assert last < first * 0.5, (first, last)


def test_hsigmoid_custom_path():
    nfeat, ncls = 4, 4
    layer = nn.HSigmoidLoss(nfeat, ncls, is_custom=True)
    x = paddle.to_tensor(np.ones((2, nfeat), np.float32))
    y = paddle.to_tensor(np.zeros((2, 1), np.int64))
    table = paddle.to_tensor(np.array([[0, 1, -1], [2, -1, -1]], np.int64))
    code = paddle.to_tensor(np.array([[1, 0, 0], [0, 0, 0]], np.int64))
    out = layer(x, y, path_table=table, path_code=code)
    assert out.shape == [2, 1]
    with pytest.raises(ValueError, match="path_table"):
        layer(x, y)


# ------------------------------------------------------- small functionals
def test_bilinear():
    rng = np.random.default_rng(0)
    x1 = rng.normal(size=(3, 4)).astype(np.float32)
    x2 = rng.normal(size=(3, 5)).astype(np.float32)
    w = rng.normal(size=(2, 4, 5)).astype(np.float32)
    b = rng.normal(size=(2,)).astype(np.float32)
    got = np.asarray(F.bilinear(paddle.to_tensor(x1), paddle.to_tensor(x2),
                                paddle.to_tensor(w),
                                paddle.to_tensor(b)).numpy())
    want = np.einsum("bi,kij,bj->bk", x1, w, x2) + b
    np.testing.assert_allclose(got, want, rtol=1e-4)


def test_diag_embed():
    v = np.arange(6, dtype=np.float32).reshape(2, 3)
    out = np.asarray(F.diag_embed(paddle.to_tensor(v)).numpy())
    assert out.shape == (2, 3, 3)
    np.testing.assert_allclose(out[0], np.diag(v[0]))
    off = np.asarray(F.diag_embed(paddle.to_tensor(v), offset=1).numpy())
    assert off.shape == (2, 4, 4)
    np.testing.assert_allclose(off[1], np.diag(v[1], k=1))


def test_log_sigmoid_and_inplace_variants():
    x = np.array([-1.0, 0.0, 2.0], np.float32)
    np.testing.assert_allclose(
        np.asarray(F.log_sigmoid(paddle.to_tensor(x)).numpy()),
        np.log(1 / (1 + np.exp(-x))), rtol=1e-5)
    t = paddle.to_tensor(x.copy())
    F.softmax_(t)
    np.testing.assert_allclose(np.asarray(t.numpy()).sum(), 1.0, rtol=1e-5)
    t2 = paddle.to_tensor(x.copy())
    F.elu_(t2)
    assert float(t2.numpy()[0]) < 0


# ------------------------------------------------------------ audit gate
def test_reference_import_surface_nearly_complete():
    """Mechanical parity gate: names the reference's package __init__
    imports must exist here, minus documented exclusions."""
    import ast, os

    EXCLUDED = {
        # internal monkey-patch machinery, not user API
        "monkey_patch_math_varbase", "monkey_patch_variable",
        "print_function",
        # nn namespace modules that are pure re-export shims upstream
        "extension", "vision", "weight_norm_hook",
        # fluid-era in-place that the reference itself removed later
    }

    def imported(path):
        names = set()
        for node in ast.parse(open(path).read()).body:
            if isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    if a.name != "*":
                        names.add(a.asname or a.name)
        return {n for n in names if not n.startswith("_")}

    ref = "/root/reference/python/paddle"
    if not os.path.isdir(ref):
        pytest.skip("reference tree not mounted")
    import paddle_tpu.tensor
    for rel, obj in [("__init__.py", paddle),
                     ("nn/__init__.py", nn),
                     ("nn/functional/__init__.py", F),
                     ("tensor/__init__.py", paddle.tensor)]:
        want = imported(os.path.join(ref, rel)) - EXCLUDED
        missing = sorted(n for n in want if not hasattr(obj, n))
        assert not missing, (rel, missing)


# ------------------------------------------------------------- param attr
def test_param_attr_trainable_and_lr_and_regularizer():
    import paddle_tpu.regularizer as reg
    frozen = paddle.create_parameter(
        [2, 2], attr=paddle.ParamAttr(trainable=False))
    assert frozen.stop_gradient
    slow = paddle.create_parameter(
        [1], attr=paddle.ParamAttr(learning_rate=0.1))
    fast = paddle.create_parameter(
        [1], attr=paddle.ParamAttr(learning_rate=1.0))
    import jax.numpy as jnp
    slow._value = jnp.zeros((1,)); fast._value = jnp.zeros((1,))
    opt = paddle.optimizer.SGD(learning_rate=1.0, parameters=[slow, fast])
    g = paddle.to_tensor(np.ones((1,), np.float32))
    slow.grad = g; fast.grad = g
    opt.step()
    np.testing.assert_allclose(np.asarray(slow._value), [-0.1], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(fast._value), [-1.0], rtol=1e-6)
    # param-level regularizer overrides optimizer-level decay
    p = paddle.create_parameter(
        [1], attr=paddle.ParamAttr(regularizer=reg.L2Decay(0.5)))
    p._value = jnp.ones((1,))
    opt2 = paddle.optimizer.SGD(learning_rate=1.0, parameters=[p],
                                weight_decay=0.0)
    p.grad = paddle.to_tensor(np.zeros((1,), np.float32))
    opt2.step()
    # grad 0 + 0.5 * w decay -> w = 1 - 0.5
    np.testing.assert_allclose(np.asarray(p._value), [0.5], rtol=1e-6)


def test_hsigmoid_missing_path_code_clear_error():
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    y = paddle.to_tensor(np.zeros((2, 1), np.int64))
    w = paddle.to_tensor(np.ones((3, 4), np.float32))
    tbl = paddle.to_tensor(np.zeros((2, 2), np.int64))
    with pytest.raises(ValueError, match="BOTH"):
        F.hsigmoid_loss(x, y, 4, w, path_table=tbl)


def test_set_printoptions_sci_mode():
    paddle.set_printoptions(sci_mode=True, precision=2)
    try:
        assert "e" in repr(paddle.to_tensor([1234.5]))
    finally:
        paddle.set_printoptions(sci_mode=False, precision=6)


# ----------------------------------------------- finite-difference grads
def test_new_op_gradients_vs_finite_differences():
    from op_test import check_grad
    rng = np.random.default_rng(0)
    x1 = rng.normal(size=(3, 4)).astype(np.float32)
    x2 = rng.normal(size=(3, 5)).astype(np.float32)
    w = rng.normal(size=(2, 4, 5)).astype(np.float32)
    b = rng.normal(size=(2,)).astype(np.float32)
    check_grad(F.bilinear, [x1, x2, w, b])
    v = rng.normal(size=(2, 3)).astype(np.float32)
    check_grad(F.diag_embed, [v])
    check_grad(F.log_sigmoid, [rng.normal(size=(6,)).astype(np.float32)])

    # hsigmoid grads w.r.t. input and weight
    xi = rng.normal(size=(4, 8)).astype(np.float32)
    wt = rng.normal(size=(5, 8)).astype(np.float32) * 0.3
    lb = rng.integers(0, 6, (4, 1)).astype(np.int64)
    check_grad(lambda a, ww: F.hsigmoid_loss(a, paddle.to_tensor(lb), 6,
                                             ww), [xi, wt])


def test_resnet_data_format_nhwc_matches_nchw():
    # reference vision resnet's data_format knob; eval mode is exactly
    # layout-invariant (train-mode BN over tiny N*H*W reductions
    # amplifies float reassociation, so eval is the equality check)
    from paddle_tpu.vision.models.resnet import ResNet, BasicBlock
    paddle.seed(0)
    m1 = ResNet(BasicBlock, depth=18, num_classes=10)
    paddle.seed(0)
    m2 = ResNet(BasicBlock, depth=18, num_classes=10,
                data_format="NHWC")
    m1.eval(); m2.eval()
    x = np.random.default_rng(0).normal(size=(2, 3, 32, 32)).astype(
        np.float32)
    a = np.asarray(m1(paddle.to_tensor(x)).numpy())
    b = np.asarray(m2(paddle.to_tensor(
        x.transpose(0, 2, 3, 1))).numpy())
    np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)
