"""PS-backed embedding serving (ISSUE 10): the pull-only serving
client wired into PredictorServer.

- a wide_deep-style predictor serves embeddings the PS updated moments
  ago — NO checkpoint round trip: push_delta on the training side is
  visible to the very next inference batch (through a read replica
  with bounded staleness);
- the embedding pull happens in the micro-batcher (once per coalesced
  batch) and its wall time is accounted in stats()["ps_ms"];
- shed/timeout semantics extend to PS-read failures: a read that fails
  past the read tier's fan-out/failover fails the batch's requests
  with typed UpstreamUnavailable and the server KEEPS serving.
"""
import time

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed.fleet.ps import SparseTable
from paddle_tpu.distributed.fleet.ps_service import PSClient, PSServer
from paddle_tpu.inference import (Config, PredictorServer,
                                  UpstreamUnavailable, create_predictor)
from paddle_tpu.static import InputSpec

_FAST = dict(connect_timeout=2.0, rpc_timeout=1.0, max_retries=3,
             backoff_base=0.02, rpc_deadline=5.0)

N_SLOTS, DIM = 4, 8


class WideDeepHead(nn.Layer):
    """Dense tower over already-pulled embedding rows — the serving
    half of the host-offloaded-embedding pattern."""

    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(3, 1)

    def forward(self, emb, dense):
        deep = emb.sum(axis=-1).sum(axis=-1)        # (B,)
        return deep + self.fc(dense)[:, 0]


@pytest.fixture(scope="module")
def exported(tmp_path_factory):
    paddle.seed(7)
    model = WideDeepHead()
    model.eval()
    path = str(tmp_path_factory.mktemp("serve_ps") / "wd_head")
    paddle.jit.save(model, path, input_spec=[
        InputSpec([None, N_SLOTS, DIM], "float32", "emb"),
        InputSpec([None, 3], "float32", "dense")])
    return path


def _predictor(path):
    cfg = Config(path)
    cfg.disable_gpu()
    return create_predictor(cfg)


def _ps_cluster():
    tbl = SparseTable(DIM, optimizer="sgd", lr=1.0, seed=0,
                      init_std=0.0)
    prim = PSServer({"emb": tbl}, host="127.0.0.1")
    prim.start()
    pep = f"127.0.0.1:{prim.port}"
    rep = PSServer({"emb": SparseTable(DIM, optimizer="sgd", lr=1.0,
                                       seed=0, init_std=0.0)},
                   host="127.0.0.1", replica_of=pep,
                   replica_mode="read")
    rep.start()
    assert rep.replica_ready.wait(10.0)
    return prim, pep, rep, f"127.0.0.1:{rep.port}"


def test_serves_fresh_embeddings_without_checkpoint_round_trip(exported):
    prim, pep, rep, rep_ep = _ps_cluster()
    pred = _predictor(exported)
    rd = PSClient([pep], mode="read", max_lag=2,
                  read_replicas=[rep_ep], **_FAST)
    w = PSClient([pep], mode="sync", **_FAST)
    server = PredictorServer(pred, max_batch=8, max_wait_ms=1.0,
                             ps_client=rd, ps_tables={0: "emb"})
    try:
        # seed the table: row k = k in every dim
        ids_all = np.arange(40, dtype=np.int64)
        w.push_delta("emb", ids_all,
                     np.repeat(ids_all.astype(np.float32)[:, None],
                               DIM, axis=1))
        server.start()
        ids = np.array([[1, 5, 9, 30], [2, 2, 7, 11]], np.int64)
        dense = np.zeros((2, 3), np.float32)
        deadline = time.monotonic() + 10.0
        want1 = DIM * ids.sum(axis=1).astype(np.float32)
        while time.monotonic() < deadline:
            out = server.infer([ids, dense], timeout_s=10.0)
            deep = out[0] - _predictor_dense_term(pred, dense)
            if np.allclose(deep, want1, atol=1e-4):
                break
            time.sleep(0.05)
        assert np.allclose(deep, want1, atol=1e-4), (deep, want1)

        # the training side moves the rows; the NEXT batches see it —
        # no checkpoint, no predictor reload
        w.push_delta("emb", ids_all,
                     np.full((40, DIM), 100.0, np.float32))
        want2 = want1 + 100.0 * DIM * N_SLOTS
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            out = server.infer([ids, dense], timeout_s=10.0)
            deep = out[0] - _predictor_dense_term(pred, dense)
            if np.allclose(deep, want2, atol=1e-3):
                break
            time.sleep(0.05)
        assert np.allclose(deep, want2, atol=1e-3), (deep, want2)
        st = server.stats()
        assert st["ps_ms"] > 0.0
        assert st["shed_ps"] == 0
        assert rd.read_fanout >= 1    # replicas actually served pulls
    finally:
        server.stop()
        rd.close()
        w.close()
        rep.stop()
        prim.stop()


def _predictor_dense_term(pred, dense):
    """The fc(dense) contribution, via the predictor itself with zero
    embeddings — keeps the test independent of the Linear init."""
    zero_emb = np.zeros((dense.shape[0], N_SLOTS, DIM), np.float32)
    return pred.run([zero_emb, dense])[0]


def test_ps_read_failure_sheds_typed_and_server_survives(exported):
    prim, pep, rep, rep_ep = _ps_cluster()
    pred = _predictor(exported)
    rd = PSClient([pep], mode="read", max_lag=2,
                  read_replicas=[rep_ep], **_FAST)
    server = PredictorServer(pred, max_batch=8, max_wait_ms=1.0,
                             ps_client=rd, ps_tables={0: "emb"})
    try:
        server.start()
        ids = np.zeros((1, N_SLOTS), np.int64)
        dense = np.zeros((1, 3), np.float32)
        server.infer([ids, dense], timeout_s=10.0)   # healthy first
        # the WHOLE read tier dies: replica + primary
        rep.stop()
        prim.stop()
        with pytest.raises(UpstreamUnavailable):
            server.infer([ids, dense], timeout_s=30.0)
        st = server.stats()
        assert st["shed_ps"] >= 1
        # the batcher thread survived: the next request fails the same
        # typed way instead of ServerClosed/timeout
        with pytest.raises(UpstreamUnavailable):
            server.infer([ids, dense], timeout_s=30.0)
    finally:
        server.stop()
        rd.close()
        rep.stop()
        prim.stop()


def test_ps_tables_without_client_rejected(exported):
    pred = _predictor(exported)
    with pytest.raises(ValueError, match="ps_client"):
        PredictorServer(pred, ps_tables={0: "emb"})
