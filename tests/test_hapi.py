"""hapi.Model tests (parity model: reference python/paddle/tests/test_model.py
— fit/evaluate/predict on a small net, save/load round-trip, callbacks,
summary and flops)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import hapi, nn
from paddle_tpu.hapi.callbacks import EarlyStopping, VisualDL
from paddle_tpu.io import Dataset
from paddle_tpu.metric import Accuracy
from paddle_tpu.optimizer import Adam


class TinyDataset(Dataset):
    def __init__(self, n=64, seed=0):
        rng = np.random.RandomState(seed)
        self.x = rng.randn(n, 8).astype("float32")
        w = rng.randn(8, 3).astype("float32")
        self.y = np.argmax(self.x @ w, axis=1).astype("int64")

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


def make_model():
    net = nn.Sequential(
        nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 3))
    model = hapi.Model(net)
    model.prepare(Adam(learning_rate=0.01,
                       parameters=net.parameters()),
                  nn.CrossEntropyLoss(), Accuracy())
    return model


def test_fit_reduces_loss_and_evaluate():
    model = make_model()
    ds = TinyDataset()
    first = model.evaluate(ds, batch_size=32, verbose=0)
    model.fit(ds, batch_size=16, epochs=8, verbose=0)
    last = model.evaluate(ds, batch_size=32, verbose=0)
    assert last["loss"] < first["loss"]
    assert last["acc"] > 0.8
    assert set(last) >= {"loss", "acc"}


def test_predict_shapes_and_stack():
    model = make_model()
    ds = TinyDataset(n=20)
    outs = model.predict(ds, batch_size=8, verbose=0)
    assert len(outs) == 1 and len(outs[0]) == 3  # 3 batches of logits
    stacked = model.predict(ds, batch_size=8, stack_outputs=True, verbose=0)
    assert stacked[0].shape == (20, 3)


def test_train_batch_and_eval_batch():
    model = make_model()
    x = np.random.randn(4, 8).astype("float32")
    y = np.array([0, 1, 2, 0], dtype="int64")
    loss, metrics = model.train_batch([x], [y])
    assert np.isfinite(loss[0])
    out = model.eval_batch([x], [y])
    assert np.isfinite(out[0][0])


def test_save_load_roundtrip(tmp_path):
    model = make_model()
    ds = TinyDataset(n=32)
    model.fit(ds, batch_size=16, epochs=1, verbose=0)
    path = str(tmp_path / "ckpt" / "m")
    model.save(path)
    assert os.path.exists(path + ".pdparams")
    assert os.path.exists(path + ".pdopt")

    model2 = make_model()
    model2.load(path)
    x = np.random.randn(2, 8).astype("float32")
    np.testing.assert_allclose(model.predict_batch([x])[0],
                               model2.predict_batch([x])[0], rtol=1e-6)


def test_fit_with_save_dir_checkpoints(tmp_path):
    model = make_model()
    save_dir = str(tmp_path / "ckpts")
    model.fit(TinyDataset(n=32), batch_size=16, epochs=2, verbose=0,
              save_dir=save_dir)
    assert os.path.exists(os.path.join(save_dir, "0.pdparams"))
    assert os.path.exists(os.path.join(save_dir, "final.pdparams"))


def test_early_stopping_stops():
    model = make_model()
    ds = TinyDataset(n=32)
    stopper = EarlyStopping(monitor="loss", patience=0, verbose=0,
                            save_best_model=False)
    # monitor improvement is impossible with lr=0 → stops after patience
    model._optimizer.set_lr(0.0)
    model.fit(ds, eval_data=ds, batch_size=16, epochs=10, verbose=0,
              callbacks=[stopper])
    assert model.stop_training
    assert stopper.stopped_epoch < 10


def test_visualdl_writes_scalars(tmp_path):
    model = make_model()
    log_dir = str(tmp_path / "vdl")
    model.fit(TinyDataset(n=32), batch_size=16, epochs=1, verbose=0,
              callbacks=[VisualDL(log_dir)])
    path = os.path.join(log_dir, "scalars.jsonl")
    assert os.path.exists(path)
    assert len(open(path).read().strip().splitlines()) >= 2


def test_summary_counts_params(capsys):
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 3))
    info = hapi.summary(net, (1, 8))
    # 8*16+16 + 16*3+3 = 195
    assert info["total_params"] == 195
    assert info["trainable_params"] == 195
    assert "Linear" in capsys.readouterr().out


def test_flops_linear():
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 3))
    n = hapi.flops(net, (1, 8))
    # (8+1)*16 + 16 + (16+1)*3 = 211
    assert n == 211


def test_model_summary_via_model():
    model = make_model()
    info = model.summary(input_size=(1, 8))
    assert info["total_params"] == 195


def test_evaluate_metrics_only_no_loss():
    # loss=None + metrics: metric must be reported under its own name
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 3))
    model = hapi.Model(net)
    model.prepare(metrics=Accuracy())
    res = model.evaluate(TinyDataset(n=32), batch_size=16, verbose=0)
    assert "acc" in res
    assert "loss" not in res


def test_fit_zero_epochs_noop():
    model = make_model()
    model.fit(TinyDataset(n=16), batch_size=8, epochs=0, verbose=0)


def test_grad_accumulation_tail_update():
    model = make_model()
    ds = TinyDataset(n=48)  # 3 batches of 16 with accumulate=2 → tail batch
    before = [np.array(p.numpy()) for p in model.parameters()]
    model.fit(ds, batch_size=16, epochs=1, verbose=0,
              accumulate_grad_batches=2)
    after = [p.numpy() for p in model.parameters()]
    assert any(not np.allclose(b, a) for b, a in zip(before, after))
    # grads from the tail batch must have been consumed, not leaked
    assert all(p.grad is None or np.allclose(p.grad.numpy(), 0)
               for p in model.parameters())


def test_top_level_exports():
    assert paddle.Model is hapi.Model
    assert paddle.summary is hapi.summary
    assert paddle.flops is hapi.flops
