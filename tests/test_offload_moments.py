"""Sharding offload + low-precision optimizer moments (r5, VERDICT r4
next-round item 1 / weak #5).

Reference parity: distributed_strategy.proto:27 ``offload`` consumed by
fleet/meta_optimizers/sharding_optimizer.py:33; moment_dtype is the
greenfield in-HBM alternative (bf16 / int8 slots).
"""
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed.fleet.dist_step import DistributedTrainStep


def _train(moment_dtype, steps=8, stage=3, offload=False):
    mesh_mod.set_mesh(None)
    mesh = mesh_mod.init_mesh({"fsdp": 4, "dp": 2})
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(32, 64), nn.ReLU(), nn.Linear(64, 8))
    opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                 parameters=model.parameters())
    s = fleet.DistributedStrategy()
    s.sharding = True
    s.sharding_configs = {"stage": stage, "moment_dtype": moment_dtype,
                          "offload": offload}

    def loss_fn(x, y):
        return F.cross_entropy(model(x), y)

    step = DistributedTrainStep(model, loss_fn, opt, s, mesh=mesh)
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.rand(16, 32).astype("float32"))
    y = paddle.to_tensor(rng.randint(0, 8, (16,)))
    losses = [float(step(x, y)) for _ in range(steps)]
    st = opt.opt_state()
    mesh_mod.set_mesh(None)
    return losses, st, opt


def test_bf16_moments_train_and_storage():
    l32, _, _ = _train("float32")
    l16, st, _ = _train("bfloat16")
    assert st[0]["m"].dtype == jnp.bfloat16
    assert st[0]["v"].dtype == jnp.bfloat16
    # scalar machinery stays f32
    assert st[0]["beta1_pow"].dtype == jnp.float32
    assert l16[-1] < l16[0]
    # trajectory tracks f32 within low-precision tolerance
    np.testing.assert_allclose(l16, l32, rtol=5e-2)


def test_int8_moments_train_and_storage():
    l32, _, _ = _train("float32")
    l8, st, _ = _train("int8")
    assert st[0]["m"].dtype == jnp.int8
    assert st[0]["v"].dtype == jnp.int8
    # per-row scales ride alongside, shaped like the slot minus last dim
    assert st[0]["m@scale"].dtype == jnp.float32
    assert st[0]["m@scale"].shape == st[0]["m"].shape[:-1]
    assert st[0]["beta1_pow"].dtype == jnp.float32
    assert l8[-1] < l8[0]
    np.testing.assert_allclose(l8, l32, rtol=5e-2)


def test_int8_moments_checkpoint_roundtrip():
    from paddle_tpu.distributed.fleet.dist_step import _q8_decode
    _, st, opt = _train("int8", steps=3)
    sd = opt.state_dict()
    # restore DECODES the int8 codes + "@scale" leaves back to plain
    # f32 slots: eager optimizer math and differently-configured steps
    # must never see raw codes; an int8-configured step re-encodes on
    # its next call
    paddle.seed(0)
    model2 = nn.Sequential(nn.Linear(32, 64), nn.ReLU(), nn.Linear(64, 8))
    opt2 = paddle.optimizer.AdamW(learning_rate=1e-2,
                                  parameters=model2.parameters())
    opt2.set_state_dict(sd)
    st2 = opt2.opt_state()
    assert st2[0]["m"].dtype == jnp.float32
    assert "m@scale" not in st2[0]
    np.testing.assert_allclose(
        np.asarray(st2[0]["m"]),
        np.asarray(_q8_decode(st[0]["m"], st[0]["m@scale"])), rtol=1e-6)
    # and the eager step consumes the restored state without blowing up
    x = paddle.to_tensor(np.random.RandomState(0).rand(4, 32)
                         .astype("float32"))
    y = paddle.to_tensor(np.random.RandomState(0).randint(0, 8, (4,)))
    loss = F.cross_entropy(model2(x), y)
    loss.backward()
    opt2.step()
    opt2.clear_grad()


def test_offload_raises_loudly_on_cpu():
    # the CPU backend cannot compile host-resident state into programs;
    # silent fallback is exactly the inert-knob sin VERDICT r4 flagged
    with pytest.raises(NotImplementedError, match="offload"):
        _train("float32", offload=True)


def test_offload_ignored_without_sharding():
    # offload lives in sharding_configs: without strategy.sharding the
    # config is inert by reference semantics and must not raise
    mesh_mod.set_mesh(None)
    mesh = mesh_mod.init_mesh({"dp": -1})
    paddle.seed(0)
    model = nn.Linear(8, 4)
    opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                 parameters=model.parameters())
    s = fleet.DistributedStrategy()
    s.sharding_configs = {"offload": True}
    step = DistributedTrainStep(
        model, lambda x, y: F.cross_entropy(model(x), y), opt, s,
        mesh=mesh)
    assert step._offload is False
    mesh_mod.set_mesh(None)


def test_q8_encode_decode_accuracy():
    from paddle_tpu.distributed.fleet.dist_step import (_q8_decode,
                                                        _q8_encode)
    rng = np.random.RandomState(0)
    # adam-moment-like values: huge dynamic range, mixed sign
    x = jnp.asarray(rng.randn(64, 128) ** 3 * 1e-3, jnp.float32)
    q, s = _q8_encode(x)
    assert q.dtype == jnp.int8 and s.shape == (64,)
    y = _q8_decode(q, s)
    # sqrt-space linear quant: worst-case per-row relative error ~2/127
    # on the largest entries
    err = np.abs(np.asarray(y) - np.asarray(x)).max(axis=1)
    ref = np.abs(np.asarray(x)).max(axis=1)
    assert float((err / np.maximum(ref, 1e-12)).max()) < 0.05
