"""Sharding offload + low-precision optimizer moments (r5, VERDICT r4
next-round item 1 / weak #5).

Reference parity: distributed_strategy.proto:27 ``offload`` consumed by
fleet/meta_optimizers/sharding_optimizer.py:33; moment_dtype is the
greenfield in-HBM alternative (bf16 / int8 slots).
"""
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed.fleet.dist_step import DistributedTrainStep


def _train(moment_dtype, steps=8, stage=3, offload=False):
    mesh_mod.set_mesh(None)
    mesh = mesh_mod.init_mesh({"fsdp": 4, "dp": 2})
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(32, 64), nn.ReLU(), nn.Linear(64, 8))
    opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                 parameters=model.parameters())
    s = fleet.DistributedStrategy()
    s.sharding = True
    s.sharding_configs = {"stage": stage, "moment_dtype": moment_dtype,
                          "offload": offload}

    def loss_fn(x, y):
        return F.cross_entropy(model(x), y)

    step = DistributedTrainStep(model, loss_fn, opt, s, mesh=mesh)
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.rand(16, 32).astype("float32"))
    y = paddle.to_tensor(rng.randint(0, 8, (16,)))
    losses = [float(step(x, y)) for _ in range(steps)]
    st = opt.opt_state()
    mesh_mod.set_mesh(None)
    return losses, st, opt


def test_bf16_moments_train_and_storage():
    l32, _, _ = _train("float32")
    l16, st, _ = _train("bfloat16")
    assert st[0]["m"].dtype == jnp.bfloat16
    assert st[0]["v"].dtype == jnp.bfloat16
    # scalar machinery stays f32
    assert st[0]["beta1_pow"].dtype == jnp.float32
    assert l16[-1] < l16[0]
    # trajectory tracks f32 within low-precision tolerance
    np.testing.assert_allclose(l16, l32, rtol=5e-2)


def test_int8_moments_train_and_storage():
    l32, _, _ = _train("float32")
    l8, st, _ = _train("int8")
    assert st[0]["m"].dtype == jnp.int8
    assert st[0]["v"].dtype == jnp.int8
    # per-row scales ride alongside, shaped like the slot minus last dim
    assert st[0]["m@scale"].dtype == jnp.float32
    assert st[0]["m@scale"].shape == st[0]["m"].shape[:-1]
    assert st[0]["beta1_pow"].dtype == jnp.float32
    assert l8[-1] < l8[0]
    np.testing.assert_allclose(l8, l32, rtol=5e-2)


def test_int8_moments_checkpoint_roundtrip():
    from paddle_tpu.distributed.fleet.dist_step import _q8_decode
    _, st, opt = _train("int8", steps=3)
    sd = opt.state_dict()
    # restore DECODES the int8 codes + "@scale" leaves back to plain
    # f32 slots: eager optimizer math and differently-configured steps
    # must never see raw codes; an int8-configured step re-encodes on
    # its next call
    paddle.seed(0)
    model2 = nn.Sequential(nn.Linear(32, 64), nn.ReLU(), nn.Linear(64, 8))
    opt2 = paddle.optimizer.AdamW(learning_rate=1e-2,
                                  parameters=model2.parameters())
    opt2.set_state_dict(sd)
    st2 = opt2.opt_state()
    assert st2[0]["m"].dtype == jnp.float32
    assert "m@scale" not in st2[0]
    np.testing.assert_allclose(
        np.asarray(st2[0]["m"]),
        np.asarray(_q8_decode(st[0]["m"], st[0]["m@scale"])), rtol=1e-6)
    # and the eager step consumes the restored state without blowing up
    x = paddle.to_tensor(np.random.RandomState(0).rand(4, 32)
                         .astype("float32"))
    y = paddle.to_tensor(np.random.RandomState(0).randint(0, 8, (4,)))
    loss = F.cross_entropy(model2(x), y)
    loss.backward()
    opt2.step()
    opt2.clear_grad()


def test_offload_raises_loudly_on_cpu():
    # the CPU backend cannot compile host-resident state into programs;
    # silent fallback is exactly the inert-knob sin VERDICT r4 flagged
    with pytest.raises(NotImplementedError, match="offload"):
        _train("float32", offload=True)


def test_offload_ignored_without_sharding():
    # offload lives in sharding_configs: without strategy.sharding the
    # config is inert by reference semantics and must not raise
    mesh_mod.set_mesh(None)
    mesh = mesh_mod.init_mesh({"dp": -1})
    paddle.seed(0)
    model = nn.Linear(8, 4)
    opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                 parameters=model.parameters())
    s = fleet.DistributedStrategy()
    s.sharding_configs = {"offload": True}
    step = DistributedTrainStep(
        model, lambda x, y: F.cross_entropy(model(x), y), opt, s,
        mesh=mesh)
    assert step._offload is False
    mesh_mod.set_mesh(None)


def test_q8_second_moment_wide_dynamic_range_no_blowup():
    """ADVICE r5 hazard: v = g^2 survives nearest-rounding only over a
    ~254:1 per-row range of |g| while m = g survives over ~64516:1 — a
    small-but-live coordinate decoded v to exactly 0 with m intact and
    the Adam update blew up to m_hat/(0+eps) ~ 1e8x.  Denominator slots
    now round codes AWAY from zero, flooring decoded v at the per-row
    quantization threshold."""
    from paddle_tpu.distributed.fleet.dist_step import (_q8_decode,
                                                        _transform_slots)
    # one row whose gradient spans the hazard window: g ∈ {1, 1e-3}
    # puts v = g^2 below v's nearest-rounding floor while m stays alive
    g = np.zeros((1, 128), np.float32)
    g[0, :64] = 1.0
    g[0, 64:] = 1e-3
    m = jnp.asarray(g)                       # first moment ~ g
    v = jnp.asarray(g * g)                   # second moment ~ g^2
    st = {"m": m, "v": v,
          "beta1_pow": jnp.asarray(0.9, jnp.float32),
          "beta2_pow": jnp.asarray(0.999, jnp.float32)}
    enc = _transform_slots(st, (1, 128), jnp.int8, "encode")
    dec = _transform_slots(enc, (1, 128), jnp.int8, "decode")
    m_dec, v_dec = np.asarray(dec["m"]), np.asarray(dec["v"])
    # the hazard coordinate: m alive => v must be alive too
    alive = np.abs(m_dec) > 0
    assert alive.any()
    assert np.all(v_dec[alive] > 0), \
        "decoded v hit exact 0 on a coordinate whose m survived"
    # and the resulting Adam step magnitude is bounded by ~|m|/sqrt(v)
    # of the true values (no eps-division blow-up); the unfixed path
    # yields ~1e5 here
    step = np.abs(m_dec) / (np.sqrt(np.maximum(v_dec, 0.0)) + 1e-8)
    assert float(step.max()) < 10.0, float(step.max())
    # round-up biases v upward only: decoded v >= nearest-rounded decode
    v_nearest = np.asarray(_q8_decode(*_q8_encode_nearest(g * g)))
    assert np.all(v_dec >= v_nearest - 1e-12)


def _q8_encode_nearest(x):
    from paddle_tpu.distributed.fleet.dist_step import _q8_encode
    return _q8_encode(jnp.asarray(x), round_up=False)


def test_q8_encode_decode_accuracy():
    from paddle_tpu.distributed.fleet.dist_step import (_q8_decode,
                                                        _q8_encode)
    rng = np.random.RandomState(0)
    # adam-moment-like values: huge dynamic range, mixed sign
    x = jnp.asarray(rng.randn(64, 128) ** 3 * 1e-3, jnp.float32)
    q, s = _q8_encode(x)
    assert q.dtype == jnp.int8 and s.shape == (64,)
    y = _q8_decode(q, s)
    # sqrt-space linear quant: worst-case per-row relative error ~2/127
    # on the largest entries
    err = np.abs(np.asarray(y) - np.asarray(x)).max(axis=1)
    ref = np.abs(np.asarray(x)).max(axis=1)
    assert float((err / np.maximum(ref, 1e-12)).max()) < 0.05
