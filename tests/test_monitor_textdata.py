"""Stats registry (SURVEY §5.5 monitor.h), device memory stats, text
datasets, sysconfig tests."""
import os
import textwrap

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.framework import monitor
from paddle_tpu.text import datasets as tds


def test_stat_registry_counters():
    monitor.stat_reset()
    assert monitor.stat_get("steps") == 0
    monitor.stat_add("steps")
    monitor.stat_add("steps", 4)
    assert monitor.stat_get("steps") == 5
    monitor.stat_add("tokens", 1024)
    snap = monitor.get_all_stats()
    assert snap == {"steps": 5, "tokens": 1024}
    monitor.stat_reset("steps")
    assert monitor.stat_get("steps") == 0
    assert monitor.stat_get("tokens") == 1024
    monitor.stat_reset()


def test_device_memory_stats_shape():
    # CPU backend may not report; the API contract is dict-of-ints
    s = monitor.device_memory_stats()
    assert isinstance(s, dict)
    assert all(isinstance(v, (int, float)) for v in s.values())
    assert monitor.memory_allocated() >= 0
    assert monitor.max_memory_allocated() >= 0
    assert paddle.device.cuda.memory_allocated() >= 0


def test_fake_text_dataset_and_loader():
    ds = tds.FakeTextDataset(num_samples=16, seq_len=8, vocab_size=100)
    x, y = ds[0]
    assert x.shape == (8,) and y.shape == ()
    from paddle_tpu.io import DataLoader
    dl = DataLoader(ds, batch_size=4)
    xb, yb = next(iter(dl))
    assert list(xb.shape) == [4, 8]


def test_uci_housing_local_file(tmp_path):
    rng = np.random.RandomState(0)
    rows = np.hstack([rng.rand(50, 13), rng.rand(50, 1) * 50])
    f = tmp_path / "housing.data"
    np.savetxt(f, rows)
    tr = tds.UCIHousing(str(f), mode="train")
    te = tds.UCIHousing(str(f), mode="test")
    assert len(tr) == 40 and len(te) == 10
    x, y = tr[0]
    assert x.shape == (13,) and y.shape == (1,)
    # features normalized around train mean
    allx = np.stack([tr[i][0] for i in range(len(tr))])
    assert abs(allx.mean()) < 0.5


def test_uci_housing_missing_file_clear_error():
    with pytest.raises(FileNotFoundError, match="no network access"):
        tds.UCIHousing("/nonexistent/housing.data")


def test_imdb_from_directory(tmp_path):
    for mode in ("train", "test"):
        for sub, texts in (("pos", ["great movie", "loved it"]),
                           ("neg", ["terrible film", "awful plot"])):
            d = tmp_path / mode / sub
            d.mkdir(parents=True)
            for i, t in enumerate(texts):
                (d / f"{i}.txt").write_text(t)
    ds = tds.Imdb(str(tmp_path), mode="train", cutoff=1)
    assert len(ds) == 4
    doc, label = ds[0]
    assert doc.dtype == np.int64 and label in (0, 1)
    assert sorted(set(ds.labels.tolist())) == [0, 1]
    # vocab is shared into test split like the reference's word_dict
    ds2 = tds.Imdb(str(tmp_path), mode="test", vocab=ds.word_idx)
    assert ds2.word_idx is ds.word_idx


def test_conll05_parsing(tmp_path):
    f = tmp_path / "srl.tsv"
    f.write_text(textwrap.dedent("""\
        The\t-\tB-A0
        cat\t-\tI-A0
        sat\tsat\tB-V

        Dogs\t-\tB-A0
        bark\tbark\tB-V
    """))
    ds = tds.Conll05st(str(f))
    assert len(ds) == 2
    w, p, l = ds[0]
    assert len(w) == 3 and p.tolist() == [0, 0, 1]


def test_imdb_cutoff_is_frequency_threshold(tmp_path):
    d = tmp_path / "train" / "pos"
    d.mkdir(parents=True)
    (d / "0.txt").write_text("common common common rare")
    n = tmp_path / "train" / "neg"
    n.mkdir(parents=True)
    (n / "0.txt").write_text("common")
    (tmp_path / "test" / "pos").mkdir(parents=True)
    (tmp_path / "test" / "neg").mkdir(parents=True)
    ds = tds.Imdb(str(tmp_path), mode="train", cutoff=2)
    assert "common" in ds.word_idx and "rare" not in ds.word_idx


def test_memory_stats_accepts_paddle_device_ids():
    # int and "backend:idx" forms must resolve, not silently report 0
    assert monitor.memory_allocated(0) >= 0
    assert monitor.memory_allocated("cpu:0") >= 0


def test_build_vocab_frequency_order():
    v = tds.build_vocab(["a b a", "a c"])
    assert v["<pad>"] == 0 and v["<unk>"] == 1
    assert v["a"] == 2  # most frequent first


def test_sysconfig_paths():
    import paddle_tpu.sysconfig as sc
    assert os.path.isdir(sc.get_include())
    assert os.path.isdir(sc.get_lib())
