"""Elastic training (ISSUE 9): membership transitions + deterministic
reshard.

The determinism bar is the one PR 3 (PS failover) and PR 4 (TrainGuard
rewind) set: ``np.array_equal``, not allclose.  The acceptance test
SIGKILLs a worker every K steps in a subprocess run driven by the
launcher's ``--elastic`` mode and proves the final weights/opt-state
equal the fault-free run bit-for-bit.
"""
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from paddle_tpu.distributed.checkpoint import CheckpointManager  # noqa: E402
from paddle_tpu.distributed.fleet import chaos  # noqa: E402
from paddle_tpu.distributed.fleet.dist_step import (  # noqa: E402
    flatten_zero_state, unflatten_zero_state, zero_reshard, zero_shard,
    zero_shard_ranges, zero_unshard)
from paddle_tpu.distributed.fleet.elastic import (  # noqa: E402
    ElasticClient, ElasticCoordinator, ElasticTrainer, _FlatAdam)
from paddle_tpu.framework import monitor as _monitor  # noqa: E402
from paddle_tpu.io.dataloader import DataLoader  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import elastic_worker  # noqa: E402


# ---------------------------------------------------------------------------
# pure reshard math (dist_step.zero_*)
# ---------------------------------------------------------------------------

def test_zero_shard_ranges_cover_and_partition():
    for total, world in [(10, 1), (10, 2), (10, 3), (7, 4), (3, 5),
                         (0, 2), (64, 8)]:
        ranges = zero_shard_ranges(total, world)
        assert len(ranges) == world
        # contiguous, ordered, exactly covering [0, total)
        pos = 0
        for lo, hi in ranges:
            assert lo == pos and hi >= lo
            pos = hi
        assert pos == total
        # remainder spread over the leading ranks (UtilBase rule)
        sizes = [hi - lo for lo, hi in ranges]
        assert max(sizes) - min(sizes) <= 1
        assert sorted(sizes, reverse=True) == sizes


def test_zero_reshard_round_trip_bit_exact():
    rng = np.random.default_rng(3)
    tree = {"w": rng.standard_normal((5, 3)).astype(np.float32),
            "b": rng.standard_normal(7).astype(np.float32),
            "s": np.float32(rng.standard_normal()).reshape(())}
    flat, meta = flatten_zero_state(tree)
    # N=3 -> M=2 -> N=3 round trip is bit-exact, with 2 and 3 both not
    # dividing the 23-element state
    assert flat.size == 23
    shards3 = [zero_shard(flat, r, 3) for r in range(3)]
    shards2 = zero_reshard(shards3, 2)
    back3 = zero_reshard(shards2, 3)
    for a, b in zip(shards3, back3):
        assert np.array_equal(a, b)
    # the resharded M-world shards ARE what a fresh M-world run shards
    for r in range(2):
        assert np.array_equal(shards2[r], zero_shard(flat, r, 2))
    assert np.array_equal(zero_unshard(shards2), flat)
    # flatten/unflatten round trip restores every leaf bit-exactly
    back = unflatten_zero_state(flat, meta)
    for k in tree:
        assert np.array_equal(back[k], tree[k])
        assert back[k].shape == tree[k].shape


def test_flatten_zero_state_rejects_mixed_dtypes():
    with pytest.raises(ValueError, match="one dtype"):
        flatten_zero_state({"a": np.zeros(2, np.float32),
                            "b": np.zeros(2, np.float64)})


def test_flat_adam_shard_update_equals_full_update():
    """The ZeRO invariant the elastic data plane rests on: the update
    is elementwise, so concatenated shard updates == the full-vector
    update bit-for-bit, for any world size."""
    rng = np.random.default_rng(5)
    n, steps = 37, 4
    p0 = rng.standard_normal(n).astype(np.float32)
    grads = [rng.standard_normal(n).astype(np.float32)
             for _ in range(steps)]

    def run(world):
        shards, opts = [], []
        for r in range(world):
            lo, hi = zero_shard_ranges(n, world)[r]
            o = _FlatAdam(0.05)
            o.load({"m": np.zeros(hi - lo, np.float32),
                    "v": np.zeros(hi - lo, np.float32)}, t=0)
            opts.append((o, lo, hi))
            shards.append(p0[lo:hi].copy())
        for g in grads:
            for r, (o, lo, hi) in enumerate(opts):
                shards[r] = o.update(shards[r], g[lo:hi])
        return (np.concatenate(shards),
                np.concatenate([o.m for o, _, _ in opts]),
                np.concatenate([o.v for o, _, _ in opts]))

    ref = run(1)
    for world in (2, 3, 5):
        got = run(world)
        for a, b in zip(ref, got):
            assert np.array_equal(a, b)


# ---------------------------------------------------------------------------
# in-process multi-worker harness
# ---------------------------------------------------------------------------

def _make_trainer(ckpt, ep, world, grad_fn=None, **kw):
    loader = DataLoader(elastic_worker.RegressionSet(), batch_size=16,
                        shuffle=True, seed=11, drop_last=True)
    defaults = dict(ckpt_dir=ckpt, optimizer="adam", lr=0.05,
                    micro_batches=4, ckpt_every=2, coordinator=ep,
                    expected_world=world, client_timeout=60.0)
    defaults.update(kw)
    return ElasticTrainer(
        {"w": np.zeros(elastic_worker.DIM, np.float32),
         "b": np.zeros((), np.float32)},
        grad_fn or elastic_worker.grad_fn, loader, **defaults)


def _run_world(ckpt, world, steps, grad_fn=None, coord=None, **kw):
    own = coord is None
    if own:
        coord = ElasticCoordinator(expected_world=world).start()
    ep = f"127.0.0.1:{coord.port}"
    trainers = [_make_trainer(ckpt, ep, world, grad_fn=grad_fn, **kw)
                for _ in range(world)]
    results = [None] * world
    errs = [None] * world

    def go(i):
        try:
            results[i] = trainers[i].run(steps)
        except BaseException as e:  # surfaced below
            errs[i] = e

    ts = [threading.Thread(target=go, args=(i,), daemon=True)
          for i in range(world)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=90)
    assert all(not t.is_alive() for t in ts), "elastic run hung"
    for e in errs:
        if e is not None:
            raise e
    if own:
        coord.stop()
    return results, trainers, coord


def test_world_invariance_and_observability(tmp_path):
    """An N-worker run and an M-worker run produce bit-identical
    trajectories (the property every elastic transition relies on),
    and the run emits the elastic metrics."""
    before = _monitor.stat_get("elastic_transitions")
    (r1,), _, _ = _run_world(str(tmp_path / "ck1"), 1, 10)
    r2, trainers, _ = _run_world(str(tmp_path / "ck2"), 2, 10)
    for r in r2:
        assert np.array_equal(r["w"], r1["w"])
        assert np.array_equal(r["b"], r1["b"])
    for tr in trainers:
        assert tr.transitions and tr.transitions[0]["world"] == 2
        assert tr.role_maker.worker_num() == 2
        assert tr.role_maker.generation() >= 1
    assert _monitor.stat_get("elastic_transitions") > before
    h = _monitor.get_histogram("reshard_ms")
    assert h is not None and h.snapshot()["count"] > 0


def test_checkpoint_content_is_world_size_invariant(tmp_path):
    """The on-disk pinned checkpoint at step S is bit-identical whether
    an N=2 or an M=3 world wrote it — THE property that makes reshard a
    pure function of (global state, new world size)."""
    _run_world(str(tmp_path / "ck2"), 2, 6)
    _run_world(str(tmp_path / "ck3"), 3, 6)
    m2 = CheckpointManager(str(tmp_path / "ck2"))
    m3 = CheckpointManager(str(tmp_path / "ck3"))
    assert 6 in m2.all_steps() and 6 in m3.all_steps()
    s2, s3 = m2.restore(6), m3.restore(6)
    assert np.array_equal(s2["model"]["flat"], s3["model"]["flat"])
    for k in ("m", "v"):
        assert np.array_equal(s2["opt"][k], s3["opt"][k])
    assert s2["meta"] == s3["meta"]


def test_reshard_n_to_m_matches_fresh_world_restore(tmp_path):
    """A world-3 run resumed from a world-2 run's pinned step loads
    exactly the shards a fresh 3-world run would load, and continues to
    the same final state an uninterrupted 3-world run reaches."""
    ck = str(tmp_path / "ck")
    _run_world(ck, 2, 6)           # ckpts pinned at 2, 4, 6
    st = CheckpointManager(ck).restore(6)
    flat = np.asarray(st["model"]["flat"], np.float32)
    # the pure reshard: N=2 shards merged == the saved global vector,
    # and the fresh M=3 partition comes straight off it
    shards2 = [zero_shard(flat, r, 2) for r in range(2)]
    for r, s in enumerate(zero_reshard(shards2, 3)):
        assert np.array_equal(s, zero_shard(flat, r, 3))
    # resume at world 3 from the same pinned step (a restarted
    # coordinator names it), train to 10
    coord = ElasticCoordinator(expected_world=3, ckpt_step=6).start()
    r3, trainers, _ = _run_world(ck, 3, 10, coord=coord)
    coord.stop()
    for tr in trainers:
        assert tr.transitions[0]["resume_step"] == 6
    # uninterrupted world-3 (== any world) run to 10
    (ref,), _, _ = _run_world(str(tmp_path / "ref"), 1, 10)
    for r in r3:
        assert np.array_equal(r["w"], ref["w"])
        assert np.array_equal(r["b"], ref["b"])


def _slow_grad_fn(params, batch):
    time.sleep(0.02)
    return elastic_worker.grad_fn(params, batch)


def test_join_mid_run_matches_fresh_run(tmp_path):
    """Training at N=2 picks up worker 3 mid-run: everyone reforms from
    the pinned step, and the post-join trajectory (== the whole run, by
    world invariance) equals a fresh run's bit-for-bit."""
    ck = str(tmp_path / "ck")
    steps = 14
    coord = ElasticCoordinator(expected_world=2).start()
    ep = f"127.0.0.1:{coord.port}"
    results = {}
    errs = []

    def worker(name):
        try:
            tr = _make_trainer(ck, ep, 2, grad_fn=_slow_grad_fn)
            results[name] = (tr.run(steps), tr)
        except BaseException as e:
            errs.append(e)

    ts = [threading.Thread(target=worker, args=(f"w{i}",), daemon=True)
          for i in range(2)]
    for t in ts:
        t.start()
    # wait until the 2-world run is demonstrably mid-flight, then join
    deadline = time.monotonic() + 30
    while coord.status()["last_step"] < 3:
        assert time.monotonic() < deadline, "run never reached step 3"
        time.sleep(0.005)
    tj = threading.Thread(target=worker, args=("joiner",), daemon=True)
    tj.start()
    for t in ts + [tj]:
        t.join(timeout=90)
    assert all(not t.is_alive() for t in ts + [tj]), "join run hung"
    for e in errs:
        raise e
    coord.stop()
    # the joiner was admitted into a live 3-world generation at a
    # pinned step, and the originals reformed to world 3 with it
    _, jt = results["joiner"]
    assert jt.transitions[0]["world"] == 3
    assert jt.transitions[0]["resume_step"] % 2 == 0
    assert any(t["world"] == 3 for _, tr in results.values()
               for t in tr.transitions)
    (ref,), _, _ = _run_world(str(tmp_path / "ref"), 1, steps)
    for r, _ in results.values():
        assert np.array_equal(r["w"], ref["w"])
        assert np.array_equal(r["b"], ref["b"])


def test_graceful_leave_and_lease_eviction_reform(tmp_path):
    """A registered-but-silent worker: lease expiry evicts it exactly
    like a crash (the survivors reshard and finish correctly); a
    graceful ``leave`` from a registered client likewise reforms."""
    ck = str(tmp_path / "ck")
    coord = ElasticCoordinator(expected_world=2, lease_s=0.4).start()
    ep = f"127.0.0.1:{coord.port}"
    wedged = ElasticClient(ep, timeout=30.0)
    out = {}
    errs = []

    def survivor():
        try:
            tr = _make_trainer(ck, ep, 2)
            out["r"] = (tr.run(8), tr)
        except BaseException as e:
            errs.append(e)

    t = threading.Thread(target=survivor, daemon=True)
    t.start()
    # the wedged member registers (completing the expected world of 2)
    # and then never exchanges — the lease must evict it
    wedged.register(2)
    t.join(timeout=60)
    assert not t.is_alive(), "survivor hung behind the wedged worker"
    for e in errs:
        raise e
    assert any(k == "lease" for k, _, _ in coord.events)
    r, tr = out["r"]
    assert any(tt["world"] == 1 for tt in tr.transitions)
    (ref,), _, _ = _run_world(str(tmp_path / "ref"), 1, 8)
    assert np.array_equal(r["w"], ref["w"])
    wedged.close()
    coord.stop()


# ---------------------------------------------------------------------------
# chaos plan + observability wiring
# ---------------------------------------------------------------------------

def test_kill_worker_chaos_plan():
    plan = chaos.named_plan("kill_worker@every=3")
    f = plan.faults[0]
    assert (f.kind, f.op, f.first, f.every, f.times) == \
        ("kill", "worker", 3, 3, 0)
    # fires on calls 3, 6, 9, ... of the incarnation
    fired = [bool(plan.match_elastic()) for _ in range(9)]
    assert fired == [False, False, True, False, False, True,
                     False, False, True]
    # env-spec spelling parses to the same schedule
    p2 = chaos.plan_from_spec("plan=kill_worker@every=5")
    assert p2.faults[0].every == 5
    p3 = chaos.plan_from_spec("kill:worker:first=2:every=4")
    assert (p3.faults[0].kind, p3.faults[0].first) == ("kill", 2)
    # no active plan: the hook is a no-op (it must not kill the test!)
    chaos.uninstall()
    chaos.maybe_kill_worker()


def test_elastic_observability_wiring():
    from paddle_tpu.observability.flight_recorder import _PROGRESS_KINDS
    assert {"elastic.join", "elastic.reshard",
            "elastic.resume"} <= set(_PROGRESS_KINDS)
    assert "elastic.leave" not in _PROGRESS_KINDS
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    import postmortem
    assert postmortem._is_bad({"kind": "elastic.leave"})
    # elastic.py is part of the default GraftLint module set and must
    # lint clean (the shipped baseline stays empty)
    from paddle_tpu.analysis import DEFAULT_LINT_PATHS, lint_file
    assert "paddle_tpu/distributed/fleet/elastic.py" in DEFAULT_LINT_PATHS
    findings = lint_file(
        os.path.join(_REPO, "paddle_tpu/distributed/fleet/elastic.py"))
    assert findings == [], [str(f) for f in findings]


# ---------------------------------------------------------------------------
# acceptance: launcher --elastic + SIGKILL every K steps (subprocess)
# ---------------------------------------------------------------------------

def _launch_elastic(tag, tmp, world, steps, ckpt_every, chaos_rank=None,
                    kill_every=5):
    coord = ElasticCoordinator(expected_world=world).start()
    ck = os.path.join(tmp, f"ck_{tag}")
    res = os.path.join(tmp, f"res_{tag}")
    cfg = {"batch_size": 16, "loader_seed": 11, "ckpt_dir": ck,
           "micro_batches": 4, "ckpt_every": ckpt_every,
           "coordinator": f"127.0.0.1:{coord.port}",
           "expected_world": world, "total_steps": steps,
           "result": res, "client_timeout": 60.0}
    cfgp = os.path.join(tmp, f"cfg_{tag}.json")
    with open(cfgp, "w") as f:
        json.dump(cfg, f)
    ips = ",".join(["127.0.0.1"] * world)
    procs = []
    for r in range(world):
        env = dict(os.environ)
        env["PYTHONPATH"] = _REPO
        env.pop("PADDLE_CHAOS", None)
        env.pop("PADDLE_COORDINATOR", None)
        if chaos_rank == r:
            env["PADDLE_CHAOS"] = f"plan=kill_worker@every={kill_every}"
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--elastic", "--max_restarts", "8",
             "--restart_backoff", "0.05", "--ips", ips,
             "--host_rank", str(r),
             "--log_dir", os.path.join(tmp, f"log_{tag}"),
             os.path.join(_REPO, "tests", "elastic_worker.py"), cfgp],
            env=env, cwd=tmp))
    rcs = [p.wait(timeout=120) for p in procs]
    coord.stop()
    outs = [np.load(res + f".rank{r}.npz") for r in range(world)]
    return rcs, outs, coord.events


def test_chaos_kill_every_k_steps_matches_fault_free_run(tmp_path):
    """THE acceptance criterion: a 2-worker run whose rank-1 worker is
    SIGKILLed every 5 executed steps (launcher --elastic restarts it,
    survivors reshard from the pinned step each loss) finishes with
    final weights AND optimizer step count np.array_equal to the
    fault-free run."""
    tmp = str(tmp_path)
    steps = 12
    rcs_ref, outs_ref, _ = _launch_elastic("ref", tmp, 2, steps, 2)
    assert rcs_ref == [0, 0]
    rcs, outs, events = _launch_elastic("chaos", tmp, 2, steps, 2,
                                        chaos_rank=1, kill_every=5)
    assert rcs == [0, 0], "elastic launcher did not recover the worker"
    # at least one SIGKILL actually landed and reformed the membership
    assert any(k == "leave" for k, _, _ in events)
    joins = [u for k, u, _ in events if k == "join"]
    assert len(joins) >= 3, "killed worker never rejoined"
    for o in outs:
        assert np.array_equal(o["w"], outs_ref[0]["w"])
        assert np.array_equal(o["b"], outs_ref[0]["b"])
        assert int(o["opt_t"]) == steps
        trans = json.loads(str(o["transitions"]))
        assert trans[0]["world"] in (1, 2)
    # the faulted run actually went through a reduced-world generation
    all_trans = [t for o in outs
                 for t in json.loads(str(o["transitions"]))]
    assert any(t["world"] == 1 for t in all_trans), \
        "no worker ever trained in a shrunken world"


# ---------------------------------------------------------------------------
# t-indexed lr schedules (ISSUE 10 satellite; PR 9 follow-up (b))
# ---------------------------------------------------------------------------

def test_lr_schedule_kinds_and_purity():
    from paddle_tpu.distributed.fleet.dist_step import make_lr_schedule
    cos = make_lr_schedule("cosine", 0.1, warmup_steps=4,
                           total_steps=20, min_lr=0.01)
    # warmup ramp, then cosine down to min_lr, clipped past the end
    assert cos(1) == np.float32(0.1 * 1 / 4)
    assert cos(4) == np.float32(0.1)
    assert cos(20) == np.float32(0.01)
    assert cos(50) == np.float32(0.01)
    vals = [cos(t) for t in range(4, 21)]
    assert all(a >= b for a, b in zip(vals, vals[1:]))  # monotone
    # pure: same t, same f32 bits, every call
    assert all(cos(t) == cos(t) and cos(t).dtype == np.float32
               for t in range(1, 25))
    step = make_lr_schedule("step", 1.0, step_size=3, gamma=0.5)
    assert [float(step(t)) for t in range(1, 8)] == \
        [1.0, 1.0, 1.0, 0.5, 0.5, 0.5, 0.25]
    lin = make_lr_schedule("linear", 1.0, total_steps=10, min_lr=0.0)
    assert lin(10) == np.float32(0.0)
    const = make_lr_schedule("constant", 0.3)
    assert const(999) == np.float32(0.3)
    with pytest.raises(ValueError, match="total_steps"):
        make_lr_schedule("cosine", 0.1)
    with pytest.raises(ValueError, match="kind"):
        make_lr_schedule("warble", 0.1)


def test_lr_schedule_bit_exact_across_reshard_mid_schedule(tmp_path):
    """THE satellite acceptance: a cosine schedule rides the flat
    elastic optimizers as a pure function of the global step, so a
    world-2 run resumed at world-3 MID-SCHEDULE stays bit-identical to
    an uninterrupted run — lr(t) never depends on who computes it."""
    sched = {"kind": "cosine", "base_lr": 0.08, "warmup_steps": 2,
             "total_steps": 10, "min_lr": 0.005}
    ck = str(tmp_path / "ck")
    _run_world(ck, 2, 6, lr_schedule=sched)      # pinned at 2, 4, 6
    coord = ElasticCoordinator(expected_world=3, ckpt_step=6).start()
    r3, trainers, _ = _run_world(ck, 3, 10, coord=coord,
                                 lr_schedule=sched)
    coord.stop()
    assert trainers[0].transitions[0]["resume_step"] == 6
    (ref,), reft, _ = _run_world(str(tmp_path / "ref"), 1, 10,
                                 lr_schedule=sched)
    for r in r3:
        assert np.array_equal(r["w"], ref["w"])
        assert np.array_equal(r["b"], ref["b"])
    # and a schedule-less run genuinely differs (the schedule was live)
    (flat,), _, _ = _run_world(str(tmp_path / "flat"), 1, 10)
    assert not np.array_equal(flat["w"], ref["w"])
