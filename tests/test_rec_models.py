"""Wide&Deep + DeepFM rec models (BASELINE config 4).

Tests mirror the reference's rec testing style (PaddleRec configs over
small synthetic CTR data): shapes, FM-term math vs a NumPy pairwise
reference, convergence on a learnable synthetic click function, and a
jit-traced serving path.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.rec import DeepFM, WideDeep

FIELDS = [10, 20, 30]


def _batch(rng, b=32):
    ids = np.stack([rng.randint(0, d, b) for d in FIELDS], axis=1)
    return ids.astype(np.int64)


def test_widedeep_forward_shapes():
    paddle.seed(0)
    m = WideDeep(FIELDS, dense_dim=4, embed_dim=8)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(_batch(rng))
    dense = paddle.to_tensor(rng.rand(32, 4).astype("float32"))
    out = m(ids, dense)
    assert out.shape == [32, 1]
    p = m.predict_proba(ids, dense).numpy()
    assert (p >= 0).all() and (p <= 1).all()


def test_deepfm_fm_term_matches_pairwise_reference():
    paddle.seed(1)
    m = DeepFM(FIELDS, embed_dim=4)
    rng = np.random.RandomState(1)
    ids = paddle.to_tensor(_batch(rng, b=8))
    emb = m.embedding(ids)
    fm = m.fm(emb).numpy()
    v = emb.numpy()  # [B, F, D]
    ref = np.zeros((8, 1), np.float32)
    for i in range(len(FIELDS)):
        for j in range(i + 1, len(FIELDS)):
            ref[:, 0] += (v[:, i] * v[:, j]).sum(-1)
    np.testing.assert_allclose(fm, ref, rtol=1e-4, atol=1e-5)


def test_field_offsets_address_disjoint_rows():
    paddle.seed(2)
    m = DeepFM(FIELDS, embed_dim=4)
    # id 0 in field 0 vs id 0 in field 1 must hit DIFFERENT table rows
    a = m.embedding(paddle.to_tensor(np.array([[0, 0, 0]]))).numpy()
    assert not np.allclose(a[0, 0], a[0, 1])


@pytest.mark.parametrize("cls,kw", [
    (WideDeep, dict(dense_dim=0, embed_dim=8, hidden_units=(32,))),
    (DeepFM, dict(embed_dim=8, hidden_units=(32,))),
])
def test_ctr_training_converges(cls, kw):
    paddle.seed(3)
    model = cls(FIELDS, **kw)
    opt = paddle.optimizer.Adam(learning_rate=0.01,
                                parameters=model.parameters())
    rng = np.random.RandomState(3)
    ids_np = _batch(rng, b=256)
    # learnable click rule: click iff field0-id parity XOR field1-id>10
    y_np = ((ids_np[:, 0] % 2) ^ (ids_np[:, 1] > 10)).astype("float32")
    ids = paddle.to_tensor(ids_np)
    y = paddle.to_tensor(y_np.reshape(-1, 1))
    l0 = None
    for _ in range(60):
        logits = model(ids)
        loss = F.binary_cross_entropy_with_logits(logits, y)
        if l0 is None:
            l0 = float(loss)
        loss.backward()
        opt.step()
        opt.clear_grad()
    assert float(loss) < l0 * 0.5
    # AUC sanity: predictions separate the classes
    p = model.predict_proba(ids).numpy().ravel()
    auc = (p[y_np == 1].mean() > p[y_np == 0].mean())
    assert auc


def test_out_of_range_id_raises():
    paddle.seed(5)
    m = DeepFM(FIELDS, embed_dim=4)
    bad = np.array([[15, 0, 0]])  # 15 >= field0 dim 10
    with pytest.raises(ValueError, match="out of range for field 0"):
        m(paddle.to_tensor(bad))


def test_dense_feats_contract():
    paddle.seed(6)
    rng = np.random.RandomState(6)
    ids = paddle.to_tensor(_batch(rng, b=2))
    dense = paddle.to_tensor(rng.rand(2, 4).astype("float32"))
    with pytest.raises(ValueError, match="dense_dim=4"):
        WideDeep(FIELDS, dense_dim=4)(ids)          # missing dense
    with pytest.raises(ValueError, match="dense_dim=0"):
        WideDeep(FIELDS)(ids, dense)                # unexpected dense


def test_deepfm_jit_serving_path():
    import jax
    paddle.seed(4)
    model = DeepFM(FIELDS, embed_dim=4, hidden_units=(16,))
    model.eval()
    rng = np.random.RandomState(4)
    ids_np = _batch(rng, b=4)
    eager = model(paddle.to_tensor(ids_np)).numpy()
    st = dict(model.named_parameters())
    names = sorted(st)

    def fn(pvals, x):
        old = {n: st[n]._value for n in names}
        try:
            for n in names:
                st[n]._value = pvals[n]
            with paddle.no_grad():
                return model(paddle.to_tensor(x))._value
        finally:
            for n in names:
                st[n]._value = old[n]

    out = jax.jit(fn)({n: st[n]._value for n in names}, ids_np)
    np.testing.assert_allclose(eager, np.asarray(out), atol=1e-5)
