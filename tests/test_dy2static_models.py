"""Whole-model dual-mode (eager vs to_static) parity suite.

Reference analog: python/paddle/fluid/tests/unittests/dygraph_to_static/
test_bert.py, test_seq2seq.py, test_resnet.py — the reference trains each
zoo model a few steps in dygraph and under @to_static from identical
seeds and asserts the loss trajectories match.  Here the same models run
eagerly and with the forward staged through ``paddle.jit.to_static(layer,
full_graph=True)``; XLA fusion may reassociate float math, so equality is
asserted to 1e-4 relative (conftest pins highest matmul precision).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F

STEPS = 4


def _train(model_fn, batch_fn, loss_fn, static, lr=1e-3, steps=STEPS):
    paddle.seed(1234)
    model = model_fn()
    runner = paddle.jit.to_static(model, full_graph=True) if static \
        else model
    opt = paddle.optimizer.Adam(learning_rate=lr,
                                parameters=model.parameters())
    rng = np.random.RandomState(7)
    args, target = batch_fn(rng)     # one fixed batch: loss must fall
    losses = []
    for _ in range(steps):
        loss = loss_fn(runner, args, target)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    return losses


def _assert_parity(model_fn, batch_fn, loss_fn, lr=1e-3):
    eager = _train(model_fn, batch_fn, loss_fn, static=False, lr=lr)
    static = _train(model_fn, batch_fn, loss_fn, static=True, lr=lr)
    assert eager[-1] < eager[0], f"eager loss did not fall: {eager}"
    assert static[-1] < static[0], f"static loss did not fall: {static}"
    # step 1 runs the identical math: tight equality proves the staged
    # forward/backward IS the eager computation
    np.testing.assert_allclose(static[0], eager[0], rtol=1e-4)
    # later steps: XLA fusion reassociates float math and a fixed batch
    # overfits toward zero, amplifying ulp-level drift — the reference's
    # dygraph_to_static model tests use relaxed equality for the same
    # reason.  Scale the tolerance by the initial loss.
    np.testing.assert_allclose(static, eager, rtol=0.15,
                               atol=5e-3 * eager[0])


def test_bert_dual_mode_parity():
    from paddle_tpu.text.models.bert import (BertForPretraining,
                                             BertPretrainingCriterion,
                                             bert_tiny)
    # dropout off: eager and staged runs draw different RNG streams, so
    # masks (not math) would differ — the reference's test_bert.py uses
    # identical mask tensors for the same reason
    cfg = bert_tiny(hidden_dropout_prob=0.0,
                    attention_probs_dropout_prob=0.0)
    crit = BertPretrainingCriterion(cfg.vocab_size)
    B, S, M = 4, 32, 5

    def batch(rng):
        ids = paddle.to_tensor(
            rng.randint(0, cfg.vocab_size, (B, S)).astype("int32"))
        pos = paddle.to_tensor(np.sort(
            rng.randint(0, S, (B, M)), axis=1).astype("int32"))
        mlm = paddle.to_tensor(
            rng.randint(0, cfg.vocab_size, (B, M)).astype("int64"))
        nsp = paddle.to_tensor(rng.randint(0, 2, (B,)).astype("int64"))
        return (ids, pos), (mlm, nsp)

    def loss_fn(runner, args, target):
        ids, pos = args
        mlm_logits, nsp_logits = runner(ids, masked_positions=pos)
        return crit(mlm_logits, nsp_logits, *target)

    _assert_parity(lambda: BertForPretraining(cfg), batch, loss_fn)


def test_resnet_dual_mode_parity():
    from paddle_tpu.vision.models import resnet18

    def batch(rng):
        img = paddle.to_tensor(
            rng.standard_normal((4, 3, 32, 32)).astype("float32"))
        lbl = paddle.to_tensor(rng.randint(0, 10, (4,)).astype("int64"))
        return (img,), lbl

    def loss_fn(runner, args, target):
        return F.cross_entropy(runner(*args), target).mean()

    _assert_parity(lambda: resnet18(num_classes=10), batch, loss_fn)


def test_seq2seq_transformer_dual_mode_parity():
    from paddle_tpu.text.models.transformer import (CrossEntropyCriterion,
                                                    TransformerModel,
                                                    transformer_tiny)
    cfg = transformer_tiny(src_vocab_size=24, trg_vocab_size=24,
                           dropout=0.0)
    crit = CrossEntropyCriterion(label_smooth_eps=0.0, pad_id=cfg.pad_id)
    B, S = 4, 10

    def batch(rng):
        src = rng.randint(4, 24, (B, S)).astype("int64")
        trg_in = np.concatenate(
            [np.full((B, 1), 2, np.int64), src[:, :-1]], axis=1)
        return ((paddle.to_tensor(src), paddle.to_tensor(trg_in)),
                paddle.to_tensor(src))

    def loss_fn(runner, args, target):
        logits = runner(*args)
        out = crit(logits, target)
        return out[0] if isinstance(out, (tuple, list)) else out

    _assert_parity(
        lambda: TransformerModel(cfg), batch, loss_fn, lr=3e-3)


class _BreakLoopNet(nn.Layer):
    """Break/continue-bearing model: adaptive scaling whose while-loop
    predicate is data-dependent and whose break fires on a step cap —
    the r4 mask-carry conversion path exercised INSIDE a trained model
    (VERDICT r4 next-round item 4: 'include a break/continue-bearing
    model').  The loop runs on DETACHED statistics: lax.while_loop is
    not reverse-differentiable, so — like real adaptive-scale tricks —
    the iteration count rides outside the gradient path."""

    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(8, 16)
        self.fc2 = nn.Linear(16, 4)

    def forward(self, x):
        h = F.relu(self.fc1(x))
        e = (h * h).sum().detach()
        n = paddle.zeros([1], "float32")
        while e.sum() > 1.0:
            e = e * 0.25
            n = n + 1.0
            if n.sum() >= 8.0:
                break
        scale = 0.5 ** n
        return self.fc2(h * scale * 4.0)


def test_break_loop_model_dual_mode_parity():
    def batch(rng):
        x = paddle.to_tensor(rng.standard_normal((4, 8)).astype("float32"))
        y = paddle.to_tensor(rng.randint(0, 4, (4,)).astype("int64"))
        return (x,), y

    def loss_fn(runner, args, target):
        return F.cross_entropy(runner(*args), target).mean()

    _assert_parity(_BreakLoopNet, batch, loss_fn, lr=1e-2)
