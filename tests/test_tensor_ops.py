"""Op parity tests: forward vs NumPy, grads vs finite difference
(modelled on the reference's per-op tests, e.g.
python/paddle/fluid/tests/unittests/test_matmul_v2_op.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from op_test import check_grad, check_op

rng = np.random.RandomState(0)


def _f32(*shape):
    return rng.randn(*shape).astype(np.float32)


class TestElementwise:
    def test_add(self):
        a, b = _f32(3, 4), _f32(3, 4)
        check_op(paddle.add, [a, b], ref=np.add)
        check_grad(paddle.add, [a, b])

    def test_broadcast_add(self):
        a, b = _f32(3, 4), _f32(4)
        check_op(paddle.add, [a, b], ref=np.add)
        check_grad(paddle.add, [a, b])

    def test_sub_mul_div(self):
        a, b = _f32(2, 5), _f32(2, 5) + 2.0
        check_op(paddle.subtract, [a, b], ref=np.subtract)
        check_op(paddle.multiply, [a, b], ref=np.multiply)
        check_op(paddle.divide, [a, b], ref=np.divide)
        check_grad(paddle.divide, [a, b])

    def test_pow_scalar(self):
        a = np.abs(_f32(3, 3)) + 0.5
        out = paddle.pow(paddle.to_tensor(a), 2.5)
        np.testing.assert_allclose(out.numpy(), a ** 2.5, rtol=1e-5)

    def test_maximum_minimum(self):
        a, b = _f32(4, 4), _f32(4, 4)
        check_op(paddle.maximum, [a, b], ref=np.maximum)
        check_op(paddle.minimum, [a, b], ref=np.minimum)

    def test_mod_floor_divide(self):
        a = np.array([7, -7, 5], np.int32)
        b = np.array([3, 3, 2], np.int32)
        check_op(paddle.mod, [a, b], ref=np.mod)
        check_op(paddle.floor_divide, [a, b], ref=np.floor_divide)


class TestUnary:
    @pytest.mark.parametrize("pfn,nfn", [
        (paddle.exp, np.exp), (paddle.tanh, np.tanh),
        (paddle.sin, np.sin), (paddle.cos, np.cos),
        (paddle.abs, np.abs), (paddle.floor, np.floor),
        (paddle.square, np.square), (paddle.sign, np.sign),
    ])
    def test_fwd(self, pfn, nfn):
        a = _f32(3, 4)
        check_op(pfn, [a], ref=nfn)

    def test_log_sqrt_grad(self):
        a = np.abs(_f32(3, 3)) + 0.5
        check_op(paddle.log, [a], ref=np.log)
        check_grad(paddle.log, [a])
        check_grad(paddle.sqrt, [a])

    def test_sigmoid(self):
        a = _f32(4, 4)
        check_op(paddle.sigmoid, [a], ref=lambda x: 1 / (1 + np.exp(-x)))
        check_grad(paddle.sigmoid, [a])


class TestReduce:
    def test_sum_axes(self):
        a = _f32(2, 3, 4)
        check_op(paddle.sum, [a], ref_out=a.sum())
        check_op(lambda x: paddle.sum(x, axis=1), [a], ref_out=a.sum(1))
        check_op(lambda x: paddle.sum(x, axis=[0, 2], keepdim=True), [a],
                 ref_out=a.sum((0, 2), keepdims=True))
        check_grad(lambda x: paddle.sum(x, axis=1), [a])

    def test_mean_max_min_prod(self):
        a = _f32(3, 5)
        check_op(paddle.mean, [a], ref_out=a.mean())
        check_op(lambda x: paddle.max(x, axis=0), [a], ref_out=a.max(0))
        check_op(lambda x: paddle.min(x, axis=1), [a], ref_out=a.min(1))
        check_op(paddle.prod, [a], ref_out=a.prod(), rtol=1e-4)
        check_grad(lambda x: paddle.max(x, axis=0), [a])

    def test_cumsum_logsumexp(self):
        a = _f32(3, 4)
        check_op(lambda x: paddle.cumsum(x, axis=1), [a],
                 ref_out=np.cumsum(a, 1))
        from scipy.special import logsumexp as slse
        check_op(lambda x: paddle.logsumexp(x, axis=1), [a],
                 ref_out=slse(a, axis=1), rtol=1e-5)


class TestMatmul:
    def test_2d(self):
        a, b = _f32(4, 3), _f32(3, 5)
        check_op(paddle.matmul, [a, b], ref=np.matmul, rtol=1e-4)
        check_grad(paddle.matmul, [a, b], rtol=2e-2)

    def test_transpose_flags(self):
        a, b = _f32(3, 4), _f32(5, 3)
        out = paddle.matmul(paddle.to_tensor(a), paddle.to_tensor(b),
                            transpose_x=True, transpose_y=True)
        np.testing.assert_allclose(out.numpy(), a.T @ b.T, rtol=1e-4)

    def test_batched(self):
        a, b = _f32(2, 4, 3), _f32(2, 3, 6)
        check_op(paddle.matmul, [a, b], ref=np.matmul, rtol=1e-4)

    def test_einsum(self):
        a, b = _f32(2, 3), _f32(3, 4)
        out = paddle.einsum("ij,jk->ik", paddle.to_tensor(a),
                            paddle.to_tensor(b))
        np.testing.assert_allclose(out.numpy(), a @ b, rtol=1e-4)


class TestManipulation:
    def test_reshape_transpose(self):
        a = _f32(2, 3, 4)
        check_op(lambda x: paddle.reshape(x, [4, 6]), [a],
                 ref_out=a.reshape(4, 6))
        check_op(lambda x: paddle.transpose(x, [2, 0, 1]), [a],
                 ref_out=a.transpose(2, 0, 1))
        check_grad(lambda x: paddle.reshape(x, [-1]), [a])

    def test_concat_stack_split(self):
        a, b = _f32(2, 3), _f32(2, 3)
        check_op(lambda x, y: paddle.concat([x, y], axis=1), [a, b],
                 ref_out=np.concatenate([a, b], 1))
        check_op(lambda x, y: paddle.stack([x, y]), [a, b],
                 ref_out=np.stack([a, b]))
        outs = paddle.split(paddle.to_tensor(a), [1, 2], axis=1)
        np.testing.assert_allclose(outs[0].numpy(), a[:, :1])
        np.testing.assert_allclose(outs[1].numpy(), a[:, 1:])

    def test_squeeze_unsqueeze_tile(self):
        a = _f32(1, 3, 1)
        assert paddle.squeeze(paddle.to_tensor(a)).shape == [3]
        assert paddle.unsqueeze(paddle.to_tensor(a), 0).shape == [1, 1, 3, 1]
        check_op(lambda x: paddle.tile(x, [2, 1, 2]), [a],
                 ref_out=np.tile(a, (2, 1, 2)))

    def test_gather_scatter(self):
        a = _f32(5, 3)
        idx = np.array([0, 2, 4], np.int32)
        check_op(lambda x: paddle.gather(x, paddle.to_tensor(idx)), [a],
                 ref_out=a[idx])
        upd = _f32(3, 3)
        out = paddle.scatter(paddle.to_tensor(a), paddle.to_tensor(idx),
                             paddle.to_tensor(upd))
        exp = a.copy()
        exp[idx] = upd
        np.testing.assert_allclose(out.numpy(), exp)
        check_grad(lambda x: paddle.gather(x, paddle.to_tensor(idx)), [a])

    def test_gather_nd(self):
        a = _f32(3, 4, 5)
        idx = np.array([[0, 1], [2, 3]], np.int32)
        check_op(lambda x: paddle.gather_nd(x, paddle.to_tensor(idx)), [a],
                 ref_out=a[idx[:, 0], idx[:, 1]])

    def test_flip_roll_take_along(self):
        a = _f32(3, 4)
        check_op(lambda x: paddle.flip(x, [0]), [a], ref_out=a[::-1])
        check_op(lambda x: paddle.roll(x, 1, 0), [a],
                 ref_out=np.roll(a, 1, 0))
        idx = np.array([[0, 1, 2, 0], [3, 2, 1, 0], [1, 1, 1, 1]], np.int32) % 3
        check_op(lambda x: paddle.take_along_axis(x, paddle.to_tensor(idx), 0),
                 [a], ref_out=np.take_along_axis(a, idx, 0))


class TestLogicSearch:
    def test_compare(self):
        a, b = _f32(3, 3), _f32(3, 3)
        assert np.array_equal((paddle.to_tensor(a) > paddle.to_tensor(b)).numpy(), a > b)
        assert np.array_equal(paddle.equal(paddle.to_tensor(a), paddle.to_tensor(a)).numpy(), a == a)

    def test_argmax_sort_topk(self):
        a = _f32(4, 5)
        assert np.array_equal(paddle.argmax(paddle.to_tensor(a), axis=1).numpy(), a.argmax(1))
        np.testing.assert_allclose(paddle.sort(paddle.to_tensor(a), axis=1).numpy(), np.sort(a, 1))
        vals, idx = paddle.topk(paddle.to_tensor(a), 2, axis=1)
        np.testing.assert_allclose(vals.numpy(), -np.sort(-a, 1)[:, :2])

    def test_where_nonzero_masked(self):
        a = _f32(3, 3)
        cond = a > 0
        out = paddle.where(paddle.to_tensor(cond), paddle.to_tensor(a),
                           paddle.to_tensor(-a))
        np.testing.assert_allclose(out.numpy(), np.where(cond, a, -a))
        np.testing.assert_allclose(
            paddle.masked_select(paddle.to_tensor(a), paddle.to_tensor(cond)).numpy(),
            a[cond])


class TestLinalg:
    def test_norm_det_inv(self):
        a = _f32(3, 3) + 3 * np.eye(3, dtype=np.float32)
        np.testing.assert_allclose(paddle.linalg.norm(paddle.to_tensor(a)).numpy(),
                                   np.linalg.norm(a), rtol=1e-5)
        np.testing.assert_allclose(paddle.linalg.det(paddle.to_tensor(a)).numpy(),
                                   np.linalg.det(a), rtol=1e-4)
        np.testing.assert_allclose(paddle.linalg.inv(paddle.to_tensor(a)).numpy(),
                                   np.linalg.inv(a), rtol=1e-4, atol=1e-5)

    def test_solve_cholesky(self):
        m = _f32(4, 4)
        a = m @ m.T + 4 * np.eye(4, dtype=np.float32)
        b = _f32(4, 2)
        np.testing.assert_allclose(paddle.linalg.solve(paddle.to_tensor(a), paddle.to_tensor(b)).numpy(),
                                   np.linalg.solve(a, b), rtol=1e-3, atol=1e-4)
        np.testing.assert_allclose(paddle.linalg.cholesky(paddle.to_tensor(a)).numpy(),
                                   np.linalg.cholesky(a), rtol=1e-4, atol=1e-5)


class TestCreationRandom:
    def test_creation(self):
        assert paddle.zeros([2, 3]).numpy().sum() == 0
        assert paddle.ones([2, 3], dtype='int32').numpy().dtype == np.int32
        assert paddle.full([2], 7.0).numpy().tolist() == [7.0, 7.0]
        assert paddle.arange(5).numpy().tolist() == [0, 1, 2, 3, 4]
        np.testing.assert_allclose(paddle.linspace(0, 1, 5).numpy(),
                                   np.linspace(0, 1, 5), rtol=1e-6)
        assert paddle.eye(3).numpy().trace() == 3

    def test_seed_reproducible(self):
        paddle.seed(7)
        a = paddle.randn([4, 4]).numpy()
        paddle.seed(7)
        b = paddle.randn([4, 4]).numpy()
        np.testing.assert_array_equal(a, b)

    def test_uniform_range(self):
        x = paddle.uniform([1000], min=2.0, max=3.0).numpy()
        assert x.min() >= 2.0 and x.max() < 3.0

    def test_randperm_multinomial(self):
        p = paddle.randperm(10).numpy()
        assert sorted(p.tolist()) == list(range(10))
        probs = paddle.to_tensor(np.array([0.1, 0.0, 0.9], np.float32))
        s = paddle.multinomial(probs, 100, replacement=True).numpy()
        assert (s != 1).all()


class TestAutogradEngine:
    def test_chain(self):
        x = paddle.to_tensor(np.array([2.0], np.float32), stop_gradient=False)
        y = (x * x + 3 * x).sum()
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [7.0])

    def test_accumulation_two_uses(self):
        x = paddle.to_tensor(np.array([3.0], np.float32), stop_gradient=False)
        y = x * x + x * x
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [12.0])

    def test_no_grad(self):
        x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
        with paddle.no_grad():
            y = x * 2
        assert y.stop_gradient

    def test_detach(self):
        x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
        y = (x * 2).detach()
        assert y.stop_gradient
        z = x.detach() * 3
        assert z.stop_gradient

    def test_grad_api(self):
        x = paddle.to_tensor(np.array([2.0], np.float32), stop_gradient=False)
        y = x ** 3
        (g,) = paddle.framework.grad(y, [x])
        np.testing.assert_allclose(g.numpy(), [12.0])
        assert x.grad is None

    def test_hook(self):
        x = paddle.to_tensor(np.ones(2, np.float32), stop_gradient=False)
        seen = []
        x.register_hook(lambda g: seen.append(g.numpy().copy()))
        (x * 5).sum().backward()
        assert len(seen) == 1
        np.testing.assert_allclose(seen[0], [5.0, 5.0])

    def test_second_use_after_backward_raises_or_empty(self):
        x = paddle.to_tensor(np.ones(2, np.float32), stop_gradient=False)
        y = (x * 2).sum()
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [2.0, 2.0])

    def test_grad_accumulate_across_backward(self):
        x = paddle.to_tensor(np.ones(2, np.float32), stop_gradient=False)
        (x * 2).sum().backward()
        (x * 3).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [5.0, 5.0])

    def test_multi_output_op(self):
        a = _f32(4, 4)
        vals, idx = paddle.topk(paddle.to_tensor(a, stop_gradient=False), 2)
        vals.sum().backward()


class TestDtypePlace:
    def test_astype(self):
        x = paddle.ones([2], dtype='float32')
        assert x.astype('int32').numpy().dtype == np.int32
        assert x.astype(paddle.bfloat16).dtype == paddle.bfloat16

    def test_place(self):
        x = paddle.ones([2])
        assert x.place is not None
        y = x.cpu()
        assert y.place.is_cpu_place()

    def test_item_float_int(self):
        assert float(paddle.to_tensor([1.5]).sum()) == 1.5
        assert int(paddle.to_tensor([3])) == 3


class TestReviewRegressions:
    """Regression tests for the round-1 code-review findings."""

    def test_grad_api_does_not_pollute_other_leaves(self):
        x = paddle.to_tensor(np.array([2.0], np.float32), stop_gradient=False)
        w = paddle.to_tensor(np.array([3.0], np.float32), stop_gradient=False)
        y = (x * w).sum()
        (g,) = paddle.framework.grad(y, [x])
        np.testing.assert_allclose(g.numpy(), [3.0])
        assert w.grad is None  # must not leak onto non-input leaves
        assert x.grad is None

    def test_logcumsumexp_correct(self):
        x = np.array([0.0, 10.0, 5.0], np.float32)
        out = paddle.logcumsumexp(paddle.to_tensor(x)).numpy()
        ref = np.logaddexp.accumulate(x.astype(np.float64))
        np.testing.assert_allclose(out, ref, rtol=1e-5)

    def test_split_non_divisible_raises(self):
        with pytest.raises(Exception):
            paddle.split(paddle.ones([5]), 2)

    def test_topk_grad_routes_to_selected(self):
        x = paddle.to_tensor(np.array([1.0, 5.0, 3.0], np.float32),
                             stop_gradient=False)
        vals, idx = paddle.topk(x, 2)
        vals.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [0.0, 1.0, 1.0])
        assert idx.numpy().tolist() == [1, 2]
