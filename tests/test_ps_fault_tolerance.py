"""Fault tolerance of the PS service layer: idempotent retries,
timeouts, hot-standby replication and SIGKILL failover.

Parity model: the reference survives worker/server churn through
brpc_ps_client.cc retry loops and the launch watchdog's restarts
(launch_utils.py:526); here the same guarantees are PROVEN under
deterministic injected failure (fleet/chaos.py) — including the
acceptance bar: a sync-mode training run whose primary server is
SIGKILLed mid-run finishes via replica failover with pulled rows
bit-for-bit equal to the fault-free run (no lost, no double-applied
pushes).

Subprocess servers deliberately avoid importing jax so they start in
well under a second.
"""
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from paddle_tpu.distributed.fleet import chaos
from paddle_tpu.distributed.fleet import ps_service as svc
from paddle_tpu.distributed.fleet.ps import SparseTable
from paddle_tpu.distributed.fleet.ps_service import (
    PSClient, PSConnectError, PSError, PSServer, PSUnavailable,
    _SeqWindow)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# fast-failing client knobs for tests (the defaults are production-scale)
_FAST = dict(connect_timeout=2.0, rpc_timeout=1.0, max_retries=6,
             backoff_base=0.02, rpc_deadline=20.0)


@pytest.fixture(autouse=True)
def _no_leaked_chaos():
    yield
    chaos.uninstall()


def _server(dim=4, lr=0.5, seed=7, replica_of=None, **kw):
    srv = PSServer({"emb": SparseTable(dim, optimizer="sgd", lr=lr,
                                       seed=seed)},
                   host="127.0.0.1", replica_of=replica_of, **kw)
    srv.start()
    return srv, f"127.0.0.1:{srv.port}"


# ---------------------------------------------------------------------------
# satellite: typed connect errors + constructor timeouts
# ---------------------------------------------------------------------------

def test_connect_refused_raises_typed_error_naming_endpoint():
    with pytest.raises(PSConnectError) as ei:
        PSClient(["127.0.0.1:1"], connect_timeout=0.5)
    assert "127.0.0.1:1" in str(ei.value)


def test_unresponsive_server_cannot_wedge_constructor():
    # a listener that accepts but never speaks the protocol: without
    # timeouts the old constructor's register would block forever
    lst = socket.socket()
    lst.bind(("127.0.0.1", 0))
    lst.listen(8)
    ep = f"127.0.0.1:{lst.getsockname()[1]}"
    t0 = time.monotonic()
    try:
        with pytest.raises(PSUnavailable) as ei:
            PSClient([ep], worker_id="w0", connect_timeout=1.0,
                     rpc_timeout=0.3, max_retries=2, backoff_base=0.01,
                     rpc_deadline=3.0)
        assert ep in str(ei.value)
        assert time.monotonic() - t0 < 10.0
    finally:
        lst.close()


# ---------------------------------------------------------------------------
# satellite: _drain error masking + empty push_delta
# ---------------------------------------------------------------------------

def test_drain_keeps_first_error_and_counts_the_rest():
    srv, ep = _server()
    cli = PSClient([ep], mode="async", rpc_timeout=0.3, max_retries=1,
                   backoff_base=0.01, rpc_deadline=0.8, connect_timeout=0.5)
    ids = np.arange(4, dtype=np.int64)
    cli.push("emb", ids, np.ones((4, 4), np.float32))
    cli.barrier()           # healthy flush first
    srv.stop()
    # fire-and-forget frames into a freshly dead server may land in the
    # TCP buffer before the RST arrives; push until the drainer records
    # the first real failure (after which the socket is dropped and
    # every further push fails deterministically at reconnect)
    deadline = time.monotonic() + 20.0
    while cli._push_err is None:
        assert time.monotonic() < deadline, "drainer never saw an error"
        cli.push("emb", ids, np.ones((4, 4), np.float32))
        cli._q.join()
    first = cli._push_err
    for _ in range(3):      # cascade errors that used to MASK the first
        cli.push("emb", ids, np.ones((4, 4), np.float32))
    cli._q.join()
    assert cli._push_err_later == 3
    with pytest.raises(RuntimeError) as ei:
        cli.barrier()
    # the FIRST failure is the cause; later cascade errors are counted,
    # not substituted
    assert ei.value.__cause__ is first
    assert isinstance(first, PSUnavailable)
    assert "3 subsequent" in str(ei.value)
    assert cli._push_err is None and cli._push_err_later == 0  # drained
    cli.close()


def test_push_delta_empty_ids_skips_rpc_and_keeps_dim():
    srv, ep = _server(dim=5)
    cli = PSClient([ep], **_FAST)
    before = srv.applied
    # regression: this used to reshape deltas to (0, 1) regardless of
    # the table dim and still ship the RPC
    cli.push_delta("emb", np.zeros(0, np.int64),
                   np.zeros((0, 5), np.float32))
    cli.push_delta("emb", [], [])
    assert srv.applied == before          # no RPC reached the server
    # non-empty path still lands
    cli.push_delta("emb", np.array([2], np.int64),
                   np.full((1, 5), 0.25, np.float32))
    assert srv.applied == before + 1
    np.testing.assert_allclose(
        cli.pull("emb", np.array([2], np.int64)),
        srv._tables["emb"].pull(np.array([2], np.int64)), rtol=1e-6)
    cli.close()
    srv.stop()


# ---------------------------------------------------------------------------
# idempotent retries
# ---------------------------------------------------------------------------

def test_seq_window_semantics():
    w = _SeqWindow()
    assert not w.check_and_record(1)
    assert w.check_and_record(1)          # immediate duplicate
    assert not w.check_and_record(3)      # gaps are fine (sharding)
    assert not w.check_and_record(2)      # late arrival inside window
    assert w.check_and_record(2)
    # ancient seqs (below the window) are treated as duplicates
    assert not w.check_and_record(10_000)
    assert w.check_and_record(10_000 - _SeqWindow.WINDOW)
    # round trip through export (replication snapshot)
    w2 = _SeqWindow.from_export(w.export())
    assert w2.max_seq == w.max_seq
    assert w2.check_and_record(10_000)


def test_lost_ack_retry_applies_push_exactly_once():
    """The classic double-apply window: server applies the push, the
    ack is lost, the client retries.  The seq window must ack the
    retry without re-applying the additive push."""
    srv, ep = _server(lr=0.5)
    chaos.install(chaos.FaultPlan(
        [chaos.Fault("drop", op="push_reply", first=1)], seed=1))
    cli = PSClient([ep], mode="sync", **_FAST)
    ids = np.arange(6, dtype=np.int64)
    base = cli.pull("emb", ids).copy()
    cli.push("emb", ids, np.ones((6, 4), np.float32))
    np.testing.assert_allclose(cli.pull("emb", ids), base - 0.5,
                               rtol=1e-5)   # once, not twice
    assert srv.dup_acks == 1
    assert cli.retries >= 1
    assert srv.applied == 1
    cli.close()
    srv.stop()


def test_duplicate_delivery_fault_plan_applies_push_once():
    """Acceptance: a duplicate-delivery fault plan proves idempotency.
    Async-mode push frames are one-way; the dup fault delivers every
    frame twice and the server must apply each seq once."""
    srv, ep = _server(lr=1.0)
    plan = chaos.install(chaos.named_plan("dup", seed=3))
    cli = PSClient([ep], mode="async", **_FAST)
    ids = np.arange(8, dtype=np.int64)
    base = cli.pull("emb", ids).copy()
    for _ in range(5):
        cli.push("emb", ids, np.ones((8, 4), np.float32))
    cli.barrier()
    after = cli.pull("emb", ids)
    np.testing.assert_allclose(after, base - 5.0, rtol=1e-5)
    assert plan.stats_dict().get("dup:push", 0) == 5
    assert srv.dup_acks == 5              # every duplicate detected
    assert srv.applied == 5               # ...and applied exactly once
    cli.close()
    srv.stop()


def test_mid_frame_cut_is_survived_by_retry():
    srv, ep = _server(lr=0.5)
    plan = chaos.install(chaos.FaultPlan(
        [chaos.Fault("cut", op="pull", first=2),
         chaos.Fault("cut", op="push", first=1)], seed=2))
    cli = PSClient([ep], mode="sync", **_FAST)
    ids = np.arange(4, dtype=np.int64)
    base = cli.pull("emb", ids).copy()      # pull #1 clean
    cli.push("emb", ids, np.ones((4, 4), np.float32))  # push frame cut
    after = cli.pull("emb", ids)            # pull #2 frame cut
    np.testing.assert_allclose(after, base - 0.5, rtol=1e-5)
    st = plan.stats_dict()
    assert st.get("cut:pull") == 1 and st.get("cut:push") == 1
    assert cli.retries >= 2
    cli.close()
    srv.stop()


def test_failed_reregister_never_reuses_half_used_socket():
    """Regression: _reconnect_locked used to install the socket BEFORE
    the re-register round trip; a timed-out register left the half-used
    socket in place and the next retry read the LATE register reply as
    its own reply (here: a pull getting {"ok": True} -> KeyError)."""
    srv, ep = _server(lr=0.5)
    cli = PSClient([ep], worker_id="w0", connect_timeout=2.0,
                   rpc_timeout=0.5, max_retries=4, backoff_base=0.01,
                   rpc_deadline=20.0)
    ids = np.arange(4, dtype=np.int64)
    base = cli.pull("emb", ids).copy()
    # delay the next register reply past the rpc timeout
    chaos.install(chaos.FaultPlan(
        [chaos.Fault("delay", op="register_reply", first=1, times=1,
                     arg=1.5)], seed=4))
    # force a reconnect: the next RPC must re-establish + re-register
    cli._socks[0].close()
    cli._socks[0] = None
    vals = cli.pull("emb", ids)
    assert np.array_equal(vals, base)
    assert cli.retries >= 1
    cli.close()
    srv.stop()


def test_unknown_table_is_typed_error_not_retry_burn():
    """A handler error (unknown table) must come back as a typed
    NON-retryable PSError naming the cause — not kill the serve thread
    and burn the whole retry budget into PSUnavailable."""
    srv, ep = _server()
    cli = PSClient([ep], **_FAST)
    ids = np.arange(2, dtype=np.int64)
    with pytest.raises(PSError) as ei:
        cli.pull("nope", ids)
    assert not isinstance(ei.value, (PSUnavailable, PSConnectError))
    assert "nope" in str(ei.value) and "KeyError" in str(ei.value)
    assert cli.retries == 0          # non-retryable: no budget burned
    # the connection (and the server) survive the handler error
    assert cli.pull("emb", ids).shape == (2, 4)
    with pytest.raises(PSError) as ei2:
        cli.push("nope", ids, np.ones((2, 4), np.float32))
    assert not isinstance(ei2.value, PSUnavailable)
    cli.close()
    srv.stop()


def test_barrier_confirms_async_delivery_and_reports_loss():
    """Async pushes are one-way frames: "sent" only means the kernel
    buffered them.  barrier() must verify the sent seqs against the
    server's applied-seq window and raise on loss instead of silently
    degrading to at-most-once."""
    srv, ep = _server(lr=1.0)
    cli = PSClient([ep], mode="async", **_FAST)
    ids = np.arange(4, dtype=np.int64)
    for _ in range(3):
        cli.push("emb", ids, np.ones((4, 4), np.float32))
    cli.barrier()                    # clean: everything confirmed
    assert cli._unconfirmed[0] == set()
    assert srv.applied == 3
    # a seq the kernel buffered but the wire never delivered: the
    # server's window has no trace of it -> the next barrier must fail
    cli._note_sent(0, 10_000)
    with pytest.raises(PSUnavailable) as ei:
        cli.barrier()
    assert "lost" in str(ei.value)
    assert cli._unconfirmed[0] == set()   # reported once, then drained
    cli.barrier()                    # back to clean
    cli.close()
    srv.stop()


# ---------------------------------------------------------------------------
# hot-standby replication + failover
# ---------------------------------------------------------------------------

def test_replica_catches_up_from_snapshot_and_stream():
    prim, pep = _server(seed=11)
    cli = PSClient([pep], **_FAST)
    ids = np.arange(8, dtype=np.int64)
    cli.pull("emb", ids)   # materialise rows pre-snapshot
    cli.push("emb", ids, np.ones((8, 4), np.float32))     # pre-snapshot
    rep, _ = _server(seed=11, replica_of=pep)
    assert rep.replica_ready.wait(10.0)
    cli.push("emb", ids, np.ones((8, 4), np.float32))     # streamed
    p = prim._tables["emb"].pull(ids)
    r = rep._tables["emb"].pull(ids)
    assert np.array_equal(p, r)           # bit-for-bit, not allclose
    assert prim._tables["emb"].version == rep._tables["emb"].version
    cli.close()
    prim.stop()
    rep.stop()


def test_client_fails_over_to_promoted_replica():
    prim, pep = _server(seed=5)
    rep, rep_ep = _server(seed=5, replica_of=pep)
    assert rep.replica_ready.wait(10.0)
    cli = PSClient([f"{pep}|{rep_ep}"], worker_id="w0", **_FAST)
    ids = np.arange(6, dtype=np.int64)
    base = cli.pull("emb", ids).copy()
    cli.push("emb", ids, np.ones((6, 4), np.float32))
    prim.stop()                           # primary gone
    after = cli.pull("emb", ids)          # transparently re-routed
    np.testing.assert_allclose(after, base - 0.5, rtol=1e-5)
    cli.push("emb", ids, np.ones((6, 4), np.float32))
    np.testing.assert_allclose(cli.pull("emb", ids), base - 1.0,
                               rtol=1e-5)
    assert cli.failovers >= 1
    st = cli.server_stats()
    assert st["role"] == "primary" and st["promoted"]
    cli.close()
    rep.stop()


def test_unpromoted_standby_refuses_data_rpcs():
    """Split-brain guard: an un-promoted standby must refuse data RPCs
    (retryable), so a client that rotated to it too eagerly — e.g. off
    a slow-but-alive primary — can neither write diverging state nor
    pull rows the stream has not caught up to."""
    prim, pep = _server(seed=5)
    rep, rep_ep = _server(seed=5, replica_of=pep)
    assert rep.replica_ready.wait(10.0)
    ids = np.arange(4, dtype=np.int64)
    # a client pointed ONLY at the standby gets a fast typed failure
    cli = PSClient([rep_ep], connect_timeout=1.0, rpc_timeout=0.5,
                   max_retries=2, backoff_base=0.01, rpc_deadline=3.0)
    with pytest.raises(PSUnavailable) as ei:
        cli.pull("emb", ids)
    assert "not promoted" in str(ei.value)
    with pytest.raises(PSUnavailable):
        cli.push("emb", ids, np.ones((4, 4), np.float32))
    assert rep.applied == 0          # nothing landed on the standby
    cli.close()

    # standby FIRST in the endpoint list: the client transparently
    # rotates to the primary instead of split-braining
    cli2 = PSClient([f"{rep_ep}|{pep}"], **_FAST)
    base = cli2.pull("emb", ids).copy()
    cli2.push("emb", ids, np.ones((4, 4), np.float32))
    np.testing.assert_allclose(cli2.pull("emb", ids), base - 0.5,
                               rtol=1e-5)
    assert cli2.failovers >= 1
    assert prim.applied == 1
    assert rep.applied == 1          # via the replication stream only
    cli2.close()
    prim.stop()
    rep.stop()


def test_failed_replica_attach_does_not_deadlock_mutations():
    """Regression: _attach_replica's failure path used to take the
    apply lock while still holding the sink's stream lock — the exact
    reverse of _forward's order — so a push concurrent with a failed
    attach deadlocked every future mutation on the primary."""
    srv, ep = _server(lr=0.5)
    cli = PSClient([ep], **_FAST)
    ids = np.arange(4, dtype=np.int64)
    cli.pull("emb", ids)             # materialise rows pre-snapshot
    # fake replica: handshake, read the snapshot, but DON'T ack yet —
    # the attach thread now holds the sink lock waiting for our ack
    raw = socket.create_connection(svc._parse_ep(ep), timeout=5.0)
    try:
        svc._send_msg_raw(raw, {"op": "replicate"})
        head = svc._recv_msg(raw)
        for _ in head["tables"]:
            assert svc._recv_msg(raw) is not None
        # a concurrent push takes the apply lock and blocks in
        # _forward on the attach's sink lock...
        done = threading.Event()

        def _push():
            cli.push("emb", ids, np.ones((4, 4), np.float32))
            done.set()

        t = threading.Thread(target=_push, daemon=True)
        t.start()
        time.sleep(0.3)
        # ...then the snapshot is rejected: the failed attach must
        # detach WITHOUT deadlocking against the in-flight push
        svc._send_msg_raw(raw, {"ok": False})
        assert done.wait(10.0), "push deadlocked behind failed attach"
        assert srv.applied == 1
        with srv._apply_lock:
            assert srv._replicas == []
        # the server still serves and mutates after the failed attach
        cli.push("emb", ids, np.ones((4, 4), np.float32))
        assert srv.applied == 2
    finally:
        raw.close()
        cli.close()
        srv.stop()


# ---------------------------------------------------------------------------
# SIGKILL e2e parity (acceptance criterion)
# ---------------------------------------------------------------------------

_SERVER_PROC_SRC = r"""
import json, os, sys
sys.path.insert(0, sys.argv[1])
cfg = json.loads(sys.argv[2])
from paddle_tpu.distributed.fleet.ps import SparseTable
from paddle_tpu.distributed.fleet.ps_service import PSServer
tables = {n: SparseTable(**kw) for n, kw in cfg["tables"].items()}
srv = PSServer(tables, host="127.0.0.1",
               replica_of=cfg.get("replica_of"))
srv.start()
print(json.dumps({"port": srv.port, "pid": os.getpid()}), flush=True)
srv._stop.wait()
"""


def _spawn_server(tables, replica_of=None, env_extra=None):
    cfg = {"tables": tables, "replica_of": replica_of}
    env = dict(os.environ)
    env.pop("PADDLE_CHAOS", None)
    if env_extra:
        env.update(env_extra)
    proc = subprocess.Popen(
        [sys.executable, "-c", _SERVER_PROC_SRC, _REPO, json.dumps(cfg)],
        stdout=subprocess.PIPE, text=True, env=env)
    line = proc.stdout.readline()
    info = json.loads(line)
    return proc, f"127.0.0.1:{info['port']}"


def _train(endpoints, steps, ids, kill_at=None, kill_proc=None,
           dim=4, seed=23):
    """Deterministic sync-mode wide_deep-style loop: pull rows, push a
    step-dependent gradient.  Returns the final pulled rows."""
    cli = PSClient(endpoints, mode="sync", worker_id="w0", **_FAST)
    for step in range(steps):
        rows = cli.pull("emb", ids)
        assert rows.shape == (ids.size, dim)
        # gradient derived from the step only — identical across runs
        g = np.full((ids.size, dim), 0.125 * ((step % 5) + 1), np.float32)
        cli.push("emb", ids, g)
        if kill_at is not None and step == kill_at:
            os.kill(kill_proc.pid, signal.SIGKILL)
            kill_proc.wait(timeout=10)
    final = cli.pull("emb", ids).copy()
    cli.close()
    return final


def test_sigkill_failover_matches_fault_free_run_bit_for_bit():
    """Sync-mode training with a mid-run primary SIGKILL completes via
    replica failover, and the pulled rows match the fault-free run
    EXACTLY — no lost pushes, no double-applied pushes."""
    spec = {"emb": dict(dim=4, optimizer="adagrad", lr=0.1, seed=23)}
    # the id universe is touched from step 0, so every row materialises
    # (deterministically) before the kill on both runs
    ids = np.arange(32, dtype=np.int64)
    steps, kill_at = 12, 5

    # fault-free reference run
    ref_proc, ref_ep = _spawn_server(spec)
    try:
        ref = _train([ref_ep], steps, ids)
    finally:
        ref_proc.kill()
        ref_proc.wait(timeout=10)

    # faulted run: subprocess primary + in-process standby
    prim_proc, prim_ep = _spawn_server(spec)
    rep = PSServer({"emb": SparseTable(**spec["emb"])}, host="127.0.0.1",
                   replica_of=prim_ep)
    rep.start()
    try:
        assert rep.replica_ready.wait(15.0)
        got = _train([f"{prim_ep}|127.0.0.1:{rep.port}"], steps, ids,
                     kill_at=kill_at, kill_proc=prim_proc)
        assert rep.promoted
        assert np.array_equal(got, ref), (
            "failover trajectory diverged from the fault-free run")
    finally:
        prim_proc.kill()
        prim_proc.wait(timeout=10)
        rep.stop()


def test_chaos_crash_fault_kills_subprocess_server():
    """PADDLE_CHAOS env activation: a crash@N plan hard-kills the
    server on the Nth push it receives (the harness the watchdog-less
    single server is tested against)."""
    spec = {"emb": dict(dim=4, optimizer="sgd", lr=0.5, seed=1)}
    proc, ep = _spawn_server(
        spec, env_extra={"PADDLE_CHAOS": "crash:push:first=2"})
    cli = PSClient([ep], mode="sync", rpc_timeout=0.5, max_retries=2,
                   backoff_base=0.01, rpc_deadline=3.0,
                   connect_timeout=1.0)
    ids = np.arange(4, dtype=np.int64)
    cli.push("emb", ids, np.ones((4, 4), np.float32))   # push #1 fine
    with pytest.raises(PSUnavailable):
        cli.push("emb", ids, np.ones((4, 4), np.float32))  # crashes it
    assert proc.wait(timeout=10) == 137
    cli.close()


# ---------------------------------------------------------------------------
# endpoint groups: role maker + fleet wiring
# ---------------------------------------------------------------------------

def test_endpoint_groups_and_replica_primary():
    from paddle_tpu.distributed.fleet.role_maker import (
        endpoint_groups, replica_primary_for)
    eps = ["10.0.0.1:7100|10.0.0.2:7100", "10.0.0.3:7100"]
    assert endpoint_groups(eps) == [["10.0.0.1:7100", "10.0.0.2:7100"],
                                    ["10.0.0.3:7100"]]
    assert replica_primary_for("10.0.0.2:7100", eps) == "10.0.0.1:7100"
    assert replica_primary_for("10.0.0.1:7100", eps) is None
    assert replica_primary_for("10.0.0.3:7100", eps) is None
    assert replica_primary_for("10.0.0.9:7100", eps) is None


def test_role_maker_shard_id_inside_replica_group(monkeypatch):
    from paddle_tpu.distributed.fleet.role_maker import PaddleCloudRoleMaker
    monkeypatch.setenv("TRAINING_ROLE", "PSERVER")
    monkeypatch.setenv("PADDLE_PSERVERS_IP_PORT_LIST",
                       "10.0.0.1:7100|10.0.0.2:7100,10.0.0.3:7100|10.0.0.4:7100")
    monkeypatch.setenv("POD_IP", "10.0.0.4")
    monkeypatch.setenv("PADDLE_PORT", "7100")
    rm = PaddleCloudRoleMaker()
    assert rm.is_server()
    assert rm.server_index() == 1    # standby of shard 1's primary


def test_fleet_run_server_starts_replica_from_env(monkeypatch):
    from paddle_tpu.distributed.fleet.fleet_base import Fleet
    prim, pep = _server(seed=3)
    try:
        # a worker must touch the table so the snapshot is non-trivial
        cli = PSClient([pep], **_FAST)
        ids = np.arange(4, dtype=np.int64)
        cli.push("emb", ids, np.ones((4, 4), np.float32))
        expect = prim._tables["emb"].pull(ids)
        monkeypatch.setenv("TRAINING_ROLE", "PSERVER")
        monkeypatch.setenv("PADDLE_PSERVERS_IP_PORT_LIST",
                           f"{pep}|127.0.0.1:0")
        monkeypatch.setenv("POD_IP", "127.0.0.1")
        monkeypatch.setenv("PADDLE_PORT", "0")
        f = Fleet()
        f.init(is_collective=False)
        f.init_server()
        f.run_server()
        srv = f._ps_runtime._server
        assert srv.role == "replica" and srv.replica_of == pep
        assert srv.replica_ready.wait(10.0)
        # replica recovered the table (dim included) from the snapshot
        assert np.array_equal(srv._tables["emb"].pull(ids), expect)
        cli.close()
        f._ps_runtime.stop()
    finally:
        prim.stop()
