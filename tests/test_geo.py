"""Geo-async replication (ISSUE 10): GeoPusher delta push between
clusters.

Acceptance contracts:
- a geo follower converges to the primary BIT-EXACTLY (the residual-
  correction pass closes the f32 ``prev + (cur - prev)`` rounding gap);
- under a seeded lossy/delayed geo link, 0 lost / 0 double-applied
  deltas (chaos_ps-style shadow count: the follower's rows equal the
  primary's for the whole id universe);
- the per-table rate limit bounds each flush, and the backlog drains
  within the configured bound once writes quiesce;
- a remote outage re-queues (never drops) the dirty ids.
"""
import time

import numpy as np
import pytest

from paddle_tpu.distributed.fleet import chaos
from paddle_tpu.distributed.fleet.geo import GeoPusher
from paddle_tpu.distributed.fleet.ps import SparseTable
from paddle_tpu.distributed.fleet.ps_service import (PSClient, PSError,
                                                     PSServer,
                                                     PSUnavailable)

_FAST = dict(connect_timeout=2.0, rpc_timeout=1.0, max_retries=6,
             backoff_base=0.02, rpc_deadline=20.0)
_SPEC = dict(dim=6, optimizer="adagrad", lr=0.1, seed=5)


@pytest.fixture(autouse=True)
def _no_leaked_chaos():
    yield
    chaos.uninstall()


def _cluster():
    srv = PSServer({"emb": SparseTable(**_SPEC)}, host="127.0.0.1")
    srv.start()
    return srv, f"127.0.0.1:{srv.port}"


def _train(ep, steps=20, batch=32, vocab=300, seed=0):
    w = PSClient([ep], mode="sync", **_FAST)
    rng = np.random.RandomState(seed)
    for step in range(steps):
        ids = np.clip(rng.zipf(1.3, batch), 1, vocab).astype(np.int64)
        w.push("emb", ids, np.full((batch, 6),
                                   0.05 * ((step % 7) + 1), np.float32))
    w.close()


def _assert_converged(local, remote, vocab=300):
    all_ids = np.arange(vocab, dtype=np.int64)
    a = local._tables["emb"].pull(all_ids)
    b = remote._tables["emb"].pull(all_ids)
    neq = ~np.all(a == b, axis=1)
    # chaos_ps-style count: ANY differing row is a lost or
    # double-applied delta — the geo contract is exactly zero of each
    assert int(neq.sum()) == 0, \
        f"{int(neq.sum())} rows diverged: ids {np.flatnonzero(neq)[:8]}"


def test_geo_follower_converges_bit_exact():
    local, lep = _cluster()
    remote, rep = _cluster()
    gp = GeoPusher(local, [rep], interval_s=0.01, **_FAST).start()
    try:
        _train(lep, steps=20)
        gp.drain(timeout=30.0)
        _assert_converged(local, remote)
        assert gp.pushed_ids > 0 and gp.push_failures == 0
    finally:
        gp.stop(drain=False)
        local.stop()
        remote.stop()


def test_geo_lossy_delayed_link_zero_lost_zero_double_applied():
    """THE geo acceptance: the geo client's push_delta frames ride a
    seeded lossy/delayed link (delays, dropped acks — the classic
    double-apply trap); the follower still lands on the primary's
    exact bits because retries re-send the SAME (src, seq) and the
    server dedups them."""
    local, lep = _cluster()
    remote, rep = _cluster()
    chaos.install(chaos.plan_from_spec(
        "seed=3;delay:push_delta:first=1:every=2:times=0:arg=0.002;"
        "drop:push_delta_reply:first=2:every=3:times=0;"
        "cut:push_delta:first=9:every=11:times=0"))
    gp = GeoPusher(local, [rep], interval_s=0.01,
                   max_ids_per_flush=64, **_FAST).start()
    try:
        _train(lep, steps=20)
        gp.drain(timeout=60.0)
        _assert_converged(local, remote)
        st = chaos.active().stats_dict()
        assert any(k.startswith(("drop", "delay", "cut"))
                   for k in st), st   # the link really was hostile
        assert remote.dup_acks >= 1   # a retry was deduped, not
        # double-applied — the idempotency stamp did its job
    finally:
        chaos.uninstall()
        gp.stop(drain=False)
        local.stop()
        remote.stop()


def test_geo_rate_limit_and_convergence_bound():
    """Per-table rate: each flush ships at most max_ids_per_flush ids,
    so a backlog of B dirty ids provably drains within ceil(B/R)
    flushes once writes quiesce — the configured staleness bound."""
    local, lep = _cluster()
    remote, rep = _cluster()
    gp = GeoPusher(local, [rep], interval_s=3600.0,   # manual flushes
                   max_ids_per_flush=50, **_FAST)
    try:
        w = PSClient([lep], mode="sync", **_FAST)
        ids = np.arange(170, dtype=np.int64)
        w.push("emb", ids, np.ones((170, 6), np.float32))
        w.close()
        assert gp.backlog() == 170
        bound = -(-170 // 50)         # ceil(B / R) = 4 flushes
        flushes = 0
        while gp.backlog() and flushes < bound:
            gp.flush()
            flushes += 1
        assert gp.backlog() == 0 and flushes == bound
        _assert_converged(local, remote)
    finally:
        gp.stop(drain=False)
        local.stop()
        remote.stop()


def test_geo_remote_outage_requeues_never_drops():
    local, lep = _cluster()
    remote, rep = _cluster()
    remote.stop()                     # remote cluster is DOWN
    gp = GeoPusher(local, [rep], interval_s=3600.0,
                   connect_timeout=0.5, rpc_timeout=0.5, max_retries=1,
                   backoff_base=0.01, rpc_deadline=1.5)
    try:
        w = PSClient([lep], mode="sync", **_FAST)
        ids = np.arange(8, dtype=np.int64)
        w.push("emb", ids, np.ones((8, 6), np.float32))
        w.close()
        assert gp.backlog() == 8
        with pytest.raises((PSError, PSUnavailable)):
            gp.flush()
        assert gp.backlog() == 8      # re-queued, not dropped
        assert gp.push_failures == 1
    finally:
        gp.stop(drain=False)
        local.stop()


def test_geo_python_backend_requires_deterministic_init():
    """The mirror contract: a python-backend table with a random init
    cannot geo-replicate (materialisation-order-dependent init would
    diverge the follower); init_std=0 can."""
    bad = PSServer({"emb": SparseTable(4, optimizer="sgd", lr=0.1,
                                       init_std=0.01,
                                       use_native=False)},
                   host="127.0.0.1")
    bad.start()
    gp = GeoPusher(bad, ["127.0.0.1:1"], interval_s=3600.0,
                   connect_timeout=0.5, rpc_timeout=0.5, max_retries=1,
                   backoff_base=0.01, rpc_deadline=1.0)
    try:
        bad._tables["emb"].push(np.arange(4, dtype=np.int64),
                                np.ones((4, 4), np.float32))
        gp._on_commit({"op": "push", "table": "emb",
                       "ids": np.arange(4, dtype=np.int64)})
        with pytest.raises(PSError, match="deterministic"):
            gp.flush()
    finally:
        gp.stop(drain=False)
        bad.stop()


def test_geo_observability_wiring():
    import os
    import sys
    from paddle_tpu.observability.flight_recorder import _PROGRESS_KINDS
    assert {"ps.geo.push", "ps.replica.attach",
            "ps.promote"} <= set(_PROGRESS_KINDS)
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools"))
    import postmortem
    assert postmortem._is_bad({"kind": "ps.read_stale_exhausted"})
    assert postmortem._is_bad({"kind": "ps.replica_error"})
    # geo.py is in the default GraftLint set and lints clean
    from paddle_tpu.analysis import DEFAULT_LINT_PATHS, lint_file
    assert "paddle_tpu/distributed/fleet/geo.py" in DEFAULT_LINT_PATHS
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    findings = lint_file(
        os.path.join(repo, "paddle_tpu/distributed/fleet/geo.py"))
    assert findings == [], [str(f) for f in findings]
