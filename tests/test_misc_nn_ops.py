"""sequence_conv / row_conv / cos_sim / data_norm vs numpy references
(reference fluid/layers/sequence_lod.py:44, nn.py:5666, nn.py:921,
operators/data_norm_op.cc:302)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F


def test_sequence_conv_matches_numpy():
    rng = np.random.RandomState(0)
    N, S, H, L, Fo = 2, 5, 3, 3, 4
    x = rng.randn(N, S, H).astype(np.float32)
    w = rng.randn(L * H, Fo).astype(np.float32)
    out = F.sequence_conv(paddle.to_tensor(x), paddle.to_tensor(w),
                          context_length=L).numpy()
    cs = -((L - 1) // 2)
    ref = np.zeros((N, S, Fo), np.float32)
    for n in range(N):
        for t in range(S):
            ctx = []
            for j in range(L):
                tt = t + cs + j
                ctx.append(x[n, tt] if 0 <= tt < S
                           else np.zeros(H, np.float32))
            ref[n, t] = np.concatenate(ctx) @ w
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_sequence_conv_respects_lengths():
    rng = np.random.RandomState(1)
    x = rng.randn(1, 6, 2).astype(np.float32)
    w = rng.randn(6, 3).astype(np.float32)
    lens = np.asarray([4], np.int64)
    out = F.sequence_conv(paddle.to_tensor(x), paddle.to_tensor(w),
                          context_length=3,
                          length=paddle.to_tensor(lens)).numpy()
    assert (out[0, 4:] == 0).all()            # padded steps are zero
    # valid steps must not see data beyond the length
    x2 = x.copy()
    x2[0, 4:] = 99.0
    out2 = F.sequence_conv(paddle.to_tensor(x2), paddle.to_tensor(w),
                           context_length=3,
                           length=paddle.to_tensor(lens)).numpy()
    np.testing.assert_allclose(out[0, :4], out2[0, :4], rtol=1e-5)


def test_row_conv_matches_numpy():
    rng = np.random.RandomState(2)
    N, S, H, k = 2, 6, 4, 2     # future_context_size = 2 -> kernel k+1
    x = rng.randn(N, S, H).astype(np.float32)
    w = rng.randn(k + 1, H).astype(np.float32)
    out = F.row_conv(paddle.to_tensor(x), paddle.to_tensor(w)).numpy()
    ref = np.zeros_like(x)
    for t in range(S):
        for i in range(k + 1):
            if t + i < S:
                ref[:, t] += x[:, t + i] * w[i]
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_row_conv_grads_flow():
    rng = np.random.RandomState(3)
    x = paddle.to_tensor(rng.randn(1, 4, 2).astype(np.float32),
                         stop_gradient=False)
    w = paddle.to_tensor(rng.randn(2, 2).astype(np.float32),
                         stop_gradient=False)
    F.row_conv(x, w).sum().backward()
    assert np.isfinite(x.grad.numpy()).all()
    assert np.isfinite(w.grad.numpy()).all()


def test_cos_sim():
    rng = np.random.RandomState(4)
    x = rng.randn(5, 8).astype(np.float32)
    y = rng.randn(5, 8).astype(np.float32)
    out = F.cos_sim(paddle.to_tensor(x), paddle.to_tensor(y)).numpy()
    ref = (x * y).sum(-1) / (np.linalg.norm(x, axis=-1)
                             * np.linalg.norm(y, axis=-1))
    np.testing.assert_allclose(out[:, 0], ref, rtol=1e-5)
    # broadcast: one reference row
    y1 = rng.randn(1, 8).astype(np.float32)
    out2 = F.cos_sim(paddle.to_tensor(x), paddle.to_tensor(y1)).numpy()
    ref2 = (x * y1).sum(-1) / (np.linalg.norm(x, axis=-1)
                               * np.linalg.norm(y1, axis=-1))
    np.testing.assert_allclose(out2[:, 0], ref2, rtol=1e-5)


def test_data_norm_reference_formula():
    rng = np.random.RandomState(5)
    N, D = 6, 3
    x = rng.rand(N, D).astype(np.float32) + 1.0
    bsz = np.full((D,), 10.0, np.float32)
    bsum = rng.rand(D).astype(np.float32) * 10
    bsq = rng.rand(D).astype(np.float32) * 10 + 5
    out = F.data_norm(paddle.to_tensor(x), paddle.to_tensor(bsz),
                      paddle.to_tensor(bsum),
                      paddle.to_tensor(bsq)).numpy()
    means = bsum / bsz
    scales = np.sqrt(bsz / bsq)          # data_norm_op.cc:303
    np.testing.assert_allclose(out, (x - means) * scales, rtol=1e-5)
    # affine fold
    sw = rng.rand(D).astype(np.float32)
    b = rng.rand(D).astype(np.float32)
    out2 = F.data_norm(paddle.to_tensor(x), paddle.to_tensor(bsz),
                       paddle.to_tensor(bsum), paddle.to_tensor(bsq),
                       scale_w=paddle.to_tensor(sw),
                       bias=paddle.to_tensor(b)).numpy()
    np.testing.assert_allclose(out2, (x - means) * scales * sw + b,
                               rtol=1e-5)
