"""Two-stage detection ops vs numpy references + Faster-RCNN-style
composition (anchors -> proposals -> FPN routing -> RoI pooling).

Parity: fluid/layers/detection.py:621/1317/1925/2399/2894/3043/3673/3871
and operators/detection/*; static-shape TPU formulations are padded +
counts, but the valid prefixes must match the reference math.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import ops as V


def test_anchor_generator_matches_numpy():
    x = paddle.to_tensor(np.zeros((1, 8, 3, 4), np.float32))
    anchors, var = V.anchor_generator(
        x, anchor_sizes=[64.0], aspect_ratios=[1.0, 2.0],
        stride=[16.0, 16.0], offset=0.5)
    a = anchors.numpy()
    assert a.shape == (3, 4, 2, 4)
    # position (0,0), ratio 1.0: 64x64 box centered at (8, 8)
    np.testing.assert_allclose(a[0, 0, 0], [8 - 32, 8 - 32, 8 + 32, 8 + 32])
    # ratio 2.0 (h/w): w = 64/sqrt(2), h = 64*sqrt(2)
    w, h = 64 / np.sqrt(2), 64 * np.sqrt(2)
    np.testing.assert_allclose(
        a[0, 0, 1], [8 - w / 2, 8 - h / 2, 8 + w / 2, 8 + h / 2],
        rtol=1e-5)
    # anchors shift by the stride across positions
    np.testing.assert_allclose(a[0, 1, 0] - a[0, 0, 0], [16, 0, 16, 0])
    np.testing.assert_allclose(var.numpy()[2, 3, 1],
                               [0.1, 0.1, 0.2, 0.2])


def test_density_prior_box_counts_and_range():
    x = paddle.to_tensor(np.zeros((1, 8, 4, 4), np.float32))
    img = paddle.to_tensor(np.zeros((1, 3, 64, 64), np.float32))
    boxes, var = V.density_prior_box(
        x, img, densities=[2], fixed_sizes=[32.0], fixed_ratios=[1.0],
        clip=False, steps=[16.0, 16.0])
    b = boxes.numpy()
    assert b.shape == (4, 4, 4, 4)      # 2^2 densities x 1 ratio
    # centers of the 2x2 sub-grid differ by shift/img = 8/64
    c0 = (b[0, 0, 0, 0] + b[0, 0, 0, 2]) / 2
    c1 = (b[0, 0, 1, 0] + b[0, 0, 1, 2]) / 2
    np.testing.assert_allclose(c1 - c0, 8.0 / 64.0, atol=1e-6)
    bc, _ = V.density_prior_box(
        x, img, densities=[2], fixed_sizes=[32.0], fixed_ratios=[1.0],
        clip=True, steps=[16.0, 16.0])
    v = bc.numpy()
    assert (v >= 0).all() and (v <= 1).all()


def test_bipartite_match_greedy():
    d = np.asarray([[0.9, 0.1, 0.3],
                    [0.8, 0.7, 0.2]], np.float32)
    idx, dist = V.bipartite_match(paddle.to_tensor(d))
    # greedy: (0,0)=0.9 first, then (1,1)=0.7; col 2 unmatched
    np.testing.assert_array_equal(idx.numpy(), [0, 1, -1])
    np.testing.assert_allclose(dist.numpy(), [0.9, 0.7, 0.0])
    # per_prediction: col 2 gets its argmax row if >= threshold
    idx2, dist2 = V.bipartite_match(paddle.to_tensor(d),
                                    match_type="per_prediction",
                                    dist_threshold=0.25)
    np.testing.assert_array_equal(idx2.numpy(), [0, 1, 0])
    np.testing.assert_allclose(dist2.numpy(), [0.9, 0.7, 0.3])


def test_box_clip():
    boxes = np.asarray([[-5.0, -3.0, 120.0, 40.0]], np.float32)
    im = np.asarray([[50.0, 100.0, 1.0]], np.float32)  # h=50, w=100
    out = V.box_clip(paddle.to_tensor(boxes), paddle.to_tensor(im))
    np.testing.assert_allclose(out.numpy()[0], [0, 0, 99, 40])


def _np_decode(anchor, var, delta):
    aw, ah = anchor[2] - anchor[0], anchor[3] - anchor[1]
    acx, acy = anchor[0] + aw / 2, anchor[1] + ah / 2
    cx = delta[0] * var[0] * aw + acx
    cy = delta[1] * var[1] * ah + acy
    w = np.exp(delta[2] * var[2]) * aw
    h = np.exp(delta[3] * var[3]) * ah
    return [cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2]


def test_generate_proposals_decode_and_nms():
    # 1x1 feature map, 3 anchors: check decode + suppression orders
    H = W = 1
    A = 3
    anchors = np.zeros((H, W, A, 4), np.float32)
    anchors[0, 0, 0] = [0, 0, 10, 10]
    anchors[0, 0, 1] = [1, 1, 11, 11]     # overlaps anchor 0 heavily
    anchors[0, 0, 2] = [30, 30, 50, 50]
    var = np.full((H, W, A, 4), 1.0, np.float32)
    scores = np.asarray([0.9, 0.8, 0.7], np.float32).reshape(1, A, 1, 1)
    deltas = np.zeros((1, 4 * A, 1, 1), np.float32)
    im_info = np.asarray([[60.0, 60.0, 1.0]], np.float32)
    rois, num = V.generate_proposals(
        paddle.to_tensor(scores), paddle.to_tensor(deltas),
        paddle.to_tensor(im_info), paddle.to_tensor(anchors),
        paddle.to_tensor(var), pre_nms_top_n=3, post_nms_top_n=3,
        nms_thresh=0.5, min_size=1.0, return_rois_num=True)
    assert int(num.numpy()[0]) == 2      # anchor 1 suppressed by 0
    np.testing.assert_allclose(rois.numpy()[0, 0], [0, 0, 10, 10])
    np.testing.assert_allclose(rois.numpy()[0, 1], [30, 30, 50, 50])
    # non-zero deltas decode like box_coder center-size
    deltas2 = np.zeros((1, 4 * A, 1, 1), np.float32)
    deltas2[0, 0:4, 0, 0] = [0.1, 0.2, 0.1, -0.1]
    rois2 = V.generate_proposals(
        paddle.to_tensor(scores), paddle.to_tensor(deltas2),
        paddle.to_tensor(im_info), paddle.to_tensor(anchors),
        paddle.to_tensor(var), pre_nms_top_n=3, post_nms_top_n=3,
        nms_thresh=0.99, min_size=0.0)
    want = _np_decode(anchors[0, 0, 0], var[0, 0, 0],
                      [0.1, 0.2, 0.1, -0.1])
    np.testing.assert_allclose(rois2.numpy()[0, 0], want, rtol=1e-5)


def test_detection_output_ssd():
    M, C = 2, 3     # 2 priors, 3 classes (0 = background)
    priors = np.asarray([[0, 0, 10, 10], [20, 20, 40, 40]], np.float32)
    pvar = np.full((M, 4), 1.0, np.float32)
    loc = np.zeros((1, M, 4), np.float32)
    scores = np.zeros((1, M, C), np.float32)
    scores[0, 0] = [0.1, 0.8, 0.1]      # prior 0 -> class 1
    scores[0, 1] = [0.2, 0.1, 0.7]      # prior 1 -> class 2
    out, counts = V.detection_output(
        paddle.to_tensor(loc), paddle.to_tensor(scores),
        paddle.to_tensor(priors), paddle.to_tensor(pvar),
        keep_top_k=4, score_threshold=0.5)
    assert int(counts.numpy()[0]) == 2
    o = out.numpy()[0]
    assert o[0, 0] == 1.0 and abs(o[0, 1] - 0.8) < 1e-6
    np.testing.assert_allclose(o[0, 2:], [0, 0, 10, 10], atol=1e-5)
    assert o[1, 0] == 2.0
    np.testing.assert_allclose(o[1, 2:], [20, 20, 40, 40], atol=1e-5)
    assert (o[2:, 0] == -1).all()       # padding rows flagged


def test_distribute_and_collect_fpn():
    rois = np.asarray([[0, 0, 224, 224],      # scale 224 -> level 4
                       [0, 0, 56, 56],        # scale 56  -> level 2
                       [0, 0, 112, 112],      # scale 112 -> level 3
                       [0, 0, 448, 448]],     # scale 448 -> level 5
                      np.float32)
    outs, restore, counts = V.distribute_fpn_proposals(
        paddle.to_tensor(rois), min_level=2, max_level=5,
        refer_level=4, refer_scale=224)
    np.testing.assert_array_equal(counts.numpy(), [1, 1, 1, 1])
    np.testing.assert_allclose(outs[0].numpy()[0], rois[1])  # lvl2
    np.testing.assert_allclose(outs[2].numpy()[0], rois[0])  # lvl4
    # restore indices rebuild the original order from the level concat
    concat = np.concatenate([o.numpy()[:int(c)] for o, c in
                             zip(outs, counts.numpy())], axis=0)
    np.testing.assert_allclose(concat[restore.numpy()[:, 0]], rois)

    # collect: global top-k by score across levels
    scores = [paddle.to_tensor(np.asarray(s, np.float32))
              for s in ([0.3, 0, 0, 0], [0.9, 0, 0, 0],
                        [0.5, 0, 0, 0], [0.7, 0, 0, 0])]
    kept, n = V.collect_fpn_proposals(
        outs, scores, 2, 5, post_nms_top_n=2,
        rois_num_per_level=[paddle.to_tensor(np.int64(1))] * 4)
    assert int(n.numpy()) == 2
    # per-level scores: lvl2=0.3, lvl3=0.9, lvl4=0.5, lvl5=0.7 — the
    # top-2 are the lvl3 (112) and lvl5 (448) rois
    np.testing.assert_allclose(kept.numpy()[0], rois[2])   # score 0.9
    np.testing.assert_allclose(kept.numpy()[1], rois[3])   # score 0.7


def test_deformable_psroi_pooling_zero_offset_matches_psroi():
    # with zero trans, deformable PS-RoI == plain PS-RoI average pool
    np.random.seed(0)
    ph = pw = 2
    out_c = 3
    x = np.random.randn(1, out_c * ph * pw, 8, 8).astype(np.float32)
    rois = np.asarray([[0, 0, 0, 4, 4]], np.float32)  # batch 0, 4x4 box
    out = V.deformable_psroi_pooling(
        paddle.to_tensor(x), paddle.to_tensor(rois), no_trans=True,
        spatial_scale=1.0, pooled_height=ph, pooled_width=pw,
        sample_per_part=2)
    assert out.numpy().shape == (1, out_c, ph, pw)
    # bin (0,0) of channel c samples channel c*4+0 inside [0,2)x[0,2)
    # with 2x2 midpoint samples at (0.5, 1.5)
    for c in range(out_c):
        plane = x[0, c * 4]
        ys = xs = np.asarray([0.5, 1.5])
        vals = []
        for yy in ys:
            for xx in xs:
                y0, x0 = int(yy), int(xx)
                wy, wx = yy - y0, xx - x0
                v = (plane[y0, x0] * (1 - wy) * (1 - wx)
                     + plane[y0, x0 + 1] * (1 - wy) * wx
                     + plane[y0 + 1, x0] * wy * (1 - wx)
                     + plane[y0 + 1, x0 + 1] * wy * wx)
                vals.append(v)
        np.testing.assert_allclose(out.numpy()[0, c, 0, 0],
                                   np.mean(vals), rtol=1e-5)
    # a non-zero offset shifts the sampling window
    trans = np.zeros((1, 2, ph, pw), np.float32)
    trans[0, 0, 0, 0] = 2.5     # dx = 2.5 * trans_std * roi_w = 1.0
    out2 = V.deformable_psroi_pooling(
        paddle.to_tensor(x), paddle.to_tensor(rois),
        trans=paddle.to_tensor(trans), spatial_scale=1.0,
        pooled_height=ph, pooled_width=pw, sample_per_part=2,
        trans_std=0.1)
    assert abs(out2.numpy()[0, 0, 0, 0] - out.numpy()[0, 0, 0, 0]) > 1e-6


def test_faster_rcnn_style_head_composes():
    """anchors -> RPN proposals -> FPN routing -> RoI align -> head:
    the full two-stage pipeline runs end-to-end with static shapes."""
    paddle.seed(0)
    rng = np.random.RandomState(0)
    N, A, H, W = 1, 3, 8, 8
    feat = paddle.to_tensor(rng.randn(N, 16, H, W).astype(np.float32))
    anchors, var = V.anchor_generator(
        feat, anchor_sizes=[32.0, 64.0], aspect_ratios=[0.5, 1.0, 2.0],
        stride=[8.0, 8.0])
    A6 = 6
    scores = paddle.to_tensor(
        rng.rand(N, A6, H, W).astype(np.float32))
    deltas = paddle.to_tensor(
        (rng.randn(N, 4 * A6, H, W) * 0.1).astype(np.float32))
    im_info = paddle.to_tensor(np.asarray([[64.0, 64.0, 1.0]],
                                          np.float32))
    rois, num = V.generate_proposals(
        scores, deltas, im_info, anchors, var, pre_nms_top_n=64,
        post_nms_top_n=16, nms_thresh=0.7, min_size=2.0,
        return_rois_num=True)
    n0 = int(num.numpy()[0])
    assert n0 > 0
    outs, restore, counts = V.distribute_fpn_proposals(
        paddle.to_tensor(rois.numpy()[0]), min_level=2, max_level=3,
        refer_level=2, refer_scale=28)
    assert int(counts.numpy().sum()) == 16   # every padded slot routed
    pooled = V.roi_align(feat, paddle.to_tensor(rois.numpy()[0]),
                         paddle.to_tensor(np.asarray([16], np.int64)),
                         output_size=4, spatial_scale=H / 64.0)
    assert pooled.numpy().shape == (16, 16, 4, 4)
    assert np.isfinite(pooled.numpy()).all()
