"""Control flow (SURVEY §2.3 controlflow/), sequence ops (sequence_ops/),
and detection ops (detection/) tests.

Modeled on the reference's OpTest style: NumPy reference implementations
compared against the op outputs; grads spot-checked through the tape.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.static import nn as static_nn
from paddle_tpu.vision import ops as vops


# ---------------------------------------------------------------- control flow

def test_cond_eager_both_branches():
    x = paddle.to_tensor(np.array([2.0], dtype="float32"))
    out = static_nn.cond(x.sum() > 1.0,
                         lambda: x * 2,
                         lambda: x - 1)
    np.testing.assert_allclose(out.numpy(), [4.0])
    out = static_nn.cond(x.sum() > 10.0, lambda: x * 2, lambda: x - 1)
    np.testing.assert_allclose(out.numpy(), [1.0])


def test_cond_traced_lowers_to_lax():
    def f(xv):
        x = paddle.to_tensor(xv)
        out = static_nn.cond(x.sum() > 0, lambda: x * 2, lambda: x * -1)
        return out._value

    jf = jax.jit(f)
    np.testing.assert_allclose(np.asarray(jf(jnp.asarray([3.0]))), [6.0])
    np.testing.assert_allclose(np.asarray(jf(jnp.asarray([-3.0]))), [3.0])


def test_while_loop_eager_and_traced():
    def counter(i, s):
        return i + 1, s + i

    i, s = static_nn.while_loop(
        lambda i, s: i < 5,
        counter,
        [paddle.to_tensor(0), paddle.to_tensor(0)])
    assert int(i) == 5 and int(s) == 10

    def f(n):
        i, s = static_nn.while_loop(
            lambda i, s: i < n,
            lambda i, s: (i + 1, s + i),
            [paddle.to_tensor(jnp.asarray(0)), paddle.to_tensor(jnp.asarray(0))])
        return s._value

    out = jax.jit(f)(jnp.asarray(5))
    assert int(out) == 10


def test_case_and_switch_case():
    x = paddle.to_tensor(np.array(3.0, dtype="float32"))
    out = static_nn.case(
        [(x > 5, lambda: x * 10), (x > 1, lambda: x * 2)],
        default=lambda: x)
    assert float(out) == 6.0
    out = static_nn.switch_case(
        paddle.to_tensor(1),
        {0: lambda: x * 0, 1: lambda: x + 1, 2: lambda: x * 2})
    assert float(out) == 4.0
    # indices beyond 1 must dispatch correctly (not collapse via bool())
    out = static_nn.switch_case(
        paddle.to_tensor(2),
        {0: lambda: x * 0, 1: lambda: x + 1, 2: lambda: x * 2})
    assert float(out) == 6.0


def test_fc_trains():
    x = paddle.to_tensor(np.random.rand(4, 3).astype("float32"),
                         stop_gradient=False)
    y = static_nn.fc(x, 8, activation="relu")
    assert y.shape == [4, 8]
    y.sum().backward()
    assert x.grad is not None
    # build-once semantics: unnamed calls create INDEPENDENT parameters
    # (stacked fc's are distinct layers, like the reference's Program)
    h = paddle.to_tensor(np.random.rand(4, 8).astype("float32"))
    outs = [static_nn.fc(h, 8) for _ in range(2)]
    assert not np.allclose(outs[0].numpy(), outs[1].numpy())
    # explicit name shares parameters
    a = static_nn.fc(h, 8, name="shared")
    b = static_nn.fc(h, 8, name="shared")
    np.testing.assert_allclose(a.numpy(), b.numpy())
    # created params are reachable through the default Program
    from paddle_tpu.static import default_main_program
    assert len(default_main_program().all_parameters()) > 0


def test_static_embedding_and_conv2d_helpers():
    ids = paddle.to_tensor(np.array([[1, 2], [3, 0]]))
    out = static_nn.embedding(ids, size=[10, 6])
    assert out.shape == [2, 2, 6]
    # named reuse returns identical values
    a = static_nn.embedding(ids, size=[10, 6], name="shared_emb")
    b = static_nn.embedding(ids, size=[10, 6], name="shared_emb")
    np.testing.assert_allclose(a.numpy(), b.numpy())
    x = paddle.to_tensor(np.random.RandomState(0)
                         .rand(2, 3, 8, 8).astype("float32"))
    y = static_nn.conv2d(x, num_filters=4, filter_size=3, padding=1,
                         act="relu")
    assert y.shape == [2, 4, 8, 8]
    assert (y.numpy() >= 0).all()
    from paddle_tpu.static import default_main_program
    assert len(default_main_program().all_parameters()) > 0


def test_download_shim(tmp_path, monkeypatch):
    from paddle_tpu.utils import download
    with pytest.raises(FileNotFoundError, match="no network access"):
        download.get_path_from_url("https://x.test/w.pdparams",
                                   str(tmp_path))
    f = tmp_path / "w.pdparams"
    f.write_bytes(b"weights")
    assert download.get_path_from_url("https://x.test/w.pdparams",
                                      str(tmp_path)) == str(f)
    import hashlib
    good = hashlib.md5(b"weights").hexdigest()
    assert download.get_path_from_url("https://x.test/w.pdparams",
                                      str(tmp_path), md5sum=good) == str(f)
    with pytest.raises(RuntimeError, match="md5"):
        download.get_path_from_url("https://x.test/w.pdparams",
                                   str(tmp_path), md5sum="0" * 32)
    # archives: extracted path returned (reference decompress behavior)
    import tarfile
    data_dir = tmp_path / "src" / "mydata"
    data_dir.mkdir(parents=True)
    (data_dir / "train.txt").write_text("x")
    tar = tmp_path / "mydata.tar.gz"
    with tarfile.open(tar, "w:gz") as t:
        t.add(str(data_dir), arcname="mydata")
    out = download.get_path_from_url("https://x.test/mydata.tar.gz",
                                     str(tmp_path))
    assert out == str(tmp_path / "mydata")
    assert (tmp_path / "mydata" / "train.txt").exists()
    # named conv2d with DIFFERENT config must not reuse the cached layer
    x = paddle.to_tensor(np.random.RandomState(1)
                         .rand(1, 3, 8, 8).astype("float32"))
    y1 = static_nn.conv2d(x, 4, 3, stride=1, padding=1, name="ck")
    y2 = static_nn.conv2d(x, 4, 3, stride=2, padding=1, name="ck")
    assert y1.shape == [1, 4, 8, 8] and y2.shape == [1, 4, 4, 4]


def test_box_coder_decode_axis0_with_var():
    priors = np.array([[0, 0, 10, 10], [10, 10, 30, 30],
                       [0, 0, 4, 4]], np.float32)
    var = np.full((3, 4), 0.5, np.float32)
    offs = np.zeros((3, 2, 4), np.float32)
    dec = vops.box_coder(paddle.to_tensor(priors), paddle.to_tensor(var),
                         paddle.to_tensor(offs),
                         code_type="decode_center_size", axis=0)
    # zero offsets decode to the priors themselves regardless of var
    for m in range(2):
        np.testing.assert_allclose(dec.numpy()[:, m], priors, atol=1e-5)


def test_sequence_pad_truncation_keeps_offsets():
    flat = paddle.to_tensor(np.arange(8, dtype="float32").reshape(8, 1))
    padded, _ = F.sequence_pad(flat, [5, 3], maxlen=3)
    # sequence 1 must be rows 5..7, not the tail of sequence 0
    np.testing.assert_allclose(padded.numpy()[1, :, 0], [5.0, 6.0, 7.0])
    np.testing.assert_allclose(padded.numpy()[0, :, 0], [0.0, 1.0, 2.0])


def test_cond_gradient_through_taken_branch():
    x = paddle.to_tensor(np.array([2.0], dtype="float32"),
                         stop_gradient=False)
    out = static_nn.cond(paddle.to_tensor(True), lambda: x * x,
                        lambda: x)
    out.backward()
    np.testing.assert_allclose(x.grad.numpy(), [4.0])


# ---------------------------------------------------------------- sequence ops

def test_sequence_mask():
    m = F.sequence_mask(paddle.to_tensor([2, 0, 3]), maxlen=4)
    np.testing.assert_array_equal(
        m.numpy(), [[1, 1, 0, 0], [0, 0, 0, 0], [1, 1, 1, 0]])


def test_sequence_pad_unpad_roundtrip():
    flat = paddle.to_tensor(np.arange(10, dtype="float32").reshape(5, 2))
    lengths = [2, 3]
    padded, ln = F.sequence_pad(flat, lengths, pad_value=-1.0)
    assert padded.shape == [2, 3, 2]
    np.testing.assert_allclose(padded.numpy()[0, 2], [-1.0, -1.0])
    back = F.sequence_unpad(padded, lengths)
    np.testing.assert_allclose(back.numpy(), flat.numpy())


def test_sequence_pool_variants():
    x = np.zeros((2, 3, 1), np.float32)
    x[0, :, 0] = [1, 2, 100]   # length 2 -> 100 is padding
    x[1, :, 0] = [4, 5, 6]     # length 3
    xt = paddle.to_tensor(x)
    ln = paddle.to_tensor([2, 3])
    np.testing.assert_allclose(
        F.sequence_pool(xt, ln, "sum").numpy()[:, 0], [3.0, 15.0])
    np.testing.assert_allclose(
        F.sequence_pool(xt, ln, "mean").numpy()[:, 0], [1.5, 5.0])
    np.testing.assert_allclose(
        F.sequence_pool(xt, ln, "max").numpy()[:, 0], [2.0, 6.0])
    np.testing.assert_allclose(
        F.sequence_pool(xt, ln, "last").numpy()[:, 0], [2.0, 6.0])
    np.testing.assert_allclose(
        F.sequence_pool(xt, ln, "first").numpy()[:, 0], [1.0, 4.0])


def test_sequence_softmax_masks_padding():
    x = paddle.to_tensor(np.ones((1, 4, 1), np.float32))
    p = F.sequence_softmax(x, paddle.to_tensor([2]))
    np.testing.assert_allclose(p.numpy()[0, :, 0], [0.5, 0.5, 0.0, 0.0],
                               atol=1e-6)


def test_sequence_pool_grad_respects_mask():
    x = paddle.to_tensor(np.ones((1, 3, 1), np.float32),
                         stop_gradient=False)
    out = F.sequence_pool(x, paddle.to_tensor([2]), "sum")
    out.backward()
    np.testing.assert_allclose(x.grad.numpy()[0, :, 0], [1.0, 1.0, 0.0])


def test_sequence_expand():
    x = paddle.to_tensor(np.array([[1.0], [2.0]], dtype="float32"))
    out = F.sequence_expand(x, [2, 3])
    np.testing.assert_allclose(out.numpy()[:, 0], [1, 1, 2, 2, 2])


def test_sequence_reverse():
    x = np.zeros((2, 4, 1), np.float32)
    x[0, :, 0] = [1, 2, 3, 99]   # len 3: 99 is padding
    x[1, :, 0] = [4, 5, 6, 7]    # len 4
    out = F.sequence_reverse(paddle.to_tensor(x),
                             paddle.to_tensor([3, 4])).numpy()
    np.testing.assert_allclose(out[0, :, 0], [3, 2, 1, 99])
    np.testing.assert_allclose(out[1, :, 0], [7, 6, 5, 4])


def test_sequence_concat():
    a = np.zeros((2, 3, 1), np.float32)
    a[0, :2, 0] = [1, 2]
    a[1, :3, 0] = [7, 8, 9]
    b = np.zeros((2, 2, 1), np.float32)
    b[0, :1, 0] = [3]
    b[1, :2, 0] = [10, 11]
    out, lens = F.sequence_concat([paddle.to_tensor(a),
                                   paddle.to_tensor(b)],
                                  [[2, 3], [1, 2]])
    np.testing.assert_array_equal(lens.numpy(), [3, 5])
    np.testing.assert_allclose(out.numpy()[0, :3, 0], [1, 2, 3])
    np.testing.assert_allclose(out.numpy()[1, :5, 0], [7, 8, 9, 10, 11])
    np.testing.assert_allclose(out.numpy()[0, 3:, 0], 0.0)


def test_sequence_slice():
    x = np.arange(8, dtype=np.float32).reshape(2, 4, 1)
    out, lens = F.sequence_slice(paddle.to_tensor(x), [4, 4],
                                 offset=[1, 0], length=[2, 3])
    np.testing.assert_array_equal(lens.numpy(), [2, 3])
    np.testing.assert_allclose(out.numpy()[0, :, 0], [1, 2, 0])
    np.testing.assert_allclose(out.numpy()[1, :, 0], [4, 5, 6])
    with pytest.raises(ValueError, match="exceeds"):
        F.sequence_slice(paddle.to_tensor(x), [4, 4],
                         offset=[3, 0], length=[3, 1])
    with pytest.raises(ValueError, match="non-negative"):
        F.sequence_slice(paddle.to_tensor(x), [4, 4],
                         offset=[-1, 0], length=[2, 3])


def test_sequence_concat_validates_lengths():
    a = paddle.to_tensor(np.zeros((1, 2, 1), np.float32))
    b = paddle.to_tensor(np.zeros((1, 2, 1), np.float32))
    with pytest.raises(ValueError, match="padded width"):
        F.sequence_concat([a, b], [[3], [1]])  # 3 > a's width 2


# ---------------------------------------------------------------- detection

def test_box_iou():
    a = paddle.to_tensor(np.array([[0, 0, 2, 2]], dtype="float32"))
    b = paddle.to_tensor(np.array([[1, 1, 3, 3], [4, 4, 5, 5]],
                                  dtype="float32"))
    iou = vops.box_iou(a, b).numpy()
    np.testing.assert_allclose(iou[0], [1 / 7, 0.0], atol=1e-6)


def test_nms_greedy():
    boxes = paddle.to_tensor(np.array(
        [[0, 0, 10, 10], [1, 1, 11, 11], [20, 20, 30, 30]],
        dtype="float32"))
    scores = paddle.to_tensor(np.array([0.9, 0.8, 0.7], dtype="float32"))
    keep = vops.nms(boxes, scores, iou_threshold=0.5).numpy()
    np.testing.assert_array_equal(keep, [0, 2])


def test_nms_multiclass_no_cross_category_suppression():
    boxes = paddle.to_tensor(np.array(
        [[0, 0, 10, 10], [1, 1, 11, 11]], dtype="float32"))
    scores = paddle.to_tensor(np.array([0.9, 0.8], dtype="float32"))
    cats = paddle.to_tensor(np.array([0, 1]))
    keep = vops.nms(boxes, scores, iou_threshold=0.5, category_idxs=cats,
                    categories=[0, 1]).numpy()
    assert set(keep.tolist()) == {0, 1}


def test_box_coder_roundtrip():
    priors = np.array([[0, 0, 10, 10], [10, 10, 30, 30]], np.float32)
    targets = np.array([[1, 1, 9, 11]], np.float32)
    enc = vops.box_coder(paddle.to_tensor(priors), None,
                         paddle.to_tensor(targets),
                         code_type="encode_center_size")
    dec = vops.box_coder(paddle.to_tensor(priors), None,
                         paddle.to_tensor(enc.numpy()),
                         code_type="decode_center_size", axis=1)
    np.testing.assert_allclose(dec.numpy()[0, 0], targets[0], atol=1e-4)
    np.testing.assert_allclose(dec.numpy()[0, 1], targets[0], atol=1e-4)


def test_yolo_box_shapes_and_range():
    n, na, c, h, w = 1, 3, 2, 4, 4
    x = paddle.to_tensor(
        np.random.RandomState(0).randn(n, na * (5 + c), h, w)
        .astype("float32"))
    img = paddle.to_tensor(np.array([[128, 128]], dtype="int32"))
    boxes, scores = vops.yolo_box(x, img, anchors=[10, 13, 16, 30, 33, 23],
                                  class_num=c, downsample_ratio=32)
    assert boxes.shape == [n, h * w * na, 4]
    assert scores.shape == [n, h * w * na, c]
    b = boxes.numpy()
    assert (b >= 0).all() and (b <= 127).all()


def test_roi_align_constant_map():
    x = paddle.to_tensor(np.full((1, 1, 8, 8), 5.0, np.float32))
    boxes = paddle.to_tensor(np.array([[0, 0, 4, 4]], dtype="float32"))
    out = vops.roi_align(x, boxes, [1], output_size=2, spatial_scale=1.0)
    assert out.shape == [1, 1, 2, 2]
    np.testing.assert_allclose(out.numpy(), np.full((1, 1, 2, 2), 5.0),
                               atol=1e-5)


def test_roi_align_grad_flows():
    x = paddle.to_tensor(np.random.rand(1, 2, 8, 8).astype("float32"),
                         stop_gradient=False)
    boxes = paddle.to_tensor(np.array([[1, 1, 6, 6]], dtype="float32"))
    out = vops.roi_align(x, boxes, [1], output_size=2)
    out.sum().backward()
    assert x.grad is not None and np.abs(x.grad.numpy()).sum() > 0


def test_roi_pool_max():
    x = np.zeros((1, 1, 4, 4), np.float32)
    x[0, 0, 0, 0] = 9.0
    out = vops.roi_pool(paddle.to_tensor(x),
                        paddle.to_tensor(np.array([[0, 0, 4, 4]],
                                                  dtype="float32")),
                        [1], output_size=1)
    assert float(out.numpy().max()) == pytest.approx(9.0, abs=1e-5)
    assert out.shape == [1, 1, 1, 1]


def test_prior_box():
    feat = paddle.to_tensor(np.zeros((1, 8, 2, 2), np.float32))
    img = paddle.to_tensor(np.zeros((1, 3, 64, 64), np.float32))
    boxes, var = vops.prior_box(feat, img, min_sizes=[16.0],
                                aspect_ratios=[1.0, 2.0], clip=True)
    assert boxes.shape == [2, 2, 2, 4]
    b = boxes.numpy()
    assert (b >= 0).all() and (b <= 1).all()
    np.testing.assert_allclose(var.numpy()[0, 0, 0], [0.1, 0.1, 0.2, 0.2])


class TestProgramSurgeryFailsLoudly:
    """Reference ProgramDesc surgery has no traced-IR counterpart; the
    shim must raise at the call site (with the tpu-native alternative in
    the message), never silently no-op (VERDICT r1 weak #8)."""

    def test_prune_raises_with_alternative(self):
        from paddle_tpu.static import Program, UnsupportedProgramSurgery
        p = Program()
        with pytest.raises(UnsupportedProgramSurgery, match="jit.save"):
            p.prune(targets=[])

    def test_desc_block_listvars_raise(self):
        from paddle_tpu.static import Program, UnsupportedProgramSurgery
        p = Program()
        with pytest.raises(UnsupportedProgramSurgery):
            _ = p.desc
        with pytest.raises(UnsupportedProgramSurgery):
            p.block(0)
        with pytest.raises(UnsupportedProgramSurgery):
            p.list_vars()

    def test_supported_surface_still_works(self):
        from paddle_tpu.static import Program
        p = Program()
        assert p.num_blocks == 1
        assert p.current_block() is p.global_block()
        assert p.clone(for_test=True) is not p
        assert "Program(" in p.to_string()
        # it is a NotImplementedError subclass: old except clauses catch it
        from paddle_tpu.static import UnsupportedProgramSurgery
        assert issubclass(UnsupportedProgramSurgery, NotImplementedError)
