"""Native MultiSlot DataFeed / InMemoryDataset tests.

Parity model: reference framework/data_feed.cc MultiSlot parsing +
data_set.h load/shuffle semantics; python fallback must agree with the
native parse bit-for-bit.
"""
import numpy as np
import pytest

from paddle_tpu.distributed.fleet.dataset import (InMemoryDataset,
                                                  QueueDataset)
from paddle_tpu.native import datafeed


requires_native = pytest.mark.skipif(datafeed() is None,
                                     reason="no C++ toolchain")


def _write_multislot(path, n_rec, seed=0):
    """3 slots: click label (1 id), sparse ids (var len), dense 4-dim."""
    rng = np.random.RandomState(seed)
    lines = []
    for _ in range(n_rec):
        click = rng.randint(0, 2)
        n_ids = rng.randint(1, 6)
        ids = rng.randint(0, 10**9, size=n_ids)
        dense = rng.rand(4)
        lines.append(
            f"1 {click} {n_ids} " + " ".join(map(str, ids)) + " 4 " +
            " ".join(f"{v:.6f}" for v in dense))
    path.write_text("\n".join(lines) + "\n")


def _make_ds(files, bs=4):
    ds = InMemoryDataset()
    ds.set_batch_size(bs)
    ds.set_use_var(["click", "ids",
                    {"name": "dense", "is_dense": True, "dim": 4}])
    ds.set_filelist([str(f) for f in files])
    return ds


@requires_native
def test_load_and_batch(tmp_path):
    f = tmp_path / "part-0.txt"
    _write_multislot(f, 10)
    ds = _make_ds([f], bs=4)
    n = ds.load_into_memory()
    assert n == 10
    assert ds.get_memory_data_size() == 10
    batches = list(ds)
    assert len(batches) == 3            # 4+4+2
    b = batches[0]
    ids, lod = b["ids"]
    assert lod[0] == 0 and lod[-1] == ids.size and len(lod) == 5
    assert b["dense"].shape == (4, 4)
    click_ids, click_lod = b["click"]
    assert click_ids.size == 4          # one label per record
    # last (ragged) batch
    assert batches[-1]["dense"].shape[0] == 2


@requires_native
def test_native_matches_python_parser(tmp_path):
    f = tmp_path / "data.txt"
    _write_multislot(f, 23, seed=3)
    ds_n = _make_ds([f], bs=23)
    ds_n.load_into_memory()
    assert ds_n._h is not None
    ds_p = _make_ds([f], bs=23)
    ds_p._load_python()
    bn = next(iter(ds_n))
    bp = ds_p._batch_at(0, 23)
    for k in ("click", "ids"):
        np.testing.assert_array_equal(bn[k][0], bp[k][0])
        np.testing.assert_array_equal(bn[k][1], bp[k][1])
    np.testing.assert_allclose(bn["dense"], bp["dense"], rtol=1e-6)


@requires_native
def test_multifile_parallel_load(tmp_path):
    files = []
    for i in range(6):
        f = tmp_path / f"part-{i}.txt"
        _write_multislot(f, 50, seed=i)
        files.append(f)
    ds = _make_ds(files, bs=64)
    assert ds.load_into_memory() == 300
    total = sum(b["dense"].shape[0] for b in ds)
    assert total == 300


@requires_native
def test_local_shuffle_permutes(tmp_path):
    f = tmp_path / "d.txt"
    _write_multislot(f, 40, seed=5)
    ds = _make_ds([f], bs=40)
    ds.load_into_memory()
    before = next(iter(ds))["dense"].copy()
    ds.local_shuffle(seed=7)
    after = next(iter(ds))["dense"]
    assert not np.array_equal(before, after)
    np.testing.assert_allclose(np.sort(before.ravel()),
                               np.sort(after.ravel()), rtol=1e-6)


@requires_native
def test_partition_disjoint_cover(tmp_path):
    f = tmp_path / "d.txt"
    _write_multislot(f, 30, seed=9)
    seen = []
    for rank in range(3):
        ds = _make_ds([f], bs=30)
        ds.load_into_memory()
        ds.local_shuffle(seed=1)
        ds._lib.dfd_partition(ds._h, rank, 3)
        assert ds.get_shuffle_data_size() == 10
        seen.append(next(iter(ds))["dense"])
    allrows = np.concatenate(seen, 0)
    ref = _make_ds([f], bs=30)
    ref.load_into_memory()
    full = next(iter(ref))["dense"]
    np.testing.assert_allclose(np.sort(allrows.ravel()),
                               np.sort(full.ravel()), rtol=1e-6)


@requires_native
def test_malformed_lines_dropped(tmp_path):
    f = tmp_path / "bad.txt"
    f.write_text("1 1 2 5 6 4 0.1 0.2 0.3 0.4\n"
                 "garbage line\n"
                 "1 0 1 7 4 0.5 0.6 0.7 0.8\n")
    ds = _make_ds([f])
    assert ds.load_into_memory() == 2


@requires_native
def test_release_memory(tmp_path):
    f = tmp_path / "d.txt"
    _write_multislot(f, 10)
    ds = _make_ds([f])
    ds.load_into_memory()
    ds.release_memory()
    assert ds.get_memory_data_size() == 0


@requires_native
def test_queue_dataset_streams(tmp_path):
    files = []
    for i in range(3):
        f = tmp_path / f"q-{i}.txt"
        _write_multislot(f, 7, seed=i)
        files.append(f)
    ds = QueueDataset()
    ds.set_batch_size(5)
    ds.set_use_var(["click", "ids",
                    {"name": "dense", "is_dense": True, "dim": 4}])
    ds.set_filelist([str(f) for f in files])
    total = sum(b["dense"].shape[0] for b in ds)
    assert total == 21


def test_python_fallback_load(tmp_path):
    f = tmp_path / "d.txt"
    _write_multislot(f, 8)
    ds = _make_ds([f], bs=3)
    ds._load_python()
    assert len(ds._py_records) == 8
    batches = [ds._batch_at(s, 3) for s in (0, 3, 6)]
    assert batches[-1]["dense"].shape[0] == 2


@requires_native
def test_truncated_line_does_not_eat_neighbor(tmp_path):
    """A record declaring more values than its line holds must be dropped
    alone — the parser must not consume the next line's tokens."""
    f = tmp_path / "trunc.txt"
    f.write_text("1 1 3 5 6\n"                      # declares 3 ids, has 2
                 "1 0 1 7 4 0.5 0.6 0.7 0.8\n")     # good record
    ds = _make_ds([f])
    assert ds.load_into_memory() == 1
    b = next(iter(ds))
    ids, lod = b["ids"]
    np.testing.assert_array_equal(ids, [7])
    np.testing.assert_allclose(b["dense"][0], [0.5, 0.6, 0.7, 0.8],
                               rtol=1e-6)


@requires_native
def test_global_shuffle_recallable_per_epoch(tmp_path):
    """Repeated global_shuffle must re-partition the FULL set each time,
    not shrink the view (reference GlobalShuffle redistributes fully)."""
    f = tmp_path / "d.txt"
    _write_multislot(f, 24, seed=11)
    ds = _make_ds([f], bs=24)
    ds.load_into_memory()
    for epoch in range(3):
        ds.local_shuffle(seed=epoch)
        ds._lib.dfd_partition(ds._h, 0, 2)
        assert ds.get_shuffle_data_size() == 12


def test_data_generator_to_datafeed_roundtrip(tmp_path):
    """fleet.DataGenerator authors MultiSlot text that the datafeed
    parses back into identical batches (parity: the reference's
    data_generator -> MultiSlotDataFeed pipe)."""
    import io

    from paddle_tpu.distributed.fleet import MultiSlotDataGenerator

    class Gen(MultiSlotDataGenerator):
        def generate_sample(self, line):
            def it():
                tok = [int(x) for x in line.split()]
                yield [("click", [tok[0]]), ("ids", tok[1:4]),
                       ("dense", [v / 10.0 for v in tok[4:8]])]
            return it

    raw = tmp_path / "raw.txt"
    rows = [" ".join(str((7 * i + j) % 50) for j in range(8))
            for i in range(10)]
    raw.write_text("\n".join(rows) + "\n")
    buf = io.StringIO()
    g = Gen()
    n = g.run_from_file(str(raw), out=buf)
    assert n == 10
    out = tmp_path / "part-0.txt"
    out.write_text(buf.getvalue())

    ds = _make_ds([out], bs=10)
    assert ds.load_into_memory() == 10
    b = next(iter(ds))
    ids, lod = b["ids"]
    assert ids.size == 30 and list(lod) == list(range(0, 31, 3))
    first = [int(x) for x in rows[0].split()]
    np.testing.assert_array_equal(ids[:3], first[1:4])
    np.testing.assert_allclose(b["dense"][0],
                               [v / 10.0 for v in first[4:8]], rtol=1e-6)


def test_data_generator_validation_and_batch_hook():
    import io

    from paddle_tpu.distributed.fleet import (DataGenerator,
                                              MultiSlotDataGenerator)

    class Bad(MultiSlotDataGenerator):
        def generate_sample(self, line):
            def it():
                yield [("s", ["not-a-number"])]
            return it

    with pytest.raises(ValueError, match="int/float"):
        Bad().run_from_memory(out=io.StringIO())

    class Batched(DataGenerator):
        def generate_sample(self, line):
            def it():
                for i in range(5):
                    yield [("v", [i])]
            return it

        def generate_batch(self, samples):
            def it():
                # batch hook sees batch_size_-sized groups
                for s in samples:
                    yield [("v", [s[0][1][0] * 2])]
            return it

    buf = io.StringIO()
    g = Batched()
    g.set_batch(2)
    assert g.run_from_memory(out=buf) == 5
    lines = buf.getvalue().strip().split("\n")
    assert lines[0] == "1 0" and lines[1] == "1 2" and lines[4] == "1 8"
