"""Continuous-batching generative serving tests (ISSUE 8 tentpole).

Acceptance contracts, tested directly:
- paged decode matches single-stream ``generate()`` token-for-token;
- concurrent mixed-length streams are bit-identical to the same
  requests run one at a time (slot math is per-sequence);
- eviction (block-pool exhaustion) + re-admission is BIT-IDENTICAL to
  uninterrupted decode, for greedy AND seeded sampling (the RNG stream
  position survives eviction), with ``check_replay`` asserting every
  replayed token live;
- block-pool accounting is exact: no leaked blocks after N
  mixed-length streams, trash block never handed out;
- steady-state decode performs ZERO retraces (``num_compiles`` delta
  is 0 after warmup, for any mix of live slots);
- typed shed semantics: ``ServerOverloaded`` at the waiting cap,
  ``RequestTimeout`` for a request whose deadline passes while waiting;
- the scan_layers stacked decoder raises the typed
  ``KVCacheUnsupportedError`` naming the workaround.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import (GenerationServer, RequestTimeout,
                                  ServerClosed, ServerOverloaded)
from paddle_tpu.text.models import LlamaForCausalLM, llama_tiny
from paddle_tpu.text.models.llama import KVCacheUnsupportedError


@pytest.fixture(scope="module")
def lm():
    paddle.seed(0)
    cfg = llama_tiny(vocab_size=64, hidden_size=32, intermediate_size=64,
                     num_hidden_layers=2, num_attention_heads=4,
                     num_key_value_heads=2, max_position_embeddings=64)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def server(lm):
    """Ample pool: no eviction possible (4 slots x full-length fit)."""
    srv = GenerationServer(lm, num_slots=4, block_size=4,
                           max_model_len=32, check_replay=True,
                           request_timeout_s=120.0)
    srv.start()
    yield srv
    srv.stop()


def _prompts(seed=0, lens=(5, 9, 3, 12)):
    rng = np.random.RandomState(seed)
    return [rng.randint(1, 64, (l,)).astype("int32") for l in lens]


# -- correctness vs the single-stream reference ----------------------

def test_single_stream_matches_generate_greedy(lm, server):
    for p in _prompts():
        ref = lm.generate(paddle.to_tensor(p[None, :]),
                          max_new_tokens=6).numpy()[0, len(p):]
        got = server.submit(p, max_new_tokens=6).result(timeout=120)
        assert got == ref.tolist()


def test_concurrent_mixed_lengths_match_sequential(server):
    prompts = _prompts(seed=3)
    base = [server.submit(p, max_new_tokens=4 + i).result(timeout=120)
            for i, p in enumerate(prompts)]
    streams = [server.submit(p, max_new_tokens=4 + i)
               for i, p in enumerate(prompts)]
    conc = [s.result(timeout=120) for s in streams]
    assert conc == base
    assert [len(o) for o in conc] == [4, 5, 6, 7]


def test_eos_ends_stream_early(lm, server):
    p = _prompts(seed=4, lens=(6,))[0]
    first = server.submit(p, max_new_tokens=1).result(timeout=120)[0]
    out = server.submit(p, max_new_tokens=8,
                        eos_token_id=first).result(timeout=120)
    assert out == [first]          # eos emitted, stream ends, slot freed
    st = server.stats()
    assert st["active"] == 0


def test_stream_iterates_incrementally(server):
    p = _prompts(seed=5, lens=(4,))[0]
    stream = server.submit(p, max_new_tokens=5)
    seen = [tok for tok in stream]
    assert seen == stream.tokens
    assert len(seen) == 5
    assert stream.finish_reason == "length"


def test_temperature_zero_is_exact_greedy(server):
    p = _prompts(seed=6, lens=(5,))[0]
    greedy = server.submit(p, max_new_tokens=5).result(timeout=120)
    cold = server.submit(p, max_new_tokens=5, do_sample=True,
                         temperature=0.0, top_k=3,
                         seed=7).result(timeout=120)
    assert cold == greedy


def test_sampling_deterministic_per_seed(server):
    p = _prompts(seed=7, lens=(6,))[0]
    a = server.submit(p, max_new_tokens=6, do_sample=True,
                      temperature=0.8, top_k=8, seed=42).result(timeout=120)
    b = server.submit(p, max_new_tokens=6, do_sample=True,
                      temperature=0.8, top_k=8, seed=42).result(timeout=120)
    c = server.submit(p, max_new_tokens=6, do_sample=True,
                      temperature=0.8, top_k=8, seed=43).result(timeout=120)
    assert a == b
    assert a != c      # 6 draws over 8 candidates: collision ~8^-6


# -- zero-retrace + accounting contracts ------------------------------

def test_steady_state_decode_never_retraces(server):
    # warmup happened at start() + earlier tests; from here on, ANY mix
    # of prompt lengths within the prewarmed buckets and any number of
    # live slots must reuse the same executables
    n = server.num_compiles()
    streams = [server.submit(p, max_new_tokens=3 + i, do_sample=i % 2,
                             temperature=0.9, seed=i)
               for i, p in enumerate(_prompts(seed=8, lens=(4, 7, 11, 2)))]
    for s in streams:
        s.result(timeout=120)
    assert server.num_compiles() == n
    st = server.stats()
    assert st["traffic_compiles"] == 0
    assert all(v["cause"] == "prewarm"
               for v in st["bucket_compiles"].values())


def test_block_accounting_exact_after_mixed_streams(server):
    st0 = server.stats()
    streams = [server.submit(p, max_new_tokens=2 + 3 * i)
               for i, p in enumerate(_prompts(seed=9, lens=(3, 8, 13, 5)))]
    for s in streams:
        s.result(timeout=120)
    st = server.stats()
    assert st["free_blocks"] == st["total_blocks"]
    assert st["allocated_blocks"] == 0
    assert st["active"] == 0 and st["waiting"] == 0
    emitted = st["tokens_generated"] - st0["tokens_generated"]
    assert emitted == 2 + 5 + 8 + 11


# -- eviction + re-admission bit-identity -----------------------------

@pytest.fixture(scope="module")
def scarce(lm):
    """13 allocatable blocks for 4 sequences that can each grow to 6:
    concurrent traffic MUST evict."""
    srv = GenerationServer(lm, num_slots=4, block_size=4,
                           max_model_len=24, num_blocks=14,
                           check_replay=True, request_timeout_s=120.0)
    srv.start()
    yield srv
    srv.stop()


def _run_scarce(srv, do_sample, concurrent, prio=(0, 1, 2, 3)):
    prompts = _prompts(seed=1, lens=(6, 10, 4, 8))
    kw = dict(max_new_tokens=12, do_sample=do_sample, temperature=0.9,
              top_k=8)
    if concurrent:
        streams = [srv.submit(p, seed=100 + i, priority=prio[i], **kw)
                   for i, p in enumerate(prompts)]
        return [s.result(timeout=120) for s in streams]
    return [srv.submit(p, seed=100 + i, **kw).result(timeout=120)
            for i, p in enumerate(prompts)]


def test_eviction_readmission_bit_identical_greedy(scarce):
    base = _run_scarce(scarce, do_sample=False, concurrent=False)
    ev0 = scarce.stats()["evicted"]
    conc = _run_scarce(scarce, do_sample=False, concurrent=True)
    st = scarce.stats()
    assert st["evicted"] > ev0, \
        "pool was never exhausted — eviction untested"
    assert st["replay_steps"] > 0
    # check_replay=True additionally asserted every replayed token
    # inside the scheduler; this is the end-to-end stream equality
    assert conc == base


def test_eviction_readmission_bit_identical_sampling(scarce):
    """Seeded sampling across eviction: the RNG key of token j is
    fold_in(request_key, j-1) — a pure function of stream position —
    so the resumed stream must reproduce the uninterrupted draw
    exactly."""
    base = _run_scarce(scarce, do_sample=True, concurrent=False)
    ev0 = scarce.stats()["evicted"]
    conc = _run_scarce(scarce, do_sample=True, concurrent=True)
    st = scarce.stats()
    assert st["evicted"] > ev0
    assert conc == base


def test_no_leaked_blocks_after_evictions(scarce):
    st = scarce.stats()
    assert st["free_blocks"] == st["total_blocks"]
    assert st["allocated_blocks"] == 0
    assert st["readmitted"] >= st["evicted"] - st["shed_timeout"]


def test_eviction_emits_flight_events(scarce):
    from paddle_tpu.observability import flight_recorder as flight
    if scarce.stats()["evicted"] == 0:   # e.g. run in isolation
        _run_scarce(scarce, do_sample=False, concurrent=True)
    kinds = {e.get("kind") for e in flight.events()}
    assert "serve.admit" in kinds
    assert "serve.evict" in kinds
    assert "serve.stream_end" in kinds
    ev = [e for e in flight.events() if e.get("kind") == "serve.evict"]
    assert all(e.get("reason") == "pool_exhausted" for e in ev)


def test_postmortem_classifies_pool_exhaustion_bad():
    """tools/postmortem.py autopsies a pool-exhaustion shed: eviction
    and shed events sort the process to the front of the report
    (first divergence first), admit/stream_end render as context."""
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import postmortem
    assert postmortem._is_bad({"kind": "serve.evict"})
    assert postmortem._is_bad({"kind": "serve.shed"})
    assert not postmortem._is_bad({"kind": "serve.admit"})
    assert not postmortem._is_bad({"kind": "serve.stream_end"})
    assert not postmortem._is_bad({"kind": "serve.decode"})
    # the generation scheduler's heartbeats feed the stall watchdog
    from paddle_tpu.observability.flight_recorder import _PROGRESS_KINDS
    assert {"serve.decode", "serve.admit"} <= set(_PROGRESS_KINDS)


# -- typed shed semantics ---------------------------------------------

def test_overload_sheds_typed(lm):
    srv = GenerationServer(lm, num_slots=1, block_size=4,
                           max_model_len=16, prompt_buckets=[8],
                           max_waiting=2, request_timeout_s=60.0)
    # not started: submissions must fail closed, not queue silently
    with pytest.raises(ServerClosed):
        srv.submit(np.ones(4, np.int32), max_new_tokens=2)
    srv.start()
    try:
        p = _prompts(seed=11, lens=(4,))[0]
        first = srv.submit(p, max_new_tokens=8)
        next(iter(first))      # admitted: the only slot is now busy
        waiters = [srv.submit(p, max_new_tokens=8) for _ in range(2)]
        # waiting queue at its cap of 2 -> typed shed
        with pytest.raises(ServerOverloaded, match="back off"):
            srv.submit(p, max_new_tokens=8)
        assert srv.stats()["shed_overload"] >= 1
        for s in [first] + waiters:
            s.result(timeout=120)
    finally:
        srv.stop()


def test_waiting_deadline_times_out_typed(lm):
    srv = GenerationServer(lm, num_slots=1, block_size=4,
                           max_model_len=32, prompt_buckets=[8],
                           request_timeout_s=60.0)
    srv.start()
    try:
        p = _prompts(seed=12, lens=(4,))[0]
        long = srv.submit(p, max_new_tokens=24)      # hogs the only slot
        quick = srv.submit(p, max_new_tokens=4, timeout_s=0.0)
        with pytest.raises(RequestTimeout, match="deadline"):
            quick.result(timeout=120)
        assert long.result(timeout=120)              # victim unaffected
        assert srv.stats()["shed_timeout"] == 1
    finally:
        srv.stop()


def test_submit_validation(lm, server):
    with pytest.raises(ValueError, match="empty prompt"):
        server.submit(np.zeros((0,), np.int32))
    with pytest.raises(ValueError, match="max_model_len"):
        server.submit(np.ones(30, np.int32), max_new_tokens=30)


def test_scan_layers_raises_typed_error():
    paddle.seed(1)
    cfg = llama_tiny(vocab_size=32, hidden_size=32, intermediate_size=64,
                     num_hidden_layers=2, num_attention_heads=4,
                     num_key_value_heads=2, max_position_embeddings=32,
                     scan_layers=True)
    m = LlamaForCausalLM(cfg)
    m.eval()
    with pytest.raises(KVCacheUnsupportedError,
                       match="scan_layers=False"):
        GenerationServer(m, num_slots=1, block_size=4)
    # and the model-level cache entry points agree (typed subclass of
    # NotImplementedError, message pins the workaround)
    assert issubclass(KVCacheUnsupportedError, NotImplementedError)
    with pytest.raises(KVCacheUnsupportedError,
                       match="scan_layers=False"):
        m.init_paged_cache(4, 4)
    with pytest.raises(NotImplementedError, match="scan_layers=False"):
        m.model(paddle.to_tensor(np.ones((1, 2), np.int32)),
                caches=[None, None])


# -- ISSUE 18 satellites: typed stop/drain admission + deadline epoch --

def test_replay_drain_stop_admission_lifecycle_typed(lm):
    """ISSUE 18 satellites on ONE server (compiles dominate on this
    1-core box), in lifecycle order:

    1. replay_tokens — the gateway failover primitive at the server
       boundary: a submit carrying ``replay_tokens`` re-prefills,
       replays through the normal decode path WITHOUT re-emitting, and
       continues the stream token-identically (greedy AND seeded
       sampling); ``len(replay) >= max_new_tokens`` is a ValueError.
    2. drain_begin — live sequences run to completion, NEW admission
       raises typed ServerDraining and bumps the shed counter.
    3. stop — submit after stop() used to check ``_running`` OUTSIDE
       the scheduler lock, so a submit racing stop could enqueue a
       stream that never starts and hang the caller until its
       deadline.  The check now lives under the lock: stopped server
       => typed ServerClosed, immediately."""
    import time
    from paddle_tpu.inference import ServerDraining
    srv = GenerationServer(lm, num_slots=2, block_size=4,
                           max_model_len=32, max_prefill_batch=1,
                           check_replay=True, request_timeout_s=60.0)
    srv.start()
    p = _prompts(seed=14, lens=(6,))[0]
    for kw in (dict(max_new_tokens=12),
               dict(max_new_tokens=12, do_sample=True,
                    temperature=0.9, top_k=8)):
        full = srv.submit(p, seed=321, **kw).result(timeout=60)
        resumed = srv.submit(p, seed=321, replay_tokens=full[:5],
                             **kw).result(timeout=60)
        assert resumed == full[5:], "replay re-emitted or diverged"
    with pytest.raises(ValueError, match="replay"):
        srv.submit(p, max_new_tokens=4, replay_tokens=[1, 2, 3, 4])

    live = srv.submit(p, max_new_tokens=8)       # admitted pre-drain
    srv.drain_begin()
    assert srv.draining and srv.stats()["draining"]
    with pytest.raises(ServerDraining):
        srv.submit(p, max_new_tokens=4)
    assert srv.stats()["shed_draining"] == 1
    # live sequences run to completion; only NEW admission closes
    assert len(live.result(timeout=60)) == 8

    srv.stop()
    t0 = time.monotonic()
    with pytest.raises(ServerClosed):
        srv.submit(np.ones(4, np.int32), max_new_tokens=4)
    assert time.monotonic() - t0 < 5.0, \
        "submit-after-stop blocked instead of failing typed"


def test_submit_stop_race_no_hung_streams(lm):
    """Hammer the submit/stop race: every submit must either raise a
    typed error or return a stream that terminates."""
    import threading
    import time
    srv = GenerationServer(lm, num_slots=2, block_size=4,
                           max_model_len=32, max_prefill_batch=1,
                           request_timeout_s=60.0)
    srv.start()
    streams, errors = [], []

    def spam():
        p = np.ones(4, np.int32)
        for _ in range(200):
            try:
                streams.append(srv.submit(p, max_new_tokens=2))
            except ServerClosed:
                errors.append(1)

    t = threading.Thread(target=spam)
    t.start()
    time.sleep(0.05)
    srv.stop()
    t.join(timeout=30)
    assert not t.is_alive()
    for s in streams:       # accepted => must terminate, never hang
        try:
            s.result(timeout=30)
        except (ServerClosed, RequestTimeout):
            pass


def test_eviction_deadline_epoch_is_submit_time(lm):
    """ISSUE 18 satellite pin: time spent evicted-awaiting-readmission
    counts against the ORIGINAL deadline exactly once — re-admission
    must not re-anchor it.  Sampled live: every sequence observed
    mid-run (including ones that have been evicted) carries
    ``deadline == t_submit + timeout_s`` to within clock noise."""
    import time
    srv = GenerationServer(lm, num_slots=4, block_size=4,
                           max_model_len=24, num_blocks=14,
                           check_replay=True, request_timeout_s=120.0)
    srv.start()
    try:
        T = 77.0
        prompts = _prompts(seed=1, lens=(6, 10, 4, 8))
        streams = [srv.submit(p, seed=100 + i, max_new_tokens=12,
                              timeout_s=T)
                   for i, p in enumerate(prompts)]
        saw_evicted = False
        deadline = time.monotonic() + 60
        while any(s.finish_reason is None and s._exc is None
                  for s in streams):
            assert time.monotonic() < deadline
            with srv._lock:
                seqs = list(srv._active.values()) + list(srv._waiting)
            for seq in seqs:
                saw_evicted = saw_evicted or seq.evictions > 0
                assert abs(seq.deadline - (seq.t_submit + T)) < 0.25, \
                    "deadline drifted from the submit epoch"
            # coarse sampling: a tighter loop steals the 1-core GIL
            # from the scheduler and doubles the test's wall time
            time.sleep(0.002)
        assert srv.stats()["evicted"] > 0, \
            "pool was never exhausted — eviction untested"
        assert saw_evicted, "never sampled an evicted-and-waiting seq"
        for s in streams:
            s.result(timeout=60)
    finally:
        srv.stop()
