"""Native C++ sparse-table core tests (paddle_tpu/native/ps_core.cc).

Parity model: reference distributed/table/common_sparse_table tests —
lazy row init, optimizer update semantics vs a numpy oracle, geo delta
push, save/load, concurrency.
"""
import os
import threading

import numpy as np
import pytest

from paddle_tpu.distributed.fleet.ps import SparseTable
from paddle_tpu.native import ps_core


requires_native = pytest.mark.skipif(ps_core() is None,
                                     reason="no C++ toolchain")


@requires_native
def test_native_backend_selected():
    t = SparseTable(8)
    assert t._native is not None


@requires_native
def test_pull_deterministic_and_lazy():
    t = SparseTable(16, seed=42)
    ids = np.array([5, 99, 5, 12345678901], np.int64)
    out = t.pull(ids)
    assert out.shape == (4, 16)
    # same id -> same row, regardless of position
    np.testing.assert_array_equal(out[0], out[2])
    assert len(t) == 3
    # re-pull is stable
    np.testing.assert_array_equal(t.pull(ids), out)
    # a fresh table with the same seed materialises identical rows even
    # when ids arrive in a different order (deterministic per-id init)
    t2 = SparseTable(16, seed=42)
    out2 = t2.pull(ids[::-1].copy())
    np.testing.assert_array_equal(out2[::-1], out)
    # init is ~ normal(0, 0.01)
    big = t.pull(np.arange(4096, dtype=np.int64))
    assert abs(float(big.mean())) < 1e-3
    assert 0.008 < float(big.std()) < 0.012


@requires_native
def test_sgd_push_matches_oracle():
    t = SparseTable(4, optimizer="sgd", lr=0.1)
    ids = np.array([1, 2], np.int64)
    before = t.pull(ids).copy()
    g = np.array([[1, 2, 3, 4], [5, 6, 7, 8]], np.float32)
    t.push(ids, g)
    np.testing.assert_allclose(t.pull(ids), before - 0.1 * g, rtol=1e-6)


@requires_native
def test_adagrad_push_matches_python_fallback():
    ids = np.array([7, 8, 7], np.int64)
    g = np.random.RandomState(0).randn(3, 6).astype(np.float32)
    tn = SparseTable(6, optimizer="adagrad", lr=0.05)
    tp = SparseTable(6, optimizer="adagrad", lr=0.05, backend="python",
                     initializer=lambda: np.zeros(6, np.float32))
    # align initial rows: zero them via import
    zeros = np.zeros((2, 6), np.float32)
    uniq = np.array([7, 8], np.int64)
    tn.load_from_arrays = None  # no-op guard
    import ctypes
    tn._lib.pts_import(tn._native, tn._c(uniq, ctypes.c_int64), 2,
                       tn._c(zeros, ctypes.c_float))
    for _ in range(3):
        tn.push(ids, g)
        tp.push(ids, g)
    np.testing.assert_allclose(tn.pull(uniq), tp.pull(uniq),
                               rtol=1e-5, atol=1e-6)


@requires_native
def test_adam_push_matches_python_fallback():
    ids = np.array([3, 4], np.int64)
    g = np.random.RandomState(1).randn(2, 5).astype(np.float32)
    tn = SparseTable(5, optimizer="adam", lr=0.01)
    tp = SparseTable(5, optimizer="adam", lr=0.01, backend="python",
                     initializer=lambda: np.zeros(5, np.float32))
    import ctypes
    zeros = np.zeros((2, 5), np.float32)
    tn._lib.pts_import(tn._native, tn._c(ids, ctypes.c_int64), 2,
                       tn._c(zeros, ctypes.c_float))
    for _ in range(5):
        tn.push(ids, g)
        tp.push(ids, g)
    np.testing.assert_allclose(tn.pull(ids), tp.pull(ids),
                               rtol=1e-4, atol=1e-6)


@requires_native
def test_push_delta_and_len():
    t = SparseTable(3)
    ids = np.array([10, 11], np.int64)
    base = t.pull(ids).copy()
    d = np.ones((2, 3), np.float32)
    t.push_delta(ids, d)
    np.testing.assert_allclose(t.pull(ids), base + 1.0, rtol=1e-6)
    assert len(t) == 2


@requires_native
def test_save_load_roundtrip(tmp_path):
    t = SparseTable(4, seed=1)
    ids = np.array([100, 200, 300], np.int64)
    t.push(ids, np.ones((3, 4), np.float32))
    vals = t.pull(ids).copy()
    p = str(tmp_path / "table")
    t.save(p)
    t2 = SparseTable(4, seed=999)   # different seed: rows must come from file
    t2.load(p)
    assert len(t2) == 3
    np.testing.assert_array_equal(t2.pull(ids), vals)
    # python-backend can read the same file (shared format)
    t3 = SparseTable(4, backend="python")
    t3.load(p + ".npz")
    np.testing.assert_allclose(t3.pull(ids), vals, rtol=1e-6)


@requires_native
def test_concurrent_push_pull():
    t = SparseTable(8, optimizer="sgd", lr=0.001)
    errs = []

    def worker(seed):
        try:
            rng = np.random.RandomState(seed)
            for _ in range(50):
                ids = rng.randint(0, 1000, size=64).astype(np.int64)
                t.pull(ids)
                t.push(ids, rng.randn(64, 8).astype(np.float32))
        except Exception as e:   # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(s,)) for s in range(8)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert not errs
    assert len(t) <= 1000
    out = t.pull(np.arange(1000, dtype=np.int64))
    assert np.isfinite(out).all()


@requires_native
def test_large_batch_threads():
    """Exercise the multi-threaded shard fan-out path (n >= 4096)."""
    t = SparseTable(16)
    ids = np.random.RandomState(3).randint(0, 10**12, size=20000)
    ids = ids.astype(np.int64)
    out = t.pull(ids)
    assert out.shape == (20000, 16)
    t.push(ids, np.ones((20000, 16), np.float32))
    assert np.isfinite(t.pull(ids)).all()


def test_python_fallback_still_works():
    t = SparseTable(4, backend="python", optimizer="adam", lr=0.01)
    ids = np.array([1, 2], np.int64)
    t.push(ids, np.ones((2, 4), np.float32))
    assert len(t) == 2
    assert np.isfinite(t.pull(ids)).all()


@requires_native
def test_load_replaces_not_merges(tmp_path):
    t = SparseTable(4, seed=1)
    t.pull(np.array([1, 2], np.int64))
    p = str(tmp_path / "snap")
    t.save(p)
    t2 = SparseTable(4, seed=2)
    t2.pull(np.array([7, 8, 9], np.int64))   # pre-existing rows
    t2.load(p)
    assert len(t2) == 2                      # replaced, not merged


def test_load_replaces_python_backend(tmp_path):
    t = SparseTable(4, backend="python", seed=1)
    t.pull(np.array([1, 2], np.int64))
    p = str(tmp_path / "snap")
    t.save(p)
    t2 = SparseTable(4, backend="python", optimizer="adam")
    t2.push(np.array([7], np.int64), np.ones((1, 4), np.float32))
    t2.load(p + ".npz")
    assert len(t2) == 2
    assert not t2._moments                   # optimizer state reset


# ---------------------------------------------------------------------
# r6: native-vs-Python parity for the full data plane (fused push,
# admission entries, moments, cross-backend checkpoints) + wide_deep
# e2e smoke — the ISSUE-1 acceptance tests.
# ---------------------------------------------------------------------

def _zero_native(t, ids):
    """Force a native table's rows for ``ids`` to zeros so both backends
    start from identical state (their default inits differ by design)."""
    import ctypes
    ids = np.ascontiguousarray(ids, np.int64)
    z = np.zeros((ids.size, t.dim), np.float32)
    t._lib.pts_import(t._native, t._c(ids, ctypes.c_int64), ids.size,
                      t._c(z, ctypes.c_float))


@requires_native
@pytest.mark.parametrize("opt", ["sgd", "adagrad", "adam"])
def test_pull_after_push_parity(opt):
    """Pull-after-push parity, duplicates included: the fused native
    push (dedup + segment-sum + single apply) must match the Python
    reference path bit-for-tolerance across every optimizer."""
    ids = np.array([3, 9, 3, 42, 9, 3], np.int64)
    uniq = np.array([3, 9, 42], np.int64)
    g = np.random.RandomState(7).randn(6, 5).astype(np.float32)
    tn = SparseTable(5, optimizer=opt, lr=0.03)
    tp = SparseTable(5, optimizer=opt, lr=0.03, use_native=False,
                     initializer=lambda: np.zeros(5, np.float32))
    _zero_native(tn, uniq)
    for _ in range(4):
        tn.push(ids, g)
        tp.push(ids, g)
    np.testing.assert_allclose(tn.pull(uniq), tp.pull(uniq),
                               rtol=1e-4, atol=1e-6)


@requires_native
def test_fused_push_equals_presummed_push():
    """The fused-push contract, stated directly: pushing duplicate ids
    equals pushing their summed gradient once (NOT sequential applies —
    the distinction matters for adagrad/adam)."""
    g = np.random.RandomState(2).randn(3, 4).astype(np.float32)
    ta = SparseTable(4, optimizer="adam", lr=0.01)
    tb = SparseTable(4, optimizer="adam", lr=0.01)
    one = np.array([11], np.int64)
    _zero_native(ta, one)
    _zero_native(tb, one)
    ta.push(np.array([11, 11, 11], np.int64), g)
    tb.push(one, g.sum(axis=0, keepdims=True))
    np.testing.assert_allclose(ta.pull(one), tb.pull(one),
                               rtol=1e-5, atol=1e-7)


@requires_native
def test_native_count_entry_matches_python():
    """CountFilterEntry admission runs inside C: threshold counting,
    one-sighting-per-unique-id-per-pull, and grad dropping must all
    match the Python reference decisions."""
    from paddle_tpu.distributed import CountFilterEntry
    tn = SparseTable(4, entry=CountFilterEntry(3), lr=1.0)
    tp = SparseTable(4, entry=CountFilterEntry(3), lr=1.0,
                     use_native=False)
    assert tn._native_entry
    ids = np.array([7, 8, 7], np.int64)     # 7 twice = ONE sighting
    for _ in range(2):                       # sightings 1, 2: rejected
        on, op = tn.pull(ids), tp.pull(ids)
        assert not on.any() and not op.any()
        assert len(tn) == 0 and len(tp._rows) == 0
    # grads before admission are dropped by both
    tn.push(ids, np.ones((3, 4), np.float32))
    tp.push(ids, np.ones((3, 4), np.float32))
    assert len(tn) == 0 and len(tp._rows) == 0
    # 3rd sighting admits in both; duplicate positions serve one row
    on, op = tn.pull(ids), tp.pull(ids)
    assert on.any() and op.any()
    np.testing.assert_array_equal(on[0], on[2])
    assert len(tn) == 2 and len(tp._rows) == 2
    # post-admission push applies (lr=1, grads summed over duplicates)
    before = tn.pull(ids).copy()
    tn.push(ids, np.ones((3, 4), np.float32))
    got = tn.pull(ids)
    np.testing.assert_allclose(got[1], before[1] - 1.0, rtol=1e-5)
    np.testing.assert_allclose(got[0], before[0] - 2.0, rtol=1e-5)


@requires_native
def test_native_probability_entry_matches_python():
    """ProbabilityEntry's C hash is bit-exact with entry.py: both
    backends must admit the IDENTICAL subset, and rejected ids must
    leave no slot behind (len == admitted rows only)."""
    from paddle_tpu.distributed import ProbabilityEntry
    tn = SparseTable(4, entry=ProbabilityEntry(0.5))
    tp = SparseTable(4, entry=ProbabilityEntry(0.5), use_native=False)
    assert tn._native_entry
    ids = np.arange(500, dtype=np.int64)
    on, op = tn.pull(ids), tp.pull(ids)
    zn = ~on.any(axis=1)
    zp = ~op.any(axis=1)
    np.testing.assert_array_equal(zn, zp)
    assert len(tn) == len(tp._rows) == int((~zn).sum())
    st = tn._entry_state()
    assert set(st["admitted"].tolist()) == tp._admitted
    assert st["seen_ids"].size == 0          # count-independent entry


@requires_native
def test_entry_state_roundtrip_cross_backend(tmp_path):
    """Checkpoint format parity including admission state: save from
    either backend, load into the other, admission picks up where it
    left off (trained rows served immediately, counters survive)."""
    from paddle_tpu.distributed import CountFilterEntry
    for src_native in (True, False):
        t = SparseTable(4, entry=CountFilterEntry(2), lr=1.0,
                        use_native=src_native)
        hot = np.asarray([5], np.int64)
        t.pull(hot)
        t.pull(hot)                          # admitted at sighting 2
        t.push(hot, np.ones((1, 4), np.float32))
        trained = t.pull(hot).copy()
        warm = np.asarray([9], np.int64)
        t.pull(warm)                         # 1 sighting, not admitted
        p = str(tmp_path / f"ck{src_native}")
        t.save(p)
        for dst_native in (True, False):
            t2 = SparseTable(4, entry=CountFilterEntry(2), lr=1.0,
                             use_native=dst_native)
            t2.load(p)
            np.testing.assert_allclose(t2.pull(hot), trained)
            t2.pull(warm)                    # counter survived: admits
            assert t2.pull(warm).any(), (src_native, dst_native)


@requires_native
def test_use_native_flag():
    assert SparseTable(4, use_native=True).is_native
    assert not SparseTable(4, use_native=False).is_native
    # use_native=False must still be a fully working table
    t = SparseTable(4, use_native=False)
    t.push(np.array([1], np.int64), np.ones((1, 4), np.float32))
    assert len(t) == 1


@requires_native
def test_wide_deep_native_e2e_smoke(monkeypatch):
    """wide_deep end-to-end through HeterTrainer with use_native=True
    (the r6 bench default): loss finite, native backend actually on."""
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench
    monkeypatch.setenv("BENCH_PS_NATIVE", "1")
    monkeypatch.setenv("BENCH_STEPS", "4")
    monkeypatch.setenv("BENCH_BATCH", "64")
    out = bench._bench_wide_deep(smoke=True, peak_tflops=100.0)
    assert out["ps_backend"] == "native"
    assert out["value"] > 0
    assert np.isfinite(out["loss_last"])
    assert out["plausible"]
