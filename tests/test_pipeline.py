"""Pipeline parallelism: GPipe wavefront correctness.

Test model: the reference's pipeline tests compare pipelined vs plain
program losses (reference: python/paddle/fluid/tests/unittests/
test_fleet_pipeline_meta_optimizer.py); here we compare pipelined (pp=4
mesh, microbatched) against the identical stacked-scan model on pp=1 —
forward logits, loss, and gradients must match.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.text.models import LlamaForCausalLM, llama_tiny


def _cfg(**kw):
    d = dict(num_hidden_layers=4, compute_dtype="float32",
             scan_layers=True)
    d.update(kw)
    return llama_tiny(**d)


def _batch(cfg, b=4, s=16, seed=0):
    ids = np.random.RandomState(seed).randint(
        0, cfg.vocab_size, size=(b, s)).astype("int32")
    return paddle.to_tensor(ids)


def teardown_function(_fn):
    mesh_mod.set_mesh(None)


def test_scan_layers_matches_layerlist():
    """Stacked-scan decoder == per-layer decoder on identical weights."""
    mesh_mod.set_mesh(None)
    cfg_list = _cfg(scan_layers=False)
    m_list = LlamaForCausalLM(cfg_list)
    m_scan = LlamaForCausalLM(_cfg())

    # copy per-layer weights into the stacked params
    import jax.numpy as jnp
    sd = m_list.state_dict()
    dec = m_scan.model.decoder
    for n in dec._names:
        vals = [sd[f"model.layers.{i}.{n}"]._value
                for i in range(cfg_list.num_hidden_layers)]
        getattr(dec, n.replace(".", "__"))._value = jnp.stack(vals)
    m_scan.model.embed_tokens.weight._value = \
        sd["model.embed_tokens.weight"]._value
    m_scan.model.norm.weight._value = sd["model.norm.weight"]._value
    m_scan.lm_head.weight._value = sd["lm_head.weight"]._value

    ids = _batch(cfg_list)
    l1 = m_list(ids)
    l2 = m_scan(ids)
    np.testing.assert_allclose(np.asarray(l1._value),
                               np.asarray(l2._value),
                               rtol=1e-4, atol=1e-5)


def _sync_models(src, dst):
    dst.set_state_dict(src.state_dict())


def test_pipeline_forward_matches_single():
    cfg = _cfg(pp_num_microbatches=2)
    mesh_mod.set_mesh(None)
    ref = LlamaForCausalLM(cfg)
    ids = _batch(cfg)
    ref_logits = np.asarray(ref(ids)._value)

    mesh_mod.init_mesh({"pp": 4, "dp": 2})
    pp = LlamaForCausalLM(cfg)
    _sync_models(ref, pp)
    out = np.asarray(pp(ids)._value)
    np.testing.assert_allclose(out, ref_logits, rtol=1e-4, atol=1e-5)


def test_pipeline_grads_match_single():
    cfg = _cfg(pp_num_microbatches=4)
    mesh_mod.set_mesh(None)
    ref = LlamaForCausalLM(cfg)
    ids = _batch(cfg)
    loss_ref, _ = ref(ids, labels=ids)
    loss_ref.backward()

    mesh_mod.init_mesh({"pp": 4, "dp": 2})
    pp = LlamaForCausalLM(cfg)
    _sync_models(ref, pp)
    loss_pp, _ = pp(ids, labels=ids)
    np.testing.assert_allclose(float(loss_pp), float(loss_ref),
                               rtol=1e-5)
    loss_pp.backward()
    ref_g = dict(ref.named_parameters())
    for n, p in pp.named_parameters():
        np.testing.assert_allclose(
            np.asarray(p.grad._value), np.asarray(ref_g[n].grad._value),
            rtol=1e-3, atol=1e-5, err_msg=n)


def test_pipeline_train_step():
    """Full DistributedTrainStep over a pp=4 x dp=2 mesh."""
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.fleet.dist_step import DistributedTrainStep

    cfg = _cfg(pp_num_microbatches=2, remat=True)
    mesh_mod.set_mesh(None)
    mesh = mesh_mod.init_mesh({"pp": 4, "dp": 2})
    model = LlamaForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())

    def loss_fn(ids, labels):
        loss, _ = model(ids, labels=labels)
        return loss

    step = DistributedTrainStep(model, loss_fn, opt,
                                fleet.DistributedStrategy(), mesh=mesh)
    ids = _batch(cfg)
    l1 = float(step(ids, ids))
    l2 = float(step(ids, ids))
    assert np.isfinite(l1) and np.isfinite(l2) and l2 < l1


def test_pp_zero3_matches_single_device():
    """North-star config (BASELINE configs[5]): pipeline x ZeRO-3.
    Losses over 3 steps must match the identical model trained on a
    single-device mesh."""
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.fleet.dist_step import DistributedTrainStep

    def run(degrees, zero_stage):
        paddle.seed(123)
        cfg = _cfg(pp_num_microbatches=2)
        mesh_mod.set_mesh(None)
        mesh = mesh_mod.init_mesh(degrees)
        model = LlamaForCausalLM(cfg)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())

        def loss_fn(ids, labels):
            loss, _ = model(ids, labels=labels)
            return loss

        strategy = fleet.DistributedStrategy()
        if zero_stage:
            strategy.sharding = True
            strategy.sharding_configs = {"stage": zero_stage}
        step = DistributedTrainStep(model, loss_fn, opt, strategy,
                                    mesh=mesh)
        ids = _batch(cfg, b=8, s=16)
        out = [float(step(ids, ids)) for _ in range(3)]
        mesh_mod.set_mesh(None)
        return out

    ref = run({"dp": 1}, 0)
    got = run({"pp": 2, "fsdp": 2, "dp": 2}, 3)
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-6)
    assert got[-1] < got[0]
