"""Process-based DataLoader workers.

Parity: reference fluid/dataloader/dataloader_iter.py:469
_DataLoaderIterMultiProcess — forked workers, ordered results, error
and dead-worker propagation. The scaling test is the evidence the
thread pool could never give: Python-heavy per-sample work (holds the
GIL) must get faster with process workers.
"""
import time

import numpy as np
import pytest

from paddle_tpu.io import DataLoader, Dataset, get_worker_info


class _Square(Dataset):
    def __init__(self, n=64):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        return np.asarray([i, i * i], np.float32)


class _PythonHeavy(Dataset):
    """Per-sample pure-Python loop: holds the GIL, the worst case for
    thread workers and the reason the reference forks processes."""

    def __init__(self, n=48, iters=60000):
        self.n = n
        self.iters = iters

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        acc = 0
        for k in range(self.iters):
            acc += (i * k) % 7
        return np.asarray([i, acc], np.float32)


class _FaultyAt(Dataset):
    def __init__(self, bad=13, n=32):
        self.bad, self.n = bad, n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        if i == self.bad:
            raise ValueError(f"poison sample {i}")
        return np.asarray([i], np.float32)


class _KillSelf(Dataset):
    """Simulates an OOM-killed / segfaulted worker."""

    def __init__(self, n=32):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        if i == 5:
            import os
            os._exit(137)
        return np.asarray([i], np.float32)


def _collect(loader):
    return [np.asarray(b.numpy()) for b in loader]


def test_process_workers_match_sync_order():
    ds = _Square(64)
    sync = _collect(DataLoader(ds, batch_size=8))
    proc = _collect(DataLoader(ds, batch_size=8, num_workers=3,
                               use_process=True))
    assert len(sync) == len(proc) == 8
    for a, b in zip(sync, proc):
        np.testing.assert_array_equal(a, b)


def test_process_workers_multiple_epochs():
    dl = DataLoader(_Square(32), batch_size=8, num_workers=2,
                    use_process=True)
    for _ in range(3):
        assert len(_collect(dl)) == 4


def test_worker_exception_propagates_with_trace():
    dl = DataLoader(_FaultyAt(13), batch_size=8, num_workers=2,
                    use_process=True)
    with pytest.raises(RuntimeError, match="poison sample 13"):
        _collect(dl)


def test_dead_worker_raises_instead_of_hanging():
    dl = DataLoader(_KillSelf(), batch_size=4, num_workers=2,
                    use_process=True)
    t0 = time.monotonic()
    with pytest.raises(RuntimeError, match="exited unexpectedly"):
        _collect(dl)
    assert time.monotonic() - t0 < 30.0


def test_worker_info_inside_process():
    class _Probe(Dataset):
        def __len__(self):
            return 8

        def __getitem__(self, i):
            info = get_worker_info()
            assert info is not None and info.num_workers == 2
            return np.asarray([info.id], np.float32)

    seen = np.concatenate(_collect(
        DataLoader(_Probe(), batch_size=2, num_workers=2,
                   use_process=True))).ravel()
    assert set(seen) <= {0.0, 1.0}


def test_early_break_releases_workers():
    dl = DataLoader(_Square(64), batch_size=4, num_workers=2,
                    use_process=True)
    for i, _ in enumerate(dl):
        if i == 2:
            break
    # a second full pass still works (no leaked/poisoned state)
    assert len(_collect(dl)) == 16


def test_python_heavy_transforms_scale_with_process_workers():
    import os
    ds = _PythonHeavy()

    def measure():
        t0 = time.monotonic()
        a = _collect(DataLoader(ds, batch_size=8))
        t_sync = time.monotonic() - t0
        t0 = time.monotonic()
        b = _collect(DataLoader(ds, batch_size=8, num_workers=4,
                                use_process=True))
        t_proc = time.monotonic() - t0
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)
        return t_sync, t_proc

    if (os.cpu_count() or 1) < 2:
        # a single core cannot parallelize CPU-bound work, and under
        # suite-wide contention even an overhead bound is meaningless;
        # correctness of process mode is covered by the other tests
        measure()
        pytest.skip("scaling assertion needs >=2 cores")
    # forked workers on GIL-bound work must win (1.3x, conservative);
    # one retry rides out transient load on a shared CI host
    for attempt in range(2):
        t_sync, t_proc = measure()
        ok = t_proc < t_sync / 1.3
        if ok:
            return
    assert ok, (t_sync, t_proc)
