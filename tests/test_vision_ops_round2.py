"""deform_conv2d / DeformConv2D / yolo_loss (parity:
operators/deformable_conv_op.*, operators/detection/yolov3_loss_op.*).
Gold checks are analytic: zero-offset deformable conv equals plain
conv, integer/fractional offsets equal shifted/averaged convs, and the
yolo loss at a perfect prediction equals its irreducible BCE entropy.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.vision.ops import DeformConv2D, deform_conv2d, yolo_loss


def _H(p):
    return -(p * np.log(p) + (1 - p) * np.log(1 - p))


class TestDeformConv2D:
    def setup_method(self, _):
        self.rng = np.random.default_rng(0)
        self.x = self.rng.normal(size=(2, 4, 8, 8)).astype(np.float32)
        self.w = self.rng.normal(size=(6, 4, 3, 3)).astype(np.float32)

    def test_zero_offset_equals_conv2d(self):
        off = np.zeros((2, 18, 6, 6), np.float32)
        a = np.asarray(deform_conv2d(
            paddle.to_tensor(self.x), paddle.to_tensor(off),
            paddle.to_tensor(self.w)).numpy())
        b = np.asarray(F.conv2d(paddle.to_tensor(self.x),
                                paddle.to_tensor(self.w)).numpy())
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)

    def test_integer_offset_is_shift(self):
        off = np.zeros((2, 18, 6, 6), np.float32)
        off[:, 0::2] = 1.0   # per-tap (dy, dx) pairs: all dy = 1
        a = np.asarray(deform_conv2d(
            paddle.to_tensor(self.x), paddle.to_tensor(off),
            paddle.to_tensor(self.w)).numpy())
        shifted = np.pad(self.x, ((0, 0), (0, 0), (0, 1), (0, 0)))[
            :, :, 1:, :]
        b = np.asarray(F.conv2d(paddle.to_tensor(shifted),
                                paddle.to_tensor(self.w)).numpy())
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)

    def test_fractional_offset_bilinear(self):
        w1 = self.rng.normal(size=(3, 4, 1, 1)).astype(np.float32)
        off = np.zeros((2, 2, 8, 8), np.float32)
        off[:, 0] = 0.5
        a = np.asarray(deform_conv2d(
            paddle.to_tensor(self.x), paddle.to_tensor(off),
            paddle.to_tensor(w1)).numpy())
        xa = (self.x + np.pad(self.x, ((0, 0), (0, 0), (0, 1),
                                       (0, 0)))[:, :, 1:, :]) / 2
        b = np.asarray(F.conv2d(paddle.to_tensor(xa),
                                paddle.to_tensor(w1)).numpy())
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)

    def test_mask_modulation_and_layer(self):
        layer = DeformConv2D(4, 3, 1)
        off = np.zeros((2, 2, 8, 8), np.float32)
        m = np.full((2, 1, 8, 8), 0.5, np.float32)
        full = np.asarray(layer(paddle.to_tensor(self.x),
                                paddle.to_tensor(off)).numpy())
        half = np.asarray(layer(paddle.to_tensor(self.x),
                                paddle.to_tensor(off),
                                mask=paddle.to_tensor(m)).numpy())
        bias = np.asarray(layer.bias._value)[None, :, None, None]
        np.testing.assert_allclose(half - bias, (full - bias) * 0.5,
                                   rtol=1e-4, atol=1e-5)
        assert len(layer.parameters()) == 2   # weight + bias registered

    def test_gradients_flow(self):
        from op_test import check_grad
        x = self.rng.normal(size=(1, 2, 5, 5)).astype(np.float32)
        w = self.rng.normal(size=(2, 2, 2, 2)).astype(np.float32)
        # bilinear sampling has gradient kinks at integer grid lines;
        # keep every sampling point >= 0.1 away so central differences
        # never straddle a kink
        off = self.rng.uniform(0.1, 0.4, size=(1, 8, 4, 4)).astype(
            np.float32)
        check_grad(deform_conv2d, [x, off, w])


class TestYoloLoss:
    ANCHORS = [10, 13, 16, 30, 33, 23]
    MASK = [0, 1, 2]
    C, H, W, DS = 3, 4, 4, 32

    def _perfect_head(self, gt, gl):
        N = gt.shape[0]
        na, C, H, W, ds = 3, self.C, self.H, self.W, self.DS
        x = np.full((N, na * (5 + C), H, W), -8.0, np.float32)
        in_w = W * ds
        anc = np.asarray(self.ANCHORS).reshape(-1, 2)
        ws, hs = gt[0, 0, 2] * in_w, gt[0, 0, 3] * in_w
        ious = [min(ws, a) * min(hs, b)
                / (ws * hs + a * b - min(ws, a) * min(hs, b))
                for a, b in anc]
        best = int(np.argmax(ious))
        gi, gj = int(gt[0, 0, 0] * W), int(gt[0, 0, 1] * H)
        tx, ty = gt[0, 0, 0] * W - gi, gt[0, 0, 1] * H - gj

        def logit(p):
            return np.log(p / (1 - p))
        base = best * (5 + C)
        x[:, base + 0, gj, gi] = logit(np.clip(tx, 1e-4, 1 - 1e-4))
        x[:, base + 1, gj, gi] = logit(np.clip(ty, 1e-4, 1 - 1e-4))
        x[:, base + 2, gj, gi] = np.log(ws / anc[best, 0])
        x[:, base + 3, gj, gi] = np.log(hs / anc[best, 1])
        x[:, base + 4, gj, gi] = 8.0
        x[:, base + 5 + int(gl[0, 0]), gj, gi] = 8.0
        return x, base, gi, gj, tx, ty

    def _gt(self):
        gt = np.zeros((2, 2, 4), np.float32)
        gt[:, 0] = [0.4, 0.6, 0.2, 0.3]
        gl = np.zeros((2, 2), np.int64)
        gl[:, 0] = 1
        return gt, gl

    def test_perfect_prediction_hits_entropy_floor(self):
        gt, gl = self._gt()
        x, base, gi, gj, tx, ty = self._perfect_head(gt, gl)
        loss = np.asarray(yolo_loss(
            paddle.to_tensor(x), paddle.to_tensor(gt),
            paddle.to_tensor(gl), self.ANCHORS, self.MASK, self.C,
            0.7, self.DS, use_label_smooth=False).numpy())
        # sigmoid-CE at the optimum equals the target entropy (weighted
        # by the small-box factor 2 - w*h); everything else ~0
        floor = (2.0 - gt[0, 0, 2] * gt[0, 0, 3]) * (_H(tx) + _H(ty))
        np.testing.assert_allclose(loss, floor, rtol=0.05)

    def test_wrong_objectness_costs_more(self):
        gt, gl = self._gt()
        x, base, gi, gj, *_ = self._perfect_head(gt, gl)
        x_bad = x.copy()
        x_bad[:, base + 4, gj, gi] = -8.0
        args = (paddle.to_tensor(gt), paddle.to_tensor(gl), self.ANCHORS,
                self.MASK, self.C, 0.7, self.DS)
        good = np.asarray(yolo_loss(paddle.to_tensor(x), *args).numpy())
        bad = np.asarray(yolo_loss(paddle.to_tensor(x_bad), *args).numpy())
        assert (bad > good + 5).all()

    def test_gradients_finite_and_nonzero(self):
        gt, gl = self._gt()
        x, *_ = self._perfect_head(gt, gl)
        xt = paddle.to_tensor(x)
        xt.stop_gradient = False
        yolo_loss(xt, paddle.to_tensor(gt), paddle.to_tensor(gl),
                  self.ANCHORS, self.MASK, self.C, 0.7,
                  self.DS).sum().backward()
        g = np.asarray(xt.grad.numpy())
        assert np.isfinite(g).all() and np.abs(g).max() > 0

    def test_gt_score_weights_loss(self):
        gt, gl = self._gt()
        x, *_ = self._perfect_head(gt, gl)
        score_half = np.zeros((2, 2), np.float32)
        score_half[:, 0] = 0.5
        args = (paddle.to_tensor(gt), paddle.to_tensor(gl), self.ANCHORS,
                self.MASK, self.C, 0.7, self.DS)
        full = np.asarray(yolo_loss(
            paddle.to_tensor(x), *args, use_label_smooth=False).numpy())
        half = np.asarray(yolo_loss(
            paddle.to_tensor(x), *args,
            gt_score=paddle.to_tensor(score_half),
            use_label_smooth=False).numpy())
        assert (half < full).all()   # down-weighted positives


def test_bilinear_initializer_fills_all_pairs():
    import paddle_tpu.nn as nn
    # canonical depthwise-upsample weight (C, 1, k, k): every channel
    # must carry the filter (reference writes it into every pair)
    w = np.asarray(nn.initializer.Bilinear()((4, 1, 4, 4)))
    sums = w.sum(axis=(2, 3)).ravel()
    np.testing.assert_allclose(sums, 4.0, rtol=1e-5)


def test_global_bias_initializer_applies_to_biases():
    import paddle_tpu.nn as nn
    nn.initializer.set_global_initializer(
        nn.initializer.Constant(9.0), nn.initializer.Constant(-3.0))
    try:
        l = nn.Linear(2, 2)
        assert float(np.asarray(l.weight._value)[0, 0]) == 9.0
        assert float(np.asarray(l.bias._value)[0]) == -3.0
    finally:
        nn.initializer.set_global_initializer(None)


def test_yolo_loss_scale_x_y_changes_ignore_mask():
    # a confident objectness at a NON-responsible cell is only forgiven
    # (ignored) when its decoded box overlaps a gt above ignore_thresh;
    # scale_x_y moves the decode enough to flip that decision
    anchors = [10, 13, 16, 30, 33, 23]
    gt = np.zeros((1, 1, 4), np.float32)
    gt[:, 0] = [0.625, 0.625, 0.25, 0.25]       # x-range [0.5, 0.75]
    gl = np.zeros((1, 1), np.int64)
    x = np.full((1, 3 * 8, 4, 4), -8.0, np.float32)
    # anchor 0 at cell (gi=1, gj=2): px logit 2 -> sigmoid 0.881;
    # plain decode centers at 0.470 (IoU~0.23 < 0.3: penalized);
    # scale_x_y=1.5 decodes 1.071 -> center 0.518 (IoU~0.4: ignored)
    x[0, 0, 2, 1] = 2.0
    x[0, 1, 2, 1] = 0.0                          # gy centered
    x[0, 2, 2, 1] = np.log(0.25 * 128 / 10)      # width 0.25
    x[0, 3, 2, 1] = np.log(0.25 * 128 / 13)      # height 0.25
    x[0, 4, 2, 1] = 6.0                          # confident objectness
    args = (paddle.to_tensor(gt), paddle.to_tensor(gl), anchors,
            [0, 1, 2], 3, 0.3, 32)
    a = np.asarray(yolo_loss(paddle.to_tensor(x), *args).numpy())
    b = np.asarray(yolo_loss(paddle.to_tensor(x), *args,
                             scale_x_y=1.5).numpy())
    assert a[0] > b[0] + 3, (a, b)   # penalty forgiven under scale_x_y
