"""Profiler (SURVEY §5.1) and nan/inf debugging (§5.2) tests.

Reference behaviors modeled: fluid.profiler start/stop + report table
(python/paddle/fluid/profiler.py), chrome-tracing timeline export
(tools/timeline.py), FLAGS_check_nan_inf post-op scan
(framework/operator.cc:1195, details/nan_inf_utils_detail.cc).
"""
import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.profiler as prof
from paddle_tpu.framework import (check_numerics, disable_check_nan_inf,
                                  enable_check_nan_inf)


@pytest.fixture(autouse=True)
def _clean_state():
    yield
    disable_check_nan_inf()


def test_profiler_records_op_events(tmp_path, capsys):
    x = paddle.to_tensor(np.random.rand(8, 8).astype("float32"),
                         stop_gradient=False)
    path = str(tmp_path / "trace.json")
    with prof.profiler(profile_path=path):
        y = paddle.matmul(x, x)
        z = paddle.tanh(y)
        z.sum().backward()
    out = capsys.readouterr().out
    assert "Profiling Report" in out
    assert "matmul" in out
    assert "_grad" in out  # backward sweep instrumented too
    # chrome tracing json written and well-formed
    with open(path) as f:
        data = json.load(f)
    names = {e["name"] for e in data["traceEvents"]}
    assert "matmul" in names
    assert all({"ph", "ts", "dur"} <= set(e) for e in data["traceEvents"])


def test_profiler_summary_sort_and_reset():
    x = paddle.to_tensor(np.ones((4, 4), "float32"))
    prof.start_profiler()
    for _ in range(3):
        x = paddle.add(x, x)
    table = prof.profiler_summary(sorted_key="calls")
    prof.stop_profiler()
    assert "add" in table
    prof.reset_profiler()
    assert "add" not in prof.profiler_summary()


def test_record_event_manual():
    prof.start_profiler()
    with prof.RecordEvent("my_block"):
        np.dot(np.ones((16, 16)), np.ones((16, 16)))
    table = prof.profiler_summary()
    prof.stop_profiler()
    assert "my_block" in table


def test_check_numerics_raises_on_nan():
    bad = paddle.to_tensor(np.array([1.0, np.nan], dtype="float32"))
    with pytest.raises(FloatingPointError, match="NaN/Inf"):
        check_numerics(bad, "bad_var")
    ok = paddle.to_tensor(np.array([1.0, 2.0], dtype="float32"))
    check_numerics(ok, "ok_var")  # no raise


def test_flags_check_nan_inf_eager_op():
    enable_check_nan_inf(debug_jit=False)
    x = paddle.to_tensor(np.array([1.0, 0.0], dtype="float32"))
    with pytest.raises(FloatingPointError, match="log"):
        paddle.log(paddle.to_tensor(np.array([-1.0], dtype="float32")))
    disable_check_nan_inf()
    # after disable, no raise
    paddle.log(paddle.to_tensor(np.array([-1.0], dtype="float32")))


def test_nan_inf_skip_op_env(monkeypatch):
    monkeypatch.setenv("PADDLE_INF_NAN_SKIP_OP", "log")
    enable_check_nan_inf(debug_jit=False)
    paddle.log(paddle.to_tensor(np.array([-1.0], dtype="float32")))  # skipped
    disable_check_nan_inf()


def test_profiler_composes_with_nan_check(capsys):
    enable_check_nan_inf(debug_jit=False)
    prof.start_profiler()
    x = paddle.to_tensor(np.ones((2, 2), "float32"))
    paddle.add(x, x)
    with pytest.raises(FloatingPointError):
        paddle.sqrt(paddle.to_tensor(np.array([-4.0], dtype="float32")))
    prof.stop_profiler()
    disable_check_nan_inf()
    out = capsys.readouterr().out
    assert "add" in out


def test_benchmark_flag_syncs():
    paddle.set_flags({"FLAGS_benchmark": True})
    try:
        prof.start_profiler()
        x = paddle.to_tensor(np.ones((8, 8), "float32"))
        y = paddle.matmul(x, x)
        prof.stop_profiler()
        np.testing.assert_allclose(y.numpy(), np.full((8, 8), 8.0))
    finally:
        paddle.set_flags({"FLAGS_benchmark": False})
