"""bench.py trial-merge integrity (ISSUE 2 satellite, ADVICE r5).

``_merge_trials`` must pair trial candidates by metric NAME: a trial
whose child emitted fewer sub-metrics (e.g. a timed-out extra metric)
must not silently shift which metric's values get merged into a row.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import bench  # noqa: E402


def test_merge_trials_pairs_by_name_not_position():
    t1 = [{"metric": "a", "value": 1.0, "unit": "x"},
          {"metric": "b", "value": 10.0, "unit": "y"}]
    # trial 2 lost its extra metric: positional pairing would merge
    # b-values into a (the ADVICE r5 failure)
    t2 = [{"metric": "a", "value": 3.0, "unit": "x"}]
    t3 = [{"metric": "a", "value": 2.0, "unit": "x"},
          {"metric": "b", "value": 30.0, "unit": "y"}]
    merged = bench._merge_trials([t1, t2, t3])
    by_name = {m["metric"]: m for m in merged}
    assert set(by_name) == {"a", "b"}
    # a merges only a-values (median of 1,3,2 = 2), b only b-values
    assert by_name["a"]["value"] == 2.0
    assert by_name["a"]["trials"] == 3
    assert by_name["a"]["trial_values"] == [1.0, 3.0, 2.0]
    assert by_name["b"]["value"] == 30.0
    assert by_name["b"]["trials"] == 2
    assert by_name["b"]["trial_values"] == [10.0, 30.0]
    # first-seen order is stable
    assert [m["metric"] for m in merged] == ["a", "b"]


def test_merge_trials_keeps_valueless_placeholder():
    # a metric whose every trial lacks a numeric value passes through
    # as-is (no crash, no fabricated median)
    t1 = [{"metric": "a", "value": None, "unit": None, "failed": True}]
    t2 = [{"metric": "a", "value": None, "unit": None, "failed": True}]
    merged = bench._merge_trials([t1, t2])
    assert len(merged) == 1 and merged[0]["failed"]
    assert merged[0].get("value") is None


def test_merge_trials_spread_annotation():
    trials = [[{"metric": "m", "value": v}] for v in (100.0, 80.0, 120.0)]
    (m,) = bench._merge_trials(trials)
    assert m["value"] == 100.0
    assert m["trial_spread_pct"] == 40.0
