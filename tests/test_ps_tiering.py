"""Tiered hot/cold PS storage (ISSUE 16): placement must be invisible.

The tentpole contract: demoting a row to the mmap spill tier (and
promoting it back) is PURE placement — every observable (pull values,
push/push_delta math, checkpoint bytes, replica snapshots) is
bit-identical whether a row lives in the RAM arena or the spill file.
Plus the crash contract: a SIGKILL at any moment mid-sweep leaves the
spill file recoverable, with every committed record bit-exact and
half-written records reclaimed (payload-before-commit-mark ordering).

Also pins the SIMD fused-push toggle: the AVX2 path preserves the
scalar evaluation order with FP contraction disabled, so both paths
produce bit-identical tables.
"""
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from paddle_tpu.distributed.fleet.ps import SparseTable
from paddle_tpu.native import ps_core

requires_native = pytest.mark.skipif(ps_core() is None,
                                     reason="no C++ toolchain")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_CFG = dict(dim=8, optimizer="adam", lr=0.01, seed=3, init_std=0.05)
_FUTURE = lambda: int(time.time() * 1000) + 60_000  # noqa: E731


def _spilled_twin(tmp_path, ids, name="spill"):
    """(plain, tiered) same-seed tables; the tiered one has every row
    demoted to the spill file."""
    a = SparseTable(**_CFG)
    b = SparseTable(**_CFG)
    assert b.enable_spill(str(tmp_path / name))
    a.pull(ids)
    b.pull(ids)
    assert b.spill_sweep(_FUTURE()) == ids.size
    assert b.spill_stats()["cold"] == ids.size
    return a, b


@requires_native
def test_pull_parity_across_tiers(tmp_path):
    ids = np.arange(500, dtype=np.int64)
    a, b = _spilled_twin(tmp_path, ids)
    # cold pull == hot pull, and the pull PROMOTED the touched rows
    probe = np.array([0, 7, 499, 7], np.int64)
    np.testing.assert_array_equal(b.pull(probe), a.pull(probe))
    st = b.spill_stats()
    assert st["promoted"] == 3 and st["hot"] == 3
    # untouched rows stay cold; full-table parity regardless of mix
    np.testing.assert_array_equal(b.pull(ids), a.pull(ids))


@requires_native
@pytest.mark.parametrize("op", ["push", "push_delta"])
def test_push_parity_across_tiers(tmp_path, op):
    ids = np.arange(200, dtype=np.int64)
    a, b = _spilled_twin(tmp_path, ids, name=op)
    g = np.random.RandomState(1).randn(50, _CFG["dim"]).astype(np.float32)
    sub = np.arange(0, 200, 4, dtype=np.int64)
    getattr(a, op)(sub, g)
    getattr(b, op)(sub, g)  # rows promote, then the same math applies
    np.testing.assert_array_equal(b.pull(ids), a.pull(ids))
    # stateful-optimizer moments advanced identically: a second push
    # diverges immediately if the first one's state differed
    getattr(a, op)(sub, -g)
    getattr(b, op)(sub, -g)
    np.testing.assert_array_equal(b.pull(ids), a.pull(ids))


@requires_native
def test_checkpoint_bit_exact_and_format_unchanged(tmp_path):
    ids = np.arange(300, dtype=np.int64)
    a, b = _spilled_twin(tmp_path, ids)
    b.pull(ids[:100])  # mixed placement: 100 hot, 200 cold
    a.save(str(tmp_path / "a"))
    b.save(str(tmp_path / "b"))
    da = np.load(str(tmp_path / "a.npz"))
    db = np.load(str(tmp_path / "b.npz"))
    # the npz checkpoint format is UNCHANGED by tiering: same keys,
    # same bytes, no placement leakage
    assert sorted(da.files) == sorted(db.files)
    for k in da.files:
        np.testing.assert_array_equal(da[k], db[k])
    # and a checkpoint saved by a never-tiered table (the pre-tiering
    # on-disk format) loads into a spill-enabled table bit-exact
    c = SparseTable(**_CFG)
    assert c.enable_spill(str(tmp_path / "c_spill"))
    c.load(str(tmp_path / "a"))
    np.testing.assert_array_equal(c.pull(ids), a.pull(ids))


@requires_native
def test_replica_snapshot_parity_across_tiers(tmp_path):
    ids = np.arange(256, dtype=np.int64)
    a, b = _spilled_twin(tmp_path, ids)
    g = np.random.RandomState(2).randn(ids.size,
                                       _CFG["dim"]).astype(np.float32)
    a.push(ids, g)
    b.push(ids, g)
    b.spill_sweep(_FUTURE())  # re-demote: snapshot reads the cold tier
    ra = SparseTable(**_CFG)
    rb = SparseTable(**_CFG)
    ra.load_state_bytes(a.state_bytes())
    rb.load_state_bytes(b.state_bytes())
    np.testing.assert_array_equal(rb.pull(ids), ra.pull(ids))
    # optimizer state crossed too: post-handoff applies stay identical
    ra.push(ids, g)
    rb.push(ids, g)
    np.testing.assert_array_equal(rb.pull(ids), ra.pull(ids))


@requires_native
def test_ttl_sweep_demotes_instead_of_evicting(tmp_path):
    t = SparseTable(**_CFG)
    assert t.enable_spill(str(tmp_path / "ttl"))
    ids = np.arange(100, dtype=np.int64)
    vals = t.pull(ids).copy()
    n = len(t)
    assert t.spill_sweep(_FUTURE()) == 100
    # nothing evicted: the id set is intact, values come back from the
    # cold tier unchanged, and stats account for the move
    assert len(t) == n
    st = t.spill_stats()
    assert st == {"hot": 0, "cold": 100, "promoted": 0, "demoted": 100}
    np.testing.assert_array_equal(t.pull(ids), vals)
    assert t.spill_stats()["promoted"] == 100


@requires_native
def test_spill_recovery_bit_exact(tmp_path):
    sdir = str(tmp_path / "rec")
    ids = np.arange(1000, dtype=np.int64)
    oracle = SparseTable(**_CFG)
    t = SparseTable(**_CFG)
    assert t.enable_spill(sdir)
    g = np.random.RandomState(4).randn(ids.size,
                                       _CFG["dim"]).astype(np.float32)
    for tab in (oracle, t):
        tab.pull(ids)
        tab.push(ids, g)
    t.spill_sweep(_FUTURE())
    del t
    r = SparseTable(**_CFG)
    assert r.recover_spill(sdir) == ids.size
    np.testing.assert_array_equal(r.pull(ids), oracle.pull(ids))
    # recovered rows carry optimizer state: the next push stays exact
    r.push(ids, g)
    oracle.push(ids, g)
    np.testing.assert_array_equal(r.pull(ids), oracle.pull(ids))


_KILL_CHILD = r"""
import sys, time
import numpy as np
sys.path.insert(0, {repo!r})
from paddle_tpu.distributed.fleet.ps import SparseTable
t = SparseTable(dim=8, optimizer="adam", lr=0.01, seed=3, init_std=0.05)
assert t.enable_spill({sdir!r})
ids = np.arange(5000, dtype=np.int64)
t.pull(ids)
g = np.random.RandomState(4).randn(5000, 8).astype(np.float32)
t.push(ids, g)
t.spill_sweep(int(time.time() * 1000) + 60_000)
print("SWEEPING", flush=True)
while True:  # promote/demote churn until SIGKILLed mid-sweep
    t.pull(ids)
    t.spill_sweep(int(time.time() * 1000) + 60_000)
"""


@requires_native
def test_sigkill_mid_sweep_recovers_committed_rows(tmp_path):
    """SIGKILL while demotion churn is rewriting spill records: every
    record the recovery accepts must be bit-exact (the commit mark
    lands after the payload, so torn records are invisible)."""
    sdir = str(tmp_path / "kill")
    p = subprocess.Popen(
        [sys.executable, "-c",
         _KILL_CHILD.format(repo=_REPO, sdir=sdir)],
        stdout=subprocess.PIPE, text=True)
    try:
        assert p.stdout.readline().strip() == "SWEEPING"
        time.sleep(0.15)  # land mid promote/demote churn
    finally:
        p.kill()
        p.wait()
    r = SparseTable(**_CFG)
    ids = np.arange(5000, dtype=np.int64)
    recovered = r.recover_spill(sdir)
    assert 0 <= recovered <= ids.size
    # oracle = the child's deterministic history (same seed, same push)
    oracle = SparseTable(**_CFG)
    oracle.pull(ids)
    g = np.random.RandomState(4).randn(5000, 8).astype(np.float32)
    oracle.push(ids, g)
    r.save(str(tmp_path / "r"))
    d = np.load(str(tmp_path / "r.npz"))
    got_ids = np.asarray(d["ids"], np.int64)
    assert got_ids.size == recovered
    if recovered:
        np.testing.assert_array_equal(
            np.asarray(d["vals"], np.float32), oracle.pull(got_ids))


@requires_native
def test_simd_toggle_is_bit_exact():
    if not SparseTable.simd_available():
        pytest.skip("native core built without AVX2")
    ids = np.arange(333, dtype=np.int64)
    g = np.random.RandomState(5).randn(ids.size, 8).astype(np.float32)
    out = {}
    try:
        for simd in (True, False):
            SparseTable.set_simd(simd)
            t = SparseTable(**_CFG)
            t.pull(ids)
            for _ in range(3):
                t.push(ids, g)
                t.push_delta(ids, g * 0.5)
            out[simd] = t.pull(ids)
    finally:
        SparseTable.set_simd(True)
    np.testing.assert_array_equal(out[True], out[False])
