"""Parameter-server end-to-end tests.

Parity model: reference test_dist_fleet_ps*.py / test_dist_ctr*.py —
multi-server localhost cluster, workers pull/push sparse params, train a
rec-model (wide&deep-style, BASELINE config #4) and assert the loss
drops. Servers here run in-process threads (the reference spawns
processes; the socket protocol is identical either way).
"""
import numpy as np
import pytest

from paddle_tpu.distributed.fleet.ps import SparseTable
from paddle_tpu.distributed.fleet.ps_service import PSClient, PSServer


def _cluster(n_servers, dim=8, optimizer="sgd", lr=0.1):
    servers, endpoints = [], []
    for _ in range(n_servers):
        tables = {"emb": SparseTable(dim, optimizer=optimizer, lr=lr)}
        srv = PSServer(tables, host="127.0.0.1")
        srv.start()
        servers.append(srv)
        endpoints.append(f"127.0.0.1:{srv.port}")
    return servers, endpoints


def test_sync_pull_push_two_servers():
    servers, eps = _cluster(2, dim=4, lr=0.5)
    try:
        cli = PSClient(eps, mode="sync")
        ids = np.array([0, 1, 2, 3, 10, 11], np.int64)
        vals = cli.pull("emb", ids)
        assert vals.shape == (6, 4)
        g = np.ones((6, 4), np.float32)
        cli.push("emb", ids, g)
        after = cli.pull("emb", ids)
        np.testing.assert_allclose(after, vals - 0.5, rtol=1e-5)
        # shard routing: even ids on server0, odd on server1
        assert len(servers[0]._tables["emb"]) == 3
        assert len(servers[1]._tables["emb"]) == 3
        cli.close()
    finally:
        for s in servers:
            s.stop()


def test_async_push_applied_after_barrier():
    servers, eps = _cluster(2, dim=4, lr=1.0)
    try:
        cli = PSClient(eps, mode="async")
        ids = np.arange(8, dtype=np.int64)
        base = cli.pull("emb", ids).copy()
        for _ in range(5):
            cli.push("emb", ids, np.ones((8, 4), np.float32))
        cli.barrier()
        after = cli.pull("emb", ids)
        np.testing.assert_allclose(after, base - 5.0, rtol=1e-5)
        cli.close()
    finally:
        for s in servers:
            s.stop()


def test_geo_delta_push():
    servers, eps = _cluster(1, dim=3)
    try:
        cli = PSClient(eps, mode="sync")
        ids = np.array([5, 6], np.int64)
        base = cli.pull("emb", ids).copy()
        # geo semantics: worker trains a local mirror, pushes raw deltas
        cli._rpc(0, {"op": "push_delta", "table": "emb", "ids": ids,
                     "deltas": np.full((2, 3), 0.25, np.float32),
                     "sync": True}, reply=True)
        np.testing.assert_allclose(cli.pull("emb", ids), base + 0.25,
                                   rtol=1e-5)
        cli.close()
    finally:
        for s in servers:
            s.stop()


def test_empty_pull():
    servers, eps = _cluster(1, dim=4)
    try:
        cli = PSClient(eps, mode="sync")
        out = cli.pull("emb", np.zeros(0, np.int64))
        assert out.shape == (0, 4)
        cli.close()
    finally:
        for s in servers:
            s.stop()


def test_wide_deep_training_slice(tmp_path):
    """BASELINE config #4: wide&deep on MultiSlot data with host-side
    sparse embeddings + TPU(jax) dense tower. Loss must drop."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.distributed.fleet.dataset import InMemoryDataset

    # synthetic CTR data: click correlates with presence of low ids
    rng = np.random.RandomState(0)
    lines = []
    for _ in range(512):
        n_ids = rng.randint(1, 5)
        ids = rng.randint(0, 1000, size=n_ids)
        click = 1 if (ids < 300).any() else 0
        dense = rng.rand(4)
        lines.append(f"1 {click} {n_ids} " + " ".join(map(str, ids)) +
                     " 4 " + " ".join(f"{v:.4f}" for v in dense))
    f = tmp_path / "ctr.txt"
    f.write_text("\n".join(lines) + "\n")

    ds = InMemoryDataset()
    ds.set_batch_size(64)
    ds.set_use_var(["click", "ids",
                    {"name": "dense", "is_dense": True, "dim": 4}])
    ds.set_filelist([str(f)])
    ds.load_into_memory()

    dim = 8
    table = SparseTable(dim, optimizer="adagrad", lr=0.1, seed=3)
    w = {"w1": jnp.zeros((dim + 4, 16)), "b1": jnp.zeros((16,)),
         "w2": jnp.zeros((16, 1)), "b2": jnp.zeros((1,))}
    key = jax.random.PRNGKey(0)
    w["w1"] = jax.random.normal(key, (dim + 4, 16)) * 0.1
    w["w2"] = jax.random.normal(jax.random.fold_in(key, 1), (16, 1)) * 0.1

    @jax.jit
    def step(w, emb, dense, y):
        def loss_fn(w, emb):
            x = jnp.concatenate([emb, dense], axis=1)
            h = jnp.tanh(x @ w["w1"] + w["b1"])
            logit = (h @ w["w2"] + w["b2"])[:, 0]
            return jnp.mean(
                jnp.maximum(logit, 0) - logit * y +
                jnp.log1p(jnp.exp(-jnp.abs(logit))))
        l, grads = jax.value_and_grad(loss_fn, argnums=(0, 1))(w, emb)
        gw, gemb = grads
        w = jax.tree_util.tree_map(lambda p, g: p - 0.5 * g, w, gw)
        return w, gemb, l

    losses = []
    for epoch in range(4):
        ds.local_shuffle(seed=epoch)
        ep_loss = []
        for batch in ds:
            ids, lod = batch["ids"]
            y = np.asarray(batch["click"][0], np.float32)
            # mean-pool variable-length id embeddings per record
            rows = table.pull(ids)
            seg = np.repeat(np.arange(len(lod) - 1),
                            np.diff(lod)).astype(np.int32)
            cnt = np.maximum(np.diff(lod), 1).astype(np.float32)
            pooled = np.zeros((len(lod) - 1, dim), np.float32)
            np.add.at(pooled, seg, rows)
            pooled /= cnt[:, None]
            w, gemb, l = step(w, jnp.asarray(pooled),
                              jnp.asarray(batch["dense"]), jnp.asarray(y))
            # scatter pooled grad back to ids and push
            grows = (np.asarray(gemb) / cnt[:, None])[seg]
            table.push(ids, grows)
            ep_loss.append(float(l))
        losses.append(np.mean(ep_loss))
    assert losses[-1] < losses[0] * 0.8, losses
