"""Feature lifecycle at the table (ISSUE 14): TTL/decay eviction.

Acceptance contracts:
- an expired id is gone EVERYWHERE — pull re-materialises fresh,
  checkpoints and replica snapshots no longer carry it;
- a surviving id's value AND per-row optimizer moments are
  bit-identical across the sweep, across backends, and across the
  checkpoint round trip;
- evictions replicate down the mutation stream: a read replica drops
  the exact same ids and keeps version parity with the primary;
- churn counters (``ps_feature_admitted`` / ``ps_feature_evicted``)
  appear on /metrics;
- :class:`FeatureLifecycle` grandfathers pre-sweeper rows (no tick-0
  mass eviction) and expires by last sighting, deterministically via
  an injected clock.
"""
import io
import time

import numpy as np
import pytest

from paddle_tpu.distributed.entry import CountFilterEntry
from paddle_tpu.distributed.fleet.ps import SparseTable
from paddle_tpu.distributed.fleet.ps_service import PSClient, PSServer
from paddle_tpu.online import FeatureLifecycle

_FAST = dict(connect_timeout=2.0, rpc_timeout=1.0, max_retries=6,
             backoff_base=0.02, rpc_deadline=20.0)


def _backends():
    # python backend needs init_std=0 for the deterministic-init checks
    return [dict(use_native=True),
            dict(use_native=False, init_std=0.0)]


def _full_rows(t):
    """id -> full replication row (value | moments | step) from the
    snapshot bytes — the bit-identity oracle."""
    d = np.load(io.BytesIO(t.state_bytes()))
    ids = d["ids"]
    rows = np.concatenate([d["vals"], d["opt_state"]], axis=1)
    return {int(i): rows[k].copy() for k, i in enumerate(ids)}


@pytest.mark.parametrize("kw", _backends())
def test_sweep_evicts_stale_keeps_survivors_bit_exact(kw):
    t = SparseTable(4, optimizer="adam", lr=0.1, seed=3, **kw)
    t.set_clock(1000)
    ids = np.arange(20, dtype=np.int64)
    for _ in range(3):   # build non-trivial adam moments + steps
        t.push(ids, np.ones((20, 4), np.float32))
    t.set_clock(2000)
    t.pull(np.arange(8, dtype=np.int64))      # refresh 0..7 only
    before = _full_rows(t)
    evicted = t.ttl_sweep(1500)
    assert list(evicted) == list(range(8, 20))
    assert len(t) == 8 and t.evicted_total == 12
    after = _full_rows(t)
    assert sorted(after) == list(range(8))
    for k, row in after.items():
        # value AND optimizer moments AND step counter: exact bits
        assert np.array_equal(row, before[k]), k


@pytest.mark.parametrize("kw", _backends())
def test_expired_id_rematerialises_fresh_and_deterministic(kw):
    t = SparseTable(4, optimizer="adagrad", lr=0.5, seed=9, **kw)
    t.set_clock(10)
    t.push(np.array([7], np.int64), np.ones((1, 4), np.float32))
    t.set_clock(99)
    assert list(t.ttl_sweep(50)) == [7]
    # the evicted id pulls the same deterministic init a fresh table
    # materialises — no trace of the trained row or its moments
    fresh = SparseTable(4, optimizer="adagrad", lr=0.5, seed=9, **kw)
    assert np.array_equal(t.pull(np.array([7], np.int64)),
                          fresh.pull(np.array([7], np.int64)))


@pytest.mark.parametrize("kw", _backends())
def test_checkpoint_after_sweep_round_trips_exact(kw):
    t = SparseTable(4, optimizer="adam", lr=0.1, seed=3, **kw)
    t.set_clock(100)
    t.push(np.arange(12, dtype=np.int64), np.ones((12, 4), np.float32))
    t.set_clock(200)
    t.pull(np.arange(6, dtype=np.int64))
    t.ttl_sweep(150)
    before = _full_rows(t)
    t2 = SparseTable(4, optimizer="adam", lr=0.1, seed=3, **kw)
    t2.load_state_bytes(t.state_bytes())
    after = _full_rows(t2)
    assert sorted(after) == sorted(before) == list(range(6))
    for k in before:
        assert np.array_equal(before[k], after[k])
    assert t2.version == t.version


def test_cross_backend_snapshot_after_sweep():
    """A python replica of a swept native table (and vice versa)
    inherits the exact surviving rows."""
    a = SparseTable(4, optimizer="adam", lr=0.1, seed=3, use_native=True)
    a.set_clock(10)
    a.push(np.arange(10, dtype=np.int64), np.ones((10, 4), np.float32))
    a.set_clock(50)
    a.pull(np.arange(4, dtype=np.int64))
    a.ttl_sweep(30)
    b = SparseTable(4, optimizer="adam", lr=0.1, seed=3,
                    use_native=False)
    b.load_state_bytes(a.state_bytes())
    ra, rb = _full_rows(a), _full_rows(b)
    assert sorted(ra) == sorted(rb) == list(range(4))
    for k in ra:
        assert np.array_equal(ra[k], rb[k])


def test_entry_counter_slots_expire_and_readmission_restarts():
    t = SparseTable(4, optimizer="sgd", lr=0.1, seed=0,
                    entry=CountFilterEntry(3))
    t.set_clock(10)
    t.pull(np.array([5], np.int64))   # 1 sighting — counter slot only
    t.pull(np.array([5], np.int64))   # 2 sightings
    t.set_clock(99)
    assert list(t.ttl_sweep(50)) == [5]
    # the counter was wiped: two more sightings still pull zeros, the
    # third admits — admission restarts from ZERO after expiry
    t.set_clock(100)
    assert np.all(t.pull(np.array([5], np.int64)) == 0.0)
    assert np.all(t.pull(np.array([5], np.int64)) == 0.0)
    assert not np.all(t.pull(np.array([5], np.int64)) == 0.0)


def test_evict_ids_replay_matches_and_ticks_version():
    t = SparseTable(4, optimizer="sgd", lr=0.1, seed=0)
    t.push(np.arange(6, dtype=np.int64), np.ones((6, 4), np.float32))
    v0 = t.version
    n = t.evict_ids(np.array([1, 3, 99], np.int64))
    assert n == 2 and len(t) == 4
    assert t.version == v0 + 1
    # absent-id replay still ticks version (parity with the primary's
    # sweep that produced the record)
    t.evict_ids(np.array([1], np.int64))
    assert t.version == v0 + 2


def test_primary_sweep_replicates_evictions_to_read_replica():
    spec = dict(dim=4, optimizer="adagrad", lr=0.1, seed=7)
    prim = PSServer({"emb": SparseTable(**spec)}, host="127.0.0.1")
    prim.start()
    rep = PSServer({"emb": SparseTable(**spec)}, host="127.0.0.1",
                   replica_of=f"127.0.0.1:{prim.port}",
                   replica_mode="read", wm_interval_s=0.05)
    rep.start()
    try:
        assert rep.replica_ready.wait(10.0)
        w = PSClient([f"127.0.0.1:{prim.port}"], **_FAST)
        ids = np.arange(30, dtype=np.int64)
        w.push("emb", ids, np.ones((30, 4), np.float32))
        # refresh 0..9 at a later tick, then sweep the rest out
        now = time.time()
        prim._tables["emb"].set_clock(int((now + 100) * 1000))
        prim._tables["emb"].pull(np.arange(10, dtype=np.int64))
        out = prim.ttl_sweep(cutoff=now + 50, now=now + 100)
        assert out == {"emb": 20}
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline \
                and len(rep._tables["emb"]) != 10:
            time.sleep(0.05)
        assert len(rep._tables["emb"]) == 10
        # version parity: the audited catch-up invariant includes the
        # eviction batch
        assert rep._tables["emb"].version == prim._tables["emb"].version
        # surviving rows bit-equal primary's
        a = prim._tables["emb"]._snapshot_arrays(full_state=True)
        b = rep._tables["emb"]._snapshot_arrays(full_state=True)
        oa, ob = np.argsort(a["ids"]), np.argsort(b["ids"])
        assert np.array_equal(a["ids"][oa], b["ids"][ob])
        assert np.array_equal(a["vals"][oa], b["vals"][ob])
        assert np.array_equal(a["opt_state"][oa], b["opt_state"][ob])
        w.close()
    finally:
        rep.stop()
        prim.stop()


def test_lifecycle_grandfathers_then_expires_deterministically():
    """Injected clock: rows created before the sweeper existed age
    from the sweeper's first pass (touch_all), not from tick zero."""
    clock = [1000.0]
    spec = dict(dim=4, optimizer="sgd", lr=0.1, seed=1)
    srv = PSServer({"emb": SparseTable(**spec)}, host="127.0.0.1")
    srv.start()
    try:
        t = srv._tables["emb"]
        t.push(np.arange(8, dtype=np.int64), np.ones((8, 4), np.float32))
        fl = FeatureLifecycle(srv, ttl_s=60.0, interval_s=999.0,
                              time_fn=lambda: clock[0])
        # first pass primes: nothing evicts even though the rows were
        # touched long before the sweeper's clock domain existed
        assert fl.sweep_once() == {"emb": 0}
        clock[0] = 1030.0                      # inside ttl
        assert fl.sweep_once() == {"emb": 0}
        # refresh half at t=1040, sweep at t=1095 (cutoff 1035): the
        # grandfathered half (stamped 1000 < 1035) expires, the
        # refreshed half (1040 >= 1035) survives
        t.set_clock(int(1040.0 * 1000))
        t.pull(np.arange(4, dtype=np.int64))
        clock[0] = 1095.0
        out = fl.sweep_once()
        assert out == {"emb": 4}, out
        assert sorted(
            int(i) for i in
            t._snapshot_arrays()["ids"]) == [0, 1, 2, 3]
        assert fl.evicted == 4 and fl.sweeps == 3
    finally:
        srv.stop()


def test_churn_counters_on_metrics_exposition():
    from paddle_tpu.framework import monitor
    from paddle_tpu.observability.metrics import prometheus_text
    spec = dict(dim=4, optimizer="sgd", lr=0.1, seed=2)
    srv = PSServer({"emb": SparseTable(**spec)}, host="127.0.0.1")
    srv.start()
    try:
        t = srv._tables["emb"]
        t.push(np.arange(5, dtype=np.int64), np.ones((5, 4), np.float32))
        now = time.time()
        t.touch_all(int(now * 1000))
        srv.ttl_sweep(cutoff=now + 50, now=now + 100)   # evicts all 5
        text = prometheus_text(monitor.metrics_snapshot())
        assert "ps_feature_admitted" in text
        assert "ps_feature_evicted" in text
    finally:
        srv.stop()


def test_observability_wiring():
    import os
    import sys
    from paddle_tpu.observability.flight_recorder import _PROGRESS_KINDS
    assert {"ps.ttl_sweep", "online.ingest"} <= set(_PROGRESS_KINDS)
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools"))
    import postmortem
    assert postmortem._is_bad({"kind": "online.freshness_breach"})
    from paddle_tpu.analysis import DEFAULT_LINT_PATHS, lint_file
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for m in ("streaming", "lifecycle", "freshness"):
        p = f"paddle_tpu/online/{m}.py"
        assert p in DEFAULT_LINT_PATHS
        assert lint_file(os.path.join(repo, p)) == []
