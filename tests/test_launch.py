"""Launcher watchdog regressions (ISSUE 9 satellite 2) + elastic mode.

Pinned behaviours: a worker killed by signal exits the launcher with
``128 + signum`` (not a raw negative code), the per-worker log handle
closes even when ``proc.wait()`` raises, and ``--elastic`` restarts a
failed worker within the budget.
"""
import os
import signal
import subprocess
import sys
import textwrap

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _launch(args, script_body, tmp_path, env_extra=None, name="w.py"):
    script = tmp_path / name
    script.write_text(textwrap.dedent(script_body))
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO
    env.update(env_extra or {})
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         *args, str(script)],
        env=env, cwd=str(tmp_path), capture_output=True, text=True,
        timeout=60)
    return proc


@pytest.mark.parametrize("sig,code", [(signal.SIGKILL, 137),
                                      (signal.SIGTERM, 143)])
def test_signal_death_normalizes_to_128_plus_signum(tmp_path, sig, code):
    proc = _launch([], f"""
        import os, signal
        os.kill(os.getpid(), {int(sig)})
    """, tmp_path)
    assert proc.returncode == code, proc.stderr


def test_plain_exit_code_passes_through(tmp_path):
    proc = _launch([], "raise SystemExit(7)", tmp_path)
    assert proc.returncode == 7


def test_log_handle_closed_when_wait_raises(tmp_path, monkeypatch):
    """The watchdog used to leak the worker log descriptor when
    ``proc.wait()`` raised; it must close in ``finally``."""
    from paddle_tpu.distributed import launch as launch_mod

    opened = []
    real_open = launch_mod._open_log
    monkeypatch.setattr(launch_mod, "_open_log",
                        lambda p: opened.append(real_open(p)) or opened[-1])

    class _Boom:
        returncode = None

        def __init__(self, *a, **kw):
            pass

        def wait(self):
            raise KeyboardInterrupt

        def send_signal(self, sig):
            pass

    monkeypatch.setattr(launch_mod.subprocess, "Popen", _Boom)
    with pytest.raises(KeyboardInterrupt):
        launch_mod._run_worker(
            [sys.executable, "-c", "pass"], dict(os.environ),
            str(tmp_path / "worker.log"), forward_signals=False)
    assert len(opened) == 1 and opened[0].closed


def test_elastic_restarts_failed_worker_until_success(tmp_path):
    """--elastic: a worker that dies (once) is restarted with
    PADDLE_ELASTIC_RESTART bumped and the launcher exits 0 when the
    retry succeeds; the restart appends to the same log."""
    proc = _launch(
        ["--elastic", "--max_restarts", "2", "--restart_backoff", "0.05",
         "--log_dir", str(tmp_path / "logs")], """
        import os, signal
        n = int(os.environ["PADDLE_ELASTIC_RESTART"])
        assert os.environ.get("PADDLE_ELASTIC") == "1"
        print(f"incarnation {n}", flush=True)
        if n == 0:
            os.kill(os.getpid(), signal.SIGKILL)
        print("recovered", flush=True)
    """, tmp_path)
    assert proc.returncode == 0, proc.stderr
    assert "elastic restart 1/2" in proc.stderr
    log = (tmp_path / "logs" / "worker.0.log").read_text()
    assert "incarnation 0" in log and "incarnation 1" in log
    assert "recovered" in log


def test_elastic_budget_exhaustion_surfaces_failure_code(tmp_path):
    proc = _launch(
        ["--elastic", "--max_restarts", "1",
         "--restart_backoff", "0.05"], """
        import os, signal
        os.kill(os.getpid(), signal.SIGKILL)
    """, tmp_path)
    assert proc.returncode == 137
    assert "restart budget" in proc.stderr


def test_elastic_rank0_hosts_coordinator_when_env_unset(tmp_path):
    """--elastic with no PADDLE_COORDINATOR: the rank-0 launcher starts
    an in-process coordinator and exports its address to the worker."""
    env = dict(os.environ)
    env.pop("PADDLE_COORDINATOR", None)
    proc = _launch(["--elastic", "--max_restarts", "0"], """
        import os, socket
        ep = os.environ["PADDLE_COORDINATOR"]
        host, port = ep.rsplit(":", 1)
        s = socket.create_connection((host, int(port)), timeout=5)
        s.close()
    """, tmp_path, env_extra={"PADDLE_COORDINATOR": ""})
    assert proc.returncode == 0, proc.stderr
