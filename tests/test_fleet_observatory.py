"""ISSUE 12: fleet observatory — labeled metrics, per-request trace
lanes + tenant accounting, the cross-process aggregator (exact merge,
rates, straggler/stale flagging), and the SLO burn-rate engine.

Coverage map:
- labeled counters/gauges/histograms in the registry + Prometheus
  rendering (unlabeled exposition stays byte-stable — the ISSUE 5
  golden test next door pins that independently);
- Histogram.from_snapshot round trip + exact cross-process histogram
  merge (merged percentiles vs numpy on the pooled samples);
- FleetAggregator over flusher JSONL files and live endpoints:
  rollup sums EXACTLY, rates from sample timestamps, straggler =
  below-median by k x MAD (>= 3 procs, degenerate fleets never flag),
  stale scrapees flagged AND excluded from the rollup;
- SloEngine: multi-window error-rate burn, latency-histogram
  objectives, gauge bounds; breach emits ONE latched flight event;
- request lanes on GenerationServer (span chain per request, TTFT
  agreement with serve_ttft_ms) and tenant tags on both servers;
- the acceptance e2e: 8 concurrent streams across 2 tenants on a
  prefix-sharing GenerationServer + subprocess PS primary + read
  replicas, one artificially delayed, all scraped by ONE aggregator —
  rollup exactness, straggler flag, TTFT SLO breach -> flight bundle
  -> tools/postmortem.py renders the request lane + breach marker.
"""
import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.framework import monitor
from paddle_tpu.observability import flight_recorder
from paddle_tpu.observability import metrics as obs_metrics
from paddle_tpu.observability import trace
from paddle_tpu.observability.aggregator import (FleetAggregator,
                                                 merge_histograms,
                                                 merge_snapshots)
from paddle_tpu.observability.slo import SLO, SloEngine

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_POSTMORTEM = os.path.join(_REPO, "tools", "postmortem.py")
_FLEET_TOP = os.path.join(_REPO, "tools", "fleet_top.py")


@pytest.fixture(autouse=True)
def _obs_clean():
    """Same discipline as test_observability.py: per-test tracing
    state must never leak into the next test (the --trace pass runs
    the whole suite with PADDLE_TRACE=1)."""
    yield
    trace.disable()
    monitor.enable_metrics(os.environ.get("PADDLE_METRICS", "0") == "1")


def _read_sink(path):
    with open(path) as f:
        return [json.loads(ln) for ln in f if ln.strip()]


def _hist_snap(samples, bounds):
    h = monitor.Histogram(buckets=bounds)
    for s in samples:
        h.observe(s)
    return h.snapshot()


def _empty_hist_baseline(name):
    """A zero-count series for priming an SloEngine's first burn
    sample when the process had no observations before the window of
    interest."""
    return {"counters": {}, "gauges": {},
            "histograms": {name: {"buckets": [], "sum": 0.0,
                                  "count": 0}}}


# ---------------------------------------------------------------------------
# labeled metrics
# ---------------------------------------------------------------------------

def test_labeled_series_in_registry_and_exposition():
    monitor.stat_add("obs12_tok", 5, labels={"tenant": "a"})
    monitor.stat_add("obs12_tok", 7, labels={"tenant": "b"})
    monitor.stat_add("obs12_tok", 2)
    monitor.gauge_set("obs12_burn", 1.5,
                      labels={"slo": "ttft", "window": "60"})
    monitor.hist_observe("obs12_ms", 3.0, buckets=(1.0, 5.0),
                         labels={"tenant": "a"})
    assert monitor.stat_get("obs12_tok", labels={"tenant": "a"}) == 5
    assert monitor.stat_get("obs12_tok") == 2
    snap = monitor.metrics_snapshot()
    assert snap["labeled"]["counters"]["obs12_tok"] == {
        'tenant="a"': 5, 'tenant="b"': 7}
    txt = obs_metrics.prometheus_text(snap)
    # one TYPE line per family, unlabeled sample first, labels sorted
    assert 'paddle_obs12_tok 2\npaddle_obs12_tok{tenant="a"} 5\n' \
           'paddle_obs12_tok{tenant="b"} 7' in txt
    assert txt.count("# TYPE paddle_obs12_tok counter") == 1
    assert 'paddle_obs12_burn{slo="ttft",window="60"} 1.5' in txt
    assert 'paddle_obs12_ms_bucket{tenant="a",le="5"} 1' in txt
    assert 'paddle_obs12_ms_sum{tenant="a"} 3.0' in txt


def test_unlabeled_snapshot_has_no_labeled_key():
    """Label-free processes keep the exact pre-label snapshot shape
    (flusher byte-stability)."""
    monitor.metrics_reset()
    monitor.stat_add("obs12_plain", 1)
    snap = monitor.metrics_snapshot()
    assert set(snap) == {"counters", "gauges", "histograms"}


def test_histogram_from_snapshot_round_trip():
    h = monitor.Histogram(buckets=(1.0, 5.0, 25.0))
    for v in (0.2, 3.0, 3.5, 20.0, 99.0):
        h.observe(v)
    h2 = monitor.Histogram.from_snapshot(h.snapshot())
    assert h2.counts == h.counts
    assert h2.sum == h.sum and h2.count == h.count
    for q in (10, 50, 99):
        assert h2.percentile(q) == h.percentile(q)


# ---------------------------------------------------------------------------
# exact merge
# ---------------------------------------------------------------------------

def test_merge_histograms_exact_and_percentiles_match_numpy():
    rng = np.random.RandomState(7)
    s1 = rng.uniform(0.0, 100.0, 5000)
    s2 = rng.uniform(20.0, 120.0, 3000)
    bounds = [float(b) for b in range(1, 131)]
    m = merge_histograms(_hist_snap(s1, bounds), _hist_snap(s2, bounds))
    assert m["count"] == 8000
    assert m["sum"] == pytest.approx(s1.sum() + s2.sum())
    pooled = np.concatenate([s1, s2])
    h = monitor.Histogram.from_snapshot(m)
    for q in (10, 50, 90, 99):
        est, ref = h.percentile(q), float(np.percentile(pooled, q))
        assert abs(est - ref) < 1.5, (q, est, ref)   # bucket width 1
    # mismatched bounds refuse instead of merging garbage
    assert merge_histograms(_hist_snap(s1, bounds),
                            _hist_snap(s2, [1.0, 2.0])) is None


def test_merge_snapshots_counters_gauges_labels():
    a = {"counters": {"x": 3, "y": 1}, "gauges": {"lag": 1.0},
         "histograms": {},
         "labeled": {"counters": {"tok": {'tenant="a"': 5}},
                     "gauges": {}, "histograms": {}}}
    b = {"counters": {"x": 4}, "gauges": {"lag": 4.0},
         "histograms": {},
         "labeled": {"counters": {"tok": {'tenant="a"': 2,
                                          'tenant="b"': 9}},
                     "gauges": {}, "histograms": {}}}
    m = merge_snapshots([a, b])
    assert m["counters"] == {"x": 7, "y": 1}
    assert m["gauges"]["lag"] == 4.0           # fleet MAX
    assert m["labeled"]["counters"]["tok"] == {'tenant="a"': 7,
                                               'tenant="b"': 9}


# ---------------------------------------------------------------------------
# aggregator over flusher files: rates, stragglers, staleness
# ---------------------------------------------------------------------------

def _write_flusher(path, recs):
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")


def _proc_file(tmp_path, role, pid, pulls_then, pulls_now, now_us,
               extra_now=None):
    p = tmp_path / f"metrics-{role}.jsonl"
    rec0 = {"ts_us": now_us - 2_000_000, "role": role, "pid": pid,
            "counters": {"pulls": pulls_then}, "gauges": {},
            "histograms": {}}
    rec1 = {"ts_us": now_us, "role": role, "pid": pid,
            "counters": {"pulls": pulls_now}, "gauges": {},
            "histograms": {}}
    if extra_now:
        rec1.update(extra_now)
    _write_flusher(p, [rec0, rec1])
    return str(p)


def test_aggregator_rates_and_straggler_flagging(tmp_path):
    """A static pair of flusher records per process gives exact,
    deterministic rates (delta / record-timestamp dt); the process
    whose rate sits below the median by > k x MAD is flagged."""
    now_us = time.time_ns() // 1000
    files = [
        _proc_file(tmp_path, "ps0", 11, 0, 2000, now_us),    # 1000/s
        _proc_file(tmp_path, "rep1", 12, 0, 1900, now_us),   # 950/s
        _proc_file(tmp_path, "rep2", 13, 0, 50, now_us),     # 25/s
    ]
    agg = FleetAggregator(files, interval_s=1.0, stale_after_s=3600.0,
                          straggler_key="pulls")
    fleet = agg.scrape_once()
    rates = {t: v["rates"]["pulls"]
             for t, v in fleet["targets"].items()}
    assert rates == {"ps0-11": 1000.0, "rep1-12": 950.0,
                     "rep2-13": 25.0}
    assert fleet["stragglers"] == ["rep2-13"]
    assert fleet["rollup"]["counters"]["pulls"] == 3950
    # the straggler transition is a flight event (postmortem marker)
    evs = [e for e in flight_recorder.recorder().events()
           if e["kind"] == "fleet.straggler"]
    assert any(e.get("proc") == "rep2-13" for e in evs)


def test_aggregator_two_proc_fleet_never_flags(tmp_path):
    """MAD over 2 processes is degenerate (each deviation == MAD) —
    two processes cannot outvote each other, so nothing is flagged no
    matter how far apart they sit."""
    now_us = time.time_ns() // 1000
    files = [
        _proc_file(tmp_path, "a", 1, 0, 2000, now_us),
        _proc_file(tmp_path, "b", 2, 0, 2, now_us),
    ]
    agg = FleetAggregator(files, interval_s=1.0, stale_after_s=3600.0,
                          straggler_key="pulls")
    assert agg.scrape_once()["stragglers"] == []


def test_aggregator_stale_target_flagged_and_excluded(tmp_path):
    """A scrapee whose newest sample is too old (or whose endpoint is
    dead) is flagged stale and its counters LEAVE the rollup — a dead
    process must not freeze into the fleet sums forever."""
    now_us = time.time_ns() // 1000
    live = _proc_file(tmp_path, "live", 1, 0, 100, now_us)
    dead = tmp_path / "metrics-dead.jsonl"
    _write_flusher(dead, [
        {"ts_us": now_us - 3600_000_000, "role": "dead", "pid": 9,
         "counters": {"pulls": 7777}, "gauges": {}, "histograms": {}}])
    gone = str(tmp_path / "metrics-gone.jsonl")     # never existed
    agg = FleetAggregator([live, str(dead), gone], interval_s=1.0,
                          stale_after_s=60.0)
    fleet = agg.scrape_once()
    assert set(fleet["stale"]) == {"dead-9", gone}
    assert fleet["rollup"]["counters"]["pulls"] == 100
    assert fleet["targets"]["dead-9"]["stale"]
    assert fleet["targets"][gone]["errors"] == 1


def test_aggregator_serves_fleet_and_merged_metrics(tmp_path):
    """serve(): /fleet returns the fleet JSON; /metrics renders the
    MERGED rollup (not the aggregator process's own registry)."""
    now_us = time.time_ns() // 1000
    files = [_proc_file(tmp_path, "a", 1, 0, 30, now_us,
                        extra_now={"counters": {"pulls": 30,
                                                "obs12_only_a": 4}}),
             _proc_file(tmp_path, "b", 2, 0, 12, now_us)]
    agg = FleetAggregator(files, interval_s=1.0, stale_after_s=3600.0)
    agg.scrape_once()
    srv = agg.serve(port=0, host="127.0.0.1")
    try:
        base = f"http://127.0.0.1:{srv.port}"
        with urllib.request.urlopen(base + "/fleet", timeout=5) as r:
            fleet = json.loads(r.read().decode())
        assert set(fleet["targets"]) == {"a-1", "b-2"}
        with urllib.request.urlopen(base + "/metrics", timeout=5) as r:
            body = r.read().decode()
        assert "paddle_pulls 42" in body
        assert "paddle_obs12_only_a 4" in body
    finally:
        agg.stop()


def test_fleet_top_once_renders_table(tmp_path):
    now_us = time.time_ns() // 1000
    files = [
        _proc_file(tmp_path, "ps0", 11, 0, 2000, now_us),
        _proc_file(tmp_path, "rep1", 12, 0, 1900, now_us),
        _proc_file(tmp_path, "rep2", 13, 0, 50, now_us),
    ]
    r = subprocess.run(
        [sys.executable, _FLEET_TOP, "--once", "--key", "pulls",
         "--stale-after", "3600", "--targets", ",".join(files)],
        capture_output=True, text=True, cwd=_REPO)
    assert r.returncode == 0, r.stderr
    assert "STRAGGLER" in r.stdout
    assert "fleet pulls total: 3950" in r.stdout
    assert "rep2-13" in r.stdout


# ---------------------------------------------------------------------------
# SLO engine
# ---------------------------------------------------------------------------

def _snap_counts(bad, tot):
    return {"counters": {"bad": bad, "tot": tot}, "gauges": {},
            "histograms": {}}


def test_slo_error_rate_multi_window_burn_and_latch():
    eng = SloEngine([SLO("obs12_shed", "error_rate", "bad",
                         total="tot", budget=0.01,
                         windows=((10.0, 10.0), (60.0, 5.0)),
                         min_events=10)])
    t0 = 1000.0
    assert eng.evaluate(_snap_counts(0, 0), now=t0)[0]["ok"]
    # 2% errors: burn 2.0 — below both thresholds
    assert eng.evaluate(_snap_counts(2, 100), now=t0 + 5)[0]["ok"]
    # short window spikes to 50% (burn 50) but the long window still
    # averages under its threshold -> NOT a breach (multi-window AND)
    st = eng.evaluate(_snap_counts(4, 104), now=t0 + 8)[0]
    assert st["ok"], st
    # sustained: both windows over threshold -> breach, one latched
    # flight event
    n0 = len([e for e in flight_recorder.recorder().events()
              if e.get("kind") == "slo.breach"])
    st = eng.evaluate(_snap_counts(80, 204), now=t0 + 50)[0]
    assert not st["ok"], st
    assert st["burn"]["60"] > 5.0
    eng.evaluate(_snap_counts(90, 220), now=t0 + 55)   # still bad
    evs = [e for e in flight_recorder.recorder().events()
           if e.get("kind") == "slo.breach"]
    assert len(evs) == n0 + 1
    assert evs[-1]["slo"] == "obs12_shed"
    # burn gauges published as labeled series
    assert monitor.gauge_get("slo_burn_rate",
                             labels={"slo": "obs12_shed",
                                     "window": "60"}) > 5.0
    assert monitor.gauge_get("slo_breached",
                             labels={"slo": "obs12_shed"}) == 1.0
    # recovery un-latches
    eng.evaluate(_snap_counts(90, 5000), now=t0 + 58)
    assert [e for e in flight_recorder.recorder().events()
            if e.get("kind") == "slo.recover"]


def test_slo_min_events_suppresses_noise():
    eng = SloEngine([SLO("obs12_noise", "error_rate", "bad",
                         total="tot", budget=0.01,
                         windows=((60.0, 1.0),), min_events=50)])
    t0 = 0.0
    eng.evaluate(_snap_counts(0, 0), now=t0)
    # 3 of 3 bad = burn 100, but 3 events < min_events
    assert eng.evaluate(_snap_counts(3, 3), now=t0 + 10)[0]["ok"]


def test_slo_latency_histogram_objective():
    bounds = [float(b) for b in range(1, 101)]
    eng = SloEngine([SLO("obs12_lat", "latency", "lat_ms", bound=50.0,
                         budget=0.10, windows=((60.0, 2.0),),
                         min_events=10)])
    t0 = 0.0
    fast = _hist_snap(np.full(100, 10.0), bounds)
    eng.evaluate({"counters": {}, "gauges": {},
                  "histograms": {"lat_ms": fast}}, now=t0)
    # next 100 samples all above the bound: window bad-rate ~50% vs
    # 10% budget -> burn ~5 -> breach
    slow = merge_histograms(fast, _hist_snap(np.full(100, 90.0),
                                             bounds))
    st = eng.evaluate({"counters": {}, "gauges": {},
                       "histograms": {"lat_ms": slow}}, now=t0 + 10)[0]
    assert not st["ok"], st


def test_slo_gauge_bound_immediate():
    eng = SloEngine([SLO("obs12_lag", "gauge_bound",
                         "ps_replica_lag_seq", bound=8.0)])
    ok = eng.evaluate({"counters": {}, "histograms": {},
                       "gauges": {"ps_replica_lag_seq": 3.0}})[0]
    assert ok["ok"]
    bad = eng.evaluate({"counters": {}, "histograms": {},
                        "gauges": {"ps_replica_lag_seq": 40.0}})[0]
    assert not bad["ok"] and bad["value"] == 40.0


# ---------------------------------------------------------------------------
# request lanes + tenants on the serving tier
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def lm():
    from paddle_tpu.text.models import LlamaForCausalLM, llama_tiny
    paddle.seed(0)
    cfg = llama_tiny(vocab_size=64, hidden_size=32,
                     intermediate_size=64, num_hidden_layers=2,
                     num_attention_heads=4, num_key_value_heads=2,
                     max_position_embeddings=64)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


def test_predictor_server_tenant_accounting():
    """Tenant counters on the fixed-shape server, no predictor build
    needed — the micro-batcher only requires .run()."""
    from paddle_tpu.inference.serving import PredictorServer

    class _Stub:
        def run(self, inputs):
            return [inputs[0] * 2.0]

    a0 = monitor.stat_get("serve_tenant_examples",
                          labels={"tenant": "obs12_a"})
    b0 = monitor.stat_get("serve_tenant_examples",
                          labels={"tenant": "obs12_b"})
    srv = PredictorServer(_Stub(), max_batch=8, max_wait_ms=1.0,
                          prewarm=False)
    srv.start()
    try:
        x = np.ones((2, 3), np.float32)
        out = srv.infer([x], tenant="obs12_a")
        assert np.array_equal(out[0], x * 2.0)
        srv.infer([x], tenant="obs12_a")
        srv.infer([np.ones((3, 3), np.float32)], tenant="obs12_b")
        srv.infer([x])                        # untagged rides along
    finally:
        srv.stop()
    assert monitor.stat_get("serve_tenant_examples",
                            labels={"tenant": "obs12_a"}) - a0 == 4
    assert monitor.stat_get("serve_tenant_examples",
                            labels={"tenant": "obs12_b"}) - b0 == 3
    assert monitor.gauge_get("serve_tenant_queue_ms",
                             labels={"tenant": "obs12_a"}) >= 0.0


def test_generation_request_lanes_and_tenant_sums(tmp_path, lm):
    """The per-request span chain exists and is self-consistent, the
    span-carried TTFT matches serve_ttft_ms EXACTLY, and per-tenant
    token counters sum to the untagged totals when every request is
    tagged."""
    from paddle_tpu.inference import GenerationServer
    monitor.enable_metrics(True)
    trace.enable(dir=str(tmp_path), role="gwunit")
    h0 = monitor.get_histogram("serve_ttft_ms")
    hc0, hs0 = (h0.count, h0.sum) if h0 is not None else (0, 0.0)
    d0 = {k: monitor.stat_get(k) for k in ("serve_tokens_in",
                                           "serve_tokens_out")}
    ta0 = {k: monitor.stat_get(f"serve_tenant_{k}",
                               labels={"tenant": "u_a"})
           for k in ("tokens_in", "tokens_out")}
    tb0 = {k: monitor.stat_get(f"serve_tenant_{k}",
                               labels={"tenant": "u_b"})
           for k in ("tokens_in", "tokens_out")}
    srv = GenerationServer(lm, num_slots=4, block_size=4,
                           max_model_len=32, prefix_cache=True,
                           max_prefill_batch=1)
    srv.start()
    rng = np.random.RandomState(0)
    pre = rng.randint(1, 64, (8,)).astype("int32")
    streams, lens = [], []
    try:
        # first request runs ALONE so its blocks land in the prefix
        # index before the burst — same-round siblings cannot alias a
        # prefix that is only indexed at post-prefill
        p0 = np.concatenate([pre,
                             rng.randint(1, 64, (3,)).astype("int32")])
        lens.append(p0.size)
        outs = [srv.submit(p0, max_new_tokens=5,
                           tenant="u_a").result(timeout=120)]
        for i in range(1, 4):
            p = np.concatenate(
                [pre, rng.randint(1, 64, (3 + i,)).astype("int32")])
            lens.append(p.size)
            streams.append(srv.submit(
                p, max_new_tokens=5,
                tenant="u_a" if i % 2 == 0 else "u_b"))
        outs += [s.result(timeout=120) for s in streams]
    finally:
        srv.stop()
        trace.disable()
    assert all(len(o) == 5 for o in outs)

    recs = _read_sink(tmp_path / f"trace-gwunit-{os.getpid()}.jsonl")
    spans = [r for r in recs if r.get("t") == "span"]
    roots = [s for s in spans if s["name"] == "req"]
    assert len(roots) == 4
    by_rid = {}
    for s in spans:
        rid = (s.get("args") or {}).get("rid")
        if rid is not None:
            by_rid.setdefault(rid, []).append(s)
    assert len(by_rid) == 4
    for rid, chain in by_rid.items():
        names = {s["name"] for s in chain}
        # the full lifecycle chain, one lane, one trace id
        assert {"req", "req.submit", "req.queue", "req.admit",
                "req.prefill", "req.first_token"} <= names
        assert len({s["tid"] for s in chain}) == 1
        assert len({s["trace"] for s in chain}) == 1
        root = next(s for s in chain if s["name"] == "req")
        assert root["args"]["lane"] == f"gen-req-{rid}"
        assert root["args"]["tenant"] in ("u_a", "u_b")
        # phases nest inside the root window
        t0, t1 = root["ts_us"], root["ts_us"] + root["dur_us"]
        for s in chain:
            assert s["ts_us"] >= t0 - 1
            assert s["ts_us"] + s["dur_us"] <= t1 + 1
        # at least one prefix admission in this warm-prefix traffic
    kinds = {(s["args"]["rid"], s["args"]["kind"])
             for s in spans if s["name"] == "req.admit"}
    assert any(k == "prefix-hit" for _, k in kinds)

    # TTFT agreement: histogram delta == the 4 span-carried values
    ft = [s for s in spans if s["name"] == "req.first_token"]
    assert len(ft) == 4
    h = monitor.get_histogram("serve_ttft_ms")
    assert h.count - hc0 == 4
    assert h.sum - hs0 == pytest.approx(
        sum(s["args"]["ttft_ms"] for s in ft), rel=1e-9)
    # and the span-derived TTFT (timestamps) agrees with the carried
    # value to clock-mapping precision
    subs = {s["args"]["rid"]: s for s in spans
            if s["name"] == "req.submit"}
    for s in ft:
        d_ms = (s["ts_us"] - subs[s["args"]["rid"]]["ts_us"]) / 1e3
        assert abs(d_ms - s["args"]["ttft_ms"]) < 5.0

    # tenant sums == untagged totals (all requests tagged)
    din = monitor.stat_get("serve_tokens_in") - d0["serve_tokens_in"]
    dout = monitor.stat_get("serve_tokens_out") \
        - d0["serve_tokens_out"]
    da_in = monitor.stat_get("serve_tenant_tokens_in",
                             labels={"tenant": "u_a"}) \
        - ta0["tokens_in"]
    db_in = monitor.stat_get("serve_tenant_tokens_in",
                             labels={"tenant": "u_b"}) \
        - tb0["tokens_in"]
    da_out = monitor.stat_get("serve_tenant_tokens_out",
                              labels={"tenant": "u_a"}) \
        - ta0["tokens_out"]
    db_out = monitor.stat_get("serve_tenant_tokens_out",
                              labels={"tenant": "u_b"}) \
        - tb0["tokens_out"]
    assert din == sum(lens) == da_in + db_in
    assert dout == 20 == da_out + db_out


# ---------------------------------------------------------------------------
# acceptance e2e: gateway + PS fleet under one aggregator
# ---------------------------------------------------------------------------

_PS_SRC = r"""
import json, os, sys
sys.path.insert(0, sys.argv[1])
cfg = json.loads(sys.argv[2])
from paddle_tpu.framework import monitor
from paddle_tpu.distributed.fleet.ps import SparseTable
from paddle_tpu.distributed.fleet.ps_service import PSServer
from paddle_tpu.observability.metrics import MetricsServer
tables = {n: SparseTable(**kw) for n, kw in cfg["tables"].items()}
srv = PSServer(tables, host="127.0.0.1",
               replica_of=cfg.get("replica_of"),
               replica_mode=cfg.get("replica_mode", "standby"),
               read_coalesce_ms=cfg.get("coalesce_ms", 0.0),
               read_coalesce_batch=cfg.get("coalesce_batch", 64))
srv.start()
# deterministic shared-histogram samples for the exact-merge check
for v in cfg.get("demo_samples", []):
    monitor.hist_observe("fleet_demo_ms", float(v),
                         buckets=[float(b) for b in range(1, 101)])
msrv = MetricsServer(port=0, host="127.0.0.1").start()
print(json.dumps({"port": srv.port, "mport": msrv.port,
                  "pid": os.getpid()}), flush=True)
srv._stop.wait()
"""

_SPEC = {"emb": dict(dim=4, optimizer="sgd", lr=0.1, seed=5)}


def _spawn_ps(role, repo, replica_of=None, mode="standby",
              coalesce_ms=0.0, demo=()):
    env = dict(os.environ)
    env.pop("PADDLE_CHAOS", None)
    env.update(PADDLE_METRICS="1", PADDLE_TRACE_ROLE=role)
    env.pop("PADDLE_TRACE", None)       # fleet procs: metrics only
    cfg = {"tables": _SPEC, "replica_of": replica_of,
           "replica_mode": mode, "coalesce_ms": coalesce_ms,
           "coalesce_batch": 100000, "demo_samples": list(demo)}
    proc = subprocess.Popen(
        [sys.executable, "-c", _PS_SRC, repo, json.dumps(cfg)],
        stdout=subprocess.PIPE, text=True, env=env)
    info = json.loads(proc.stdout.readline())
    return proc, info


def test_fleet_observatory_end_to_end(tmp_path, monkeypatch):
    """The ISSUE 12 acceptance run (docstring at the top of this
    file): (a) exact rollup + pooled-percentile merge, (b) span TTFT
    vs serve_ttft_ms, (c) tenant sums, (d) straggler flag + TTFT SLO
    breach -> flight bundle -> postmortem renders lane + marker."""
    from paddle_tpu.distributed.fleet.ps_service import PSClient
    from paddle_tpu.inference import GenerationServer

    monkeypatch.setenv("PADDLE_TRACE_DIR", str(tmp_path))
    monkeypatch.setenv("PADDLE_TRACE_ROLE", "gateway")
    monkeypatch.setattr(flight_recorder, "_dumps_on", True)
    monitor.enable_metrics(True)
    trace.enable(dir=str(tmp_path), role="gateway")

    rng = np.random.RandomState(42)
    demo = {r: rng.uniform(0.0, 100.0, 400).round(3)
            for r in ("ps0", "repA", "repB", "repSlow")}

    prim, pinfo = _spawn_ps("ps0", _REPO, demo=demo["ps0"])
    pep = f"127.0.0.1:{pinfo['port']}"
    reps = {}
    try:
        for role, cms in (("repA", 0.0), ("repB", 0.0),
                          ("repSlow", 60.0)):
            # repSlow is ARTIFICIALLY DELAYED: a 60 ms read-coalesce
            # window with an unreachable early-flush ceiling makes
            # every sustained pull pay the window — an honest in-repo
            # way to slow one replica's serve rate
            reps[role] = _spawn_ps(role, _REPO, replica_of=pep,
                                   mode="read", coalesce_ms=cms,
                                   demo=demo[role])

        # seed rows + wait for replicas to catch up
        w = PSClient([pep], mode="sync", worker_id="w0",
                     connect_timeout=5.0, rpc_timeout=5.0,
                     max_retries=4, backoff_base=0.02,
                     rpc_deadline=30.0)
        ids = np.arange(32, dtype=np.int64)
        w.pull("emb", ids)
        w.push("emb", ids, np.ones((32, 4), np.float32))

        # ---- serving traffic: 8 streams x 2 tenants, shared prefix
        from paddle_tpu.text.models import LlamaForCausalLM, llama_tiny
        paddle.seed(0)
        cfg = llama_tiny(vocab_size=64, hidden_size=32,
                         intermediate_size=64, num_hidden_layers=2,
                         num_attention_heads=4, num_key_value_heads=2,
                         max_position_embeddings=64)
        lm = LlamaForCausalLM(cfg)
        lm.eval()
        h0 = monitor.get_histogram("serve_ttft_ms")
        hc0, hs0 = (h0.count, h0.sum) if h0 is not None else (0, 0.0)
        tin0 = monitor.stat_get("serve_tokens_in")
        tout0 = monitor.stat_get("serve_tokens_out")
        ten0 = {t: {k: monitor.stat_get(f"serve_tenant_{k}",
                                        labels={"tenant": t})
                    for k in ("tokens_in", "tokens_out")}
                for t in ("acme", "zeta")}
        gsrv = GenerationServer(lm, num_slots=8, block_size=4,
                                max_model_len=32, prefix_cache=True,
                                max_prefill_batch=2)
        gsrv.start()
        prng = np.random.RandomState(1)
        pre = prng.randint(1, 64, (8,)).astype("int32")
        streams, lens = [], []
        for i in range(8):
            p = np.concatenate(
                [pre,
                 prng.randint(1, 64, (2 + i % 4,)).astype("int32")])
            lens.append(p.size)
            streams.append(gsrv.submit(
                p, max_new_tokens=4,
                tenant="acme" if i % 2 == 0 else "zeta"))
        outs = [s.result(timeout=180) for s in streams]
        assert all(len(o) == 4 for o in outs)

        # gateway's own demo samples + metrics endpoint
        gw_demo = rng.uniform(0.0, 100.0, 400).round(3)
        for v in gw_demo:
            monitor.hist_observe(
                "fleet_demo_ms", float(v),
                buckets=[float(b) for b in range(1, 101)])
        gw_msrv = obs_metrics.MetricsServer(port=0,
                                            host="127.0.0.1").start()

        # ---- read traffic: one hammering reader per replica AND one
        # on the primary, so every rate-bearing process sees the same
        # symmetric load (GIL-shared reader threads) and the delayed
        # replica is the only outlier
        stop = threading.Event()
        read_errs = []

        def reader(ep):
            try:
                if ep == pep:
                    cli = PSClient([pep], mode="sync", worker_id="wr",
                                   connect_timeout=5.0,
                                   rpc_timeout=5.0, max_retries=4,
                                   backoff_base=0.02,
                                   rpc_deadline=30.0)
                else:
                    cli = PSClient([pep], mode="read", max_lag=1000,
                                   read_replicas=[ep],
                                   connect_timeout=5.0,
                                   rpc_timeout=5.0, max_retries=4,
                                   backoff_base=0.02,
                                   rpc_deadline=30.0)
                sub = np.arange(16, dtype=np.int64)
                while not stop.is_set():
                    cli.pull("emb", sub)
                cli.close()
            except Exception as e:      # noqa: BLE001
                read_errs.append(e)

        threads = [threading.Thread(target=reader, args=(pep,),
                                    daemon=True)]
        threads[0].start()
        for role, (proc, info) in reps.items():
            # wait until the replica serves bounded reads
            rep_ep = f"127.0.0.1:{info['port']}"
            deadline = time.monotonic() + 20.0
            while True:
                try:
                    cli = PSClient([pep], mode="read", max_lag=1000,
                                   read_replicas=[rep_ep],
                                   connect_timeout=5.0,
                                   rpc_timeout=5.0, max_retries=6,
                                   backoff_base=0.05,
                                   rpc_deadline=20.0)
                    cli.pull("emb", ids[:4])
                    cli.close()
                    break
                except Exception:
                    assert time.monotonic() < deadline, \
                        f"{role} never served reads"
                    time.sleep(0.2)
            t = threading.Thread(target=reader, args=(rep_ep,),
                                 daemon=True)
            t.start()
            threads.append(t)

        # ---- ONE aggregator scrapes the whole fleet
        targets = [f"127.0.0.1:{gw_msrv.port}",
                   f"127.0.0.1:{pinfo['mport']}"] + \
                  [f"127.0.0.1:{info['mport']}"
                   for _, info in reps.values()]
        agg = FleetAggregator(targets, interval_s=1.0,
                              stale_after_s=3600.0,
                              straggler_key="ps_server_pulls",
                              straggler_k=2.0)
        agg.scrape_once()              # opens the rate window
        time.sleep(1.2)                # readers hammer meanwhile
        fleet = agg.scrape_once()
        stop.set()
        for t in threads:
            t.join(10.0)
        assert not read_errs, read_errs

        # (a) rollup counters == exact per-process sums
        per_snaps = [t.last_snap for t in agg._targets]
        for key in ("ps_server_pulls", "serve_tokens_out",
                    "serve_gen_finished"):
            total = sum(int(s.get("counters", {}).get(key, 0))
                        for s in per_snaps)
            assert fleet["rollup"]["counters"].get(key, 0) == total, key
        # merged histogram percentiles == numpy on the pooled samples
        pooled = np.concatenate(list(demo.values()) + [gw_demo])
        mh = monitor.Histogram.from_snapshot(
            fleet["rollup"]["histograms"]["fleet_demo_ms"])
        assert mh.count == pooled.size
        for q in (50, 90, 99):
            assert abs(mh.percentile(q)
                       - float(np.percentile(pooled, q))) < 1.5
        # per-tenant labeled counters survived the merge
        lab = fleet["rollup"]["labeled"]["counters"]
        assert "serve_tenant_tokens_out" in lab

        # (d-1) the delayed replica is the straggler
        rates = {t: v["rates"].get("ps_server_pulls")
                 for t, v in fleet["targets"].items()
                 if "ps_server_pulls" in v["rates"]}
        slow_tid = f"repSlow-{reps['repSlow'][1]['pid']}"
        assert slow_tid in fleet["stragglers"], (rates,
                                                 fleet["stragglers"])

        # (b) span TTFT == serve_ttft_ms observations
        trace.flush()
        sink = tmp_path / f"trace-gateway-{os.getpid()}.jsonl"
        spans = [r for r in _read_sink(sink) if r.get("t") == "span"]
        ft = [s for s in spans if s["name"] == "req.first_token"]
        assert len(ft) == 8
        h = monitor.get_histogram("serve_ttft_ms")
        assert h.count - hc0 == 8
        assert h.sum - hs0 == pytest.approx(
            sum(s["args"]["ttft_ms"] for s in ft), rel=1e-9)

        # (c) tenant sums == untagged totals
        din = monitor.stat_get("serve_tokens_in") - tin0
        dout = monitor.stat_get("serve_tokens_out") - tout0
        tin = sum(monitor.stat_get("serve_tenant_tokens_in",
                                   labels={"tenant": t})
                  - ten0[t]["tokens_in"] for t in ("acme", "zeta"))
        tout = sum(monitor.stat_get("serve_tenant_tokens_out",
                                    labels={"tenant": t})
                   - ten0[t]["tokens_out"] for t in ("acme", "zeta"))
        assert din == sum(lens) == tin
        assert dout == 32 == tout

        # (d-2) inject a TTFT SLO breach -> flight bundle
        n_bundles0 = len(flight_recorder.bundle_paths())
        eng = SloEngine([SLO("ttft_e2e", "latency", "serve_ttft_ms",
                             bound=1e-4, budget=0.01,
                             windows=((60.0, 1.0),), min_events=4)])
        t0 = time.time()
        eng.evaluate(_empty_hist_baseline("serve_ttft_ms"), now=t0)
        st = eng.evaluate(now=t0 + 10)[0]    # live local registry
        assert not st["ok"], st
        bundles = flight_recorder.bundle_paths()
        assert len(bundles) > n_bundles0, "breach produced no bundle"

        gsrv.stop()
        gw_msrv.stop()
        agg.stop()
        w.stop_server()
        w.close()
        trace.disable()

        # (d-3) postmortem renders the request lane + breach marker
        out = tmp_path / "postmortem.json"
        rep_txt = tmp_path / "postmortem.txt"
        r = subprocess.run(
            [sys.executable, _POSTMORTEM, "--dir", str(tmp_path),
             "-o", str(out), "--report", str(rep_txt)],
            capture_output=True, text=True, cwd=_REPO)
        assert r.returncode == 0, r.stderr
        txt = rep_txt.read_text()
        assert "slo.breach" in txt
        bad_lines = [ln for ln in txt.splitlines()
                     if "slo.breach" in ln and "<-- BAD" in ln]
        assert bad_lines, "breach not marked BAD in the report"
        merged = json.load(open(out))
        evs = merged["traceEvents"]
        lanes = [e for e in evs if e.get("ph") == "M"
                 and e.get("name") == "thread_name"
                 and str(e["args"].get("name", "")
                         ).startswith("gen-req-")]
        assert lanes, "no request lanes in the postmortem timeline"
        lane_tids = {(e["pid"], e["tid"]) for e in lanes}
        req_spans = [e for e in evs if e.get("ph") == "X"
                     and e.get("name", "").startswith("req")
                     and (e["pid"], e["tid"]) in lane_tids]
        assert req_spans, "request lane holds no spans"
        breach_marks = [e for e in evs if e.get("ph") == "i"
                        and e.get("name") == "slo.breach"]
        assert breach_marks, "no slo.breach instant on the timeline"
    finally:
        prim.kill()
        prim.wait(timeout=10)
        for proc, _ in reps.values():
            proc.kill()
            proc.wait(timeout=10)
