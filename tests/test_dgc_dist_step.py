"""strategy.dgc wired into DistributedTrainStep (no silent toggles).

Parity: reference fleet/meta_optimizers/dgc_optimizer.py +
details/sparse_all_reduce_op_handle.cc — here the compression (momentum
correction, top-k, error feedback, warmup) runs inside the compiled step
on the XLA-summed global gradient.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed.fleet.dist_step import DistributedTrainStep


def _run(strategy, steps=25, lr=0.2, seed=0):
    mesh_mod.set_mesh(None)
    mesh = mesh_mod.init_mesh({"dp": -1})
    paddle.seed(seed)
    model = nn.Sequential(nn.Linear(6, 32), nn.Tanh(), nn.Linear(32, 2))
    opt = paddle.optimizer.Momentum(learning_rate=lr, momentum=0.9,
                                    parameters=model.parameters())

    def loss_fn(x, y):
        return F.cross_entropy(model(x), y).mean()

    step = DistributedTrainStep(model, loss_fn, opt, strategy, mesh=mesh)
    rng = np.random.RandomState(1)
    x_np = rng.randn(16, 6).astype(np.float32)
    y_np = (x_np.sum(axis=1) > 0).astype(np.int64)
    x, y = paddle.to_tensor(x_np), paddle.to_tensor(y_np)
    losses = [float(step(x, y)) for _ in range(steps)]
    mesh_mod.set_mesh(None)
    return losses


def test_dgc_trains_close_to_dense():
    dense = _run(fleet.DistributedStrategy())
    s = fleet.DistributedStrategy()
    s.dgc = True
    s.dgc_configs = {"rampup_begin_step": 0, "sparsity": [0.75],
                     "momentum": 0.9}
    dgc = _run(s)
    assert dgc[-1] < 0.5 * dgc[0]          # converges
    assert dgc[-1] < dense[0]              # and beats the dense start
    # error feedback keeps compressed training near the dense trajectory
    assert abs(dgc[-1] - dense[-1]) < 0.25


def test_dgc_warmup_matches_dense_exactly():
    """Before rampup_begin_step no compression: identical losses."""
    dense = _run(fleet.DistributedStrategy(), steps=5)
    s = fleet.DistributedStrategy()
    s.dgc = True
    s.dgc_configs = {"rampup_begin_step": 100, "sparsity": [0.999]}
    dgc = _run(s, steps=5)
    np.testing.assert_allclose(dense, dgc, rtol=1e-6)


def test_dgc_post_warmup_uses_sgd_apply():
    """Once compressing, momentum lives in DGC's u accumulator and the
    optimizer's own velocity must stay zero (reference dgc_momentum_op.h
    switches momentum→sgd at rampup_begin_step) — no double momentum."""
    mesh_mod.set_mesh(None)
    mesh = mesh_mod.init_mesh({"dp": -1})
    paddle.seed(0)
    model = nn.Linear(4, 2)
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                    parameters=model.parameters())
    s = fleet.DistributedStrategy()
    s.dgc = True
    s.dgc_configs = {"rampup_begin_step": 0, "sparsity": [0.5]}

    def loss_fn(x, y):
        return F.cross_entropy(model(x), y).mean()

    step = DistributedTrainStep(model, loss_fn, opt, s, mesh=mesh)
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(8, 4).astype(np.float32))
    y = paddle.to_tensor(rng.randint(0, 2, (8,)).astype(np.int64))
    for _ in range(3):
        step(x, y)
    for st in opt.opt_state():
        for k, v in st.items():
            if k == "velocity":
                assert float(np.abs(np.asarray(v)).sum()) == 0.0
    mesh_mod.set_mesh(None)


def test_dgc_multi_stage_ramp_trains():
    s = fleet.DistributedStrategy()
    s.dgc = True
    s.dgc_configs = {"rampup_begin_step": 2, "rampup_step": 10,
                     "sparsity": [0.5, 0.75, 0.9], "momentum": 0.9}
    losses = _run(s, steps=30)
    assert losses[-1] < 0.5 * losses[0]


def test_dgc_requires_momentum_or_sgd():
    mesh_mod.set_mesh(None)
    mesh = mesh_mod.init_mesh({"dp": -1})
    paddle.seed(0)
    model = nn.Linear(4, 2)
    opt = paddle.optimizer.Adam(learning_rate=0.1,
                                parameters=model.parameters())
    s = fleet.DistributedStrategy()
    s.dgc = True
    step = DistributedTrainStep(
        model, lambda x, y: F.cross_entropy(model(x), y).mean(),
        opt, s, mesh=mesh)
    x = paddle.to_tensor(np.zeros((4, 4), np.float32))
    y = paddle.to_tensor(np.zeros((4,), np.int64))
    with pytest.raises(ValueError, match="Momentum or SGD"):
        step(x, y)
    mesh_mod.set_mesh(None)


def test_distributed_optimizer_warns_dgc_and_fp16():
    s = fleet.DistributedStrategy()
    s.dgc = True
    s.fp16_allreduce = True
    paddle.seed(0)
    model = nn.Linear(2, 2)
    opt = paddle.optimizer.Momentum(learning_rate=0.1,
                                    parameters=model.parameters())
    fleet.init(is_collective=True, strategy=s)
    with pytest.warns(UserWarning):
        fleet.distributed_optimizer(opt, s)


def test_dgc_keeps_clip_and_decay_when_compressing():
    """The compressed (SGD-apply) branch must still run the optimizer's
    grad_clip + weight_decay like the warmup branch does."""
    from paddle_tpu.nn.clip import ClipGradByGlobalNorm
    mesh_mod.set_mesh(None)
    mesh = mesh_mod.init_mesh({"dp": -1})
    paddle.seed(0)
    model = nn.Linear(4, 2)
    w0 = np.asarray(model.weight._value).copy()
    opt = paddle.optimizer.Momentum(
        learning_rate=1.0, momentum=0.0, weight_decay=0.5,
        grad_clip=ClipGradByGlobalNorm(1e-12),
        parameters=model.parameters())
    s = fleet.DistributedStrategy()
    s.dgc = True
    s.dgc_configs = {"rampup_begin_step": 0, "sparsity": [0.0]}

    def loss_fn(x, y):
        return F.cross_entropy(model(x), y).mean()

    step = DistributedTrainStep(model, loss_fn, opt, s, mesh=mesh)
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(8, 4).astype(np.float32))
    y = paddle.to_tensor(rng.randint(0, 2, (8,)).astype(np.int64))
    step(x, y)
    # clip crushes the data gradient to ~0; the visible update is pure
    # weight decay: w1 ≈ w0 - lr * 0.5 * w0 = 0.5 * w0
    w1 = np.asarray(model.weight._value)
    np.testing.assert_allclose(w1, 0.5 * w0, rtol=1e-4, atol=1e-6)
    mesh_mod.set_mesh(None)


def test_dgc_nesterov_rejected():
    mesh_mod.set_mesh(None)
    mesh = mesh_mod.init_mesh({"dp": -1})
    paddle.seed(0)
    model = nn.Linear(4, 2)
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                    use_nesterov=True,
                                    parameters=model.parameters())
    s = fleet.DistributedStrategy()
    s.dgc = True
    step = DistributedTrainStep(
        model, lambda x, y: F.cross_entropy(model(x), y).mean(),
        opt, s, mesh=mesh)
    x = paddle.to_tensor(np.zeros((4, 4), np.float32))
    y = paddle.to_tensor(np.zeros((4,), np.int64))
    with pytest.raises(NotImplementedError, match="nesterov"):
        step(x, y)
    mesh_mod.set_mesh(None)


def test_dgc_incompatible_combos_raise():
    s = fleet.DistributedStrategy()
    s.dgc = True
    s.gradient_merge = True
    s.gradient_merge_configs = {"k_steps": 2, "avg": True}
    with pytest.raises(NotImplementedError):
        _run(s, steps=1)


def test_fp16_allreduce_warns_loudly():
    s = fleet.DistributedStrategy()
    s.fp16_allreduce = True
    with pytest.warns(UserWarning, match="no-op"):
        _run(s, steps=1)
