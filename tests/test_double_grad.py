"""Higher-order autograd (double grad) tests.

Parity target: the reference's PartialGradEngine + per-op double-grad
registrations (reference: paddle/fluid/imperative/partial_grad_engine.cc,
python/paddle/fluid/backward.py:1795 calc_gradient; double-grad ops e.g.
operators/activation_op.cc TanhDoubleGrad).  Here the backward sweep with
``create_graph=True`` re-linearizes every recorded op through ``_apply``,
so grads carry their own tape and can be differentiated again — to any
order (the reference needs hand-written NthGrad kernels per op; jax.vjp
composition gives it for every op at once).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def test_second_order_polynomial():
    x = paddle.to_tensor(np.array([1.5, -2.0, 0.5], np.float32),
                         stop_gradient=False)
    y = (x * x * x).sum()
    (g1,) = paddle.grad(y, [x], create_graph=True)
    np.testing.assert_allclose(np.asarray(g1._value),
                               3 * np.array([1.5, -2.0, 0.5]) ** 2, rtol=1e-6)
    assert not g1.stop_gradient  # differentiable result
    (g2,) = paddle.grad(g1.sum(), [x])
    np.testing.assert_allclose(np.asarray(g2._value),
                               6 * np.array([1.5, -2.0, 0.5]), rtol=1e-6)


def test_third_order():
    x = paddle.to_tensor(np.array([1.2], np.float32), stop_gradient=False)
    (g1,) = paddle.grad((x ** 4).sum(), [x], create_graph=True)
    (g2,) = paddle.grad(g1.sum(), [x], create_graph=True)
    (g3,) = paddle.grad(g2.sum(), [x])
    np.testing.assert_allclose(np.asarray(g3._value), [24 * 1.2], rtol=1e-5)


def test_tanh_double_grad_vs_finite_difference():
    pts = np.array([0.3, -0.7, 1.1], np.float32)
    x = paddle.to_tensor(pts, stop_gradient=False)
    (g1,) = paddle.grad(paddle.tanh(x).sum(), [x], create_graph=True)
    (g2,) = paddle.grad(g1.sum(), [x])
    # finite difference of the analytic first derivative 1 - tanh^2
    eps = 1e-3
    fd = ((1 - np.tanh(pts + eps) ** 2) - (1 - np.tanh(pts - eps) ** 2)) \
        / (2 * eps)
    np.testing.assert_allclose(np.asarray(g2._value), fd, atol=1e-3)


def test_first_order_result_is_detached_without_create_graph():
    x = paddle.to_tensor(np.array([2.0], np.float32), stop_gradient=False)
    (g,) = paddle.grad((x * x).sum(), [x])
    assert g.stop_gradient
    assert g._node is None


def test_gradient_penalty_reaches_params():
    """WGAN-GP pattern: penalty on d(out)/d(input), backward into weights."""
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 1))
    xi = paddle.to_tensor(
        np.random.RandomState(0).rand(3, 4).astype(np.float32),
        stop_gradient=False)
    out = net(xi).sum()
    (gx,) = paddle.grad(out, [xi], create_graph=True)
    gp = ((gx * gx).sum(axis=1).sqrt() - 1.0)
    loss = (gp * gp).mean()
    loss.backward()
    w = net[0].weight
    assert w.grad is not None
    assert float(np.abs(np.asarray(w.grad._value)).sum()) > 0


def test_gradient_penalty_matches_jax_reference():
    """Second-order param grads equal pure-jax nested AD on the same net."""
    import jax
    import jax.numpy as jnp

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 1))
    w0 = np.asarray(net[0].weight._value)
    b0 = np.asarray(net[0].bias._value)
    w1 = np.asarray(net[2].weight._value)
    b1 = np.asarray(net[2].bias._value)
    xin = np.random.RandomState(1).rand(3, 4).astype(np.float32)

    def jref(params, x):
        W0, B0, W1, B1 = params

        def f(xv):
            return (jnp.tanh(xv @ W0 + B0) @ W1 + B1).sum()

        gx = jax.grad(f)(x)
        gp = jnp.sqrt((gx * gx).sum(1)) - 1.0
        return (gp * gp).mean()

    ref_grads = jax.grad(jref)((w0, b0, w1, b1), jnp.asarray(xin))

    xi = paddle.to_tensor(xin, stop_gradient=False)
    out = net(xi).sum()
    (gx,) = paddle.grad(out, [xi], create_graph=True)
    gp = ((gx * gx).sum(axis=1).sqrt() - 1.0)
    ((gp * gp).mean()).backward()
    got = [net[0].weight.grad, net[0].bias.grad,
           net[2].weight.grad, net[2].bias.grad]
    for g, r in zip(got, ref_grads):
        np.testing.assert_allclose(np.asarray(g._value), np.asarray(r),
                                   rtol=2e-4, atol=2e-5)


def test_grad_outputs_and_multi_inputs():
    x = paddle.to_tensor(np.array([1.0, 2.0], np.float32),
                         stop_gradient=False)
    y = paddle.to_tensor(np.array([3.0, 4.0], np.float32),
                         stop_gradient=False)
    z = (x * x * y).sum()
    gx, gy = paddle.grad(z, [x, y], create_graph=True)
    # d2z/dxdy = 2x via differentiating gx w.r.t. y
    (gxy,) = paddle.grad(gx.sum(), [y])
    np.testing.assert_allclose(np.asarray(gxy._value), [2.0, 4.0], rtol=1e-6)
    (gyx,) = paddle.grad(gy.sum(), [x])
    np.testing.assert_allclose(np.asarray(gyx._value), [2.0, 4.0], rtol=1e-6)


def test_create_graph_after_freed_graph_raises():
    x = paddle.to_tensor(np.array([1.0], np.float32), stop_gradient=False)
    y = (x * x).sum()
    y.backward()  # frees vjp closures
    with pytest.raises(RuntimeError):
        paddle.grad(y, [x], create_graph=True)
