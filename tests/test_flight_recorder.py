"""ISSUE 7: the flight recorder — ring-buffer eviction exactness, dump
triggers (SIGUSR2 / stall watchdog / unhandled-exception hooks), the
compile observatory, and the fp16 guard_health gap closure.

Subprocess tests run with ``PADDLE_FLIGHT=1`` (full mode: handlers
installed at package import); in-process tests drive the recorder
singleton directly and point ``PADDLE_TRACE_DIR`` at tmp so no bundle
can leak into the repo.
"""
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from paddle_tpu.observability import flight_recorder as fl
from paddle_tpu.observability.flight_recorder import FlightRecorder

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _flight_clean(monkeypatch, tmp_path):
    """Every test gets an empty ring and a tmp bundle dir; dumps stay
    disabled unless the test enables them.  Teardown restores the
    env-derived state (the run_tier1 --trace pass runs this suite
    with PADDLE_FLIGHT=1 global — later tests must see full mode
    again)."""
    monkeypatch.setenv("PADDLE_TRACE_DIR", str(tmp_path / "flight"))
    fl.disable()      # dumps off even when the pass set PADDLE_FLIGHT=1
    fl.clear()
    yield
    fl.disable()
    fl.clear()
    if os.environ.get("PADDLE_FLIGHT", "") == "1":
        fl.enable()


def _read_bundle(path):
    with open(path) as f:
        return [json.loads(ln) for ln in f if ln.strip()]


# ---------------------------------------------------------------------------
# ring buffer: eviction exactness
# ---------------------------------------------------------------------------

def test_ring_evicts_oldest_first_by_count():
    r = FlightRecorder(capacity=5, max_bytes=10 ** 9)
    for i in range(8):
        r.record("e", i=i)
    evs = r.events()
    assert [e["i"] for e in evs] == [3, 4, 5, 6, 7]
    assert r.dropped == 3


def test_ring_respects_byte_bound_exactly():
    """The byte cost of an event is the length of its JSONL line (what
    a dump would write); the ring evicts oldest-first until under the
    bound — simulate the accounting and demand an exact match."""
    r = FlightRecorder(capacity=10 ** 6, max_bytes=400)
    sizes = []
    for i in range(32):
        rec = r.record("e", i=i, pad="x" * (i % 7))
        sizes.append(len(json.dumps(rec, separators=(",", ":"))) + 1)
    # replay the eviction: append each size, then pop from the front
    # while over budget
    kept: list = []
    total = 0
    for i, n in enumerate(sizes):
        kept.append((i, n))
        total += n
        while total > 400:
            _, m = kept.pop(0)
            total -= m
    assert [e["i"] for e in r.events()] == [i for i, _ in kept]
    assert r.nbytes() == total
    assert r.nbytes() <= 400
    assert r.dropped == 32 - len(kept)


def test_ring_stringifies_unserializable_fields():
    r = FlightRecorder()
    r.record("e", obj=object(), arr=np.arange(3))
    (ev,) = r.events()
    assert isinstance(ev["obj"], str) and isinstance(ev["arr"], str)


def test_begin_end_pairs_and_inflight_table():
    fl.record("step", i=0)
    tok = fl.begin("rpc", op="pull", shard=0)
    # an open op sits in the in-flight table, NOT the ring (the
    # completed-op hot path pays exactly one ring event)
    assert [o["op"] for o in fl.in_flight()] == ["pull"]
    assert [e["kind"] for e in fl.events()] == ["step"]
    fl.end(tok, ok=True)
    assert fl.in_flight() == []
    evs = fl.events()
    assert [e["kind"] for e in evs] == ["step", "rpc"]
    rpc = evs[-1]
    # one combined record: begin ts + duration + merged fields
    assert rpc["dur_us"] >= 0 and rpc["op"] == "pull"
    assert rpc["ok"] is True and rpc["shard"] == 0


# ---------------------------------------------------------------------------
# dumps: content + stall watchdog (in-process)
# ---------------------------------------------------------------------------

def test_dump_contains_ring_inflight_stacks_metrics(tmp_path):
    fl.record("health", step=3, norm=1.5, nonfinite=0.0, loss=0.7,
              verdict="ok")
    fl.begin("rpc", op="push", shard=1)
    path = fl.dump("test_reason", path=str(tmp_path / "b.jsonl"))
    recs = _read_bundle(path)
    by_t = {}
    for r in recs:
        by_t.setdefault(r["t"], []).append(r)
    meta = by_t["meta"][0]
    assert meta["reason"] == "test_reason" and meta["pid"] == os.getpid()
    evs = by_t["event"]
    assert any(e["kind"] == "health" and e["verdict"] == "ok"
               for e in evs)
    (infl,) = by_t["inflight"]
    assert infl["ops"][0]["op"] == "push"
    assert infl["ops"][0]["open_us"] >= 0
    stacks = by_t["stacks"][0]["threads"]
    assert any("MainThread" == v["name"] for v in stacks.values())
    assert "counters" in by_t["metrics"][0]


def test_watchdog_fires_on_wedged_loop_and_rearms(tmp_path):
    """No progress for > deadline => exactly one stall dump; progress
    resuming re-arms it."""
    fl.record("step", i=0)                      # progress now
    wd = fl.Watchdog(0.3, poll_s=0.05)
    wd.start()
    try:
        deadline = time.monotonic() + 5.0
        while wd.stalls == 0 and time.monotonic() < deadline:
            time.sleep(0.05)                    # the "wedged" loop
        assert wd.stalls == 1
        bundles = [p for p in fl.bundle_paths()
                   if "flight-" in os.path.basename(p)]
        assert bundles, "watchdog wrote no bundle"
        recs = _read_bundle(bundles[-1])
        assert recs[0]["reason"] == "stall"
        assert any(r.get("kind") == "stall" for r in recs)
        # one stall = one dump, even though the poll kept running
        time.sleep(0.3)
        assert wd.stalls == 1
        # progress re-arms; a second wedge fires again
        fl.record("step", i=1)
        time.sleep(0.1)
        deadline = time.monotonic() + 5.0
        while wd.stalls < 2 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert wd.stalls == 2
    finally:
        wd.stop()


def test_maybe_dump_requires_full_mode(tmp_path):
    fl.record("e", i=1)
    assert not fl.dumps_enabled()
    assert fl.maybe_dump("PSUnavailable") is None
    d = str(tmp_path / "flight")
    assert not os.path.exists(d) or not os.listdir(d)


# ---------------------------------------------------------------------------
# dump triggers in subprocesses (PADDLE_FLIGHT=1 full mode)
# ---------------------------------------------------------------------------

def _flight_env(tmp_path, role):
    env = dict(os.environ)
    env.pop("PADDLE_CHAOS", None)
    env.update(JAX_PLATFORMS="cpu", PADDLE_FLIGHT="1",
               PADDLE_TRACE_DIR=str(tmp_path),
               PADDLE_TRACE_ROLE=role)
    env.pop("PADDLE_FLIGHT_STALL_S", None)
    return env


def _wait_for_bundle(tmp_path, role, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        found = sorted(tmp_path.glob(f"flight-{role}-*.jsonl"))
        if found:
            return found
        time.sleep(0.05)
    raise AssertionError(f"no flight-{role}-* bundle appeared under "
                         f"{tmp_path}: {sorted(tmp_path.glob('*'))}")


_SIGUSR2_SRC = r"""
import os, sys, time
sys.path.insert(0, sys.argv[1])
from paddle_tpu.observability import flight_recorder as fl
for i in range(7):
    fl.record("step", i=i)
print("READY", flush=True)
time.sleep(60)
"""


def test_sigusr2_dumps_on_demand_in_subprocess(tmp_path):
    proc = subprocess.Popen(
        [sys.executable, "-c", _SIGUSR2_SRC, _REPO],
        stdout=subprocess.PIPE, text=True,
        env=_flight_env(tmp_path, "usr2"))
    try:
        assert proc.stdout.readline().strip() == "READY"
        proc.send_signal(signal.SIGUSR2)
        bundles = _wait_for_bundle(tmp_path, "usr2")
        recs = _read_bundle(bundles[0])
        assert recs[0]["t"] == "meta"
        assert recs[0]["reason"] == "SIGUSR2"
        steps = [r for r in recs if r.get("kind") == "step"]
        assert [s["i"] for s in steps] == list(range(7))
        # the process SURVIVES an on-demand dump
        assert proc.poll() is None
    finally:
        proc.kill()
        proc.wait(timeout=10)


_RAISE_SRC = r"""
import sys
sys.path.insert(0, sys.argv[1])
import paddle_tpu  # installs the excepthooks (PADDLE_FLIGHT=1)
from paddle_tpu.observability import flight_recorder as fl
fl.record("step", i=41)
raise ValueError("boom at step 41")
"""


def test_unhandled_exception_writes_excepthook_bundle(tmp_path):
    proc = subprocess.run(
        [sys.executable, "-c", _RAISE_SRC, _REPO],
        capture_output=True, text=True,
        env=_flight_env(tmp_path, "crash"))
    assert proc.returncode != 0
    assert "boom at step 41" in proc.stderr   # the previous hook ran
    bundles = _wait_for_bundle(tmp_path, "crash", timeout=5.0)
    recs = _read_bundle(bundles[0])
    assert recs[0]["reason"] == "unhandled"
    (exc,) = [r for r in recs if r["t"] == "exc"]
    assert exc["type"] == "ValueError"
    assert "boom at step 41" in exc["value"]
    assert any(r.get("kind") == "step" and r.get("i") == 41
               for r in recs)


_STALL_SRC = r"""
import sys, time
sys.path.insert(0, sys.argv[1])
from paddle_tpu.observability import flight_recorder as fl
fl.record("step", i=0)
print("READY", flush=True)
time.sleep(60)   # wedged: no progress ever again
"""


def test_env_watchdog_fires_in_subprocess(tmp_path):
    env = _flight_env(tmp_path, "stall")
    env["PADDLE_FLIGHT_STALL_S"] = "0.5"
    proc = subprocess.Popen(
        [sys.executable, "-c", _STALL_SRC, _REPO],
        stdout=subprocess.PIPE, text=True, env=env)
    try:
        assert proc.stdout.readline().strip() == "READY"
        bundles = _wait_for_bundle(tmp_path, "stall")
        recs = _read_bundle(bundles[0])
        assert recs[0]["reason"] == "stall"
        assert recs[0]["progress_age_s"] >= 0.5
    finally:
        proc.kill()
        proc.wait(timeout=10)


# ---------------------------------------------------------------------------
# compile observatory (DistributedTrainStep retrace classification)
# ---------------------------------------------------------------------------

def _mk_step(guard_health=False):
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu import nn
    from paddle_tpu.distributed import fleet, mesh as mesh_mod
    from paddle_tpu.distributed.fleet.dist_step import \
        DistributedTrainStep

    paddle.seed(7)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    opt = paddle.optimizer.Adam(parameters=net.parameters())

    def loss_fn(x, y):
        return F.cross_entropy(net(x), y)

    mesh_mod.set_mesh(None)
    mesh = mesh_mod.init_mesh({"dp": -1})
    return DistributedTrainStep(net, loss_fn, opt,
                                fleet.DistributedStrategy(), mesh=mesh,
                                guard_health=guard_health)


def test_compile_observatory_classifies_retraces():
    import paddle_tpu as paddle
    step = _mk_step()
    rng = np.random.default_rng(0)
    x4 = rng.random((4, 8), np.float32)
    y4 = rng.integers(0, 4, 4).astype(np.int64)
    step(paddle.to_tensor(x4), paddle.to_tensor(y4))
    step(paddle.to_tensor(x4), paddle.to_tensor(y4))   # cache hit
    x8 = rng.random((8, 8), np.float32)
    y8 = rng.integers(0, 4, 8).astype(np.int64)
    step(paddle.to_tensor(x8), paddle.to_tensor(y8))   # new bucket
    # same shapes as the 4-row batch, inputs narrowed to f16: an
    # AVOIDABLE retrace (cast at the source instead)
    step(paddle.to_tensor(x4.astype(np.float16)), paddle.to_tensor(y4))
    log = [e for e in fl.compile_log()
           if e["program"] == "DistributedTrainStep"]
    assert [e["cause"] for e in log] == \
        ["first_build", "new_shape_bucket", "avoidable_retrace"]
    assert all(e["wall_ms"] > 0 for e in log)
    # compile events landed in the ring too
    ring = [e for e in fl.events() if e["kind"] == "compile"]
    assert len(ring) == 3
    # lazy memory analysis resolves on demand with the XLA observables
    resolved = [e for e in fl.compile_log(resolve=True)
                if e["program"] == "DistributedTrainStep"]
    assert all("peak_bytes" in e and "argument_bytes" in e
               and "output_bytes" in e for e in resolved)
    assert all(e["peak_bytes"] > 0 for e in resolved)
    # ISSUE 15 satellite: every byte-carrying record speaks the
    # versioned memory schema — the calibration hook's contract
    for e in resolved:
        assert e["mem_schema"] == fl.MEM_SCHEMA_VERSION
        for k in fl.MEM_SCHEMA_KEYS:
            assert k in e and isinstance(e[k], int), (k, e)


def test_compile_log_memory_schema_shape_drift_detected():
    """A future rename of the arg/temp/peak byte keys (or a version
    bump) must make the planner's calibration consumer raise LOUDLY —
    a silently-zeroed calibration is the failure mode the versioned
    schema exists to prevent."""
    import pytest
    from paddle_tpu.distributed.planner.calibrate import (
        Calibration, CalibrationError)
    good = {"program": "DistributedTrainStep", "cause": "abstract",
            "mem_schema": fl.MEM_SCHEMA_VERSION}
    good.update({k: 10 for k in fl.MEM_SCHEMA_KEYS})
    assert Calibration.from_compile_log([good]).observations
    # simulate the recorder renaming a schema field WITHOUT bumping
    # the version: consumer must raise, never read zeros
    renamed = dict(good)
    renamed["args_bytes"] = renamed.pop("argument_bytes")
    with pytest.raises(CalibrationError, match="missing schema keys"):
        Calibration.from_compile_log([renamed])
    # version bump without a consumer update: same contract
    bumped = dict(good)
    bumped["mem_schema"] = fl.MEM_SCHEMA_VERSION + 1
    with pytest.raises(CalibrationError, match="mem_schema"):
        Calibration.from_compile_log([bumped])
    # REAL records from this process's log satisfy the consumer
    cal = Calibration.from_compile_log(fl.compile_log(resolve=True))
    assert all(set(fl.MEM_SCHEMA_KEYS) <= set(o) and
               o["peak_bytes"] >= 0 for o in cal.observations)


def test_dist_step_records_step_events_and_health():
    import paddle_tpu as paddle
    from paddle_tpu.train_guard import TrainGuard
    step = _mk_step(guard_health=True)
    guard = TrainGuard()
    rng = np.random.default_rng(1)
    x = rng.random((4, 8), np.float32)
    y = rng.integers(0, 4, 4).astype(np.int64)
    for i in range(3):
        step(paddle.to_tensor(x), paddle.to_tensor(y))
        assert guard.check(step.last_health, step=i) == "ok"
    evs = fl.events()
    assert [e["i"] for e in evs if e["kind"] == "step"] == [0, 1, 2]
    healths = [e for e in evs if e["kind"] == "health"]
    assert len(healths) == 3
    assert all(e["verdict"] == "ok" and np.isfinite(e["loss"])
               for e in healths)
