"""Optimizer + LR scheduler tests (modelled on the reference's
test_adam_op.py / test_momentum_op.py / test_lr_scheduler.py — here
validated against torch (cpu) as an independent reference)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F

rng = np.random.RandomState(0)


def _toy_problem():
    paddle.seed(5)
    net = nn.Linear(4, 1)
    x = paddle.to_tensor(rng.randn(32, 4).astype(np.float32))
    y = paddle.matmul(x, paddle.ones([4, 1])) * 0.5
    return net, x, y


def _run(opt_factory, steps=40, thresh=0.5):
    net, x, y = _toy_problem()
    opt = opt_factory(net.parameters())
    l0 = None
    for _ in range(steps):
        loss = F.mse_loss(net(x), y)
        if l0 is None:
            l0 = float(loss)
        loss.backward()
        opt.step()
        opt.clear_grad()
    assert float(loss) < l0 * thresh, (l0, float(loss))


class TestOptimizersConverge:
    def test_sgd(self):
        _run(lambda ps: paddle.optimizer.SGD(0.1, parameters=ps))

    def test_momentum(self):
        _run(lambda ps: paddle.optimizer.Momentum(0.02, parameters=ps))

    def test_momentum_nesterov(self):
        _run(lambda ps: paddle.optimizer.Momentum(0.02, parameters=ps,
                                                  use_nesterov=True))

    def test_adam(self):
        _run(lambda ps: paddle.optimizer.Adam(0.05, parameters=ps))

    def test_adamw(self):
        _run(lambda ps: paddle.optimizer.AdamW(0.05, parameters=ps))

    def test_rmsprop(self):
        _run(lambda ps: paddle.optimizer.RMSProp(0.01, parameters=ps))

    def test_adagrad(self):
        _run(lambda ps: paddle.optimizer.Adagrad(0.1, parameters=ps))

    def test_adadelta(self):
        _run(lambda ps: paddle.optimizer.Adadelta(2.0, parameters=ps),
             steps=100, thresh=0.8)

    def test_adamax(self):
        _run(lambda ps: paddle.optimizer.Adamax(0.05, parameters=ps))

    def test_lamb(self):
        _run(lambda ps: paddle.optimizer.Lamb(0.05, parameters=ps))

    def test_lars_update_rule(self):
        # LARS is a large-batch optimizer; on a toy problem we check the
        # update math against a manual NumPy step instead of convergence.
        p0 = np.array([3.0, 4.0], np.float32)  # |w| = 5
        g = np.array([0.6, 0.8], np.float32)   # |g| = 1
        p = nn.Parameter(paddle.to_tensor(p0)._value)
        opt = paddle.optimizer.Lars(0.1, momentum=0.9, lars_coeff=0.001,
                                    lars_weight_decay=0.0005,
                                    parameters=[p])
        p.grad = paddle.to_tensor(g)
        opt.step()
        local_lr = 0.001 * 5.0 / (1.0 + 0.0005 * 5.0)
        v = 0.1 * local_lr * (g + 0.0005 * p0)
        np.testing.assert_allclose(p.numpy(), p0 - v, rtol=1e-5)


class TestAgainstTorch:
    def _compare(self, make_ours, make_torch, steps=5, rtol=1e-4, atol=1e-5):
        import torch
        p0 = rng.randn(6).astype(np.float32)
        gs = [rng.randn(6).astype(np.float32) for _ in range(steps)]
        tp = torch.tensor(p0, requires_grad=True)
        topt = make_torch([tp])
        our_p = nn.Parameter(paddle.to_tensor(p0)._value)
        oopt = make_ours([our_p])
        for g in gs:
            tp.grad = torch.tensor(g)
            topt.step()
            our_p.grad = paddle.to_tensor(g)
            oopt.step()
        np.testing.assert_allclose(our_p.numpy(), tp.detach().numpy(),
                                   rtol=rtol, atol=atol)

    def test_sgd(self):
        import torch
        self._compare(lambda ps: paddle.optimizer.SGD(0.1, parameters=ps),
                      lambda ps: torch.optim.SGD(ps, lr=0.1))

    def test_adam(self):
        import torch
        self._compare(lambda ps: paddle.optimizer.Adam(0.1, parameters=ps),
                      lambda ps: torch.optim.Adam(ps, lr=0.1))

    def test_adamw(self):
        import torch
        self._compare(
            lambda ps: paddle.optimizer.AdamW(0.1, parameters=ps,
                                              weight_decay=0.05),
            lambda ps: torch.optim.AdamW(ps, lr=0.1, weight_decay=0.05))

    def test_momentum(self):
        import torch
        self._compare(
            lambda ps: paddle.optimizer.Momentum(0.1, 0.9, parameters=ps),
            lambda ps: torch.optim.SGD(ps, lr=0.1, momentum=0.9))


class TestOptimizerMechanics:
    def test_grad_clip_integration(self):
        net, x, y = _toy_problem()
        opt = paddle.optimizer.SGD(
            0.1, parameters=net.parameters(),
            grad_clip=nn.ClipGradByGlobalNorm(0.001))
        before = net.weight.numpy().copy()
        F.mse_loss(net(x), y).backward()
        opt.step()
        delta = np.abs(net.weight.numpy() - before).sum()
        assert delta < 0.001  # tiny because clipped

    def test_weight_decay_regularizer(self):
        p = nn.Parameter(paddle.ones([3])._value)
        opt = paddle.optimizer.SGD(0.1, parameters=[p], weight_decay=0.5)
        p.grad = paddle.zeros([3])
        opt.step()
        # grad 0 + l2 0.5*p -> p = 1 - 0.1*0.5 = 0.95
        np.testing.assert_allclose(p.numpy(), [0.95] * 3, rtol=1e-6)

    def test_state_dict_roundtrip(self):
        net, x, y = _toy_problem()
        opt = paddle.optimizer.Adam(0.05, parameters=net.parameters())
        F.mse_loss(net(x), y).backward()
        opt.step()
        sd = opt.state_dict()
        opt2 = paddle.optimizer.Adam(0.05, parameters=net.parameters())
        opt2.set_state_dict(sd)
        s1 = opt.opt_state()
        s2 = opt2.opt_state()
        np.testing.assert_allclose(np.asarray(s1[0]["m"]),
                                   np.asarray(s2[0]["m"]))

    def test_minimize_api(self):
        net, x, y = _toy_problem()
        opt = paddle.optimizer.SGD(0.1, parameters=net.parameters())
        loss = F.mse_loss(net(x), y)
        before = float(loss)
        opt.minimize(loss)
        opt.clear_grad()
        assert float(F.mse_loss(net(x), y)) < before


class TestLRSchedulers:
    def test_step_decay(self):
        s = paddle.optimizer.lr.StepDecay(0.1, step_size=2, gamma=0.5)
        lrs = []
        for _ in range(5):
            lrs.append(s())
            s.step()
        np.testing.assert_allclose(lrs, [0.1, 0.1, 0.05, 0.05, 0.025])

    def test_multistep(self):
        s = paddle.optimizer.lr.MultiStepDecay(1.0, [2, 4], gamma=0.1)
        lrs = [s() for _ in range(1)]
        for _ in range(4):
            s.step()
            lrs.append(s())
        np.testing.assert_allclose(lrs, [1.0, 1.0, 0.1, 0.1, 0.01])

    def test_cosine(self):
        s = paddle.optimizer.lr.CosineAnnealingDecay(1.0, T_max=10)
        assert abs(s() - 1.0) < 1e-6
        s.step(10)
        assert abs(s() - 0.0) < 1e-6

    def test_linear_warmup(self):
        s = paddle.optimizer.lr.LinearWarmup(0.5, warmup_steps=5,
                                             start_lr=0.0, end_lr=0.5)
        assert s() == 0.0
        for _ in range(5):
            s.step()
        assert abs(s() - 0.5) < 1e-9

    def test_reduce_on_plateau(self):
        s = paddle.optimizer.lr.ReduceOnPlateau(1.0, patience=1, factor=0.1)
        s.step(1.0)
        s.step(1.0)
        s.step(1.0)  # no improvement beyond patience
        assert s() == pytest.approx(0.1)

    def test_scheduler_with_optimizer(self):
        net, x, y = _toy_problem()
        sched = paddle.optimizer.lr.ExponentialDecay(0.1, gamma=0.5)
        opt = paddle.optimizer.SGD(sched, parameters=net.parameters())
        assert opt.get_lr() == pytest.approx(0.1)
        sched.step()
        assert opt.get_lr() == pytest.approx(0.05)

    def test_noam_warmup_shape(self):
        s = paddle.optimizer.lr.NoamDecay(d_model=512, warmup_steps=10)
        lrs = []
        for _ in range(20):
            lrs.append(s())
            s.step()
        peak = int(np.argmax(lrs))
        assert 8 <= peak <= 11  # peaks at warmup boundary
