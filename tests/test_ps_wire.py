"""PS wire paths (ISSUE 16): zero-copy pull2, int8 pull_q8, and the
scatter-gather send plumbing.

Contracts pinned here:

- ``_sendall_vec`` survives partial sends, EINTR, and >IOV_MAX view
  lists with byte-exact output, and its no-``sendmsg`` fallback
  produces the identical byte stream;
- the native ``pts_sendv_addrs`` scatter-gather emits byte-for-byte
  the frame a staged send would (zeros rows, fragmented and contiguous
  runs, partial-send advance across a real socketpair);
- the ``zc`` and ``q8`` wires are semantically invisible: a client on
  any wire sees the same rows (zc bit-exact, q8 == the documented
  quantize/dequant oracle), hot AND cold;
- the q8 wire's measured egress-byte reduction holds (>= 1.8x);
- geo LWW stamps live in the NATIVE stamp directory at vocab scale:
  the server-side ``_geo_stamps`` view materialises from the table,
  and eviction drops stamps with the slot.
"""
import socket
import threading

import numpy as np
import pytest

from paddle_tpu.distributed.fleet.ps import (SparseTable,
                                             dequantize_rows_q8,
                                             quantize_rows_q8,
                                             sendv_addrs)
from paddle_tpu.distributed.fleet.ps_service import (PSClient, PSServer,
                                                     _frame_bytes,
                                                     _sendall_vec)
from paddle_tpu.native import ps_core

requires_native = pytest.mark.skipif(ps_core() is None,
                                     reason="no C++ toolchain")

_CFG = dict(dim=16, optimizer="sgd", lr=0.1, seed=5, init_std=0.05)


# -- _sendall_vec fake-socket plumbing ---------------------------------
class _ChunkSock:
    """sendmsg that accepts at most ``chunk`` bytes per call and raises
    InterruptedError every ``eintr_every``-th call — the worst-case
    kernel behaviour the consume loop must survive."""

    def __init__(self, chunk=7, eintr_every=0):
        self.buf = bytearray()
        self.calls = 0
        self.chunk = chunk
        self.eintr_every = eintr_every

    def sendmsg(self, views):
        self.calls += 1
        if self.eintr_every and self.calls % self.eintr_every == 0:
            raise InterruptedError
        take = self.chunk
        for v in views:
            if take <= 0:
                break
            b = bytes(v)[:take]
            self.buf += b
            take -= len(b)
        return self.chunk - take


class _SendallSock:
    """No ``sendmsg`` attribute at all: the byte-exact fallback."""

    def __init__(self):
        self.buf = bytearray()

    def sendall(self, v):
        self.buf += bytes(v)


def _views(n_views, seed=0):
    r = np.random.RandomState(seed)
    return [r.bytes(int(r.randint(0, 40))) for _ in range(n_views)]


def test_sendall_vec_partial_sends_byte_exact():
    views = _views(50)
    want = b"".join(views)
    s = _ChunkSock(chunk=7)
    _sendall_vec(s, list(views))
    assert bytes(s.buf) == want


def test_sendall_vec_eintr_retries_same_window():
    views = _views(50, seed=1)
    want = b"".join(views)
    s = _ChunkSock(chunk=13, eintr_every=3)
    _sendall_vec(s, list(views))
    assert bytes(s.buf) == want


def test_sendall_vec_beyond_iov_max():
    # >1024 views must split into multiple sendmsg windows, losing
    # nothing at the seams even when sends are partial
    views = [bytes([i % 251]) * (i % 5) for i in range(3000)]
    want = b"".join(views)
    s = _ChunkSock(chunk=997)
    _sendall_vec(s, list(views))
    assert bytes(s.buf) == want
    assert s.calls > 1


def test_sendall_vec_no_sendmsg_fallback_byte_exact():
    views = _views(200, seed=2)
    a = _ChunkSock(chunk=10**9)
    b = _SendallSock()
    _sendall_vec(a, list(views))
    _sendall_vec(b, list(views))
    assert bytes(b.buf) == bytes(a.buf) == b"".join(views)


# -- native scatter-gather send ----------------------------------------
@requires_native
def test_sendv_addrs_byte_exact_over_socketpair():
    """Frame assembled by the native sendmsg loop == the staged
    concatenation: zeros rows (addr 0), fragmented singleton rows, and
    one long contiguous run, with a payload big enough to force
    partial sends through a real socketpair."""
    row_bytes = 256
    rows = np.arange(400 * 64, dtype=np.float32).reshape(400, 64)
    base = rows.ctypes.data
    # sorted plan: 3 zeros rows, every 7th row (fragments), then a
    # 200-row contiguous run
    frag = [base + i * row_bytes for i in range(0, 199, 7)]
    run = [base + i * row_bytes for i in range(200, 400)]
    addrs = np.asarray([0, 0, 0] + frag + run, np.uint64)
    hdr = b"HDR!" * 9
    inv = np.arange(1000, dtype=np.int32)
    want = hdr + inv.tobytes() + bytes(3 * row_bytes) + b"".join(
        rows[i // row_bytes * row_bytes // row_bytes].tobytes()
        for i in [])  # (built below row-wise instead)
    body = bytearray()
    for a in addrs:
        if a == 0:
            body += bytes(row_bytes)
        else:
            off = (int(a) - base) // row_bytes
            body += rows[off].tobytes()
    want = hdr + inv.tobytes() + bytes(body)

    a_sock, b_sock = socket.socketpair()
    got = bytearray()
    def reader():
        while len(got) < len(want):
            chunk = b_sock.recv(65536)
            if not chunk:
                break
            got.extend(chunk)
    th = threading.Thread(target=reader)
    th.start()
    sent = sendv_addrs(a_sock.fileno(), addrs, row_bytes, hdr, inv,
                       timeout_ms=10_000)
    th.join(10)
    a_sock.close()
    b_sock.close()
    assert sent == len(want)
    assert bytes(got) == want


# -- wire-mode parity over a live server -------------------------------
@pytest.fixture()
def served(tmp_path):
    t = SparseTable(**_CFG)
    ids = np.arange(400, dtype=np.int64)
    t.pull(ids)
    g = np.random.RandomState(6).randn(400, 16).astype(np.float32)
    t.push(ids, g)
    srv = PSServer({"emb": t}, host="127.0.0.1")
    srv.start()
    yield t, ids, f"127.0.0.1:{srv.port}", tmp_path
    srv.stop()


def _client_pull(ep, wire, ids):
    c = PSClient([ep], pull_wire=wire)
    try:
        return c.pull("emb", ids)
    finally:
        c.close()


def test_zc_wire_bit_exact_with_duplicates(served):
    t, ids, ep, _ = served
    req = np.asarray([7, 3, 7, 399, 0, 3, 7], np.int64)
    want = t.pull(req)
    np.testing.assert_array_equal(_client_pull(ep, "zc", req), want)
    np.testing.assert_array_equal(_client_pull(ep, "row", req), want)


def test_q8_wire_matches_quantizer_oracle(served):
    t, ids, ep, _ = served
    req = np.asarray([5, 5, 123, 50], np.int64)
    codes, scales = quantize_rows_q8(t.pull(req))
    want = dequantize_rows_q8(codes, scales)
    np.testing.assert_array_equal(_client_pull(ep, "q8", req), want)


@requires_native
def test_wires_bit_exact_on_cold_rows(served):
    t, ids, ep, tmp_path = served
    assert t.enable_spill(str(tmp_path / "spill"))
    import time as _t
    want = t.pull(ids).copy()
    t.spill_sweep(int(_t.time() * 1000) + 60_000)  # demote everything
    np.testing.assert_array_equal(_client_pull(ep, "zc", ids), want)
    t.spill_sweep(int(_t.time() * 1000) + 60_000)
    codes, scales = quantize_rows_q8(want)
    np.testing.assert_array_equal(_client_pull(ep, "q8", ids),
                                  dequantize_rows_q8(codes, scales))


def test_q8_egress_reduction(served):
    t, ids, ep, _ = served
    # a serving-shaped batch: zipf dups over the vocab
    r = np.random.RandomState(8)
    req = ids[np.minimum(r.zipf(1.3, 512) - 1, ids.size - 1)]
    uniq, inv = np.unique(req, return_inverse=True)
    f32 = len(_frame_bytes({"vals": t.pull(req)}))
    codes, scales = quantize_rows_q8(t.pull(uniq))
    q8 = len(_frame_bytes({"inv": np.ascontiguousarray(inv, np.int32),
                           "codes": codes, "scales": scales}))
    assert f32 / q8 >= 1.8


def test_client_pull_q8_returns_raw_codes(served):
    t, ids, ep, _ = served
    req = np.asarray([9, 2, 9, 77], np.int64)
    c = PSClient([ep], pull_wire="q8")
    try:
        codes, scales = c.pull_q8("emb", req)
    finally:
        c.close()
    want_c, want_s = quantize_rows_q8(t.pull(req))
    np.testing.assert_array_equal(codes, want_c)
    np.testing.assert_array_equal(scales, want_s)


# -- native geo stamp directory ----------------------------------------
@requires_native
def test_geo_stamps_live_in_native_table():
    t = SparseTable(dim=4, optimizer="sgd", lr=1.0, seed=0,
                    init_std=0.0, geo_policy="lww")
    srv = PSServer({"emb": t}, host="127.0.0.1", geo_site="siteA")
    srv.start()
    try:
        c = PSClient([f"127.0.0.1:{srv.port}"], mode="sync")
        c.push("emb", np.asarray([11, 22], np.int64),
               -np.ones((2, 4), np.float32))
        c.close()
        # the server-side view materialises from the table's native
        # stamp directory, not a python dict
        sq, si = t.geo_get(np.asarray([11, 22, 33], np.int64))
        assert sq[0] >= 0 and sq[1] >= 0 and sq[2] == -1
        stamps = srv._geo_stamps["emb"]
        assert set(stamps) == {11, 22}
        seq, site = stamps[11]
        assert seq >= 0 and site == "siteA"
        # stamps die with the slot: TTL eviction drops them
        t.ttl_sweep(10**18)
        assert srv._geo_stamps.get("emb", {}) == {}
    finally:
        srv.stop()
