"""Coordinator HA (ISSUE 10): the ElasticCoordinator rendezvous SPOF
closed with the PR 3 hot-standby pattern.

- a standby subscribes to the primary's replicated membership log
  (generation / uid counter / pinned checkpoint step) and promotes on
  EOF with a generation FENCE past everything the dead primary handed
  out;
- an un-promoted standby answers every worker op with a typed
  ``standby`` status, and the worker client rotates past it;
- ``ckpt_dir=`` (satellite): a coordinator (re)started over a
  populated checkpoint directory resumes from the latest pinned step
  with NO manual ``ckpt_step=``;
- THE acceptance: SIGKILL the primary coordinator mid-run under the
  elastic launcher — the standby promotes, workers re-register, and
  the run finishes with weights ``np.array_equal`` to the fault-free
  run.
"""
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from paddle_tpu.distributed.checkpoint import CheckpointManager  # noqa: E402
from paddle_tpu.distributed.fleet.elastic import (  # noqa: E402
    CoordinatorLost, ElasticClient, ElasticCoordinator, _scan_ckpt_dir)

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import elastic_worker  # noqa: E402
import test_elastic as _te  # noqa: E402  (reuse the in-process harness)


# ---------------------------------------------------------------------------
# standby replication + promotion (in-process)
# ---------------------------------------------------------------------------

def test_standby_replicates_state_and_promotes_on_eof():
    prim = ElasticCoordinator(expected_world=1).start()
    stby = ElasticCoordinator(
        standby_of=f"127.0.0.1:{prim.port}").start()
    try:
        cli = ElasticClient(
            f"127.0.0.1:{prim.port}|127.0.0.1:{stby.port}", timeout=20)
        info = cli.register(1)
        assert info["rank"] == 0 and info["world"] == 1
        cli.report_ckpt(4)
        # the replicated log reaches the standby
        deadline = time.monotonic() + 5.0
        while stby.status()["ckpt_step"] != 4 \
                and time.monotonic() < deadline:
            time.sleep(0.02)
        assert stby.status()["ckpt_step"] == 4
        assert stby.status()["role"] == "standby"
        gen_before = prim.status()["gen"]
        prim.stop()                      # EOF -> promote
        deadline = time.monotonic() + 10.0
        while not stby.promoted and time.monotonic() < deadline:
            time.sleep(0.02)
        st = stby.status()
        assert stby.promoted and st["role"] == "primary"
        assert st["ckpt_step"] == 4
        assert st["gen"] > gen_before    # fence: zombie rounds dead
        # the worker's next op fails typed, and rejoin lands on the
        # promoted standby with the replicated pinned step
        with pytest.raises(CoordinatorLost):
            cli.exchange(info["gen"], 0, "x", {})
        info2 = cli.rejoin(1)
        assert info2["ckpt_step"] == 4
        assert info2["gen"] > info["gen"]
        cli.leave()
    finally:
        prim.stop()
        stby.stop()


def test_client_rotates_past_unpromoted_standby():
    """Standby FIRST in the endpoint list: register must transparently
    rotate to the promoted primary."""
    prim = ElasticCoordinator(expected_world=1).start()
    stby = ElasticCoordinator(
        standby_of=f"127.0.0.1:{prim.port}").start()
    try:
        cli = ElasticClient(
            f"127.0.0.1:{stby.port}|127.0.0.1:{prim.port}",
            timeout=20, retry_delay=0.05)
        info = cli.register(1)
        assert info["status"] == "ok" and info["world"] == 1
        cli.leave()
    finally:
        prim.stop()
        stby.stop()


def test_standby_cannot_seed_another_standby():
    stby = ElasticCoordinator(standby_of="127.0.0.1:1").start()
    try:
        cli = ElasticClient(f"127.0.0.1:{stby.port}", timeout=5,
                            connect_retries=2, retry_delay=0.05)
        rep = cli._rpc({"op": "co_replicate"})
        assert rep.get("status") == "standby"
        cli.close()
    finally:
        stby.stop()


# ---------------------------------------------------------------------------
# ckpt-dir scan (satellite)
# ---------------------------------------------------------------------------

def test_ckpt_dir_scan_picks_latest_step(tmp_path):
    ck = str(tmp_path / "ck")
    mgr = CheckpointManager(ck, max_to_keep=10)
    for s in (0, 2, 4, 6):
        mgr.save(s, {"model": {"flat": np.zeros(3, np.float32)}})
    assert _scan_ckpt_dir(ck) == 6
    coord = ElasticCoordinator(ckpt_dir=ck).start()
    try:
        assert coord.status()["ckpt_step"] == 6
    finally:
        coord.stop()
    # empty dir -> fresh run (rank 0 bootstraps step 0)
    coord = ElasticCoordinator(
        ckpt_dir=str(tmp_path / "empty")).start()
    try:
        assert coord.status()["ckpt_step"] is None
    finally:
        coord.stop()


def test_coordinator_restart_resumes_without_explicit_step(tmp_path):
    """The satellite's acceptance: train, lose the coordinator, start a
    FRESH one over the same ckpt_dir with no ckpt_step — the run
    resumes from the latest pinned step and finishes identical to an
    uninterrupted run."""
    ck = str(tmp_path / "ck")
    _te._run_world(ck, 1, 6)               # pinned ckpts at 2, 4, 6
    coord = ElasticCoordinator(expected_world=1, ckpt_dir=ck).start()
    r, trainers, _ = _te._run_world(ck, 1, 10, coord=coord)
    coord.stop()
    assert trainers[0].transitions[0]["resume_step"] == 6
    (ref,), _, _ = _te._run_world(str(tmp_path / "ref"), 1, 10)
    assert np.array_equal(r[0]["w"], ref["w"])
    assert np.array_equal(r[0]["b"], ref["b"])


# ---------------------------------------------------------------------------
# THE acceptance: SIGKILL the primary coordinator mid-run
# ---------------------------------------------------------------------------

_COORD_SRC = r"""
import json, os, sys
sys.path.insert(0, sys.argv[1])
cfg = json.loads(sys.argv[2])
from paddle_tpu.distributed.fleet.elastic import ElasticCoordinator
coord = ElasticCoordinator(expected_world=cfg.get("expected_world"),
                           standby_of=cfg.get("standby_of"),
                           ckpt_dir=cfg.get("ckpt_dir"))
coord.start()
print(json.dumps({"port": coord.port, "pid": os.getpid()}), flush=True)
coord._stop_evt.wait()
"""


def _spawn_coord(expected_world=None, standby_of=None, ckpt_dir=None):
    cfg = {"expected_world": expected_world, "standby_of": standby_of,
           "ckpt_dir": ckpt_dir}
    env = dict(os.environ)
    env.pop("PADDLE_CHAOS", None)
    proc = subprocess.Popen(
        [sys.executable, "-c", _COORD_SRC, _REPO, json.dumps(cfg)],
        stdout=subprocess.PIPE, text=True, env=env)
    info = json.loads(proc.stdout.readline())
    return proc, f"127.0.0.1:{info['port']}"


def _launch_workers(tag, tmp, world, steps, coordinator, ckpt_every=2):
    ck = os.path.join(tmp, f"ck_{tag}")
    res = os.path.join(tmp, f"res_{tag}")
    # paced steps (~50 ms): the SIGKILL must land while the run is
    # still in flight — an unpaced 12-step run can finish before the
    # status poll even sees step 3 (the shuffled-order flake)
    cfg = {"batch_size": 16, "loader_seed": 11, "ckpt_dir": ck,
           "micro_batches": 4, "ckpt_every": ckpt_every,
           "coordinator": coordinator, "expected_world": world,
           "total_steps": steps, "result": res, "client_timeout": 60.0,
           "step_sleep_s": 0.05}
    cfgp = os.path.join(tmp, f"cfg_{tag}.json")
    with open(cfgp, "w") as f:
        json.dump(cfg, f)
    ips = ",".join(["127.0.0.1"] * world)
    procs = []
    for r in range(world):
        env = dict(os.environ)
        env["PYTHONPATH"] = _REPO
        env.pop("PADDLE_CHAOS", None)
        env.pop("PADDLE_COORDINATOR", None)
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--elastic", "--max_restarts", "4",
             "--restart_backoff", "0.05", "--ips", ips,
             "--host_rank", str(r),
             "--log_dir", os.path.join(tmp, f"log_{tag}"),
             os.path.join(_REPO, "tests", "elastic_worker.py"), cfgp],
            env=env, cwd=tmp))
    return procs, res, ck


def test_sigkill_coordinator_acceptance(tmp_path):
    tmp = str(tmp_path)
    steps, world = 12, 2

    # fault-free reference (its own coordinator, untouched)
    ref_coord, ref_ep = _spawn_coord(expected_world=world)
    try:
        procs, ref_res, _ = _launch_workers("ref", tmp, world, steps,
                                            ref_ep)
        rcs = [p.wait(timeout=120) for p in procs]
        assert rcs == [0, 0]
    finally:
        ref_coord.kill()
        ref_coord.wait(timeout=10)
    outs_ref = [np.load(ref_res + f".rank{r}.npz") for r in range(world)]

    # HA run: primary + standby coordinator subprocesses; the workers
    # hold the failover list
    ck_dir = os.path.join(tmp, "ck_ha")
    prim, prim_ep = _spawn_coord(expected_world=world, ckpt_dir=ck_dir)
    stby, stby_ep = _spawn_coord(standby_of=prim_ep, ckpt_dir=ck_dir)
    try:
        procs, res, _ = _launch_workers(
            "ha", tmp, world, steps, f"{prim_ep}|{stby_ep}")
        # poll the primary until real progress, then SIGKILL it
        poll = ElasticClient(prim_ep, timeout=30)
        deadline = time.monotonic() + 60.0
        killed = False
        while time.monotonic() < deadline:
            try:
                st = poll.status()
            except ConnectionError:
                break
            if st.get("last_step", -1) >= 3:
                os.kill(prim.pid, signal.SIGKILL)
                prim.wait(timeout=10)
                killed = True
                break
            time.sleep(0.1)
        poll.close()
        assert killed, "primary coordinator never reached step 3"
        rcs = [p.wait(timeout=150) for p in procs]
        assert rcs == [0, 0], \
            "workers did not finish after coordinator failover"
        outs = [np.load(res + f".rank{r}.npz") for r in range(world)]
        for o in outs:
            assert np.array_equal(o["w"], outs_ref[0]["w"])
            assert np.array_equal(o["b"], outs_ref[0]["b"])
            assert int(o["opt_t"]) == steps
        # the workers really did live through a coordinator failover:
        # somebody's transition log shows a post-fence generation jump
        # with a resume from a pinned step
        all_trans = [t for o in outs
                     for t in json.loads(str(o["transitions"]))]
        assert len(all_trans) >= world + 1, all_trans
        assert any(t["resume_step"] not in (None, 0)
                   for t in all_trans), all_trans
    finally:
        for p in (prim, stby):
            p.kill()
            p.wait(timeout=10)
