"""Row-sparse embedding gradients (SelectedRows) tests.

Parity targets: reference framework/selected_rows.h:41 (container),
imperative/gradient_accumulator.cc (sparse sum), operators/optimizers/
adam_op.h lazy_mode (row-wise updates), fluid/clip.py merge_selected_rows.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.framework.selected_rows import SelectedRows


def _np(v):
    return np.asarray(v)


def test_merge_and_to_dense():
    import jax.numpy as jnp
    sr = SelectedRows(jnp.asarray([2, 0, 2], jnp.int32),
                      jnp.asarray([[1., 1.], [2., 2.], [3., 3.]]),
                      (4, 2))
    m = sr.merge()
    assert sorted(_np(m.rows).tolist()) == [0, 2]
    dense = _np(sr.to_dense())
    exp = np.zeros((4, 2), np.float32)
    exp[2] = [4, 4]
    exp[0] = [2, 2]
    np.testing.assert_allclose(dense, exp)
    np.testing.assert_allclose(_np(m.to_dense()), exp)


def test_embedding_sparse_grad_structure():
    paddle.seed(0)
    emb = nn.Embedding(100, 8, sparse=True)
    ids = paddle.to_tensor(np.array([[1, 5, 5], [7, 1, 9]], np.int64))
    out = emb(ids)
    out.sum().backward()
    g = emb.weight.grad
    assert isinstance(g, SelectedRows)
    assert g.values.shape == (6, 8)      # batch*seq rows, not vocab
    assert g.dense_shape == (100, 8)
    # matches the dense gradient exactly
    emb2 = nn.Embedding(100, 8, sparse=False)
    emb2.weight._value = emb.weight._value
    out2 = emb2(ids)
    out2.sum().backward()
    np.testing.assert_allclose(_np(g.to_dense()),
                               _np(emb2.weight.grad._value), rtol=1e-6)


def test_padding_idx_rows_get_zero_grad():
    paddle.seed(0)
    emb = nn.Embedding(50, 4, padding_idx=0, sparse=True)
    ids = paddle.to_tensor(np.array([0, 3, 0, 7], np.int64))
    emb(ids).sum().backward()
    dense = _np(emb.weight.grad.to_dense())
    np.testing.assert_allclose(dense[0], 0.0)
    assert np.abs(dense[3]).sum() > 0


def test_accumulation_two_backwards():
    paddle.seed(0)
    emb = nn.Embedding(20, 4, sparse=True)
    ids1 = paddle.to_tensor(np.array([1, 2], np.int64))
    ids2 = paddle.to_tensor(np.array([2, 3], np.int64))
    emb(ids1).sum().backward()
    emb(ids2).sum().backward()
    g = emb.weight.grad
    assert isinstance(g, SelectedRows)
    dense = _np(g.to_dense())
    exp = np.zeros((20, 4), np.float32)
    for i in (1, 2, 2, 3):
        exp[i] += 1
    np.testing.assert_allclose(dense, exp, rtol=1e-6)


def _train(sparse, opt_name, steps=4, clip=None):
    paddle.seed(0)
    emb = nn.Embedding(16, 4, sparse=sparse)
    head = nn.Linear(4, 2)
    params = list(emb.parameters()) + list(head.parameters())
    kw = dict(learning_rate=0.1, parameters=params)
    if clip is not None:
        kw["grad_clip"] = clip
    opt = getattr(paddle.optimizer, opt_name)(**kw)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, 16, (8,)).astype(np.int64))
    y = paddle.to_tensor(rng.randint(0, 2, (8,)).astype(np.int64))
    losses = []
    for s in range(steps):
        loss = F.cross_entropy(head(emb(ids)), y)
        losses.append(float(loss))
        loss.backward()
        opt.step()
        opt.clear_grad()
    return _np(emb.weight._value), losses


@pytest.mark.parametrize("opt_name", ["SGD", "Adam", "AdamW", "Momentum"])
def test_sparse_matches_dense_when_all_rows_touched(opt_name):
    """With every step's batch drawn over the whole vocab repeatedly,
    lazy row updates coincide with dense updates on touched rows; over a
    few steps trajectories must agree wherever rows were touched every
    step — enforced here by a vocab small enough that updates dominate."""
    w_sparse, l_sparse = _train(True, opt_name)
    w_dense, l_dense = _train(False, opt_name)
    np.testing.assert_allclose(l_sparse[0], l_dense[0], rtol=1e-5)
    if opt_name == "SGD":  # SGD is stateless: exact row-for-row parity
        np.testing.assert_allclose(w_sparse, w_dense, rtol=1e-5, atol=1e-6)
    # training progressed in both modes
    assert l_sparse[-1] < l_sparse[0]
    assert l_dense[-1] < l_dense[0]


def test_lazy_momentum_leaves_untouched_rows_alone():
    """Step 1 touches row 1; step 2 touches only row 2.  Dense momentum
    would keep moving row 1 in step 2 (velocity), lazy must not."""
    paddle.seed(0)
    emb = nn.Embedding(4, 3, sparse=True)
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                    parameters=emb.parameters())
    ids1 = paddle.to_tensor(np.array([1], np.int64))
    emb(ids1).sum().backward()
    opt.step()
    opt.clear_grad()
    row1_after_step1 = _np(emb.weight._value)[1].copy()
    ids2 = paddle.to_tensor(np.array([2], np.int64))
    emb(ids2).sum().backward()
    opt.step()
    opt.clear_grad()
    np.testing.assert_allclose(_np(emb.weight._value)[1], row1_after_step1)


def test_global_norm_clip_mixed_sparse_dense():
    from paddle_tpu.nn.clip import ClipGradByGlobalNorm
    w_sparse, _ = _train(True, "SGD", clip=ClipGradByGlobalNorm(0.1))
    w_dense, _ = _train(False, "SGD", clip=ClipGradByGlobalNorm(0.1))
    np.testing.assert_allclose(w_sparse, w_dense, rtol=1e-5, atol=1e-6)


def test_big_vocab_memory_bounded():
    """1M-row embedding: the gradient object stays O(batch*dim) — the
    VERDICT acceptance test (no dense vocab-sized grad materialized)."""
    paddle.seed(0)
    emb = nn.Embedding(1_000_000, 8, sparse=True)
    opt = paddle.optimizer.Adam(learning_rate=0.01,
                                parameters=emb.parameters())
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(
        rng.randint(0, 1_000_000, (32,)).astype(np.int64))
    target = paddle.to_tensor(rng.rand(32, 8).astype(np.float32))
    losses = []
    for _ in range(3):
        loss = ((emb(ids) - target) ** 2).mean()
        losses.append(float(loss))
        loss.backward()
        g = emb.weight.grad
        assert isinstance(g, SelectedRows)
        assert g.values.shape == (32, 8)
        assert int(np.prod(g.values.shape)) < 1000  # vs 8M dense elems
        opt.step()
        opt.clear_grad()
    assert losses[-1] < losses[0]


def test_hook_sees_densified_sparse_grad():
    paddle.seed(0)
    emb = nn.Embedding(10, 4, sparse=True)
    calls = []
    emb.weight.register_hook(lambda g: calls.append(g) or None)
    ids = paddle.to_tensor(np.array([1, 2], np.int64))
    emb(ids).sum().backward()
    assert len(calls) == 1          # hook ran (densified grad)
    assert calls[0]._value.shape == (10, 4)
    assert not isinstance(emb.weight.grad, SelectedRows)


def test_sparse_create_graph_raises_clear_error():
    paddle.seed(0)
    emb = nn.Embedding(10, 4, sparse=True)
    ids = paddle.to_tensor(np.array([1, 2], np.int64))
    out = emb(ids).sum()
    with pytest.raises(RuntimeError, match="does not support"):
        paddle.grad(out, [emb.weight], create_graph=True)


def test_grad_scaler_unscales_sparse():
    paddle.seed(0)
    emb = nn.Embedding(10, 4, sparse=True)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=emb.parameters())
    scaler = paddle.amp.GradScaler(init_loss_scaling=128.0)
    ids = paddle.to_tensor(np.array([1, 2], np.int64))
    loss = emb(ids).sum()
    scaler.scale(loss).backward()
    g = emb.weight.grad
    assert isinstance(g, SelectedRows)
    scaler.step(opt)  # unscale + apply must handle SelectedRows
    # after unscale the effective grad was 1.0 per touched element
    assert not np.isnan(_np(emb.weight._value)).any()


def test_sparse_inside_jit_falls_back_to_dense():
    """Under to_static/jit tracing the dense path is used (XLA fuses the
    scatter); the program must still compile and train."""
    class M(nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = nn.Embedding(32, 4, sparse=True)

        def forward(self, ids):
            return self.emb(ids).sum()

    paddle.seed(0)
    net = M()
    fwd = paddle.jit.to_static(net)
    ids = paddle.to_tensor(np.array([1, 2, 3], np.int64))
    out = fwd(ids)
    out.backward()
    g = net.emb.weight.grad
    assert g is not None and not isinstance(g, SelectedRows)
