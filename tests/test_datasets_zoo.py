"""Dataset zoo breadth (parity: python/paddle/dataset/ — movielens,
imikolov, wmt14/16, flowers, voc2012). Zero-egress environment: each
test writes a tiny local corpus in the reference's on-disk layout and
checks parsing, encoding, and split semantics.
"""
import os

import numpy as np
import pytest

from paddle_tpu.text.datasets import Imikolov, Movielens, WMT14, WMT16
from paddle_tpu.vision.datasets import VOC2012, VOC_CLASSES, Flowers


# ---------------------------------------------------------------- movielens
def _write_ml1m(root):
    os.makedirs(root, exist_ok=True)
    with open(os.path.join(root, "users.dat"), "w") as f:
        f.write("1::F::1::10::48067\n2::M::56::16::70072\n"
                "3::M::25::15::55117\n")
    with open(os.path.join(root, "movies.dat"), "w") as f:
        f.write("1::Toy Story (1995)::Animation|Children's|Comedy\n"
                "2::Jumanji (1995)::Adventure|Children's|Fantasy\n"
                "3::Heat (1995)::Action|Crime|Thriller\n")
    with open(os.path.join(root, "ratings.dat"), "w") as f:
        for n, (u, m, r) in enumerate([(1, 1, 5), (1, 2, 3), (2, 1, 4),
                                       (2, 3, 4), (3, 2, 2), (3, 3, 5),
                                       (1, 3, 4), (2, 2, 1), (3, 1, 3),
                                       (1, 1, 2)]):
            f.write(f"{u}::{m}::{r}::97830{n:04d}\n")


def test_movielens_features_and_split(tmp_path):
    root = str(tmp_path / "ml-1m")
    _write_ml1m(root)
    train = Movielens(root, mode="train")
    test = Movielens(root, mode="test")
    assert len(train) + len(test) == 10
    assert len(test) == 1  # 1-in-10 deterministic holdout
    uid, gender, age, job, mid, genres, title, rating = train[0]
    assert gender in (0, 1) and genres.shape == (train.n_genres,)
    assert genres.sum() == 3.0   # every ml-1m movie row lists 3 genres
    assert title.shape == (Movielens.TITLE_LEN,)
    assert 1.0 <= float(rating[0]) <= 5.0
    # age bucket: user 1 has age 1 -> bucket 0; user 2 age 56 -> bucket 6
    assert train.user[1][2] == 0 and train.user[2][2] == 6


def test_movielens_missing_dir_raises():
    with pytest.raises(FileNotFoundError, match="no local data"):
        Movielens("/nonexistent/ml-1m")


# ---------------------------------------------------------------- imikolov
def test_imikolov_ngram_and_seq(tmp_path):
    p = tmp_path / "ptb.train.txt"
    p.write_text("the cat sat\nthe dog sat on the mat\n")
    ng = Imikolov(str(p), data_type="NGRAM", window_size=3)
    # sentence 1: <s> the cat sat <e> -> 3 windows; sentence 2: 6 windows
    assert len(ng) == 9
    assert all(s.shape == (3,) for s in ng)
    seq = Imikolov(str(p), data_type="SEQ")
    x, y = seq[0]
    np.testing.assert_array_equal(x[1:], y[:-1])  # shifted by one
    assert x[0] == seq.word_idx["<s>"] and y[-1] == seq.word_idx["<e>"]
    # vocab is shared/reusable across splits like the reference
    valid = Imikolov(str(p), data_type="SEQ", vocab=seq.word_idx)
    assert valid.word_idx is seq.word_idx


# ---------------------------------------------------------------- wmt
def test_wmt14_pairs_and_vocab_cap(tmp_path):
    src = tmp_path / "train.src"
    trg = tmp_path / "train.trg"
    src.write_text("ein haus\nder hund schläft\n")
    trg.write_text("a house\nthe dog sleeps\n")
    ds = WMT14(str(src), str(trg))
    assert len(ds) == 2
    s, tin, tout = ds[1]
    assert tin[0] == ds.trg_vocab["<s>"]
    assert tout[-1] == ds.trg_vocab["<e>"]
    np.testing.assert_array_equal(tin[1:], tout[:-1])
    capped = WMT14(str(src), str(trg), dict_size=5)
    assert len(capped.src_vocab) == 5  # most-frequent truncation
    # unknown words map to <unk>, ids stay in range
    for si, ti, to in capped:
        assert si.max() < 5 and ti.max() < 5 and to.max() < 5


def test_wmt_unaligned_raises(tmp_path):
    src = tmp_path / "s"; trg = tmp_path / "t"
    src.write_text("one line\n")
    trg.write_text("two\nlines\n")
    with pytest.raises(ValueError, match="unaligned"):
        WMT16(str(src), str(trg))


# ---------------------------------------------------------------- flowers
def test_flowers_layout_and_splits(tmp_path):
    from PIL import Image
    from scipy.io import savemat
    root = tmp_path / "flowers"
    (root / "jpg").mkdir(parents=True)
    for i in range(1, 7):
        Image.fromarray(
            np.full((8, 8, 3), i * 20, np.uint8)).save(
                root / "jpg" / f"image_{i:05d}.jpg")
    savemat(root / "imagelabels.mat",
            {"labels": np.asarray([[1, 1, 2, 2, 3, 3]])})
    savemat(root / "setid.mat",
            {"trnid": np.asarray([[1, 3, 5]]),
             "valid": np.asarray([[2, 4]]),
             "tstid": np.asarray([[6]])})
    train = Flowers(str(root), mode="train")
    assert len(train) == 3
    img, label = train[1]
    assert img.shape == (8, 8, 3) and label == 1  # 1-based -> 0-based
    assert len(Flowers(str(root), mode="valid")) == 2
    assert len(Flowers(str(root), mode="test")) == 1


def test_flowers_plain_setid_npy_rejected(tmp_path):
    from PIL import Image
    root = tmp_path / "flowers"
    (root / "jpg").mkdir(parents=True)
    Image.fromarray(np.zeros((4, 4, 3), np.uint8)).save(
        root / "jpg" / "image_00001.jpg")
    np.save(root / "imagelabels.npy", np.asarray([1]))
    np.save(root / "setid.npy", np.asarray([1]))  # plain array: ambiguous
    with pytest.raises(ValueError, match="trnid/valid/tstid"):
        Flowers(str(root), mode="train")


# ---------------------------------------------------------------- voc2012
def test_voc2012_detection_samples(tmp_path):
    from PIL import Image
    root = tmp_path / "VOCdevkit" / "VOC2012"
    for d in ("JPEGImages", "Annotations", "ImageSets/Main"):
        (root / d).mkdir(parents=True)
    Image.fromarray(np.zeros((10, 12, 3), np.uint8)).save(
        root / "JPEGImages" / "2007_000001.jpg")
    (root / "Annotations" / "2007_000001.xml").write_text("""
<annotation><size><width>12</width><height>10</height></size>
 <object><name>dog</name><difficult>0</difficult>
  <bndbox><xmin>1</xmin><ymin>2</ymin><xmax>6</xmax><ymax>8</ymax></bndbox>
 </object>
 <object><name>person</name><difficult>1</difficult>
  <bndbox><xmin>3</xmin><ymin>1</ymin><xmax>9</xmax><ymax>9</ymax></bndbox>
 </object>
</annotation>""")
    (root / "ImageSets" / "Main" / "train.txt").write_text("2007_000001\n")
    ds = VOC2012(str(tmp_path), mode="train")  # outer level accepted
    assert len(ds) == 1
    img, boxes, labels, difficult = ds[0]
    assert img.shape == (10, 12, 3)
    np.testing.assert_allclose(boxes[0], [1, 2, 6, 8])
    assert labels.tolist() == [VOC_CLASSES.index("dog"),
                               VOC_CLASSES.index("person")]
    assert difficult.tolist() == [0, 1]
