"""Tests: distribution package (§2.5 parity with paddle.distribution),
DGC compression semantics (§2.6), heterogeneous PS trainer (§2.6).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distribution import Categorical, Normal, Uniform, kl_divergence
from paddle_tpu.distributed.fleet import HeterTrainer, SparseTable, dgc


# ------------------------------------------------------------- distribution

def test_normal_log_prob_entropy():
    d = Normal(0.0, 2.0)
    lp = float(d.log_prob(paddle.to_tensor(1.0)))
    ref = -0.5 * (1.0 / 4.0) - np.log(2.0) - 0.5 * np.log(2 * np.pi)
    assert lp == pytest.approx(ref, rel=1e-5)
    ent = float(d.entropy())
    assert ent == pytest.approx(0.5 + 0.5 * np.log(2 * np.pi) + np.log(2.0),
                                rel=1e-5)


def test_normal_sampling_moments():
    paddle.seed(0)
    d = Normal(3.0, 0.5)
    s = d.sample([20000]).numpy()
    assert s.mean() == pytest.approx(3.0, abs=0.05)
    assert s.std() == pytest.approx(0.5, abs=0.05)


def test_normal_kl_zero_for_same():
    d1, d2 = Normal(1.0, 2.0), Normal(1.0, 2.0)
    assert float(kl_divergence(d1, d2)) == pytest.approx(0.0, abs=1e-6)
    d3 = Normal(2.0, 2.0)
    assert float(kl_divergence(d1, d3)) > 0


def test_uniform():
    paddle.seed(1)
    d = Uniform(2.0, 6.0)
    s = d.sample([10000]).numpy()
    assert s.min() >= 2.0 and s.max() < 6.0
    assert s.mean() == pytest.approx(4.0, abs=0.1)
    assert float(d.entropy()) == pytest.approx(np.log(4.0), rel=1e-6)
    assert float(d.log_prob(paddle.to_tensor(3.0))) == pytest.approx(
        -np.log(4.0))
    assert float(d.log_prob(paddle.to_tensor(7.0))) == -np.inf


def test_categorical():
    paddle.seed(2)
    logits = paddle.to_tensor(np.log(np.array([0.2, 0.3, 0.5],
                                              dtype="float32")))
    d = Categorical(logits)
    np.testing.assert_allclose(d.probs().numpy(), [0.2, 0.3, 0.5],
                               rtol=1e-5)
    s = d.sample([8000]).numpy()
    freq = np.bincount(s, minlength=3) / s.size
    np.testing.assert_allclose(freq, [0.2, 0.3, 0.5], atol=0.03)
    ent = float(d.entropy())
    assert ent == pytest.approx(-(0.2 * np.log(0.2) + 0.3 * np.log(0.3)
                                  + 0.5 * np.log(0.5)), rel=1e-4)
    lp = d.log_prob(paddle.to_tensor(np.array([2])))
    assert float(lp.numpy()[0]) == pytest.approx(np.log(0.5), rel=1e-4)
    d2 = Categorical(paddle.to_tensor(np.zeros(3, dtype="float32")))
    assert float(d.kl_divergence(d2)) > 0


def test_log_prob_gradient_through_value():
    # reparameterized-sample path: d(logN(z;0,1))/dz = -z
    z = paddle.to_tensor(np.array(0.7, dtype="float32"),
                         stop_gradient=False)
    d = Normal(0.0, 1.0)
    (-d.log_prob(z)).backward()
    assert float(z.grad) == pytest.approx(0.7, rel=1e-5)


def test_normal_log_prob_gradient():
    mu = paddle.to_tensor(np.array(0.5, dtype="float32"),
                          stop_gradient=False)
    d = Normal(mu, 1.0)
    nll = -d.log_prob(paddle.to_tensor(2.0))
    nll.backward()
    # d/dmu of -logN = -(x-mu)/var = -(2-0.5) = -1.5
    assert float(mu.grad) == pytest.approx(-1.5, rel=1e-5)


# --------------------------------------------------------------------- DGC

def test_dgc_sparsity_and_error_feedback():
    import jax.numpy as jnp
    g = {"w": jnp.asarray(np.arange(1, 101, dtype=np.float32))}
    st = dgc.dgc_init(g)
    st, out = dgc.dgc_compress(st, g, momentum=0.0, sparsity=0.9)
    sent = np.asarray(out["w"])
    # exactly 10% of entries exchanged, the largest-|v| ones
    assert (sent != 0).sum() == 10
    assert set(np.nonzero(sent)[0]) == set(range(90, 100))
    # residual keeps the unsent mass (error feedback)
    resid = np.asarray(st["v"]["w"])
    np.testing.assert_allclose(resid[:90], np.arange(1, 91))
    assert np.all(resid[90:] == 0)
    # second step: accumulated residual + new grad competes again
    st, out2 = dgc.dgc_compress(st, g, momentum=0.0, sparsity=0.9)
    assert (np.asarray(out2["w"]) != 0).sum() == 10


def test_dgc_total_mass_conserved_without_momentum():
    """Everything is eventually sent: sum(sent over steps) + residual ==
    sum(grads over steps)."""
    import jax.numpy as jnp
    rng = np.random.RandomState(0)
    g = {"w": jnp.asarray(rng.randn(64).astype(np.float32))}
    st = dgc.dgc_init(g)
    total_sent = np.zeros(64, np.float32)
    for _ in range(5):
        st, out = dgc.dgc_compress(st, g, momentum=0.0, sparsity=0.75)
        total_sent += np.asarray(out["w"])
    np.testing.assert_allclose(
        total_sent + np.asarray(st["v"]["w"]),
        5 * np.asarray(g["w"]), rtol=1e-4, atol=1e-5)


def test_dgc_momentum_correction_masks_velocity():
    import jax.numpy as jnp
    g = {"w": jnp.asarray(np.array([10.0, 1.0], np.float32))}
    st = dgc.dgc_init(g)
    st, out = dgc.dgc_compress(st, g, momentum=0.9, sparsity=0.5)
    # sent entry's velocity cleared, unsent kept
    u = np.asarray(st["u"]["w"])
    assert u[0] == 0.0 and u[1] != 0.0


def test_dgc_rampup():
    import jax.numpy as jnp
    s0 = float(dgc.rampup_sparsity(jnp.asarray(0), rampup_begin_step=5,
                                   rampup_step=4,
                                   sparsity=[0.75, 0.9375, 0.999]))
    assert s0 == 0.0  # warmup: no compression
    s_end = float(dgc.rampup_sparsity(jnp.asarray(100),
                                      rampup_begin_step=5, rampup_step=4,
                                      sparsity=[0.75, 0.9375, 0.999]))
    assert s_end == pytest.approx(0.999)


# ------------------------------------------------------------------- heter

def _run_heter(sync):
    dim = 4
    table = SparseTable(dim, optimizer="sgd", lr=1.0)
    seen = []

    def dense_step(emb, batch):
        rows = emb["emb"]                      # [n_ids, dim]
        loss = float((rows ** 2).sum()) / 2
        seen.append(batch["step"])
        return loss, {"emb": rows}             # d(loss)/d(rows) = rows

    tr = HeterTrainer({"emb": table}, dense_step, sync_mode=sync)
    ids = np.array([1, 2, 3], np.int64)
    batches = [{"step": i, "ids": ids} for i in range(6)]
    n = tr.run(batches, ids_fn=lambda b: {"emb": b["ids"]})
    tr.shutdown()
    assert n == 6
    assert seen == list(range(6))  # order preserved through the pipeline
    return table.pull(ids)


def test_heter_trainer_sync_and_async_when_grads_value_free():
    """With gradients independent of the pulled values, the async
    pipeline's one-batch staleness is invisible: both modes apply the
    same total update (the reference's async communicator guarantee)."""
    def run(sync):
        table = SparseTable(3, optimizer="sgd", lr=0.1)
        ids = np.array([5, 9], np.int64)

        def dense_step(emb, batch):
            return None, {"emb": np.ones_like(emb["emb"])}

        tr = HeterTrainer({"emb": table}, dense_step, sync_mode=sync)
        tr.run([{"ids": ids}] * 5, ids_fn=lambda b: {"emb": b["ids"]})
        tr.shutdown()
        return table.pull(ids)

    np.testing.assert_allclose(run(True), run(False), atol=1e-6)


def test_heter_trainer_async_staleness_bounded_to_one_batch():
    """Pull for batch i+1 must see every push through batch i-1 — grads
    that depend on values lag by at most ONE batch vs sync."""
    r_sync = _run_heter(sync=True)
    r_async = _run_heter(sync=False)
    # value-dependent grads (g = rows, lr=1): sync zeroes the table on
    # the first push and stays 0. Async batch 1 reads pre-push rows
    # (staleness 1) so one extra -r0 lands; from batch 2 onward pulls see
    # zeroed rows and push 0. Net: async == sync - r0_initial, bounded,
    # deterministic.
    assert np.all(np.isfinite(r_async))
    assert np.abs(r_async - r_sync).max() <= 1.0 + 1e-6


def test_heter_trainer_pushes_reach_table():
    dim = 2
    table = SparseTable(dim, optimizer="sgd", lr=0.5)
    before = table.pull(np.array([7], np.int64)).copy()

    def dense_step(emb, batch):
        return None, {"emb": np.ones_like(emb["emb"])}

    tr = HeterTrainer({"emb": table}, dense_step)
    tr.run([{"ids": np.array([7], np.int64)}] * 3,
           ids_fn=lambda b: {"emb": b["ids"]})
    tr.shutdown()
    after = table.pull(np.array([7], np.int64))
    np.testing.assert_allclose(after, before - 0.5 * 3, atol=1e-6)
