"""1F1B pipeline schedule tests.

Parity: the reference only has GPipe-style streaming (SectionWorker,
framework/device_worker.h:641); 1F1B is the standard fix for its bubble
and memory profile.  Requirements (VERDICT r1 #8): both schedules run on
the virtual-device mesh, numerics identical, and the tick/stash
accounting shows the shrink.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed.pipeline import (
    pipeline_apply, pipeline_train_1f1b, ring_size, schedule_ticks)


def _stage_fn(local_params, h):
    """Scan this stage's chunk of stacked tanh-linear layers."""
    def body(carry, wb):
        w, b = wb
        return jnp.tanh(carry @ w + b), None

    out, _ = jax.lax.scan(body, h, local_params)
    return out


def _head_loss(h, y):
    return jnp.mean((h - y) ** 2)


def _setup(L=4, d=8, B=8, seed=0):
    rng = np.random.RandomState(seed)
    stacked = (jnp.asarray(rng.randn(L, d, d).astype(np.float32)) * 0.3,
               jnp.asarray(rng.randn(L, d).astype(np.float32)) * 0.1)
    x = jnp.asarray(rng.randn(B, d).astype(np.float32))
    y = jnp.asarray(rng.randn(B, d).astype(np.float32))
    return stacked, x, y


def _reference_grads(stacked, x, y):
    """Ground truth: no pipeline, plain autodiff over the stacked scan."""
    def whole(params, h):
        return _head_loss(_stage_fn(params, h), y)

    loss, (dp, dx) = jax.value_and_grad(whole, argnums=(0, 1))(stacked, x)
    return loss, dp, dx


@pytest.mark.parametrize("pp,M", [(2, 4), (4, 8), (4, 2)])
def test_1f1b_matches_ground_truth(pp, M):
    mesh_mod.set_mesh(None)
    mesh = mesh_mod.init_mesh({"pp": pp, "dp": -1})
    stacked, x, y = _setup()
    ref_loss, ref_dp, ref_dx = _reference_grads(stacked, x, y)
    loss, dp, dx = pipeline_train_1f1b(
        _stage_fn, stacked, x, y, _head_loss,
        num_microbatches=M, mesh=mesh)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for g, r in zip(jax.tree_util.tree_leaves(dp),
                    jax.tree_util.tree_leaves(ref_dp)):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=2e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(dx), np.asarray(ref_dx),
                               rtol=2e-4, atol=1e-5)
    mesh_mod.set_mesh(None)


def test_1f1b_matches_gpipe_schedule():
    """Same math, different schedule: GPipe forward + autodiff backward
    must produce identical numbers to the interleaved 1F1B loop."""
    mesh_mod.set_mesh(None)
    mesh = mesh_mod.init_mesh({"pp": 4, "dp": -1})
    stacked, x, y = _setup(seed=3)
    M = 4

    def gpipe_loss(params, h):
        out = pipeline_apply(lambda p, hh, e: _stage_fn(p, hh), params, h,
                             num_microbatches=M, mesh=mesh)
        return _head_loss(out, y)

    g_loss, (g_dp, g_dx) = jax.value_and_grad(
        gpipe_loss, argnums=(0, 1))(stacked, x)
    f_loss, f_dp, f_dx = pipeline_train_1f1b(
        _stage_fn, stacked, x, y, _head_loss,
        num_microbatches=M, mesh=mesh)
    np.testing.assert_allclose(float(f_loss), float(g_loss), rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(f_dp),
                    jax.tree_util.tree_leaves(g_dp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(f_dx), np.asarray(g_dx),
                               rtol=2e-4, atol=1e-5)
    mesh_mod.set_mesh(None)


def test_single_stage_fallback():
    mesh_mod.set_mesh(None)
    mesh_mod.init_mesh({"dp": -1})  # no pp axis
    stacked, x, y = _setup()
    ref_loss, ref_dp, ref_dx = _reference_grads(stacked, x, y)
    loss, dp, dx = pipeline_train_1f1b(_stage_fn, stacked, x, y,
                                       _head_loss, num_microbatches=2)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(dx), np.asarray(ref_dx),
                               rtol=1e-5)
    mesh_mod.set_mesh(None)


def test_tick_accounting_bubble_shrink():
    """1F1B finishes the combined fwd+bwd in fewer ticks than GPipe for
    every M, S — and the gap grows with M (the bubble amortizes)."""
    for S in (2, 4, 8):
        for M in (S, 2 * S, 8 * S):
            f1b = schedule_ticks(M, S, "1F1B")
            gp = schedule_ticks(M, S, "gpipe")
            assert f1b == M + 2 * (S - 1)
            assert gp == 2 * (M + S - 1)
            assert f1b < gp
    # memory: the activation stash is O(S), not O(M)
    assert ring_size(64, 4) == 7
    assert ring_size(2, 4) == 2
    assert ring_size(64, 8) == 15
